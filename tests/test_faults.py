"""Fault-injection plane tests (analysis/faults.py and its hook sites).

The contract under test, layer by layer:

  * registry — spec parsing fails loudly on typos, arms fire with
    their declared semantics (p / count / oneshot / who-scoping),
    seeded probability draws are reproducible, and an unarmed plane
    is one bool test (``_ACTIVE``) on every hot path.
  * arming doors — the config observer (``fault_inject_spec``) and
    the admin-socket ``fault`` command drive the same armed set.
  * messenger — wire faults (corrupt / truncate / drop / dup /
    delay) surface as MalformedInput + clean session reset at the
    receiver, and the lossless session's replay carries the op
    through: no hang, no lost ack.
  * stores — WAL torn appends roll back to a record boundary and the
    store stays usable; a journal fsync EIO poisons it (the
    reference asserts out for the same reason); objectstore read EIO
    is a one-op event.
  * osd — write-pipeline kill points on a replica leave the op
    ackable via min_size; a shard read EIO degrades (decode from
    survivors), books ``degraded_reads``, and recovery re-decodes
    the dropped shard.
  * monitor — dropped pg_stats beacons and rank isolation fire and
    heal.
  * the seeded thrasher soak (tools/thrasher.py) ends HEALTH_OK with
    zero acked-write loss while every armed failpoint fired.
"""

import json
import pathlib
import sys
import time

import pytest

from ceph_tpu.analysis import faults
from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.backoff import Backoff
from ceph_tpu.common.config import Config
from ceph_tpu.common.context import Context
from ceph_tpu.common.encoding import MalformedInput
from ceph_tpu.msg.messenger import Messenger, _flip_control_byte, \
    decode_frame, encode_frame
from ceph_tpu.os.memstore import MemStore
from ceph_tpu.os.objectstore import Transaction
from ceph_tpu.os.wal_store import WALStore
from ceph_tpu.services.cluster import MiniCluster
from ceph_tpu.services.osd_service import pg_cid

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from tools import perf_history, thrasher  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with nothing armed and zeroed
    totals — the plane is process-global state."""
    faults.reset()
    yield
    faults.reset()


def _fast_conf():
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.2)
    c.set("mon_osd_down_out_interval", 1.5)
    c.set("osd_pg_stat_report_interval", 0.2)
    return c


# -- registry ---------------------------------------------------------
def test_parse_spec_rejects_unknown_name_and_arm():
    with pytest.raises(ValueError):
        faults.parse_spec("msgr.eat_frame=oneshot")
    with pytest.raises(ValueError):
        faults.parse_spec("msgr.drop_frame=always")
    with pytest.raises(ValueError):
        faults.parse_spec("msgr.drop_frame")
    with pytest.raises(ValueError):
        faults.parse_spec("osd.slow_op=oneshot,delay 0.1")


def test_parse_spec_multi_failpoint_with_extras():
    fps = faults.parse_spec(
        "msgr.corrupt_frame=p:0.25; "
        "osd.slow_op=count:3,delay:0.5,who:osd.1")
    assert fps["msgr.corrupt_frame"].mode == "p"
    assert fps["msgr.corrupt_frame"].p == 0.25
    assert fps["osd.slow_op"].remaining == 3
    assert fps["osd.slow_op"].extras == {"delay": "0.5",
                                         "who": "osd.1"}


def test_oneshot_fires_exactly_once():
    assert not faults.fires("msgr.drop_frame")  # unarmed
    faults.arm("msgr.drop_frame", "oneshot")
    assert faults.fires("msgr.drop_frame")
    assert not faults.fires("msgr.drop_frame")
    assert faults.snapshot() == {"msgr.drop_frame": 1}
    assert not faults._ACTIVE  # spent arm disarmed the plane


def test_count_arm_decrements_then_disarms():
    faults.arm("os.read_eio", "count", count=3)
    assert sum(faults.fires("os.read_eio") for _ in range(10)) == 3
    assert faults.snapshot()["os.read_eio"] == 3


def test_probability_arm_is_seed_deterministic():
    def draws():
        faults.seed(42)
        faults.arm("msgr.dup_frame", "p", p=0.5)
        out = [faults.fires("msgr.dup_frame") for _ in range(64)]
        faults.clear()
        return out

    a, b = draws(), draws()
    assert a == b
    assert 5 < sum(a) < 60  # actually probabilistic, not 0%/100%


def test_who_prefix_scoping():
    faults.arm("osd.slow_op", "count", count=100, who="osd.1")
    assert not faults.fires("osd.slow_op", "osd.2")
    assert not faults.fires("osd.slow_op", "osd.22")
    assert not faults.fires("osd.slow_op")  # anonymous site
    assert faults.fires("osd.slow_op", "osd.1")
    faults.clear()
    faults.arm("osd.slow_op", "count", count=100, who="osd")
    assert faults.fires("osd.slow_op", "osd.7")  # prefix match


def test_apply_spec_replaces_and_empty_disarms():
    faults.arm("msgr.drop_frame", "oneshot")
    faults.apply_spec("os.read_eio=count:2")
    armed = faults.list_faults()["armed"]
    assert set(armed) == {"os.read_eio"}  # replaced, not merged
    faults.apply_spec("")
    assert not faults.list_faults()["armed"]
    assert not faults._ACTIVE


def test_clear_keeps_totals_reset_zeroes():
    faults.arm("msgr.drop_frame", "count", count=5)
    faults.fires("msgr.drop_frame")
    faults.clear()
    assert faults.snapshot() == {"msgr.drop_frame": 1}
    faults.reset()
    assert faults.snapshot() == {}


def test_extra_and_sleep_if_delay():
    faults.arm("osd.slow_op", "oneshot", delay="0.15")
    assert faults.extra("osd.slow_op", "delay", 0.0) == 0.15
    t0 = time.monotonic()
    assert faults.sleep_if("osd.slow_op")
    assert time.monotonic() - t0 >= 0.12
    assert not faults.sleep_if("osd.slow_op")  # spent


# -- arming doors -----------------------------------------------------
def test_config_observer_arms_and_disarms():
    conf = Config()
    faults.install(conf)
    conf.set("fault_inject_spec", "msgr.dup_frame=oneshot")
    assert set(faults.list_faults()["armed"]) == {"msgr.dup_frame"}
    conf.set("fault_inject_spec", "")
    assert not faults.list_faults()["armed"]


def test_admin_socket_fault_command(tmp_path):
    ctx = Context("osd.77", admin_dir=str(tmp_path))
    ctx.start_admin_socket()
    try:
        rep = AdminSocket.request(
            ctx.admin_socket_path, "fault", mode="set",
            spec="osd.slow_op=count:3,delay:0.01")
        assert rep["armed"]["osd.slow_op"]["mode"] == "count"
        assert faults.fires("osd.slow_op")  # in-process: same plane
        rep = AdminSocket.request(ctx.admin_socket_path, "fault",
                                  mode="list")
        assert rep["fired"].get("osd.slow_op") == 1
        rep = AdminSocket.request(ctx.admin_socket_path, "fault",
                                  mode="clear")
        assert not rep["armed"]
        assert not faults.fires("osd.slow_op")
    finally:
        ctx.shutdown()


# -- backoff ----------------------------------------------------------
def test_backoff_intervals_jittered_and_capped():
    bo = Backoff(base=0.05, cap=0.2)
    prev = 0.0
    for _ in range(50):
        iv = bo.next_interval()
        assert 0.05 <= iv <= 0.2
        prev = max(prev, iv)
    assert prev > 0.05  # jitter actually moved off the base


def test_backoff_deadline_budget_bounds_total_sleep():
    bo = Backoff(base=0.01, cap=0.02, deadline=0.08)
    t0 = time.monotonic()
    n = 0
    while bo.sleep():
        n += 1
        assert n < 100, "budget never expired"
    spent = time.monotonic() - t0
    assert spent < 0.5  # budget + one interval of slop, not unbounded
    assert bo.expired()
    assert bo.remaining() == 0.0
    assert not bo.sleep()  # stays refused once spent


def test_backoff_unbudgeted_never_expires():
    bo = Backoff(base=0.001, cap=0.002)
    assert bo.remaining() == float("inf")
    for _ in range(5):
        assert bo.sleep()
    assert not bo.expired()


# -- messenger wire faults --------------------------------------------
def _mk_pair(lossless=True):
    server = Messenger("server", lossless=lossless)
    client = Messenger("client-side", lossless=lossless)
    server.start()
    client.start()
    return server, client


def test_flipped_control_byte_is_malformed_input():
    payload = encode_frame({"type": "op", "n": 7, "blob": b"\x00" * 32})
    framed = b"\x00\x00\x00\x00" + payload  # outer length word slot
    mutated = _flip_control_byte(framed)[4:]
    assert mutated != payload
    with pytest.raises(MalformedInput):
        decode_frame(mutated)
    decode_frame(payload)  # the unmutated twin still parses


def test_corrupt_frame_on_live_connection_resets_and_replays():
    """The satellite's headline: a corrupted frame mid-session is a
    clean MalformedInput reset at the receiver — never a wedged
    reader — and the lossless replay still lands the op."""
    server, client = _mk_pair()
    try:
        server.register("op", lambda m: {"ok": True, "n": m["n"]})
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["n"] == 0  # warm session
        faults.arm("msgr.corrupt_frame", "oneshot", who="client-side")
        t0 = time.monotonic()
        rep = client.call(server.addr, {"type": "op", "n": 1},
                          timeout=20)
        assert rep["n"] == 1  # replayed uncorrupted after the reset
        assert time.monotonic() - t0 < 15
        assert faults.snapshot()["msgr.corrupt_frame"] == 1
    finally:
        client.shutdown()
        server.shutdown()


def test_corrupt_frame_lossy_session_fails_fast_then_recovers():
    """On a lossy (client-like) session there is no replay: the op
    must fail FAST when the session dies — not hang to timeout — and
    the next op gets a fresh session."""
    server, client = _mk_pair(lossless=False)
    try:
        server.register("op", lambda m: {"ok": True, "n": m["n"]})
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["n"] == 0
        faults.arm("msgr.corrupt_frame", "oneshot", who="client-side")
        t0 = time.monotonic()
        with pytest.raises((OSError, TimeoutError)):
            client.call(server.addr, {"type": "op", "n": 1},
                        timeout=30)
        assert time.monotonic() - t0 < 20, \
            "corrupted frame wedged the call instead of failing fast"
        rep = client.call(server.addr, {"type": "op", "n": 2},
                          timeout=10)
        assert rep["n"] == 2
    finally:
        client.shutdown()
        server.shutdown()


def test_close_mid_frame_replays_through_reconnect():
    server, client = _mk_pair()
    try:
        server.register("op", lambda m: {"ok": True, "n": m["n"]})
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["n"] == 0
        faults.arm("msgr.close_mid_frame", "oneshot",
                   who="client-side")
        rep = client.call(server.addr, {"type": "op", "n": 1},
                          timeout=20)
        assert rep["n"] == 1
        assert faults.snapshot()["msgr.close_mid_frame"] == 1
    finally:
        client.shutdown()
        server.shutdown()


def test_drop_frame_lossless_replay_recovers():
    server, client = _mk_pair()
    try:
        server.register("op", lambda m: {"ok": True, "n": m["n"]})
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["n"] == 0
        faults.arm("msgr.drop_frame", "oneshot", who="client-side")
        rep = client.call(server.addr, {"type": "op", "n": 1},
                          timeout=20)
        assert rep["n"] == 1
        assert faults.snapshot()["msgr.drop_frame"] == 1
    finally:
        client.shutdown()
        server.shutdown()


def test_dup_frame_absorbed_by_dedup():
    server, client = _mk_pair()
    seen = []
    try:
        server.register("op",
                        lambda m: (seen.append(m["n"]),
                                   {"ok": True, "n": m["n"]})[1])
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["n"] == 0
        faults.arm("msgr.dup_frame", "oneshot", who="client-side")
        rep = client.call(server.addr, {"type": "op", "n": 1},
                          timeout=20)
        assert rep["n"] == 1
        assert faults.snapshot()["msgr.dup_frame"] == 1
        time.sleep(0.3)  # give a re-executed dup time to surface
        assert seen.count(1) == 1, f"dup re-executed: {seen}"
    finally:
        client.shutdown()
        server.shutdown()


def test_delay_frame_injects_latency():
    server, client = _mk_pair()
    try:
        server.register("op", lambda m: {"ok": True})
        assert client.call(server.addr, {"type": "op", "n": 0},
                           timeout=10)["ok"]
        faults.arm("msgr.delay_frame", "oneshot", who="client-side",
                   delay="0.3")
        t0 = time.monotonic()
        assert client.call(server.addr, {"type": "op", "n": 1},
                           timeout=10)["ok"]
        assert time.monotonic() - t0 >= 0.25
    finally:
        client.shutdown()
        server.shutdown()


# -- objectstore / WAL faults -----------------------------------------
def test_memstore_read_eio_is_one_op():
    st = MemStore()
    st.queue_transaction(
        Transaction().create_collection("pg1").write(
            "pg1", "a", 0, b"hello"))
    faults.arm("os.read_eio", "oneshot")
    with pytest.raises(OSError):
        st.read("pg1", "a")
    assert st.read("pg1", "a") == b"hello"  # transient, not sticky


def test_wal_torn_append_rolls_back_and_store_survives(tmp_path):
    st = WALStore(str(tmp_path / "s"))
    st.mkfs()
    st.mount()
    st.queue_transaction(
        Transaction().create_collection("pg1").write(
            "pg1", "a", 0, b"good"))
    faults.arm("os.torn_append", "oneshot")
    with pytest.raises(OSError):
        st.queue_transaction(
            Transaction().write("pg1", "torn", 0, b"x" * 512))
    # the rollback cut the torn bytes: the store keeps serving and
    # journaling, and the failed txn never became visible
    with pytest.raises(KeyError):
        st.read("pg1", "torn")
    st.queue_transaction(
        Transaction().write("pg1", "b", 0, b"after"))
    assert st.read("pg1", "b") == b"after"
    # crash image: a fresh mount replays only the good records
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.read("pg1", "a") == b"good"
    assert st2.read("pg1", "b") == b"after"
    with pytest.raises(KeyError):
        st2.read("pg1", "torn")
    st2.umount()
    st.umount()


def test_wal_fsync_eio_poisons_store(tmp_path):
    st = WALStore(str(tmp_path / "s"))
    st.mkfs()
    st.mount()
    faults.arm("os.fsync_eio", "oneshot")
    with pytest.raises(OSError):
        st.queue_transaction(
            Transaction().create_collection("pg1").write(
                "pg1", "a", 0, b"x"))
    # the journal cannot prove durability anymore: the store must
    # refuse every later write, not limp along un-journaled
    with pytest.raises((OSError, AssertionError)):
        st.queue_transaction(
            Transaction().create_collection("pg2"))


# -- osd write-pipeline / degraded reads ------------------------------
def test_replica_kill_points_op_still_acks():
    """A replica dying before OR after its WAL commit must not fail
    the client op: min_size acks carry it, and the data reads back."""
    c = MiniCluster(n_osds=3, hosts=3, config=_fast_conf()).start()
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        cli = c.client("killpoints")
        for name, oid, val in (("osd.kill_before_commit", "kb",
                                b"alpha"),
                               ("osd.kill_after_commit", "ka",
                                b"beta")):
            _pool, _ps, up = cli._up(1, oid)
            faults.arm(name, "oneshot", who=f"osd.{up[1]}")
            cli.put(1, oid, val)  # acks via the surviving min_size
            assert faults.snapshot()[name] == 1
            assert cli.get(1, oid) == val
    finally:
        c.shutdown()


def test_degraded_ec_read_decodes_counts_and_repairs():
    """A shard read EIO degrades instead of failing: the client
    decodes from survivors, the holder books ``degraded_reads`` (perf
    counter AND pool-stats), and recovery re-decodes the shard."""
    c = MiniCluster(n_osds=4, hosts=4, config=_fast_conf()).start()
    try:
        c.create_ec_pool(2, "flt21",
                         {"plugin": "jerasure",
                          "technique": "reed_sol_van",
                          "k": "2", "m": "1", "w": "8"}, pg_num=8)
        cli = c.client("degraded")
        data = bytes(range(256)) * 8
        cli.put(2, "degobj", data)
        _pool, ps, up = cli._up(2, "degobj")
        victim = up[0]  # shard 0's holder: first probed on read
        faults.arm("osd.shard_read_eio", "oneshot",
                   who=f"osd.{victim}")
        assert cli.get(2, "degobj") == data  # decoded from survivors
        assert faults.snapshot()["osd.shard_read_eio"] == 1
        svc = c.osds[victim]
        assert svc.pc.dump().get("degraded_reads", 0) >= 1
        # the bad shard was dropped for repair: recovery re-decodes it
        cid = pg_cid(2, ps)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if svc.store.collection_exists(cid) and \
                    svc.store.stat(cid, "degobj.s0") is not None:
                break
            time.sleep(0.1)
        assert svc.store.stat(cid, "degobj.s0") is not None, \
            "EIO'd shard never repaired"
        # the accounting reaches the monitor's pool-stats surface
        deadline = time.monotonic() + 20.0
        got = 0
        while time.monotonic() < deadline:
            cur = c.pool_stats(2)["pools"].get("2", {}).get(
                "current", {})
            got = cur.get("degraded_reads", 0)
            if got >= 1:
                break
            time.sleep(0.2)
        assert got >= 1, "degraded_reads never surfaced in pool-stats"
    finally:
        c.shutdown()


# -- client retry pacing ----------------------------------------------
def test_client_retry_deadline_bounds_retry_storm():
    """The regression the backoff budget exists for: with every OSD
    dead, put(retries=1000) must give up when the SLEEP budget is
    spent — seconds — not pace out 1000 fixed sleeps."""
    c = MiniCluster(n_osds=3, hosts=3).start()  # default (slow)
    # failure detection: the map keeps the dead OSDs "up", so every
    # attempt fails at the transport and the retry loop is the only
    # thing between the client and a 1000-sleep stall
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        cli = c.client("deadline")
        cli.put(1, "warm", b"x")
        c.conf.set("client_retry_deadline", 0.5)
        for o in list(c.osds):
            c.kill_osd(o)
        t0 = time.monotonic()
        with pytest.raises((OSError, TimeoutError, KeyError)):
            cli.put(1, "unreachable", b"y", retries=1000)
        assert time.monotonic() - t0 < 30, \
            "retry loop ignored the sleep budget"
    finally:
        c.shutdown()


# -- monitor faults ---------------------------------------------------
def test_mon_drop_pg_stats_fires_and_health_recovers():
    c = MiniCluster(n_osds=2, hosts=2, config=_fast_conf()).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=2)
        faults.arm("mon.drop_pg_stats", "count", count=3)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and \
                faults.snapshot().get("mon.drop_pg_stats", 0) < 3:
            time.sleep(0.1)
        assert faults.snapshot().get("mon.drop_pg_stats", 0) >= 3
        faults.clear()
        c.wait_for_health_ok(timeout=20.0)
    finally:
        c.shutdown()


def test_mon_isolate_rank_fires_and_quorum_serves():
    conf = _fast_conf()
    conf.set("mon_lease", 0.3)
    conf.set("mon_election_timeout", 0.5)
    c = MiniCluster(n_osds=2, hosts=2, config=conf,
                    n_mons=3).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=2)
        faults.arm("mon.isolate_rank", "count", count=30,
                   who="mon.2")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and \
                not faults.snapshot().get("mon.isolate_rank"):
            time.sleep(0.1)
        assert faults.snapshot().get("mon.isolate_rank", 0) >= 1
        faults.clear()
        # the surviving majority (and, after healing, all three)
        # still serves commands
        assert "status" in c.health()
        c.wait_for_health_ok(timeout=20.0)
    finally:
        c.shutdown()


# -- the seeded chaos soak --------------------------------------------
def test_thrasher_smoke_seeded():
    """The tier-1 chaos gate: a short seeded soak with the full
    default fault spec armed must end with zero acked-write loss,
    HEALTH_OK, clean lockdep/span planes, and every armed failpoint
    actually fired (rec["ok"] folds all of it)."""
    rec = thrasher.soak(seed=8, duration=3.0, n_osds=4,
                        settle_timeout=45.0)
    assert rec["ok"], rec
    assert rec["ops"] > 0
    assert rec["fired"], "no failpoint ever fired under the spec"


@pytest.mark.slow
def test_thrasher_full_soak():
    """The full soak (CI's -m slow lane): longer, more daemons, a
    thrashed 3-monitor quorum."""
    rec = thrasher.soak(seed=8, duration=15.0, n_osds=5, n_mons=3,
                        settle_timeout=90.0)
    assert rec["ok"], rec


def test_perf_history_ingests_chaos_records(tmp_path):
    (tmp_path / "CHAOS_r01.json").write_text(json.dumps(
        {"kind": "chaos", "seed": 8, "ops": 120, "lost": 0,
         "health_converge_s": 1.2, "ok": True}))
    assert perf_history.main([str(tmp_path), "--check"]) == 0
    rows = perf_history.load_all(str(tmp_path))
    assert rows[-1]["metrics"]["chaos_ops"] == 120.0
    # lost acked writes are a regression outright, no threshold
    (tmp_path / "CHAOS_r02.json").write_text(json.dumps(
        {"kind": "chaos", "seed": 9, "ops": 118, "lost": 2,
         "health_converge_s": 1.0, "ok": False}))
    assert perf_history.main([str(tmp_path), "--check"]) == 1


def test_perf_history_ingests_race_records(tmp_path):
    (tmp_path / "RACE_r01.json").write_text(json.dumps(
        {"kind": "race", "seed": 8, "violations": 0, "lost": 0,
         "checked": 50, "overhead_pct": 3.2, "ok": True}))
    assert perf_history.main([str(tmp_path), "--check"]) == 0
    rows = perf_history.load_all(str(tmp_path))
    assert rows[-1]["metrics"]["race_violations"] == 0.0
    assert rows[-1]["metrics"]["race_overhead_pct"] == 3.2
    # ANY recorded data-race violation is a regression outright
    (tmp_path / "RACE_r02.json").write_text(json.dumps(
        {"kind": "race", "seed": 8, "violations": 1, "lost": 0,
         "checked": 50, "overhead_pct": 3.0, "ok": False}))
    assert perf_history.main([str(tmp_path), "--check"]) == 1
    # ...and so is a checker-overhead breach, even with ok=true
    (tmp_path / "RACE_r02.json").write_text(json.dumps(
        {"kind": "race", "seed": 8, "violations": 0, "lost": 0,
         "checked": 50, "overhead_pct": 12.5, "ok": True}))
    assert perf_history.main([str(tmp_path), "--check"]) == 1
