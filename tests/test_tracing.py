"""Unit tests for the tracing plane (common/tracing.py) and the
observability satellites: log2 latency histograms, strict perf-counter
type checks, idempotent TrackedOp.finish."""

import threading

import pytest

from ceph_tpu.common import tracing
from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.common.perf_counters import PerfCounters
from ceph_tpu.common.tracing import NOOP_SPAN, Tracer


# -- spans ------------------------------------------------------------------

def test_span_basics_and_dump():
    t = Tracer("svc")
    with t.start_span("op", tags={"pool": 1}) as sp:
        sp.log("phase-1")
        sp.set_tag("oid", "x")
        assert t.current() is sp
    assert t.current() is None
    d = t.dump()
    assert d["service"] == "svc"
    (s,) = d["spans"]
    assert s["name"] == "op" and s["parent_id"] is None
    assert s["tags"] == {"pool": 1, "oid": "x"}
    assert s["events"][0]["event"] == "phase-1"
    assert s["finished"] and s["duration"] >= 0


def test_thread_local_parenting_and_trace_id():
    t = Tracer("svc")
    with t.start_span("root") as root:
        with t.start_span("child") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            assert t.current() is child
        assert t.current() is root
    # siblings from another thread do NOT inherit this thread's stack
    seen = {}

    def other():
        with t.start_span("elsewhere") as sp:
            seen["parent"] = sp.parent_id

    th = threading.Thread(target=other)
    th.start()
    th.join()
    assert seen["parent"] is None


def test_wire_carrier_round_trip():
    a, b = Tracer("client"), Tracer("osd")
    with a.start_span("put") as sp:
        carrier = Tracer.inject(sp)
    assert carrier["trace_id"] == sp.trace_id
    with b.start_span("handle", child_of=carrier) as remote:
        assert remote.trace_id == sp.trace_id
        assert remote.parent_id == sp.span_id
        assert remote.sampled


def test_require_parent_noop_and_inject_none():
    t = Tracer("svc")
    sp = t.start_span("orphan", require_parent=True)
    assert sp is NOOP_SPAN
    assert Tracer.inject(sp) is None
    with sp:  # context manager is a no-op, records nothing
        sp.log("ignored")
    assert t.dump()["spans"] == []
    # with a live parent the same call makes a real child
    with t.start_span("root") as root:
        with t.start_span("child", require_parent=True) as child:
            assert child.trace_id == root.trace_id


def test_sampling_decided_at_root_and_inherited():
    t = Tracer("svc", sample_rate=0.0)
    with t.start_span("root") as root:
        assert not root.sampled
        carrier = Tracer.inject(root)
        assert carrier["sampled"] is False
    # never recorded, but counted
    assert t.dump()["spans"] == []
    assert t.sampled_out == 1
    # a remote child inherits the unsampled decision even on a
    # sample-everything tracer
    t2 = Tracer("peer", sample_rate=1.0)
    with t2.start_span("handle", child_of=carrier):
        pass
    assert t2.dump()["spans"] == []


def test_ring_bound_and_trace_filter():
    t = Tracer("svc", ring_size=4)
    ids = []
    for i in range(8):
        with t.start_span(f"op{i}") as sp:
            ids.append(sp.trace_id)
    d = t.dump()
    assert [s["name"] for s in d["spans"]] == \
        ["op4", "op5", "op6", "op7"]
    only = t.dump(trace_id=ids[-1])
    assert [s["name"] for s in only["spans"]] == ["op7"]


def test_span_finish_idempotent_and_error_tag():
    t = Tracer("svc")
    with pytest.raises(ValueError):
        with t.start_span("boom") as sp:
            sp.finish()  # explicit finish inside the with
            raise ValueError("x")
    d = t.dump()
    assert len(d["spans"]) == 1  # not double-recorded
    assert t.finished == 1
    # the error raised AFTER finish is still not lost silently: the
    # context manager only tags spans it finishes itself
    with pytest.raises(RuntimeError):
        with t.start_span("tagged"):
            raise RuntimeError("y")
    tagged = t.dump()["spans"][-1]
    assert "RuntimeError" in tagged["tags"]["error"]


def test_scope_adopts_span_on_another_thread():
    t = Tracer("svc")
    got = {}
    with t.start_span("fanout-root") as root:
        def worker():
            with t.scope(root):
                with t.start_span("pushed") as sp:
                    got["parent"] = sp.parent_id
            got["after"] = t.current()

        th = threading.Thread(target=worker)
        th.start()
        th.join()
    assert got["parent"] == root.span_id
    assert got["after"] is None


def test_active_spans_and_abandon():
    t = Tracer("svc")
    sp = t.start_span("leaky")
    assert any(s is sp for _svc, s in tracing.active_spans())
    leaked = t.abandon_active()
    assert leaked == [sp]
    assert not any(s is sp for _svc, s in tracing.active_spans())
    # a later finish of an abandoned span must not blow up
    sp.finish()


# -- perf-counter satellites -------------------------------------------------

def test_hist_log2_bucketing_resolves_subsecond():
    pc = PerfCounters("x")
    pc.add_histogram("lat", buckets=32)  # min 1 µs
    for v in (5e-7, 2e-6, 1e-3, 0.5):
        pc.hist_add("lat", v)
    buckets = pc.dump()["lat"]["buckets"]
    assert pc.dump()["lat"]["min"] == 1e-6
    assert buckets[0] == 1               # <= 1 µs floor
    assert buckets[2] == 1               # 2 µs -> [2, 4) µs
    assert buckets[10] == 1              # 1 ms -> [512, 1024) µs
    assert buckets[19] == 1              # 0.5 s -> [0.26, 0.52) s
    # four distinct sub-second samples, four distinct buckets — the
    # old int(value).bit_length() collapsed all of these into bucket 0
    assert sum(buckets) == 4
    # clamping at the top
    pc.hist_add("lat", 1e12)
    assert pc.dump()["lat"]["buckets"][-1] == 1


def test_hist_custom_min_value():
    pc = PerfCounters("x")
    pc.add_histogram("sz", buckets=8, min_value=1)
    pc.hist_add("sz", 1)
    pc.hist_add("sz", 3)
    pc.hist_add("sz", 1024)
    b = pc.dump()["sz"]["buckets"]
    assert b[0] == 1 and b[2] == 1 and b[-1] == 1


def test_strict_type_checks_on_updates():
    pc = PerfCounters("x")
    pc.add_u64_counter("ops")
    pc.add_u64("gauge")
    pc.add_histogram("hist")
    pc.add_u64_avg("avg")
    with pytest.raises(AssertionError, match="no key"):
        pc.inc("tpyo")
    with pytest.raises(AssertionError, match="no key"):
        pc.set("tpyo", 1)
    with pytest.raises(AssertionError):
        pc.inc("hist")  # histograms take hist_add, not inc
    with pytest.raises(AssertionError):
        pc.set("avg", 2)
    with pytest.raises(AssertionError):
        pc.hist_add("ops", 1)
    pc.inc("ops")
    pc.set("gauge", 7)
    assert pc.dump()["ops"] == 1 and pc.dump()["gauge"] == 7


# -- op tracker satellite ----------------------------------------------------

def test_tracked_op_finish_idempotent():
    tr = OpTracker()
    op = tr.create("osd_op", "write x")
    op.finish()
    served = tr.dump_historic_ops()["served_total"]
    events = len(op.events)
    op.finish()  # double finish: no-op
    assert tr.dump_historic_ops()["served_total"] == served == 1
    assert len(op.events) == events
    assert sum(1 for e in op.events if e[1] == "done") == 1
    assert len(tr.dump_historic_ops()["ops"]) == 1
    # the context-manager path double-finishes by design (explicit +
    # __exit__): still one history entry
    with tr.create("osd_op", "read y") as op2:
        op2.finish()
    assert tr.dump_historic_ops()["served_total"] == 2
