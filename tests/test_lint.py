"""Static lint enforcement: the concurrency rules
(tools/lint_concurrency.py, CONC00x), the JAX compile-hygiene rules
(tools/lint_jax.py, JAX00x), and the wire-schema rules
(tools/lint_wire.py, WIRE00x).  Rule unit tests run on synthetic
modules; the enforcement tests keep ``ceph_tpu/`` clean — a new raw
lock, a blocking call under a lock, a device call in a messenger
handler, a fresh host-device sync point in a hot module, or ad-hoc
JSON on a wire/disk path fails CI here unless explicitly justified
(``# conc-ok:`` / ``# jax-ok:`` / ``# wire-ok:`` inline, or the
committed allowlists below)."""

import pathlib
import textwrap

from tools.lint_concurrency import lint_file, lint_paths
from tools import lint_async, lint_jax, lint_wire

REPO = pathlib.Path(__file__).resolve().parent.parent


def _lint(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    return lint_file(f)


def codes(violations):
    return [v.code for v in violations]


def test_repo_is_clean():
    violations = lint_paths([REPO / "ceph_tpu"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_raw_lock_construction_flagged(tmp_path):
    vs = _lint(tmp_path, """
        import threading
        a = threading.Lock()
        b = threading.RLock()
    """)
    assert codes(vs) == ["CONC001", "CONC001"]


def test_registry_lock_not_flagged(tmp_path):
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        a = make_lock("x::y")
    """)
    assert vs == []


def test_blocking_call_under_lock_flagged(tmp_path):
    vs = _lint(tmp_path, """
        import os, time

        class S:
            def write(self, f):
                with self._lock:
                    os.fsync(f.fileno())

            def wait_holding(self):
                with self._pg_lock(1, 2):
                    time.sleep(1)

            def rx(self, sock):
                with self.buf_lock:
                    sock.recv(4)

            def sub(self):
                with self._lock:
                    self.sched.submit("client", lambda: 1)
    """)
    assert codes(vs) == ["CONC002"] * 4


def test_blocking_call_outside_lock_ok(tmp_path):
    vs = _lint(tmp_path, """
        import os, time

        class S:
            def write(self, f):
                with self._lock:
                    n = 1
                os.fsync(f.fileno())
                time.sleep(0.1)

            def pool(self):
                # executor submit does not block; only sched.submit
                with self._lock:
                    self._pool.submit(print)
    """)
    assert vs == []


def test_nested_def_under_lock_not_flagged(tmp_path):
    """A function DEFINED under a lock runs later, lock-free."""
    vs = _lint(tmp_path, """
        import time

        def outer(self):
            with self._lock:
                def cb():
                    time.sleep(1)
                return cb
    """)
    assert vs == []


def test_swallowing_runloop_except_flagged(tmp_path):
    vs = _lint(tmp_path, """
        def _reader(self):
            while self._running:
                try:
                    step()
                except Exception:
                    pass

        def _serve(self):
            while True:
                try:
                    step()
                except:
                    log(1)
    """)
    assert codes(vs) == ["CONC003", "CONC003"]


def test_logging_or_narrow_runloop_except_ok(tmp_path):
    vs = _lint(tmp_path, """
        def _loop(self):
            while self._running:
                try:
                    step()
                except Exception as e:
                    self.log.derr(repr(e))
                try:
                    step()
                except OSError:
                    break

        def not_a_loop(self):
            try:
                step()
            except Exception:
                pass
    """)
    assert vs == []


def test_span_outside_with_flagged(tmp_path):
    vs = _lint(tmp_path, """
        def leaky(self):
            sp = self.tracer.start_span("op")
            work()
            sp.finish()

        def assigned_from_call(tracer):
            return tracer.start_span("escapes")
    """)
    assert codes(vs) == ["CONC004", "CONC004"]


def test_span_in_with_ok(tmp_path):
    vs = _lint(tmp_path, """
        def clean(self):
            with self.tracer.start_span("op", tags={"x": 1}) as sp:
                sp.log("phase")
            with self.tracer.start_span("a") as a, open("f") as f:
                pass

        def suppressed(self):
            sp = self.tracer.start_span("op")  # conc-ok: handed to a callback that finishes it
            return sp
    """)
    assert vs == []


def test_conc_ok_suppression(tmp_path):
    vs = _lint(tmp_path, """
        import os, threading
        a = threading.Lock()  # conc-ok: test fixture, not a daemon lock

        def write(self, f):
            with self._lock:
                os.fsync(f.fileno())  # conc-ok: the fsync is the ack point
    """)
    assert vs == []


def test_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text("import threading\nx = threading.Lock()\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_concurrency.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "CONC001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_concurrency.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# JAX compile-hygiene lint (tools/lint_jax.py)
# ---------------------------------------------------------------------------

def _jlint(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint_jax.lint_file(f)


# Known-acceptable JAX002 hits live in tools/lint_jax.py (ALLOWLIST)
# so the CLI, the unified tools/lint.py runner and this test share one
# source of truth about what is clean.
JAX_ALLOWLIST = lint_jax.ALLOWLIST


def _jax_allowlisted(v):
    return lint_jax.allowlisted(v)


def test_repo_is_jax_clean():
    violations = [v for v in lint_jax.lint_paths([REPO / "ceph_tpu"])
                  if not _jax_allowlisted(v)]
    assert not violations, "\n".join(str(v) for v in violations)


def test_jax001_device_call_under_lock(tmp_path):
    vs = _jlint(tmp_path, """
        import jax.numpy as jnp

        class S:
            def update(self):
                with self._lock:
                    self.table = jnp.zeros((4, 4))

            def ok(self):
                with self._lock:
                    n = 1
                return jnp.zeros((4, 4))
    """)
    assert codes(vs) == ["JAX001"]


def test_jax001_device_call_in_handler(tmp_path):
    vs = _jlint(tmp_path, """
        import jax.numpy as jnp

        class OSD:
            def _h_shard_write(self, msg):
                return {"sum": jnp.sum(jnp.asarray(msg["data"]))}

            def helper(self, data):
                return jnp.sum(data)
    """)
    assert codes(vs) == ["JAX001", "JAX001"]


def test_jax002_sync_points_hot_module_only(tmp_path):
    src = """
        import numpy as np

        def hot(x):
            v = x.item()
            y = np.asarray(x)
            x.block_until_ready()
            return float(v)

        def fine(x):
            return int(x.shape[0])

        class C:
            def __init__(self, m):
                self.m = np.asarray(m)  # setup, not the hot path
    """
    # same source: flagged under a hot-module name, silent elsewhere
    hot = _jlint(tmp_path, src, name="engine.py")
    assert codes(hot) == []
    (tmp_path / "ec").mkdir()
    f = tmp_path / "ec" / "engine.py"
    f.write_text(textwrap.dedent(src))
    vs = lint_jax.lint_file(f, root=tmp_path)
    assert codes(vs) == ["JAX002"] * 4


def test_jax002_suppression(tmp_path):
    (tmp_path / "ec").mkdir()
    f = tmp_path / "ec" / "engine.py"
    f.write_text(textwrap.dedent("""
        import numpy as np

        def egress(x):
            return np.asarray(x)  # jax-ok: the public host-API boundary
    """))
    assert lint_jax.lint_file(f, root=tmp_path) == []


def test_jax003_jit_over_self_and_global(tmp_path):
    vs = _jlint(tmp_path, """
        import functools
        import jax

        class Engine:
            @jax.jit
            def encode(self, data):
                return data @ self.matrix

        @functools.partial(jax.jit, static_argnames=("k",))
        def counted(x, k):
            global calls
            calls += 1
            return x

        @jax.jit
        def clean(bm, planes):
            return bm @ planes
    """)
    assert codes(vs) == ["JAX003", "JAX003"]


def test_jax004_python_if_on_traced(tmp_path):
    vs = _jlint(tmp_path, """
        import functools
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x

        @functools.partial(jax.jit, static_argnames=("mode",))
        def ok_static(x, mode):
            if mode == "fast":
                return x
            return x * 2
    """)
    assert codes(vs) == ["JAX004"]


def test_jax_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def f(self):
            with self._lock:
                return jnp.zeros(3)
    """))
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_jax.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "JAX001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_jax.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# wire-schema lint (tools/lint_wire.py)
# ---------------------------------------------------------------------------

# Synthetic rule tests pass the registry sets explicitly so they
# exercise the rules, not the live registry.
_COVERED = {"Covered"}
_FRAMES = {"__hello__", "__ack__", "__reply__"}


def _wlint(tmp_path, source, rel="msg/peer.py"):
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return lint_wire.lint_file(f, root=tmp_path, covered=_COVERED,
                               frames=_FRAMES)


# Known-acceptable WIRE hits in ceph_tpu/ — every one a deliberate
# seam, marked inline with `# wire-ok:`; this committed allowlist is
# for hits that cannot carry an inline mark.  Entries are
# (path suffix, code, substring of the flagged line).
WIRE_ALLOWLIST = ()


def _wire_allowlisted(v):
    src = (REPO / "ceph_tpu" / ".." / v.path).resolve()
    try:
        line = src.read_text().splitlines()[v.line - 1]
    except (OSError, IndexError):
        return False
    return any(v.path.endswith(path) and v.code == code and sub in line
               for path, code, sub in WIRE_ALLOWLIST)


def test_repo_is_wire_clean():
    violations = [v for v in lint_wire.lint_paths([REPO / "ceph_tpu"])
                  if not _wire_allowlisted(v)]
    assert not violations, "\n".join(str(v) for v in violations)


def test_wire001_raw_json_on_wire_path(tmp_path):
    src = """
        import json

        def save(h):
            return json.dumps(h).encode()

        def load(raw):
            return json.loads(raw)
    """
    vs = _wlint(tmp_path, src, rel="os/store.py")
    assert codes(vs) == ["WIRE001", "WIRE001"]
    # the same source outside the wire/disk scope is not flagged
    assert _wlint(tmp_path, src, rel="tools/cli.py") == []
    # and the envelope seam itself is exempt
    assert _wlint(tmp_path, src, rel="common/encoding.py") == []


def test_wire001_tracks_json_alias(tmp_path):
    vs = _wlint(tmp_path, """
        import json as _json

        def save(h):
            return _json.dumps(h)
    """, rel="osdmap/enc.py")
    assert codes(vs) == ["WIRE001"]


def test_wire001_suppression(tmp_path):
    vs = _wlint(tmp_path, """
        import json

        def codec(msg):
            return json.dumps(msg)  # wire-ok: the codec seam itself
    """, rel="msg/frames.py")
    assert vs == []


def test_wire002_unregistered_wire_class(tmp_path):
    vs = _wlint(tmp_path, """
        class Rogue:
            def to_dict(self):
                return {}

            @classmethod
            def from_dict(cls, d):
                return cls()

        class Covered:
            def to_dict(self):
                return {}

            @classmethod
            def from_dict(cls, d):
                return cls()

        class NotWireShaped:
            def to_dict(self):
                return {}
    """, rel="osdmap/types.py")
    assert codes(vs) == ["WIRE002"]
    assert "Rogue" in str(vs[0])


def test_wire002_scope_is_wire_dirs_only(tmp_path):
    src = """
        class Rogue:
            def to_dict(self):
                return {}

            @classmethod
            def from_dict(cls, d):
                return cls()
    """
    assert _wlint(tmp_path, src, rel="services/helper.py") == []


def test_wire003_unregistered_frame_literal(tmp_path):
    vs = _wlint(tmp_path, """
        def dispatch(self, type_):
            if type_ == "__hello__":
                return 1
            if type_ == "__evil__":
                return 2
            if type_ in ("__ack__", "__reply__"):
                return 3
    """)
    assert codes(vs) == ["WIRE003"]
    assert "__evil__" in str(vs[0])
    # frame literals outside msg/ are not this rule's business
    assert _wlint(tmp_path, """
        def f(x):
            return x == "__evil__"
    """, rel="os/store.py") == []


def test_wire004_swallowed_decode(tmp_path):
    vs = _wlint(tmp_path, """
        def read(self, raw):
            try:
                rec = decode(raw)
            except Exception:
                pass

        def read2(self, raw):
            try:
                rec = self.codec.loads(raw)
            except:
                continue
    """, rel="os/store.py")
    assert codes(vs) == ["WIRE004", "WIRE004"]


def test_wire004_narrow_or_surfacing_ok(tmp_path):
    vs = _wlint(tmp_path, """
        def read(self, raw):
            try:
                rec = decode(raw)
            except MalformedInput:
                pass
            try:
                rec = decode(raw)
            except Exception as e:
                self.log.derr(repr(e))
            try:
                step()
            except Exception:
                pass
    """, rel="os/store.py")
    assert vs == []


def test_wire_cli_exit_status(tmp_path):
    import subprocess
    import sys

    (tmp_path / "os").mkdir()
    bad = tmp_path / "os" / "bad.py"
    bad.write_text("import json\nx = json.dumps({})\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_wire.py"),
         str(tmp_path / "os")], capture_output=True, text=True)
    assert p.returncode == 1
    assert "WIRE001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_wire.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# observability lint (tools/lint_obs.py): counter names must live in
# the central registry (ceph_tpu/common/counters.py), so the
# daemonperf/telemetry column schemas can never silently drift from
# the counters the daemons actually book
# ---------------------------------------------------------------------------

from tools import lint_obs  # noqa: E402


def _olint(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    return lint_obs.lint_file(f)


def test_repo_is_obs_clean():
    violations = lint_obs.lint_paths([REPO / "ceph_tpu"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_obs001_literal_names(tmp_path):
    vs = _olint(tmp_path, """
        pc.inc("ops_w")
        _pc.hist_add("op_lat", 0.1)
        pc.inc("not_a_counter")
        self.pc.add_u64_counter("also_missing")
    """)
    assert [v.code for v in vs] == ["OBS001", "OBS001"]
    assert "not_a_counter" in vs[0].message
    assert "also_missing" in vs[1].message


def test_obs001_for_loop_declarations(tmp_path):
    vs = _olint(tmp_path, """
        for key in ("ops_w", "ops_r"):
            pc.add_u64_counter(key)
        for key in ("ops_w", "drifted"):
            pc.add_u64_counter(key)
    """)
    assert len(vs) == 1 and "drifted" in vs[0].message


def test_obs001_fstring_patterns(tmp_path):
    # f"{kind}_ops" matches encode_ops/decode_ops -> fine; a pattern
    # matching NOTHING in the registry is an orphaned family
    vs = _olint(tmp_path, """
        pc.inc(f"{kind}_ops")
        pc.inc(f"zz_{kind}_orphan")
    """)
    assert len(vs) == 1 and "zz_" in vs[0].message


def test_obs001_dynamic_needs_suppression(tmp_path):
    vs = _olint(tmp_path, """
        pc.inc(some_variable)
        pc.inc(other_variable)  # obs-ok: computed from registry
    """)
    assert len(vs) == 1


def test_obs001_scope_is_counter_receivers_only(tmp_path):
    """conf.set / Event.set / arbitrary .inc receivers are not
    counter objects."""
    vs = _olint(tmp_path, """
        conf.set("whatever_option", 1)
        ev.set()
        counterish.inc("nope")
        self._done.set()
    """)
    assert vs == []


def test_obs_telemetry_columns_in_registry():
    """The daemonperf column schema (and therefore `top`/`history`)
    must only reference registered counters — the drift this lint
    family exists to prevent."""
    from ceph_tpu.common.counters import all_names
    from ceph_tpu.tools.telemetry import DEFAULT_COLUMNS

    names = all_names()
    for _glob, key, header in DEFAULT_COLUMNS:
        assert key in names, (
            f"daemonperf column {header!r} reads counter {key!r} "
            f"which is not in ceph_tpu/common/counters.py")


def test_obs002_registry_sync(monkeypatch):
    """Every attribution stage and copy-ledger site must have its
    registry row; dropping one (or adding a stage without the
    counter) is an OBS002 violation, not a zero-column two PRs
    later."""
    from ceph_tpu.common import attribution, copytrack

    assert lint_obs.lint_registry_sync() == []
    monkeypatch.setattr(attribution, "STAGES",
                        attribution.STAGES + ("made_up_stage",))
    vs = lint_obs.lint_registry_sync()
    assert [v.code for v in vs] == ["OBS002"]
    assert "made_up_stage" in vs[0].message
    monkeypatch.setattr(copytrack, "SITES",
                        copytrack.SITES + ("rogue_site",))
    vs = lint_obs.lint_registry_sync()
    # the bogus stage + the bogus site's _bytes and _copies rows
    assert len(vs) == 3
    assert any("rogue_site_bytes" in v.message for v in vs)
    assert any("rogue_site_copies" in v.message for v in vs)


def test_obs003_prometheus_export_roundtrip(monkeypatch):
    """Every registered counter must come back from to_prometheus
    with its sanitized family HELP header; an exporter that drops a
    family (or a sanitize collision merging two types) is OBS003."""
    from ceph_tpu.common import counters
    from ceph_tpu.tools import telemetry

    assert lint_obs.lint_prometheus_export() == []
    # exporter drift: the scrape silently loses one family
    real = telemetry.to_prometheus

    def dropping(snapshot, prefix="ceph_tpu"):
        return "\n".join(
            line for line in real(snapshot, prefix).splitlines()
            if "ceph_tpu_ops_w" not in line) + "\n"

    monkeypatch.setattr(telemetry, "to_prometheus", dropping)
    vs = lint_obs.lint_prometheus_export()
    assert vs and all(v.code == "OBS003" for v in vs)
    assert any("ops_w" in v.message for v in vs)
    monkeypatch.setattr(telemetry, "to_prometheus", real)
    # sanitization collision: 'op.lat' (u64) merges into the family
    # of the registered 'op_lat' histogram -> conflicting # TYPE
    reg = {fam: dict(names)
           for fam, names in counters.REGISTRY.items()}
    reg["client"]["op.lat"] = counters.U64
    monkeypatch.setattr(counters, "REGISTRY", reg)
    vs = lint_obs.lint_prometheus_export()
    collisions = [v for v in vs if "merges" in v.message]
    assert collisions and collisions[0].code == "OBS003"
    assert "op_lat" in collisions[0].message


def test_obs002_profile_start_must_be_gated(tmp_path):
    """The wallclock sampler is off by default: an unconditional
    profile_start() in daemon code is a violation; the admin-verb
    dispatch shape (inside an `if`) and suppressed calls pass."""
    vs = _olint(tmp_path, """
        prof.profile_start()
    """)
    assert [v.code for v in vs] == ["OBS002"]
    vs = _olint(tmp_path, """
        if sub == "start":
            prof.profile_start(hz=200)
        if enabled:
            profile_start()
    """)
    assert vs == []
    vs = _olint(tmp_path, """
        prof.profile_start()  # obs-ok: module-level demo harness
    """)
    assert vs == []


def test_obs002_profile_start_exempt_paths(tmp_path):
    """Tests and the bench drivers start the sampler around bounded
    bursts on purpose — exempt by path."""
    (tmp_path / "tests").mkdir()
    t = tmp_path / "tests" / "test_prof.py"
    t.write_text("prof.profile_start()\n")
    assert lint_obs.lint_file(t) == []
    b = tmp_path / "rados_bench.py"
    b.write_text("prof.profile_start()\n")
    assert lint_obs.lint_file(b) == []


def _clint(tmp_path, source, rel="msg/peer.py"):
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return lint_obs.lint_file(f)


def test_copy001_hot_path_copies_flagged(tmp_path):
    src = """
        def rx(view, arr):
            a = bytes(view)
            b = arr.tobytes()
            return a, b
    """
    vs = _clint(tmp_path, src)
    assert codes(vs) == ["COPY001", "COPY001"]
    assert "bytes(...)" in vs[0].message
    assert ".tobytes()" in vs[1].message
    # the EC hot files are in scope by suffix; siblings are not
    assert codes(_clint(tmp_path, src, rel="ec/engine.py")) == \
        ["COPY001", "COPY001"]
    assert codes(_clint(tmp_path, src, rel="ec/batcher.py")) == \
        ["COPY001", "COPY001"]
    assert _clint(tmp_path, src, rel="ec/registry.py") == []
    # the same source outside the hot data plane is not flagged,
    # and tests are exempt even under a hot directory name
    assert _clint(tmp_path, src, rel="tools/cli.py") == []
    assert _clint(tmp_path, src, rel="tests/msg/test_rx.py") == []


def test_copy001_suppression_requires_reason(tmp_path):
    # same-line mark with a reason
    assert _clint(tmp_path, """
        def ok(view):
            return bytes(view)  # copy-ok: reply payload must outlive the recv segment
    """) == []
    # mark in the comment block directly above the call
    assert _clint(tmp_path, """
        def ok(arr):
            # copy-ok: materialised once at the session boundary and
            # handed to the store by reference
            return arr.tobytes()
    """) == []
    # a bare mark with no reason does not count — the reason is the
    # point of the rule
    vs = _clint(tmp_path, """
        def bad(view):
            return bytes(view)  # copy-ok:
    """)
    assert codes(vs) == ["COPY001"]
    # a mark separated from the call by code does not reach it
    vs = _clint(tmp_path, """
        def bad(view):
            # copy-ok: too far away
            n = len(view)
            return bytes(view)
    """)
    assert codes(vs) == ["COPY001"]


def test_copy001_non_copy_shapes_not_flagged(tmp_path):
    assert _clint(tmp_path, """
        def fine(enc, s):
            a = enc.bytes()        # an encoder method, not a copy
            b = bytes()            # empty construction
            c = bytes(s, "utf-8")  # str encode, not a buffer copy
            return a, b, c
    """) == []


def test_obs_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text('pc.inc("unregistered_thing")\n')
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_obs.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "OBS001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text('pc.inc("ops_w")\n')
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_obs.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# retry-pacing lint (tools/lint_faults.py, FAULT001)
# ---------------------------------------------------------------------------

def _flint(tmp_path, source, name="mod.py"):
    from tools import lint_faults

    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint_faults.lint_file(f)


def test_repo_is_fault_clean():
    """No fixed-interval retry pacing anywhere in ceph_tpu/ or
    tools/: retries go through common/backoff.py (jittered +
    deadline-budgeted) or carry an explicit # fault-ok: reason."""
    from tools import lint_faults

    violations = lint_faults.lint_paths([REPO / "ceph_tpu",
                                         REPO / "tools"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_fault001_sleep_in_retry_loop_flagged(tmp_path):
    vs = _flint(tmp_path, """
        import time

        def fetch(call):
            for attempt in range(3):
                try:
                    return call()
                except OSError:
                    time.sleep(0.3)
    """)
    assert [v.code for v in vs] == ["FAULT001"]


def test_fault001_while_retry_loop_flagged(tmp_path):
    vs = _flint(tmp_path, """
        import time

        def follow(call):
            while True:
                try:
                    return call()
                except (OSError, TimeoutError):
                    pass
                time.sleep(0.25)
    """)
    assert [v.code for v in vs] == ["FAULT001"]


def test_fault001_poll_loop_without_except_ok(tmp_path):
    # waiting on local state is not retry pacing — nothing to storm
    vs = _flint(tmp_path, """
        import time

        def wait(done):
            while not done():
                time.sleep(0.1)
    """)
    assert vs == []


def test_fault001_backoff_sleep_ok(tmp_path):
    vs = _flint(tmp_path, """
        from ceph_tpu.common.backoff import Backoff

        def fetch(call):
            bo = Backoff(base=0.1, deadline=5.0)
            while True:
                try:
                    return call()
                except OSError:
                    if not bo.sleep():
                        raise
    """)
    assert vs == []


def test_fault001_nested_def_not_flagged(tmp_path):
    # a sleep inside an inner callback is a fresh frame, not paced
    # by the outer retry loop
    vs = _flint(tmp_path, """
        import time

        def outer(call, spawn):
            for attempt in range(3):
                try:
                    def cb():
                        time.sleep(1.0)
                    return spawn(cb)
                except OSError:
                    pass
    """)
    assert vs == []


def test_fault001_suppression(tmp_path):
    vs = _flint(tmp_path, """
        import time

        def tick(call):
            while True:
                try:
                    call()
                except OSError:
                    pass
                time.sleep(1.0)  # fault-ok: tick cadence, not retries
    """)
    assert vs == []


def test_fault_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "def f(c):\n"
        "    while True:\n"
        "        try:\n"
        "            return c()\n"
        "        except OSError:\n"
        "            time.sleep(0.3)\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_faults.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "FAULT001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_faults.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# CONC005: unguarded writes to declared race-guarded state
# ---------------------------------------------------------------------------

def test_conc005_unguarded_write_flagged(tmp_path):
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table")
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}

            def reset(self):
                self.table = {}
    """)
    assert codes(vs) == ["CONC005"]
    assert "table" in vs[0].message and "svc::state" in vs[0].message
    assert "_lock" in vs[0].message  # names the lock attr to take


def test_conc005_write_under_declared_lock_ok(tmp_path):
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table")
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}

            def reset(self):
                with self._lock:
                    self.table = {}
    """)
    assert vs == []


def test_conc005_init_and_owned_fields_exempt(tmp_path):
    # __init__ is the single-owner init phase; owned_by_thread fields
    # are writer-confined, not lock-disciplined
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table", owned_by_thread=("scratch",))
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}
                self.scratch = 0

            def sample(self):
                self.scratch += 1
    """)
    assert vs == []


def test_conc005_race_ok_requires_reason(tmp_path):
    suppressed = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table")
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}

            def mount(self):
                self.table = {}  # race-ok: mount-time, single-threaded
    """)
    assert suppressed == []
    bare = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table")
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}

            def mount(self):
                self.table = {}  # race-ok:
    """)
    assert codes(bare) == ["CONC005"]
    assert "no reason" in bare[0].message


def test_conc005_nested_def_resets_held_set(tmp_path):
    # a closure defined under the lock runs LATER, lock-free
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        @guarded_by("svc::state", "table")
        class Svc:
            def __init__(self):
                self._lock = make_lock("svc::state")
                self.table = {}

            def arm(self, timers):
                with self._lock:
                    def fire():
                        self.table = {}
                    timers.append(fire)
    """)
    assert codes(vs) == ["CONC005"]


def test_conc005_module_level_guard_accepts_any_lock(tmp_path):
    # guard's lock is not a self attribute: any lockish with suffices
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        from ceph_tpu.analysis.racecheck import guarded_by

        _mod_lock = make_lock("svc::module")

        @guarded_by("svc::module", "table")
        class Svc:
            def __init__(self):
                self.table = {}

            def reset(self):
                with _mod_lock:
                    self.table = {}
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# Config-option lint (tools/lint_config.py, CONF001)
# ---------------------------------------------------------------------------

from tools import lint_config  # noqa: E402


def _cflint(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    return lint_config.lint_file(f)


def test_repo_is_config_clean():
    violations = lint_config.lint_paths([REPO / "ceph_tpu"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_conf001_unknown_literal_flagged(tmp_path):
    vs = _cflint(tmp_path, """
        def f(ctx):
            return ctx.conf.get("osd_heartbaet_interval")
    """)
    assert [v.code for v in vs] == ["CONF001"]
    assert "osd_heartbaet_interval" in vs[0].message


def test_conf001_known_option_ok(tmp_path):
    vs = _cflint(tmp_path, """
        def f(self, conf):
            conf.set("osd_heartbeat_interval", 1.0)
            self.ctx.conf.add_observer("debug_osd", print)
            return conf.get("osd_pool_default_size")
    """)
    assert vs == []


def test_conf001_subscript_access_checked(tmp_path):
    vs = _cflint(tmp_path, """
        def f(config):
            return config["not_an_option_at_all"]
    """)
    assert [v.code for v in vs] == ["CONF001"]


def test_conf001_fstring_pattern(tmp_path):
    # at least one registered option must match the literal fragments
    ok = _cflint(tmp_path, """
        def f(conf, subsys):
            return conf.get(f"debug_{subsys}")
    """)
    assert ok == []
    gone = _cflint(tmp_path, """
        def f(conf, subsys):
            return conf.get(f"tracing_{subsys}_level")
    """)
    assert [v.code for v in gone] == ["CONF001"]


def test_conf001_non_config_receiver_ignored(tmp_path):
    vs = _cflint(tmp_path, """
        def f(store, d):
            store.get("definitely_not_an_option")
            return d["also_not_an_option"]
    """)
    assert vs == []


def test_conf001_suppression_requires_reason(tmp_path):
    ok = _cflint(tmp_path, """
        def f(conf, name):
            return conf.get("future_option")  # conf-ok: staged for PR 19
    """)
    assert ok == []
    bare = _cflint(tmp_path, """
        def f(conf, name):
            return conf.get("future_option")  # conf-ok:
    """)
    assert [v.code for v in bare] == ["CONF001"]
    assert "no reason" in bare[0].message


def test_config_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text('def f(conf):\n    return conf.get("nope_opt")\n')
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_config.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "CONF001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_config.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0


# ---------------------------------------------------------------------------
# Unified runner (tools/lint.py)
# ---------------------------------------------------------------------------

def test_lint_runner_registry_matches_module_set():
    """Adding tools/lint_foo.py without registering it in
    tools/lint.py FAMILIES (or vice versa) fails here — the unified
    runner cannot silently miss a family."""
    from tools import lint as lint_runner

    on_disk = {p.stem[len("lint_"):]
               for p in (REPO / "tools").glob("lint_*.py")}
    assert set(lint_runner.FAMILIES) == on_disk
    for name, mod in lint_runner.FAMILIES.items():
        assert mod.__name__ == f"tools.lint_{name}", name


def test_lint_runner_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text("import threading\nx = threading.Lock()\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), str(bad)],
        capture_output=True, text=True)
    assert p.returncode == 1
    assert "lint FAILED: concurrency" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), str(good)],
        capture_output=True, text=True)
    assert p.returncode == 0
    assert "lint clean (7 families)" in p.stdout


def test_lint_runner_json_output(tmp_path):
    import json
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text("import threading\nx = threading.Lock()\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--json",
         str(bad)],
        capture_output=True, text=True)
    assert p.returncode == 1
    rep = json.loads(p.stdout)
    assert rep["ok"] is False
    assert set(rep["families"]) == {
        "async", "concurrency", "config", "faults", "jax", "obs",
        "wire"}
    conc = rep["families"]["concurrency"]
    assert conc["rc"] == 1
    assert any("CONC001" in f for f in conc["findings"])
    assert conc["elapsed_s"] >= 0
    # clean target: every family rc 0, no findings, one exit code
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), "--json",
         str(good)],
        capture_output=True, text=True)
    assert p.returncode == 0
    rep = json.loads(p.stdout)
    assert rep["ok"] is True
    assert all(f["rc"] == 0 and not f["findings"]
               for f in rep["families"].values())


def test_suppression_audit_repo_is_clean():
    """Every ``# <fam>-ok:`` mark in the repo names a real family,
    carries a reason, and still suppresses a finding — the audit
    sweep that keeps suppressions honest."""
    from tools import lint as lint_runner

    assert lint_runner.audit_suppressions(REPO) == 0


def test_suppression_audit_flags_bad_marks(tmp_path):
    """A typo'd family word and a reasonless mark both fail the
    audit (SUP001/SUP002); stale detection is covered by the
    repo-wide clean run above."""
    import contextlib
    import io

    from tools import lint as lint_runner

    sub = tmp_path / "ceph_tpu"
    sub.mkdir()
    (sub / "mod.py").write_text(
        "import time\n"
        "x = 1  # blok-ok: typo'd family word\n"
        "y = 2  # conc-ok:\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = lint_runner.audit_suppressions(tmp_path)
    assert rc == 1
    out = buf.getvalue()
    assert "SUP001" in out and "blok" in out
    assert "SUP002" in out and "no reason" in out


# ---------------------------------------------------------------------------
# Async-safety reachability (tools/lint_async.py, BLOCK001)
# ---------------------------------------------------------------------------

def _alint(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    return lint_async.lint_file(f)


def test_repo_is_async_clean():
    """The asyncheck static pass IS tier-1: zero unsuppressed
    may-block paths reachable from any @nonblocking context across
    the project (msg/, services/, mgr/ and everything else)."""
    violations = lint_async.lint_paths([REPO / "ceph_tpu"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_block001_direct_primitive_flagged(tmp_path):
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg):
            time.sleep(0.1)
    """)
    assert codes(vs) == ["BLOCK001"]
    assert "time.sleep" in vs[0].message


def test_block001_transitive_chain_named(tmp_path):
    """The report carries the full static call chain from the
    @nonblocking root to the primitive, not just the endpoint."""
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        def deep():
            time.sleep(0.1)

        def mid():
            deep()

        @nonblocking
        def handler(msg):
            mid()
    """)
    assert codes(vs) == ["BLOCK001"]
    msg = vs[0].message
    assert "handler" in msg and "mid" in msg and "deep" in msg


def test_block001_decorated_callee_transparent(tmp_path):
    """A decorator on the callee must not hide its blocking body —
    the analyzer sees through the decoration to the def."""
    vs = _alint(tmp_path, """\
        import functools
        import time

        from ceph_tpu.analysis.asyncheck import nonblocking

        def logged(fn):
            @functools.wraps(fn)
            def w(*a, **k):
                return fn(*a, **k)
            return w

        @logged
        def drain():
            time.sleep(0.2)

        @nonblocking
        def handler(msg):
            drain()
    """)
    assert "BLOCK001" in codes(vs)


def test_block001_lambda_bound_callee(tmp_path):
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg):
            f = lambda: time.sleep(0.5)
            f()
    """)
    assert "BLOCK001" in codes(vs)


def test_block001_functools_partial(tmp_path):
    """partial(fn, ...) bound to a local and called: the call edge
    lands on the wrapped function."""
    vs = _alint(tmp_path, """\
        import functools
        import time

        from ceph_tpu.analysis.asyncheck import nonblocking

        def flush_all(n):
            time.sleep(n)

        @nonblocking
        def handler(msg):
            f = functools.partial(flush_all, 3)
            f()
    """)
    assert "BLOCK001" in codes(vs)


def test_block001_inherited_method(tmp_path):
    """self.m() resolves through the class's MRO: a blocking method
    inherited from a base is reachable from the subclass handler."""
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        class Base:
            def drain(self):
                time.sleep(1.0)

        class Worker(Base):
            @nonblocking
            def handle(self, msg):
                self.drain()
    """)
    assert "BLOCK001" in codes(vs)


def test_block001_dynamic_callback_conservative(tmp_path):
    """self._callbacks[type](msg)-style value-dependent dispatch
    cannot be resolved statically: the analyzer assumes may-block and
    SAYS it assumed (the documented conservative fallback)."""
    vs = _alint(tmp_path, """\
        from ceph_tpu.analysis.asyncheck import nonblocking

        class Dispatcher:
            def __init__(self):
                self._callbacks = {}

            @nonblocking
            def handle(self, msg):
                self._callbacks[msg["type"]](msg)
    """)
    assert codes(vs) == ["BLOCK001"]
    assert "conservative" in vs[0].message


def test_block001_pool_submit_is_not_an_edge(tmp_path):
    """Passing a blocking fn AS AN ARGUMENT creates no call edge —
    handing work to a pool/thread is the off-loop idiom the analyzer
    must not punish."""
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        def slow():
            time.sleep(1.0)

        @nonblocking
        def handler(msg, pool):
            pool.submit(slow)
    """)
    assert vs == []


def test_block001_nonblocking_acquire_ok(tmp_path):
    """lock.acquire(blocking=False) never waits — not a primitive."""
    vs = _alint(tmp_path, """\
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg, lk):
            if not lk.acquire(blocking=False):
                return None
            lk.release()
    """)
    assert vs == []


def test_block001_mark_suppresses_with_reason(tmp_path):
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg):
            time.sleep(0.01)  # block-ok: bounded pacing, 10ms by construction
    """)
    assert vs == []


def test_block001_mark_requires_reason(tmp_path):
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg):
            time.sleep(0.01)  # block-ok:
    """)
    # the bare mark suppresses NOTHING: the primitive still reports,
    # plus one violation naming the reasonless mark itself
    assert codes(vs) == ["BLOCK001", "BLOCK001"]
    assert any("no reason" in v.message for v in vs)


def test_block001_edge_mark_cuts_subtree(tmp_path):
    """A mark on a CALL EDGE suppresses everything reachable through
    it — one reasoned mark at the fan-out site covers the whole
    bounded-send machinery behind it."""
    vs = _alint(tmp_path, """\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        def deep():
            time.sleep(0.1)

        @nonblocking
        def handler(msg):
            deep()  # block-ok: deadline-bounded by the 2s frame timeout
    """)
    assert vs == []


def test_async_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import time
        from ceph_tpu.analysis.asyncheck import nonblocking

        @nonblocking
        def handler(msg):
            time.sleep(0.1)
    """))
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_async.py"),
         str(bad)],
        capture_output=True, text=True)
    assert p.returncode == 1
    assert "BLOCK001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_async.py"),
         str(good)],
        capture_output=True, text=True)
    assert p.returncode == 0
    assert "async lint clean" in p.stdout
