"""Concurrency lint (tools/lint_concurrency.py): rule unit tests on
synthetic modules, plus the enforcement test that keeps ``ceph_tpu/``
clean — a new raw lock, a blocking call under a lock, or a swallowing
run-loop except fails CI here unless explicitly allowlisted with a
``# conc-ok: <reason>`` justification."""

import pathlib
import textwrap

from tools.lint_concurrency import lint_file, lint_paths

REPO = pathlib.Path(__file__).resolve().parent.parent


def _lint(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    return lint_file(f)


def codes(violations):
    return [v.code for v in violations]


def test_repo_is_clean():
    violations = lint_paths([REPO / "ceph_tpu"])
    assert not violations, "\n".join(str(v) for v in violations)


def test_raw_lock_construction_flagged(tmp_path):
    vs = _lint(tmp_path, """
        import threading
        a = threading.Lock()
        b = threading.RLock()
    """)
    assert codes(vs) == ["CONC001", "CONC001"]


def test_registry_lock_not_flagged(tmp_path):
    vs = _lint(tmp_path, """
        from ceph_tpu.analysis.lockdep import make_lock
        a = make_lock("x::y")
    """)
    assert vs == []


def test_blocking_call_under_lock_flagged(tmp_path):
    vs = _lint(tmp_path, """
        import os, time

        class S:
            def write(self, f):
                with self._lock:
                    os.fsync(f.fileno())

            def wait_holding(self):
                with self._pg_lock(1, 2):
                    time.sleep(1)

            def rx(self, sock):
                with self.buf_lock:
                    sock.recv(4)

            def sub(self):
                with self._lock:
                    self.sched.submit("client", lambda: 1)
    """)
    assert codes(vs) == ["CONC002"] * 4


def test_blocking_call_outside_lock_ok(tmp_path):
    vs = _lint(tmp_path, """
        import os, time

        class S:
            def write(self, f):
                with self._lock:
                    n = 1
                os.fsync(f.fileno())
                time.sleep(0.1)

            def pool(self):
                # executor submit does not block; only sched.submit
                with self._lock:
                    self._pool.submit(print)
    """)
    assert vs == []


def test_nested_def_under_lock_not_flagged(tmp_path):
    """A function DEFINED under a lock runs later, lock-free."""
    vs = _lint(tmp_path, """
        import time

        def outer(self):
            with self._lock:
                def cb():
                    time.sleep(1)
                return cb
    """)
    assert vs == []


def test_swallowing_runloop_except_flagged(tmp_path):
    vs = _lint(tmp_path, """
        def _reader(self):
            while self._running:
                try:
                    step()
                except Exception:
                    pass

        def _serve(self):
            while True:
                try:
                    step()
                except:
                    log(1)
    """)
    assert codes(vs) == ["CONC003", "CONC003"]


def test_logging_or_narrow_runloop_except_ok(tmp_path):
    vs = _lint(tmp_path, """
        def _loop(self):
            while self._running:
                try:
                    step()
                except Exception as e:
                    self.log.derr(repr(e))
                try:
                    step()
                except OSError:
                    break

        def not_a_loop(self):
            try:
                step()
            except Exception:
                pass
    """)
    assert vs == []


def test_span_outside_with_flagged(tmp_path):
    vs = _lint(tmp_path, """
        def leaky(self):
            sp = self.tracer.start_span("op")
            work()
            sp.finish()

        def assigned_from_call(tracer):
            return tracer.start_span("escapes")
    """)
    assert codes(vs) == ["CONC004", "CONC004"]


def test_span_in_with_ok(tmp_path):
    vs = _lint(tmp_path, """
        def clean(self):
            with self.tracer.start_span("op", tags={"x": 1}) as sp:
                sp.log("phase")
            with self.tracer.start_span("a") as a, open("f") as f:
                pass

        def suppressed(self):
            sp = self.tracer.start_span("op")  # conc-ok: handed to a callback that finishes it
            return sp
    """)
    assert vs == []


def test_conc_ok_suppression(tmp_path):
    vs = _lint(tmp_path, """
        import os, threading
        a = threading.Lock()  # conc-ok: test fixture, not a daemon lock

        def write(self, f):
            with self._lock:
                os.fsync(f.fileno())  # conc-ok: the fsync is the ack point
    """)
    assert vs == []


def test_cli_exit_status(tmp_path):
    import subprocess
    import sys

    bad = tmp_path / "bad.py"
    bad.write_text("import threading\nx = threading.Lock()\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_concurrency.py"),
         str(bad)], capture_output=True, text=True)
    assert p.returncode == 1
    assert "CONC001" in p.stdout
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    p = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_concurrency.py"),
         str(good)], capture_output=True, text=True)
    assert p.returncode == 0
