"""Batched EC encode — equivalence and recompile-budget contracts.

The data-plane batching layer (ec/engine.py encode_batched,
ErasureCode.encode_batched, ec/batcher.py EncodeBatcher) is only
admissible if it is BYTE-IDENTICAL to the per-stripe path for every
registered plugin/profile, and if its batch shapes stay inside the
PR-3 steady-state recompile budget — a batching layer that silently
recompiles per call or drifts a parity byte is worse than no batching.
"""

import numpy as np
import pytest

from ceph_tpu.analysis import jaxcheck
from ceph_tpu.ec.registry import factory

# the plugin/profile grid of the jaxcheck contract registry (the
# jerasure technique/w/packetsize points, isa, LRC layers, SHEC, and
# the sub-chunked CLAY, which must take the exact per-object fallback)
PROFILES = [
    ("jerasure", {"technique": "reed_sol_van", "k": "2", "m": "1",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "3", "m": "2",
                  "w": "16"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "3", "m": "2",
                  "w": "32"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "4", "m": "2",
                  "w": "8"}),
    ("jerasure", {"technique": "cauchy_good", "k": "4", "m": "2",
                  "w": "8", "packetsize": "8"}),
    ("jerasure", {"technique": "liberation", "k": "3", "m": "2",
                  "w": "7", "packetsize": "8"}),
    ("isa", {"k": "4", "m": "2"}),
    ("lrc", {"k": "4", "m": "2", "l": "3"}),
    ("shec", {"k": "4", "m": "3", "c": "2"}),
    ("clay", {"k": "4", "m": "2"}),
]


def _objects(n, size, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size, dtype=np.uint8).tobytes()
            for _ in range(n)]


@pytest.mark.parametrize("plugin,profile", PROFILES,
                         ids=lambda p: p if isinstance(p, str)
                         else "-".join(f"{k}{v}" for k, v in
                                       sorted(p.items())))
def test_plugin_encode_batched_byte_identical(plugin, profile):
    code = factory(plugin, dict(profile))
    n = code.get_chunk_count()
    want = set(range(n))
    for B, size in ((2, 4096), (3, 8192)):  # 3 exercises pow2 pad
        raws = _objects(B, size, seed=B)
        batched = code.encode_batched(want, raws)
        assert len(batched) == B
        for raw, got in zip(raws, batched):
            ref = code.encode(want, raw)
            assert set(got) == set(ref)
            for i in ref:
                assert np.asarray(got[i], np.uint8).tobytes() == \
                    np.asarray(ref[i], np.uint8).tobytes(), \
                    f"{plugin} chunk {i} drifted under batching"


def test_plugin_encode_batched_mixed_sizes_fall_back():
    code = factory("jerasure", {"technique": "reed_sol_van",
                                "k": "2", "m": "1", "w": "8"})
    want = set(range(3))
    raws = [b"a" * 1024, b"b" * 2048]
    batched = code.encode_batched(want, raws)
    for raw, got in zip(raws, batched):
        ref = code.encode(want, raw)
        for i in ref:
            assert np.asarray(got[i]).tobytes() == \
                np.asarray(ref[i]).tobytes()


def test_engine_encode_batched_byte_identical():
    from ceph_tpu.ec.rs_jax import RSCode

    bc = RSCode(4, 2)._bit
    rng = np.random.default_rng(7)
    stripes = rng.integers(0, 256, (8, 4, 2048), dtype=np.uint8)
    out = np.asarray(bc.encode_batched(stripes))
    assert out.shape == (8, 2, 2048)
    for b in range(8):
        ref = np.asarray(bc.encode(stripes[b]))
        assert out[b].tobytes() == ref.tobytes()


def test_engine_encode_batched_recompile_budget():
    """A warmed batch shape must hit the jit cache: zero new XLA
    compiles inside the steady-state window (the conftest gate fails
    this test on any violation; the assert below is the explicit
    twin)."""
    from ceph_tpu.ec.rs_jax import RSCode

    bc = RSCode(4, 2)._bit
    rng = np.random.default_rng(8)
    stripes = rng.integers(0, 256, (8, 4, 2048), dtype=np.uint8)
    np.asarray(bc.encode_batched(stripes))  # warmup: trace + compile
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("ec.encode_batched"):
        for seed in range(3):
            s = rng.integers(0, 256, (8, 4, 2048), dtype=np.uint8)
            np.asarray(bc.encode_batched(s))
    assert len(jaxcheck.recompile_violations()) == base


def test_encode_batcher_coalesces_concurrent_encodes():
    """Concurrent encodes through the coalescer: outputs identical to
    the direct path, and at least one multi-object batch dispatched
    (the ec_batch_size histogram's depth-1-regression canary)."""
    import threading

    from ceph_tpu.ec.batcher import EncodeBatcher
    from ceph_tpu.ec.engine import _pc

    code = factory("jerasure", {"technique": "reed_sol_van",
                                "k": "2", "m": "1", "w": "8"})
    want = set(range(3))
    batcher = EncodeBatcher(max_delay_us=5000)
    raws = _objects(12, 4096, seed=3)
    refs = [code.encode(want, r) for r in raws]
    base = _pc.dump()["ec_batch_size"]["buckets"]
    outs = [None] * len(raws)
    errs = []

    def worker(i):
        try:
            outs[i] = batcher.encode(code, want, raws[i])
        except Exception as e:  # surfaced below
            errs.append(e)

    ths = [threading.Thread(target=worker, args=(i,))
           for i in range(len(raws))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert not errs
    for got, ref in zip(outs, refs):
        for i in ref:
            assert np.asarray(got[i]).tobytes() == \
                np.asarray(ref[i]).tobytes()
    cur = _pc.dump()["ec_batch_size"]["buckets"]
    grew = [c - b for c, b in zip(cur, base)]
    assert sum(grew[1:]) > 0, "no multi-object batch ever dispatched"


def test_batcher_error_propagates_to_all_requesters():
    from ceph_tpu.ec.batcher import EncodeBatcher

    class Boom:
        def encode(self, want, raw):
            raise ValueError("boom")

        def encode_batched(self, want, raws):
            raise ValueError("boom")

    b = EncodeBatcher()
    with pytest.raises(ValueError):
        b.encode(Boom(), {0}, b"x")
