"""SHEC plugin tests — mirrors src/test/erasure-code/
TestErasureCodeShec.cc and TestErasureCodeShec_all.cc (the exhaustive
erasure sweep over recoverable patterns)."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.shec import make_shec, shec_coding_matrix


def _obj(n=3000, seed=21):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_defaults_and_registry():
    code = factory("shec", {})
    assert (code.k, code.m, code.c) == (4, 3, 2)
    assert code.get_chunk_count() == 7


def test_parse_constraints():
    for bad in ({"k": "4", "m": "3"},             # c missing
                {"k": "4", "m": "3", "c": "4"},   # c > m
                {"k": "13", "m": "3", "c": "2"},  # k > 12
                {"k": "12", "m": "12", "c": "2", "w": "8"},  # k+m>20
                {"k": "3", "m": "4", "c": "2"}):  # m > k
        with pytest.raises(ErasureCodeError):
            make_shec(dict(bad))
    # bad w falls back to 8, not an error (the reference's behavior)
    code = make_shec({"k": "4", "m": "3", "c": "2", "w": "7"})
    assert code.w == 8


def test_matrix_is_shingled():
    """Each parity row must cover a strict subset of the data chunks
    (the shingle), except in degenerate configs."""
    mat = shec_coding_matrix(8, 4, 3, 8)
    zero_counts = [sum(1 for v in row if v == 0) for row in mat]
    assert any(z > 0 for z in zero_counts)
    # every data chunk is covered by at least one parity
    for j in range(8):
        assert any(mat[i][j] for i in range(4))


def test_roundtrip_no_loss():
    code = make_shec({"k": "4", "m": "3", "c": "2"})
    raw = _obj()
    chunks = code.encode(range(7), raw)
    assert code.decode_concat(chunks)[:len(raw)] == raw


def test_all_recoverable_erasures():
    """Exhaustive <= c erasure sweep: SHEC guarantees recovery of any
    c erasures; beyond c some patterns work, some don't — every
    pattern must either round-trip or raise, never corrupt."""
    code = make_shec({"k": "4", "m": "3", "c": "2"})
    raw = _obj(1777)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    for r in range(1, code.c + 1):
        for erased in itertools.combinations(range(n), r):
            avail = {i: ch for i, ch in chunks.items()
                     if i not in erased}
            got = code.decode_concat(avail)
            assert got[:len(raw)] == raw, f"erased={erased}"
    recovered = failed = 0
    for erased in itertools.combinations(range(n), code.c + 1):
        avail = {i: ch for i, ch in chunks.items() if i not in erased}
        try:
            got = code.decode_concat(avail)
        except ErasureCodeError:
            failed += 1
            continue
        assert got[:len(raw)] == raw, f"erased={erased}"
        recovered += 1
    assert recovered > 0  # beyond-c recovery exists (m=3 > c=2)


def test_minimum_to_decode_is_sparse():
    """Recovering one lost chunk must read fewer than k+m-1 chunks —
    the whole point of shingling (reduced recovery I/O)."""
    code = make_shec({"k": "8", "m": "4", "c": "3"})
    n = code.get_chunk_count()
    minimum = code.minimum_to_decode({0}, set(range(1, n)))
    assert len(minimum) < code.k
    # and the minimum actually suffices
    raw = _obj(4096)
    chunks = code.encode(range(n), raw)
    avail = {i: chunks[i] for i in minimum}
    out = code.decode({0}, avail)
    assert np.array_equal(np.asarray(out[0]), np.asarray(chunks[0]))


def test_parity_reconstruction():
    code = make_shec({"k": "4", "m": "3", "c": "2"})
    raw = _obj(900)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    lost = n - 1
    avail = {i: c for i, c in chunks.items() if i != lost}
    out = code.decode({lost}, avail)
    assert np.array_equal(np.asarray(out[lost]),
                          np.asarray(chunks[lost]))


def test_single_technique():
    code = make_shec({"technique": "single", "k": "4", "m": "3",
                      "c": "2"})
    raw = _obj(600)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    for erased in itertools.combinations(range(n), 2):
        avail = {i: c for i, c in chunks.items() if i not in erased}
        assert code.decode_concat(avail)[:len(raw)] == raw


def test_w16_layout():
    code = make_shec({"k": "4", "m": "3", "c": "2", "w": "16"})
    raw = _obj(2222)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    avail = {i: c for i, c in chunks.items() if i not in (0, 5)}
    assert code.decode_concat(avail)[:len(raw)] == raw
