"""Native GF(2^8) engine — parity-identical to the array engine."""

import numpy as np
import pytest

from ceph_tpu.ec.native_gf import NativeRS, available, gf8_matmul
from ceph_tpu.ec import gf
from ceph_tpu.ec.rs_jax import RSCode

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable")


def test_gf8_matmul_matches_reference():
    rng = np.random.default_rng(1)
    mat = rng.integers(0, 256, (3, 5), dtype=np.uint8)
    data = rng.integers(0, 256, (5, 700), dtype=np.uint8)
    got = gf8_matmul(mat, data)
    want = np.zeros((3, 700), np.uint8)
    for r in range(3):
        for j in range(5):
            want[r] ^= gf.gf_mul(
                np.full(700, mat[r, j], np.uint8), data[j])
    assert np.array_equal(got, want)


@pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
def test_native_rs_equals_engine(k, m):
    rng = np.random.default_rng(k)
    data = rng.integers(0, 256, (k, 4096), dtype=np.uint8)
    nat, eng = NativeRS(k, m), RSCode(k, m)
    assert np.array_equal(nat.encode(data),
                          np.asarray(eng.encode(data)))
    full = nat.all_chunks(data)
    chunks = {i: full[i] for i in range(k + m)}
    for erasures in ([0], [k - 1, k], list(range(m))):
        got = nat.decode(chunks, erasures)
        assert np.array_equal(got, data), erasures
    with pytest.raises(ValueError):
        nat.decode({0: full[0]}, [])
