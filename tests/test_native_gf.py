"""Native GF(2^8) engine — parity-identical to the array engine."""

import numpy as np
import pytest

from ceph_tpu.ec.native_gf import NativeRS, available, gf8_matmul
from ceph_tpu.ec import gf
from ceph_tpu.ec.rs_jax import RSCode

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable")


def test_gf8_matmul_matches_reference():
    rng = np.random.default_rng(1)
    mat = rng.integers(0, 256, (3, 5), dtype=np.uint8)
    data = rng.integers(0, 256, (5, 700), dtype=np.uint8)
    got = gf8_matmul(mat, data)
    want = np.zeros((3, 700), np.uint8)
    for r in range(3):
        for j in range(5):
            want[r] ^= gf.gf_mul(
                np.full(700, mat[r, j], np.uint8), data[j])
    assert np.array_equal(got, want)


@pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
def test_native_rs_equals_engine(k, m):
    rng = np.random.default_rng(k)
    data = rng.integers(0, 256, (k, 4096), dtype=np.uint8)
    nat, eng = NativeRS(k, m), RSCode(k, m)
    assert np.array_equal(nat.encode(data),
                          np.asarray(eng.encode(data)))
    full = nat.all_chunks(data)
    chunks = {i: full[i] for i in range(k + m)}
    for erasures in ([0], [k - 1, k], list(range(m))):
        got = nat.decode(chunks, erasures)
        assert np.array_equal(got, data), erasures
    with pytest.raises(ValueError):
        nat.decode({0: full[0]}, [])


@pytest.mark.parametrize("plugin,profile", [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "4", "m": "2",
                  "w": "8"}),
    ("isa", {"technique": "reed_sol_van", "k": "4", "m": "2"}),
    ("isa", {"technique": "cauchy", "k": "6", "m": "3"}),
])
def test_plugin_engines_byte_identical(monkeypatch, plugin, profile):
    """The registry's engine dispatch must be invisible: the native
    GF(2^8) engine and the portable bit-plane engine produce the SAME
    chunk bytes for every w=8 matrix technique (whichever one a given
    machine defaults to, the other is covered here)."""
    from ceph_tpu.ec.registry import factory

    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 1 << 14, dtype=np.uint8).tobytes()

    out = {}
    for engine in ("native", "bitplane"):
        monkeypatch.setenv("CEPH_TPU_EC_ENGINE", engine)
        code = factory(plugin, dict(profile))
        n = code.get_chunk_count()
        chunks = code.encode(range(n), data)
        out[engine] = [np.asarray(chunks[i]) for i in range(n)]
        # decode parity too: drop the first data + last parity chunk
        k = code.get_data_chunk_count()
        avail = {i: np.asarray(chunks[i]) for i in range(n)
                 if i not in (0, n - 1)}
        dec = code.decode({0, n - 1}, avail)
        assert np.array_equal(np.asarray(dec[0]),
                              np.asarray(chunks[0]))
        assert np.array_equal(np.asarray(dec[n - 1]),
                              np.asarray(chunks[n - 1]))
    for a, b in zip(out["native"], out["bitplane"]):
        assert np.array_equal(a, b)
