"""rados_bench harness + dmClock OpScheduler QoS enforcement."""

import threading
import time

from ceph_tpu.common.op_queue import (ClientInfo, MClockQueue,
                                      OpScheduler)
from ceph_tpu.tools.rados_bench import bench_minicluster


def test_rados_bench_minicluster_smoke():
    out = bench_minicluster(op="seq", seconds=1.0, concurrent=4,
                            object_size=4096, n_osds=3, pg_num=8)
    w, s = out["write"], out["seq"]
    assert w["ops"] > 0 and w["errors"] == 0
    assert s["ops"] > 0 and s["errors"] == 0
    assert w["iops"] > 0 and w["lat_p99_ms"] >= w["lat_p50_ms"]


def test_mclock_weight_shares_under_backlog():
    """Two classes, weight 4:1, full backlog: dmClock serves them in
    a 4:1 ratio (deterministic tag-order check, no threads)."""
    q = MClockQueue({
        "hi": ClientInfo(reservation=0.0, weight=4.0, limit=0.0),
        "lo": ClientInfo(reservation=0.0, weight=1.0, limit=0.0),
    })
    for i in range(50):
        q.enqueue("hi", f"h{i}", now=0.0)
        q.enqueue("lo", f"l{i}", now=0.0)
    served = []
    now = 0.0
    while len(served) < 40:
        got = q.dequeue(now)
        if got is None:
            now += 0.01
            continue
        served.append(got[0])
    hi = served.count("hi")
    assert 28 <= hi <= 36, f"expected ~32/40 hi, got {hi}"


def test_opscheduler_limit_ceiling():
    """A limited class cannot exceed its ops/sec ceiling even alone."""
    q = MClockQueue({
        "capped": ClientInfo(reservation=0.0, weight=1.0, limit=50.0),
    })
    sched = OpScheduler(queue=q, n_workers=2)
    try:
        t0 = time.monotonic()
        n = 12
        for _ in range(n):
            sched.submit("capped", lambda: None)
        dt = time.monotonic() - t0
        # 12 ops at 50/s needs >= ~0.2s (first is free)
        assert dt >= (n - 1) / 50.0 * 0.8, dt
    finally:
        sched.shutdown()


def test_rados_bench_qd_sweep_smoke():
    """The pipelined aio write path at a queue-depth sweep: each depth
    reports, the best is promoted, and the sweep rides the summary."""
    out = bench_minicluster(op="seq", seconds=0.8, concurrent=4,
                            object_size=4096, n_osds=3, pg_num=8,
                            qd_sweep=[4, 8])
    assert set(out["qd_sweep"]) == {"4", "8"}
    w = out["write"]
    assert w["qd"] in (4, 8)
    assert w["ops"] > 0 and w["errors"] == 0
    assert out["seq"]["ops"] > 0


def test_bench_init_probe_fail_fast():
    """The staged-lane backend-init probe (satellite regression for
    the BENCH_r05 300 s hang): a worker that never emits its init
    line must be declared dead at INIT_DEADLINE (60 s default), not
    at the full worker deadline — checked here with a tiny deadline
    against a sleeping child."""
    import subprocess
    import sys

    import bench

    assert bench.INIT_DEADLINE <= 60.0  # the fail-fast contract
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"],
        stdout=subprocess.PIPE, text=True)
    try:
        stream = bench.Stream(proc, "probe-test")
        t0 = time.monotonic()
        got = stream.wait(lambda r: r.get("stage") == "init", 0.5)
        dt = time.monotonic() - t0
        assert got is None, "no init line must mean probe failure"
        assert dt < 5.0, f"probe waited {dt:.1f}s past its deadline"
    finally:
        proc.kill()
        proc.wait()


def test_bench_worker_balancer_smoke(tmp_path, monkeypatch, capsys):
    """The balancer bench lane end-to-end on a shrunk synthetic map:
    the record lands with the convergence trajectory perf_history
    ingests (kind/rounds/stddevs/sweep rate), and the offline loop
    actually converged."""
    import json

    import bench

    out = tmp_path / "BALANCE_r99.json"
    monkeypatch.setenv("CEPH_TPU_BALANCE_OSDS", "32")
    monkeypatch.setenv("CEPH_TPU_BALANCE_PGS", "128")
    monkeypatch.setenv("CEPH_TPU_BALANCE_SEED", "1")
    monkeypatch.setenv("CEPH_TPU_BALANCE_ITERS", "30")
    monkeypatch.setenv("CEPH_TPU_BALANCE_ROUNDS", "8")
    monkeypatch.setenv("CEPH_TPU_BALANCE_MAX_DEVIATION", "2")
    monkeypatch.setenv("CEPH_TPU_BALANCE_OUT", str(out))
    bench.worker_balancer()
    lines = [json.loads(ln.split(" ", 1)[1])
             for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("BENCH_RESULT ")]
    assert any(r.get("stage") == "balancer" for r in lines)
    rec = json.loads(out.read_text())
    assert rec["kind"] == "balance"
    assert rec["converged"]
    assert rec["final_stddev"] <= rec["initial_stddev"]
    assert rec["sweep_mappings_per_sec"] > 0
    assert rec["rounds"] >= 1 and rec["upmaps"] > 0
