"""OSDMap::Incremental tests — epoch deltas round-trip, apply cleanly,
and actually carry the cluster's map distribution."""

import pytest

from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.osdmap.incremental import (Incremental, apply_incremental,
                                         diff_maps)
from ceph_tpu.osdmap.osdmap import OSD_EXISTS, OSD_UP, OSDMap, PgPool


def make_map(n=6):
    w = CrushWrapper()
    for d in range(n):
        w.insert_item(d, 0x10000, f"osd.{d}",
                      {"host": f"h{d}", "root": "default"})
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    m = OSDMap(w.crush)
    for d in range(n):
        m.add_osd(d)
    m.pools[1] = PgPool(size=3, pg_num=16, crush_rule=rid)
    return m


def clone(m):
    return OSDMap.from_dict(m.to_dict())


def test_diff_apply_roundtrip():
    old = make_map()
    new = clone(old)
    new.epoch = old.epoch + 1
    new.osd_weight[2] = 0
    new.osd_state[3] = OSD_EXISTS  # down
    new.pools[2] = PgPool(size=2, pg_num=8, crush_rule=0)
    new.pg_upmap_items[(1, 3)] = [(0, 5)]
    new.pg_temp[(1, 1)] = [4, 5]
    new.set_primary_affinity(1, 0x8000)

    inc = diff_maps(old, new)
    assert not inc.empty()
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_apply_removals_and_state_xor():
    old = make_map()
    old.pg_upmap_items[(1, 2)] = [(1, 4)]
    old.pg_temp[(1, 0)] = [0, 1]
    new = clone(old)
    new.epoch += 1
    del new.pg_upmap_items[(1, 2)]
    del new.pg_temp[(1, 0)]
    new.osd_state[0] = OSD_EXISTS | OSD_UP  # unchanged
    inc = diff_maps(old, new)
    assert (1, 2) in inc.old_pg_upmap_items
    assert inc.new_pg_temp[(1, 0)] == []  # [] removes
    assert 0 not in inc.new_state
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_full_upmap_primary_temp_and_pool_delete():
    """pg_upmap (full remap), primary_temp, and pool deletions must
    travel in deltas (OSDMap.h:382-405); a follower applying increments
    must converge on maps that mutate them."""
    old = make_map()
    old.pg_upmap[(1, 7)] = [5, 4, 3]
    old.primary_temp[(1, 9)] = 2
    old.pools[3] = PgPool(size=2, pg_num=8, crush_rule=0)
    new = clone(old)
    new.epoch += 1
    new.pg_upmap[(1, 8)] = [0, 1, 2]      # add
    del new.pg_upmap[(1, 7)]              # remove
    new.primary_temp[(1, 4)] = 1          # add
    del new.primary_temp[(1, 9)]          # remove
    del new.pools[3]                      # pool deletion
    inc = diff_maps(old, new)
    assert inc.new_pg_upmap[(1, 8)] == [0, 1, 2]
    assert (1, 7) in inc.old_pg_upmap
    assert inc.new_primary_temp[(1, 4)] == 1
    assert inc.new_primary_temp[(1, 9)] == -1  # -1 removes
    assert 3 in inc.old_pools
    rt = Incremental.from_dict(inc.to_dict())  # wire round-trip
    got = clone(old)
    apply_incremental(got, rt)
    assert got.to_dict() == new.to_dict()


def test_primary_affinity_reset_to_default():
    """new map with affinity None (all-default) after a non-default old
    list must emit explicit default deltas, or followers keep stale
    affinities."""
    old = make_map()
    old.set_primary_affinity(1, 0x8000)
    old.set_primary_affinity(4, 0x4000)
    new = clone(old)
    new.epoch += 1
    new.osd_primary_affinity = None  # reset to default
    inc = diff_maps(old, new)
    assert set(inc.new_primary_affinity) == {1, 4}
    got = clone(old)
    apply_incremental(got, inc)
    # applying materializes an explicit all-default list; placement
    # equivalence is what matters, compare through the accessor
    from ceph_tpu.osdmap.osdmap import DEFAULT_PRIMARY_AFFINITY
    assert all(a == DEFAULT_PRIMARY_AFFINITY
               for a in got.osd_primary_affinity)


def test_shrink_max_osd():
    """A shrink must not emit deltas for truncated osds (they'd index
    out of bounds after new_max_osd applies)."""
    old = make_map(6)
    new = clone(old)
    new.epoch += 1
    new.set_max_osd(4)
    inc = diff_maps(old, new)
    assert inc.new_max_osd == 4
    assert all(o < 4 for o in inc.new_state)
    assert all(o < 4 for o in inc.new_weight)
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_catch_up_walks_incrementals():
    """A follower several epochs behind catches up via get_inc deltas
    (no full-map fetch while history is retained)."""
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 5.0)
    cl = MiniCluster(n_osds=3, config=conf).start()
    try:
        c = cl.client("behind")
        # freeze the client's view, advance the mon several epochs
        import copy
        frozen = (c.map, c.epoch)
        cl.create_replicated_pool(1, pg_num=4, size=2)
        cl.create_replicated_pool(2, pg_num=4, size=2)
        cl.create_replicated_pool(3, pg_num=4, size=2)
        target = cl.mon.map.epoch
        with c._lock:
            c.map, c.epoch = frozen
        c._catch_up(target, {})
        assert c.epoch == target
        assert c.map.to_dict() == cl.mon.map.to_dict()
    finally:
        cl.shutdown()


def test_apply_rejects_gaps():
    m = make_map()
    inc = Incremental(epoch=m.epoch + 2)
    with pytest.raises(ValueError):
        apply_incremental(m, inc)


def test_versioned_wire_roundtrip():
    old = make_map()
    new = clone(old)
    new.epoch += 1
    new.osd_weight[1] = 0x8000
    inc = diff_maps(old, new)
    blob = inc.encode_versioned()
    inc2 = Incremental.decode_versioned(blob)
    got = clone(old)
    apply_incremental(got, inc2)
    assert got.to_dict() == new.to_dict()


def test_cluster_distributes_deltas():
    """Live daemons follow epochs through incrementals: after changes,
    subscriber epochs match the mon and their maps are bit-identical
    to the mon's full map."""
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.5)
    cl = MiniCluster(n_osds=3, config=conf).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=2)
        cl.create_replicated_pool(2, pg_num=4, size=3)
        c = cl.client("delta")
        c.put(1, "o", b"x" * 100)
        # incrementals were built for post-genesis epochs
        assert cl.mon._incs
        import time
        deadline = time.monotonic() + 10
        want = cl.mon.map.epoch
        while time.monotonic() < deadline:
            if all(svc.epoch == want
                   for svc in cl.osds.values()) and c.epoch == want:
                break
            time.sleep(0.1)
        assert c.epoch == want
        mon_map = cl.mon.map.to_dict()
        assert c.map.to_dict() == mon_map
        for svc in cl.osds.values():
            assert svc.epoch == want
            assert svc.map.to_dict() == mon_map
    finally:
        cl.shutdown()


# ---------------------------------------------------------------------------
# versioned wire coverage: every delta kind + the archived-v1 path
# ---------------------------------------------------------------------------

def _mut_state(new):
    new.osd_state[3] = OSD_EXISTS  # down: state XOR delta


def _mut_weight(new):
    new.osd_weight[2] = 0x4000


def _mut_affinity(new):
    new.set_primary_affinity(1, 0x8000)


def _mut_pool_add(new):
    new.pools[7] = PgPool(size=2, pg_num=8, crush_rule=0)


def _mut_pool_del(new):
    del new.pools[1]


def _mut_max_osd(new):
    new.set_max_osd(8)


def _mut_upmap_add(new):
    new.pg_upmap[(1, 4)] = [5, 0, 1]


def _mut_upmap_items(new):
    new.pg_upmap_items[(1, 5)] = [(2, 4)]


def _mut_pg_temp(new):
    new.pg_temp[(1, 6)] = [3, 1]


def _mut_primary_temp(new):
    new.primary_temp[(1, 6)] = 3


def _mut_crush_swap(new):
    from ceph_tpu.crush.wrapper import CrushWrapper

    w = CrushWrapper(new.crush)
    w.insert_item(6, 0x10000, "osd.6",
                  {"host": "h9", "root": "default"})


@pytest.mark.parametrize("mutate", [
    _mut_state, _mut_weight, _mut_affinity, _mut_pool_add,
    _mut_pool_del, _mut_max_osd, _mut_upmap_add, _mut_upmap_items,
    _mut_pg_temp, _mut_primary_temp, _mut_crush_swap,
], ids=lambda f: f.__name__[5:])
def test_every_delta_kind_roundtrips_versioned(mutate):
    """Each delta kind survives the FULL wire path — diff → versioned
    encode → decode → apply — and converges the follower bit-exactly
    (the conformance layer's per-kind witness)."""
    old = make_map()
    new = clone(old)
    new.epoch += 1
    mutate(new)
    inc = diff_maps(old, new)
    inc.epoch = new.epoch
    rt = Incremental.decode_versioned(inc.encode_versioned())
    assert rt.to_dict() == inc.to_dict()
    got = clone(old)
    apply_incremental(got, rt)
    assert got.to_dict() == new.to_dict()


def test_removal_kinds_roundtrip_versioned():
    """The remove-direction deltas (upmap/pg_temp/primary_temp/pool
    removal) through the versioned wire path."""
    old = make_map()
    old.pg_upmap[(1, 4)] = [5, 0, 1]
    old.pg_upmap_items[(1, 5)] = [(2, 4)]
    old.pg_temp[(1, 6)] = [3, 1]
    old.primary_temp[(1, 6)] = 3
    new = clone(old)
    new.epoch += 1
    del new.pg_upmap[(1, 4)]
    del new.pg_upmap_items[(1, 5)]
    del new.pg_temp[(1, 6)]
    del new.primary_temp[(1, 6)]
    inc = diff_maps(old, new)
    rt = Incremental.decode_versioned(inc.encode_versioned())
    got = clone(old)
    apply_incremental(got, rt)
    assert got.to_dict() == new.to_dict()


def test_upgrade_hook_decodes_archived_v1_payload():
    """A delta archived from the v1 era (no pg_upmap/primary_temp/
    pool-deletion tables) decodes through upgrade() and applies — the
    committed corpus blob is the long-term witness; this test walks
    the same path explicitly."""
    import json
    import pathlib

    blob = (pathlib.Path(__file__).parent / "corpus" / "encodings" /
            "osdmap.incremental" / "1" / "archived.bin").read_bytes()
    env = json.loads(blob)
    assert env["v"] == 1  # genuinely a v1 writer
    inc = Incremental.decode_versioned(blob)
    # v2-added tables defaulted by the upgrade hook
    assert inc.new_pg_upmap == {}
    assert inc.old_pg_upmap == []
    assert inc.new_primary_temp == {}
    assert inc.old_pools == []
    # v1 content preserved
    assert inc.new_state == {0: 2}
    assert inc.new_weight == {1: 32768}
    assert inc.new_pg_temp == {(1, 5): [1, 0]}
    # and it applies onto a map at the right epoch
    m = make_map()
    m.epoch = 2
    apply_incremental(m, inc)
    assert m.epoch == 3
    assert m.pg_temp[(1, 5)] == [1, 0]


def test_malformed_payload_is_typed_and_named():
    """A tampered payload surfaces as MalformedInput naming the
    struct — never a raw KeyError out of from_dict."""
    import json

    import pytest as _pytest

    from ceph_tpu.common.encoding import MalformedInput, encode

    blob = encode({"not_epoch": 1}, version=2, compat=2)
    with _pytest.raises(MalformedInput) as ei:
        Incremental.decode_versioned(blob)
    assert "Incremental" in str(ei.value)
    # and future-compat refusal names both versions
    env = json.loads(Incremental(epoch=2).encode_versioned())
    env["v"] = env["compat"] = 99
    with _pytest.raises(MalformedInput) as ei:
        Incremental.decode_versioned(json.dumps(env))
    assert "v99" in str(ei.value) and "Incremental" in str(ei.value)
