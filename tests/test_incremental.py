"""OSDMap::Incremental tests — epoch deltas round-trip, apply cleanly,
and actually carry the cluster's map distribution."""

import pytest

from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.osdmap.incremental import (Incremental, apply_incremental,
                                         diff_maps)
from ceph_tpu.osdmap.osdmap import OSD_EXISTS, OSD_UP, OSDMap, PgPool


def make_map(n=6):
    w = CrushWrapper()
    for d in range(n):
        w.insert_item(d, 0x10000, f"osd.{d}",
                      {"host": f"h{d}", "root": "default"})
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    m = OSDMap(w.crush)
    for d in range(n):
        m.add_osd(d)
    m.pools[1] = PgPool(size=3, pg_num=16, crush_rule=rid)
    return m


def clone(m):
    return OSDMap.from_dict(m.to_dict())


def test_diff_apply_roundtrip():
    old = make_map()
    new = clone(old)
    new.epoch = old.epoch + 1
    new.osd_weight[2] = 0
    new.osd_state[3] = OSD_EXISTS  # down
    new.pools[2] = PgPool(size=2, pg_num=8, crush_rule=0)
    new.pg_upmap_items[(1, 3)] = [(0, 5)]
    new.pg_temp[(1, 1)] = [4, 5]
    new.set_primary_affinity(1, 0x8000)

    inc = diff_maps(old, new)
    assert not inc.empty()
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_apply_removals_and_state_xor():
    old = make_map()
    old.pg_upmap_items[(1, 2)] = [(1, 4)]
    old.pg_temp[(1, 0)] = [0, 1]
    new = clone(old)
    new.epoch += 1
    del new.pg_upmap_items[(1, 2)]
    del new.pg_temp[(1, 0)]
    new.osd_state[0] = OSD_EXISTS | OSD_UP  # unchanged
    inc = diff_maps(old, new)
    assert (1, 2) in inc.old_pg_upmap_items
    assert inc.new_pg_temp[(1, 0)] == []  # [] removes
    assert 0 not in inc.new_state
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_full_upmap_primary_temp_and_pool_delete():
    """pg_upmap (full remap), primary_temp, and pool deletions must
    travel in deltas (OSDMap.h:382-405); a follower applying increments
    must converge on maps that mutate them."""
    old = make_map()
    old.pg_upmap[(1, 7)] = [5, 4, 3]
    old.primary_temp[(1, 9)] = 2
    old.pools[3] = PgPool(size=2, pg_num=8, crush_rule=0)
    new = clone(old)
    new.epoch += 1
    new.pg_upmap[(1, 8)] = [0, 1, 2]      # add
    del new.pg_upmap[(1, 7)]              # remove
    new.primary_temp[(1, 4)] = 1          # add
    del new.primary_temp[(1, 9)]          # remove
    del new.pools[3]                      # pool deletion
    inc = diff_maps(old, new)
    assert inc.new_pg_upmap[(1, 8)] == [0, 1, 2]
    assert (1, 7) in inc.old_pg_upmap
    assert inc.new_primary_temp[(1, 4)] == 1
    assert inc.new_primary_temp[(1, 9)] == -1  # -1 removes
    assert 3 in inc.old_pools
    rt = Incremental.from_dict(inc.to_dict())  # wire round-trip
    got = clone(old)
    apply_incremental(got, rt)
    assert got.to_dict() == new.to_dict()


def test_primary_affinity_reset_to_default():
    """new map with affinity None (all-default) after a non-default old
    list must emit explicit default deltas, or followers keep stale
    affinities."""
    old = make_map()
    old.set_primary_affinity(1, 0x8000)
    old.set_primary_affinity(4, 0x4000)
    new = clone(old)
    new.epoch += 1
    new.osd_primary_affinity = None  # reset to default
    inc = diff_maps(old, new)
    assert set(inc.new_primary_affinity) == {1, 4}
    got = clone(old)
    apply_incremental(got, inc)
    # applying materializes an explicit all-default list; placement
    # equivalence is what matters, compare through the accessor
    from ceph_tpu.osdmap.osdmap import DEFAULT_PRIMARY_AFFINITY
    assert all(a == DEFAULT_PRIMARY_AFFINITY
               for a in got.osd_primary_affinity)


def test_shrink_max_osd():
    """A shrink must not emit deltas for truncated osds (they'd index
    out of bounds after new_max_osd applies)."""
    old = make_map(6)
    new = clone(old)
    new.epoch += 1
    new.set_max_osd(4)
    inc = diff_maps(old, new)
    assert inc.new_max_osd == 4
    assert all(o < 4 for o in inc.new_state)
    assert all(o < 4 for o in inc.new_weight)
    got = clone(old)
    apply_incremental(got, inc)
    assert got.to_dict() == new.to_dict()


def test_catch_up_walks_incrementals():
    """A follower several epochs behind catches up via get_inc deltas
    (no full-map fetch while history is retained)."""
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 5.0)
    cl = MiniCluster(n_osds=3, config=conf).start()
    try:
        c = cl.client("behind")
        # freeze the client's view, advance the mon several epochs
        import copy
        frozen = (c.map, c.epoch)
        cl.create_replicated_pool(1, pg_num=4, size=2)
        cl.create_replicated_pool(2, pg_num=4, size=2)
        cl.create_replicated_pool(3, pg_num=4, size=2)
        target = cl.mon.map.epoch
        with c._lock:
            c.map, c.epoch = frozen
        c._catch_up(target, {})
        assert c.epoch == target
        assert c.map.to_dict() == cl.mon.map.to_dict()
    finally:
        cl.shutdown()


def test_apply_rejects_gaps():
    m = make_map()
    inc = Incremental(epoch=m.epoch + 2)
    with pytest.raises(ValueError):
        apply_incremental(m, inc)


def test_versioned_wire_roundtrip():
    old = make_map()
    new = clone(old)
    new.epoch += 1
    new.osd_weight[1] = 0x8000
    inc = diff_maps(old, new)
    blob = inc.encode_versioned()
    inc2 = Incremental.decode_versioned(blob)
    got = clone(old)
    apply_incremental(got, inc2)
    assert got.to_dict() == new.to_dict()


def test_cluster_distributes_deltas():
    """Live daemons follow epochs through incrementals: after changes,
    subscriber epochs match the mon and their maps are bit-identical
    to the mon's full map."""
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.5)
    cl = MiniCluster(n_osds=3, config=conf).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=2)
        cl.create_replicated_pool(2, pg_num=4, size=3)
        c = cl.client("delta")
        c.put(1, "o", b"x" * 100)
        # incrementals were built for post-genesis epochs
        assert cl.mon._incs
        import time
        deadline = time.monotonic() + 10
        want = cl.mon.map.epoch
        while time.monotonic() < deadline:
            if all(svc.epoch == want
                   for svc in cl.osds.values()) and c.epoch == want:
                break
            time.sleep(0.1)
        assert c.epoch == want
        mon_map = cl.mon.map.to_dict()
        assert c.map.to_dict() == mon_map
        for svc in cl.osds.values():
            assert svc.epoch == want
            assert svc.map.to_dict() == mon_map
    finally:
        cl.shutdown()
