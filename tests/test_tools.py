"""Tools tests — crushtool/osdmaptool/ec_benchmark end-to-end.

Mirrors the reference's CLI QA (src/test/cli/crushtool,
src/test/cli/osdmaptool): compile ⇄ decompile round-trips, --test
stats, --build, --compare, map-pgs and the upmap flow — all through
the CLI mains, on the scalar path (tiny inputs, no compile cost).
"""

import json

import numpy as np
import pytest

from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.tools import crushtool, ec_benchmark, osdmaptool
from ceph_tpu.tools.compiler import (CompileError, compile_crushmap,
                                     decompile_crushmap)
from ceph_tpu.tools.tester import CrushTester

SAMPLE = """\
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0 class ssd
device 1 osd.1 class ssd
device 2 osd.2 class hdd
device 3 osd.3 class hdd

# types
type 0 osd
type 1 host
type 2 root

# buckets
host host0 {
\tid -1
\talg straw2
\thash 0
\titem osd.0 weight 1.000
\titem osd.2 weight 1.000
}
host host1 {
\tid -2
\talg straw2
\thash 0
\titem osd.1 weight 2.000
\titem osd.3 weight 1.000
}
root default {
\tid -3
\talg straw2
\thash 0
\titem host0 weight 2.000
\titem host1 weight 3.000
}

# rules
rule replicated_rule {
\tid 0
\ttype replicated
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
rule ssd_rule {
\tid 1
\ttype replicated
\tstep take default class ssd
\tstep chooseleaf firstn 0 type host
\tstep emit
}
# end crush map
"""


def test_compile_basics():
    w = compile_crushmap(SAMPLE)
    assert w.crush.tunables.choose_total_tries == 50
    assert w.get_item_id("default") == -3
    assert w.get_item_class(0) == "ssd"
    assert w.get_item_weight(1) == 0x20000
    assert 0 in w.crush.rules and 1 in w.crush.rules
    # class rule resolved to the shadow root
    take = w.crush.rules[1].steps[0]
    root = w.get_item_id("default")
    cid = w.get_or_create_class_id("ssd")
    assert take.arg1 == w.class_bucket[(root, cid)]


def test_compiled_map_places_correctly():
    w = compile_crushmap(SAMPLE)
    weight = [0x10000] * 4
    for x in range(32):
        res = w.do_rule(0, x, 2, weight)
        assert len(res) == 2
        assert {o // 1 for o in res}  # non-empty
        # ssd rule only places on ssd devices (0, 1)
        res = w.do_rule(1, x, 2, weight)
        assert all(o in (0, 1) for o in res)


def test_decompile_roundtrip():
    w1 = compile_crushmap(SAMPLE)
    text = decompile_crushmap(w1)
    w2 = compile_crushmap(text)
    # identical placement behavior after a full round-trip
    weight = [0x10000] * 4
    for rno in (0, 1):
        for x in range(64):
            assert w1.do_rule(rno, x, 2, weight) == \
                w2.do_rule(rno, x, 2, weight)
    # and a second decompile is textually stable
    assert decompile_crushmap(w2) == text


def test_compile_errors():
    with pytest.raises(CompileError):
        compile_crushmap("nonsense line\n")
    with pytest.raises(CompileError):
        compile_crushmap("tunable bogus_knob 1\n")
    with pytest.raises(CompileError):
        compile_crushmap("type 0 osd\nhost h {\n\titem osd.9 weight "
                         "1.0\n}\n")


def test_tester_stats_scalar():
    w = compile_crushmap(SAMPLE)
    t = CrushTester(w)
    rep = t.test_rule(0, 2, 0, 255, scalar=True)
    assert rep.total == 256
    assert rep.size_counts.get(2, 0) == 256
    assert int(rep.device_stored.sum()) == 512
    assert abs(float(rep.device_expected.sum()) - 512) < 1e-6
    # expected derives from the TESTER's weight vector (default all
    # equal — CrushTester.cc:521-545), not the crush weights
    assert rep.device_expected[1] == rep.device_expected[0]
    # --weight halves a device: its expected share drops
    t.set_device_weight(3, 0.5)
    rep2 = t.test_rule(0, 2, 0, 255, scalar=True)
    assert rep2.device_expected[3] < rep2.device_expected[0]
    # and stored placements on it drop too (weight-based rejection)
    assert int(rep2.device_stored[3]) < int(rep.device_stored[3])


def test_tester_compare_detects_difference():
    w1 = compile_crushmap(SAMPLE)
    w2 = compile_crushmap(SAMPLE)
    t1, t2 = CrushTester(w1), CrushTester(w2)
    diff, total = t1.compare(t2, 0, 2, 0, 127, scalar=True)
    assert diff == 0
    w2.adjust_item_weight(3, 0x80000)
    diff, total = t1.compare(t2, 0, 2, 0, 127, scalar=True)
    assert diff > 0


def test_crushtool_cli_flow(tmp_path):
    src = tmp_path / "map.txt"
    src.write_text(SAMPLE)
    out = tmp_path / "map.json"
    assert crushtool.main(["-c", str(src), "-o", str(out)]) == 0
    d = json.loads(out.read_text())
    assert "map" in d and "name_map" in d
    # decompile back
    txt = tmp_path / "back.txt"
    assert crushtool.main(["-d", str(out), "-o", str(txt)]) == 0
    assert "root default" in txt.read_text()
    # --test on the scalar path
    assert crushtool.main(["-i", str(out), "--test", "--num-rep", "2",
                           "--max-x", "63", "--scalar",
                           "--show-statistics"]) == 0
    # --tree
    assert crushtool.main(["-i", str(out), "--tree"]) == 0


def test_crushtool_build(tmp_path):
    out = tmp_path / "built.json"
    assert crushtool.main(
        ["--build", "--num-osds", "8", "-o", str(out),
         "host", "straw2", "2", "root", "straw2", "0"]) == 0
    w = crushtool.load_map(str(out))
    root = w.get_item_id("root")
    assert len(w.get_leaves(root)) == 8
    # a built map has no rules: --test says so (crushtool.cc behavior)
    assert crushtool.main(["-i", str(out), "--test", "--scalar"]) == 1
    # add a rule, then test works
    assert crushtool.main(
        ["-i", str(out), "--create-replicated-rule",
         "replicated_rule", "root", "host"]) == 0
    w = crushtool.load_map(str(out))
    assert w.get_rule_id("replicated_rule") == 0
    assert crushtool.main(["-i", str(out), "--test", "--num-rep", "2",
                           "--max-x", "31", "--scalar"]) == 0


def test_osdmaptool_flow(tmp_path):
    mapfn = tmp_path / "osdmap.json"
    assert osdmaptool.main([str(mapfn), "--createsimple", "8",
                            "--pg-bits", "3"]) == 0
    m_d = json.loads(mapfn.read_text())
    assert m_d["max_osd"] == 8
    # test-map-pgs on the scalar path
    assert osdmaptool.main([str(mapfn), "--test-map-pgs",
                            "--scalar"]) == 0
    # upmap flow writes commands
    cmds = tmp_path / "upmap.sh"
    assert osdmaptool.main([str(mapfn), "--upmap", str(cmds),
                            "--upmap-deviation", "1",
                            "--upmap-max", "16", "--scalar"]) == 0
    text = cmds.read_text()
    if text:  # balancer found improvements
        assert "pg-upmap-items" in text


def test_osdmaptool_dump(tmp_path, capsys):
    mapfn = tmp_path / "om.json"
    assert osdmaptool.main([str(mapfn), "--createsimple", "4",
                            "--pg-bits", "2"]) == 0
    capsys.readouterr()
    assert osdmaptool.main([str(mapfn), "--test-map-pgs-dump",
                            "--scalar"]) == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("1.")]
    assert len(lines) == 16  # 4 osds << 2 pg bits
    pgid, up, up_p, acting, act_p = lines[0].split("\t")
    assert pgid == "1.0" and int(up_p) >= 0


def test_ec_benchmark_cli(capsys):
    assert ec_benchmark.main(
        ["--plugin", "jerasure", "-P", "k=4", "-P", "m=2",
         "--workload", "encode", "--size", "8192",
         "--iterations", "2"]) == 0
    out = capsys.readouterr().out.strip().split("\t")
    assert float(out[0]) > 0 and int(out[1]) == 16
    assert ec_benchmark.main(
        ["--plugin", "lrc", "-P", "k=4", "-P", "m=2", "-P", "l=3",
         "--workload", "decode", "--size", "4096", "--erasures", "1",
         "--erasures-generation", "exhaustive", "--verify"]) == 0


def test_rados_cli_and_objectstore_tool(tmp_path):
    """The rados CLI round-trips through a live cluster by mon
    address, and objectstore-tool inspects/exports/imports the downed
    OSD's store offline."""
    import json
    import os

    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster
    from ceph_tpu.tools import objectstore_tool, rados

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.3)
    conf.set("osd_heartbeat_grace", 2.0)
    data_dir = str(tmp_path / "cluster")
    c = MiniCluster(n_osds=3, config=conf, data_dir=data_dir).start()
    try:
        c.create_replicated_pool(1, pg_num=8, size=2)
        mon = f"{c.mon.addr[0]}:{c.mon.addr[1]}"
        src = tmp_path / "in.bin"
        src.write_bytes(b"rados-cli-payload" * 100)
        out = tmp_path / "out.bin"
        assert rados.main(["--mon", mon, "-p", "1", "put", "obj-a",
                           str(src)]) == 0
        assert rados.main(["--mon", mon, "-p", "1", "get", "obj-a",
                           str(out)]) == 0
        assert out.read_bytes() == src.read_bytes()

        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rados.main(["--mon", mon, "-p", "1", "ls"])
        assert "obj-a" in buf.getvalue().splitlines()

        assert rados.main(["--mon", mon, "-p", "1", "rm",
                           "obj-a"]) == 0
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rados.main(["--mon", mon, "-p", "1", "ls"])
        assert "obj-a" not in buf.getvalue().splitlines()

        # seed an object, then take osd.0 down for offline surgery
        rados.main(["--mon", mon, "-p", "1", "put", "obj-b",
                    str(src)])
        c.kill_osd(0)
    finally:
        c.shutdown()

    store_path = os.path.join(data_dir, "osd0", "osd.0.wal")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        objectstore_tool.main(["--data-path", store_path,
                               "--op", "list"])
    listing = json.loads(buf.getvalue())
    pgs = [cid for cid, objs in listing.items()
           if any(o.startswith("obj-b") for o in objs)]
    if pgs:  # osd.0 held a shard: export -> import round-trip
        pgid = pgs[0]
        exp = tmp_path / "pg.export"
        objectstore_tool.main(["--data-path", store_path,
                               "--op", "export", "--pgid", pgid,
                               "--file", str(exp)])
        fresh = tmp_path / "fresh.wal"
        from ceph_tpu.os.wal_store import WALStore

        w = WALStore(str(fresh))
        w.mkfs()
        w.umount()
        objectstore_tool.main(["--data-path", str(fresh),
                               "--op", "import", "--file", str(exp)])
        w2 = WALStore(str(fresh))
        w2.mount()
        assert set(w2.list_objects(pgid)) == set(listing[pgid])
        w2.umount()


def test_ceph_cli(capsys):
    """The `ceph` admin CLI: status/health/osd tree/pool verbs against
    a live cluster by monitor address."""
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster
    from ceph_tpu.tools import ceph_cli

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.3)
    conf.set("osd_heartbeat_grace", 3.0)
    c = MiniCluster(n_osds=3, config=conf).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=2)
        mon = f"{c.mon.addr[0]}:{c.mon.addr[1]}"
        assert ceph_cli.main(["--mon", mon, "status"]) == 0
        out = capsys.readouterr().out
        assert "osds:    3 up" in out and "pools:   1" in out

        assert ceph_cli.main(["--mon", mon, "osd", "tree"]) == 0
        out = capsys.readouterr().out
        # the wire map carries structure, not the builder's name maps
        assert "root" in out and "host" in out
        assert any(ln.strip().startswith("0\t")
                   for ln in out.splitlines())

        assert ceph_cli.main(["--mon", mon, "pool", "create", "5",
                              "4", "2"]) == 0
        capsys.readouterr()
        assert ceph_cli.main(["--mon", mon, "pool", "ls"]) == 0
        out = capsys.readouterr().out
        assert "pool 5:" in out
        assert ceph_cli.main(["--mon", mon, "pool", "delete",
                              "5"]) == 0
        capsys.readouterr()
        assert ceph_cli.main(["--mon", mon, "osd", "reweight", "1",
                              "0.5"]) == 0
        capsys.readouterr()
        payload = c.mon_command({"type": "get_map"})
        from ceph_tpu.osdmap.bincode_maps import payload_map
        assert payload_map(payload).osd_weight[1] == 0x8000

        # health returns nonzero on WARN
        c.kill_osd(2)
        c.wait_for_down(2, timeout=10)
        rc = ceph_cli.main(["--mon", mon, "health"])
        out = capsys.readouterr().out
        assert rc == 1 and "HEALTH_WARN" in out
    finally:
        c.shutdown()
