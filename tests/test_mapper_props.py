"""Property tests for map shapes the golden corpus doesn't cover.

The golden maps are each single-algorithm; these tests build tree-bucket
and mixed-algorithm hierarchies with the builder and check the batched
JAX mapper against the scalar executable spec (itself golden-tested), so
the lax.switch multi-branch dispatch path is exercised.  Also covers the
statistical tests the reference runs in src/test/crush/crush.cc
(straw2_stddev:514, indep stability under failures: indep_out_*:151).
"""

import numpy as np
import pytest

import conftest  # noqa: F401  (forces CPU platform)

from ceph_tpu.crush import constants as C
from ceph_tpu.crush.builder import (add_simple_rule, make_list_bucket,
                                    make_straw2_bucket, make_tree_bucket,
                                    make_uniform_bucket,
                                    sample_cluster_map)
from ceph_tpu.crush.map import CrushMap, Rule, RuleStep
from ceph_tpu.crush.mapper_jax import BatchedMapper
from ceph_tpu.crush.mapper_ref import crush_do_rule


def _check_vs_ref(cmap, ruleno, numrep, weight, n=256):
    m = BatchedMapper(cmap)
    xs = np.arange(n, dtype=np.uint32)
    res, lens = m.map_batch(ruleno, xs, numrep, weight)
    res = np.asarray(res)
    lens = np.asarray(lens)
    for i, x in enumerate(xs):
        want = crush_do_rule(cmap, ruleno, int(x), numrep, list(weight))
        got = list(res[i, :lens[i]])
        assert got == want, (int(x), got, want)


def test_tree_bucket_map():
    cmap = CrushMap()
    ids = []
    for h in range(3):
        b = make_tree_bucket(list(range(4 * h, 4 * h + 4)),
                             [0x10000, 0x20000, 0x10000, 0x8000], 1)
        ids.append(cmap.add_bucket(b))
    root = make_tree_bucket(ids, [b and 0x40000 or 0x40000 for b in ids],
                            2)
    root_id = cmap.add_bucket(root)
    cmap.max_devices = 12
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=True, ruleno=0)
    _check_vs_ref(cmap, 0, 3, np.full(12, 0x10000, np.uint32))


def test_mixed_alg_map():
    """One host of each algorithm under a straw2 root — every lax.switch
    branch executes for every lane."""
    cmap = CrushMap()
    hosts = [
        make_straw2_bucket([0, 1, 2], [0x10000] * 3, 1),
        make_list_bucket([3, 4, 5], [0x10000, 0x18000, 0x8000], 1),
        make_tree_bucket([6, 7, 8], [0x10000, 0x10000, 0x20000], 1),
        make_uniform_bucket([9, 10, 11], 0x10000, 1),
    ]
    ids = [cmap.add_bucket(b) for b in hosts]
    root = make_straw2_bucket(ids, [b.weight for b in hosts], 2)
    root_id = cmap.add_bucket(root)
    cmap.max_devices = 12
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=True, ruleno=0)
    add_simple_rule(cmap, root_id, leaf_type=1, firstn=False, ruleno=1)
    w = np.full(12, 0x10000, np.uint32)
    _check_vs_ref(cmap, 0, 3, w, n=128)
    _check_vs_ref(cmap, 1, 3, w, n=128)


def test_straw2_weight_proportionality():
    """straw2_stddev analogue (src/test/crush/crush.cc:514): selection
    frequency tracks weight within a few percent."""
    cmap = CrushMap()
    weights = [0x10000, 0x20000, 0x30000, 0x40000]
    b = make_straw2_bucket([0, 1, 2, 3], weights, 1)
    root_id = cmap.add_bucket(b)
    cmap.max_devices = 4
    cmap.add_rule(Rule([RuleStep(C.CRUSH_RULE_TAKE, root_id, 0),
                        RuleStep(C.CRUSH_RULE_CHOOSE_FIRSTN, 1, 0),
                        RuleStep(C.CRUSH_RULE_EMIT, 0, 0)]), 0)
    m = BatchedMapper(cmap)
    n = 40000
    res, lens = m.map_batch(0, np.arange(n, dtype=np.uint32), 1,
                            np.full(4, 0x10000, np.uint32))
    counts = np.bincount(np.asarray(res)[:, 0], minlength=4)
    total_w = sum(weights)
    for i, w in enumerate(weights):
        expect = n * w / total_w
        assert abs(counts[i] - expect) / expect < 0.05, (i, counts)


def test_indep_positional_stability():
    """indep_out_* analogue (src/test/crush/crush.cc:151-233): marking a
    device out must not disturb other positions of EC mappings."""
    cmap = sample_cluster_map(3, 3, 3)
    m = BatchedMapper(cmap)
    D = cmap.max_devices
    xs = np.arange(2048, dtype=np.uint32)
    w = np.full(D, 0x10000, np.uint32)
    res, _ = m.map_batch(1, xs, 6, w)
    res = np.asarray(res)
    w2 = w.copy()
    w2[5] = 0
    res2 = np.asarray(m.map_batch(1, xs, 6, w2)[0])
    # positions that didn't hold osd.5 keep their device (or NONE)
    unchanged = res != 5
    assert (res2[unchanged] == res[unchanged]).mean() > 0.98


def test_u32_x_wraparound():
    """x is u32: -1 and 2**32-1 must map identically (goldengen gotcha)."""
    cmap = sample_cluster_map()
    m = BatchedMapper(cmap)
    w = np.full(cmap.max_devices, 0x10000, np.uint32)
    a = np.asarray(m.map_batch(0, np.array([2**32 - 1], np.uint32), 3,
                               w)[0])
    ref = crush_do_rule(cmap, 0, 2**32 - 1, 3, list(w))
    assert list(a[0][:len(ref)]) == ref
