"""The continuous-profiling plane's unit half.

Covers the pieces the MiniCluster integration test
(tests/test_telemetry.py::test_attribution_*) composes:

- critical-path attribution: fold_tree charges every instant of the
  root interval to exactly one stage (sum == client latency by
  construction), the q_wait carve surfaces dispatch queueing, unknown
  spans land in ``unattributed`` instead of inflating a neighbor;
- the wallclock sampler: off by default, bounded retention
  (max_stacks overflow bucket, max_seconds auto-stop), role folding;
- the byte-copy ledger: per-collection counters, idempotent creation,
  zero-booking no-op;
- metrics-history ring wrap: rates derive from retained samples only
  (the derive_rates docstring pins this file's test by name);
- op_tracker: the slow-op ring survives a fast-op burst that churns
  the main history ring end to end.
"""

from __future__ import annotations

import threading
import time

import pytest

from ceph_tpu.common import attribution, copytrack
from ceph_tpu.common import metrics_history as mh_mod
from ceph_tpu.common.metrics_history import MetricsHistory, derive_rates
from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.common.perf_counters import PerfCountersCollection
from ceph_tpu.common.profiler import (WallclockProfiler, merge_folded,
                                      render_flame, thread_role)


# ---------------------------------------------------------------------------
# attribution: fold_tree / fold_spans / StageAggregator
# ---------------------------------------------------------------------------

def _span(name, start, dur, span_id=None, parent_id=None,
          trace_id="t", finished=True, tags=None, children=None):
    s = {"name": name, "start": start, "duration": dur,
         "trace_id": trace_id, "span_id": span_id or name,
         "parent_id": parent_id, "finished": finished,
         "tags": tags or {}}
    if children is not None:
        s["children"] = children
    return s


def test_fold_tree_sums_to_total_across_parallel_children():
    # client.put [0, 10ms] with encode, fan-out, handler, and WAL
    # commit nested the way the write path nests them
    root = _span("client.put", 0.0, 0.010, children=[
        _span("ec.encode", 0.001, 0.002),
        _span("call:shard_write", 0.003, 0.006, children=[
            _span("handle:shard_write", 0.0035, 0.004, children=[
                _span("store.commit", 0.004, 0.002),
            ]),
        ]),
    ])
    fold = attribution.fold_tree(root)
    assert fold is not None
    st = fold["stages"]
    assert fold["total"] == pytest.approx(0.010)
    # every instant charged exactly once: stage totals sum to the
    # measured client latency by construction
    assert sum(st.values()) == pytest.approx(fold["total"], abs=1e-12)
    assert st["client"] == pytest.approx(0.002)   # head + tail
    assert st["encode"] == pytest.approx(0.002)
    assert st["fanout"] == pytest.approx(0.002)   # call minus handler
    assert st["osd_op"] == pytest.approx(0.002)   # handler minus WAL
    assert st["wal"] == pytest.approx(0.002)
    assert st["unattributed"] == pytest.approx(0.0, abs=1e-12)


def test_fold_tree_qwait_carves_dispatch_out_of_messenger():
    root = _span("client.put", 0.0, 0.010, children=[
        _span("call:write", 0.001, 0.008, children=[
            _span("handle:write", 0.003, 0.004,
                  tags={"q_wait": 0.002}),
        ]),
    ])
    st = attribution.fold_tree(root)["stages"]
    # messenger held 4ms on the timeline; 2ms of it was the dispatch
    # queue wait the handler tagged
    assert st["messenger"] == pytest.approx(0.002)
    assert st["dispatch"] == pytest.approx(0.002)
    assert st["osd_op"] == pytest.approx(0.004)
    assert sum(st.values()) == pytest.approx(0.010, abs=1e-12)


def test_fold_tree_qwait_clamped_to_messenger_time():
    # a q_wait claim larger than the surrounding messenger time
    # (overlapping parallel fan-out waits) cannot go negative
    root = _span("client.put", 0.0, 0.010, children=[
        _span("call:write", 0.001, 0.008, children=[
            _span("handle:write", 0.003, 0.004,
                  tags={"q_wait": 0.050}),
        ]),
    ])
    st = attribution.fold_tree(root)["stages"]
    assert st["messenger"] == pytest.approx(0.0, abs=1e-12)
    assert st["dispatch"] == pytest.approx(0.004)
    assert sum(st.values()) == pytest.approx(0.010, abs=1e-12)


def test_fold_tree_unknown_spans_land_in_unattributed():
    root = _span("client.get", 0.0, 0.010, children=[
        _span("mystery.op", 0.002, 0.003),
    ])
    st = attribution.fold_tree(root)["stages"]
    assert st["unattributed"] == pytest.approx(0.003)
    assert st["client"] == pytest.approx(0.007)


def test_fold_tree_rejects_unfinished_and_untimed_roots():
    assert attribution.fold_tree(
        _span("client.put", 0.0, 0.01, finished=False)) is None
    assert attribution.fold_tree(
        {"name": "client.put", "children": []}) is None


def test_stage_of_mapping():
    assert attribution.stage_of("client.put") == "client"
    assert attribution.stage_of("call:shard_write") == "fanout"
    assert attribution.stage_of("ec.encode") == "encode"
    assert attribution.stage_of("store.commit") == "wal"
    assert attribution.stage_of("call:write") == "messenger"
    assert attribution.stage_of("send:ping") == "messenger"
    assert attribution.stage_of("handle:write") == "osd_op"
    assert attribution.stage_of("mystery") is None
    assert attribution.stage_of(None) is None


def test_fold_spans_groups_parents_and_skips_non_roots():
    spans = [
        # t1: a complete client trace across two "daemons"
        _span("client.put", 100.0, 0.010, span_id="a",
              trace_id="t1"),
        _span("ec.encode", 100.001, 0.002, span_id="b",
              parent_id="a", trace_id="t1"),
        # t2: unfinished root — not folded
        _span("client.put", 200.0, 0.010, span_id="c",
              trace_id="t2", finished=False),
        # t3: a non-client root (orphaned handler) — not folded
        _span("handle:write", 300.0, 0.010, span_id="d",
              trace_id="t3"),
    ]
    folds = attribution.fold_spans(spans)
    assert len(folds) == 1
    assert folds[0]["trace_id"] == "t1"
    assert folds[0]["stages"]["encode"] == pytest.approx(0.002)
    assert folds[0]["stages"]["client"] == pytest.approx(0.008)


def test_stage_aggregator_report_shares_sum_to_one():
    agg = attribution.StageAggregator()
    for _ in range(4):
        agg.add(attribution.fold_tree(
            _span("client.put", 0.0, 0.010, children=[
                _span("ec.encode", 0.002, 0.004),
            ])))
    rep = agg.report()
    assert rep["n_ops"] == 4
    shares = [row["share"] for row in rep["stages"].values()]
    assert sum(shares) == pytest.approx(1.0, abs=0.01)
    assert rep["stages"]["encode"]["share"] == pytest.approx(0.4,
                                                            abs=0.01)
    text = attribution.render_report(rep)
    assert "encode" in text and "4 ops" in text


# ---------------------------------------------------------------------------
# wallclock profiler
# ---------------------------------------------------------------------------

def test_profiler_off_by_default_and_dump_empty():
    prof = WallclockProfiler(name="t")
    assert prof.running is False
    d = prof.profile_dump()
    assert d["running"] is False
    assert d["samples"] == 0 and d["folded"] == []


def test_profiler_samples_roles_and_stops():
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            time.sleep(0.001)

    t = threading.Thread(target=spin, name="mclock-w0", daemon=True)
    t.start()
    prof = WallclockProfiler(hz=400.0, max_seconds=10.0, name="t")
    try:
        assert prof.profile_start() is True
        assert prof.profile_start() is False  # idempotent
        time.sleep(0.15)
        assert prof.profile_stop() is True
        d = prof.profile_dump()
        assert d["running"] is False
        assert d["samples"] >= 5
        # the worker's pool role, index trimmed, leads its lines
        assert any(line.startswith("mclock-w;")
                   for line in d["folded"]), d["folded"][:5]
        # flamegraph-collapsed: "role;frame;... count"
        stack, _, count = d["folded"][0].rpartition(" ")
        assert int(count) >= 1 and ";" in stack
    finally:
        prof.profile_stop()
        stop.set()
        t.join(timeout=2)


def test_profiler_bounded_retention_and_auto_stop():
    prof = WallclockProfiler(hz=500.0, max_seconds=0.1, max_stacks=1,
                             name="t")
    try:
        prof.profile_start()
        deadline = time.monotonic() + 3.0
        while prof.running and time.monotonic() < deadline:
            time.sleep(0.02)
        # max_seconds auto-stop: a forgotten `profile start` dies alone
        assert prof.running is False
        d = prof.profile_dump()
        # max_stacks: beyond the cap, samples land in the explicit
        # overflow bucket instead of growing without bound
        distinct = {line.rpartition(" ")[0] for line in d["folded"]}
        assert len([s for s in distinct if "<overflow>" not in s]) <= 1
        if d["truncated"]:
            assert any("<overflow>" in s for s in distinct)
    finally:
        prof.profile_stop()


def test_thread_role_trimming():
    assert thread_role("msgr-dispatch:osd.1_3") == "msgr-dispatch"
    assert thread_role("mclock-w0") == "mclock-w"
    assert thread_role("wal-commit_12") == "wal-commit"
    assert thread_role("MainThread") == "MainThread"
    assert thread_role("") == "?"


def test_merge_folded_and_render_flame():
    merged = merge_folded({
        "osd.0": {"folded": ["mclock-w;a.py:f;b.py:g 3"]},
        "osd.1": {"folded": ["mclock-w;a.py:f;b.py:g 2",
                             "not an int line"]},
    })
    assert merged == {"osd.0/mclock-w;a.py:f;b.py:g": 3,
                      "osd.1/mclock-w;a.py:f;b.py:g": 2}
    text = render_flame(merged)
    assert "5 samples" in text and "b.py:g" in text


# ---------------------------------------------------------------------------
# byte-copy ledger
# ---------------------------------------------------------------------------

def test_copytrack_books_site_and_rollup_counters():
    coll = PerfCountersCollection()
    copytrack.book("recv", 100, copies=2, coll=coll)
    copytrack.book("ec_assembly", 50, copies=3, coll=coll)
    d = coll.dump()[copytrack.LOGGER]
    assert d["bytes_copied"] == 150 and d["copies"] == 5
    assert d["recv_bytes"] == 100 and d["recv_copies"] == 2
    assert d["ec_assembly_bytes"] == 50 and d["ec_assembly_copies"] == 3
    assert d["send_bytes"] == 0  # every site pre-declared, reads 0


def test_copytrack_ledger_is_per_collection_and_cached():
    a, b = PerfCountersCollection(), PerfCountersCollection()
    pa, pb = copytrack.ledger(a), copytrack.ledger(b)
    assert pa is not pb
    assert copytrack.ledger(a) is pa  # cached, not re-created
    copytrack.book_pc(pa, "send", 10)
    assert a.dump()[copytrack.LOGGER]["send_bytes"] == 10
    assert b.dump()[copytrack.LOGGER]["send_bytes"] == 0


def test_copytrack_zero_booking_is_noop():
    coll = PerfCountersCollection()
    copytrack.book_pc(copytrack.ledger(coll), "recv", 0, copies=0)
    d = coll.dump()[copytrack.LOGGER]
    assert d["bytes_copied"] == 0 and d["copies"] == 0


# ---------------------------------------------------------------------------
# metrics-history ring wrap (satellite 1 — pinned by the
# derive_rates docstring)
# ---------------------------------------------------------------------------

class _FakePerf:
    def __init__(self):
        self.v = 0

    def dump(self):
        return {"fake": {"ops": self.v}}


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def time(self):
        return self.t

    def monotonic(self):
        return self.t


def test_metrics_history_ring_wrap_rates(monkeypatch):
    """Once the bounded ring wraps, rates must pair consecutive
    RETAINED samples only — never a phantom interval against an
    evicted predecessor (which would report a rate spanning time the
    ring no longer holds)."""
    clock = _Clock()
    monkeypatch.setattr(mh_mod, "time", clock)
    fake = _FakePerf()
    hist = MetricsHistory("t", perf=fake, interval=1.0, retention=4)
    for _ in range(10):  # 10 samples into a 4-deep ring
        fake.v += 10
        clock.t += 1.0
        hist.sample()
    samples = hist.samples()
    assert len(samples) == 4  # wrapped: only the last 4 retained
    assert [s["perf"]["fake"]["ops"] for s in samples] == \
        [70, 80, 90, 100]
    rates = derive_rates(samples)["fake.ops"]
    # 4 retained samples -> exactly 3 derived intervals; a phantom
    # pair against an evicted sample would add a 4th (or skew the
    # first dt across the evicted gap)
    assert len(rates) == 3
    for r in rates:
        assert r["dt"] == pytest.approx(1.0)
        assert r["rate"] == pytest.approx(10.0)
    # the first interval's right endpoint is the SECOND-oldest
    # retained sample — the oldest retained is only ever a left edge
    assert rates[0]["ts"] == samples[1]["ts"]


def test_metrics_history_dump_matches_read_time_derivation(
        monkeypatch):
    clock = _Clock()
    monkeypatch.setattr(mh_mod, "time", clock)
    fake = _FakePerf()
    hist = MetricsHistory("t", perf=fake, interval=1.0, retention=8)
    for _ in range(3):
        fake.v += 5
        clock.t += 2.0
        hist.sample()
    d = hist.dump()
    assert d["n"] == 3
    assert d["rates"]["fake.ops"] == derive_rates(d["samples"])[
        "fake.ops"]
    assert [r["rate"] for r in d["rates"]["fake.ops"]] == \
        pytest.approx([2.5, 2.5])


# ---------------------------------------------------------------------------
# op_tracker slow-op ring (satellite 2)
# ---------------------------------------------------------------------------

def test_op_tracker_slow_ring_survives_fast_burst():
    """The regression the dedicated ring exists to prevent: a burst
    of fast ops used to churn the shared history ring end to end and
    evict the slow ops an operator was hunting."""
    trk = OpTracker(history_size=4, history_slow_threshold=0.05,
                    slow_history_size=8)
    slow = trk.create("osd_op", "the one that was slow")
    slow.start -= 1.0  # backdate: duration >= threshold
    slow.finish()
    for i in range(20):  # fast burst wraps _history five times over
        trk.create("osd_op", f"fast-{i}").finish()
    hist = trk.dump_historic_ops()
    assert hist["num_ops"] == 4
    assert all(o["description"].startswith("fast-")
               for o in hist["ops"])  # slow op gone from history...
    slow_dump = trk.dump_historic_slow_ops()
    assert slow_dump["threshold"] == pytest.approx(0.05)
    descs = [o["description"] for o in slow_dump["ops"]]
    assert descs == ["the one that was slow"]  # ...but kept here


def test_op_tracker_slow_ring_sized_independently():
    trk = OpTracker(history_size=2, history_slow_threshold=0.05,
                    slow_history_size=3)
    for i in range(5):
        op = trk.create("osd_op", f"slow-{i}")
        op.start -= 1.0
        op.finish()
    descs = [o["description"]
             for o in trk.dump_historic_slow_ops()["ops"]]
    assert descs == ["slow-2", "slow-3", "slow-4"]
    # default: the slow ring inherits history_size
    assert OpTracker(history_size=7)._slow.maxlen == 7
