"""LRC + ISA plugin + registry tests.

Mirrors src/test/erasure-code/TestErasureCodeLrc.cc (generated k/m/l
profiles, explicit layers, minimum_to_decode locality) and
TestErasureCodeIsa.cc (both techniques, round-trips, chunk size), plus
plugin-registry dispatch (TestErasureCodePlugin.cc's factory flow).
"""

import itertools
import json

import numpy as np
import pytest

from ceph_tpu.ec import registry
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.isa import make_isa
from ceph_tpu.ec.lrc import make_lrc


def _obj(n=3000, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# -- registry ---------------------------------------------------------------

def test_registry_dispatch():
    assert set(registry.plugins()) >= {"jerasure", "isa", "lrc"}
    code = registry.factory("jerasure", {"technique": "reed_sol_van",
                                         "k": "2", "m": "1"})
    assert code.get_chunk_count() == 3
    code = registry.profile_factory({"plugin": "isa", "k": "4",
                                     "m": "2"})
    assert code.get_chunk_count() == 6
    with pytest.raises(ErasureCodeError):
        registry.factory("nope", {})


# -- isa --------------------------------------------------------------------

@pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy"])
def test_isa_roundtrip(technique):
    code = make_isa({"technique": technique, "k": "7", "m": "3"})
    raw = _obj(5000)
    chunks = code.encode(range(10), raw)
    assert chunks[0].shape[0] % 32 == 0  # EC_ISA_ADDRESS_ALIGNMENT
    for erased in itertools.combinations(range(10), 3):
        avail = {i: c for i, c in chunks.items() if i not in erased}
        assert code.decode_concat(avail)[:len(raw)] == raw


def test_isa_m1_xor_path():
    """m=1 degenerates to XOR parity (the region_xor fast path): the
    parity chunk must equal the XOR of the data chunks."""
    code = make_isa({"k": "4", "m": "1"})
    raw = _obj(1000)
    chunks = code.encode(range(5), raw)
    want = np.zeros_like(np.asarray(chunks[0]))
    for i in range(4):
        want ^= np.asarray(chunks[i])
    assert np.array_equal(np.asarray(chunks[4]), want)


def test_isa_vandermonde_clamps():
    with pytest.raises(ErasureCodeError):
        make_isa({"k": "33", "m": "3"})
    with pytest.raises(ErasureCodeError):
        make_isa({"k": "7", "m": "5"})
    with pytest.raises(ErasureCodeError):
        make_isa({"k": "22", "m": "4"})
    make_isa({"technique": "cauchy", "k": "33", "m": "5"})  # no clamp


# -- lrc --------------------------------------------------------------------

def test_lrc_kml_profile_generation():
    code = make_lrc({"k": "4", "m": "2", "l": "3"})
    prof = code.get_profile()
    assert prof["mapping"] == "DD__DD__"
    layers = json.loads(prof["layers"])
    assert layers[0][0] == "DDc_DDc_"
    assert layers[1][0] == "DDDc____"
    assert layers[2][0] == "____DDDc"
    assert code.get_chunk_count() == 8
    assert code.get_data_chunk_count() == 4


def test_lrc_kml_validation():
    with pytest.raises(ErasureCodeError):
        make_lrc({"k": "4", "m": "2"})  # l missing
    with pytest.raises(ErasureCodeError):
        make_lrc({"k": "4", "m": "2", "l": "5"})  # (k+m) % l != 0
    with pytest.raises(ErasureCodeError):
        make_lrc({"k": "4", "m": "2", "l": "3",
                  "mapping": "DD__DD__"})  # generated key set
    with pytest.raises(ErasureCodeError):
        make_lrc({})  # no mapping at all


def test_lrc_roundtrip_all_single_and_double_losses():
    code = make_lrc({"k": "4", "m": "2", "l": "3"})
    raw = _obj(4000)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    for r in (1, 2):
        for erased in itertools.combinations(range(n), r):
            avail = {i: c for i, c in chunks.items()
                     if i not in erased}
            try:
                got = code.decode_concat(avail)
            except ErasureCodeError:
                continue  # some double losses exceed LRC's capability
            assert got[:len(raw)] == raw, f"erased={erased}"


def test_lrc_local_repair_reads_fewer_than_k():
    """BASELINE config 4: a single lost chunk repairs from its LOCAL
    layer — strictly fewer chunks than the global k would need."""
    code = make_lrc({"k": "4", "m": "2", "l": "3"})
    n = code.get_chunk_count()
    # lose data chunk 0 (in local group 0 = positions {0,1,2,3})
    want = set(range(n))
    minimum = code.minimum_to_decode({0}, want - {0})
    assert set(minimum) <= {1, 2, 3}  # local group only
    assert len(minimum) == 3  # l chunks, < global k=4 never mind equal
    # and the repair actually works from exactly those chunks
    raw = _obj(2000)
    chunks = code.encode(range(n), raw)
    avail = {i: chunks[i] for i in minimum}
    out = code.decode({0}, avail)
    assert np.array_equal(np.asarray(out[0]), np.asarray(chunks[0]))


def test_lrc_explicit_layers():
    code = make_lrc({
        "mapping": "__DD__DD",
        "layers": json.dumps([
            ["_cDD_cDD", ""],
            ["cDDD____", ""],
            ["____cDDD", ""],
        ]),
    })
    assert code.get_chunk_count() == 8
    assert code.get_data_chunk_count() == 4
    raw = _obj(1000)
    chunks = code.encode(range(8), raw)
    for erased in itertools.combinations(range(8), 1):
        avail = {i: c for i, c in chunks.items() if i not in erased}
        assert code.decode_concat(avail)[:len(raw)] == raw


def test_lrc_minimum_no_erasure_is_want():
    code = make_lrc({"k": "4", "m": "2", "l": "3"})
    n = code.get_chunk_count()
    got = code.minimum_to_decode({1, 2}, set(range(n)))
    assert set(got) == {1, 2}


def test_lrc_unrecoverable_raises():
    code = make_lrc({"k": "4", "m": "2", "l": "3"})
    with pytest.raises(ErasureCodeError):
        # lose an entire local group plus its global parity
        code.minimum_to_decode({0}, {4, 5, 6, 7})


def test_lrc_create_rule_and_placement():
    from ceph_tpu.crush.wrapper import CrushWrapper

    w = CrushWrapper()
    dev = 0
    for h in range(8):
        for _ in range(2):
            w.insert_item(dev, 0x10000, f"osd.{dev}",
                          {"host": f"host{h}", "root": "default"})
            dev += 1
    code = make_lrc({"k": "4", "m": "2", "l": "3",
                     "crush-root": "default",
                     "crush-failure-domain": "host"})
    rid = code.create_rule("lrcpool", w)
    n = code.get_chunk_count()
    for x in range(16):
        res = w.do_rule(rid, x, n, [0x10000] * 16)
        assert len(res) == n
        hosts = {o // 2 for o in res}
        assert len(hosts) == n  # failure-domain separation
