"""The scalar reference mapper vs every golden do_rule vector."""

import json

import pytest

from conftest import GOLDEN_DIR

from ceph_tpu.crush.map import CrushMap
from ceph_tpu.crush.mapper_ref import crush_do_rule

MAP_FILES = [
    "map_flat12", "map_tree3", "map_tree3_chooseargs", "map_tree3_legacy",
    "map_uniform", "map_list", "map_straw", "map_weird", "map_big10k",
]


def load(name):
    d = json.load(open(GOLDEN_DIR / f"{name}.json"))
    cmap = CrushMap.from_dict(d["map"])
    return cmap, d


@pytest.mark.parametrize("name", MAP_FILES)
def test_golden_map(name):
    cmap, d = load(name)
    cargs = cmap.choose_args.get("golden")
    for case in d["cases"]:
        ruleno = case["ruleno"]
        numrep = case["numrep"]
        weight = case["weight"]
        x0, x1 = case["x0"], case["x1"]
        # keep the big map quick: every x still covered for small maps
        step = 4 if name == "map_big10k" else 1
        for i, x in enumerate(range(x0, x1, step)):
            want = case["results"][x - x0]
            got = crush_do_rule(cmap, ruleno, x, numrep, weight,
                                choose_args=cargs)
            assert got == want, (name, ruleno, numrep, x, got, want)


def test_roundtrip_json():
    cmap, d = load("map_tree3")
    again = CrushMap.from_json(cmap.to_json())
    assert again.to_dict() == cmap.to_dict()
