"""Thrash stress — the qa/tasks/thrashosds.py role.

Concurrent writers against replicated and EC pools while OSDs (and a
quorum monitor) are killed and revived under them.  The invariant under
test is the storage system's only promise: every ACKED write is
readable afterwards, at its acked value — across failovers, peering,
reconciliation, and RMW.  This is the systematic concurrency-stress
story for SURVEY §5's race-detection row: the races it exercises are
real daemon races (map install vs op dispatch, peering vs writes,
election vs command forwarding), caught by invariant violation rather
than a sanitizer.
"""

import threading
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.services.client import ObjectNotFound
from ceph_tpu.services.cluster import MiniCluster

THRASH_SECONDS = 12.0


def conf():
    # Deflake history (PR 15): the [3] variant used to flake under
    # full-suite CPU load.  Root cause: the old beacon-only failure
    # detector let ONE stalled mon-beat delivery (GIL contention can
    # stretch a 0.2s cadence past the 1.2s grace) falsely mark a
    # healthy OSD down; the 1.5s down-out then remapped PGs and the
    # resulting recovery storm blew the verify deadlines.  The fix is
    # structural, not a widened timeout: markdown now needs >= 2 peer
    # REPORTERS from distinct CRUSH host subtrees (services/
    # heartbeat.py + check_failure), the peer grace self-adapts to
    # load via the ping-RTT EWMA, and the direct beacon survives only
    # as liveness-of-last-resort at mon_osd_report_timeout (5x grace
    # = 6s here) — a single slow beat can no longer kill anyone.
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.2)
    c.set("mon_osd_down_out_interval", 1.5)
    c.set("mon_lease", 0.3)
    c.set("mon_election_timeout", 0.5)
    return c


class Writer(threading.Thread):
    """Loops put/overwrite/delete over its own key space, recording
    the last ACKED value per key; unacked attempts may or may not
    land — both are legal."""

    def __init__(self, cluster, wid, pool_id, ec):
        super().__init__(daemon=True)
        self.cluster = cluster
        self.wid = wid
        self.pool = pool_id
        self.ec = ec
        self.cli = cluster.client(f"thrash-w{wid}-{pool_id}")
        self.acked = {}
        # keys whose exact content is indeterminate: an UNACKED op may
        # still have landed durably (reply lost after >= k shards
        # persisted — a legal outcome), so only readability is asserted
        # until a later fully-acked full overwrite re-determines them
        self.dirty = set()
        self.ops = 0
        self.stop = threading.Event()

    def run(self):
        i = 0
        while not self.stop.is_set():
            key = f"w{self.wid}-k{i % 7}"
            val = f"{self.wid}:{i}:".encode() * 40
            op = None
            try:
                if self.ec and i % 3 == 2:
                    # partial overwrite keeps base data outside range
                    base = self.acked.get(key)
                    if base is not None:
                        op = "rmw"
                        self.cli.write(self.pool, key, 8, val[:64])
                        merged = bytearray(base)
                        if len(merged) < 72:
                            merged.extend(bytes(72 - len(merged)))
                        merged[8:72] = val[:64]
                        self.acked[key] = bytes(merged)
                        # an acked RMW on a dirty key merges over
                        # unknown base content: stays dirty
                elif i % 11 == 10:
                    op = "delete"
                    self.cli.delete(self.pool, key)
                    self.acked[key] = None
                    self.dirty.discard(key)  # state fully determined
                else:
                    op = "put"
                    self.cli.put(self.pool, key, val)
                    self.acked[key] = val
                    self.dirty.discard(key)  # full overwrite
                self.ops += 1
            except Exception:
                if op is not None:
                    self.dirty.add(key)  # may or may not have landed
            i += 1
        self.cli.shutdown()


@pytest.mark.parametrize("n_mons", [1, 3])
def test_thrash_acked_writes_survive(tmp_path, n_mons):
    c = MiniCluster(n_osds=5, hosts=5, config=conf(),
                    data_dir=str(tmp_path / f"m{n_mons}"),
                    n_mons=n_mons).start()
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.create_ec_pool(2, "t21", {"plugin": "jerasure",
                                    "technique": "reed_sol_van",
                                    "k": "2", "m": "1", "w": "8"},
                         pg_num=8)
        writers = [Writer(c, 0, 1, ec=False),
                   Writer(c, 1, 1, ec=False),
                   Writer(c, 2, 2, ec=True)]
        for w in writers:
            w.start()

        end = time.monotonic() + THRASH_SECONDS
        victim = 0
        while time.monotonic() < end:
            c.kill_osd(victim)
            try:
                c.wait_for_down(victim, timeout=8)
            except TimeoutError:
                pass
            if n_mons == 3 and victim % 2 == 0:
                rank = 0 if victim == 0 else 1
                if rank in c.mons and len(c.mons) == 3:
                    c.kill_mon(rank)
                    time.sleep(1.2)
                    c.revive_mon(rank)
            time.sleep(1.5)
            c.revive_osd(victim)
            try:
                c.wait_for_up(victim, timeout=8)
            except TimeoutError:
                pass
            victim = (victim + 1) % 5

        for w in writers:
            w.stop.set()
        for w in writers:
            w.join(timeout=30)
        assert sum(w.ops for w in writers) > 30, \
            "thrash produced too few acked ops to mean anything"

        # settle: all osds up, recovery quiesced
        for o in range(5):
            if o not in c.osds:
                c.revive_osd(o)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if len(c.status()["up_osds"]) == 5:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        time.sleep(3.0)  # a peering pass after the last epoch

        checker = c.client("thrash-check")
        bad = []
        for w in writers:
            for key, want in w.acked.items():
                fuzzy = key in w.dirty
                deadline = time.monotonic() + 20
                while True:
                    try:
                        if want is None and not fuzzy:
                            try:
                                checker.get(w.pool, key,
                                            notfound_retries=0)
                                got = "EXISTS"
                            except ObjectNotFound:
                                got = None
                        else:
                            try:
                                got = checker.get(w.pool, key)
                            except ObjectNotFound:
                                got = None
                        if fuzzy:
                            # an unacked op may have landed: exact
                            # content is indeterminate, but the object
                            # must be READABLE (or legally absent)
                            break
                        if got == want:
                            break
                        if time.monotonic() > deadline:
                            bad.append((w.pool, key, "mismatch"))
                            break
                    except Exception as e:
                        if time.monotonic() > deadline:
                            bad.append((w.pool, key, repr(e)))
                            break
                    time.sleep(0.5)
        assert not bad, f"acked writes lost/corrupt: {bad[:5]}"
    finally:
        c.shutdown()
