"""Manager daemon + balancer module: the closed upmap loop.

Acceptance drill (ISSUE 10): on a live MiniCluster, enabling the
balancer proposes ``pg_upmap_items`` through a monitor incremental
that every subscribed daemon observes, and the loop provably pauses
under PG_DEGRADED (an OSD is killed mid-loop).  Offline, the same
module converges a synthetic uneven map with batched per-pool sweeps.
Plus the module-plane satellites: ``mgr module ls|enable|disable``,
module-error health folded into the monitor's coded checks, the
``ceph_cli balancer``/``mgr`` verbs, and the stale-map failpoint.
"""

import glob
import os
import time

import pytest

from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.config import Config
from ceph_tpu.mgr import (evaluate, make_synthetic_map, run_offline)
from ceph_tpu.mgr.daemon import MgrModule, _ModuleSched
from ceph_tpu.services.cluster import MiniCluster


def _fast_conf(**extra):
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.0)
    conf.set("mon_osd_down_out_interval", 1.0)
    conf.set("osd_pg_stat_report_interval", 0.2)
    conf.set("osd_scrub_interval", 0.0)
    conf.set("mgr_tick_interval", 0.1)
    conf.set("balancer_interval", 0.3)
    conf.set("balancer_max_deviation", 1)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


# -- offline: synthetic maps + convergence ----------------------------------

def test_synthetic_map_uneven_and_classes():
    m, w, rules = make_synthetic_map(
        n_osds=16, osds_per_host=2, hosts_per_rack=4, pg_num=64,
        seed=3, device_classes=["ssd", "hdd"], with_choose_args=True)
    # uneven: more than one distinct CRUSH weight step
    assert len({w.get_item_weight(d) for d in range(16)}) > 1
    assert set(rules) == {"repl", "repl-ssd", "repl-hdd"}
    assert set(m.pools) == {1, 2, 3}
    assert "compat" in m.crush.choose_args
    # the class rules map ONLY devices of their class (ssd = even
    # ids: classes assign round-robin)
    ssd = {d for d in range(16) if d % 2 == 0}
    for pid, want in ((2, ssd), (3, set(range(16)) - ssd)):
        pool = m.pools[pid]
        mapped = set()
        for ps in range(pool.pg_num):
            up, _p, _a, _ap = m.pg_to_up_acting_osds(pid, ps)
            mapped.update(o for o in up if o >= 0)
        assert mapped, f"pool {pid} mapped nothing"
        assert mapped <= want, f"pool {pid} left its device class"


@pytest.mark.slow
def test_offline_loop_converges_on_uneven_map():
    m, w, _rules = make_synthetic_map(
        n_osds=48, osds_per_host=4, hosts_per_rack=4, pg_num=256,
        seed=1)
    rec = run_offline(m, w, max_deviation=1, max_iterations=20,
                      max_rounds=15, seed=1)
    assert rec["converged"], rec
    assert rec["rounds"] >= 1
    assert rec["upmaps"] > 0
    # the ISSUE acceptance bar: deviation stddev reduced >= 5x
    assert rec["final_stddev"] * 5 <= rec["initial_stddev"], rec
    # every evaluation was a batched sweep: one launch per pool per
    # sweep, and the trajectory is monotone non-increasing
    assert rec["sweep_launches"] >= rec["rounds"] + 1
    traj = rec["stddev_trajectory"]
    assert all(b <= a + 1e-9 for a, b in zip(traj, traj[1:]))


def test_evaluate_per_pool_breakdown():
    m, w, _rules = make_synthetic_map(
        n_osds=8, osds_per_host=2, hosts_per_rack=2, pg_num=32,
        seed=2, device_classes=["ssd", "hdd"])
    ev = evaluate(m, w)
    # ONE batched launch per pool, every pool in the breakdown
    assert ev["sweep_launches"] == len(m.pools)
    assert set(ev["pools"]) == set(m.pools)
    for row in ev["pools"].values():
        assert row["stddev"] >= 0.0
        assert 0.0 <= row["score"] < 1.0
    assert ev["mapped_pgs"] == sum(p.pg_num for p in m.pools.values())


# -- live: module framework -------------------------------------------------

class _Boom(MgrModule):
    NAME = "boom"

    def tick(self):
        raise RuntimeError("boom")


def test_mgr_module_framework_and_health_fold():
    cl = MiniCluster(n_osds=3, config=_fast_conf()).start()
    try:
        mgr = cl.start_mgr()
        path = glob.glob(os.path.join(cl.asok_dir, "mgr.*.asok"))[0]

        rep = AdminSocket.request(path, "mgr", argv=["module", "ls"])
        assert "balancer" in rep["modules"]
        assert rep["modules"]["balancer"]["enabled"]

        rep = AdminSocket.request(
            path, "mgr", argv=["module", "disable", "balancer"])
        assert "success" in rep
        rep = AdminSocket.request(path, "balancer", argv=["status"])
        assert "error" in rep  # disabled modules take no commands
        rep = AdminSocket.request(
            path, "mgr", argv=["module", "enable", "balancer"])
        assert "success" in rep
        rep = AdminSocket.request(path, "balancer", argv=["status"])
        assert rep["active"] is False

        # a module that raises: jittered backoff records the error
        # and the monitor's coded health grows MGR_MODULE_ERROR
        mgr.modules["boom"] = _Boom(mgr)
        mgr.enabled["boom"] = True
        with mgr._lock:
            mgr._sched["boom"] = _ModuleSched()
        _wait(lambda: "MGR_MODULE_ERROR" in
              cl.health()["check_codes"], 20,
              "MGR_MODULE_ERROR health check")
        with mgr._lock:
            assert mgr._sched["boom"].error
        # disabling clears the fold on the next report
        mgr.enabled["boom"] = False
        _wait(lambda: "MGR_MODULE_ERROR" not in
              cl.health()["check_codes"], 20,
              "MGR_MODULE_ERROR to clear")
    finally:
        cl.shutdown()


# -- live: the closed loop --------------------------------------------------

def test_balancer_proposes_upmaps_and_pauses_degraded():
    # down-out disabled: the killed OSD stays IN, so PG_DEGRADED
    # holds for as long as it is dead and the pause is observable
    cl = MiniCluster(n_osds=4, config=_fast_conf(
        mon_osd_down_out_interval=600.0)).start()
    try:
        cl.create_replicated_pool(1, pg_num=32, size=2)
        # objects make degradation observable: PG state is computed
        # from shard deficits, so an empty pool never reports it
        c = cl.client("seed")
        for i in range(32):
            c.put(1, f"obj-{i}", b"x" * 4096)
        # manufacture imbalance: a half-weight device keeps its PGs
        # but its weight-proportional target halves
        cl.reweight_osd(0, 0.5)
        cl.wait_for_health_ok(timeout=60)
        epoch0 = cl.status()["epoch"]

        mgr = cl.start_mgr()
        bal = mgr.modules["balancer"]
        path = glob.glob(os.path.join(cl.asok_dir, "mgr.*.asok"))[0]
        rep = AdminSocket.request(path, "balancer", argv=["on"])
        assert "success" in rep

        # the loop proposes pg_upmap_items through a real monitor
        # incremental...
        _wait(lambda: len(cl.mon.map.pg_upmap_items) > 0, 60,
              "balancer upmap proposals at the monitor")
        assert cl.status()["epoch"] > epoch0
        # ...that every subscribed daemon observes
        pgid = next(iter(cl.mon.map.pg_upmap_items))

        def _osds_observed():
            return all(pgid in svc.map.pg_upmap_items
                       for svc in cl.osds.values())
        _wait(_osds_observed, 30, "OSD followers observing the upmap")
        assert pgid in mgr.map.pg_upmap_items  # and the mgr itself
        # the round logs its record after the LAST proposal commits,
        # while the monitor map shows the first one immediately
        _wait(lambda: bal.proposal_log, 30, "proposal round recorded")
        assert all(not p["degraded"] for p in bal.proposal_log)

        # kill an OSD mid-loop: the loop must pause while health
        # shows the cluster degraded, proposing nothing
        victim = cl.status()["up_osds"][-1]
        cl.kill_osd(victim)
        _wait(lambda: "PG_DEGRADED" in cl.health()["check_codes"],
              30, "PG_DEGRADED after kill")
        _wait(lambda: bal.paused, 30, "balancer pause")
        proposals_at_pause = len(bal.proposal_log)
        time.sleep(1.0)  # several ticks under degraded health
        assert bal.paused
        assert len(bal.proposal_log) == proposals_at_pause
        assert all(not p["degraded"] for p in bal.proposal_log)
        assert mgr.pc.dump()["balancer_paused"] >= 1

        # recovery completes -> the loop resumes
        cl.revive_osd(victim)
        cl.wait_for_health_ok(timeout=60)
        _wait(lambda: not bal.paused, 30, "balancer resume")

        # counters booked and live (OBS001's runtime face)
        pc = mgr.pc.dump()
        assert pc["balancer_rounds"] >= 1
        assert pc["balancer_sweep_launches"] >= 1
        assert pc["balancer_upmaps_proposed"] >= 1
    finally:
        cl.shutdown()


def test_balancer_stale_map_failpoint():
    cl = MiniCluster(n_osds=3, config=_fast_conf()).start()
    try:
        cl.create_replicated_pool(1, pg_num=16, size=2)
        cl.reweight_osd(0, 0.5)
        cl.wait_for_health_ok(timeout=60)
        mgr = cl.start_mgr()
        bal = mgr.modules["balancer"]
        cl.set_faults("mgr.balancer.stale_map=count:1")
        bal.active = True
        _wait(lambda: bal.stale_discards >= 1, 30,
              "stale-map discard")
        # the faulted round was discarded whole; the loop recovers
        # and a later clean sweep still lands proposals
        _wait(lambda: len(cl.mon.map.pg_upmap_items) > 0, 60,
              "post-discard proposals")
    finally:
        cl.set_faults("")
        cl.shutdown()


# -- CLI ---------------------------------------------------------------------

def test_ceph_cli_balancer_and_mgr_verbs(capsys):
    from ceph_tpu.tools import ceph_cli

    cl = MiniCluster(n_osds=3, config=_fast_conf()).start()
    try:
        cl.create_replicated_pool(1, pg_num=16, size=2)
        cl.start_mgr()

        rc = ceph_cli.main(["--asok-dir", cl.asok_dir,
                            "mgr", "module", "ls"])
        assert rc == 0
        assert "balancer" in capsys.readouterr().out

        rc = ceph_cli.main(["--asok-dir", cl.asok_dir,
                            "balancer", "status"])
        assert rc == 0
        assert '"active": false' in capsys.readouterr().out

        rc = ceph_cli.main(["--asok-dir", cl.asok_dir,
                            "balancer", "on"])
        assert rc == 0
        capsys.readouterr()

        # eval prints the per-pool score breakdown
        rc = ceph_cli.main(["--asok-dir", cl.asok_dir,
                            "balancer", "eval"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "cluster: stddev" in out
        assert "pool 1:" in out and "score" in out

        # no mgr socket -> clear failure
        rc = ceph_cli.main(["--asok-dir", "/nonexistent-dir",
                            "balancer", "status"])
        assert rc == 2
    finally:
        cl.shutdown()
