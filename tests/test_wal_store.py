"""WALStore crash-consistency tests.

The contract under test (src/os/ObjectStore.h atomicity; BlueStore
WAL role): a transaction whose queue_transaction returned is durable
(survives kill -9), state after any crash is a prefix of the acked
transaction stream, and a torn WAL tail (the in-flight record at the
moment of death) is discarded, never half-applied.
"""

import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from ceph_tpu.common.bincode import Decoder, Encoder, decode_txn, \
    encode_txn
from ceph_tpu.os.objectstore import Transaction
from ceph_tpu.os.wal_store import WALStore


def make(tmp_path, **kw):
    st = WALStore(str(tmp_path / "store"), **kw)
    st.mkfs()
    st.mount()
    return st


def test_bincode_txn_roundtrip():
    t = Transaction()
    t.create_collection("pg1")
    t.write("pg1", "obj", 4, b"\x00\xffdata")
    t.setattr("pg1", "obj", "hinfo", b"\x01\x02")
    t.omap_setkeys("pg1", "obj", {"k1": b"v1", "k2": b""})
    t.omap_rmkeys("pg1", "obj", ["k2"])
    t.truncate("pg1", "obj", 3)
    enc = Encoder()
    encode_txn(t.ops, enc)
    assert decode_txn(Decoder(enc.bytes())) == t.ops


def test_mount_replays_unclean_shutdown(tmp_path):
    st = make(tmp_path)
    t = Transaction().create_collection("pg1")
    t.write("pg1", "a", 0, b"hello")
    st.queue_transaction(t)
    st.queue_transaction(Transaction().write("pg1", "a", 5, b" world"))
    st.queue_transaction(
        Transaction().omap_setkeys("pg1", "a", {"v": b"1"}))
    # NO umount/checkpoint: simulate a crash by just dropping the
    # handle; a fresh mount must replay the WAL
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.read("pg1", "a") == b"hello world"
    assert st2.omap_get("pg1", "a") == {"v": b"1"}
    assert st2._seq == 3


def test_clean_umount_checkpoints_and_truncates(tmp_path):
    st = make(tmp_path)
    st.queue_transaction(
        Transaction().create_collection("pg1").write(
            "pg1", "a", 0, b"x" * 1000))
    st.umount()
    assert os.path.getsize(os.path.join(st.path, "wal.log")) == 0
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.read("pg1", "a") == b"x" * 1000


def test_torn_tail_discarded_prefix_survives(tmp_path):
    st = make(tmp_path)
    st.queue_transaction(Transaction().create_collection("pg1"))
    for i in range(5):
        st.queue_transaction(
            Transaction().write("pg1", f"o{i}", 0, bytes([i]) * 64))
    wal = os.path.join(st.path, "wal.log")
    size = os.path.getsize(wal)
    # tear the last record in half (the kill-9-mid-append shape)
    with open(wal, "r+b") as f:
        f.truncate(size - 40)
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.list_objects("pg1") == [f"o{i}" for i in range(4)]
    # and a corrupt (bit-rot) record also stops replay at its seq
    st3 = make(tmp_path / "c")
    st3.queue_transaction(Transaction().create_collection("pg1"))
    st3.queue_transaction(
        Transaction().write("pg1", "good", 0, b"g"))
    st3.queue_transaction(
        Transaction().write("pg1", "bad", 0, b"b"))
    wal3 = os.path.join(st3.path, "wal.log")
    data = bytearray(open(wal3, "rb").read())
    data[-1] ^= 0xFF  # flip a payload byte of the last record
    open(wal3, "wb").write(data)
    st4 = WALStore(st3.path)
    st4.mount()
    assert st4.list_objects("pg1") == ["good"]


def test_writes_after_torn_tail_remount_survive(tmp_path):
    """mount() must CUT a torn tail before appending: a record written
    after garbage bytes would be unreachable to the next replay —
    an acked transaction silently lost."""
    st = make(tmp_path)
    st.queue_transaction(Transaction().create_collection("pg1"))
    st.queue_transaction(Transaction().write("pg1", "o1", 0, b"1"))
    st.queue_transaction(Transaction().write("pg1", "o2", 0, b"2"))
    wal = os.path.join(st.path, "wal.log")
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 3)  # torn tail
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.list_objects("pg1") == ["o1"]
    st2.queue_transaction(Transaction().write("pg1", "post", 0, b"p"))
    st3 = WALStore(st.path)
    st3.mount()
    assert st3.read("pg1", "post") == b"p"
    assert st3.list_objects("pg1") == ["o1", "post"]


def test_checkpoint_then_more_txns_then_crash(tmp_path):
    st = make(tmp_path)
    st.queue_transaction(
        Transaction().create_collection("pg1").write(
            "pg1", "pre", 0, b"pre"))
    st.checkpoint()
    st.queue_transaction(Transaction().write("pg1", "post", 0, b"post"))
    st2 = WALStore(st.path)  # crash: no umount
    st2.mount()
    assert st2.read("pg1", "pre") == b"pre"
    assert st2.read("pg1", "post") == b"post"


def test_auto_checkpoint_threshold(tmp_path):
    st = make(tmp_path, checkpoint_every_bytes=4096)
    st.queue_transaction(Transaction().create_collection("pg1"))
    for i in range(8):
        st.queue_transaction(
            Transaction().write("pg1", f"o{i}", 0, b"z" * 1024))
    assert st._ckpt_seq > 0  # folded at least once without umount
    st2 = WALStore(st.path)
    st2.mount()
    assert len(st2.list_objects("pg1")) == 8


def test_failed_txn_never_journals(tmp_path):
    st = make(tmp_path)
    st.queue_transaction(Transaction().create_collection("pg1"))
    seq = st._seq
    bad = Transaction().write("pg1", "a", 0, b"ok").remove(
        "pg1", "missing")
    with pytest.raises(Exception):
        st.queue_transaction(bad)
    assert st._seq == seq  # nothing journaled
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.list_objects("pg1") == []  # nothing half-applied


_CHILD = r"""
import sys
from ceph_tpu.os.objectstore import Transaction
from ceph_tpu.os.wal_store import WALStore

st = WALStore(sys.argv[1])
st.mkfs()
st.mount()
st.queue_transaction(Transaction().create_collection("pg1"))
print("ack 0", flush=True)
i = 0
while True:
    i += 1
    t = Transaction().write("pg1", "o%d" % i, 0, bytes([i % 256]) * 512)
    t.omap_setkeys("pg1", "o%d" % i, {"seq": str(i).encode()})
    st.queue_transaction(t)
    print("ack %d" % i, flush=True)
"""


def test_kill9_mid_burst_every_acked_write_survives(tmp_path):
    """The headline contract: kill -9 an OSD-grade store mid-write-
    burst; after remount the state is a prefix of acked transactions
    and EVERY acked write survives."""
    path = str(tmp_path / "store")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, path],
        stdout=subprocess.PIPE, text=True)
    acked = -1
    deadline = time.monotonic() + 30
    while acked < 25 and time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("ack "):
            acked = int(line.split()[1])
    assert acked >= 25, "child too slow to ack writes"
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()

    st = WALStore(path)
    st.mount()
    objs = st.list_objects("pg1")
    # every acked txn survives …
    for i in range(1, acked + 1):
        assert f"o{i}" in objs, f"acked write o{i} lost"
        assert st.read("pg1", f"o{i}") == bytes([i % 256]) * 512
        assert st.omap_get("pg1", f"o{i}")["seq"] == str(i).encode()
    # … and the state is a PREFIX: object seqs are contiguous from 1
    # (at most one un-acked in-flight txn may also have landed)
    seqs = sorted(int(o[1:]) for o in objs)
    assert seqs == list(range(1, len(seqs) + 1))
    assert len(seqs) >= acked


# ---------------------------------------------------------------------------
# group commit: concurrent txns share one fsync; the ack point stays
# the fsync; a crash between the group append and the shared fsync
# replays an all-or-prefix of the group in submission order
# ---------------------------------------------------------------------------

def _wal_pc():
    from ceph_tpu.os.wal_store import _pc

    return _pc.dump()


def test_group_commit_depth1_synchronous_fallback(tmp_path):
    """A lone writer is its own group-commit leader: exactly one fsync
    per txn, inline — the depth-1 path costs what the old
    fsync-per-txn path cost."""
    st = make(tmp_path)
    base = _wal_pc()
    st.queue_transaction(Transaction().create_collection("pg1"))
    st.queue_transaction(Transaction().write("pg1", "a", 0, b"x"))
    cur = _wal_pc()
    assert cur["txns"] - base["txns"] == 2
    assert cur["group_commits"] - base["group_commits"] == 2


def test_group_commit_coalesces_concurrent_fsyncs(tmp_path):
    """N concurrent writers cost far fewer than N fsyncs, at least one
    multi-txn group forms, and every acked txn is durable across a
    crash-remount."""
    import threading

    st = make(tmp_path, group_commit_max_delay_us=5000)
    st.queue_transaction(Transaction().create_collection("pg1"))
    base = _wal_pc()
    n_threads, n_txns = 8, 5
    errs = []

    def worker(tid):
        try:
            for i in range(n_txns):
                st.queue_transaction(Transaction().write(
                    "pg1", f"o-{tid}-{i}", 0, b"x" * 128))
        except Exception as e:  # surfaced below
            errs.append(e)

    ths = [threading.Thread(target=worker, args=(t,))
           for t in range(n_threads)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert not errs
    cur = _wal_pc()
    txns = cur["txns"] - base["txns"]
    fsyncs = cur["group_commits"] - base["group_commits"]
    assert txns == n_threads * n_txns
    assert fsyncs < txns, \
        f"no coalescing: {fsyncs} fsyncs for {txns} txns"
    grew = [c - b for c, b in zip(cur["wal_group_size"]["buckets"],
                                  base["wal_group_size"]["buckets"])]
    assert sum(grew[1:]) > 0, "no multi-txn group ever formed"
    # the ack point stayed the fsync: a crash-remount holds every
    # acked txn
    st2 = WALStore(st.path)
    st2.mount()
    assert len(st2.list_objects("pg1")) == txns


def test_checkpoint_completes_pending_group(tmp_path):
    """An auto-checkpoint triggered mid-group is itself the group's
    durability: waiters complete, nothing hangs, everything mounts."""
    st = make(tmp_path, checkpoint_every_bytes=2048,
              group_commit_max_delay_us=2000)
    st.queue_transaction(Transaction().create_collection("pg1"))
    import threading

    def worker(tid):
        for i in range(4):
            st.queue_transaction(Transaction().write(
                "pg1", f"o-{tid}-{i}", 0, b"z" * 512))

    ths = [threading.Thread(target=worker, args=(t,))
           for t in range(4)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert st._ckpt_seq > 0
    st2 = WALStore(st.path)
    st2.mount()
    assert len(st2.list_objects("pg1")) == 16


_GROUP_CHILD = r"""
import os, sys, threading
from ceph_tpu.os.objectstore import Transaction
from ceph_tpu.os.wal_store import WALStore

st = WALStore(sys.argv[1], group_commit_max_delay_us=3000)
st.mkfs()
st.mount()
st.queue_transaction(Transaction().create_collection("pg1"))

groups = [0]
def fault(seqs):
    # the crash point of the satellite contract: AFTER the group's
    # records are appended, BEFORE the shared fsync covers them
    groups[0] += 1
    if groups[0] > 5:
        os._exit(9)
st._fault_before_sync = fault

lk = threading.Lock()
ctr = [0]
def worker():
    while True:
        st.queue_transaction(Transaction().write(
            "pg1", "obj-%d" % threading.get_ident(), 0, b"d" * 64))
        with lk:
            ctr[0] += 1
            print("ack %d" % ctr[0], flush=True)

for _ in range(6):
    threading.Thread(target=worker, daemon=True).start()
import time
time.sleep(30)
"""


def test_group_crash_between_append_and_fsync(tmp_path):
    """Kill the store between the group append and the shared fsync:
    replay must yield an all-or-prefix of the group in submission
    (WAL) order, every acked txn must survive, and last_mount_error
    must stay clean."""
    path = str(tmp_path / "store")
    proc = subprocess.Popen(
        [sys.executable, "-c", _GROUP_CHILD, path],
        stdout=subprocess.PIPE, text=True)
    acked = 0
    deadline = time.monotonic() + 30
    while proc.poll() is None and time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("ack "):
            acked = max(acked, int(line.split()[1]))
    proc.wait(timeout=30)
    assert proc.returncode == 9, "child never hit the fault hook"
    assert acked > 0, "child acked nothing before the crash"

    st = WALStore(path)
    st.mount()
    assert st.last_mount_error is None
    objs = st.list_objects("pg1")
    # submission-order prefix: one object-create per txn, so the
    # replayed seq must account for exactly the replayed objects
    # (create_collection is seq 1) — a record skipped mid-stream
    # would break this
    n_writes = st._seq - 1
    assert n_writes >= acked, \
        f"acked txn lost: replayed {n_writes}, acked {acked}"

    # now tear the crashed group's LAST appended record (the torn-
    # append shape): replay yields a shorter prefix, still clean
    wal = os.path.join(path, "wal.log")
    size = os.path.getsize(wal)
    if size > 0:
        with open(wal, "r+b") as f:
            f.truncate(size - 1)
        st2 = WALStore(path)
        st2.mount()
        assert st2._seq <= st._seq
        assert st2.last_mount_error is None


def test_memstore_concurrent_transactions_atomic():
    """prepare/commit both run under the store lock via
    queue_transaction: concurrent writers must never lose updates
    (the OSD service applies shard writes from per-connection
    threads)."""
    import threading

    from ceph_tpu.os.memstore import MemStore
    from ceph_tpu.os.objectstore import Transaction

    s = MemStore()
    t = Transaction()
    t.create_collection("c")
    s.queue_transaction(t)
    n_threads, n_txns = 8, 100

    def worker(tid):
        for i in range(n_txns):
            t = Transaction()
            t.write("c", f"o-{tid}-{i}", 0, b"x")
            s.queue_transaction(t)

    ths = [threading.Thread(target=worker, args=(k,))
           for k in range(n_threads)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert len(s.list_objects("c")) == n_threads * n_txns


def test_wal_journal_failure_rolls_back(tmp_path):
    """A failed append must neither apply in memory nor leave bytes
    that replay or strand later records (review: seq reuse after
    EIO)."""
    import os

    from ceph_tpu.os.objectstore import Transaction
    from ceph_tpu.os.wal_store import WALStore

    p = str(tmp_path / "w")
    s = WALStore(p)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    t.write("c", "o", 0, b"base")
    s.queue_transaction(t)

    s._wal_f.close()  # force the next append to fail
    t2 = Transaction()
    t2.write("c", "o", 0, b"FAIL")
    try:
        s.queue_transaction(t2)
        assert False, "append on closed journal must raise"
    except ValueError:
        pass
    assert s.read("c", "o") == b"base"  # memory not mutated

    # the rollback reopened the log at the last valid boundary: later
    # acked writes land, survive remount, and the failed txn is absent
    t3 = Transaction()
    t3.write("c", "o", 0, b"good")
    s.queue_transaction(t3)
    s2 = WALStore(p)
    s2.mount()
    assert s2.read("c", "o") == b"good"


def test_incremental_refused_by_older_reader():
    """v2 deltas carry placement-affecting fields an old reader cannot
    skip; the envelope must refuse, not silently diverge."""
    import pytest

    from ceph_tpu.common.encoding import MalformedInput, decode
    from ceph_tpu.osdmap.incremental import Incremental

    inc = Incremental(epoch=5)
    inc.new_pg_upmap[(1, 2)] = [3, 4]
    blob = inc.encode_versioned()
    assert Incremental.decode_versioned(blob).new_pg_upmap == \
        {(1, 2): [3, 4]}
    with pytest.raises(MalformedInput):
        decode(blob, supported=1)  # a v1 follower refuses and full-fetches


def test_checkpoint_compression_roundtrip(tmp_path):
    """Checkpoints run through the compressor registry; stores written
    with different codecs (or none) all mount."""
    import os

    from ceph_tpu.os.objectstore import Transaction
    from ceph_tpu.os.wal_store import WALStore

    p = str(tmp_path / "c")
    s = WALStore(p, compression="zlib")
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    t.write("c", "o", 0, b"A" * 100_000)  # compressible
    s.queue_transaction(t)
    s.umount()
    raw = os.path.getsize(os.path.join(p, "checkpoint"))
    assert raw < 10_000, f"checkpoint not compressed: {raw}B"

    # a zlib-written store mounts under a different configured codec
    s2 = WALStore(p, compression="none")
    s2.mount()
    assert s2.read("c", "o") == b"A" * 100_000
    s2.umount()
    s3 = WALStore(p, compression="lzma")
    s3.mount()
    assert s3.read("c", "o") == b"A" * 100_000


# ---------------------------------------------------------------------------
# checkpoint robustness: bad compressor tag / truncated compressed
# body must surface a clean error and the store must still mount from
# the WAL (never crash, never silently drop journaled txns)
# ---------------------------------------------------------------------------

def _write_txns(st, n=3):
    st.queue_transaction(Transaction().create_collection("pg1"))
    for i in range(n):
        st.queue_transaction(
            Transaction().write("pg1", f"o{i}", 0, b"x" * 8))
    st._wal_f.flush()


def _corrupt_ckpt(path, mangle):
    raw = bytearray(open(path, "rb").read())
    mangle(raw)
    open(path, "wb").write(bytes(raw))


def test_checkpoint_unknown_compressor_tag_mounts_from_wal(tmp_path):
    from ceph_tpu.common.encoding import MalformedInput
    from ceph_tpu.os.wal_store import (_MAGIC_Z, _crc32c, _HDR,
                                       decode_checkpoint)

    st = make(tmp_path)
    _write_txns(st)

    # forge the mkfs checkpoint to claim a compressor this build
    # lacks (a "zstd9" store opened by an older binary), crc valid
    raw = open(st._ckpt_path, "rb").read()
    magic, seq, ln, crc = _HDR.unpack_from(raw)
    tag = b"zstd9"
    body = bytes([len(tag)]) + tag + b"\x00" * 16
    forged = _HDR.pack(_MAGIC_Z, seq, len(body),
                       _crc32c(body)) + body
    open(st._ckpt_path, "wb").write(forged)

    # the pure codec refuses it CLEANLY (typed, names the struct)
    with pytest.raises(MalformedInput) as ei:
        decode_checkpoint(forged)
    assert "os.wal_checkpoint" in str(ei.value)

    # ...and the store still mounts, recovering every acked txn from
    # the WAL, with the error surfaced on the store object
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.last_mount_error is not None
    assert "zstd9" in st2.last_mount_error or \
        "compressor" in st2.last_mount_error
    assert st2.list_objects("pg1") == ["o0", "o1", "o2"]
    assert st2.read("pg1", "o1") == b"x" * 8
    # the recovered store keeps working: write + checkpoint + remount
    st2.queue_transaction(
        Transaction().write("pg1", "post", 0, b"p"))
    st2.umount()  # checkpoints: the bad file is overwritten
    st3 = WALStore(st.path)
    st3.mount()
    assert st3.last_mount_error is None
    assert st3.read("pg1", "post") == b"p"


def test_checkpoint_truncated_compressed_body_mounts_from_wal(
        tmp_path):
    st = make(tmp_path)
    _write_txns(st)
    st.checkpoint()  # fold into a real zlib checkpoint, WAL truncated
    st.queue_transaction(
        Transaction().write("pg1", "after", 0, b"a"))

    # bit rot tears bytes off the checkpoint tail: the folded state
    # is genuinely gone from disk.  mount() must still come up (the
    # acked-prefix contract over what the disk still PROVES), surface
    # the loss on last_mount_error — and never crash on the WAL
    # record whose base state vanished with the checkpoint.
    raw = open(st._ckpt_path, "rb").read()
    open(st._ckpt_path, "wb").write(raw[:len(raw) - 7])
    st2 = WALStore(st.path)
    st2.mount()
    assert st2.last_mount_error is not None
    assert "undecodable" in st2.last_mount_error
    # the store is usable again: writes, checkpoint, clean remount
    st2.queue_transaction(Transaction().create_collection("pg2"))
    st2.queue_transaction(
        Transaction().write("pg2", "fresh", 0, b"f"))
    st2.umount()
    st3 = WALStore(st.path)
    st3.mount()
    assert st3.last_mount_error is None
    assert st3.read("pg2", "fresh") == b"f"


def test_checkpoint_valid_crc_corrupt_zlib_stream(tmp_path):
    """crc recomputed over a damaged compressed stream (a forged or
    torn-then-rewritten file): decompression fails -> clean fallback,
    not a zlib.error crash."""
    from ceph_tpu.os.wal_store import _crc32c, _HDR

    st = make(tmp_path)
    _write_txns(st)
    raw = bytearray(open(st._ckpt_path, "rb").read())
    magic, seq, ln, crc = _HDR.unpack_from(raw)
    body = bytearray(raw[_HDR.size:_HDR.size + ln])
    if len(body) > 4:
        body[-2] ^= 0xFF  # damage inside the zlib stream
    forged = _HDR.pack(magic, seq, len(body),
                       _crc32c(bytes(body))) + bytes(body)
    open(st._ckpt_path, "wb").write(forged)
    st2 = WALStore(st.path)
    st2.mount()  # must not raise
    assert st2.last_mount_error is not None
    assert st2.list_objects("pg1") == ["o0", "o1", "o2"]
