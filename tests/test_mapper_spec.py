"""The speculative firstn mapper vs the golden do_rule vectors.

Same corpus as test_mapper_jax.py restricted to eligible cases (straw2-only
maps, take/chooseleaf-firstn/emit rules, modern tunables) — the speculative
program must be bit-exact there, and `analyze` must correctly refuse
everything else (legacy tunables, other bucket algs, multi-step rules).
Both straw2 lowerings (LN16-table key and computed-ln draw) are covered.
"""

import json

import numpy as np
import pytest

from conftest import GOLDEN_DIR

from ceph_tpu.crush.map import CrushMap
from ceph_tpu.crush.mapper_spec import (Ineligible, SpeculativeMapper,
                                        analyze)

MAP_FILES = [
    "map_flat12", "map_tree3", "map_tree3_chooseargs", "map_tree3_legacy",
    "map_uniform", "map_list", "map_straw", "map_weird", "map_big10k",
]

# cases analyze() must accept: (map, ruleno) pairs known eligible — the
# default replicated-rule shape on every straw2 map in the corpus
ELIGIBLE = {("map_flat12", 0), ("map_tree3", 0),
            ("map_tree3_chooseargs", 0), ("map_weird", 0),
            ("map_big10k", 0)}
# and ones it must refuse, with the reason class
INELIGIBLE = {("map_tree3_legacy", 0): "legacy",
              ("map_uniform", 0): "alg",
              ("map_tree3", 2): "non-device"}


def load(name):
    d = json.load(open(GOLDEN_DIR / f"{name}.json"))
    return CrushMap.from_dict(d["map"]), d


@pytest.mark.parametrize("k_tries", [1, 8])
@pytest.mark.parametrize("name", MAP_FILES)
def test_golden_eligible_cases(name, k_tries):
    cmap, d = load(name)
    cargs = cmap.choose_args.get("golden")
    mapper = None
    covered = 0
    for case in d["cases"]:
        ruleno, numrep = case["ruleno"], case["numrep"]
        try:
            analyze(cmap, ruleno, numrep)
        except Ineligible:
            continue
        if mapper is None:
            mapper = SpeculativeMapper(cmap, choose_args=cargs,
                                       k_tries=k_tries)
        weight = np.asarray(case["weight"], np.uint32)
        x0, x1 = case["x0"], case["x1"]
        n = min(x1 - x0, 48 if name == "map_big10k" else x1 - x0)
        xs = np.arange(x0, x0 + n, dtype=np.uint32)
        res, lens = mapper.map_batch(ruleno, xs, numrep, weight)
        res = np.asarray(res)
        lens = np.asarray(lens)
        for i in range(n):
            want = case["results"][i]
            got = list(res[i, :lens[i]])
            assert got == want, (name, ruleno, numrep, int(xs[i]),
                                 got, want)
        covered += 1
    if any(nm == name for nm, _ in ELIGIBLE):
        assert covered > 0, f"{name}: expected at least one eligible case"


def test_eligibility_judgments():
    for name, ruleno in ELIGIBLE:
        cmap, d = load(name)
        numrep = next(c["numrep"] for c in d["cases"]
                      if c["ruleno"] == ruleno)
        analyze(cmap, ruleno, numrep)  # must not raise
    for (name, ruleno), _why in INELIGIBLE.items():
        cmap, d = load(name)
        numrep = next((c["numrep"] for c in d["cases"]
                       if c["ruleno"] == ruleno), 3)
        with pytest.raises(Ineligible):
            analyze(cmap, ruleno, numrep)


def test_compute_mode_matches_table_mode(monkeypatch):
    """Both straw2 lowerings agree with the golden vectors (the table
    mode is exercised by the parametrized test above; this pins the
    computed-ln mode)."""
    import importlib

    import ceph_tpu.crush.mapper_spec as MS
    monkeypatch.setenv("CEPH_TPU_STRAW2", "compute")
    importlib.reload(MS)
    try:
        cmap, d = load("map_tree3")
        case = d["cases"][0]
        m = MS.SpeculativeMapper(cmap)
        weight = np.asarray(case["weight"], np.uint32)
        xs = np.arange(case["x0"], case["x1"], dtype=np.uint32)
        res, lens = m.map_batch(case["ruleno"], xs, case["numrep"], weight)
        res, lens = np.asarray(res), np.asarray(lens)
        for i, want in enumerate(case["results"]):
            assert list(res[i, :lens[i]]) == want
    finally:
        monkeypatch.delenv("CEPH_TPU_STRAW2")
        importlib.reload(MS)


def test_indep_cases_covered_and_leaf_type0_rejected():
    """The indep lowering: eligible golden indep cases are bit-exact
    (covered by the parametrized sweep), chooseleaf-indep-of-type-0 is
    REFUSED (the reference leaks the last is_out-rejected device
    through out2 there — a quirk the spec path does not reproduce),
    and a randomized zero-weight differential pins the accepted shapes
    against the scalar spec."""
    import random

    cmap, d = load("map_big10k")
    # the golden corpus includes at least one eligible indep case
    indep_cases = [c for c in d["cases"] if c["ruleno"] == 1]
    assert indep_cases, "corpus lost its indep case"
    analyze(cmap, 1, indep_cases[0]["numrep"])  # eligible

    # randomized differential with rejections in play (zeroed weights)
    from ceph_tpu.crush.mapper_ref import crush_do_rule

    case = indep_cases[0]
    rng = random.Random(99)
    weights = list(case["weight"])
    for _ in range(40):
        weights[rng.randrange(len(weights))] = 0
    m = SpeculativeMapper(cmap, k_tries=1)
    import numpy as np

    xs = np.arange(500, 564, dtype=np.uint32)
    res, lens = m.map_batch(1, xs, case["numrep"],
                            np.asarray(weights, np.uint32))
    res, lens = np.asarray(res), np.asarray(lens)
    for i, x in enumerate(xs):
        want = crush_do_rule(cmap, 1, int(x), case["numrep"],
                             list(weights))
        assert list(res[i, :lens[i]]) == want, int(x)

    # chooseleaf indep of type 0: must fall back to the general VM
    from ceph_tpu.crush.map import Rule, RuleStep
    from ceph_tpu.crush import constants as CC

    cmap2, _ = load("map_flat12")
    root_id = next(b.id for b in cmap2.buckets.values()
                   if all(i >= 0 for i in b.items))
    cmap2.rules[9] = Rule(steps=[
        RuleStep(CC.CRUSH_RULE_TAKE, root_id, 0),
        RuleStep(CC.CRUSH_RULE_CHOOSELEAF_INDEP, 4, 0),
        RuleStep(CC.CRUSH_RULE_EMIT, 0, 0)])
    # match on the ValueError base: the reload test earlier in this
    # module swaps the Ineligible class identity in analyze's globals
    with pytest.raises(ValueError, match="type 0"):
        analyze(cmap2, 9, 4)
