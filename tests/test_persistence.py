"""OSD restart persistence + CrushLocation.

Restart-replay (the superblock flow): an OSD with a data_dir remounts
its checkpoint on revive — data survives without backfill.  Plus the
CrushLocation string parsing and create-or-move placement.
"""

import os

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.crush.location import (create_or_move_item,
                                     default_location, format_loc,
                                     parse_loc)
from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.services.cluster import MiniCluster


def test_parse_and_format_loc():
    loc = parse_loc("root=default rack=r1 host=node3")
    assert loc == {"root": "default", "rack": "r1", "host": "node3"}
    assert parse_loc("host=a,rack=b") == {"host": "a", "rack": "b"}
    assert format_loc(loc) == "host=node3 rack=r1 root=default"
    assert default_location("n1") == {"host": "n1", "root": "default"}
    with pytest.raises(ValueError):
        parse_loc("hostnoequals")


def test_create_or_move_item():
    w = CrushWrapper()
    changed = create_or_move_item(w, 0, 0x20000, "osd.0",
                                  parse_loc("root=default host=h1"))
    assert changed
    assert w.get_item_weight(0) == 0x20000
    # same location: no-op
    assert not create_or_move_item(w, 0, 0x20000, "osd.0",
                                   parse_loc("root=default host=h1"))
    # moved host: relocates, keeps the EXISTING weight
    changed = create_or_move_item(w, 0, 0x99999, "osd.0",
                                  parse_loc("root=default host=h2"))
    assert changed
    assert w.get_item_weight(0) == 0x20000
    h2 = w.get_item_id("h2")
    assert 0 in w.get_bucket(h2).items
    assert w.get_bucket(w.get_item_id("h1")).items == []


def test_create_or_move_keeps_class_and_is_pure_on_noop():
    w = CrushWrapper()
    create_or_move_item(w, 0, 0x10000, "osd.0",
                        parse_loc("root=default host=h1"))
    w.set_item_class(0, "ssd")
    buckets_before = len(w.crush.buckets)
    # no-op with an EXTRA (nonexistent) level must not create buckets
    assert not create_or_move_item(
        w, 0, 0x10000, "osd.0",
        parse_loc("root=default rack=rX host=h1"))
    assert len(w.crush.buckets) == buckets_before
    assert not w.name_exists("rX")
    # a real move keeps the device class
    create_or_move_item(w, 0, 0x10000, "osd.0",
                        parse_loc("root=default host=h2"))
    assert w.get_item_class(0) == "ssd"


def test_osd_restart_remounts_data(tmp_path):
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.5)
    cl = MiniCluster(n_osds=3, config=conf,
                     data_dir=str(tmp_path)).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=2)
        c = cl.client("persist")
        data = {f"po{i}": (f"payload-{i}" * 40).encode()
                for i in range(5)}
        for oid, d in data.items():
            c.put(1, oid, d)
        cl.wait_for_recovery(1, data, timeout=20)

        victim = 1
        before = set()
        for cid in cl.osds[victim].store.list_collections():
            for name in cl.osds[victim].store.list_objects(cid):
                before.add((cid, name))
        cl.kill_osd(victim)
        assert os.path.exists(
            str(tmp_path / f"osd{victim}" /
                f"osd.{victim}.wal" / "checkpoint"))

        svc = cl.revive_osd(victim)
        after = set()
        for cid in svc.store.list_collections():
            for name in svc.store.list_objects(cid):
                after.add((cid, name))
        # everything remounted from the checkpoint, not re-backfilled
        assert before <= after
        assert svc.pc.dump()["recovered_objects"] == 0
        for oid, d in data.items():
            assert c.get(1, oid) == d
    finally:
        cl.shutdown()


def test_single_mon_restart_resumes_epochs(tmp_path):
    """A restarted solo monitor resumes from its persisted epoch store
    instead of resetting to genesis (which would freeze daemons that
    already hold newer epochs)."""
    import time

    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.3)
    conf.set("osd_heartbeat_grace", 3.0)
    c = MiniCluster(n_osds=3, config=conf,
                    data_dir=str(tmp_path)).start()
    try:
        c.create_replicated_pool(1, pg_num=8, size=2)
        cli = c.client()
        cli.put(1, "survivor", b"pre-restart")
        epoch_before = c.mon.last_committed()
        assert epoch_before > 1

        c.kill_mon(0)
        c.revive_mon(0)
        assert c.mon.last_committed() >= epoch_before

        # the control plane still works after restart: new commands
        # commit NEWER epochs, daemons keep following
        c.create_replicated_pool(3, pg_num=4, size=2)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                cli.refresh_map()
                if 3 in cli.map.pools:
                    break
            except Exception:
                pass
            time.sleep(0.3)
        cli.put(3, "post-restart", b"new-pool-write")
        assert cli.get(3, "post-restart") == b"new-pool-write"
        assert cli.get(1, "survivor") == b"pre-restart"
    finally:
        c.shutdown()


def test_pool_delete_and_reweight(tmp_path):
    """pool_delete rides the old_pools incremental and OSDs drop the
    pool's PGs; reweight overrides an osd's in/out weight."""
    import time

    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.3)
    conf.set("osd_heartbeat_grace", 3.0)
    c = MiniCluster(n_osds=3, config=conf).start()
    try:
        c.create_replicated_pool(1, pg_num=8, size=2)
        c.create_replicated_pool(2, pg_num=4, size=2)
        cli = c.client()
        cli.put(2, "doomed", b"x" * 100)
        assert c.status()["num_pools"] == 2

        c.delete_pool(2)
        assert c.status()["num_pools"] == 1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if not any(cid.startswith("2.")
                       for svc in c.osds.values()
                       for cid in svc.store.list_collections()):
                break
            time.sleep(0.5)
        assert not any(cid.startswith("2.")
                       for svc in c.osds.values()
                       for cid in svc.store.list_collections()), \
            "deleted pool's PG collections not removed"

        c.reweight_osd(1, 0.5)
        payload = c.mon_command({"type": "get_map"})
        from ceph_tpu.osdmap.bincode_maps import payload_map
        assert payload_map(payload).osd_weight[1] == 0x8000
    finally:
        c.shutdown()
