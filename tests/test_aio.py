"""Pipelined data-path smoke: aio window → group commit → batched EC.

The CI canary for the whole batching stack (the satellite contract):
64 ``aio_put``s at window 16 through a WALStore-backed MiniCluster
must light up BOTH coalescing layers — non-zero multi-txn
``wal_group_size`` buckets (shared fsyncs) and multi-object
``ec_batch_size`` buckets (shared encode dispatches) — so neither
path can silently regress to depth 1.
"""

import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.services.cluster import MiniCluster


def _hist(pc_dump, key):
    return list(pc_dump[key]["buckets"])


def _wal_hist():
    from ceph_tpu.os.wal_store import _pc

    return _hist(_pc.dump(), "wal_group_size")


def _ec_hist():
    from ceph_tpu.ec.engine import _pc

    return _hist(_pc.dump(), "ec_batch_size")


def _multi(cur, base):
    """Samples that landed in buckets past index 0 (= depth > 1)."""
    return sum(c - b for c, b in zip(cur[1:], base[1:]))


@pytest.fixture
def cluster(tmp_path):
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.5)
    conf.set("osd_heartbeat_grace", 5.0)
    conf.set("client_aio_window", 16)
    # widen both coalescing windows so the batching is deterministic
    # under test load (the knobs exist for exactly this)
    conf.set("wal_group_commit_max_delay_us", 3000)
    conf.set("ec_encode_batch_max_delay_us", 3000)
    cl = MiniCluster(n_osds=4, config=conf,
                     data_dir=str(tmp_path / "data")).start()
    try:
        yield cl
    finally:
        cl.shutdown()


def test_aio_window_drives_group_commit_and_batched_encode(cluster):
    cluster.create_ec_pool(
        1, "aio21", {"plugin": "jerasure",
                     "technique": "reed_sol_van",
                     "k": "2", "m": "1", "w": "8"}, pg_num=16)
    cli = cluster.client("aio")
    blob = bytes((i * 7 + 3) & 0xFF for i in range(8192))

    wal_base, ec_base = _wal_hist(), _ec_hist()
    n = 0
    deadline = time.monotonic() + 60
    # drive rounds of 64 aio_puts until BOTH coalescing layers show a
    # multi-entry group (normally the first round; bounded retries
    # absorb scheduler timing on a loaded host) — a regression to
    # depth-1 batching never shows one and fails at the deadline
    while time.monotonic() < deadline:
        comps = [cli.aio_put(1, f"obj-{n}-{i}", blob)
                 for i in range(64)]
        n += 1
        cli.flush(timeout=60)
        assert all(c.done() for c in comps)
        errs = [c.error for c in comps if c.error is not None]
        assert not errs, f"aio_put failed: {errs[:3]}"
        if _multi(_wal_hist(), wal_base) > 0 and \
                _multi(_ec_hist(), ec_base) > 0:
            break
    assert _multi(_wal_hist(), wal_base) > 0, \
        "no multi-txn WAL group formed — group commit regressed " \
        "to one fsync per txn"
    assert _multi(_ec_hist(), ec_base) > 0, \
        "no multi-object encode batch formed — EC coalescing " \
        "regressed to one dispatch per stripe"

    # the window actually pipelined (depth histogram saw > 1)...
    depth = cli.pc.dump()["aio_depth"]["buckets"]
    assert sum(depth[1:]) > 0, "aio window never held 2+ ops"
    # ...and the data is real: read a sample back
    for i in (0, 31, 63):
        assert cli.get(1, f"obj-0-{i}") == blob


def test_aio_flush_propagates_op_error(cluster):
    cluster.create_replicated_pool(2, pg_num=8, size=3)
    cli = cluster.client("aioerr")
    comp = cli.aio_put(2, "ok", b"x" * 128)
    comp.wait(timeout=30)
    # an op against a nonexistent pool fails ITS completion (wait()
    # re-raises on the caller's thread) without poisoning later ops
    bad = cli.aio_put(99, "nope", b"y", retries=1)
    with pytest.raises(Exception):
        bad.wait(timeout=30)
    assert bad.error is not None
    ok2 = cli.aio_put(2, "ok2", b"z" * 128)
    cli.flush(timeout=30)  # the failed op settled; flush is clean
    assert ok2.done() and ok2.error is None
    assert cli.get(2, "ok2") == b"z" * 128
