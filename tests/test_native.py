"""Native C++ host mapper tests — bit-exact equivalence against the
scalar executable spec and the reference C golden vectors, across
bucket algorithms, tunables, choose_args, and rule shapes."""

import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.crush import constants as C
from ceph_tpu.crush.builder import (add_simple_rule, build_hierarchy,
                                    make_list_bucket,
                                    make_straw2_bucket,
                                    make_tree_bucket,
                                    make_uniform_bucket,
                                    sample_cluster_map, calc_straw)
from ceph_tpu.crush.map import (Bucket, ChooseArg, ChooseArgMap,
                                CrushMap, Rule, RuleStep, Tunables)
from ceph_tpu.crush.mapper_ref import crush_do_rule
from ceph_tpu.crush.native import NativeMapper, available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable")

GOLDEN = pathlib.Path(__file__).parent / "golden"


def assert_equivalent(cmap, ruleno, numrep, weight, xs,
                      choose_args=None):
    nm = NativeMapper(cmap, choose_args)
    res, lens = nm.map_batch(ruleno, np.asarray(xs, np.uint32),
                             numrep, np.asarray(weight, np.uint32))
    for i, x in enumerate(xs):
        want = crush_do_rule(cmap, ruleno, int(x), numrep,
                             list(weight), choose_args=choose_args)
        got = list(res[i, :lens[i]])
        assert got == want, f"x={x}: native {got} != spec {want}"


def test_sample_map_both_rules():
    cmap = sample_cluster_map()
    w = [0x10000] * cmap.max_devices
    assert_equivalent(cmap, 0, 3, w, range(256))
    assert_equivalent(cmap, 1, 6, w, range(256))


def test_weight_rejection_and_zero_weights():
    cmap = sample_cluster_map()
    w = [0x10000] * cmap.max_devices
    w[0] = 0
    w[5] = 0x4000  # 25% acceptance
    assert_equivalent(cmap, 0, 3, w, range(512))


def test_golden_10k_map():
    d = json.load(open(GOLDEN / "map_big10k.json"))
    cmap = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    nm = NativeMapper(cmap)
    res, lens = nm.map_batch(
        case["ruleno"],
        np.arange(case["x0"], case["x1"], dtype=np.uint32),
        case["numrep"], np.asarray(case["weight"], np.uint32))
    for i in range(case["x1"] - case["x0"]):
        assert list(res[i, :lens[i]]) == case["results"][i], f"i={i}"


def test_all_bucket_algorithms():
    """uniform/list/tree/straw/straw2 buckets each as the leaf layer."""
    for maker in ("uniform", "list", "tree", "straw", "straw2"):
        cmap = CrushMap()
        items = list(range(8))
        weights = [0x10000 * (1 + i % 3) for i in items]
        if maker == "uniform":
            b = make_uniform_bucket(items, 0x10000, 1)
        elif maker == "list":
            b = make_list_bucket(items, weights, 1)
        elif maker == "tree":
            b = make_tree_bucket(items, weights, 1)
        elif maker == "straw":
            b = Bucket(id=0, alg=C.CRUSH_BUCKET_STRAW, type=1,
                       items=items, item_weights=weights,
                       straws=calc_straw(weights),
                       weight=sum(weights))
        else:
            b = make_straw2_bucket(items, weights, 1)
        root = cmap.add_bucket(b)
        cmap.max_devices = 8
        add_simple_rule(cmap, root, leaf_type=0, firstn=True, ruleno=0)
        w = [0x10000] * 8
        assert_equivalent(cmap, 0, 3, w, range(200))


def test_legacy_tunables():
    cmap = sample_cluster_map()
    cmap.tunables = Tunables.legacy()
    w = [0x10000] * cmap.max_devices
    assert_equivalent(cmap, 0, 3, w, range(256))


def test_choose_args_weight_sets():
    cmap = sample_cluster_map()
    cargs = ChooseArgMap()
    for idx, b in cmap.buckets.items():
        ws = [[max(0, int(wt) - (i * 0x1000) % 0x8000)
               for i, wt in enumerate(b.item_weights)],
              list(b.item_weights)]
        cargs[idx] = ChooseArg(ids=None, weight_set=ws)
    w = [0x10000] * cmap.max_devices
    assert_equivalent(cmap, 0, 3, w, range(200), choose_args=cargs)


def test_multi_step_rule_with_set_ops():
    """The LRC-style rule shape: set_* steps + choose + chooseleaf."""
    cmap = CrushMap()
    root = build_hierarchy(cmap, [(1, 2), (2, 2), (3, 4)])
    steps = [
        RuleStep(C.CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5, 0),
        RuleStep(C.CRUSH_RULE_SET_CHOOSE_TRIES, 100, 0),
        RuleStep(C.CRUSH_RULE_TAKE, root, 0),
        RuleStep(C.CRUSH_RULE_CHOOSE_INDEP, 2, 2),
        RuleStep(C.CRUSH_RULE_CHOOSELEAF_INDEP, 2, 1),
        RuleStep(C.CRUSH_RULE_EMIT, 0, 0),
    ]
    cmap.add_rule(Rule(steps=steps, type=3), 0)
    w = [0x10000] * cmap.max_devices
    assert_equivalent(cmap, 0, 4, w, range(200))


def test_u32_x_wraparound():
    cmap = sample_cluster_map()
    w = [0x10000] * cmap.max_devices
    assert_equivalent(cmap, 0, 3, w,
                      [0xFFFFFFFF, 0x7FFFFFFF, 0x80000000, 12345])


def test_tester_native_path_matches_scalar():
    from ceph_tpu.crush.wrapper import CrushWrapper
    from ceph_tpu.tools.tester import CrushTester

    w = CrushWrapper(sample_cluster_map())
    t = CrushTester(w)
    a = t.test_rule(0, 3, 0, 127, scalar=True, collect_mappings=True)
    b = t.test_rule(0, 3, 0, 127, native=True, collect_mappings=True)
    assert a.mappings == b.mappings
    assert np.array_equal(a.device_stored, b.device_stored)
