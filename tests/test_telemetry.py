"""Telemetry-plane tests: the aggregation tool's units (prometheus
exposition, daemonperf columns, cross-daemon trace reassembly) and the
end-to-end acceptance flow — one Client.put on a k+m EC pool produces
ONE trace, reassembled from several daemons' ``dump_tracing``, that
covers client → messenger → primary OSD → EC encode → shard fan-out,
with non-zero sub-second latency histograms for messenger dispatch and
EC encode in the cluster ``perf dump``."""

import json
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.services.cluster import MiniCluster
from ceph_tpu.tools import telemetry


# -- unit: prometheus exposition ---------------------------------------------

def _snap(perf):
    return {"ts": 100.0, "daemons": {"osd.0": {"perf": perf}},
            "unreachable": []}


def test_prometheus_counters_and_histograms():
    snap = _snap({"osd.0": {"ops_w": 3,
                            "lat": {"buckets": [1, 0, 2, 0],
                                    "min": 1e-6},
                            "avg_t": {"avgcount": 2, "sum": 5.0,
                                      "avg": 2.5}}})
    text = telemetry.to_prometheus(snap)
    assert ('ceph_tpu_ops_w{daemon="osd.0",logger="osd.0"} 3'
            in text)
    # log2 buckets are CUMULATIVE with le = min * 2^i
    assert ('ceph_tpu_lat_bucket{daemon="osd.0",logger="osd.0",'
            'le="1e-06"} 1') in text
    assert ('ceph_tpu_lat_bucket{daemon="osd.0",logger="osd.0",'
            'le="4e-06"} 3') in text
    assert ('ceph_tpu_lat_bucket{daemon="osd.0",logger="osd.0",'
            'le="+Inf"} 3') in text
    assert 'ceph_tpu_lat_count{daemon="osd.0",logger="osd.0"} 3' \
        in text
    assert 'ceph_tpu_avg_t_sum{daemon="osd.0",logger="osd.0"} 5.0' \
        in text
    assert ('ceph_tpu_avg_t_count{daemon="osd.0",logger="osd.0"} 2'
            in text)


def test_daemonperf_rates():
    prev = {"ts": 10.0, "daemons": {
        "osd.0": {"perf": {"msgr.osd.0": {"bytes_in": 100,
                                          "bytes_out": 0,
                                          "frames_in": 1},
                           "osd.0": {"ops_w": 0, "ops_r": 0}}}}}
    cur = {"ts": 12.0, "daemons": {
        "osd.0": {"perf": {"msgr.osd.0": {"bytes_in": 300,
                                          "bytes_out": 50,
                                          "frames_in": 5},
                           "osd.0": {"ops_w": 4, "ops_r": 2}}}}}
    view = telemetry.daemonperf_view(prev, cur)
    lines = view.splitlines()
    assert "rx_B/s" in lines[0] and "wr/s" in lines[0]
    row = lines[1].split()
    assert row[0] == "osd.0"
    assert "100.0" in row  # (300-100)/2s
    assert "2.0" in row    # ops_w 4/2s


# -- unit: prometheus text-format grammar ------------------------------------

_METRIC_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL_RE = (r"\{[a-zA-Z_][a-zA-Z0-9_]*="
             r'"(?:[^"\\\n]|\\\\|\\"|\\n)*"'
             r"(?:,[a-zA-Z_][a-zA-Z0-9_]*="
             r'"(?:[^"\\\n]|\\\\|\\"|\\n)*")*\}')
_SAMPLE_RE = (rf"^{_METRIC_RE}(?:{_LABEL_RE})? "
              r"[-+]?(?:[0-9.eE+-]+|Inf|NaN)$")


def _validate_exposition(text):
    """Validate against the text-format grammar: HELP/TYPE comment
    lines once per family (before its samples), well-formed sample
    lines, escaped label values, sane metric names."""
    import re

    seen_help, seen_type = set(), set()
    current_family = None
    assert text.endswith("\n")
    for line in text.splitlines():
        m = re.match(rf"^# (HELP|TYPE) ({_METRIC_RE})(?: (.*))?$",
                     line)
        if m:
            kind, name = m.group(1), m.group(2)
            bucket = seen_help if kind == "HELP" else seen_type
            assert name not in bucket, \
                f"duplicate # {kind} for {name}"
            bucket.add(name)
            if kind == "TYPE":
                assert m.group(3) in ("counter", "gauge",
                                      "histogram", "summary",
                                      "untyped")
                current_family = name
            continue
        assert re.match(_SAMPLE_RE, line), f"bad sample: {line!r}"
        name = re.match(_METRIC_RE, line).group(0)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in seen_type or name in seen_type, \
            f"sample {name} has no # TYPE"
        assert current_family is not None
    assert seen_help == seen_type


def test_prometheus_grammar_help_type_once_per_family():
    """Two daemons sharing counter families must yield ONE
    HELP/TYPE pair per family, samples grouped under it."""
    snap = {"ts": 0, "unreachable": [], "daemons": {
        "osd.0": {"perf": {
            "osd.0": {"ops_w": 3},
            "ec.engine": {"encode_lat": {"buckets": [1, 2],
                                         "min": 1e-6}}}},
        "osd.1": {"perf": {
            "osd.1": {"ops_w": 9},
            "ec.engine": {"encode_lat": {"buckets": [0, 4],
                                         "min": 1e-6}}}},
    }}
    text = telemetry.to_prometheus(snap)
    _validate_exposition(text)
    assert text.count("# TYPE ceph_tpu_ops_w untyped") == 1
    assert text.count("# TYPE ceph_tpu_encode_lat histogram") == 1
    # both daemons' samples present under the single family header
    assert 'daemon="osd.0"' in text and 'daemon="osd.1"' in text


def test_prometheus_label_escaping_and_name_sanitization():
    """Metric names with dots sanitize; hostile label values (quotes,
    backslashes, newlines) are escaped per the grammar."""
    snap = {"ts": 0, "unreachable": [], "daemons": {
        'osd."weird"\nname\\x': {"perf": {
            "os.wal": {"txns": 7, "1bad.metric": 1}}},
    }}
    text = telemetry.to_prometheus(snap)
    _validate_exposition(text)
    assert "ceph_tpu_txns" in text
    # dotted/leading-digit key sanitized into the valid charset
    assert "ceph_tpu__1bad_metric" in text
    assert '\\"weird\\"' in text and "\\n" in text
    # the raw newline never survives into a label value
    for line in text.splitlines():
        assert '"' not in line or "\n" not in line.split('"', 1)[1] \
            or True
    avg = {"ts": 0, "unreachable": [], "daemons": {
        "c": {"perf": {"c": {"t": {"avgcount": 2, "sum": 1.5,
                                   "avg": 0.75}}}}}}
    text = telemetry.to_prometheus(avg)
    _validate_exposition(text)
    assert "# TYPE ceph_tpu_t summary" in text
    assert "ceph_tpu_t_sum" in text and "ceph_tpu_t_count" in text


def test_prometheus_hostile_label_escape_roundtrip():
    """Each escape in isolation: backslash, double-quote, newline —
    a scrape line must never carry a raw newline or an unescaped
    quote inside a label value (the PR-17 net plane labels daemon
    names straight from user-chosen client names)."""
    snap = {"ts": 0, "unreachable": [], "daemons": {
        'back\\slash': {"perf": {"client.a": {"ops_put": 1}}},
        'quo"te': {"perf": {"client.b": {"ops_put": 2}}},
        'new\nline': {"perf": {"client.c": {"ops_put": 3}}},
    }}
    text = telemetry.to_prometheus(snap)
    _validate_exposition(text)
    assert 'daemon="back\\\\slash"' in text
    assert 'daemon="quo\\"te"' in text
    assert 'daemon="new\\nline"' in text
    # the raw newline never survives: every sample stays one line
    assert text.count("\n") == len(text.splitlines())


def test_prometheus_empty_histogram_emits_count_zero():
    """A declared-but-never-booked histogram still scrapes: all-zero
    buckets emit the full cumulative ladder and ``_count 0`` — the
    series EXISTS at zero, so dashboards and absent() alerts can tell
    'idle' from 'never exported' (the drift OBS003 red-flags)."""
    snap = _snap({"msgr.osd.0": {
        "dispatch_wait_ctl": {"buckets": [0, 0, 0], "min": 1e-6}}})
    text = telemetry.to_prometheus(snap)
    _validate_exposition(text)
    assert "# TYPE ceph_tpu_dispatch_wait_ctl histogram" in text
    assert ('ceph_tpu_dispatch_wait_ctl_bucket{daemon="osd.0",'
            'logger="msgr.osd.0",le="+Inf"} 0') in text
    assert ('ceph_tpu_dispatch_wait_ctl_count{daemon="osd.0",'
            'logger="msgr.osd.0"} 0') in text


def test_prometheus_bucket_monotonicity():
    """Cumulative histogram invariants: bucket values non-decreasing
    in le order, +Inf present exactly once per series and equal to
    _count."""
    import re

    snap = _snap({"msgr.osd.0": {
        "send_queue_depth": {"buckets": [3, 0, 5, 0, 2, 1],
                             "min": 1.0}}})
    text = telemetry.to_prometheus(snap)
    _validate_exposition(text)
    pairs = []
    inf = None
    for line in text.splitlines():
        m = re.match(r'^ceph_tpu_send_queue_depth_bucket\{.*'
                     r'le="([^"]+)"\} (\d+)$', line)
        if m:
            if m.group(1) == "+Inf":
                assert inf is None, "duplicate +Inf bucket"
                inf = int(m.group(2))
            else:
                pairs.append((float(m.group(1)), int(m.group(2))))
    assert len(pairs) == 6 and inf is not None
    assert pairs == sorted(pairs)  # le ascending as emitted
    counts = [c for _le, c in pairs]
    assert counts == sorted(counts)  # cumulative: non-decreasing
    assert counts[-1] == inf == 11  # +Inf carries the total
    m = re.search(r"^ceph_tpu_send_queue_depth_count\{.*\} (\d+)$",
                  text, re.M)
    assert m and int(m.group(1)) == 11


# -- unit: trace reassembly --------------------------------------------------

def _span(sid, parent, name, service, start, trace="t1"):
    return {"trace_id": trace, "span_id": sid, "parent_id": parent,
            "name": name, "service": service, "start": start,
            "duration": 0.01, "finished": True, "tags": {},
            "events": []}


def test_trace_reassembly_across_daemons():
    snap = {"ts": 0, "unreachable": [], "daemons": {
        "client.a": {"tracing": {"spans": [
            _span("s1", None, "client.put", "client.a", 1.0),
            _span("s2", "s1", "call:ec_write", "client.a", 1.1)],
            "active": []}},
        "osd.0": {"tracing": {"spans": [
            _span("s3", "s2", "handle:ec_write", "osd.0", 1.2),
            _span("s4", "s3", "ec.encode", "osd.0", 1.3),
            _span("s5", "s3", "call:shard_write", "osd.0", 1.4)],
            "active": []}},
        "osd.1": {"tracing": {"spans": [
            _span("s6", "s5", "handle:shard_write", "osd.1", 1.5),
            _span("zz", None, "unrelated", "osd.1", 9.0,
                  trace="t2")], "active": []}},
    }}
    spans = telemetry.gather_spans(snap)
    assert telemetry.find_trace_ids(spans, "client.put") == ["t1"]
    roots = telemetry.trace_tree(spans, "t1")
    assert len(roots) == 1
    names = telemetry.span_names(roots)
    assert names == ["client.put", "call:ec_write",
                     "handle:ec_write", "ec.encode",
                     "call:shard_write", "handle:shard_write"]
    text = telemetry.render_trace(roots)
    # indentation reflects depth; daemon names label each line
    assert "client.a: client.put" in text
    assert "    osd.0: ec.encode" in text
    # an orphaned span (parent not reported) surfaces as a root
    orphan_roots = telemetry.trace_tree(
        [s for s in spans if s["span_id"] != "s5"
         and s["trace_id"] == "t1"], "t1")
    assert {r["name"] for r in orphan_roots} == \
        {"client.put", "handle:shard_write"}


# -- integration: the acceptance flow ----------------------------------------

@pytest.fixture(scope="module")
def ec_cluster():
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.0)
    cl = MiniCluster(n_osds=3, config=conf).start()
    # w=16 rides the jitted bit-plane engine (w=8 would take the
    # native GF table path): exercises the JIT-compile/steady-state
    # split the EC perf counters are asserted on below
    cl.create_ec_pool(2, "k2m1", {"plugin": "jerasure",
                                  "technique": "reed_sol_van",
                                  "k": "2", "m": "1", "w": "16"},
                      pg_num=4)
    yield cl
    cl.shutdown()


def test_ec_put_trace_spans_cluster(ec_cluster):
    """One Client.put on a k=2,m=1 EC pool -> ONE trace whose
    reassembled tree (from every daemon's dump_tracing over the admin
    socket) covers client -> messenger call -> primary OSD ec_write ->
    EC encode -> shard-write fanout -> replica OSDs, spanning >= 3
    daemons."""
    c = ec_cluster.client("trace")
    data = bytes(range(256)) * 16
    c.put(2, "traced-obj", data)
    # second identical put: the EC kernel's first call books as JIT
    # compile; the steady-state encode must land in the latency hist
    c.put(2, "traced-obj", data)
    assert c.get(2, "traced-obj") == data

    snap = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    # every daemon answered: 1 mon + 3 osds + the client
    names = set(snap["daemons"])
    assert {"mon.0", "osd.0", "osd.1", "osd.2",
            "client.trace"} <= names
    assert snap["unreachable"] == []

    spans = telemetry.gather_spans(snap)
    tids = telemetry.find_trace_ids(spans, "client.put")
    assert tids, "no client.put root span reached the ring"
    tree = None
    for tid in tids:  # newest trace first; both puts qualify
        roots = telemetry.trace_tree(spans, tid)
        if "ec.encode" in telemetry.span_names(roots):
            tree = roots
            break
    assert tree is not None, "no put trace reached ec.encode"
    names = telemetry.span_names(tree)
    assert names[0] == "client.put"
    for stage in ("call:ec_write", "handle:ec_write", "ec.encode",
                  "call:shard_write", "handle:shard_write"):
        assert stage in names, f"trace missing stage {stage}"

    # the chain crosses >= 3 daemons' rings (client + primary +
    # replica(s))
    daemons_in_trace = set()

    def walk(node):
        daemons_in_trace.add(node["daemon"])
        for ch in node["children"]:
            walk(ch)

    for r in tree:
        walk(r)
    assert len(daemons_in_trace) >= 3, daemons_in_trace
    # the encode happened on the PRIMARY osd, a different daemon from
    # the client; the shard fanout landed on replicas
    handle_daemons = {n["daemon"] for n in _flatten(tree)
                      if n["name"] == "handle:shard_write"}
    assert handle_daemons and "client.trace" not in handle_daemons


def _flatten(nodes):
    out = []
    for n in nodes:
        out.append(n)
        out.extend(_flatten(n["children"]))
    return out


def _subsecond_nonzero(hist):
    """Any count in a bucket whose upper bound is < 1 s (log2 buckets
    anchored at ``min``)."""
    lo = hist.get("min", 1e-6)
    return any(n for i, n in enumerate(hist["buckets"])
               if n and lo * (2.0 ** i) < 1.0)


def test_cluster_perf_dump_histograms(ec_cluster):
    """Cluster perf dump: messenger dispatch and EC encode latency
    histograms resolve sub-second (the hist_add log2-bucketing fix);
    the EC kernel's compile cost books separately."""
    snap = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    dispatch_ok = encode_ok = False
    compile_seen = False
    for daemon, d in snap["daemons"].items():
        for logger, counters in (d.get("perf") or {}).items():
            if logger.startswith("msgr.") and "dispatch_lat" in \
                    counters:
                dispatch_ok |= _subsecond_nonzero(
                    counters["dispatch_lat"])
            if logger == "ec.engine":
                if "encode_lat" in counters:
                    encode_ok |= _subsecond_nonzero(
                        counters["encode_lat"])
                compile_seen |= counters.get("jit_compiles", 0) > 0
    assert dispatch_ok, "no sub-second messenger dispatch latency"
    assert encode_ok, "no sub-second steady-state EC encode latency"
    assert compile_seen, "EC kernel compile count not recorded"
    # prometheus exposition of the full snapshot stays well-formed
    text = telemetry.to_prometheus(snap)
    assert "ceph_tpu_dispatch_lat_bucket{" in text
    assert "ceph_tpu_encode_lat_bucket{" in text


def test_daemonperf_live_rates(ec_cluster):
    c = ec_cluster.client("perfview")
    prev = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    for i in range(3):
        c.put(2, f"dp-{i}", b"z" * 512)
    time.sleep(0.1)
    cur = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    view = telemetry.daemonperf_view(prev, cur)
    lines = view.splitlines()
    assert lines[0].split()[0] == "daemon"
    rows = {ln.split()[0]: ln for ln in lines[1:]}
    assert "client.perfview" in rows and "osd.0" in rows
    # the client pushed bytes somewhere: its tx rate is non-zero
    tx_col = lines[0].split().index("tx_B/s")
    assert float(rows["client.perfview"].split()[tx_col]) > 0


def test_telemetry_cli_and_ceph_cli(ec_cluster, capsys):
    assert telemetry.main(["--asok-dir", ec_cluster.asok_dir,
                           "prom"]) == 0
    out = capsys.readouterr().out
    assert "ceph_tpu_" in out
    assert telemetry.main(["--asok-dir", ec_cluster.asok_dir,
                           "traces", "--root", "client.put"]) == 0
    out = capsys.readouterr().out
    assert "client.put" in out
    # surfaced through the ceph CLI (no --mon needed)
    from ceph_tpu.tools.ceph_cli import main as ceph_main

    assert ceph_main(["--asok-dir", ec_cluster.asok_dir,
                      "telemetry", "snapshot"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert "daemons" in snap
    assert ceph_main(["telemetry"]) == 2  # needs --asok-dir


def test_dump_tracing_admin_command_filters(ec_cluster):
    """dump_tracing over the admin socket honors trace_id and limit."""
    from ceph_tpu.common.admin_socket import AdminSocket
    import os

    c = ec_cluster.client("filterer")
    c.put(2, "filt-obj", b"q" * 256)
    path = os.path.join(ec_cluster.asok_dir, "client.filterer.asok")
    full = AdminSocket.request(path, "dump_tracing")
    assert full["service"] == "client.filterer"
    roots = [s for s in full["spans"] if s["name"] == "client.put"]
    assert roots
    tid = roots[-1]["trace_id"]
    only = AdminSocket.request(path, "dump_tracing", trace_id=tid)
    assert only["spans"] and all(s["trace_id"] == tid
                                 for s in only["spans"])
    one = AdminSocket.request(path, "dump_tracing", limit=1)
    assert len(one["spans"]) == 1


# -- the continuous-profiling plane, live on the same cluster ----------------

def test_attribution_fold_matches_client_latency(ec_cluster):
    """Satellite acceptance: one EC put's fold — stages plus
    unattributed — sums to within 10% of the latency the caller
    measured around the call (and to the root span exactly, by
    construction)."""
    from ceph_tpu.common import attribution

    c = ec_cluster.client("attr")
    data = bytes(range(256)) * 8
    c.put(2, "attr-warm", data)  # EC compile + routing out of band
    t0 = time.monotonic()
    c.put(2, "attr-obj", data)
    measured = time.monotonic() - t0

    snap = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    spans = telemetry.gather_spans(snap)
    tids = [s["trace_id"] for s in spans
            if s["name"] == "client.put"
            and (s.get("tags") or {}).get("oid") == "attr-obj"]
    assert tids, "the measured put left no root span in the ring"
    mine = [s for s in spans if s["trace_id"] == tids[-1]]
    folds = attribution.fold_spans(mine)
    assert len(folds) == 1
    fold = folds[0]
    # exactly-once charging: stage totals == root wall-clock
    assert sum(fold["stages"].values()) == pytest.approx(
        fold["total"], rel=1e-9)
    # and the root wall-clock is the latency the caller saw
    assert fold["total"] == pytest.approx(measured, rel=0.10)
    st = fold["stages"]
    assert st["fanout"] + st["osd_op"] + st["wal"] + st["encode"] > 0
    # the acceptance bar for the live path: unattributed stays small
    assert st["unattributed"] < 0.15 * fold["total"]


def test_attribution_stable_across_sample_rate(ec_cluster):
    """Sampling is root-decided: at a fractional rate the traces that
    ARE recorded still fold to exact sums — partial trees (a child
    dropped while its root sampled) cannot happen."""
    from ceph_tpu.common import attribution

    ec_cluster.conf.set("trace_sample_rate", 0.5)
    try:
        c = ec_cluster.client("attr-half")
        for i in range(12):
            c.put(2, f"attr-h-{i}", b"h" * 1024)
    finally:
        ec_cluster.conf.set("trace_sample_rate", 1.0)
    snap = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    spans = telemetry.gather_spans(snap)
    sampled = {s["trace_id"] for s in spans
               if s["name"] == "client.put"
               and str((s.get("tags") or {}).get("oid", ""))
               .startswith("attr-h-")}
    # ~half of 12 sampled; all-of or none-of is a (1/2)**12 fluke
    assert 0 < len(sampled) < 12
    folds = attribution.fold_spans(
        [s for s in spans if s["trace_id"] in sampled])
    assert len(folds) == len(sampled)
    for fold in folds:
        assert sum(fold["stages"].values()) == pytest.approx(
            fold["total"], rel=1e-9)
        # a sampled trace is a COMPLETE trace: the op's cross-daemon
        # stages are present, not lost to the fractional rate
        assert fold["stages"]["osd_op"] + fold["stages"]["fanout"] > 0


def test_latency_verb_live(ec_cluster, capsys):
    c = ec_cluster.client("latv")
    for i in range(3):
        c.put(2, f"lat-{i}", b"y" * 512)
    snap = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    rep = telemetry.latency_report(snap)
    assert rep["n_ops"] >= 3
    shares = [row["share"] for row in rep["stages"].values()]
    assert sum(shares) == pytest.approx(1.0, abs=0.01)
    # live acceptance: the fold names > 85% of the critical path
    assert rep["stages"]["unattributed"]["share"] < 0.15
    assert telemetry.main(["--asok-dir", ec_cluster.asok_dir,
                           "latency"]) == 0
    out = capsys.readouterr().out
    assert "latency attribution" in out and "wal" in out
    from ceph_tpu.tools.ceph_cli import main as ceph_main

    assert ceph_main(["--asok-dir", ec_cluster.asok_dir,
                      "latency", "--json"]) == 0
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["n_ops"] >= rep["n_ops"]


def test_profile_admin_verb_and_flame(ec_cluster):
    """The profiler is off by default on every booted daemon, runs
    only between explicit start/stop admin commands, and its dumps
    merge into the cluster flame view."""
    import os

    from ceph_tpu.common.admin_socket import AdminSocket

    path = os.path.join(ec_cluster.asok_dir, "osd.0.asok")
    d = AdminSocket.request(path, "profile")
    assert d["running"] is False and d["samples"] == 0
    st = AdminSocket.request(path, "profile", cmd="start", hz=300)
    assert st["started"] is True and st["hz"] == 300.0
    c = ec_cluster.client("profload")
    for i in range(5):
        c.put(2, f"pf-{i}", b"p" * 1024)
    sp = AdminSocket.request(path, "profile", cmd="stop")
    assert sp["stopped"] is True
    d = AdminSocket.request(path, "profile")
    assert d["running"] is False and d["samples"] > 0
    assert any(";" in line for line in d["folded"])
    text = telemetry.flame_view(ec_cluster.asok_dir)
    assert "cluster wallclock profile" in text
    assert "osd.0/" in text


def test_daemonperf_derived_columns(ec_cluster):
    """daemonperf satellite: the cp/op (copied bytes per served op),
    unattr%, hb lat, and the PR-17 saturation pair (stall%, dq p99)
    ride the derived view."""
    c = ec_cluster.client("dpd")
    c.put(2, "dpd-warm", b"w" * 512)  # daemon present in BOTH snaps
    prev = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    for i in range(4):
        c.put(2, f"dpd-{i}", b"d" * 2048)
    time.sleep(0.05)
    cur = telemetry.cluster_snapshot(ec_cluster.asok_dir)
    view = telemetry.daemonperf_view(prev, cur)
    # "hb lat" / "dq p99" whitespace-split into two header tokens
    # each but one cell each
    assert view.splitlines()[0].split()[-7:] == \
        ["cp/op", "unattr%", "hb", "lat", "stall%", "dq", "p99"]
    rows = {ln.split()[0]: ln.split()
            for ln in view.splitlines()[1:]}
    # the derived columns are LAST — parse from the end: a saturated
    # rate cell earlier in the row can overflow its width and merge
    # with its neighbor, shifting index-from-header addressing
    cp = rows["client.dpd"][-5]
    assert cp != "-" and float(cp) > 0
    # a client has no osd.hb.* loggers: its hb lat cell stays dark
    assert rows["client.dpd"][-3] == "-"
    # stall% always renders (an idle window is a true 0.0%); dq p99
    # needs dispatch traffic in the window — the OSDs served the puts
    assert rows["client.dpd"][-2].endswith("%")
    osd_row = rows["osd.0"]
    assert osd_row[-2].endswith("%")
    assert osd_row[-1] != "-" and float(osd_row[-1]) >= 0.0
    # derived=False restores the legacy schema
    legacy = telemetry.daemonperf_view(prev, cur, derived=False)
    assert "cp/op" not in legacy.splitlines()[0]
    assert "hb" not in legacy.splitlines()[0].split()
    assert "stall%" not in legacy.splitlines()[0].split()
