"""Mesh-sharded data plane: EC stripe-batch sharding, the plugin and
batcher mesh paths, the meshed OSDMap pipeline + CrushTester sweep,
per-device work accounting, and the bench/perf_history multichip lane.

The CRUSH half (PlacementPlane) lives in test_placement.py; this file
covers everything the data-plane mesh touches downstream of it.  All
tests run on the conftest's 8-virtual-CPU-device layout, with the
1-device degenerate cases exercised explicitly.
"""

import json
import os

import numpy as np
import pytest

import conftest  # noqa: F401

import jax

from ceph_tpu.common import device_metrics
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.rs_jax import RSCode
from ceph_tpu.parallel.placement import (data_plane, data_plane_mesh,
                                         make_mesh,
                                         set_data_plane_mesh)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devs)}")
    return make_mesh(devs[:N_DEV], axis_name="ec")


def _bitplane(profile):
    """Plugin under the bitplane engine: the sharded path needs the
    JITted BitCode (the native GF engine is host-only)."""
    old = os.environ.get("CEPH_TPU_EC_ENGINE")
    os.environ["CEPH_TPU_EC_ENGINE"] = "bitplane"
    try:
        plugin, prof = profile
        return factory(plugin, dict(prof))
    finally:
        if old is None:
            os.environ.pop("CEPH_TPU_EC_ENGINE", None)
        else:
            os.environ["CEPH_TPU_EC_ENGINE"] = old


# the EC corpus grid (mirrors tests/test_ec_batch.py PROFILES): every
# technique/w/packetsize family, plus the layered/sub-chunked plugins
# that must take the (still byte-identical) fallback path
PROFILES = [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "3", "m": "2",
                  "w": "16"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "3", "m": "2",
                  "w": "32"}),
    ("jerasure", {"technique": "cauchy_good", "k": "4", "m": "2",
                  "w": "8", "packetsize": "8"}),
    ("jerasure", {"technique": "liberation", "k": "3", "m": "2",
                  "w": "7", "packetsize": "8"}),
    ("isa", {"k": "4", "m": "2"}),
    ("lrc", {"k": "4", "m": "2", "l": "3"}),
    ("shec", {"k": "4", "m": "3", "c": "2"}),
    ("clay", {"k": "4", "m": "2"}),
]


def _objects(n, size, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size, dtype=np.uint8).tobytes()
            for _ in range(n)]


# -- engine level -----------------------------------------------------------

def test_engine_sharded_byte_identical_all_layouts(mesh):
    """encode_batched_sharded == per-stripe encode for every layout
    family (w8 bytes, w16/w32 words, packet), divisible and
    non-divisible batch sizes, 1-device and 8-device meshes."""
    mesh1 = make_mesh(jax.devices()[:1], axis_name="ec")
    rng = np.random.default_rng(11)
    cases = [
        RSCode(4, 2)._bit,                               # w8
        _bitplane(PROFILES[1])._code,                    # w16
        _bitplane(PROFILES[2])._code,                    # w32
        _bitplane(PROFILES[3])._code,                    # packet
    ]
    for bc in cases:
        blk = bc.layout.w * bc.layout.packetsize \
            if bc.layout.is_packet else max(1, bc.layout.w // 8)
        L = 64 * blk
        for B in (8, 5, 1):
            stripes = rng.integers(0, 256, (B, bc.k, L),
                                   dtype=np.uint8)
            for m in (mesh, mesh1):
                got = np.asarray(
                    bc.encode_batched_sharded(stripes, m))
                assert got.shape == (B, bc.m, L)
                for b in range(B):
                    ref = np.asarray(bc.encode(stripes[b]))
                    assert got[b].tobytes() == ref.tobytes(), \
                        (bc.layout.w, bc.layout.packetsize, B, b)


def test_engine_default_mesh_routing(mesh):
    """encode_batched with no explicit mesh takes the process-default
    data-plane mesh — and stays unsharded when none is installed or
    when the installed mesh is single-device."""
    bc = RSCode(4, 2)._bit
    rng = np.random.default_rng(12)
    stripes = rng.integers(0, 256, (8, 4, 1024), dtype=np.uint8)
    ref = np.asarray(bc.encode_batched(stripes))
    assert data_plane_mesh() is None
    with data_plane(mesh):
        assert data_plane_mesh() is mesh
        got = np.asarray(bc.encode_batched(stripes))
    assert data_plane_mesh() is None
    assert got.tobytes() == ref.tobytes()


def test_engine_sharded_recompile_budget(mesh):
    """Warmed sharded batch shapes must hit the jit cache: pad-and-
    mask batches that land on a warmed pow2 shape book zero new XLA
    compiles inside the steady-state window, on both mesh sizes."""
    from ceph_tpu.analysis import jaxcheck

    bc = RSCode(4, 2)._bit
    mesh1 = make_mesh(jax.devices()[:1], axis_name="ec")
    rng = np.random.default_rng(13)
    for m in (mesh, mesh1):       # warmup: one compile per mesh size
        s = rng.integers(0, 256, (8, 4, 1024), dtype=np.uint8)
        np.asarray(bc.encode_batched_sharded(s, m))
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("ec.encode_batched_sharded.mesh_sizes"):
        for m in (mesh, mesh1):
            for B in (8, 5, 7):   # all pad to the warmed 8
                s = rng.integers(0, 256, (B, 4, 1024), dtype=np.uint8)
                out = np.asarray(bc.encode_batched_sharded(s, m))
                assert out.shape == (B, 2, 1024)
    assert len(jaxcheck.recompile_violations()) == base


# -- plugin + batcher level -------------------------------------------------

@pytest.mark.parametrize("profile", PROFILES,
                         ids=lambda p: p[0] + "-" + "-".join(
                             f"{k}{v}" for k, v in sorted(p[1].items())))
def test_plugin_encode_batched_mesh_byte_identical(mesh, profile):
    """Plugin-level encode_batched under the mesh == per-object
    encode, over the corpus grid.  BitCode-backed plugins (jerasure,
    isa) take the sharded stripe-batch path; layered/sub-chunked ones
    (lrc, shec, clay) keep the fallback — both must stay
    byte-identical."""
    code = _bitplane(profile)
    n = code.get_chunk_count()
    want = set(range(n))
    for B, size in ((3, 4096), (5, 8192)):
        raws = _objects(B, size, seed=B)
        batched = code.encode_batched(want, raws, mesh=mesh)
        assert len(batched) == B
        for raw, got in zip(raws, batched):
            ref = code.encode(want, raw)
            assert set(got) == set(ref)
            for i in ref:
                assert np.asarray(got[i], np.uint8).tobytes() == \
                    np.asarray(ref[i], np.uint8).tobytes(), \
                    (profile[0], i)


def test_plugin_mesh_path_actually_shards(mesh):
    """The jerasure/bitplane mesh path must really run the sharded
    kernel: the per-device mesh table grows on every mesh device."""
    device_metrics.reset_for_tests()
    code = _bitplane(PROFILES[0])
    assert hasattr(code._code, "encode_batched_sharded")
    raws = _objects(4, 4096, seed=21)
    code.encode_batched(set(range(code.get_chunk_count())), raws,
                        mesh=mesh)
    table = device_metrics.mesh_device_table()
    ids = {int(d.id) for d in np.asarray(mesh.devices).ravel()}
    assert ids <= set(table), (sorted(table), sorted(ids))
    assert all(table[i]["launches"] >= 1 for i in ids)


def test_encode_batcher_mesh_coalesced_identical(mesh):
    """Concurrent encodes through an EncodeBatcher carrying the mesh:
    outputs identical to the direct path and at least one multi-object
    batch dispatched."""
    import threading

    from ceph_tpu.ec.batcher import EncodeBatcher
    from ceph_tpu.ec.engine import _pc

    code = _bitplane(PROFILES[0])
    want = set(range(code.get_chunk_count()))
    batcher = EncodeBatcher(max_delay_us=5000, mesh=mesh)
    raws = _objects(8, 4096, seed=3)
    refs = [code.encode(want, r) for r in raws]
    base = _pc.dump()["ec_batch_size"]["buckets"]
    outs = [None] * len(raws)
    errs = []

    def worker(i):
        try:
            outs[i] = batcher.encode(code, want, raws[i])
        except Exception as e:  # surfaced below
            errs.append(e)

    ths = [threading.Thread(target=worker, args=(i,))
           for i in range(len(raws))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert not errs
    for got, ref in zip(outs, refs):
        for i in ref:
            assert np.asarray(got[i]).tobytes() == \
                np.asarray(ref[i]).tobytes()
    cur = _pc.dump()["ec_batch_size"]["buckets"]
    grew = [c - b for c, b in zip(cur, base)]
    assert sum(grew[1:]) > 0, "no multi-object batch ever dispatched"


# -- osdmap + tester sweeps -------------------------------------------------

def test_pool_mapper_mesh_equals_unsharded(mesh):
    """The meshed OSDMap pipeline (ps axis + exception tables sharded,
    pow2-padded non-divisible pg_num) == the unsharded PoolMapper,
    through upmap/pg_temp edits and refresh_tables."""
    from ceph_tpu.crush.builder import sample_cluster_map
    from ceph_tpu.osdmap.osdmap import (OSDMap, PgPool,
                                        POOL_TYPE_REPLICATED)
    from ceph_tpu.osdmap.pipeline_jax import PoolMapper

    cmap = sample_cluster_map(3, 4, 4)
    m = OSDMap(cmap)
    for o in range(48):
        m.add_osd(o)
    m.pools[1] = PgPool(pool_type=POOL_TYPE_REPLICATED, size=3,
                        pg_num=100, crush_rule=0)   # non-divisible
    m.pg_upmap[(1, 5)] = [1, 2, 3]
    m.pg_upmap_items[(1, 3)] = [(0, 47)]
    m.pg_temp[(1, 7)] = [9, 10, 11]
    m.primary_temp[(1, 8)] = 12
    pm_ref = PoolMapper(m, 1)
    pm_mesh = PoolMapper(m, 1, mesh=make_mesh())
    a, b = pm_ref.map_all(), pm_mesh.map_all()
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k
    m.pg_upmap[(1, 6)] = [2, 3, 4]
    pm_ref.refresh_tables()
    pm_mesh.refresh_tables()
    a, b = pm_ref.map_all(), pm_mesh.map_all()
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_crush_tester_mesh_sweep_matches_scalar(mesh):
    """CrushTester.test_rule over the mesh: same mappings, same
    utilization tally (the all-reduced on-device counts) as the
    scalar sweep."""
    from ceph_tpu.crush.builder import sample_cluster_map
    from ceph_tpu.crush.wrapper import CrushWrapper
    from ceph_tpu.tools.tester import CrushTester

    w = CrushWrapper(sample_cluster_map(2, 2, 4))
    t = CrushTester(w)
    rep_mesh = t.test_rule(0, 3, 0, 99, mesh=make_mesh())
    rep_scalar = t.test_rule(0, 3, 0, 99, scalar=True)
    assert rep_mesh.total == rep_scalar.total == 100
    assert rep_mesh.size_counts == rep_scalar.size_counts
    assert np.array_equal(rep_mesh.device_stored,
                          rep_scalar.device_stored)
    assert rep_mesh.bad == rep_scalar.bad


# -- bench lane + trajectory ------------------------------------------------

def test_bench_multichip_worker_smoke():
    """The multichip lane end-to-end in a subprocess: init + multichip
    stages land, with 1-dev vs N-dev rates, scaling-efficiency
    figures, a per-device breakdown row per mesh device, and passing
    SLO blocks (floors sized for one CPU core time-slicing the
    virtual mesh)."""
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "CEPH_TPU_PLATFORM": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "CEPH_TPU_MULTICHIP_MAP": "map_flat12",
        "CEPH_TPU_MULTICHIP_BATCH": "2048",
        "CEPH_TPU_MULTICHIP_ITERS": "2",
        "CEPH_TPU_MULTICHIP_EC_BATCH": "8",
        "CEPH_TPU_MULTICHIP_EC_CHUNK": "16384",
    })
    out = subprocess.run(
        [sys.executable, str(repo / "bench.py"), "--worker",
         "multichip"],
        env=env, cwd=str(repo), capture_output=True, text=True,
        timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    results = [json.loads(line[len("BENCH_RESULT "):])
               for line in out.stdout.splitlines()
               if line.startswith("BENCH_RESULT ")]
    stages = {r["stage"]: r for r in results}
    assert "init" in stages and stages["init"]["n_devices"] >= 2
    mc = stages["multichip"]
    n = mc["n_devices"]
    assert mc["crush_1dev_mappings_per_sec"] > 0
    assert mc["crush_ndev_mappings_per_sec"] > 0
    want_eff = mc["crush_ndev_mappings_per_sec"] / (
        n * mc["crush_1dev_mappings_per_sec"])
    assert mc["crush_scaling_efficiency"] == pytest.approx(
        want_eff, rel=0.01)
    assert mc["ec_ndev_gbps"] > 0 and mc["ec_1dev_gbps"] > 0
    assert len(mc["per_device"]) == n
    assert all(d.get("kernel_launches", 0) > 0
               for d in mc["per_device"])
    slos = {b["metric"]: b for b in mc["slo"]}
    assert slos["multichip_crush_mappings_per_sec"]["pass"] is True
    assert slos["multichip_encode_gbps"]["pass"] is True


def test_perf_history_ingests_multichip(tmp_path):
    """perf_history merges the bench lane's multichip stage JSON and
    the MULTICHIP_rNN dryrun records into the trajectory, and
    red-checks a >25% scaling-efficiency drop between runs."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve()
                           .parent.parent))
    from tools import perf_history

    def mc_tail(ndev_rate, eff, ec_eff):
        return "# multichip json: " + json.dumps({
            "stage": "multichip", "n_devices": 8,
            "crush_ndev_mappings_per_sec": ndev_rate,
            "crush_scaling_efficiency": eff,
            "ec_scaling_efficiency": ec_eff})

    def write_bench(n, rate, tail):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "bench", "rc": 0, "tail": tail,
            "parsed": {"metric": "crush_mappings_per_sec",
                       "value": rate, "platform": "cpu"}}))

    # a MULTICHIP dryrun record with no same-numbered bench run gets
    # its own trajectory row; its efficiency lands in the mc_dry_*
    # columns (smaller workload — never delta'd against bench-lane
    # values) and its small-map absolute rate is dropped
    (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps({
        "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
        "tail": "multichip scaling: " + json.dumps({
            "n_devices": 8, "crush_ndev_mappings_per_sec": 50000.0,
            "crush_scaling_efficiency": 0.8,
            "ec_scaling_efficiency": 0.9})}))
    write_bench(2, 100000.0, mc_tail(52000.0, 0.82, 0.88))
    write_bench(3, 101000.0, mc_tail(53000.0, 0.80, 0.91))
    rows = perf_history.load_all(str(tmp_path))
    assert [r["run"] for r in rows] == ["r01", "r02", "r03"]
    assert rows[0]["metrics"]["mc_dry_crush_eff"] == 0.8
    assert "mc_crush_ndev_s" not in rows[0]["metrics"]
    assert rows[1]["metrics"]["mc_crush_ndev_s"] == 52000.0
    perf_history.compute_deltas(rows)
    assert "mc_crush_eff" in rows[2]["deltas"]
    assert perf_history.main([str(tmp_path), "--check"]) == 0
    # a 50% efficiency collapse in the latest run is a red check
    write_bench(4, 102000.0, mc_tail(26000.0, 0.40, 0.89))
    assert perf_history.main([str(tmp_path), "--check"]) == 1
    rows = perf_history.load_all(str(tmp_path))
    perf_history.compute_deltas(rows)
    assert any("mc_crush_eff" in r for r in rows[-1]["regressions"])
