"""Pooled buffer plane (common/bufpool) — lifecycle, recycling, leak
accounting, and the view-outlives-frame safety contract under the
messenger's session reset/replay machinery (ROADMAP item 2).
"""

import gc
import threading
import time

import pytest

from ceph_tpu.common import bufpool
from ceph_tpu.common.bufpool import BufferPool, DoubleRelease
from ceph_tpu.msg.messenger import Messenger


# -- pool lifecycle ---------------------------------------------------

def test_acquire_release_recycles_buffer():
    pool = BufferPool()
    seg = pool.acquire(4096, tag="t1")
    assert seg.nbytes == 4096
    assert len(pool.outstanding()) == 1
    buf_id = id(seg._buf)
    seg.release()
    assert pool.outstanding() == []
    # same size class comes back as the SAME underlying buffer
    seg2 = pool.acquire(3000, tag="t2")
    assert id(seg2._buf) == buf_id
    seg2.release()
    d = pool._counters().dump()
    assert d["pool_hits"] == 1
    assert d["pool_misses"] == 1
    assert d["acquires"] == 2 and d["releases"] == 2
    assert d["live_segments"] == 0 and d["live_bytes"] == 0


def test_size_classes_are_powers_of_two():
    pool = BufferPool()
    for n, want in [(1, 1024), (1024, 1024), (1025, 2048),
                    (100_000, 131072)]:
        seg = pool.acquire(n)
        assert len(seg._buf) == want, n
        assert seg.nbytes == n
        assert len(seg.writable()) == n
        seg.release()


def test_oversized_request_served_unpooled():
    pool = BufferPool()
    n = (1 << 24) + 1  # above the largest retained class
    seg = pool.acquire(n, tag="big")
    assert len(seg._buf) == n
    seg.release()
    assert pool.free_buffers() == 0  # never retained
    assert pool._counters().dump()["pool_misses"] == 1


def test_free_list_bounded_per_class():
    pool = BufferPool(per_class=2)
    segs = [pool.acquire(2048) for _ in range(5)]
    for s in segs:
        s.release()
    assert pool.free_buffers() == 2


def test_incref_extends_lifetime_across_handoff():
    pool = BufferPool()
    seg = pool.acquire(512, tag="handoff")
    seg.incref()
    seg.release()
    # still held by the second reference: view stays valid
    view = seg.view()
    view[:3] = b"abc"
    assert bytes(seg.view(0, 3)) == b"abc"
    assert len(pool.outstanding()) == 1
    seg.release()
    assert pool.outstanding() == []


def test_double_release_raises():
    pool = BufferPool()
    seg = pool.acquire(256)
    seg.release()
    with pytest.raises(DoubleRelease):
        seg.release()
    with pytest.raises(DoubleRelease):
        seg.incref()  # resurrection is the same bug class


def test_gc_leak_is_counted_not_silent():
    pool = BufferPool()
    seg = pool.acquire(1024, tag="leaky")
    before = pool._counters().dump()["leaked_segments"]
    del seg  # dropped while still referenced
    gc.collect()
    d = pool._counters().dump()
    assert d["leaked_segments"] == before + 1
    assert d["live_segments"] == 0 and d["live_bytes"] == 0
    assert pool.outstanding() == []
    # the buffer itself was reclaimed into the free list
    assert pool.free_buffers() == 1


def test_concurrent_acquire_release_consistent():
    pool = BufferPool()
    errors = []

    def worker():
        try:
            for _ in range(200):
                seg = pool.acquire(4096, tag="conc")
                seg.view()[:4] = b"\xde\xad\xbe\xef"
                seg.release()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ths = [threading.Thread(target=worker) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert not errors
    assert pool.outstanding() == []
    d = pool._counters().dump()
    assert d["acquires"] == d["releases"] == 8 * 200


# -- messenger integration: views must not outlive their frame --------

def test_reply_payload_survives_segment_recycling():
    """A call() reply blob is handed to the caller AFTER its pooled
    recv segment is released.  If the messenger returned a raw view,
    the next recv into the recycled buffer would rewrite the caller's
    bytes under it — so replies must be materialised (and booked)."""
    server = Messenger("bp-server")
    client = Messenger("bp-client")
    server.start()
    client.start()
    try:
        server.register(
            "get", lambda m: {"ok": True, "data": b"\xaa" * 2000})
        rep = client.call(server.addr, {"type": "get"}, timeout=5)
        got = rep["data"]
        snapshot = bytes(got)
        # hammer the SAME connection so recycled recv segments are
        # rewritten many times over
        for i in range(20):
            client.call(server.addr,
                        {"type": "get", "i": i}, timeout=5)
        assert bytes(got) == snapshot == b"\xaa" * 2000
    finally:
        client.shutdown()
        server.shutdown()


def test_request_views_stable_through_session_reset_and_replay():
    """The satellite-3 safety drill: request blobs reach handlers as
    views into pooled segments; killing the transport mid-stream
    forces session reset + frame replay.  Every handler must observe
    its payload intact (no recycled-buffer aliasing), and the pool
    must drain back to empty."""
    server = Messenger("rs-server", lossless=True)
    client = Messenger("rs-client", lossless=True)
    server.start()
    client.start()
    corrupt = []
    payload = lambda n: bytes([n & 0xFF]) * 1500  # noqa: E731

    def h(msg):
        data = msg["data"]
        want = payload(msg["n"])
        # read twice with a scheduling gap between — an aliased
        # recycled buffer would tear between the reads
        first = bytes(data)
        time.sleep(0.001)
        if first != want or bytes(data) != want:
            corrupt.append(msg["n"])
        return {"ok": True, "n": msg["n"]}

    server.register("put", h)
    errors = []
    N, WRITERS = 40, 3

    def writer(w):
        for i in range(N):
            n = w * N + i
            try:
                rep = client.call(
                    server.addr,
                    {"type": "put", "n": n, "data": payload(n)},
                    timeout=20)
                assert rep.get("n") == n
            except Exception as e:  # pragma: no cover
                errors.append((n, e))

    ths = [threading.Thread(target=writer, args=(w,))
           for w in range(WRITERS)]
    for t in ths:
        t.start()
    for _ in range(4):
        time.sleep(0.1)
        with client._conn_lock:
            socks = list(client._conns.values())
        for s in socks:
            try:
                s.close()  # RST under the session layer -> replay
            except OSError:
                pass
    for t in ths:
        t.join()
    try:
        assert not errors, f"lost ops: {errors[:3]}"
        assert not corrupt, \
            f"payload corrupted for ops {sorted(corrupt)[:10]} — " \
            f"a view outlived its pooled segment"
    finally:
        client.shutdown()
        server.shutdown()
    # drained: the per-test conftest gate re-checks this, but assert
    # here too so the failure names THIS contract
    deadline = time.monotonic() + 2.0
    while bufpool.outstanding() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert bufpool.outstanding() == []
