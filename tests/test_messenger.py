"""Messenger session layer: reconnect/replay, dedup, policies,
throttles — the ProtocolV2 acceptance tests from the round-3 review.

The headline test drops the TCP connection repeatedly under an
in-flight op stream and asserts ZERO lost and ZERO duplicated ops.
"""

import threading
import time

import pytest

from ceph_tpu.common.throttle import Throttle
from ceph_tpu.msg.auth import Keyring
from ceph_tpu.msg.messenger import Messenger, _send_frame


def mk_pair(lossless=True, keyring=None, throttles=None):
    server = Messenger("server", lossless=lossless, keyring=keyring,
                       throttles=throttles)
    client = Messenger("client-side", lossless=lossless,
                       keyring=keyring)
    server.start()
    client.start()
    return server, client


def test_drop_connection_under_stream_zero_lost_zero_dup():
    server, client = mk_pair()
    seen = []
    seen_lock = threading.Lock()

    def h(msg):
        with seen_lock:
            seen.append(msg["n"])
        return {"ok": True, "n": msg["n"]}

    server.register("op", h)
    errors = []
    N, WRITERS = 60, 4

    def writer(w):
        for i in range(N):
            n = w * N + i
            try:
                rep = client.call(server.addr,
                                  {"type": "op", "n": n}, timeout=20)
                assert rep.get("n") == n
            except Exception as e:
                errors.append((n, e))

    ths = [threading.Thread(target=writer, args=(w,))
           for w in range(WRITERS)]
    for t in ths:
        t.start()
    # kill the transport repeatedly mid-stream
    for _ in range(6):
        time.sleep(0.15)
        with client._conn_lock:
            socks = list(client._conns.values())
        for s in socks:
            try:
                s.close()  # RST from under the session layer
            except OSError:
                pass
    for t in ths:
        t.join()
    try:
        assert not errors, f"lost ops: {errors[:3]}"
        assert sorted(seen) == list(range(N * WRITERS)), \
            f"dups/gaps: {len(seen)} served vs {N * WRITERS}"
    finally:
        client.shutdown()
        server.shutdown()


def test_duplicate_sequenced_frame_not_reexecuted():
    """A captured signed frame replayed verbatim must not re-run the
    handler (the cephx seq-binding / ADVICE replay item)."""
    kr = Keyring.generate()
    server, client = mk_pair(keyring=kr)
    calls = []
    server.register("op", lambda m: calls.append(m["n"]) or
                    {"ok": True})
    try:
        client.call(server.addr, {"type": "op", "n": 1}, timeout=10)
        # replay the same frame content with a valid signature (the
        # capture scenario: signing is deterministic, so an on-path
        # attacker's byte-identical frame carries this exact MAC)
        frame = {"type": "op", "n": 1, "_s": 1,
                 "_sess": client.session_id, "frm": client.name}
        import socket as _socket

        raw = _socket.create_connection(server.addr, timeout=5)
        _send_frame(raw, frame, kr)
        time.sleep(0.5)
        raw.close()
        assert calls == [1], f"replay executed: {calls}"
    finally:
        client.shutdown()
        server.shutdown()


def test_tampered_frame_dropped():
    kr = Keyring.generate()
    server, client = mk_pair(keyring=kr)
    calls = []
    server.register("op", lambda m: calls.append(m["n"]) or
                    {"ok": True})
    try:
        import socket as _socket

        frame = {"type": "op", "n": 7, "_s": 1,
                 "_sess": client.session_id, "frm": client.name}
        frame["mac"] = kr.sign(frame)
        frame["n"] = 8  # tamper after signing
        raw = _socket.create_connection(server.addr, timeout=5)
        _send_frame(raw, frame)  # no keyring: the stale mac rides along
        time.sleep(0.4)
        raw.close()
        assert calls == []
    finally:
        client.shutdown()
        server.shutdown()


def test_lossy_policy_unsequenced():
    server, client = mk_pair(lossless=False)
    got = []
    server.register("op", lambda m: got.append(m.get("_s")) or
                    {"ok": True})
    try:
        client.call(server.addr, {"type": "op"}, timeout=10)
        assert got == [None]  # no sequence numbers on lossy frames
    finally:
        client.shutdown()
        server.shutdown()


def test_per_type_byte_throttle_bounds_inflight():
    th = Throttle("t", 40_000)  # two ~17KB frames fit, three don't
    server, client = mk_pair(throttles={"big": th})
    inflight = []
    peak = [0]
    lk = threading.Lock()

    def h(msg):
        with lk:
            inflight.append(1)
            peak[0] = max(peak[0], len(inflight))
        time.sleep(0.2)
        with lk:
            inflight.pop()
        return {"ok": True}

    server.register("big", h)
    try:
        blob = "x" * 16_000
        ths = [threading.Thread(
            target=lambda: client.call(
                server.addr, {"type": "big", "d": blob}, timeout=20))
            for _ in range(5)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert peak[0] <= 2, f"throttle admitted {peak[0]} at once"
    finally:
        client.shutdown()
        server.shutdown()


def test_large_frames_compress_on_the_wire():
    """Full-map-sized frames ride zlib-compressed (high bit of the
    length word), transparently to both sides."""
    server, client = mk_pair(lossless=False)
    server.register("blob", lambda m: {"echo_len": len(m["d"]),
                                       "d": m["d"][:8]})
    try:
        big = "A" * 300_000  # compressible, like a JSON map
        rep = client.call(server.addr, {"type": "blob", "d": big},
                          timeout=15)
        assert rep["echo_len"] == 300_000 and rep["d"] == "A" * 8
        # and the reply path with a big payload
        server.register("pull", lambda m: {"d": big})
        rep = client.call(server.addr, {"type": "pull"}, timeout=15)
        assert rep["d"] == big
    finally:
        client.shutdown()
        server.shutdown()


def test_ordered_types_dispatch_fifo_per_session():
    """Sequenced frames of ordered types execute in arrival order
    even when the first one is slow — the quorum-layer contract
    (mon_commit(v) before mon_accept(v+1)); unordered types keep
    fast-dispatch parallelism (ADVICE round-5 medium #1)."""
    server, client = mk_pair()
    seen = []
    lk = threading.Lock()

    def slow(m):
        time.sleep(0.3)
        with lk:
            seen.append(m["i"])
        return None

    def fast(m):
        with lk:
            seen.append(m["i"])
        return None

    server.register("slow", slow, ordered=True)
    server.register("fast", fast, ordered=True)
    try:
        client.send(server.addr, {"type": "slow", "i": 0})
        for i in range(1, 6):
            client.send(server.addr, {"type": "fast", "i": i})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(seen) < 6:
            time.sleep(0.02)
        assert seen == [0, 1, 2, 3, 4, 5], seen
    finally:
        client.shutdown()
        server.shutdown()


def test_blob_sentinel_literal_roundtrip():
    """A payload value that happens to look exactly like the wire's
    blob sentinel (or its escape) must arrive verbatim, not be
    resolved into an unrelated data segment (ADVICE round-5 low #5)."""
    server, client = mk_pair(lossless=False)
    got = []
    server.register("echo", lambda m: {"back": m["payload"]})
    try:
        tricky = {
            "literal_blob": {"__frame_blob__": 0},
            "oob_blob": {"__frame_blob__": 99},
            "literal_esc": {"__frame_esc__": "x"},
            "mixed": [{"__frame_blob__": 7}, b"real-bytes", "s"],
        }
        rep = client.call(server.addr,
                          {"type": "echo", "payload": tricky},
                          timeout=10)
        back = rep["back"]
        assert back["literal_blob"] == {"__frame_blob__": 0}
        assert back["oob_blob"] == {"__frame_blob__": 99}
        assert back["literal_esc"] == {"__frame_esc__": "x"}
        assert back["mixed"][0] == {"__frame_blob__": 7}
        assert back["mixed"][1] == b"real-bytes"
    finally:
        client.shutdown()
        server.shutdown()


def test_corrupt_frames_do_not_kill_the_server():
    """Truncated/forged blob tables, bad blob indices, and garbage
    bytes must drop the offending connection or frame cleanly; the
    messenger keeps serving (ADVICE round-5 low #2)."""
    import json as _json
    import socket as _socket
    import struct as _struct
    import zlib as _zlib

    server, client = mk_pair(lossless=False)
    server.register("ping", lambda m: {"pong": True})
    try:
        def raw_payload(body: bytes, nblobs_field: int,
                        blob_parts: bytes = b"", flags: int = 0,
                        ver: int = 2) -> bytes:
            return (_struct.pack("<BBI", ver, flags, len(body)) + body
                    + _struct.pack("<I", nblobs_field) + blob_parts)

        body = _json.dumps({"type": "ping"}).encode()
        evil = [
            # forged huge blob count (would allocate/overread)
            raw_payload(body, 0xFFFFFFFF),
            # blob table claims one blob, provides a truncated length
            raw_payload(body, 1, _struct.pack("<I", 1 << 30)),
            # control segment longer than the frame
            _struct.pack("<BBI", 2, 0, 1 << 20) + b"short",
            # zlib flag set over garbage
            raw_payload(b"not-zlib", 0, flags=1),
            # out-of-range blob reference inside valid framing
            raw_payload(_json.dumps(
                {"type": "ping",
                 "d": {"__frame_blob__": 5}}).encode(), 0),
            # unknown version byte
            raw_payload(body, 0, ver=9),
        ]
        for payload in evil:
            s = _socket.create_connection(server.addr, timeout=5)
            s.sendall(_struct.pack(">I", len(payload)) + payload)
            time.sleep(0.05)
            s.close()
        # the server survived every poisoned frame and still serves
        rep = client.call(server.addr, {"type": "ping"}, timeout=10)
        assert rep.get("pong") is True
    finally:
        client.shutdown()
        server.shutdown()


def test_control_lane_survives_op_burst():
    """ADVICE round-5 low #3: latency-critical control frames
    (heartbeats, map pushes, peering probes) get a dedicated dispatch
    lane.  Saturate every op-pool worker (16) with slow shard writes,
    then time a control-lane call: without the lane it waits for an
    op worker (>= the shard-write service time); with it, it must
    complete while every op worker is still blocked."""
    server, client = mk_pair(lossless=False)
    try:
        release = threading.Event()
        started = []
        started_lock = threading.Lock()

        def slow_write(msg):
            with started_lock:
                started.append(msg["n"])
            release.wait(10)  # a shard write stuck in the store
            return {"ok": True}

        beats = []

        def heartbeat(msg):
            beats.append(time.monotonic())
            return {"alive": True}

        server.register("shard_write", slow_write)
        server.register("heartbeat", heartbeat, control=True)

        # saturate the op pool: 16 workers, 16 wedged writes
        for n in range(16):
            client.send(server.addr, {"type": "shard_write", "n": n})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with started_lock:
                if len(started) >= 16:
                    break
            time.sleep(0.01)
        with started_lock:
            assert len(started) >= 16, f"only {len(started)} writes " \
                f"started — op pool not saturated, test is vacuous"

        t0 = time.monotonic()
        rep = client.call(server.addr, {"type": "heartbeat"},
                          timeout=5)
        dt = time.monotonic() - t0
        assert rep.get("alive") is True
        # every op worker is still wedged: the heartbeat can only have
        # run on the control lane.  Generous bound — the regression
        # mode is ~10s (waiting out a slow write), not ~2s.
        assert dt < 2.0, f"heartbeat took {dt:.2f}s with the op pool " \
            f"saturated — control lane is not isolating it"
        assert beats, "heartbeat handler never ran"
        release.set()
    finally:
        release.set()
        client.shutdown()
        server.shutdown()


def test_compression_bomb_drops_session_not_daemon():
    """Satellite regression: a ~1 KiB frame whose compressed control
    segment claims 100 MiB must be rejected at the codec (bounded
    decompression, MalformedInput) — the unbounded zlib.decompress it
    replaces would have allocated the full 100 MiB before any check.
    The server keeps serving afterwards."""
    import socket as _socket
    import struct as _struct
    import zlib as _zlib

    from ceph_tpu.msg.messenger import MAX_DECOMPRESSED

    server, client = mk_pair(lossless=False)
    server.register("ping", lambda m: {"pong": True})
    try:
        plain = 100 << 20
        assert plain > MAX_DECOMPRESSED  # the claim exceeds the cap
        comp = _zlib.compress(b"a" * plain, 6)
        payload = (_struct.pack("<BBI", 2, 0x01, len(comp)) + comp
                   + _struct.pack("<I", 0))
        assert len(payload) < 256 << 10  # a genuinely small frame
        s = _socket.create_connection(server.addr, timeout=5)
        s.sendall(_struct.pack(">I", len(payload)) + payload)
        time.sleep(0.1)
        s.close()
        rep = client.call(server.addr, {"type": "ping"}, timeout=10)
        assert rep.get("pong") is True
    finally:
        client.shutdown()
        server.shutdown()


def test_send_writer_table_bounded_across_reconnect_cycles():
    """Satellite regression: the per-socket writer table (the old
    ``_send_locks``) leaked one entry per reconnect cycle — dead
    connections were never reaped after ``_on_conn_death``.  N
    kill/reconnect cycles must not grow the table."""
    from ceph_tpu.msg import messenger as M

    server, client = mk_pair(lossless=False)
    server.register("ping", lambda m: {"pong": True})
    try:
        assert client.call(server.addr, {"type": "ping"},
                           timeout=5).get("pong")
        base = len(M._sock_writers)
        for _ in range(8):
            # hard-drop the cached conn (the reconnect-cycle shape)
            client._drop(server.addr)
            assert client.call(server.addr, {"type": "ping"},
                               timeout=5).get("pong")
        # stragglers reap on reader exit; give them a beat
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and \
                len(M._sock_writers) > base + 4:
            time.sleep(0.05)
        grown = len(M._sock_writers) - base
        assert grown <= 4, \
            f"writer table grew by {grown} over 8 reconnect cycles"
        # let the dropped conns' reader threads drain so the next
        # test starts quiesced (they exit on the hard-close EOF)
        deadline = time.monotonic() + 4
        while time.monotonic() < deadline and sum(
                1 for t in threading.enumerate()
                if t.name == "msgr-rd:client-side") > 1:
            time.sleep(0.05)
    finally:
        client.shutdown()
        server.shutdown()


def test_concurrent_sends_coalesce_without_corruption():
    """Many threads sending frames over ONE shared connection: the
    per-socket writer coalesces queued frames into single gathered
    sends — every frame must still arrive intact, exactly once (a
    framing slip would surface as a dropped session or a mangled
    payload)."""
    server, client = mk_pair(lossless=False)
    seen = []
    lk = threading.Lock()

    def h(msg):
        with lk:
            seen.append((msg["n"], bytes(msg["blob"])))
        return None

    server.register("op", h)
    try:
        N, WRITERS = 50, 8

        def writer(w):
            for i in range(N):
                n = w * N + i
                client.send(server.addr,
                            {"type": "op", "n": n,
                             "blob": bytes([n & 0xFF]) * (64 + n)})

        ths = [threading.Thread(target=writer, args=(w,))
               for w in range(WRITERS)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with lk:
                if len(seen) >= N * WRITERS:
                    break
            time.sleep(0.02)
        with lk:
            got = dict(seen)
            assert len(seen) == N * WRITERS, \
                f"lost frames: {len(seen)}/{N * WRITERS}"
        for n, blob in got.items():
            assert blob == bytes([n & 0xFF]) * (64 + n), \
                f"frame {n} corrupted by coalesced send"
    finally:
        client.shutdown()
        server.shutdown()
