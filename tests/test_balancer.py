"""Upmap balancer tests — mirrors the reference's TestOSDMap.cc upmap
coverage (calc_pg_upmaps behavior) against the scalar pipeline spec.

The key discipline: after calc_pg_upmaps mutates pg_upmap_items, the
improvement must be visible when the cluster is remapped FROM SCRATCH
through the full pipeline (not just in the optimizer's bookkeeping) —
i.e. the balancer's internal tallies match a scalar re-derivation.
"""

import numpy as np
import pytest

from ceph_tpu.crush.wrapper import CrushWrapper
from ceph_tpu.osdmap.balancer import (build_pgs_by_osd, calc_pg_upmaps,
                                      get_rule_weight_osd_map,
                                      pg_to_raw_upmap)
from ceph_tpu.osdmap.osdmap import OSDMap, PgPool


def make_cluster(hosts=4, osds_per_host=4, pg_num=256, size=3):
    w = CrushWrapper()
    dev = 0
    for h in range(hosts):
        for _ in range(osds_per_host):
            w.insert_item(dev, 0x10000, f"osd.{dev}",
                          {"host": f"host{h}", "root": "default"})
            dev += 1
    rid = w.add_simple_rule("repl", "default", "host", "", "firstn")
    m = OSDMap(w.crush)
    for d in range(dev):
        m.add_osd(d)
    m.pools[1] = PgPool(size=size, pg_num=pg_num, crush_rule=rid)
    return m, w, rid


def _stats(m, osd_weight_keys):
    pgs = build_pgs_by_osd(m)
    counts = {o: len(pgs.get(o, ())) for o in osd_weight_keys}
    vals = np.asarray(list(counts.values()), float)
    target = vals.mean()
    dev = vals - target
    return counts, float((dev ** 2).sum()), float(np.abs(dev).max())


def test_rule_weight_osd_map_normalized():
    m, w, rid = make_cluster()
    pmap = get_rule_weight_osd_map(w, rid)
    assert set(pmap) == set(range(16))
    assert abs(sum(pmap.values()) - 1.0) < 1e-6
    # double one osd's crush weight: its share doubles
    w.adjust_item_weight(0, 0x20000)
    pmap2 = get_rule_weight_osd_map(w, rid)
    assert pmap2[0] == pytest.approx(2 * pmap2[1], rel=1e-6)


def test_pg_to_raw_upmap_applies_items():
    m, w, rid = make_cluster(pg_num=32)
    raw, up = pg_to_raw_upmap(m, 1, 5)
    assert raw == up
    # remap first osd of pg 5 to some other osd
    frm = raw[0]
    to = next(o for o in range(16) if o not in raw)
    m.pg_upmap_items[(1, 5)] = [(frm, to)]
    raw2, up2 = pg_to_raw_upmap(m, 1, 5)
    assert raw2 == raw
    assert up2[0] == to


def test_calc_pg_upmaps_reduces_deviation():
    m, w, rid = make_cluster(hosts=4, osds_per_host=4, pg_num=256)
    osds = set(range(16))
    _, stddev0, max0 = _stats(m, osds)
    changed = calc_pg_upmaps(m, max_deviation=1, max_iterations=20,
                             wrapper=w)
    assert changed > 0
    counts, stddev1, max1 = _stats(m, osds)
    # the optimizer's claimed improvement is real when re-derived from
    # scratch through the pipeline
    assert stddev1 < stddev0
    assert max1 <= max0
    # and the remapped cluster still respects the failure domain
    host = {d: d // 4 for d in range(16)}
    for ps in range(256):
        up, _p, _a, _ap = m.pg_to_up_acting_osds(1, ps)
        assert len({host[o] for o in up}) == len(up)


def test_calc_pg_upmaps_converges_to_max_deviation():
    m, w, rid = make_cluster(hosts=4, osds_per_host=4, pg_num=128)
    calc_pg_upmaps(m, max_deviation=2, max_iterations=50, wrapper=w)
    _, _sd, maxd = _stats(m, set(range(16)))
    assert maxd <= 2.5  # float target vs integer pg counts


def test_calc_pg_upmaps_noop_when_balanced():
    m, w, rid = make_cluster(pg_num=16)
    calc_pg_upmaps(m, max_deviation=1, max_iterations=10, wrapper=w)
    before = dict(m.pg_upmap_items)
    # huge tolerance: nothing exceeds it, so no changes
    changed = calc_pg_upmaps(m, max_deviation=1000, wrapper=w)
    assert changed == 0
    assert m.pg_upmap_items == before


def test_calc_pg_upmaps_respects_only_pools():
    m, w, rid = make_cluster(pg_num=64)
    m.pools[2] = PgPool(size=3, pg_num=64, crush_rule=rid)
    calc_pg_upmaps(m, max_deviation=1, max_iterations=10, wrapper=w,
                   only_pools={2})
    assert all(pgid[0] == 2 for pgid in m.pg_upmap_items)


def test_build_pgs_by_osd_batched_equals_scalar():
    m, w, rid = make_cluster(hosts=3, osds_per_host=2, pg_num=32)
    scalar = build_pgs_by_osd(m)
    batched = build_pgs_by_osd(m, use_batched=True)
    assert scalar == batched


def test_crush_compat_reduces_score():
    """The balancer's second mode: choose_args weight-sets steer
    straw2 draws without touching the real hierarchy weights
    (module.py do_crush_compat)."""
    from ceph_tpu.osdmap.balancer import (distribution_score,
                                          do_crush_compat)

    m, w, rid = make_cluster(hosts=4, osds_per_host=4, pg_num=256)
    s0, s1, cam = do_crush_compat(m, wrapper=w, max_iterations=15,
                                  step=0.5, max_misplaced=0.5)
    assert cam is not None and s1 < s0
    # the improvement is real when re-derived from scratch with the
    # installed choose_args (the pipeline consumes them per pool)
    assert 1 in m.crush.choose_args
    pgs = build_pgs_by_osd(m)
    counts = np.asarray([len(pgs.get(o, ())) for o in range(16)], float)
    assert counts.sum() == 256 * 3
    # real crush weights untouched (the whole point of compat mode)
    assert all(w.get_item_weight(o) == 0x10000 for o in range(16))


def test_weight_set_choose_args_shape():
    from ceph_tpu.osdmap.balancer import weight_set_to_choose_args

    m, w, rid = make_cluster(hosts=2, osds_per_host=2, pg_num=8)
    cam = weight_set_to_choose_args(w, {0: 1.0, 1: 0.5, 2: 1.0,
                                        3: 1.0})
    root_idx = -1 - w.get_item_id("default")
    for idx, arg in cam.items():
        b = m.crush.buckets[idx]
        assert len(arg.weight_set[0]) == len(b.items)
    # root row = accumulated subtree values
    assert sum(cam[root_idx].weight_set[0]) == int(3.5 * 0x10000)


def test_upmap_items_survive_weight_change_rejection():
    """Items moving data onto a zero-weight osd are ignored by the
    pipeline (OSDMap.cc:2472 semantics already pinned in osdmap tests)
    — the balancer must not crash on such maps."""
    m, w, rid = make_cluster(pg_num=64)
    m.osd_weight[3] = 0
    changed = calc_pg_upmaps(m, max_deviation=1, max_iterations=10,
                             wrapper=w)
    # osd 3 is out: no new items may target it
    for items in m.pg_upmap_items.values():
        assert all(to != 3 for _f, to in items)


def test_try_remap_rule_randomized_differential_big10k():
    """Round-3 review item 10: thousands of random overfull/underfull
    sets on the 10k-OSD map.  Every try_remap_rule output must (a)
    swap only overfull->underfull devices, (b) preserve failure-domain
    disjointness (distinct host ancestors, verified by ancestor walks),
    and (c) keep the mapping size/validity."""
    import json
    import pathlib
    import random

    from ceph_tpu.crush.map import CrushMap
    from ceph_tpu.crush.mapper_ref import crush_do_rule
    from ceph_tpu.crush.wrapper import CrushWrapper

    gold = pathlib.Path(__file__).parent / "golden/map_big10k.json"
    d = json.load(open(gold))
    cmap = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    ruleno, numrep = case["ruleno"], case["numrep"]
    wrapper = CrushWrapper(cmap)
    host_type = 1  # big10k: host=1, rack=2, root=3
    weights = [0x10000] * cmap.max_devices
    rng = random.Random(1234)

    def host_of(osd: int) -> int:
        return wrapper.get_parent_of_type(osd, host_type, ruleno)

    checked = remapped = 0
    for trial in range(2000):
        x = rng.randrange(1 << 30)
        orig = crush_do_rule(cmap, ruleno, x, numrep, weights)
        if len(orig) < numrep:
            continue
        overfull = set(rng.sample(orig, rng.randint(1, len(orig))))
        # underfull: random devices on OTHER hosts than the mapping
        used_hosts = {host_of(o) for o in orig}
        underfull = []
        while len(underfull) < 8:
            cand = rng.randrange(cmap.max_devices)
            if cand not in orig and host_of(cand) not in used_hosts:
                underfull.append(cand)
        more_underfull = []
        out = wrapper.try_remap_rule(
            ruleno, numrep, overfull, underfull, more_underfull,
            list(orig))
        checked += 1
        assert len(out) == len(orig), (trial, orig, out)
        # (a) only overfull devices may have been replaced, and only
        # by underfull ones
        for pos, (a, b) in enumerate(zip(orig, out)):
            if a != b:
                assert a in overfull, \
                    f"trial {trial}: swapped non-overfull {a}"
                assert b in underfull, \
                    f"trial {trial}: replacement {b} not underfull"
                remapped += 1
        # (b) failure-domain disjointness: pairwise distinct hosts
        hosts = [host_of(o) for o in out]
        assert len(set(hosts)) == len(hosts), \
            f"trial {trial}: failure domains collide: {out} -> {hosts}"
    # the property test must actually exercise remaps, not vacuously
    # pass on "nothing changed"
    assert checked >= 1900 and remapped >= 1000, (checked, remapped)


# -- PR 10 satellites: calc_pg_upmaps edge cases ------------------------

def test_calc_pg_upmaps_device_class_rules_stay_in_class():
    """A class-scoped pool's upmap targets never leave the device
    class: the rule's weight map only contains class members, so
    overfull/underfull sets — and thus every proposed move — are
    class-local."""
    from ceph_tpu.mgr import make_synthetic_map

    m, w, rules = make_synthetic_map(
        n_osds=16, osds_per_host=2, hosts_per_rack=4, pg_num=64,
        seed=5, device_classes=["ssd", "hdd"])
    ssd = {d for d in range(16) if d % 2 == 0}  # round-robin classes
    changed = calc_pg_upmaps(m, max_deviation=1, max_iterations=20,
                             wrapper=w, only_pools={2})
    assert changed > 0, "uneven class pool produced no upmaps"
    for pgid, items in m.pg_upmap_items.items():
        assert pgid[0] == 2
        for frm, to in items:
            assert frm in ssd and to in ssd, \
                f"pg {pgid}: move {frm}->{to} left class ssd"


def test_try_remap_rule_rejects_failure_domain_collision():
    """size == hosts: every host is a used failure domain, so the
    only underfull candidate (the sibling of a RETAINED member)
    collides and the mapping must come back unchanged."""
    m, w, rid = make_cluster(hosts=3, osds_per_host=2, pg_num=16,
                             size=3)
    # orig: one device per host; swap target osd.3 shares host1 with
    # the retained osd.2
    orig = [0, 2, 4]
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[3],
                           more_underfull=[], orig=orig)
    assert out == orig
    # a non-colliding candidate on the SAME construction is taken
    out2 = w.try_remap_rule(rid, 3, overfull={0}, underfull=[1],
                            more_underfull=[], orig=orig)
    assert out2 == [1, 2, 4]


def test_run_offline_balanced_map_is_noop():
    from ceph_tpu.mgr import make_synthetic_map, run_offline

    m, w, _rules = make_synthetic_map(
        n_osds=16, osds_per_host=2, hosts_per_rack=4, pg_num=64,
        seed=0, uneven=False)
    # tolerance above this map's natural CRUSH variance (max_dev 6):
    # within tolerance means balanced, and balanced means untouched
    rec = run_offline(m, w, max_deviation=8, max_iterations=10,
                      max_rounds=5, seed=0)
    assert rec["converged"]
    assert rec["upmaps"] == 0
    assert not m.pg_upmap_items
    assert rec["final_stddev"] == rec["initial_stddev"]


def test_calc_pg_upmaps_seeded_reproducibility():
    results = []
    for _ in range(2):
        m, w, rid = make_cluster(hosts=4, osds_per_host=4, pg_num=128)
        w.adjust_item_weight(0, 0x20000)  # force imbalance
        changed = calc_pg_upmaps(m, max_deviation=1,
                                 max_iterations=15, wrapper=w, seed=7)
        results.append((changed, dict(m.pg_upmap_items)))
    assert results[0][0] > 0
    assert results[0] == results[1]
