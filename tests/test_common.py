"""Foundation-layer tests: config layering/observers, subsystem log +
crash ring, perf counters, admin socket round-trips, throttles —
mirrors the reference's src/test/common coverage for the pieces the
framework keeps (config.h layering, Log.cc dump_recent,
perf_counters.h types, admin_socket.h command plane)."""

import io
import json
import threading
import time

import pytest

from ceph_tpu.common.admin_socket import AdminSocket, wire_defaults
from ceph_tpu.common.config import Config, Option
from ceph_tpu.common.context import Context
from ceph_tpu.common.log import LogCore, SubsysLogger
from ceph_tpu.common.perf_counters import (PerfCounters,
                                           PerfCountersCollection)
from ceph_tpu.common.throttle import Throttle


# -- config -----------------------------------------------------------------

def test_config_layering(tmp_path, monkeypatch):
    conf = Config()
    assert conf["osd_pool_default_size"] == 3
    assert conf.source_of("osd_pool_default_size") == "default"

    f = tmp_path / "ceph.conf"
    f.write_text("[global]\nosd pool default size = 5\n"
                 "# comment\ndebug_crush = 10\n")
    assert conf.load_file(str(f)) == 2
    assert conf["osd_pool_default_size"] == 5
    assert conf.source_of("osd_pool_default_size") == "file"

    monkeypatch.setenv("CEPH_TPU_OPT_OSD_POOL_DEFAULT_SIZE", "7")
    conf2 = Config()
    conf2.load_file(str(f))
    assert conf2["osd_pool_default_size"] == 7  # env beats file

    conf2.set("osd_pool_default_size", 9)  # override beats env
    assert conf2["osd_pool_default_size"] == 9
    conf2.rm_override("osd_pool_default_size")
    assert conf2["osd_pool_default_size"] == 7


def test_config_json_file_and_bool_coercion(tmp_path):
    conf = Config()
    f = tmp_path / "conf.json"
    f.write_text(json.dumps(
        {"osd_calc_pg_upmaps_aggressively": "false"}))
    conf.load_file(str(f))
    assert conf["osd_calc_pg_upmaps_aggressively"] is False


def test_config_observer_fires():
    conf = Config()
    seen = []
    conf.add_observer("debug_crush",
                      lambda name, v: seen.append((name, v)))
    conf.set("debug_crush", 20)
    assert seen == [("debug_crush", 20)]
    with pytest.raises(KeyError):
        conf.set("not_an_option", 1)
    assert "value" in conf.show()["debug_crush"]


# -- log --------------------------------------------------------------------

def test_log_gating_and_ring():
    sink = io.StringIO()
    core = LogCore(max_recent=8, stream=sink)
    log = SubsysLogger("crush", core)
    core.set_level("crush", 5)
    log.dout(1, "visible")
    log.dout(10, "suppressed but ringed")
    assert "visible" in sink.getvalue()
    assert "suppressed" not in sink.getvalue()

    dump = io.StringIO()
    n = core.dump_recent(dump)
    assert n == 2
    assert "suppressed but ringed" in dump.getvalue()

    for i in range(20):
        log.dout(9, f"entry{i}")
    dump2 = io.StringIO()
    assert core.dump_recent(dump2) == 8  # ring bounded
    assert "entry19" in dump2.getvalue()


# -- perf counters ----------------------------------------------------------

def test_perf_counter_types():
    pc = PerfCounters("osd.0")
    pc.add_u64_counter("ops")
    pc.add_u64("queue_len")
    pc.add_time("op_latency_total")
    pc.add_u64_avg("op_latency")
    pc.add_histogram("op_size", buckets=8)
    pc.inc("ops")
    pc.inc("ops", 2)
    pc.set("queue_len", 5)
    pc.dec("queue_len")
    pc.tinc("op_latency_total", 0.5)
    pc.avg_add("op_latency", 2.0)
    pc.avg_add("op_latency", 4.0)
    pc.hist_add("op_size", 100)
    d = pc.dump()
    assert d["ops"] == 3
    assert d["queue_len"] == 4
    assert d["op_latency_total"] == 0.5
    assert d["op_latency"]["avg"] == 3.0
    assert sum(d["op_size"]["buckets"]) == 1


def test_perf_collection_dump():
    col = PerfCountersCollection()
    a = col.create("osd.0")
    a.add_u64_counter("ops")
    a.inc("ops")
    b = col.create("osd.1")
    b.add_u64_counter("ops")
    full = col.dump()
    assert full["osd.0"]["ops"] == 1 and full["osd.1"]["ops"] == 0
    only = col.dump("osd.0")
    assert list(only) == ["osd.0"]


# -- admin socket -----------------------------------------------------------

def test_admin_socket_round_trip(tmp_path):
    path = str(tmp_path / "test.asok")
    sock = AdminSocket(path)
    conf = Config()
    col = PerfCountersCollection()
    pc = col.create("svc")
    pc.add_u64_counter("reqs")
    core = LogCore(stream=io.StringIO())
    wire_defaults(sock, config=conf, perf=col, logcore=core)
    sock.register("ping", lambda a: {"pong": a.get("x", 0)}, "ping")
    sock.start()
    try:
        assert AdminSocket.request(path, "ping", x=7) == {"pong": 7}
        pc.inc("reqs")
        assert AdminSocket.request(path, "perf dump")["svc"]["reqs"] == 1
        show = AdminSocket.request(path, "config show")
        assert show["osd_pool_default_size"]["value"] == 3
        AdminSocket.request(path, "config set",
                            key="debug_crush", value=10)
        assert AdminSocket.request(
            path, "config get", key="debug_crush") == {"debug_crush": 10}
        err = AdminSocket.request(path, "bogus")
        assert "error" in err
        helps = AdminSocket.request(path, "help")
        assert "perf dump" in helps
    finally:
        sock.shutdown()


def test_admin_socket_error_plane(tmp_path):
    """The error plane: unknown command reply, malformed JSON,
    client disconnect mid-line, undecodable bytes — each must produce
    a clean reply or a counted serve-loop fault, and the loop must
    keep serving afterwards."""
    import socket as pysock

    path = str(tmp_path / "err.asok")
    sock = AdminSocket(path)
    sock.register("ping", lambda _a: {"pong": 1}, "ping")
    sock.start()
    try:
        # unknown command: structured error naming what DOES exist
        rep = AdminSocket.request(path, "bogus")
        assert "unknown command" in rep["error"]
        assert "ping" in rep["have"]

        # malformed JSON line: error reply, not a dead connection
        with pysock.socket(pysock.AF_UNIX,
                           pysock.SOCK_STREAM) as s:
            s.settimeout(5)
            s.connect(path)
            s.sendall(b"{not json\n")
            data = b""
            while not data.endswith(b"\n"):
                got = s.recv(65536)
                if not got:
                    break
                data += got
        assert "error" in json.loads(data.decode())

        # undecodable bytes kill that connection's handling inside
        # the serve loop: errors/last_error populate, loop survives
        assert sock.errors == 0 and sock.last_error is None
        with pysock.socket(pysock.AF_UNIX,
                           pysock.SOCK_STREAM) as s2:
            s2.settimeout(5)
            s2.connect(path)
            s2.sendall(b"\xff\xfe\n")
            try:
                s2.recv(65536)
            except OSError:
                pass
        deadline = time.monotonic() + 5
        while sock.errors == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sock.errors >= 1
        assert "UnicodeDecodeError" in sock.last_error

        # client disconnect mid-line: the serve loop must survive it
        # (the truncated request may fault when the reply hits the
        # closed socket — counted, never fatal)
        s = pysock.socket(pysock.AF_UNIX, pysock.SOCK_STREAM)
        s.connect(path)
        s.sendall(b'{"prefix": "pi')
        s.close()

        # after every abuse above, a normal request still round-trips
        assert AdminSocket.request(path, "ping") == {"pong": 1}
    finally:
        sock.shutdown()


def test_context_wires_everything(tmp_path):
    ctx = Context("testd", admin_dir=str(tmp_path))
    log = ctx.logger("crush")
    ctx.conf.set("debug_crush", 7)  # observer drives the level live
    assert ctx.log.get_level("crush") == 7
    ctx.start_admin_socket()
    try:
        out = AdminSocket.request(ctx.admin_socket_path, "config get",
                                  key="debug_crush")
        assert out == {"debug_crush": 7}
    finally:
        ctx.shutdown()


# -- throttle ---------------------------------------------------------------

def test_throttle_blocks_and_releases():
    th = Throttle("backfill", 2)
    assert th.get_or_fail() and th.get_or_fail()
    assert not th.get_or_fail()
    assert not th.get(timeout=0.05)

    done = []

    def waiter():
        done.append(th.get(timeout=2))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    th.put()
    t.join()
    assert done == [True]
    assert th.get_current() == 2
