"""Jerasure-plugin tests — mirrors the reference's per-technique suite.

Reference model: src/test/erasure-code/TestErasureCodeJerasure.cc
(encode/decode round-trips per technique through the ErasureCode
interface), TestErasureCode.cc (base-class semantics: encode_prepare
padding, chunk mapping, minimum_to_decode), plus chunk-size/alignment
arithmetic vs ErasureCodeJerasure.cc:80-104.  Parity bytes are pinned by
committed golden vectors (tests/golden/ec_parity.json) so refactors
cannot silently change on-wire data.
"""

import hashlib
import itertools
import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.ec.gfw import GFW, GF_POLY, gf2_mat_inv
from ceph_tpu.ec.interface import ErasureCode, ErasureCodeError
from ceph_tpu.ec.jerasure import TECHNIQUES, make_jerasure

GOLDEN = pathlib.Path(__file__).parent / "golden"

# every technique x a few (k, m, w) shapes; packetsize=8 keeps chunks
# small (TestErasureCodeJerasure.cc uses the same trick)
PROFILES = [
    {"technique": "reed_sol_van", "k": "2", "m": "2", "w": "8"},
    {"technique": "reed_sol_van", "k": "3", "m": "2", "w": "16"},
    {"technique": "reed_sol_van", "k": "4", "m": "3", "w": "32"},
    {"technique": "reed_sol_r6_op", "k": "4", "m": "2", "w": "8"},
    {"technique": "cauchy_orig", "k": "2", "m": "2", "w": "4",
     "packetsize": "8"},
    {"technique": "cauchy_orig", "k": "4", "m": "3", "w": "8",
     "packetsize": "8"},
    {"technique": "cauchy_good", "k": "4", "m": "3", "w": "8",
     "packetsize": "8"},
    {"technique": "liberation", "k": "2", "m": "2", "w": "7",
     "packetsize": "8"},
    {"technique": "blaum_roth", "k": "2", "m": "2", "w": "6",
     "packetsize": "8"},
    {"technique": "liber8tion", "k": "2", "m": "2", "w": "8",
     "packetsize": "8"},
]

_IDS = ["%s-k%s-m%s-w%s" % (p["technique"], p["k"], p["m"], p["w"])
        for p in PROFILES]


def _object_bytes(n=1537, seed=0xEC):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


@pytest.fixture(params=PROFILES, ids=_IDS)
def code(request):
    return make_jerasure(dict(request.param))


# -- GF(2^w) foundations ----------------------------------------------------

def test_gfw_primitive_small_w():
    """Every tabled w in 2..16 must use a PRIMITIVE polynomial: the
    exp cycle covers the whole multiplicative group."""
    for w in range(2, 17):
        g = GFW(w)
        n = (1 << w) - 1
        assert len({int(v) for v in g.exp[:n]}) == n, f"w={w}"


def test_gfw_field_axioms_large_w():
    for w in (17, 19, 24, 29, 31, 32):
        g = GFW(w)
        mask = (1 << w) - 1
        for a in (1, 2, 0x12345 & mask, mask - 1):
            assert g.mul(a, g.inv(a)) == 1
        a, b, c = 0x1234 & mask, 0xBEEF & mask, 0x7F & mask
        assert g.mul(a, b ^ c) == g.mul(a, b) ^ g.mul(a, c)
        assert g.mul(a, g.mul(b, c)) == g.mul(g.mul(a, b), c)


def test_gfw_poly_table_complete():
    assert set(GF_POLY) == set(range(2, 33))


def test_gf2_mat_inv_roundtrip():
    rng = np.random.default_rng(1)
    for n in (4, 16, 33):
        while True:
            M = rng.integers(0, 2, (n, n)).astype(np.uint8)
            try:
                inv = gf2_mat_inv(M)
                break
            except np.linalg.LinAlgError:
                continue
        assert np.array_equal((M.astype(int) @ inv.astype(int)) % 2,
                              np.eye(n, dtype=int))


# -- interface / base-class semantics (TestErasureCode.cc) ------------------

def test_encode_prepare_pads(code):
    raw = _object_bytes(1000)
    data = code.encode_prepare(raw)
    k = code.get_data_chunk_count()
    cs = code.get_chunk_size(len(raw))
    assert data.shape == (k, cs)
    flat = data.reshape(-1)
    assert flat[:1000].tobytes() == raw
    assert not flat[1000:].any()


def test_chunk_size_math(code):
    """get_chunk_size mirrors ErasureCodeJerasure.cc:80-104: aligned,
    and k*chunk_size >= object_size."""
    k = code.get_data_chunk_count()
    align = code.get_alignment()
    for size in (1, 511, 1537, 4096):
        cs = code.get_chunk_size(size)
        assert cs * k >= size
        assert (cs * k) % align == 0


def test_roundtrip_no_erasure(code):
    raw = _object_bytes()
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    assert set(chunks) == set(range(n))
    got = code.decode_concat(chunks)
    assert got[:len(raw)] == raw


def test_all_erasure_combinations(code):
    """Exhaustive <= m erasure sweep — the TestErasureCodeShec_all /
    ceph_erasure_code_benchmark --erasures-generation exhaustive
    discipline applied to every technique."""
    raw = _object_bytes(769)
    k, n = code.get_data_chunk_count(), code.get_chunk_count()
    m = n - k
    chunks = code.encode(range(n), raw)
    for r in range(1, m + 1):
        for erased in itertools.combinations(range(n), r):
            avail = {i: c for i, c in chunks.items() if i not in erased}
            got = code.decode_concat(avail)
            assert got[:len(raw)] == raw, f"erased={erased}"


def test_decode_reconstructs_parity(code):
    """decode() must also rebuild wanted PARITY chunks."""
    raw = _object_bytes(512)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    lost = n - 1  # last parity chunk
    avail = {i: c for i, c in chunks.items() if i != lost}
    out = code.decode({lost}, avail)
    assert np.array_equal(np.asarray(out[lost]),
                          np.asarray(chunks[lost]))


def test_minimum_to_decode(code):
    k, n = code.get_data_chunk_count(), code.get_chunk_count()
    want = set(range(k))
    # all present: exactly the wanted set
    got = code.minimum_to_decode(want, set(range(n)))
    assert set(got) == want
    # one wanted missing: k chunks, none of them the missing one
    avail = set(range(n)) - {0}
    got = code.minimum_to_decode(want, avail)
    assert len(got) == k and 0 not in got
    # not enough: raises
    with pytest.raises(ErasureCodeError):
        code.minimum_to_decode(want, set(range(k - 1)))


def test_chunk_mapping_remap():
    """profile mapping=_DD: data chunks land on the 'D' positions
    (ErasureCode.cc:260-279 parameter example)."""
    code = make_jerasure({"technique": "reed_sol_van", "k": "2",
                          "m": "1", "w": "8", "mapping": "_DD"})
    assert code.get_chunk_mapping() == [1, 2, 0]
    raw = _object_bytes(256)
    chunks = code.encode(range(3), raw)
    cs = code.get_chunk_size(len(raw))
    flat = np.zeros(2 * cs, np.uint8)
    flat[:256] = np.frombuffer(raw, np.uint8)
    assert np.array_equal(chunks[1], flat[:cs])      # data 0 -> pos 1
    assert np.array_equal(chunks[2], flat[cs:])      # data 1 -> pos 2
    got = code.decode_concat({1: chunks[1], 2: chunks[2]})
    assert got[:256] == raw


def test_profile_validation():
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "nope"})
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "reed_sol_van", "k": "1", "m": "1"})
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "reed_sol_van", "k": "2", "m": "1",
                       "w": "9"})
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "liberation", "k": "2", "m": "2",
                       "w": "6", "packetsize": "8"})  # w not prime
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "liber8tion", "k": "2", "m": "2",
                       "w": "7", "packetsize": "8"})  # w must be 8
    with pytest.raises(ErasureCodeError):
        make_jerasure({"technique": "reed_sol_r6_op", "k": "2",
                       "m": "3", "w": "8"})  # m must be 2


def test_technique_registry_complete():
    assert set(TECHNIQUES) == {
        "reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
        "liberation", "blaum_roth", "liber8tion"}


def test_cauchy_small_w():
    """cauchy supports any w (reference jerasure cauchy.c); w=4 was
    rejected before GFW grew the full 2..32 domain."""
    code = make_jerasure({"technique": "cauchy_orig", "k": "3",
                          "m": "2", "w": "5", "packetsize": "4"})
    raw = _object_bytes(300)
    chunks = code.encode(range(5), raw)
    avail = {i: c for i, c in chunks.items() if i not in (0, 3)}
    assert code.decode_concat(avail)[:300] == raw


def test_chunk_mapping_decode_with_erasure():
    """mapping= must be honored SYMMETRICALLY: decoding an erased data
    chunk through a non-identity layout returns the right bytes (the
    decode side used to skip the remap and solve a garbage system)."""
    for plugin, profile in (
            ("jerasure", {"technique": "reed_sol_van", "k": "2",
                          "m": "2", "w": "8", "mapping": "_DD_"}),
            ("shec", {"k": "2", "m": "2", "c": "1",
                      "mapping": "_DD_"})):
        from ceph_tpu.ec.registry import factory

        code = factory(plugin, profile)
        raw = _object_bytes(512, seed=5)
        chunks = code.encode(range(4), raw)
        for erased in range(4):
            avail = {i: c for i, c in chunks.items() if i != erased}
            got = code.decode_concat(avail)
            assert got[:len(raw)] == raw, (plugin, erased)
            out = code.decode({erased}, avail)
            assert np.array_equal(np.asarray(out[erased]),
                                  np.asarray(chunks[erased])), \
                (plugin, erased)


# -- golden parity pinning --------------------------------------------------

def test_golden_parity():
    g = json.load(open(GOLDEN / "ec_parity.json"))
    raw = _object_bytes(g["object_size"])
    assert hashlib.sha256(raw).hexdigest() == g["object_sha256"]
    for case in g["cases"]:
        code = make_jerasure(dict(case["profile"]))
        chunks = code.encode(range(code.get_chunk_count()), raw)
        assert chunks[0].shape[0] == case["chunk_size"], case["profile"]
        for i_str, want in case["chunk_sha256"].items():
            got = hashlib.sha256(
                np.asarray(chunks[int(i_str)], np.uint8).tobytes()
            ).hexdigest()
            assert got == want, (case["profile"], i_str)
