"""The continuous stats plane, end to end.

Acceptance drill: a MiniCluster write burst followed by killing one
OSD must yield (1) a `pool-stats` series showing nonzero client write
B/s and then recovery B/s, (2) a `progress` event that starts on the
failure and completes with fraction 1.0, (3) health transitioning
HEALTH_WARN(PG_DEGRADED) -> HEALTH_OK, and (4) a
`dump_metrics_history` ring on every daemon with >= 3 samples whose
derived rates are consistent with the counter deltas.  Plus the
satellites: pg_stats staleness (STALE_PG_STATS + aging), bench stage
SLO blocks, and the perf_history trajectory."""

import glob
import json
import os
import time

import pytest

from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.config import Config
from ceph_tpu.services.cluster import MiniCluster


def _fast_conf(**extra):
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.0)
    conf.set("mon_osd_down_out_interval", 1.0)
    conf.set("osd_pg_stat_report_interval", 0.2)
    conf.set("metrics_history_interval", 0.2)
    conf.set("osd_scrub_interval", 0.0)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


# -- the acceptance drill ---------------------------------------------------

def test_write_burst_failure_recovery_stats_plane():
    cl = MiniCluster(n_osds=4, config=_fast_conf()).start()
    try:
        cl.create_replicated_pool(1, pg_num=8, size=2)
        c = cl.client("burst")
        for i in range(24):
            c.put(1, f"obj-{i}", b"x" * 65536)
        time.sleep(0.5)

        # (1a) the pool-stats series saw the client write burst
        series = cl.pool_stats(1)["pools"]["1"]["series"]
        assert len(series) >= 2
        assert max(r["wr_bps"] for r in series) > 0
        assert max(r["wr_ops_s"] for r in series) > 0

        # failure: kill one OSD, then watch the plane tell the story
        victim = cl.status()["up_osds"][-1]
        t_kill = time.time()
        cl.kill_osd(victim)

        # (3a) HEALTH_WARN with the PG_DEGRADED check
        deadline = time.monotonic() + 30
        saw_degraded = False
        while time.monotonic() < deadline and not saw_degraded:
            h = cl.health()
            saw_degraded = (h["status"] == "HEALTH_WARN"
                            and "PG_DEGRADED" in h["check_codes"])
            time.sleep(0.05)
        assert saw_degraded, "no HEALTH_WARN(PG_DEGRADED) after kill"

        # (3b) ... transitioning back to HEALTH_OK once recovered
        cl.wait_for_health_ok(timeout=60)

        # (2) a progress event that started on the failure and
        # completed with fraction 1.0
        events = cl.progress()["events"]
        assert events, "no recovery progress event"
        ev = events[-1]
        assert ev["started_at"] >= t_kill - 1.0
        assert ev["done"] and ev["fraction"] == 1.0
        assert ev.get("ended_at", 0) >= ev["started_at"]

        # (1b) the series saw recovery traffic
        series = cl.pool_stats(1)["pools"]["1"]["series"]
        assert max(r["recovery_bps"] for r in series) > 0, \
            "recovery B/s never surfaced in pool-stats"

        # (4) every daemon's metrics-history ring: >= 3 samples, and
        # the derived rates are exactly consistent with the counter
        # deltas in the samples they were derived from
        socks = sorted(glob.glob(os.path.join(cl.asok_dir,
                                              "*.asok")))
        assert len(socks) >= 5  # mon + 3 live osds + client
        for path in socks:
            hist = AdminSocket.request(path, "dump_metrics_history")
            assert hist["n"] >= 3, \
                f"{os.path.basename(path)}: ring has {hist['n']} " \
                f"samples"
            assert hist["rates"], "no counter ever moved?"
            _check_rates_consistent(hist)
    finally:
        cl.shutdown()


def test_cli_pool_stats_progress_top(capsys):
    """The operator surface: `ceph_cli pool-stats` / `progress`
    against the monitor, `top` / `history` against the asok dir."""
    from ceph_tpu.tools.ceph_cli import main as ceph_main

    cl = MiniCluster(n_osds=2, config=_fast_conf()).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=2)
        c = cl.client("cli")
        for i in range(4):
            c.put(1, f"cli-{i}", b"z" * 4096)
        time.sleep(0.6)
        mon = f"{cl.mon.addr[0]}:{cl.mon.addr[1]}"
        assert ceph_main(["--mon", mon, "pool-stats", "1"]) == 0
        out = capsys.readouterr().out
        assert "pool 1:" in out and "wr " in out
        assert ceph_main(["--mon", mon, "progress"]) == 0
        out = capsys.readouterr().out
        assert "progress" in out or "recovery" in out
        assert ceph_main(["--asok-dir", cl.asok_dir, "top",
                          "--interval", "0.2", "--count", "1"]) == 0
        out = capsys.readouterr().out
        assert "ceph-tpu top" in out and "daemon" in out
        assert ceph_main(["--asok-dir", cl.asok_dir,
                          "history"]) == 0
        out = capsys.readouterr().out
        assert "time" in out.splitlines()[0]
    finally:
        cl.shutdown()


def _flatten(perf):
    out = {}
    for logger, counters in perf.items():
        for key, val in counters.items():
            if isinstance(val, (int, float)):
                out[f"{logger}.{key}"] = float(val)
    return out


def _check_rates_consistent(hist):
    """Each reported rate must equal the clamped counter delta over
    the monotonic interval of its sample pair."""
    samples = hist["samples"]
    flats = [_flatten(s["perf"]) for s in samples]
    checked = 0
    for key, points in hist["rates"].items():
        # points align with consecutive sample pairs where the
        # counter exists on both sides
        idx = 0
        for (a, fa), (b, fb) in zip(zip(samples, flats),
                                    zip(samples[1:], flats[1:])):
            if key not in fa or key not in fb:
                continue
            want = max(0.0, (fb[key] - fa[key])
                       / max(1e-9, b["mono"] - a["mono"]))
            got = points[idx]["rate"]
            assert got == pytest.approx(want, rel=1e-6, abs=1e-9), \
                f"{key}: rate {got} != delta/dt {want}"
            idx += 1
            checked += 1
        assert idx == len(points)
    assert checked > 0


# -- satellite: pg_stats staleness ------------------------------------------

def test_pg_stats_go_stale_and_age_out():
    """Down an OSD whose PGs have no surviving holder: its PGs'
    stats must go STALE (health check) and then age out entirely
    instead of poisoning the PGMap forever."""
    conf = _fast_conf(mon_pg_stats_stale_grace=1.5,
                      # keep the dead osd "in": a remap would elect a
                      # new (empty) primary whose fresh reports would
                      # mask the staleness under test
                      mon_osd_down_out_interval=3600.0)
    cl = MiniCluster(n_osds=2, config=conf).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=1)
        c = cl.client("w")
        for i in range(4):
            c.put(1, f"s-{i}", b"y" * 1024)
        # every PG reported by its (single) holder
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            pg = cl.status()["pgmap"]
            if pg["pgs_reported"] == pg["pgs_total"]:
                break
            time.sleep(0.1)
        assert cl.status()["pgmap"]["pgs_reported"] == 4

        victim = cl.status()["up_osds"][0]
        cl.kill_osd(victim)

        # STALE_PG_STATS surfaces after the grace
        deadline = time.monotonic() + 20
        saw_stale = False
        while time.monotonic() < deadline and not saw_stale:
            h = cl.health()
            saw_stale = "STALE_PG_STATS" in h.get("check_codes", [])
            time.sleep(0.1)
        assert saw_stale, "STALE_PG_STATS never fired"

        # ... and the entries age out (4x grace), shrinking
        # pgs_reported instead of keeping dead state forever
        deadline = time.monotonic() + 30
        aged = False
        while time.monotonic() < deadline and not aged:
            pg = cl.status()["pgmap"]
            aged = pg["pgs_reported"] < 4
            time.sleep(0.2)
        assert aged, "stale pg_stats entries never aged out"
    finally:
        cl.shutdown()


# -- satellite: bench SLO blocks --------------------------------------------

def test_bench_stage_emits_slo_and_counter_deltas(capsys):
    """Every bench stage JSON carries an SLO block and the counter
    deltas booked during the stage (the device-plane story)."""
    import bench

    bench._stage_ec_batch("cpu", k=2, m=1, n_stripes=4, chunk=512,
                          iters=2)
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines()
             if ln.startswith(bench.RESULT_TAG)]
    assert lines
    r = json.loads(lines[0][len(bench.RESULT_TAG):])
    slo = r["slo"]
    assert slo["metric"] == "ec_batch_speedup"
    assert "floor" in slo and isinstance(slo["pass"], bool)
    assert any(k.startswith("ec.engine.") for k in r["counters"])
    assert any(k.startswith("device.") for k in r["counters"])


def test_bench_slo_block_semantics():
    import bench

    ok = bench._slo("cluster_write_iops", 500.0, p99_ms=12.5)
    assert ok["pass"] is True and ok["p99_ms"] == 12.5
    bad = bench._slo("cluster_write_iops", 3.0)
    assert bad["pass"] is False
    unfloored = bench._slo("some_unfloored_metric", 1.0)
    assert "pass" not in unfloored


# -- satellite: perf_history trajectory -------------------------------------

def test_perf_history_renders_repo_trajectory():
    """The committed BENCH_r01..rNN series renders as a trajectory
    table with per-metric deltas."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve()
                           .parent.parent))
    from tools import perf_history

    repo = pathlib.Path(__file__).resolve().parent.parent
    rows = perf_history.load_all(str(repo))
    assert len(rows) >= 5, "BENCH_r*.json series missing"
    perf_history.compute_deltas(rows)
    by_run = {r["run"]: r for r in rows}
    # r05 recorded the measured trajectory numbers
    assert by_run["r05"]["metrics"]["crush_mappings_s"] > 0
    assert "crush_mappings_s" in by_run["r05"]["deltas"]
    table = perf_history.render(rows)
    assert "r05" in table and "crush_mappings_s" in table
    for row in rows:
        assert isinstance(row["regressions"], list)


def test_perf_history_regression_check(tmp_path):
    """A throughput drop beyond the threshold in the latest run is a
    red check (exit 1); a healthy series passes."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve()
                           .parent.parent))
    from tools import perf_history

    def write_run(n, rate, tail=""):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "bench", "rc": 0, "tail": tail,
            "parsed": {"metric": "crush_mappings_per_sec",
                       "value": rate, "platform": "cpu",
                       "vs_baseline": rate / 85099.6}}))

    write_run(1, 100000.0,
              tail="# cluster 4-osd: write 500.0 IOPS; "
                   "seq 1000.0 IOPS")
    write_run(2, 101000.0,
              tail="# cluster 4-osd: write 520.0 IOPS; "
                   "seq 990.0 IOPS")
    assert perf_history.main([str(tmp_path), "--check"]) == 0
    # now a 60% crush regression in the latest run
    write_run(3, 40000.0)
    assert perf_history.main([str(tmp_path), "--check"]) == 1
    rows = perf_history.load_all(str(tmp_path))
    perf_history.compute_deltas(rows)
    assert rows[-1]["regressions"]
    # a bench-recorded failing SLO block is a regression by itself
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "n": 4, "cmd": "bench", "rc": 0,
        "tail": "# slo cluster_write_iops: value 50 floor 100 -> "
                "FAIL",
        "parsed": {"value": 100000.0, "platform": "cpu",
                   "slo": {"metric": "crush_big10k_mappings_per_sec",
                           "value": 100000.0, "floor": 80000,
                           "pass": True}}}))
    assert perf_history.main([str(tmp_path), "--check"]) == 1


def test_perf_history_zero_copy_goal_gate(tmp_path):
    """copy_bytes_per_op is gated absolutely from r14 on: a run above
    0.6x the r13 baseline (191,330 -> goal 114,798) red-checks even
    when the run-over-run delta stays inside the drift threshold."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve()
                           .parent.parent))
    from tools import perf_history

    def write_run(n, bpo):
        cl = json.dumps({"copy": {"bytes_per_op": bpo}})
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({
            "n": n, "cmd": "bench", "rc": 0,
            "tail": "# cluster json: " + cl,
            "parsed": {"value": 100000.0, "platform": "cpu"}}))

    write_run(13, 191330.0)  # the baseline run itself is not gated
    write_run(14, 110000.0)  # under the goal: ok
    assert perf_history.main([str(tmp_path), "--check"]) == 0
    write_run(14, 120000.0)  # a 37% cut, but above the 114,798 goal
    assert perf_history.main([str(tmp_path), "--check"]) == 1
    rows = perf_history.load_all(str(tmp_path))
    perf_history.compute_deltas(rows)
    assert any("zero-copy goal" in r
               for r in rows[-1]["regressions"])


# -- telemetry history/top views --------------------------------------------

def _hist_sample(ts, mono, bytes_out):
    return {"ts": ts, "mono": mono,
            "perf": {"msgr.osd.0": {"bytes_out": bytes_out,
                                    "bytes_in": 0}},
            "shapes": {}}


def test_history_view_time_aligned_merge():
    from ceph_tpu.tools import telemetry

    histories = {
        "osd.0": {"samples": [_hist_sample(100.0, 10.0, 0),
                              _hist_sample(101.0, 11.0, 1000),
                              _hist_sample(102.0, 12.0, 3000)]},
        "osd.1": {"samples": [_hist_sample(100.1, 20.0, 0),
                              _hist_sample(101.1, 21.0, 500)]},
    }
    view = telemetry.history_view(histories)
    lines = view.splitlines()
    assert "tx_B/s" in lines[0]
    assert len(lines) >= 3  # header + >=2 time buckets
    col = lines[0].split().index("tx_B/s")
    rates = [float(ln.split()[col]) for ln in lines[1:]]
    # bucket at ~101s sums osd.0 (1000/s) + osd.1 (500/s); the 102s
    # bucket is osd.0 alone at 2000/s
    assert 1500.0 in rates and 2000.0 in rates


def test_top_view_frame():
    from ceph_tpu.tools import telemetry

    prev = {"ts": 100.0, "daemons": {
        "osd.0": {"perf": {"msgr.osd.0": {"bytes_out": 0}},
                  "ops_in_flight": {"num_ops": 1}}},
        "unreachable": []}
    cur = {"ts": 101.0, "daemons": {
        "osd.0": {"perf": {"msgr.osd.0": {"bytes_out": 2000}},
                  "ops_in_flight": {"num_ops": 3}}},
        "unreachable": ["osd.9"]}
    frame = telemetry.top_view(prev, cur)
    assert "ops in flight: 3" in frame
    assert "unreachable: 1" in frame
    assert "osd.0" in frame
