"""Saturation & SLO plane (PR 17): messenger backpressure books,
SLOW_OPS health escalation, and heartbeat ping-time health.

Units first (OpTracker.slow_summary, the heartbeat RTT window math,
the telemetry net roll-up), then the acceptance drill: a MiniCluster
under write load with ONE throttled OSD must show nonzero send-stall
on that daemon only (dump_messenger over the admin socket), a
SLOW_OPS health check naming it that clears back to HEALTH_OK when
the stall is healed, and OSD_SLOW_PING_TIME with the slow peer worst
first in dump_osd_network.
"""

import os
import threading
import time

import pytest

from ceph_tpu.common.admin_socket import AdminSocket
from ceph_tpu.common.config import Config
from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.services.cluster import MiniCluster
from ceph_tpu.services.heartbeat import _Peer
from ceph_tpu.tools import telemetry


# -- unit: the OpTracker slow-op summary ------------------------------

def test_slow_summary_counts_aged_inflight_ops():
    t = OpTracker(history_slow_threshold=0.05)
    assert t.slow_summary() == {"count": 0, "oldest_age": 0.0,
                                "threshold": 0.05}
    with t.create("osd_op", "young"):
        with t.create("osd_op", "old"):
            time.sleep(0.08)
            s = t.slow_summary()
            # both ops are in flight and both are past the threshold
            assert s["count"] == 2
            assert s["oldest_age"] >= 0.08
            assert s["threshold"] == 0.05
    # completed ops leave the in-flight summary (they live on in the
    # historic-slow ring, which is dump_historic_slow_ops' concern)
    assert t.slow_summary()["count"] == 0


def test_slow_threshold_rides_config_knob():
    """Satellite 1: osd_op_complaint_time IS the tracker threshold —
    one knob for dump_historic_slow_ops and the SLOW_OPS beacon."""
    conf = Config()
    assert conf["osd_op_complaint_time"] == \
        OpTracker().slow_threshold == 0.5


# -- unit: heartbeat RTT windows --------------------------------------

def test_peer_window_averages_age_out():
    now = 10_000.0
    p = _Peer(now)
    p.rtts.append((now - 500.0, 0.400))   # only the 15min window
    p.rtts.append((now - 120.0, 0.100))   # 5min + 15min
    p.rtts.append((now - 10.0, 0.020))    # all three
    avgs = p.window_avgs_ms(now)
    assert avgs["1min"] == pytest.approx(20.0)
    assert avgs["5min"] == pytest.approx(60.0)    # (100+20)/2 ms
    assert avgs["15min"] == pytest.approx(1e3 * 0.52 / 3,
                                          abs=1e-3)
    # an empty ring reads 0.0, not NaN
    assert _Peer(now).window_avgs_ms(now) == \
        {"1min": 0.0, "5min": 0.0, "15min": 0.0}


# -- unit: the telemetry net roll-up ----------------------------------

def _msgr_perf(stall_s, wait_buckets, lat_buckets, ctl_buckets):
    return {"msgr.osd.0": {
        "send_stall_time": stall_s,
        "send_stalls": 1,
        "dispatch_wait_data": {"buckets": wait_buckets,
                               "min": 1e-6},
        "dispatch_lat_data": {"buckets": lat_buckets, "min": 1e-6},
        "dispatch_lat_ctl": {"buckets": ctl_buckets, "min": 1e-6},
    }}


def test_net_summary_shares_p99_and_slow_peers():
    cur = {"ts": 10.0, "unreachable": [], "daemons": {
        "osd.0": {"perf": _msgr_perf(2.0, [0, 100], [0, 100],
                                     [50]),
                  "network": {"entries": [
                      {"peer": 1, "worst_ms": 80.0},
                      {"peer": 2, "worst_ms": 15.0}]}},
        "osd.1": {"perf": _msgr_perf(0.0, [100], [100], [0])},
    }}
    s = telemetry.net_summary(cur, dt=10.0)
    assert s["dt_s"] == 10.0
    assert s["send_stall_s"] == pytest.approx(2.0)
    # normalized per daemon: 2 stalled seconds / (10s * 2 daemons)
    assert s["send_stall_share"] == pytest.approx(0.1)
    d0 = s["per_daemon"]["osd.0"]
    assert d0["send_stall_share"] == pytest.approx(0.2)
    assert d0["dispatch_wait_p99_ms"] > 0
    assert d0["ctl_per_s"] == pytest.approx(5.0)
    assert d0["data_per_s"] == pytest.approx(10.0)
    # osd.1's ops all landed in bucket 0 (<= 1us): p99 is the bucket
    # edge, far below osd.0's bucket-1 edge
    assert s["per_daemon"]["osd.1"]["dispatch_p99_ms"] < \
        d0["dispatch_p99_ms"]
    # the heartbeat dump's entries surface worst first with the
    # observing daemon attributed
    assert [e["peer"] for e in s["slow_peers"]] == [1, 2]
    assert s["slow_peers"][0]["daemon"] == "osd.0"
    # and the rendered table carries the headline + the peer line
    view = telemetry.net_view(cur, dt=10.0)
    assert "stall%" in view and "osd.0" in view
    assert "slow heartbeat peers" in view


def test_hist_quantile_upper_edge():
    # 10 samples <= 1us, 0 in (1,2]us, 2 in (2,4]us: p50 is the
    # first bucket's edge, p99 the third's
    buckets = [10.0, 0.0, 2.0]
    assert telemetry.hist_quantile(buckets, 1e-6, 0.5) == \
        pytest.approx(1e-6)
    assert telemetry.hist_quantile(buckets, 1e-6, 0.99) == \
        pytest.approx(4e-6)
    assert telemetry.hist_quantile([], 1e-6, 0.99) == 0.0
    assert telemetry.hist_quantile([0.0, 0.0], 1e-6, 0.99) == 0.0


# -- acceptance: the load-stall drill ---------------------------------

def test_saturation_drill_slow_ops_raise_and_clear():
    """ONE throttled OSD under cluster write load: its messenger
    books the stall, the monitor raises SLOW_OPS naming it and
    OSD_SLOW_PING_TIME for its ping lag, dump_osd_network lists the
    slow peer worst first — and everything clears to HEALTH_OK once
    the throttle lifts."""
    conf = Config()
    conf.set("osd_op_complaint_time", 0.2)
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_ping_threshold_ms", 20.0)
    cluster = MiniCluster(n_osds=3, config=conf).start()
    try:
        cluster.create_replicated_pool(1, pg_num=8, size=3)
        cluster.wait_for_health_ok()
        c = cluster.client("satdrill")
        stop = threading.Event()

        def _writes():
            i = 0
            while not stop.is_set():
                try:
                    c.put(1, f"sat-{i % 16}", b"s" * 4096)
                except Exception:
                    time.sleep(0.05)
                i += 1

        writer = threading.Thread(target=_writes, daemon=True)
        writer.start()
        # osd.1 is the saturated daemon: every op sleeps past the
        # complaint time, every frame it SENDS drags 40ms (so its
        # ping replies and its own pings both carry the lag)
        cluster.set_faults(
            "osd.slow_op=p:1.0,delay:0.5,who:osd.1;"
            "msgr.delay_frame=p:1.0,delay:0.04,who:osd.1")
        try:
            deadline = time.monotonic() + 30.0
            seen = set()
            while time.monotonic() < deadline:
                h = cluster.health()
                seen = set(h.get("check_codes", []))
                if {"SLOW_OPS", "OSD_SLOW_PING_TIME"} <= seen:
                    break
                time.sleep(0.3)
            assert {"SLOW_OPS", "OSD_SLOW_PING_TIME"} <= seen, seen
            checks = {ck.split(":", 1)[0]: ck
                      for ck in h.get("checks", [])}
            # per-daemon attribution: the check names the throttled
            # daemon, not just a count
            assert "osd.1" in checks["SLOW_OPS"]
            assert "slow ops" in checks["SLOW_OPS"]
            assert "ms" in checks["OSD_SLOW_PING_TIME"]

            # dump_messenger (admin socket): the stall books on the
            # throttled daemon's messenger, not on a healthy one's
            dm1 = AdminSocket.request(
                os.path.join(cluster.asok_dir, "osd.1.asok"),
                "dump_messenger")
            dm0 = AdminSocket.request(
                os.path.join(cluster.asok_dir, "osd.0.asok"),
                "dump_messenger")
            s1 = dm1["totals"]["send_stall_s"]
            s0 = dm0["totals"]["send_stall_s"]
            assert s1 > 0.05, dm1["totals"]
            assert s1 > 2 * s0, (s1, s0)
            # connections come worst first and carry the lane books
            assert dm1["connections"], dm1
            assert dm1["connections"][0]["send_stall_s"] >= \
                dm1["connections"][-1]["send_stall_s"]

            # the cluster net roll-up sees the same skew, and the
            # throttled daemon's dispatch-wait p99 is live
            snap = telemetry.cluster_snapshot(cluster.asok_dir)
            net = telemetry.net_summary(snap, dt=5.0)
            per = net["per_daemon"]
            assert per["osd.1"]["send_stall_s"] > \
                2 * per["osd.0"]["send_stall_s"]
            assert per["osd.1"]["dispatch_wait_p99_ms"] > 0
            assert any(e["peer"] == 1 for e in net["slow_peers"])

            # dump_osd_network from a HEALTHY daemon: the throttled
            # peer breaches the threshold and sorts worst first
            dn = AdminSocket.request(
                os.path.join(cluster.asok_dir, "osd.0.asok"),
                "dump_osd_network")
            assert dn["threshold_ms"] == 20.0
            assert dn["entries"], dn
            assert dn["entries"][0]["peer"] == 1
            assert dn["entries"][0]["worst_ms"] >= 20.0
            assert {"1min", "5min", "15min"} <= \
                set(dn["entries"][0])
            # threshold 0 lists every peer, still worst first
            dn_all = AdminSocket.request(
                os.path.join(cluster.asok_dir, "osd.0.asok"),
                "dump_osd_network", threshold_ms=0)
            assert dn_all["total_peers"] == len(dn_all["entries"]) \
                == 2
            worsts = [e["worst_ms"] for e in dn_all["entries"]]
            assert worsts == sorted(worsts, reverse=True)
        finally:
            cluster.set_faults("")
            stop.set()
            writer.join(timeout=5.0)
        # heal: in-flight ops drain, RTT windows decay below the
        # threshold as fresh fast samples land, checks clear
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            h = cluster.health()
            if h.get("status") == "HEALTH_OK":
                break
            time.sleep(0.5)
        assert h.get("status") == "HEALTH_OK", h
        assert not h.get("check_codes")
    finally:
        cluster.shutdown()
