"""Pallas fused GF(2)-matmul kernel tests (interpret mode on CPU).

The kernel must be bit-identical to the engine's XLA path — same
unpack/matmul/pack semantics, one fused pass.  On real TPU the driver's
bench exercises the compiled path; here ``interpret=True`` runs the
identical kernel logic under the Pallas interpreter.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ceph_tpu.ec import gf
from ceph_tpu.ec.engine import (BitCode, Layout, _mod2_matmul,
                                _pack_bytes, _unpack_bytes)
from ceph_tpu.ec.pallas_kernels import fused_gf2_matmul_w8


def _xla_reference(bm, data):
    rows = _unpack_bytes(jnp.asarray(data))
    return np.asarray(_pack_bytes(_mod2_matmul(jnp.asarray(bm), rows)))


@pytest.mark.parametrize("k,m,L", [(4, 2, 512), (8, 3, 2048),
                                   (2, 1, 100), (5, 4, 513)])
def test_fused_matches_xla_encode(k, m, L):
    rng = np.random.default_rng(k * 100 + m)
    G = gf.rs_vandermonde_matrix(k, m)
    bm = gf.expand_bitmatrix(G[k:])
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    want = _xla_reference(bm, data)
    got = np.asarray(fused_gf2_matmul_w8(bm, data, interpret=True))
    assert np.array_equal(got, want)


def test_fused_decode_matrix():
    rng = np.random.default_rng(3)
    k, m, L = 6, 3, 777
    code = BitCode(k, m,
                   gf.expand_bitmatrix(gf.rs_vandermonde_matrix(k, m)[k:]),
                   Layout(8))
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    full = np.asarray(code.all_chunks(data))
    # decode matrix for survivors {2..7} (data 0,1 lost)
    present = tuple(range(2, 2 + k))
    (inv,) = code._decode_mats(present)
    stack = full[list(present)]
    want = _xla_reference(np.asarray(inv), stack)
    got = np.asarray(fused_gf2_matmul_w8(inv, stack, interpret=True))
    assert np.array_equal(got, want)
    assert np.array_equal(want, data)  # and it IS the decode
