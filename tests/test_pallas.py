"""Pallas fused GF(2)-matmul kernel tests (interpret mode on CPU).

The kernel must be bit-identical to the engine's XLA path — same
unpack/matmul/pack semantics, one fused pass.  On real TPU the driver's
bench exercises the compiled path; here ``interpret=True`` runs the
identical kernel logic under the Pallas interpreter.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ceph_tpu.ec import gf
from ceph_tpu.ec.engine import (BitCode, Layout, _mod2_matmul,
                                _pack_bytes, _unpack_bytes)
from ceph_tpu.ec.pallas_kernels import fused_gf2_matmul_w8


def _xla_reference(bm, data):
    rows = _unpack_bytes(jnp.asarray(data))
    return np.asarray(_pack_bytes(_mod2_matmul(jnp.asarray(bm), rows)))


@pytest.mark.parametrize("k,m,L", [(4, 2, 512), (8, 3, 2048),
                                   (2, 1, 100), (5, 4, 513)])
def test_fused_matches_xla_encode(k, m, L):
    rng = np.random.default_rng(k * 100 + m)
    G = gf.rs_vandermonde_matrix(k, m)
    bm = gf.expand_bitmatrix(G[k:])
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    want = _xla_reference(bm, data)
    got = np.asarray(fused_gf2_matmul_w8(bm, data, interpret=True))
    assert np.array_equal(got, want)


def test_fused_decode_matrix():
    rng = np.random.default_rng(3)
    k, m, L = 6, 3, 777
    code = BitCode(k, m,
                   gf.expand_bitmatrix(gf.rs_vandermonde_matrix(k, m)[k:]),
                   Layout(8))
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    full = np.asarray(code.all_chunks(data))
    # decode matrix for survivors {2..7} (data 0,1 lost)
    present = tuple(range(2, 2 + k))
    (inv,) = code._decode_mats(present)
    stack = full[list(present)]
    want = _xla_reference(np.asarray(inv), stack)
    got = np.asarray(fused_gf2_matmul_w8(inv, stack, interpret=True))
    assert np.array_equal(got, want)
    assert np.array_equal(want, data)  # and it IS the decode


# -- the registry-promoted 'pallas-fused' engine ----------------------
#
# engine=pallas-fused in a pool profile routes the plugin's BitCode
# through the fused kernel unconditionally (interpret mode on CPU).
# Parity is pinned byte-for-byte against the bit-plane engine over the
# golden-corpus profile grid's byte-layout (w=8 matrix) members — the
# same object/seed the ec_parity.json corpus uses.

# every byte-layout (w=8 matrix) profile of the corpus grid
# (tests/golden/_gen_ec_parity.py CONFIGS), plus the isa plugin's two
# techniques at the reference defaults
_W8_GRID = [
    ("jerasure", {"technique": "reed_sol_van", "k": "2", "m": "2",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "3",
                  "w": "8"}),
    ("jerasure", {"technique": "reed_sol_r6_op", "k": "4", "m": "2",
                  "w": "8"}),
    ("isa", {"technique": "reed_sol_van", "k": "7", "m": "3"}),
    ("isa", {"technique": "cauchy", "k": "4", "m": "2"}),
]

_OBJECT_SIZE = 1537  # corpus object: deliberately unaligned


def _grid_pair(plugin, prof):
    from ceph_tpu.ec.registry import factory

    fused = factory(plugin, dict(prof, engine="pallas-fused"))
    plain = factory(plugin, dict(prof, engine="bitplane"))
    assert fused._code.force_fused
    assert not plain._code.force_fused
    return fused, plain


@pytest.mark.parametrize("plugin,prof", _W8_GRID,
                         ids=[f"{p}-{c['technique']}-k{c['k']}m{c['m']}"
                              for p, c in _W8_GRID])
def test_pallas_engine_corpus_grid_encode_parity(plugin, prof):
    fused, plain = _grid_pair(plugin, prof)
    rng = np.random.default_rng(0xEC)
    raw = rng.integers(0, 256, _OBJECT_SIZE, dtype=np.uint8).tobytes()
    n = fused.get_chunk_count()
    a = fused.encode(range(n), raw)
    b = plain.encode(range(n), raw)
    for i in range(n):
        assert np.array_equal(np.asarray(a[i]), np.asarray(b[i])), \
            f"chunk {i} differs between pallas-fused and bit-plane"


@pytest.mark.parametrize("plugin,prof", _W8_GRID[:3],
                         ids=[f"{p}-{c['technique']}-k{c['k']}m{c['m']}"
                              for p, c in _W8_GRID[:3]])
def test_pallas_engine_corpus_grid_batched_parity(plugin, prof):
    fused, plain = _grid_pair(plugin, prof)
    k = fused.get_data_chunk_count()
    rng = np.random.default_rng(0xEC ^ k)
    B, L = 5, 512
    stripes = rng.integers(0, 256, (B, k, L), dtype=np.uint8)
    a = np.asarray(fused._code.encode_batched(stripes, mesh=None))
    b = np.asarray(plain._code.encode_batched(stripes, mesh=None))
    assert np.array_equal(a, b)
    # and batched == B independent per-stripe encodes
    for s in range(B):
        assert np.array_equal(
            a[s], np.asarray(fused._code.encode(stripes[s])))


def test_pallas_engine_mesh_parity():
    import jax

    from ceph_tpu.parallel.placement import make_mesh

    fused, plain = _grid_pair("jerasure",
                              {"technique": "reed_sol_van", "k": "4",
                               "m": "2", "w": "8"})
    rng = np.random.default_rng(7)
    stripes = rng.integers(0, 256, (6, 4, 512), dtype=np.uint8)
    want = np.asarray(plain._code.encode_batched(stripes, mesh=None))
    mesh = make_mesh(jax.devices(), axis_name="ec")
    got = np.asarray(fused._code.encode_batched_sharded(stripes, mesh))
    assert np.array_equal(got, want)


def test_pallas_engine_decode_roundtrip():
    fused, _plain = _grid_pair("jerasure",
                               {"technique": "reed_sol_van", "k": "4",
                                "m": "2", "w": "8"})
    rng = np.random.default_rng(11)
    raw = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    n = fused.get_chunk_count()
    chunks = fused.encode(range(n), raw)
    # lose one data + one parity chunk; recover through the fused
    # kernel's decode-matrix path
    have = {i: chunks[i] for i in range(n) if i not in (0, 4)}
    out = fused.decode(range(n), have, 0)
    for i in range(n):
        assert np.array_equal(np.asarray(out[i]),
                              np.asarray(chunks[i]))


def test_pallas_engine_recompile_budget():
    """Steady-state batched encodes at a FIXED shape through the
    fused engine must hit the jit cache — the recompile gate in
    conftest turns any violation into a failure, but assert locally
    too so this test names the contract."""
    from ceph_tpu.analysis import jaxcheck

    fused, _plain = _grid_pair("jerasure",
                               {"technique": "reed_sol_van", "k": "4",
                                "m": "2", "w": "8"})
    rng = np.random.default_rng(13)
    stripes = rng.integers(0, 256, (4, 4, 512), dtype=np.uint8)
    fused._code.encode_batched(stripes, mesh=None)  # warm
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("pallas-fused batched encode"):
        for _ in range(3):
            fused._code.encode_batched(stripes, mesh=None)
    assert jaxcheck.recompile_violations()[base:] == []


def test_engine_profile_key_validated():
    from ceph_tpu.ec.interface import ErasureCodeError
    from ceph_tpu.ec.registry import factory

    with pytest.raises(ErasureCodeError):
        factory("jerasure", {"technique": "reed_sol_van", "k": "2",
                             "m": "1", "w": "8", "engine": "cuda"})
    # fused engine is a byte-layout engine: w=16 and packet
    # techniques must reject it at profile parse, not fall back
    with pytest.raises(ErasureCodeError):
        factory("jerasure", {"technique": "reed_sol_van", "k": "3",
                             "m": "2", "w": "16",
                             "engine": "pallas-fused"})
    with pytest.raises(ErasureCodeError):
        factory("jerasure", {"technique": "cauchy_good", "k": "4",
                             "m": "2", "w": "8", "packetsize": "8",
                             "engine": "pallas-fused"})
