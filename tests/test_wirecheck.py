"""Wire-format conformance: every registered type passes the five
dencoder properties, the committed corpus byte-matches, and archived
older-version blobs keep decoding (the ceph-dencoder +
ceph-object-corpus + readable.sh roles in one gate)."""

import pathlib

import pytest

from ceph_tpu.analysis import wirecheck
from ceph_tpu.common.encoding import MalformedInput

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "tests" / "corpus" / "encodings"

ENTRIES = wirecheck.entries()
NAMES = [e.name for e in ENTRIES]


def _blob(entry) -> bytes:
    raw = entry.encode(entry.factory())
    return raw.encode() if isinstance(raw, str) else bytes(raw)


def test_registry_is_wide_enough():
    """The acceptance floor: >= 12 registered wire types covering
    every layer (messenger, auth, osdmap, crush, object store,
    services)."""
    assert len(ENTRIES) >= 12, NAMES
    prefixes = {n.split(".")[0] for n in NAMES}
    assert {"msg", "osdmap", "crush", "os", "osd", "rbd",
            "mon"} <= prefixes


@pytest.mark.parametrize("name", NAMES)
def test_conformance_properties(name):
    """Round-trip, determinism, forward-compat, compat-floor refusal,
    mutation robustness — all five, per type."""
    fails = wirecheck.check(wirecheck.get(name))
    assert not fails, "\n".join(fails)


@pytest.mark.parametrize("name", NAMES)
def test_corpus_byte_compare(name):
    """The committed golden blob at the CURRENT struct_v is
    byte-identical to a fresh encode — cross-PR determinism."""
    e = wirecheck.get(name)
    p = CORPUS / e.name / str(e.struct_v) / "example.bin"
    assert p.exists(), (
        f"no committed corpus blob for {e.name} v{e.struct_v}; run "
        f"tests/golden/_gen_wire_corpus.py --write and commit")
    assert p.read_bytes() == _blob(e), (
        f"{e.name}: encoding diverged from the committed corpus "
        f"without a struct_v bump (see tests/corpus/encodings/"
        f"README.md)")


def _archived():
    out = []
    for e in ENTRIES:
        tdir = CORPUS / e.name
        if not tdir.is_dir():
            continue
        for vdir in sorted(tdir.iterdir()):
            if not vdir.is_dir() or int(vdir.name) >= e.struct_v:
                continue
            for blob in sorted(vdir.glob("*.bin")):
                out.append((e.name, int(vdir.name), blob))
    return out


@pytest.mark.parametrize(
    "name,writer_v,path",
    _archived(),
    ids=[f"{n}-v{v}" for n, v, _p in _archived()])
def test_archived_blobs_still_decode(name, writer_v, path):
    """readable.sh: a blob written at any committed older version
    (including the pre-envelope v0 era for migrated formats) must
    decode with today's code."""
    e = wirecheck.get(name)
    got = e.decode(path.read_bytes())
    assert got is not None


def test_archived_coverage_exists():
    """At least the formats migrated in this PR must carry archived
    witnesses — deleting them would silently drop the back-compat
    proof."""
    have = {(n, v) for n, v, _p in _archived()}
    for want in (("osdmap.incremental", 1), ("rbd.image_header", 0),
                 ("os.memstore_export", 0), ("osd.pg_log_entry", 0),
                 ("mon.epoch_payload", 0), ("crush.map_json", 0),
                 ("msg.auth.ticket", 0)):
        assert want in have, f"archived corpus blob missing: {want}"


def test_corpus_freshness_gate():
    """The check-generated.sh role: the generator's --check mode
    agrees the committed corpus matches the code."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_gen_wire_corpus",
        REPO / "tests" / "golden" / "_gen_wire_corpus.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []


def test_compat_floor_error_names_struct_and_versions():
    """Satellite: refusal messages carry WHICH structure and both
    versions — 'structure requires decoder v2' with no name is not
    actionable."""
    from ceph_tpu.osdmap.incremental import Incremental

    e = wirecheck.get("osdmap.incremental")
    blob = e.forge_compat(_blob(e))
    with pytest.raises(MalformedInput) as ei:
        Incremental.decode_versioned(blob)
    msg = str(ei.value)
    assert "Incremental" in msg
    assert f"v{Incremental.STRUCT_V + 1}" in msg  # writer's demand
    assert f"v{Incremental.STRUCT_V}" in msg      # reader's ceiling


def test_bincode_compat_floor_names_struct():
    from ceph_tpu.common.bincode import DecodeError
    from ceph_tpu.osdmap.bincode_maps import osdmap_from_bytes

    e = wirecheck.get("osdmap.full")
    with pytest.raises(DecodeError) as ei:
        osdmap_from_bytes(e.forge_compat(_blob(e)))
    assert "osdmap.full" in str(ei.value)


# ---------------------------------------------------------------------------
# messenger compression-bomb guard (satellite)
# ---------------------------------------------------------------------------

def _bomb_frame(plain_size: int) -> bytes:
    import struct
    import zlib

    comp = zlib.compress(b"a" * plain_size, 6)
    return (struct.pack("<BBI", 2, 0x01, len(comp)) + comp
            + struct.pack("<I", 0))


def test_compression_bomb_rejected():
    """A ~1 KiB frame claiming 100 MiB of decompressed control must
    be refused as MalformedInput before the memory is allocated."""
    from ceph_tpu.msg import messenger

    bomb = _bomb_frame(100 << 20)
    assert len(bomb) < 200 << 10  # genuinely a small frame
    with pytest.raises(MalformedInput) as ei:
        messenger.decode_frame(bomb)
    assert "cap" in str(ei.value)


def test_compressed_frame_under_cap_decodes():
    from ceph_tpu.msg import messenger

    # large-but-legit compressed control segments still decode
    msg, blobs = messenger.decode_frame(
        messenger.encode_frame({"type": "t", "pad": "x" * (64 << 10)}))
    assert msg["type"] == "t" and blobs == []


# ---------------------------------------------------------------------------
# dencoder CLI
# ---------------------------------------------------------------------------

def test_dencoder_list_enumerates(capsys):
    from ceph_tpu.tools.ceph_cli import main

    assert main(["dencoder", "list"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) >= 12
    assert any(line.startswith("osdmap.incremental ") for line in out)


def test_dencoder_encode_decode_roundtrip(tmp_path, capsys):
    from ceph_tpu.tools.ceph_cli import main

    assert main(["dencoder", "encode", "osd.pg_log_entry"]) == 0
    hexstr = capsys.readouterr().out.strip()
    f = tmp_path / "blob.hex"
    f.write_text(hexstr)
    assert main(["dencoder", "decode", "osd.pg_log_entry",
                 str(f)]) == 0
    out = capsys.readouterr().out
    assert '"oid": "obj-1"' in out


def test_dencoder_roundtrip_verb(capsys):
    from ceph_tpu.tools.ceph_cli import main

    assert main(["dencoder", "roundtrip", "msg.frame"]) == 0
    assert "msg.frame: ok" in capsys.readouterr().out


def test_dencoder_decode_refuses_garbage(tmp_path, capsys):
    from ceph_tpu.tools.ceph_cli import main

    f = tmp_path / "bad.hex"
    f.write_text((b"\xff" * 32).hex())
    assert main(["dencoder", "decode", "osdmap.full", str(f)]) == 1
