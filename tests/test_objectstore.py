"""ObjectStore/MemStore tests — mirrors src/test/objectstore/store_test
scenarios: transactional atomicity, extents/clone/omap semantics, and
the checkpoint round-trip the OSD-analogue restart path uses."""

import pytest

from ceph_tpu.os.memstore import MemStore, TransactionError
from ceph_tpu.os.objectstore import Transaction


def make_store():
    st = MemStore()
    st.queue_transaction(Transaction().create_collection("pg1"))
    return st


def test_write_read_extents():
    st = make_store()
    st.queue_transaction(
        Transaction().write("pg1", "obj", 0, b"hello")
        .write("pg1", "obj", 10, b"world"))
    assert st.read("pg1", "obj") == b"hello\0\0\0\0\0world"
    assert st.read("pg1", "obj", 10, 5) == b"world"
    assert st.stat("pg1", "obj")["size"] == 15


def test_zero_truncate_remove():
    st = make_store()
    st.queue_transaction(Transaction().write("pg1", "o", 0, b"x" * 16))
    st.queue_transaction(Transaction().zero("pg1", "o", 4, 8))
    assert st.read("pg1", "o") == b"xxxx" + b"\0" * 8 + b"xxxx"
    # zero past EOF extends (reference _zero-via-_write semantics)
    st.queue_transaction(Transaction().zero("pg1", "o", 16, 8))
    assert st.stat("pg1", "o")["size"] == 24
    assert st.read("pg1", "o", 16) == b"\0" * 8
    st.queue_transaction(Transaction().truncate("pg1", "o", 4))
    assert st.read("pg1", "o") == b"xxxx"
    st.queue_transaction(Transaction().truncate("pg1", "o", 8))
    assert st.read("pg1", "o") == b"xxxx\0\0\0\0"
    st.queue_transaction(Transaction().remove("pg1", "o"))
    assert st.stat("pg1", "o") is None


def test_clone_and_attrs_and_omap():
    st = make_store()
    st.queue_transaction(
        Transaction().write("pg1", "src", 0, b"abc")
        .setattr("pg1", "src", "version", b"7")
        .omap_setkeys("pg1", "src", {"k1": b"v1", "k2": b"v2"}))
    st.queue_transaction(Transaction().clone("pg1", "src", "dst"))
    # clone is a snapshot: later writes to src don't leak into dst
    st.queue_transaction(Transaction().write("pg1", "src", 0, b"zzz"))
    assert st.read("pg1", "dst") == b"abc"
    assert st.getattr("pg1", "dst", "version") == b"7"
    assert st.omap_get("pg1", "dst") == {"k1": b"v1", "k2": b"v2"}
    st.queue_transaction(
        Transaction().omap_rmkeys("pg1", "dst", ["k1"]))
    assert st.omap_get("pg1", "dst") == {"k2": b"v2"}


def test_transaction_atomicity_on_failure():
    """A failing op must leave the store untouched — the
    queue_transaction contract."""
    st = make_store()
    st.queue_transaction(Transaction().write("pg1", "a", 0, b"keep"))
    txn = (Transaction().write("pg1", "a", 0, b"clobbered")
           .remove("pg1", "missing"))  # fails here
    with pytest.raises(TransactionError):
        st.queue_transaction(txn)
    assert st.read("pg1", "a") == b"keep"  # first op rolled back


def test_collection_lifecycle():
    st = MemStore()
    st.queue_transaction(Transaction().create_collection("c1"))
    assert st.collection_exists("c1")
    with pytest.raises(TransactionError):
        st.queue_transaction(Transaction().create_collection("c1"))
    st.queue_transaction(Transaction().touch("c1", "o"))
    with pytest.raises(TransactionError):  # non-empty
        st.queue_transaction(Transaction().remove_collection("c1"))
    st.queue_transaction(
        Transaction().remove("c1", "o").remove_collection("c1"))
    assert not st.collection_exists("c1")
    with pytest.raises(TransactionError):
        st.queue_transaction(Transaction().touch("nope", "o"))


def test_checkpoint_roundtrip():
    st = make_store()
    st.queue_transaction(
        Transaction().write("pg1", "o", 0, bytes(range(256)))
        .setattr("pg1", "o", "hinfo", b"\x01\x02")
        .omap_setkeys("pg1", "o", {"epoch": b"5"}))
    st2 = MemStore.import_state(st.export_state())
    assert st2.read("pg1", "o") == bytes(range(256))
    assert st2.getattr("pg1", "o", "hinfo") == b"\x01\x02"
    assert st2.omap_get("pg1", "o") == {"epoch": b"5"}
    assert st2.list_collections() == ["pg1"]
    assert st2.list_objects("pg1") == ["o"]
