"""Multi-device CI tests — the sharded path must equal the scalar spec.

Runs on the 8-virtual-CPU-device mesh the conftest provisions (the
driver's separate dryrun validates the same layout; here CI pins the
*values*): ``sharded_rule_fn`` over the mesh == unsharded
``BatchedMapper`` == the scalar ``mapper_ref`` specification, and the
all-reduced utilization tally == a numpy bincount.  This is the
TPU-native re-expression of the reference's multi-process QA
(qa/standalone/ — many OSDs, one host): many devices, one host.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ceph_tpu.crush.builder import sample_cluster_map
from ceph_tpu.crush.map import CrushMap
from ceph_tpu.crush.mapper_jax import BatchedMapper, build_rule_fn
from ceph_tpu.crush import mapper_ref
from ceph_tpu.ec.rs_jax import RSCode
from ceph_tpu.parallel.placement import (make_mesh, sharded_rule_fn,
                                         utilization)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devs)}")
    return make_mesh(devs[:N_DEV])


@pytest.fixture(scope="module")
def cmap():
    return sample_cluster_map(racks=3, hosts_per_rack=2, osds_per_host=4)


def _scalar_results(cmap, ruleno, numrep, weight, xs):
    out = []
    for x in xs:
        r = mapper_ref.crush_do_rule(cmap, ruleno, int(x), numrep,
                                     list(weight))
        out.append(r)
    return out


def test_sharded_equals_unsharded_equals_scalar(mesh, cmap):
    numrep = 3
    weight = [0x10000] * cmap.max_devices
    xs_np = np.arange(N_DEV * 16, dtype=np.uint32)

    fn, static, arrays = sharded_rule_fn(cmap, 0, numrep, mesh)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("pg"))
    A = jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), repl), arrays)
    w_dev = jax.device_put(
        jnp.asarray(np.asarray(weight, np.uint32)), repl)
    xs = jax.device_put(jnp.asarray(xs_np), shard)

    res_sh, lens_sh, counts = fn(A, w_dev, xs)
    res_sh, lens_sh = np.asarray(res_sh), np.asarray(lens_sh)

    # unsharded BatchedMapper on the same inputs
    bm = BatchedMapper(cmap)
    res_un, lens_un = bm.map_batch(0, xs_np, numrep,
                                   np.asarray(weight, np.uint32))
    res_un, lens_un = np.asarray(res_un), np.asarray(lens_un)
    assert np.array_equal(res_sh, res_un)
    assert np.array_equal(lens_sh, lens_un)

    # scalar executable spec
    want = _scalar_results(cmap, 0, numrep, weight, xs_np)
    for i, w in enumerate(want):
        assert list(res_sh[i, :lens_sh[i]]) == w, f"x={i}"

    # utilization == numpy bincount over valid entries
    valid = []
    for i, w in enumerate(want):
        valid.extend(v for v in w if 0 <= v < static.max_devices)
    want_counts = np.bincount(np.asarray(valid, np.int64),
                              minlength=static.max_devices)
    assert np.array_equal(np.asarray(counts), want_counts)


def test_utilization_matches_bincount_random():
    rng = np.random.default_rng(7)
    max_dev = 24
    res = rng.integers(-1, max_dev, (64, 3)).astype(np.int32)
    lens = rng.integers(0, 4, 64).astype(np.int32)
    got = np.asarray(utilization(jnp.asarray(res), jnp.asarray(lens),
                                 max_dev))
    want = np.zeros(max_dev, np.int64)
    for i in range(64):
        for j in range(lens[i]):
            v = res[i, j]
            if 0 <= v < max_dev:
                want[v] += 1
    assert np.array_equal(got, want)


def test_sharded_ec_encode_equals_single_device(mesh):
    """The dryrun's stripe-byte-axis sharding, value-checked: encode of
    a stripe batch sharded over the mesh == single-device encode."""
    code = RSCode(4, 2)
    rng = np.random.default_rng(3)
    data_np = rng.integers(0, 256, (4, 128 * N_DEV), dtype=np.uint8)

    single = np.asarray(code.encode(jnp.asarray(data_np)))

    sh = NamedSharding(mesh, P(None, "pg"))
    data_sh = jax.device_put(jnp.asarray(data_np), sh)
    enc = jax.jit(code.encode, in_shardings=(sh,), out_shardings=sh)
    parity = np.asarray(enc(data_sh))
    assert np.array_equal(parity, single)


def test_golden_map_sharded(mesh):
    """Production-shaped check: the 10k-OSD golden map, sharded over the
    mesh, still reproduces the reference C core's golden vectors."""
    import json
    import pathlib

    d = json.load(open(pathlib.Path(__file__).parent /
                       "golden/map_big10k.json"))
    cmap10k = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    n = 64  # first 64 golden xs, padded to a multiple of N_DEV
    fn, static, arrays = sharded_rule_fn(cmap10k, case["ruleno"],
                                         case["numrep"], mesh,
                                         gather_stats=False)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("pg"))
    A = jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), repl), arrays)
    w = jax.device_put(
        jnp.asarray(np.asarray(case["weight"], np.uint32)), repl)
    xs = jax.device_put(
        jnp.arange(case["x0"], case["x0"] + n, dtype=np.uint32), shard)
    res, lens = fn(A, w, xs)
    res, lens = np.asarray(res), np.asarray(lens)
    for i in range(n):
        assert list(res[i, :lens[i]]) == case["results"][i], f"i={i}"
