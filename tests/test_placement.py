"""Multi-device CI tests — the sharded path must equal the scalar spec.

Runs on the 8-virtual-CPU-device mesh the conftest provisions (the
driver's separate dryrun validates the same layout; here CI pins the
*values*): ``sharded_rule_fn`` over the mesh == unsharded
``BatchedMapper`` == the scalar ``mapper_ref`` specification, and the
all-reduced utilization tally == a numpy bincount.  This is the
TPU-native re-expression of the reference's multi-process QA
(qa/standalone/ — many OSDs, one host): many devices, one host.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ceph_tpu.analysis import jaxcheck
from ceph_tpu.crush.builder import sample_cluster_map
from ceph_tpu.crush.map import CrushMap
from ceph_tpu.crush.mapper_jax import BatchedMapper, build_rule_fn
from ceph_tpu.crush import mapper_ref
from ceph_tpu.ec.rs_jax import RSCode
from ceph_tpu.parallel.placement import (PlacementPlane, make_mesh,
                                         pad_batch, sharded_rule_fn,
                                         utilization)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devs)}")
    return make_mesh(devs[:N_DEV])


@pytest.fixture(scope="module")
def cmap():
    return sample_cluster_map(racks=3, hosts_per_rack=2, osds_per_host=4)


def _scalar_results(cmap, ruleno, numrep, weight, xs):
    out = []
    for x in xs:
        r = mapper_ref.crush_do_rule(cmap, ruleno, int(x), numrep,
                                     list(weight))
        out.append(r)
    return out


def test_sharded_equals_unsharded_equals_scalar(mesh, cmap):
    numrep = 3
    weight = [0x10000] * cmap.max_devices
    xs_np = np.arange(N_DEV * 16, dtype=np.uint32)

    fn, static, arrays = sharded_rule_fn(cmap, 0, numrep, mesh)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("pg"))
    A = jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), repl), arrays)
    w_dev = jax.device_put(
        jnp.asarray(np.asarray(weight, np.uint32)), repl)
    xs = jax.device_put(jnp.asarray(xs_np), shard)

    res_sh, lens_sh, counts = fn(A, w_dev, xs)
    res_sh, lens_sh = np.asarray(res_sh), np.asarray(lens_sh)

    # unsharded BatchedMapper on the same inputs
    bm = BatchedMapper(cmap)
    res_un, lens_un = bm.map_batch(0, xs_np, numrep,
                                   np.asarray(weight, np.uint32))
    res_un, lens_un = np.asarray(res_un), np.asarray(lens_un)
    assert np.array_equal(res_sh, res_un)
    assert np.array_equal(lens_sh, lens_un)

    # scalar executable spec
    want = _scalar_results(cmap, 0, numrep, weight, xs_np)
    for i, w in enumerate(want):
        assert list(res_sh[i, :lens_sh[i]]) == w, f"x={i}"

    # utilization == numpy bincount over valid entries
    valid = []
    for i, w in enumerate(want):
        valid.extend(v for v in w if 0 <= v < static.max_devices)
    want_counts = np.bincount(np.asarray(valid, np.int64),
                              minlength=static.max_devices)
    assert np.array_equal(np.asarray(counts), want_counts)


def test_utilization_matches_bincount_random():
    rng = np.random.default_rng(7)
    max_dev = 24
    res = rng.integers(-1, max_dev, (64, 3)).astype(np.int32)
    lens = rng.integers(0, 4, 64).astype(np.int32)
    got = np.asarray(utilization(jnp.asarray(res), jnp.asarray(lens),
                                 max_dev))
    want = np.zeros(max_dev, np.int64)
    for i in range(64):
        for j in range(lens[i]):
            v = res[i, j]
            if 0 <= v < max_dev:
                want[v] += 1
    assert np.array_equal(got, want)


def test_sharded_ec_encode_equals_single_device(mesh):
    """The dryrun's stripe-byte-axis sharding, value-checked: encode of
    a stripe batch sharded over the mesh == single-device encode."""
    code = RSCode(4, 2)
    rng = np.random.default_rng(3)
    data_np = rng.integers(0, 256, (4, 128 * N_DEV), dtype=np.uint8)

    single = np.asarray(code.encode(jnp.asarray(data_np)))

    sh = NamedSharding(mesh, P(None, "pg"))
    data_sh = jax.device_put(jnp.asarray(data_np), sh)
    enc = jax.jit(code.encode, in_shardings=(sh,), out_shardings=sh)
    parity = np.asarray(enc(data_sh))
    assert np.array_equal(parity, single)


# -- PlacementPlane: the production mesh-sharded distribution layer --------

@pytest.mark.parametrize("ruleno,numrep", [(0, 3), (0, 5), (1, 3),
                                           (1, 6)])
def test_placement_plane_bit_exact_grid(mesh, cmap, ruleno, numrep):
    """Sharded results/lens/utilization identical to the unsharded
    ``build_rule_fn`` output across the rule 0/1 (firstn/indep) x R
    grid — including a batch NOT divisible by the mesh (pad lanes
    masked out of the tally)."""
    weight = np.full(cmap.max_devices, 0x10000, np.uint32)
    weight[3] = 0x8000
    plane = PlacementPlane(cmap, mesh=mesh)
    bm = BatchedMapper(cmap)
    for n in (N_DEV * 8, 100):    # divisible and pad-and-mask
        xs = np.arange(n, dtype=np.uint32)
        res, lens, counts = plane.map_batch(ruleno, xs, numrep,
                                            weight,
                                            gather_stats=True)
        res_un, lens_un = bm.map_batch(ruleno, xs, numrep, weight)
        res_un = np.asarray(res_un)
        lens_un = np.asarray(lens_un)
        assert np.array_equal(np.asarray(res), res_un), (ruleno, n)
        assert np.array_equal(np.asarray(lens), lens_un), (ruleno, n)
        want = np.zeros(cmap.max_devices, np.int64)
        for i in range(n):
            for v in res_un[i, :lens_un[i]]:
                if 0 <= v < cmap.max_devices:
                    want[v] += 1
        assert np.array_equal(np.asarray(counts), want), (ruleno, n)


def test_placement_plane_choose_args_bit_exact(mesh):
    """The choose_args grid point: the golden chooseargs map through
    the plane == the unsharded mapper with the same choose_args."""
    import json
    import pathlib

    d = json.load(open(pathlib.Path(__file__).parent /
                       "golden/map_tree3_chooseargs.json"))
    cmap = CrushMap.from_dict(d["map"])
    cargs = cmap.choose_args.get("golden")
    assert cargs is not None, "golden chooseargs map lost its args"
    case = d["cases"][0]
    n = min(64, case["x1"] - case["x0"])
    xs = np.arange(case["x0"], case["x0"] + n, dtype=np.uint32)
    weight = np.asarray(case["weight"], np.uint32)

    plane = PlacementPlane(cmap, choose_args=cargs, mesh=mesh)
    res, lens = plane.map_batch(case["ruleno"], xs, case["numrep"],
                                weight)
    bm = BatchedMapper(cmap, choose_args=cargs)
    res_un, lens_un = bm.map_batch(case["ruleno"], xs, case["numrep"],
                                   weight)
    assert np.array_equal(np.asarray(res), np.asarray(res_un))
    assert np.array_equal(np.asarray(lens), np.asarray(lens_un))
    res, lens = np.asarray(res), np.asarray(lens)
    for i in range(n):
        assert list(res[i, :lens[i]]) == case["results"][i], f"x={i}"


def test_placement_plane_single_device_mesh(cmap):
    """The degenerate 1-device mesh: same code path, same results —
    the tier-1 guarantee that nothing forks on single-chip hosts
    (runs regardless of how many devices the env provides)."""
    mesh1 = make_mesh(jax.devices()[:1])
    plane = PlacementPlane(cmap, mesh=mesh1)
    weight = np.full(cmap.max_devices, 0x10000, np.uint32)
    xs = np.arange(37, dtype=np.uint32)   # non-pow2, non-divisible
    res, lens, counts = plane.map_batch(0, xs, 3, weight,
                                        gather_stats=True)
    bm = BatchedMapper(cmap)
    res_un, lens_un = bm.map_batch(0, xs, 3, weight)
    assert np.array_equal(np.asarray(res), np.asarray(res_un))
    assert np.array_equal(np.asarray(lens), np.asarray(lens_un))
    assert int(np.asarray(counts).sum()) == int(
        np.asarray(lens_un).sum())


def test_pad_batch_bounds_signatures():
    """pow2 padding: every batch size in [1, 4096] lands on one of
    O(log) padded shapes, all divisible by the mesh size."""
    for n_dev in (1, 3, 8):
        pads = {pad_batch(n, n_dev) for n in range(1, 4097)}
        assert len(pads) <= 14, (n_dev, sorted(pads))
        assert all(p % n_dev == 0 for p in pads)
        assert all(pad_batch(n, n_dev) >= n for n in range(1, 4097))


def test_placement_plane_recompile_budget(mesh, cmap):
    """Mesh size changes must not leak compile signatures beyond the
    pow2-padding budget: after warming a plane per mesh size, every
    further batch that pads to a warmed shape hits the jit cache —
    zero new compiles in the steady-state window (the conftest gate
    fails this test on any violation; the assert is the explicit
    twin)."""
    weight = np.full(cmap.max_devices, 0x10000, np.uint32)
    planes = [PlacementPlane(cmap, mesh=mesh),
              PlacementPlane(cmap, mesh=make_mesh(jax.devices()[:1]))]
    for plane in planes:          # warmup: one compile per mesh size
        plane.map_batch(0, np.arange(64, dtype=np.uint32), 3, weight)
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("placement.plane.mesh_sizes"):
        for plane in planes:
            for n in (64, 40, 33, 64):   # all pad to the warmed 64
                res, lens = plane.map_batch(
                    0, np.arange(n, dtype=np.uint32), 3, weight)
                assert np.asarray(res).shape == (n, 3)
    assert len(jaxcheck.recompile_violations()) == base


def test_golden_map_sharded(mesh):
    """Production-shaped check: the 10k-OSD golden map, sharded over the
    mesh, still reproduces the reference C core's golden vectors."""
    import json
    import pathlib

    d = json.load(open(pathlib.Path(__file__).parent /
                       "golden/map_big10k.json"))
    cmap10k = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    n = 64  # first 64 golden xs, padded to a multiple of N_DEV
    fn, static, arrays = sharded_rule_fn(cmap10k, case["ruleno"],
                                         case["numrep"], mesh,
                                         gather_stats=False)
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("pg"))
    A = jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), repl), arrays)
    w = jax.device_put(
        jnp.asarray(np.asarray(case["weight"], np.uint32)), repl)
    xs = jax.device_put(
        jnp.arange(case["x0"], case["x0"] + n, dtype=np.uint32), shard)
    res, lens = fn(A, w, xs)
    res, lens = np.asarray(res), np.asarray(lens)
    for i in range(n):
        assert list(res[i, :lens[i]]) == case["results"][i], f"i={i}"
