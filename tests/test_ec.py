"""GF(2^8) + RS kernel tests.

Mirrors the reference's per-plugin test strategy
(src/test/erasure-code/TestErasureCodeJerasure.cc,
TestErasureCodeIsa.cc, and the SHEC-style exhaustive erasure sweeps):
field axioms, matrix algebra, encode/decode round-trips for every
erasure combination, and numpy-vs-JAX bit equality.
"""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import gf
from ceph_tpu.ec.rs_jax import RSCode, gf_matmul_bits

RNG = np.random.default_rng(1234)


def test_field_axioms():
    a = RNG.integers(1, 256, 64, dtype=np.uint8)
    b = RNG.integers(1, 256, 64, dtype=np.uint8)
    c = RNG.integers(1, 256, 64, dtype=np.uint8)
    assert np.array_equal(gf.gf_mul(a, b), gf.gf_mul(b, a))
    assert np.array_equal(gf.gf_mul(a, gf.gf_mul(b, c)),
                          gf.gf_mul(gf.gf_mul(a, b), c))
    # distributivity over XOR
    assert np.array_equal(gf.gf_mul(a, b ^ c),
                          gf.gf_mul(a, b) ^ gf.gf_mul(a, c))
    # inverses
    for v in range(1, 256):
        assert gf.GF_MUL[v, gf.gf_inv(v)] == 1


def test_matrix_inverse():
    for n in (2, 4, 7):
        M = RNG.integers(0, 256, (n, n), dtype=np.uint8)
        M += np.eye(n, dtype=np.uint8)  # nudge towards invertibility
        try:
            inv = gf.gf_inv_matrix(M)
        except np.linalg.LinAlgError:
            continue
        assert np.array_equal(gf.gf_matmul(M, inv),
                              np.eye(n, dtype=np.uint8))


def test_bitmatrix_equals_table_mul():
    x = np.arange(256, dtype=np.uint8)
    for c in (0, 1, 2, 3, 0x1D, 0x80, 0xFF):
        B = gf.gf_const_bitmatrix(c)
        bits = ((x[None, :] >> np.arange(8)[:, None]) & 1).astype(np.uint8)
        out_bits = (B.astype(np.int32) @ bits) & 1
        out = np.zeros(256, np.uint8)
        for b in range(8):
            out |= (out_bits[b] << b).astype(np.uint8)
        assert np.array_equal(out, gf.gf_mul(c, x)), hex(c)


@pytest.mark.parametrize("tech", ["reed_sol_van", "cauchy_good"])
@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 3)])
def test_mds_property(tech, k, m):
    """Every k-subset of rows of the generator must be invertible."""
    G = (gf.rs_vandermonde_matrix(k, m) if tech == "reed_sol_van"
         else gf.rs_cauchy_matrix(k, m))
    for rows in itertools.combinations(range(k + m), k):
        inv = gf.gf_inv_matrix(G[list(rows)])  # raises if singular
        assert inv is not None


@pytest.mark.parametrize("tech", ["reed_sol_van", "cauchy_good"])
@pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
def test_roundtrip_all_erasures(tech, k, m):
    """Exhaustive erasure sweep (TestErasureCodeShec_all.cc style): every
    combination of <= m lost chunks must decode to the original data."""
    L = 64
    code = RSCode(k, m, tech)
    data = RNG.integers(0, 256, (k, L), dtype=np.uint8)
    chunks = np.asarray(code.all_chunks(data))
    # parity matches the numpy reference spec
    assert np.array_equal(chunks[k:], gf.encode_ref(code.G, data))
    for nerase in range(1, m + 1):
        for erased in itertools.combinations(range(k + m), nerase):
            avail = {i: chunks[i] for i in range(k + m) if i not in erased}
            got = code.decode_np(avail, erased)
            assert np.array_equal(got, data), (tech, k, m, erased)


def test_jax_matches_numpy_large():
    k, m, L = 8, 3, 4096
    code = RSCode(k, m)
    data = RNG.integers(0, 256, (k, L), dtype=np.uint8)
    assert np.array_equal(code.encode_np(data),
                          gf.encode_ref(code.G, data))


def test_gf_matmul_bits_identity():
    data = RNG.integers(0, 256, (4, 128), dtype=np.uint8)
    bm = gf.expand_bitmatrix(np.eye(4, dtype=np.uint8))
    out = np.asarray(gf_matmul_bits(bm, data))
    assert np.array_equal(out, data)
