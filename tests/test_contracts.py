"""JAX kernel contracts (analysis/jaxcheck): every jitted EC/CRUSH
kernel's shape/dtype contract proven via jax.eval_shape under strict
dtype promotion, plus the recompilation budget gate.

The parametrized test IS ``jaxcheck.verify_all()`` — one parameter per
registered contract, each covering its plugin's k/m (and w/packetsize)
grid including decode-with-erasures signatures.  A kernel change that
drifts an output dtype (silent int64/float64 promotion, a float leak
into the uint8 chunk lanes) or an output shape fails here without
executing a single device op.
"""

import numpy as np
import pytest

from ceph_tpu.analysis import jaxcheck

# registration completeness: every EC plugin and both CRUSH lowerings
# must carry a contract — deleting one (or forgetting to register a
# new kernel's) fails here, not silently
EXPECTED_CONTRACTS = {
    "ec.engine.mod2_matmul", "ec.engine.encode_batched",
    "ec.engine.encode_batched_sharded", "ec.rs_jax",
    "ec.jerasure", "ec.isa", "ec.lrc", "ec.shec", "ec.clay",
    "ec.native_gf", "ec.pallas", "ec.pallas_engine",
    "crush.mapper_jax", "crush.mapper_spec",
    "parallel.sharded_rule_fn",
}


def test_every_kernel_has_a_contract():
    assert set(jaxcheck.contracts()) == EXPECTED_CONTRACTS


@pytest.mark.parametrize("name", sorted(EXPECTED_CONTRACTS))
def test_contract_holds(name):
    violations = jaxcheck.verify(name)
    assert not violations, "\n".join(str(v) for v in violations)


def test_checker_catches_dtype_drift():
    """The checker must actually fire: a kernel whose output silently
    promotes to int64 (and one whose shape is wrong) is flagged."""
    import jax
    import jax.numpy as jnp

    def drifty(x):
        # u8 + i64 → weak promotion the strict context forbids
        return x.astype(jnp.int32) + jnp.int64(1)

    def wrong_shape(x):
        return jnp.zeros((x.shape[0] + 1,), jnp.uint8)

    jaxcheck.register_contract("_test.bad", lambda: [
        jaxcheck.Case("drift", drifty,
                      [jax.ShapeDtypeStruct((8,), "uint8")],
                      [((8,), "int32")]),
        jaxcheck.Case("shape", wrong_shape,
                      [jax.ShapeDtypeStruct((8,), "uint8")],
                      [((8,), "uint8")]),
    ])
    try:
        vs = jaxcheck.verify("_test.bad")
        msgs = "\n".join(str(v) for v in vs)
        assert len(vs) == 2, msgs
        assert "strict" in vs[0].message or "drift" in vs[0].case
        assert "mismatch" in vs[1].message
    finally:
        jaxcheck._REGISTRY.pop("_test.bad", None)


def test_checker_catches_int64_lane_even_when_declared():
    """Declaring an int64 output is not a loophole: integer lanes are
    uint8/int32/uint32 by contract unless the case opts out."""
    import jax
    import jax.numpy as jnp

    jaxcheck.register_contract("_test.lane", lambda: [
        jaxcheck.Case("i64", lambda x: x.astype(jnp.int64),
                      [jax.ShapeDtypeStruct((4,), "int32")],
                      [((4,), "int64")]),
    ])
    try:
        vs = jaxcheck.verify("_test.lane")
        assert any("integer-lane drift" in v.message for v in vs)
    finally:
        jaxcheck._REGISTRY.pop("_test.lane", None)


# ---------------------------------------------------------------------------
# recompilation budget gate
# ---------------------------------------------------------------------------

def _fresh_rs():
    """An RS instance with shapes unlikely to collide with any other
    test's booked compile signatures (the counters are process-global)."""
    from ceph_tpu.ec.rs_jax import RSCode

    return RSCode(5, 2)


def test_steady_state_clean_after_warmup():
    code = _fresh_rs()
    data = np.random.default_rng(7).integers(
        0, 256, (5, 1184), dtype=np.uint8)
    code.encode(data)  # warmup: trace + compile OUTSIDE the window
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("rs-steady"):
        for _ in range(3):
            code.encode(data)  # same shape signature: cache hits
    assert jaxcheck.recompile_violations()[base:] == []


def test_recompile_gate_catches_shape_instability():
    """The acceptance case: a deliberately shape-unstable steady-state
    phase (a new chunk length every call — the recompilation-storm
    shape) must be caught by the gate."""
    code = _fresh_rs()
    base = len(jaxcheck.recompile_violations())
    with jaxcheck.steady_state("rs-shape-unstable"):
        for L in (1216, 1248, 1280):
            code.encode(np.zeros((5, L), np.uint8))
    caught = jaxcheck.recompile_violations()[base:]
    # consume the violations: this test ASSERTS the gate fires; the
    # per-test conftest gate must not then fail the test for it
    jaxcheck.clear_recompile_violations()
    assert caught, "shape-unstable phase was not caught"
    assert "rs-shape-unstable" in caught[-1]["label"]
    assert "ec.engine.jit_compiles" in caught[-1]["message"]


def test_tracer_leak_gate_fires():
    """The jax.checking_leaks gate (enabled module-wide by conftest
    for the kernel suites): a jit that leaks its tracer through a
    side channel raises instead of silently miscomputing later."""
    import jax
    import jax.numpy as jnp

    leaked = []

    @jax.jit
    def leaky(x):
        leaked.append(x)  # the tracer escapes the trace
        return x * 2

    with pytest.raises(Exception, match="[Ll]eak"):
        with jax.checking_leaks():
            leaky(jnp.arange(4))
