"""Test harness config.

Tests run hermetically on CPU with 8 virtual XLA devices so every multi-chip
sharding path (pjit/shard_map over a Mesh) is exercised without TPU hardware;
the driver separately compile-checks the real-chip path via __graft_entry__.

The environment may preload jax and pin JAX_PLATFORMS to a hardware backend
before pytest ever runs, so plain env-var setdefault is NOT enough: force the
platform through jax.config (honored until the first backend client is
created) and inject the virtual-device XLA flag before any client exists.
"""

import os
import pathlib
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The axon TPU PJRT plugin is registered into EVERY python process by a
# sitecustomize hook (gated on PALLAS_AXON_POOL_IPS), and a *registered*
# plugin is initialized by backend discovery even under
# JAX_PLATFORMS=cpu — which blocks forever whenever the TPU tunnel is
# down.  Tests are CPU-only by design, so drop the factory before any
# backend client exists.  (An execve re-exec would also work but loses
# pytest's fd-level capture — the report would vanish.)
try:  # noqa: SIM105 — private API; harmless if it moves
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

GOLDEN_DIR = pathlib.Path(__file__).resolve().parent / "golden"
