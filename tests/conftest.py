"""Test harness config.

Tests run hermetically on CPU with 8 virtual XLA devices so every multi-chip
sharding path (pjit/shard_map over a Mesh) is exercised without TPU hardware;
the driver separately compile-checks the real-chip path via __graft_entry__.

The environment may preload jax and pin JAX_PLATFORMS to a hardware backend
before pytest ever runs, so plain env-var setdefault is NOT enough: force the
platform through jax.config (honored until the first backend client is
created) and inject the virtual-device XLA flag before any client exists.
"""

import os
import pathlib
import sys
import threading
import time
import warnings

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soaks excluded from the tier-1 lane "
        "(-m 'not slow'); run explicitly with -m slow")

# lockdep on for the WHOLE suite (overridable with CEPH_TPU_LOCKDEP=0):
# every test inherits the lock-order checker, so a future PR that
# introduces an inversion fails its own tests with both witness
# stacks.  Must precede any ceph_tpu import — make_lock() decides
# wrapper-vs-raw at construction time.
os.environ.setdefault("CEPH_TPU_LOCKDEP", "1")
# racecheck rides lockdep's held-set: the data-race lockset checker is
# on for the whole suite too (overridable with CEPH_TPU_RACECHECK=0).
# Must also precede any ceph_tpu import — guarded_by()/shared()
# decide instrument-vs-identity at class decoration time.
os.environ.setdefault("CEPH_TPU_RACECHECK", "1")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache, shared with bench.py's .jax_cache:
# the suite's wall time is dominated by compiling the big golden
# mapper programs, and recompiling identical programs every run is
# exactly the waste this PR's recompile gate exists to catch — warm
# runs (the driver's verify pass after a populated run) save ~1-2
# minutes.  Strictly an optimization: never a failure.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        str(pathlib.Path(__file__).resolve().parent.parent
            / ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # cache unavailable on this jax build
    pass

# The axon TPU PJRT plugin is registered into EVERY python process by a
# sitecustomize hook (gated on PALLAS_AXON_POOL_IPS), and a *registered*
# plugin is initialized by backend discovery even under
# JAX_PLATFORMS=cpu — which blocks forever whenever the TPU tunnel is
# down.  Tests are CPU-only by design, so drop the factory before any
# backend client exists.  (An execve re-exec would also work but loses
# pytest's fd-level capture — the report would vanish.)
try:  # noqa: SIM105 — private API; harmless if it moves
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

GOLDEN_DIR = pathlib.Path(__file__).resolve().parent / "golden"

from ceph_tpu.analysis import (jaxcheck, lockdep, racecheck,  # noqa: E402
                               watchdog)
from ceph_tpu.common import bufpool, tracing  # noqa: E402

# -- JAX hygiene gates (the XLA twin of the concurrency gates below) --
#
# Kernel test modules run under jax_numpy_dtype_promotion=strict: a
# silent int64/float64 weak-type promotion in EC/CRUSH math becomes a
# TypePromotionError at the test that introduces it (the contract
# checker pins the fixed dtypes; this keeps new code honest at
# runtime too).
STRICT_DTYPE_MODULES = {
    "test_ec", "test_jerasure", "test_lrc_isa", "test_shec",
    "test_clay", "test_stripe", "test_native_gf", "test_pallas",
    "test_mapper_jax", "test_mapper_spec", "test_contracts",
}
# jax.checking_leaks for the kernel suites that exercise every jitted
# kernel cheaply: test_contracts traces them all (and this gate caught
# a real leaked-tracer bug in the straw2 table-key path), test_pallas
# covers the fused kernel.  NOT the wide EC roundtrip matrices — leak
# checking disables trace caching and turned test_ec's 2s erasure
# sweeps into 75s (measured), blowing the tier-1 time budget.
TRACER_LEAK_MODULES = {"test_contracts", "test_pallas"}


@pytest.fixture(scope="session", autouse=True)
def _stall_watchdog():
    """Session-wide stall watchdog: a test that wedges a lock or a
    messenger handler gets an all-thread stack dump on stderr while
    it hangs, instead of an opaque suite timeout."""
    yield watchdog.start_global(threshold=30.0)


@pytest.fixture(autouse=True)
def _jax_hygiene_gate(request):
    """Per-test JAX gates, mirroring the concurrency gates below.

    1. Strict dtype promotion + tracer-leak checking for the kernel
       test modules (see the module sets above).
    2. Recompile budget: any ``jaxcheck.steady_state()`` window that
       booked a new XLA compile (the ec.engine / crush.mapper
       per-shape-signature counters) fails THAT test — the
       recompilation-storm class caught at the test introducing it.
    """
    import contextlib

    mod = getattr(getattr(request, "module", None), "__name__", "")
    mod = mod.rsplit(".", 1)[-1]
    base = len(jaxcheck.recompile_violations())
    with contextlib.ExitStack() as stack:
        if mod in STRICT_DTYPE_MODULES:
            stack.enter_context(jax.numpy_dtype_promotion("strict"))
        if mod in TRACER_LEAK_MODULES:
            stack.enter_context(jax.checking_leaks())
        yield
    vs = jaxcheck.recompile_violations()[base:]
    if vs:
        jaxcheck.clear_recompile_violations()  # don't re-fail later tests
        detail = "\n".join(f"- [{v['label']}] {v['message']}"
                           for v in vs)
        pytest.fail(f"recompile gate: {len(vs)} steady-state "
                    f"compile violation(s) during this test:\n{detail}")


@pytest.fixture(autouse=True)
def _concurrency_gate(request):
    """Per-test concurrency gates.

    1. Lockdep: any lock-order violation recorded during the test
       fails THAT test (witness stacks were already printed).
    2. Thread leak: threads a test spawned must be gone shortly after
       it finishes.  Leaked non-daemon threads fail the test; leaked
       daemon threads (a cluster not fully shut down — the exact
       cross-test interference that made the quorum rejoin test
       flaky) get a grace period to die, then a warning.  Either way
       the NEXT test starts from a quiesced process.
    3. Buffer leak: every pooled recv segment acquired during the
       test must be released by test end (after the thread quiesce) —
       a held segment means a messenger/dispatch path dropped its
       ``Segment.release()``, the use-after-free-in-waiting the
       refcount contract exists to catch.  Like the span gate, live
       daemon threads (a shared cluster fixture still draining) may
       yet release — warn instead of fail.
    4. Span leak: every tracing span opened during the test must be
       finished by test end (after the thread quiesce above).  A span
       left open with no daemon thread alive to ever finish it means a
       code path began a span outside a ``with`` (lint CONC004's
       runtime twin) or an op died mid-trace — that fails the test,
       and the spans are abandoned so one leaky test cannot re-fail
       every later one.  With live daemon threads still draining (a
       shared cluster fixture's background recovery/heartbeat RPCs),
       an open span may yet finish — warn, like the thread gate.
    """
    before = set(threading.enumerate())
    before_spans = {id(s) for _svc, s in tracing.active_spans()}
    before_segs = len(bufpool.outstanding())
    base = len(lockdep.violations())
    race_base = racecheck.mark()
    yield
    vs = lockdep.violations()[base:]
    if vs:
        lockdep.clear_violations()  # don't re-fail every later test
        detail = "\n".join(
            f"- {v['message']} [{v['thread']}]\n"
            f"  existing order recorded at:\n{v['existing_stack']}"
            f"  conflicting order taken at:\n{v['current_stack']}"
            for v in vs)
        pytest.fail(f"lockdep: {len(vs)} lock-order violation(s) "
                    f"during this test:\n{detail}")

    # racecheck gate: a data-race violation (empty candidate lockset,
    # broken thread confinement) fails the owning test with both
    # access stacks, exactly like the lockdep gate above
    race_msg = racecheck.gate_check(race_base)
    if race_msg is not None:
        pytest.fail(race_msg)

    def leaked():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive()]

    # daemon-only stragglers get a short grace (they die with their
    # sockets); anything non-daemon gets longer before failing
    deadline = time.monotonic() + 1.5
    hard_deadline = time.monotonic() + 5.0
    left = leaked()
    while left and time.monotonic() < deadline:
        time.sleep(0.05)
        left = leaked()
    while left and any(not t.daemon for t in left) and \
            time.monotonic() < hard_deadline:
        time.sleep(0.05)
        left = leaked()
    bad = [t for t in left if not t.daemon]
    assert not bad, (f"test leaked non-daemon thread(s): "
                     f"{[t.name for t in bad]}")
    if left:
        warnings.warn(
            f"{request.node.nodeid} leaked daemon thread(s): "
            f"{sorted(t.name for t in left)[:10]}"
            f"{'...' if len(left) > 10 else ''}")

    # bufpool leak gate: in-flight dispatch gets a short drain window;
    # comparing against the BEFORE count means a segment stuck forever
    # fails only the test that leaked it, not every later one
    seg_deadline = time.monotonic() + 2.0
    held = bufpool.outstanding()
    while len(held) > before_segs and time.monotonic() < seg_deadline:
        time.sleep(0.05)
        held = bufpool.outstanding()
    if len(held) > before_segs:
        detail = "\n".join(f"- tag={tag!r} nbytes={n}"
                           for tag, n in held[:20])
        if left:
            warnings.warn(
                f"{request.node.nodeid}: {len(held) - before_segs} "
                f"pooled segment(s) still held at test end:\n{detail}")
        else:
            pytest.fail(
                f"{len(held) - before_segs} pooled buffer segment(s) "
                f"leaked (acquired during this test, never "
                f"released):\n{detail}")

    # span-leak gate: give in-flight ops a short drain window (the
    # thread gate above already quiesced daemon threads)
    def new_spans():
        return [(svc, s) for svc, s in tracing.active_spans()
                if id(s) not in before_spans]

    span_deadline = time.monotonic() + 2.0
    leaked_spans = new_spans()
    while leaked_spans and time.monotonic() < span_deadline:
        time.sleep(0.05)
        leaked_spans = new_spans()
    if leaked_spans:
        detail = "\n".join(
            f"- [{svc}] {s.name} (trace {s.trace_id}, "
            f"open {time.monotonic() - s._t0:.1f}s, "
            f"tags {s.tags})"
            for svc, s in leaked_spans[:20])
        if left:
            # live daemon threads may still finish these (background
            # ops of a shared cluster fixture) — not a proven leak
            warnings.warn(
                f"{request.node.nodeid}: {len(leaked_spans)} span(s) "
                f"still open at test end:\n{detail}")
        else:
            tracing.abandon_all_active()
            pytest.fail(
                f"{len(leaked_spans)} tracing span(s) left "
                f"unfinished at test end with no thread alive to "
                f"finish them:\n{detail}")
