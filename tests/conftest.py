"""Test harness config.

Tests run hermetically on CPU with 8 virtual XLA devices so every multi-chip
sharding path (pjit/shard_map over a Mesh) is exercised without TPU hardware;
the driver separately compile-checks the real-chip path via __graft_entry__.
Must run before anything imports jax.
"""

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

GOLDEN_DIR = pathlib.Path(__file__).resolve().parent / "golden"
