"""CrushWrapper facade tests — mirrors src/test/crush/CrushWrapper.cc
scenarios: topology edits (insert/move/adjust, :87-964), device classes
(device_class_clone :1148, populate_classes :1227), simple-rule
generation, and the upmap engine (try_remap_rule :1261)."""

import pytest

from ceph_tpu.crush import constants as C
from ceph_tpu.crush.wrapper import CrushWrapper


def build_cluster(hosts=4, osds_per_host=2, weight=0x10000):
    """root -> host{i} -> osd, all straw2, via insert_item only (the
    facade path, like the reference tests)."""
    w = CrushWrapper()
    dev = 0
    for h in range(hosts):
        for _ in range(osds_per_host):
            w.insert_item(dev, weight, f"osd.{dev}",
                          {"host": f"host{h}", "root": "default"})
            dev += 1
    return w


def test_insert_item_builds_hierarchy():
    w = build_cluster()
    root = w.get_item_id("default")
    assert root < 0
    hosts = w.get_children(root)
    assert len(hosts) == 4
    assert {w.get_item_name(h) for h in hosts} == \
        {f"host{i}" for i in range(4)}
    for h in hosts:
        assert w.get_bucket_type(h) == w.get_type_id("host")
        assert len(w.get_children(h)) == 2
    # weights accumulated up the chain
    assert w.get_bucket(root).weight == 8 * 0x10000
    assert w.get_bucket(hosts[0]).weight == 2 * 0x10000


def test_adjust_item_weight_propagates():
    w = build_cluster()
    root = w.get_item_id("default")
    h0 = w.get_item_id("host0")
    w.adjust_item_weight(0, 0x30000)
    assert w.get_item_weight(0) == 0x30000
    assert w.get_bucket(h0).weight == 0x40000
    assert w.get_bucket(root).weight == 10 * 0x10000


def test_remove_item_propagates():
    w = build_cluster()
    root = w.get_item_id("default")
    w.remove_item(7)
    assert w.get_bucket(root).weight == 7 * 0x10000
    with pytest.raises(KeyError):
        w.get_item_weight(7)


def test_move_bucket():
    w = build_cluster(hosts=2)
    w.insert_item(99, 0x10000, "osd.99",
                  {"host": "hostx", "root": "other"})
    root = w.get_item_id("default")
    hx = w.get_item_id("hostx")
    w.move_bucket(hx, {"root": "default"})
    assert hx in w.get_children(root)
    assert w.get_bucket(root).weight == 5 * 0x10000
    other = w.get_item_id("other")
    assert w.get_bucket(other).weight == 0


def test_move_bucket_under_itself_rejected():
    w = build_cluster(hosts=2)
    with pytest.raises(ValueError):
        w.move_bucket(w.get_item_id("default"),
                      {"host": "host0", "root": "default"})


def test_swap_bucket():
    w = build_cluster(hosts=2)
    h0, h1 = w.get_item_id("host0"), w.get_item_id("host1")
    w.adjust_item_weight(0, 0x20000)
    a_items = list(w.get_bucket(h0).items)
    b_items = list(w.get_bucket(h1).items)
    w.swap_bucket(h0, h1)
    assert w.get_bucket(h0).items == b_items
    assert w.get_bucket(h1).items == a_items
    root = w.get_item_id("default")
    assert w.get_bucket(root).weight == 5 * 0x10000


def test_name_maps():
    w = build_cluster(hosts=1)
    assert w.get_item_id("osd.0") == 0
    assert w.name_exists("host0")
    w.rename_item("host0", "hostA")
    assert w.name_exists("hostA") and not w.name_exists("host0")
    with pytest.raises(ValueError):
        w.set_item_name(0, "hostA")  # duplicate
    with pytest.raises(KeyError):
        w.get_item_id("nope")


def test_do_rule_on_facade_map():
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("replicated", "default", "host", "",
                            "firstn")
    weight = [0x10000] * 8
    for x in range(32):
        res = w.do_rule(rid, x, 3, weight)
        assert len(res) == 3
        hosts = {w.get_parent_of_type(o, w.get_type_id("host"))
                 for o in res}
        assert len(hosts) == 3  # failure-domain separation


def test_device_classes_shadow_tree():
    w = build_cluster(hosts=4)
    for d in range(8):
        w.set_item_class(d, "ssd" if d % 2 == 0 else "hdd")
    w.populate_classes()
    root = w.get_item_id("default")
    cid = w.get_or_create_class_id("ssd")
    shadow = w.class_bucket[(root, cid)]
    assert w.get_item_name(shadow) == "default~ssd"
    leaves = w.get_leaves(shadow)
    assert sorted(leaves) == [0, 2, 4, 6]
    assert w.get_bucket(shadow).weight == 4 * 0x10000

    # a class rule maps only to devices of that class
    rid = w.add_simple_rule("ssd_rule", "default", "host", "ssd",
                            "firstn")
    weight = [0x10000] * 8
    for x in range(32):
        res = w.do_rule(rid, x, 3, weight)
        assert len(res) == 3
        assert all(o % 2 == 0 for o in res), res


def test_device_class_missing_raises():
    w = build_cluster(hosts=2)
    with pytest.raises(KeyError):
        w.add_simple_rule("r", "default", "host", "nvme", "firstn")


def test_create_rule_signature_from_ec_interface():
    """interface.create_rule must be resolvable against the facade
    (VERDICT r2: no object satisfied that signature)."""
    from ceph_tpu.ec.jerasure import make_jerasure

    w = build_cluster(hosts=4)
    code = make_jerasure({"technique": "reed_sol_van", "k": "2",
                          "m": "1", "w": "8"})
    rid = code.create_rule("ecpool", w)
    rule = w.crush.rules[rid]
    assert rule.type == 3
    assert rule.steps[1].op == C.CRUSH_RULE_CHOOSELEAF_INDEP


def test_shadow_tree_tracks_topology_edits():
    """Edits after populate_classes must not leave stale shadow trees
    (weights and membership refresh before the next map consumption),
    and shadow ids stay stable so existing class rules remain valid."""
    w = build_cluster(hosts=4)
    for d in range(8):
        w.set_item_class(d, "ssd" if d % 2 == 0 else "hdd")
    rid = w.add_simple_rule("ssdr", "default", "host", "ssd", "firstn")
    root = w.get_item_id("default")
    cid = w.get_or_create_class_id("ssd")
    shadow_before = w.class_bucket[(root, cid)]

    w.adjust_item_weight(0, 0x80000)
    w.remove_item(2)
    weight = [0x10000] * 8
    res = [w.do_rule(rid, x, 3, weight) for x in range(32)]
    # shadow refreshed: id stable, weight current, osd 2 gone
    assert w.class_bucket[(root, cid)] == shadow_before
    assert not any(2 in m for m in res)
    assert all(o % 2 == 0 for m in res for o in m)
    assert w.get_bucket(shadow_before).weight == \
        0x80000 + 2 * 0x10000  # osds 0,4,6


def test_failed_move_does_not_corrupt_map():
    w = build_cluster(hosts=2)
    root = w.get_item_id("default")
    before = w.get_bucket(root).weight
    with pytest.raises(ValueError):
        w.move_bucket(root, {"host": "host0", "root": "default"})
    # root still intact and attached as before
    assert w.get_bucket(root).weight == before
    assert len(w.get_children(root)) >= 2
    assert w.do_rule(0, 1, 3, [0x10000] * 4) if 0 in w.crush.rules \
        else True


def test_reweight_recomputes_bottom_up():
    w = build_cluster(hosts=2)
    root = w.get_item_id("default")
    h0 = w.get_item_id("host0")
    # corrupt weights deliberately, then reweight restores consistency
    w.get_bucket(h0).item_weights[0] = 0x50000
    w.reweight()
    assert w.get_bucket(h0).weight == 0x50000 + 0x10000
    assert w.get_bucket(root).weight == 0x50000 + 3 * 0x10000


def test_calc_straw_v1_values():
    """Pin straw_calc_version=1 semantics: NO equal-weight skip (that
    branch is v0-only); at equal weights wnext=0 so the straw carries
    unchanged.  Hand-derived trace for [1, 1, 2] (16.16):
    items 0,1 -> straw 1.0; item 2 -> 1.0 * (1/(3/4))^(1/1) = 4/3."""
    from ceph_tpu.crush.builder import calc_straw

    got = calc_straw([0x10000, 0x10000, 0x20000])
    assert got[0] == got[1] == 0x10000
    assert got[2] == int((4 / 3) * 0x10000)
    # zero-weight items get zero straws (v1 branch)
    assert calc_straw([0, 0x10000])[0] == 0


def test_wrapper_serialization_roundtrip():
    w = build_cluster(hosts=2)
    for d in range(4):
        w.set_item_class(d, "ssd" if d % 2 else "hdd")
    w.add_simple_rule("r", "default", "host", "ssd", "firstn")
    from ceph_tpu.crush.map import ChooseArg, ChooseArgMap
    cam = ChooseArgMap()
    cam[0] = ChooseArg(ids=None, weight_set=[[0x8000, 0x10000]])
    w.crush.choose_args["p1"] = cam

    w2 = CrushWrapper.from_dict(w.to_dict())
    assert w2.get_item_id("default") == w.get_item_id("default")
    assert w2.get_item_class(1) == "ssd"
    assert w2.class_bucket == w.class_bucket
    # choose_args survive (CrushWrapper::encode parity)
    assert "p1" in w2.crush.choose_args
    assert w2.crush.choose_args["p1"][0].weight_set == \
        [[0x8000, 0x10000]]
    weight = [0x10000] * 4
    for x in range(32):
        assert w.do_rule(0, x, 2, weight) == w2.do_rule(0, x, 2, weight)


# -- try_remap_rule (the upmap engine) --------------------------------------

def test_try_remap_rule_swaps_overfull():
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    orig = [0, 2, 4]
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[6],
                           more_underfull=[], orig=orig)
    assert out == [6, 2, 4]


def test_try_remap_rule_prefers_same_failure_domain():
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    # osd 1 shares host0 with the overfull osd 0: valid swap in place
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[1],
                           more_underfull=[], orig=[0, 2, 4])
    assert out == [1, 2, 4]


def test_try_remap_rule_skips_used_and_orig():
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    # candidate 2 is already in orig -> must not be chosen twice
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[2, 6],
                           more_underfull=[], orig=[0, 2, 4])
    assert out == [6, 2, 4]


def test_try_remap_rule_no_candidates_keeps_orig():
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[],
                           more_underfull=[], orig=[0, 2, 4])
    assert out == [0, 2, 4]


def test_try_remap_rule_more_underfull_fallback():
    """more_underfull doesn't steer bucket selection (only `underfull`
    feeds underfull_buckets, CrushWrapper.cc:3884), so a fallback
    candidate must sit under an already-chosen bucket to be used."""
    w = build_cluster(hosts=4)
    rid = w.add_simple_rule("r", "default", "host", "", "firstn")
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[],
                           more_underfull=[1], orig=[0, 2, 4])
    assert out == [1, 2, 4]
    # a cross-host fallback alone cannot be reached
    out = w.try_remap_rule(rid, 3, overfull={0}, underfull=[],
                           more_underfull=[6], orig=[0, 2, 4])
    assert out == [0, 2, 4]
