"""EC non-regression corpus check + OpTracker — the cross-version
parity archive (ceph_erasure_code_non_regression.cc role) and the
in-flight/slow-op introspection (TrackedOp.h role)."""

import time

import pytest

from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.tools.ec_non_regression import DEFAULT_BASE, check_all


def test_corpus_non_regression():
    """Every archived corpus entry must re-encode byte-identically and
    decode from its ARCHIVED chunks under every single erasure."""
    entries = [p for p in DEFAULT_BASE.iterdir() if p.is_dir()]
    assert len(entries) >= 6  # jerasure x2, isa, lrc, shec, clay
    assert check_all(DEFAULT_BASE) == []


def test_op_tracker_inflight_and_history():
    t = OpTracker(history_size=4, history_slow_threshold=0.05)
    op = t.create("osd_op", "write 1.0/obj")
    assert t.dump_ops_in_flight()["num_ops"] == 1
    op.mark_event("commit")
    op.finish()
    assert t.dump_ops_in_flight()["num_ops"] == 0
    hist = t.dump_historic_ops()
    assert hist["num_ops"] == 1 and hist["served_total"] == 1
    events = [e["event"] for e in hist["ops"][0]["events"]]
    assert events == ["initiated", "commit", "done"]

    # slow-op capture
    slow = t.create("osd_op", "slow one")
    time.sleep(0.06)
    slow.finish()
    assert len(t.dump_historic_slow_ops()["ops"]) == 1

    # history ring is bounded
    for i in range(10):
        t.create("x", str(i)).finish()
    assert t.dump_historic_ops()["num_ops"] == 4
    assert t.dump_historic_ops()["served_total"] == 12


def test_op_tracker_context_manager_and_admin(tmp_path):
    from ceph_tpu.common.admin_socket import AdminSocket

    t = OpTracker()
    with t.create("osd_op", "ctx"):
        assert t.dump_ops_in_flight()["num_ops"] == 1
    assert t.dump_ops_in_flight()["num_ops"] == 0

    sock = AdminSocket(str(tmp_path / "a.asok"))
    t.wire(sock)
    sock.start()
    try:
        got = AdminSocket.request(str(tmp_path / "a.asok"),
                                  "dump_historic_ops")
        assert got["num_ops"] == 1
    finally:
        sock.shutdown()


# -- history semantics (satellite: PR 6) ------------------------------------

def test_historic_ops_completion_order_and_eviction():
    """dump_historic_ops lists ops in COMPLETION order and the ring
    evicts oldest-first at its bound."""
    t = OpTracker(history_size=3, history_slow_threshold=99.0)
    a = t.create("op", "a")
    b = t.create("op", "b")
    c = t.create("op", "c")
    # completion order deliberately differs from creation order
    b.finish()
    a.finish()
    c.finish()
    descs = [o["description"]
             for o in t.dump_historic_ops()["ops"]]
    assert descs == ["b", "a", "c"]
    t.create("op", "d").finish()
    descs = [o["description"]
             for o in t.dump_historic_ops()["ops"]]
    assert descs == ["a", "c", "d"]  # "b" evicted, bound respected
    assert t.dump_historic_ops()["served_total"] == 4


def test_slow_op_threshold_boundary(monkeypatch):
    """An op whose duration is EXACTLY the threshold is slow (>=),
    one epsilon under is not — pinned with a frozen clock so the
    boundary is deterministic."""
    import ceph_tpu.common.op_tracker as ot

    t = OpTracker(history_size=8, history_slow_threshold=0.5)
    now = [1000.0]
    monkeypatch.setattr(ot.time, "time", lambda: now[0])

    exact = t.create("op", "exactly-at-threshold")
    now[0] += 0.5
    exact.finish()
    under = t.create("op", "just-under")
    now[0] += 0.5 - 1e-9
    under.finish()
    slow = [o["description"]
            for o in t.dump_historic_slow_ops()["ops"]]
    assert slow == ["exactly-at-threshold"]
    # both still land in the general history
    assert len(t.dump_historic_ops()["ops"]) == 2


def test_idempotent_finish_single_history_insertion():
    """A double finish (explicit finish inside a `with`) must insert
    into history ONCE, count one serve, and append one done event."""
    t = OpTracker(history_size=8, history_slow_threshold=99.0)
    with t.create("op", "double") as op:
        op.finish()
        op.finish()
    hist = t.dump_historic_ops()
    assert hist["num_ops"] == 1 and hist["served_total"] == 1
    events = [e["event"] for e in hist["ops"][0]["events"]]
    assert events.count("done") == 1
    # the recorded duration is frozen at the FIRST finish
    d1 = hist["ops"][0]["age"]
    import time as _t
    _t.sleep(0.02)
    assert t.dump_historic_ops()["ops"][0]["age"] == d1
