"""The recovery engine — pipelined, load-balanced, failure-tolerant.

The contract under test, layer by layer:

  * stripe batching (ec/stripe.py recover_stripes) — the decode
    launch recovery hangs everything on: exactly-m losses still
    decode, per-pattern launches reconstruct byte-identically, and
    an LRC single-shard loss plans INSIDE its local group (fewer
    than k helpers — the locality win the strategy chooser books).
  * helper ledger + reservations (services/recovery.py) — the
    least-loaded fan-out's load accounting, the per-object exclusion
    table with its doubling TTL, and the shared local/remote
    reservation slot pool.
  * the engine in vivo (services/osd_service.py _run_recovery) —
    a failed helper read excludes that OSD for the object's
    remaining attempts and the decode re-plans from remaining
    survivors in the SAME pass; serial (depth 1) and pipelined
    modes both reconverge and book their batch counters; mixed
    erasure patterns in one PG pass all recover.
  * silent bit rot (store.bit_rot) — a flipped byte on a store read
    is caught by crc verification, degrades instead of serving
    corrupt data, and the shard is dropped for repair.
  * the drill plumbing (tools/thrasher.py --host-kill +
    tools/perf_history.py) — DRILL records ingest into the
    trajectory table and durability/SLO/pipeline-gate failures
    red-check.
"""

import json
import pathlib
import sys
import time

import numpy as np
import pytest

from ceph_tpu.analysis import faults
from ceph_tpu.common.config import Config
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.stripe import recover_stripes, sinfo_for
from ceph_tpu.services.cluster import MiniCluster
from ceph_tpu.services.osd_service import pg_cid
from ceph_tpu.services.recovery import (EXCLUDE_BASE_S, HelperLedger,
                                        ReservationBook)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from tools import perf_history, thrasher  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.reset()
    yield
    faults.reset()


def _fast_conf(**over):
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.2)
    c.set("mon_osd_down_out_interval", 60.0)
    c.set("osd_pg_stat_report_interval", 0.2)
    for k, v in over.items():
        c.set(k, v)
    return c


def _encode_obj(code, data):
    return {i: np.asarray(v, np.uint8).ravel()
            for i, v in code.encode(set(range(
                code.get_chunk_count())), data).items()}


# -- recover_stripes batching edge cases ------------------------------
def test_recover_stripes_exactly_m_failures():
    """The worst survivable pattern: every parity count spent — m
    simultaneous losses decode from exactly k survivors, multi-stripe
    runs in one launch."""
    code = factory("jerasure", {"technique": "reed_sol_van",
                                "k": "2", "m": "2", "w": "8"})
    sinfo = sinfo_for(code, stripe_unit=512)
    data = bytes(range(256)) * 16  # 4 stripes of width 1024
    enc = _encode_obj(code, data)
    lost = {0, 3}  # one data + one parity: exactly m
    surviving = {i: enc[i] for i in enc if i not in lost}
    out = recover_stripes(sinfo, code, surviving, lost)
    for i in lost:
        assert np.asarray(out[i], np.uint8).tobytes() == \
            enc[i].tobytes(), f"chunk {i} drifted through recovery"


def test_recover_stripes_mixed_patterns_decode_independently():
    """Two erasure patterns over the same profile: each pattern is
    its own launch (the engine buckets by survivor set) and both
    reconstruct byte-identically — a re-planned object deviating
    from its group's pattern must not poison the batch."""
    code = factory("jerasure", {"technique": "reed_sol_van",
                                "k": "2", "m": "2", "w": "8"})
    sinfo = sinfo_for(code, stripe_unit=512)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    enc = _encode_obj(code, data)
    for lost in ({1}, {2, 3}):
        surviving = {i: enc[i] for i in enc if i not in lost}
        out = recover_stripes(sinfo, code, surviving, lost)
        for i in lost:
            assert np.asarray(out[i], np.uint8).tobytes() == \
                enc[i].tobytes()


def test_lrc_single_loss_plans_inside_local_group():
    """LRC's reason to exist: one lost shard repairs from its LOCAL
    group — fewer helpers than k — and the decode from only those
    helpers is byte-correct (what the engine's 'lrc' strategy and
    its helper_bytes_saved booking rely on)."""
    code = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    k = code.get_data_chunk_count()
    n = code.get_chunk_count()
    plan = code.minimum_to_decode({0}, set(range(n)) - {0})
    assert len(plan) < k, "local repair should need < k helpers"
    data = bytes(range(256)) * 16
    enc = _encode_obj(code, data)
    out = code.decode({0}, {i: enc[i] for i in plan})
    assert np.asarray(out[0], np.uint8).tobytes() == enc[0].tobytes()


# -- helper ledger + reservation book ---------------------------------
def test_helper_ledger_load_and_exclusion_ttl():
    led = HelperLedger()
    led.start(3)
    led.start(3)
    led.note_load(3, 1.5)
    led.note_load(5, 9.0)
    assert led.load(3) > led.load(1)  # in-flight counts
    assert led.load(5) == 9.0
    led.finish(3)
    led.finish(3)

    key = (1, 0, "obj")
    led.exclude(key, 3)
    assert led.excluded(key) == {3}
    assert led.excluded((1, 0, "other")) == set()  # per-object
    # a repeat failure doubles the TTL (capped) so the exclusion
    # outlives the next recovery passes
    led.exclude(key, 3)
    _exp, ttl = led._excluded[key][3]
    assert ttl == 2 * EXCLUDE_BASE_S
    # expiry prunes in place
    led._excluded[key][3] = (time.monotonic() - 1.0, ttl)
    assert led.excluded(key) == set()


def test_reservation_book_bounds_and_releases():
    book = ReservationBook(2)
    assert book.try_acquire() and book.try_acquire()
    assert not book.try_acquire()  # slots exhausted
    book.release()
    assert book.try_acquire()
    for _ in range(5):
        book.release()  # over-release must not go negative
    assert book.held == 0


# -- silent bit rot (store.bit_rot) -----------------------------------
def test_bit_rot_detected_degraded_and_repaired():
    """A flipped byte on a store read must never reach the client:
    crc verification catches it, the read degrades (decode from
    survivors), ``degraded_reads`` books, and the poisoned shard is
    dropped so recovery re-decodes it."""
    c = MiniCluster(n_osds=4, hosts=4, config=_fast_conf()).start()
    try:
        c.create_ec_pool(2, "rot21",
                         {"plugin": "jerasure",
                          "technique": "reed_sol_van",
                          "k": "2", "m": "1", "w": "8"}, pg_num=8)
        cli = c.client("bitrot")
        data = bytes(range(256)) * 8
        cli.put(2, "rotobj", data)
        _pool, ps, up = cli._up(2, "rotobj")
        # global oneshot: the MemStore hook passes no who, so a
        # who-targeted arm would never fire there
        faults.arm("store.bit_rot", "oneshot")
        assert cli.get(2, "rotobj") == data, \
            "bit rot reached the client"
        assert faults.snapshot()["store.bit_rot"] == 1
        assert sum(svc.pc.dump().get("degraded_reads", 0)
                   for svc in c.osds.values()) >= 1
        # every up shard healthy again (the bad one re-decoded)
        cid = pg_cid(2, ps)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if all(c.osds[o].store.stat(cid, f"rotobj.s{pos}")
                   is not None for pos, o in enumerate(up)):
                break
            time.sleep(0.1)
        for pos, o in enumerate(up):
            assert c.osds[o].store.stat(cid, f"rotobj.s{pos}") \
                is not None, "rotted shard never repaired"
        assert cli.get(2, "rotobj") == data
    finally:
        c.shutdown()


# -- helper-read failure: exclusion + same-pass re-plan ---------------
def test_helper_eio_excludes_osd_and_replans_same_pass():
    """The retry-duplication fix: a helper whose read EIO'd is
    EXCLUDED for that object's remaining attempts and the decode is
    re-planned from the remaining survivors — recovery completes in
    the same pass instead of hammering the failed OSD."""
    c = MiniCluster(n_osds=4, hosts=4, config=_fast_conf()).start()
    try:
        c.create_ec_pool(2, "exc22",
                         {"plugin": "jerasure",
                          "technique": "reed_sol_van",
                          "k": "2", "m": "2", "w": "8"}, pg_num=4)
        cli = c.client("excl")
        data = bytes(range(256)) * 8
        cli.put(2, "excobj", data)
        _pool, ps, up = cli._up(2, "excobj")
        primary = up[0]
        cid = pg_cid(2, ps)
        # drop a NON-primary shard so the rebuild needs k=2 helpers,
        # at least one of them remote — the armed EIO hits that read
        c.repair(up[1], 2, ps, "excobj.s1")
        assert c.osds[up[1]].store.stat(cid, "excobj.s1") is None
        faults.arm("osd.shard_read_eio", "count", count=1)
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            if c.osds[up[1]].store.stat(cid, "excobj.s1") is not None:
                break
            time.sleep(0.1)
        assert c.osds[up[1]].store.stat(cid, "excobj.s1") \
            is not None, "shard never rebuilt past the EIO'd helper"
        rec = c.osds[primary].rec_pc.dump()
        assert rec.get("helper_eio_excluded", 0) >= 1, \
            "failed helper was not excluded"
        assert rec.get("replans", 0) >= 1, \
            "decode was not re-planned after the helper failure"
        assert faults.snapshot().get("osd.shard_read_eio") == 1
        assert cli.get(2, "excobj") == data
    finally:
        c.shutdown()


# -- pipeline modes ---------------------------------------------------
@pytest.mark.parametrize("depth,counter", [(1, "serial_batches"),
                                           (3, "pipelined_batches")])
def test_recovery_pipeline_depth_modes(depth, counter):
    """Depth <= 1 degrades to serial gather-then-decode; depth > 1
    streams unit N+1's helper reads while unit N decodes.  Both must
    reconverge losslessly and book their own batch counter (the
    drill's serial-baseline knob depends on the distinction)."""
    conf = _fast_conf(osd_recovery_pipeline_depth=depth,
                      osd_recovery_batch_max_objects=2)
    c = MiniCluster(n_osds=4, hosts=4, config=conf).start()
    try:
        c.create_ec_pool(2, "pipe21",
                         {"plugin": "jerasure",
                          "technique": "reed_sol_van",
                          "k": "2", "m": "1", "w": "8"}, pg_num=4)
        cli = c.client(f"pipe{depth}")
        acked = {}
        for i in range(8):
            val = (b"%02d!" % i) * 300
            cli.put(2, f"p{i}", val)
            acked[f"p{i}"] = val
        victim = 1
        c.kill_osd(victim)
        c.wait_for_down(victim, timeout=20)
        c.revive_osd(victim)  # empty store: real recovery traffic
        c.wait_for_recovery(2, acked, timeout=30)
        for key, val in acked.items():
            assert cli.get(2, key) == val
        total = sum(svc.rec_pc.dump().get(counter, 0)
                    for svc in c.osds.values())
        assert total >= 1, f"{counter} never booked at depth {depth}"
    finally:
        c.shutdown()


def test_recovery_mixed_patterns_one_pass():
    """Objects with DIFFERENT erasure patterns in one PG pass (shard
    1 of one object, shard 2 of another) all recover — the engine
    plans per pattern group and buckets decodes by survivor set."""
    c = MiniCluster(n_osds=4, hosts=4, config=_fast_conf()).start()
    try:
        c.create_ec_pool(2, "mix22",
                         {"plugin": "jerasure",
                          "technique": "reed_sol_van",
                          "k": "2", "m": "2", "w": "8"}, pg_num=1)
        cli = c.client("mix")
        acked = {}
        for i in range(4):
            val = (b"m%d." % i) * 256
            cli.put(2, f"mx{i}", val)
            acked[f"mx{i}"] = val
        _pool, ps, up = cli._up(2, "mx0")
        c.repair(up[1], 2, ps, "mx0.s1")
        c.repair(up[2], 2, ps, "mx1.s2")
        c.repair(up[1], 2, ps, "mx2.s1")
        cid = pg_cid(2, ps)
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            if (c.osds[up[1]].store.stat(cid, "mx0.s1") is not None
                    and c.osds[up[2]].store.stat(
                        cid, "mx1.s2") is not None
                    and c.osds[up[1]].store.stat(
                        cid, "mx2.s1") is not None):
                break
            time.sleep(0.1)
        for oid, osd, pos in (("mx0", up[1], 1), ("mx1", up[2], 2),
                              ("mx2", up[1], 1)):
            assert c.osds[osd].store.stat(
                cid, f"{oid}.s{pos}") is not None, \
                f"{oid} shard {pos} never rebuilt"
        for key, val in acked.items():
            assert cli.get(2, key) == val
    finally:
        c.shutdown()


# -- drill record ingestion -------------------------------------------
def _write_drill(tmp_path, n, **over):
    rec = {"kind": "drill", "seed": 8, "n": n,
           "recovery_mbps": 40.0, "recovery_mbps_serial": 16.0,
           "pipeline_speedup": 2.5, "converge_s": 3.2,
           "lost": 0, "checked": 96,
           "soak": {"p99_ms": 55.0,
                    "slo": {"metric": "degraded_read_p99_ms",
                            "limit": 250.0, "value": 55.0,
                            "pass": True}},
           "ok": True}
    rec.update(over)
    path = tmp_path / f"DRILL_r{n:02d}.json"
    path.write_text(json.dumps(rec))
    return rec


def test_perf_history_ingests_drill_records(tmp_path):
    _write_drill(tmp_path, 1)
    rows = perf_history.load_all(str(tmp_path))
    assert len(rows) == 1
    m = rows[0]["metrics"]
    assert m["drill_recovery_mbs"] == 40.0
    assert m["drill_speedup"] == 2.5
    assert m["drill_p99_ms"] == 55.0
    perf_history.compute_deltas(rows)
    assert rows[0]["regressions"] == []


def test_perf_history_red_checks_drill_failures(tmp_path):
    _write_drill(tmp_path, 1)
    soak = {"p99_ms": 400.0,
            "slo": {"metric": "degraded_read_p99_ms",
                    "limit": 250.0, "value": 400.0, "pass": False}}
    _write_drill(tmp_path, 2, recovery_mbps=10.0, lost=3,
                 pipeline_speedup=1.2, converge_s=None, soak=soak,
                 ok=False)
    rows = perf_history.load_all(str(tmp_path))
    perf_history.compute_deltas(rows)
    regs = " ".join(rows[-1]["regressions"])
    assert "drill_lost_writes=3" in regs
    assert "drill_not_converged" in regs
    assert "drill_slo_fail:degraded_read_p99_ms" in regs
    assert "drill_speedup_below_1.5x" in regs
    # the >25% recovery-MB/s drop red-checks like any throughput
    assert any(r.startswith("drill_recovery_mbs")
               for r in rows[-1]["regressions"])


def test_thrasher_drill_run_numbering(tmp_path):
    _write_drill(tmp_path, 4)
    assert thrasher.next_run_number(str(tmp_path)) == 4


# -- the full drill (slow: two measured clusters + a soak) ------------
@pytest.mark.slow
def test_host_kill_drill_end_to_end():
    rec = thrasher.host_kill_drill(seed=8, n_objects=24,
                                   settle_timeout=120.0)
    assert rec["lost"] == 0
    assert rec["converge_s"] is not None
    assert rec.get("pipeline_speedup", 0) > 1.5, rec


@pytest.mark.slow
def test_degraded_read_soak_end_to_end():
    rec = thrasher.degraded_read_soak(seed=8, duration=5.0,
                                      settle_timeout=120.0)
    assert rec["slo"]["pass"], rec
    assert rec["read_errors"] == 0
