"""Bit-exactness of crush_ln / the straw2 draw vs the golden full-domain sweep."""

import json

import numpy as np
import pytest

from conftest import GOLDEN_DIR

from ceph_tpu.crush import ln as LN
from ceph_tpu.crush.mapper_ref import _straw2_draw, crush_ln_int


@pytest.fixture(scope="module")
def golden():
    return json.load(open(GOLDEN_DIR / "crush_ln.json"))


def test_tables_match_reference(golden):
    np.testing.assert_array_equal(LN.RH_LH_NP,
                                  np.array(golden["RH_LH_tbl"], dtype=np.uint64))
    np.testing.assert_array_equal(LN.LL_NP,
                                  np.array(golden["LL_tbl"], dtype=np.uint64))


def test_full_domain_numpy(golden):
    want = np.array(golden["ln"], dtype=np.uint64)
    got = LN.crush_ln(np.arange(0x10000, dtype=np.uint32))
    np.testing.assert_array_equal(got, want)


def test_full_domain_jax(golden):
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    want = np.array(golden["ln"], dtype=np.uint64)
    with enable_x64():
        tables = (jnp.asarray(LN.RH_LH_NP), jnp.asarray(LN.LL_NP))
        got = jax.jit(lambda v: LN.crush_ln(v, xp=jnp, tables=tables))(
            jnp.arange(0x10000, dtype=jnp.uint32))
        np.testing.assert_array_equal(np.asarray(got), want)


def test_int_port_spot(golden):
    want = golden["ln"]
    for x in list(range(0, 0x10000, 997)) + [0, 1, 0x7FFF, 0x8000, 0xFFFF]:
        assert crush_ln_int(x) == want[x], x


def test_straw2_draw_vectorized_matches_scalar():
    rng = np.random.default_rng(0)
    u = rng.integers(0, 0x10000, size=512).astype(np.uint32)
    w = rng.integers(0, 0x200000, size=512).astype(np.uint32)
    w[::17] = 0  # exercise the zero-weight S64_MIN path
    got = LN.straw2_draw(u, w)
    for i in range(512):
        # scalar: ln-and-divide with python ints (trunc toward zero)
        if int(w[i]) == 0:
            want = -(2**63)
        else:
            ln = crush_ln_int(int(u[i])) - 0x1000000000000
            want = -((-ln) // int(w[i]))
        assert int(got[i]) == want, (i, u[i], w[i])


def test_straw2_draw_scalar_ref():
    # _straw2_draw composes hash+ln+div; check a couple of hand cases
    assert _straw2_draw(0, 1, 2, 0, 0) == -(2**63)
    d = _straw2_draw(0, 1, 2, 0, 0x10000)
    assert -(2**48) <= d <= 0


def test_ln16_table_matches_crush_ln():
    tab = LN.ln16_table()
    np.testing.assert_array_equal(
        tab, LN.crush_ln(np.arange(0x10000, dtype=np.uint32)))


def test_straw2_key_selects_identically_to_draw():
    """The division-free key must order every (u, w) pair exactly like the
    reference draw: argmin(key) == first-argmax(draw), including zero
    weights, w=1, saturated weights, and the neg extremes."""
    rng = np.random.default_rng(7)
    u = rng.integers(0, 0x10000, size=4096).astype(np.uint32)
    w = rng.integers(0, 1 << 32, size=4096, dtype=np.uint64).astype(np.uint32)
    w[::13] = 0
    w[1::13] = 1
    w[2::13] = 0x10000
    w[3::13] = 0xFFFFFFFF
    u[::29] = 0xFFFF   # ln = 2^48 -> neg = 0
    u[1::29] = 0       # smallest ln -> largest neg
    rec = LN.recip64(w)
    key = LN.straw2_key(u, w, rec)
    draw = LN.straw2_draw(u, w)
    # exact q equality where w > 0
    nz = w > 0
    np.testing.assert_array_equal(key[nz].astype(np.int64), -draw[nz])
    assert (key[~nz] == np.uint64(0xFFFFFFFFFFFFFFFF)).all()
    # selection equivalence over random rows
    for row in range(64):
        sl = slice(row * 64, row * 64 + 64)
        assert int(np.argmin(key[sl])) == int(np.argmax(draw[sl]))
