"""Mini-cluster integration tests — the qa/standalone tier.

Mirrors qa/standalone/erasure-code/test-erasure-code.sh (EC pool
write/read end-to-end through real daemons on one host) and the
thrashosds flow (kill → mark-down → degraded reads → revive →
recovery/backfill → clean), plus messenger and map-epoch mechanics.
"""

import io
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.msg.messenger import Messenger
from ceph_tpu.services.cluster import MiniCluster


# -- messenger ---------------------------------------------------------------

def test_messenger_call_and_send():
    a = Messenger("a")
    b = Messenger("b")
    got = []
    b.register("echo", lambda m: {"echo": m["x"]})
    b.register("note", lambda m: got.append(m["x"]))
    a.start()
    b.start()
    try:
        assert a.call(b.addr, {"type": "echo", "x": 5}) == {"echo": 5}
        assert "error" in a.call(b.addr, {"type": "nope"})
        a.send(b.addr, {"type": "note", "x": "fire-and-forget"})
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got == ["fire-and-forget"]
        big = "ab" * 300000  # 600 KB frame
        assert a.call(b.addr, {"type": "echo", "x": big}) == \
            {"echo": big}
    finally:
        a.shutdown()
        b.shutdown()


# -- monitor boot/out semantics (unit; no daemons started) -------------------

def test_boot_weight_policy():
    """OSDMonitor::prepare_boot weight policy: an admin mark_out sticks
    across reboot; an auto-out is undone by reboot; a known osd keeps
    its weight; every map change gets a commit (epoch bump)."""
    from ceph_tpu.common.context import Context
    from ceph_tpu.crush.wrapper import CrushWrapper
    from ceph_tpu.osdmap.osdmap import OSDMap
    from ceph_tpu.services.monitor import Monitor

    w = CrushWrapper()
    for d in range(3):
        w.insert_item(d, 0x10000, f"osd.{d}",
                      {"host": f"h{d}", "root": "default"})
    mon = Monitor(Context(), OSDMap(w.crush))
    try:
        mon._commit("genesis")
        for d in range(3):
            mon._h_boot({"osd": d, "addr": ["127.0.0.1", 7000 + d]})
        # admin out, then reboot: weight must STAY 0
        mon._h_mark_out({"osd": 1})
        e = mon.map.epoch
        mon._h_boot({"osd": 1, "addr": ["127.0.0.1", 7001]})
        assert mon.map.osd_weight[1] == 0
        # unchanged reboot → no epoch churn
        mon._h_boot({"osd": 2, "addr": ["127.0.0.1", 7002]})
        assert mon.map.epoch == e
        # auto-out (monitor-initiated), then reboot: weight restored,
        # and the change is committed so the stored epoch matches
        mon.mark_down(2)
        with mon._lock:
            mon._auto_out[2] = mon.map.osd_weight[2]
            mon.map.osd_weight[2] = 0
        mon._commit("osd.2 auto-out")
        mon._h_boot({"osd": 2, "addr": ["127.0.0.1", 7002]})
        assert mon.map.osd_weight[2] == 0x10000
        stored = mon.get_epoch_payload(mon.map.epoch)
        assert stored["map"]["osd_weight"][2] == 0x10000
    finally:
        mon.msgr.shutdown()


# -- cluster ------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.0)
    conf.set("mon_osd_down_out_interval", 1.0)
    cl = MiniCluster(n_osds=5, config=conf).start()
    cl.create_replicated_pool(1, pg_num=8, size=3)
    cl.create_ec_pool(2, "k2m2", {"plugin": "jerasure",
                                  "technique": "reed_sol_van",
                                  "k": "2", "m": "2", "w": "8"},
                      pg_num=8)
    yield cl
    cl.shutdown()


def test_cluster_boots(cluster):
    st = cluster.status()
    assert sorted(st["up_osds"]) == [0, 1, 2, 3, 4]
    assert st["num_pools"] == 2
    assert st["epoch"] > 1


def test_replicated_write_read(cluster):
    c = cluster.client("repl")
    data = b"replicated payload " * 100
    c.put(1, "obj-r", data)
    assert c.get(1, "obj-r") == data


def test_ec_write_read(cluster):
    c = cluster.client("ec")
    data = bytes(range(256)) * 37  # unaligned size
    c.put(2, "obj-e", data)
    assert c.get(2, "obj-e") == data


def test_copy_ledger_books_every_site(cluster, monkeypatch):
    """Satellite regression: r13 shipped ec_assembly=0 in every BENCH
    record because the write lane's booking was dropped.  After an EC
    write burst (plus one real recovery push) every copy-ledger site
    must carry nonzero traffic — a zero site means its call path lost
    the booking, not that the path went copy-free."""
    from ceph_tpu.common import copytrack
    from ceph_tpu.msg import messenger as _msgr

    c = cluster.client("ledger")
    for i in range(8):
        c.put(2, f"obj-cl{i}", bytes(range(256)) * 16)

    # the uncontended sendmsg fast path books nothing (no userspace
    # join happens), so "send booked zero" would be correct-and-green
    # there; drive a couple of writes down the join fallback so the
    # send site's booking itself is exercised deterministically
    monkeypatch.setattr(_msgr, "_HAS_SENDMSG", False)
    for i in range(2):
        c.put(2, f"obj-cl-join{i}", bytes(range(256)) * 16)
    monkeypatch.setattr(_msgr, "_HAS_SENDMSG", True)

    # recovery_push books only on the recovery lane: drive one real
    # push to a remote holder under recovery QoS
    src = cluster.osds[min(cluster.osds)]
    dst = next(i for i in cluster.osds if i != src.id)
    blob = b"recovered-shard" * 64
    rep = src._push_shard(2, 0, dst, "obj-cl-push", 0, blob,
                          len(blob), None, qos="recovery")
    assert rep is not None and rep.get("ok")
    # the pushed shard is an orphan (1 shard of a k=2,m=2 object that
    # never existed) — tombstone it so the module-scoped cluster's
    # later health/recovery tests don't inherit an unrecoverable pg
    cluster.osds[dst]._h_obj_delete(
        {"type": "obj_delete", "pool": 2, "ps": 0,
         "oid": "obj-cl-push", "v": None, "force": True})

    totals = {}
    for svc in cluster.osds.values():
        for k, v in svc.ctx.perf.dump().get(
                copytrack.LOGGER, {}).items():
            if isinstance(v, (int, float)):
                totals[k] = totals.get(k, 0) + v
    for site in copytrack.SITES:
        assert totals.get(f"{site}_bytes", 0) > 0, \
            f"copy-ledger site {site!r} booked zero bytes"
        assert totals.get(f"{site}_copies", 0) > 0, \
            f"copy-ledger site {site!r} booked zero copies"


def test_degraded_read_and_recovery(cluster):
    """The full elastic-recovery loop: kill an OSD holding a shard,
    reads still succeed degraded, mon marks it down, the remapped OSD
    backfills the shard, cluster returns to clean."""
    c = cluster.client("thrash")
    objs = {f"obj-t{i}": None for i in range(6)}
    payload = {}
    for oid in objs:
        payload[oid] = (oid.encode() + b"-") * 200
        c.put(2, oid, payload[oid])
    cluster.wait_for_recovery(2, payload, timeout=20)

    victim = cluster.status()["up_osds"][0]
    cluster.kill_osd(victim)
    cluster.wait_for_down(victim, timeout=10)

    # degraded reads: every object still comes back
    for oid, data in payload.items():
        assert c.get(2, oid) == data

    # after remap, surviving OSDs backfill the lost shards
    cluster.wait_for_recovery(2, payload, timeout=30)

    # revive: the osd rejoins, map epoch bumps, and it backfills
    # whatever the new map assigns it
    cluster.revive_osd(victim)
    cluster.wait_for_up(victim, timeout=10)
    cluster.wait_for_recovery(2, payload, timeout=30)
    for oid, data in payload.items():
        assert c.get(2, oid) == data


def test_perf_counters_and_pglog(cluster):
    """Observability: daemons expose perf counters; every PG carries
    an auditable log of writes/recoveries."""
    some_osd = next(iter(cluster.osds.values()))
    st = some_osd.msgr.call(some_osd.addr, {"type": "status"})
    assert "perf" in st and "ops_w" in st["perf"]
    logged = 0
    for svc in cluster.osds.values():
        for cid in svc.store.list_collections():
            logged += len(svc.store.omap_get(cid, "pglog"))
    assert logged > 0


def test_scrub_detects_and_repairs_corruption(cluster):
    """The deep-scrub + EIO-repair loop (test-erasure-eio.sh role):
    flip bits in a stored shard, scrub flags it, repair drops it, and
    recovery re-decodes it from the survivors."""
    c = cluster.client("scrub")
    data = b"scrub-payload " * 150
    c.put(2, "obj-scrub", data)
    cluster.wait_for_recovery(2, {"obj-scrub": None}, timeout=20)
    assert cluster.scrub(2) == {}  # clean

    # white-box corruption of one stored shard (EIO injection)
    from ceph_tpu.services.client import object_to_ps
    ps = object_to_ps("obj-scrub") % 8
    payload = cluster.mon.msgr.call(cluster.mon.addr,
                                    {"type": "get_map"})
    from ceph_tpu.osdmap.osdmap import OSDMap
    from ceph_tpu.osdmap.bincode_maps import payload_map
    m = payload_map(payload)
    up, _p, _a, _ap = m.pg_to_up_acting_osds(2, ps)
    victim_osd = up[1]
    svc = cluster.osds[victim_osd]
    cid = f"2.{ps}"
    name = "obj-scrub.s1"
    svc.store._coll[cid][name].data[0] ^= 0xFF

    bad = cluster.scrub(2)
    assert victim_osd in bad
    assert (2, ps, name) in bad[victim_osd]

    cluster.repair(victim_osd, 2, ps, name)
    cluster.wait_for_recovery(2, {"obj-scrub": None}, timeout=20)
    assert cluster.scrub(2) == {}
    assert c.get(2, "obj-scrub") == data


def test_striped_objects_over_ec_pool(cluster):
    """Striping composes with EC: a large logical object striped over
    backing objects, each EC-coded (the §5 long-context axis)."""
    from ceph_tpu.services.striper import Striper

    c = cluster.client("striper")
    s = Striper(c, stripe_unit=512, stripe_count=3)
    data = bytes(range(256)) * 20  # 5120 bytes -> several pieces
    s.write(2, "bigobj", data)
    assert s.read(2, "bigobj") == data
    assert s.read(2, "bigobj", 1000, 600) == data[1000:1600]


def test_image_block_device_over_ec(cluster):
    """librbd-analogue flow: create image, random-offset writes,
    snapshot, diverge, read-snap, rollback — over the EC pool."""
    from ceph_tpu.services.image import Image, ImageError

    c = cluster.client("rbd")
    img = Image.create(c, 2, "vm-disk", size=1 << 16,
                       stripe_unit=512, stripe_count=3,
                       object_size=2048)
    with pytest.raises(ImageError):
        Image.create(c, 2, "vm-disk", size=1)

    img.write(0, b"BOOT" * 128)            # 512B at 0
    img.write(10_000, b"data-at-10k" * 10)
    assert img.read(0, 512) == b"BOOT" * 128
    assert img.read(10_000, 110) == (b"data-at-10k" * 10)
    assert img.read(30_000, 16) == b"\0" * 16  # unwritten = zeros
    with pytest.raises(ImageError):
        img.write(img.size - 1, b"xx")

    img.snapshot("s1")
    img.write(0, b"OVERWRITTEN!")
    assert img.read(0, 12) == b"OVERWRITTEN!"
    assert img.read_snap("s1", 0, 12) == b"BOOT" * 3
    img.rollback("s1")
    assert img.read(0, 512) == b"BOOT" * 128

    img2 = Image.open(c, 2, "vm-disk")
    assert img2.size == 1 << 16
    assert img2.snaps() == ["s1"]
    assert img2.read(10_000, 110) == (b"data-at-10k" * 10)
    img2.resize(1 << 17)
    assert Image.open(c, 2, "vm-disk").size == 1 << 17

    # shrink discards: grow back reads zeros, not resurrected bytes
    img2.write(50_000, b"SECRET")
    img2.resize(4096)
    img2.resize(1 << 17)
    assert img2.read(50_000, 6) == b"\0" * 6
    # snapshots keep their own size across a shrink
    assert img2.read_snap("s1", 0, 12) == b"BOOT" * 3
    # shrink must NOT clobber live data interleaved in the same
    # backing object as truncated stripe units
    img2.write(0, b"LIVE" * 128)         # unit 0 -> object 0
    img2.write(3 * 512, b"gone" * 128)   # later unit, same object set
    img2.resize(512)                     # keep only unit 0
    img2.resize(1 << 17)
    assert img2.read(0, 512) == b"LIVE" * 128
    assert img2.read(3 * 512, 512) == b"\0" * 512


def test_map_epoch_catchup(cluster):
    """Any epoch in the retained window is servable — the
    MonitorDBStore resume-at-any-epoch property."""
    st = cluster.status()
    cur = st["epoch"]
    old = cluster.mon.msgr.call(cluster.mon.addr,
                                {"type": "get_map", "epoch": cur - 1})
    assert old["epoch"] == cur - 1
    assert "map_bin" in old or "map" in old  # wire form is binary
    missing = cluster.mon.msgr.call(cluster.mon.addr,
                                    {"type": "get_map", "epoch": 10 ** 9})
    assert "error" in missing


def test_ec_partial_stripe_overwrite(cluster):
    """VERDICT #7 acceptance: non-aligned overwrites on an EC pool
    round-trip — create, overwrite mid-object, extend past the end,
    write into a hole — all through the primary-coordinated RMW op."""
    c = cluster.client("rmw")
    base = bytes(range(256)) * 13  # 3328 B, deliberately unaligned
    c.put(2, "rmw-obj", base)

    # unaligned interior overwrite
    patch = b"PATCHED!" * 5
    c.write(2, "rmw-obj", 1001, patch)
    want = bytearray(base)
    want[1001:1001 + len(patch)] = patch
    assert c.get(2, "rmw-obj") == bytes(want)

    # extend past the current end
    tail = b"-tail-bytes-"
    c.write(2, "rmw-obj", len(want) + 100, tail)
    want = want + bytes(100) + tail
    assert c.get(2, "rmw-obj") == bytes(want)

    # offset write into a brand-new object (hole-fill semantics)
    c.write(2, "rmw-new", 64, b"deep")
    assert c.get(2, "rmw-new") == bytes(64) + b"deep"


def test_ec_degraded_overwrite(cluster):
    """Partial overwrite while a shard holder is down: the RMW decodes
    from survivors, writes degraded, and recovery completes the
    missing position after revive."""
    c = cluster.client("rmw-deg")
    base = b"0123456789abcdef" * 100
    c.put(2, "deg-obj", base)
    cluster.wait_for_recovery(2, {"deg-obj": None}, timeout=20)

    victim = cluster.status()["up_osds"][-1]
    cluster.kill_osd(victim)
    cluster.wait_for_down(victim, timeout=10)

    patch = b"DEGRADED-WRITE"
    c.write(2, "deg-obj", 333, patch)
    want = bytearray(base)
    want[333:333 + len(patch)] = patch
    assert c.get(2, "deg-obj") == bytes(want)

    cluster.revive_osd(victim)
    cluster.wait_for_up(victim, timeout=10)
    cluster.wait_for_recovery(2, {"deg-obj": None}, timeout=30)
    assert c.get(2, "deg-obj") == bytes(want)


def test_watch_notify(cluster):
    """librados watch/notify: a watcher gets every notify with its
    payload and the notifier collects acks; registration follows the
    PG primary across map changes (re-watch on epoch)."""
    import threading
    import time as _time

    watcher = cluster.client("watcher")
    notifier = cluster.client("notifier")
    got = []
    ev = threading.Event()

    def cb(oid, payload, notifier_name):
        got.append((oid, payload, notifier_name))
        ev.set()

    watcher.put(1, "watched", b"state-0")
    watcher.watch(1, "watched", cb)
    rep = notifier.notify(1, "watched", {"event": "flush", "n": 1})
    assert "client.watcher" in rep["acks"] or \
        "watcher" in str(rep["acks"])
    assert ev.wait(timeout=5)
    assert got[0][0] == "watched" and got[0][1]["event"] == "flush"

    # unwatch: no further delivery, notifier sees zero acks
    watcher.unwatch(1, "watched")
    ev.clear()
    rep = notifier.notify(1, "watched", {"event": "x"})
    assert rep["acks"] == []
    assert not ev.wait(timeout=1.0)


def test_watch_survives_primary_move(cluster):
    """Kill the PG primary: after remap + re-watch, notifies reach the
    watcher through the new primary."""
    import threading

    watcher = cluster.client("watcher2")
    notifier = cluster.client("notifier2")
    ev = threading.Event()
    watcher.put(1, "roaming", b"x")
    watcher.watch(1, "roaming", lambda *a: ev.set())

    _pool, _ps, up = watcher._up(1, "roaming")
    victim = up[0]
    cluster.kill_osd(victim)
    cluster.wait_for_down(victim, timeout=10)

    import time as _time

    deadline = _time.monotonic() + 15
    while _time.monotonic() < deadline:
        notifier.refresh_map()
        watcher.refresh_map()
        try:
            rep = notifier.notify(1, "roaming", {"ping": 1})
            if rep.get("acks"):
                break
        except Exception:
            pass
        _time.sleep(0.5)
    assert ev.wait(timeout=5), "notify never reached the watcher " \
        "after primary failover"
    cluster.revive_osd(victim)
    cluster.wait_for_up(victim, timeout=10)


def test_image_clone_cow_and_flatten(cluster):
    """librbd clone semantics: protect -> clone (no data copied) ->
    child reads fall through to the parent snap, child writes COW,
    flatten detaches, unprotect guarded by children."""
    import pytest as _pytest

    from ceph_tpu.services.image import Image, ImageError

    cli = cluster.client("rbd-clone")
    img = Image.create(cli, 1, "parent-img", 64 * 1024,
                       object_size=16 * 1024)
    img.write(0, b"P" * 1000)
    img.write(30_000, b"Q" * 500)
    img.snapshot("s1")
    with _pytest.raises(ImageError):
        img.clone("s1", "child-unprotected")
    img.protect_snap("s1")
    child = img.clone("s1", "child-img")

    # child sees parent data without copies, parent changes don't leak
    assert child.read(0, 1000) == b"P" * 1000
    img.write(0, b"X" * 1000)  # post-snap parent write
    assert child.read(0, 1000) == b"P" * 1000
    # COW: child write covers only its range; rest still parent's
    child.write(100, b"c" * 50)
    got = child.read(0, 1000)
    assert got[:100] == b"P" * 100 and got[100:150] == b"c" * 50 \
        and got[150:] == b"P" * 850
    assert child.read(30_000, 500) == b"Q" * 500

    # unprotect refused while the child exists; flatten releases it
    with _pytest.raises(ImageError):
        img.unprotect_snap("s1")
    child.flatten()
    assert child.read(0, 100) == b"P" * 100
    assert child.read(30_000, 500) == b"Q" * 500
    img.unprotect_snap("s1")

    # shrink-then-grow exposes zeros, never stale parent bytes
    child2 = None
    img.protect_snap("s1")
    child2 = img.clone("s1", "child2-img")
    child2.resize(1024)
    child2.resize(40_000)
    assert child2.read(30_000, 500) == bytes(500)


def test_health_and_pg_states(cluster):
    """The PGMap/health surface: all-clean reports HEALTH_OK; killing
    an OSD surfaces down-osd and degraded checks; recovery + revive
    return to HEALTH_OK."""
    import time as _time

    cluster.wait_for_health_ok(timeout=40)
    st = cluster.status()
    assert st["pgmap"]["pgs_reported"] == st["pgmap"]["pgs_total"]
    assert all("clean" in s for s in st["pgmap"]["by_state"])

    victim = cluster.status()["up_osds"][0]
    cluster.kill_osd(victim)
    cluster.wait_for_down(victim, timeout=10)
    deadline = _time.monotonic() + 20
    saw_warn = False
    while _time.monotonic() < deadline:
        h = cluster.health()
        if h["status"] == "HEALTH_WARN" and \
                any("down" in c for c in h["checks"]):
            saw_warn = True
            break
        _time.sleep(0.3)
    assert saw_warn, "no HEALTH_WARN after killing an osd"

    cluster.revive_osd(victim)
    cluster.wait_for_up(victim, timeout=10)
    cluster.wait_for_health_ok(timeout=40)


def test_pg_log_trim(cluster):
    """After a clean pass, each member's PG log keeps only the newest
    record per object (older history trimmed)."""
    import time as _time

    from ceph_tpu.common.encoding import MalformedInput
    from ceph_tpu.services.pg_log import PgLogEntry

    c = cluster.client("trim")
    for i in range(10):
        c.put(1, "trim-obj", f"gen-{i}".encode() * 50)
    # force a peering pass (epoch bump via a pg_temp-free poke)
    for svc in cluster.osds.values():
        svc._recover_wake.set()
    deadline = _time.monotonic() + 20
    trimmed = False
    while _time.monotonic() < deadline and not trimmed:
        counts = []
        for svc in cluster.osds.values():
            for cid in svc.store.list_collections():
                if not cid.startswith("1."):
                    continue
                per_oid = {}
                for key, raw in svc.store.omap_get(
                        cid, "pglog").items():
                    try:
                        rec = PgLogEntry.decode_blob(raw)
                    except MalformedInput:
                        continue
                    if rec.oid == "trim-obj":
                        per_oid.setdefault("trim-obj", []).append(key)
                if per_oid:
                    counts.append(len(per_oid["trim-obj"]))
        trimmed = bool(counts) and all(n <= 2 for n in counts)
        _time.sleep(0.5)
    assert trimmed, f"log never trimmed: {counts}"


def test_scheduled_scrub_auto_repairs(tmp_path):
    """Periodic deep scrub (no manual scrub call): a corrupted shard
    is detected by the scheduled pass, dropped, and re-decoded."""
    import time as _time

    from ceph_tpu.common.config import Config as _Config
    from ceph_tpu.services.cluster import MiniCluster as _MC
    from ceph_tpu.services.client import object_to_ps
    from ceph_tpu.ec.stripe import crc32c as _crc

    conf = _Config()
    conf.set("osd_heartbeat_interval", 0.3)
    conf.set("osd_heartbeat_grace", 3.0)
    conf.set("osd_scrub_interval", 2.0)
    c = _MC(n_osds=4, config=conf).start()
    try:
        c.create_ec_pool(2, "sk21", {"plugin": "jerasure",
                                     "technique": "reed_sol_van",
                                     "k": "2", "m": "1", "w": "8"},
                         pg_num=8)
        cli = c.client("sched-scrub")
        data = b"scheduled-scrub " * 120
        cli.put(2, "ss-obj", data)
        c.wait_for_recovery(2, {"ss-obj": None}, timeout=20)

        ps = object_to_ps("ss-obj") % 8
        payload = c.mon_command({"type": "get_map"})
        from ceph_tpu.osdmap.osdmap import OSDMap as _OM
        from ceph_tpu.osdmap.bincode_maps import payload_map as _pm
        m = _pm(payload)
        up, _p, _a, _ap = m.pg_to_up_acting_osds(2, ps)
        victim = c.osds[up[1]]
        cid = f"2.{ps}"
        victim.store._coll[cid]["ss-obj.s1"].data[3] ^= 0x5A

        # no manual scrub: the scheduled pass must find and fix it
        deadline = _time.monotonic() + 40
        fixed = False
        while _time.monotonic() < deadline and not fixed:
            obj = victim.store._coll.get(cid, {}).get("ss-obj.s1")
            if obj is not None:
                stored = victim.store.getattr(cid, "ss-obj.s1", "crc")
                fixed = stored is not None and \
                    int(stored) == _crc(bytes(obj.data))
            _time.sleep(0.5)
        assert fixed, "scheduled scrub never repaired the shard"
        assert cli.get(2, "ss-obj") == data
    finally:
        c.shutdown()


def test_image_on_ec_pool(cluster):
    """RBD-on-EC (the erasure-coded data-pool feature): a striped
    image's RMW read/write, snapshot, and clone flows all ride the
    primary-coordinated EC write path."""
    from ceph_tpu.services.image import Image

    cli = cluster.client("rbd-ec")
    img = Image.create(cli, 2, "ec-img", 48 * 1024,
                       object_size=8 * 1024)
    img.write(0, b"EC-HEAD" * 100)
    img.write(20_000, b"EC-TAIL" * 100)
    assert img.read(0, 700) == (b"EC-HEAD" * 100)
    assert img.read(20_000, 700) == (b"EC-TAIL" * 100)
    # interior RMW within one piece: the FULL window must match, so a
    # merge that corrupts neighbors of the patched range is caught
    img.write(100, b"patch!")
    base = b"EC-HEAD" * 100
    want = bytearray(base)
    want[100:106] = b"patch!"
    assert img.read(95, 16) == bytes(want[95:111])

    img.snapshot("ecsnap")
    img.protect_snap("ecsnap")
    child = img.clone("ecsnap", "ec-img-child")
    img.write(0, b"X" * 700)
    assert child.read(100, 6) == b"patch!"  # COW isolation
    child.flatten()
    img.unprotect_snap("ecsnap")
    assert child.read(20_000, 7) == b"EC-TAIL"
