"""OSDMap pipeline: batched JAX vs the scalar spec, across every stage.

Covers the scenario matrix of the reference's TestOSDMap.cc: down/out
OSDs, pg_upmap / pg_upmap_items rejection rules, pg_temp / primary_temp
overlays, primary affinity, replicated (shifting) vs erasure
(positional) pools, and non-power-of-two pg_num (stable_mod).
"""

import numpy as np
import pytest

import conftest  # noqa: F401

from ceph_tpu.crush.builder import sample_cluster_map
from ceph_tpu.crush.constants import CRUSH_ITEM_NONE as NONE
from ceph_tpu.osdmap.osdmap import (OSDMap, PgPool, POOL_TYPE_ERASURE,
                                    POOL_TYPE_REPLICATED)
from ceph_tpu.osdmap.pipeline_jax import PoolMapper


def make_map(n_osd=48, pg_num=128):
    cmap = sample_cluster_map(3, 4, 4)
    m = OSDMap(cmap)
    for o in range(n_osd):
        m.add_osd(o)
    m.pools[1] = PgPool(pool_type=POOL_TYPE_REPLICATED, size=3,
                        pg_num=pg_num, crush_rule=0)
    m.pools[2] = PgPool(pool_type=POOL_TYPE_ERASURE, size=6,
                        pg_num=pg_num, crush_rule=1)
    return m


def assert_match(m, pool_id, note=""):
    pm = PoolMapper(m, pool_id)
    out = pm.map_all()
    up = np.asarray(out["up"])
    ulen = np.asarray(out["up_len"])
    uprim = np.asarray(out["up_primary"])
    act = np.asarray(out["acting"])
    alen = np.asarray(out["acting_len"])
    aprim = np.asarray(out["acting_primary"])
    pool = m.pools[pool_id]
    for ps in range(pool.pg_num):
        w_up, w_up_p, w_act, w_act_p = m.pg_to_up_acting_osds(pool_id, ps)
        g_up = list(up[ps, :ulen[ps]])
        g_act = list(act[ps, :alen[ps]])
        assert g_up == w_up, (note, pool_id, ps, "up", g_up, w_up)
        assert uprim[ps] == w_up_p, (note, pool_id, ps, "up_primary")
        assert g_act == w_act, (note, pool_id, ps, "acting", g_act, w_act)
        assert aprim[ps] == w_act_p, (note, pool_id, ps, "act_primary")


def test_clean_cluster():
    m = make_map()
    assert_match(m, 1, "clean-rep")
    assert_match(m, 2, "clean-ec")


def test_down_and_out_osds():
    m = make_map()
    for o in (3, 17, 40):
        m.osd_state[o] &= ~2  # down
    m.osd_weight[8] = 0       # out
    m.osd_weight[22] = 0x8000  # half in
    assert_match(m, 1, "down-rep")
    assert_match(m, 2, "down-ec")


def test_nonexistent_osd():
    m = make_map()
    m.osd_state[30] = 0  # does not exist
    assert_match(m, 1, "dne-rep")
    assert_match(m, 2, "dne-ec")


def test_pg_upmap_full():
    m = make_map()
    m.pg_upmap[(1, 5)] = [1, 2, 3]
    m.pg_upmap[(1, 9)] = [4, 5, 44]
    m.pg_upmap[(2, 7)] = [0, 1, 2, 3, 4, 5]
    # rejected: target marked out
    m.osd_weight[10] = 0
    m.pg_upmap[(1, 11)] = [10, 11, 12]
    assert_match(m, 1, "upmap-rep")
    assert_match(m, 2, "upmap-ec")


def test_pg_upmap_items():
    m = make_map()
    pm0 = PoolMapper(m, 1)
    up0 = np.asarray(pm0.map_all()["up"])
    # remap first osd of pg 3 to osd 47, and a no-op pair
    src = int(up0[3, 0])
    m.pg_upmap_items[(1, 3)] = [(src, 47), (200, 5)]
    # pair whose target already appears in the set (must be skipped)
    src2 = int(up0[4, 0])
    tgt2 = int(up0[4, 1])
    m.pg_upmap_items[(1, 4)] = [(src2, tgt2)]
    # pair whose target is marked out (must be skipped)
    m.osd_weight[46] = 0
    src3 = int(up0[6, 1])
    m.pg_upmap_items[(1, 6)] = [(src3, 46)]
    assert_match(m, 1, "upmap-items")


def test_pg_temp_and_primary_temp():
    m = make_map()
    m.pg_temp[(1, 2)] = [9, 10, 11]
    m.pg_temp[(2, 2)] = [0, 1, 2, 3, 4, 5]
    m.primary_temp[(1, 8)] = 33
    m.pg_temp[(1, 12)] = [20, 21]
    m.primary_temp[(1, 12)] = 21
    # temp containing a down osd
    m.osd_state[10] &= ~2
    # temp that filters to empty (all down) -> falls back to up
    m.osd_state[44] &= ~2
    m.osd_state[45] &= ~2
    m.pg_temp[(1, 14)] = [44, 45]
    assert_match(m, 1, "temp-rep")
    assert_match(m, 2, "temp-ec")


def test_primary_affinity():
    m = make_map()
    m.set_primary_affinity(0, 0)        # never primary
    m.set_primary_affinity(7, 0x8000)   # half
    m.set_primary_affinity(13, 0x4000)  # quarter
    assert_match(m, 1, "paff-rep")
    assert_match(m, 2, "paff-ec")
    # osd.0 must never be primary where alternatives exist
    pm = PoolMapper(m, 1)
    out = pm.map_all()
    uprim = np.asarray(out["up_primary"])
    ulen = np.asarray(out["up_len"])
    assert not ((uprim == 0) & (ulen > 1)).any()


def test_non_pow2_pg_num():
    m = make_map(pg_num=100)  # stable_mod split domain
    assert_match(m, 1, "pg100-rep")
    m2 = make_map(pg_num=96)
    m2.pools[2].pgp_num = 48  # pgp < pg
    assert_match(m2, 2, "pgp48-ec")


def test_everything_at_once():
    m = make_map()
    for o in (3, 17):
        m.osd_state[o] &= ~2
    m.osd_weight[8] = 0
    m.set_primary_affinity(7, 0x8000)
    m.pg_upmap[(1, 5)] = [1, 2, 3]
    m.pg_upmap_items[(1, 7)] = [(0, 47), (1, 46)]
    m.pg_temp[(1, 2)] = [9, 10, 11]
    m.primary_temp[(1, 2)] = 10
    assert_match(m, 1, "combo")


def test_refresh_tables():
    m = make_map()
    pm = PoolMapper(m, 1)
    up0 = np.asarray(pm.map_all()["up"])

    def check(note):
        out = pm.map_all()
        up = np.asarray(out["up"])
        ulen = np.asarray(out["up_len"])
        for ps in range(m.pools[1].pg_num):
            w_up, _, _, _ = m.pg_to_up_acting_osds(1, ps)
            assert list(up[ps, :ulen[ps]]) == w_up, (note, ps)

    # stage appears: upmap_items added after build -> rebuild path
    m.pg_upmap_items[(1, 3)] = [(int(up0[3, 0]), 47)]
    pm.refresh_tables()
    check("refresh-new-stage")
    # same stage, more pairs per pg -> relower + retrace path
    m.pg_upmap_items[(1, 5)] = [(int(up0[5, 0]), 46),
                                (int(up0[5, 1]), 45)]
    pm.refresh_tables()
    check("refresh-more-pairs")


def test_oversized_upmap_rejected():
    m = make_map()
    m.pg_upmap[(1, 5)] = [1, 2, 3, 4]  # longer than pool size 3
    with pytest.raises(ValueError):
        PoolMapper(m, 1)


def test_stale_out_of_range_entries_ignored():
    m = make_map(pg_num=16)
    m.pg_temp[(1, 20)] = [1, 2, 3]  # ps >= pg_num: unreachable
    assert_match(m, 1, "stale-temp")


def test_osdmap_json_roundtrip():
    m = make_map()
    m.pg_upmap[(1, 5)] = [1, 2, 3]
    m.pg_temp[(1, 2)] = [9, 10, 11]
    m.primary_temp[(1, 8)] = 33
    m2 = OSDMap.from_json(m.to_json())
    for ps in (0, 2, 5, 8, 31):
        assert m.pg_to_up_acting_osds(1, ps) == \
            m2.pg_to_up_acting_osds(1, ps)
