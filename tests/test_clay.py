"""CLAY plugin tests — mirrors src/test/erasure-code/
TestErasureCodeClay.cc: geometry (q, t, nu, sub_chunk_no), full
encode/decode round-trips, and the bandwidth-optimal single-node
repair path reading only d helpers x 1/q of each chunk."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec.clay import make_clay
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import factory


def _obj(n, seed=31):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_geometry():
    code = make_clay({"k": "4", "m": "2"})  # d defaults to k+m-1=5
    assert (code.q, code.t, code.nu) == (2, 3, 0)
    assert code.get_sub_chunk_count() == 8
    assert code.get_chunk_count() == 6

    code = make_clay({"k": "3", "m": "3", "d": "4"})
    assert code.q == 2
    assert code.nu == 0
    code = make_clay({"k": "4", "m": "3", "d": "6"})
    assert (code.q, code.nu) == (3, 2)  # k+m=7 padded to 9
    assert code.t == 3
    assert code.get_sub_chunk_count() == 27


def test_parse_validation():
    with pytest.raises(ErasureCodeError):
        make_clay({"k": "4", "m": "2", "d": "3"})  # d < k
    with pytest.raises(ErasureCodeError):
        make_clay({"k": "4", "m": "2", "d": "6"})  # d > k+m-1
    with pytest.raises(ErasureCodeError):
        make_clay({"k": "4", "m": "2", "scalar_mds": "nope"})


def test_roundtrip_and_all_erasures():
    code = factory("clay", {"k": "4", "m": "2"})
    raw = _obj(6000)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    assert code.decode_concat(chunks)[:len(raw)] == raw
    for r in (1, 2):
        for erased in itertools.combinations(range(n), r):
            avail = {i: c for i, c in chunks.items()
                     if i not in erased}
            got = code.decode_concat(avail)
            assert got[:len(raw)] == raw, f"erased={erased}"


def test_roundtrip_with_nu_shortening():
    code = make_clay({"k": "4", "m": "3", "d": "6"})  # nu=2
    raw = _obj(5000)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    for erased in itertools.combinations(range(n), 3):
        avail = {i: c for i, c in chunks.items() if i not in erased}
        got = code.decode_concat(avail)
        assert got[:len(raw)] == raw, f"erased={erased}"


def test_minimum_to_repair_is_partial_reads():
    """Single-node repair reads d helpers x (1/q) sub-chunks — NOT
    whole chunks (the regenerating-code win; ErasureCodeClay.h:88)."""
    code = make_clay({"k": "4", "m": "2"})
    n = code.get_chunk_count()
    minimum = code.minimum_to_decode({0}, set(range(1, n)))
    assert len(minimum) == code.d
    total_sub = code.get_sub_chunk_count()
    for node, ranges in minimum.items():
        got = sum(cnt for _off, cnt in ranges)
        assert got == total_sub // code.q  # 1/q of each helper
    # multi-loss falls back to the conventional plan (whole chunks)
    minimum = code.minimum_to_decode({0, 1}, set(range(2, n)))
    for node, ranges in minimum.items():
        assert ranges == [(0, total_sub)]


def test_repair_path_from_partial_helpers():
    """Feed the repair path exactly the sub-chunk ranges
    minimum_to_decode asked for and verify the lost chunk comes back
    bit-exact (the TestErasureCodeClay.cc repair scenario)."""
    code = make_clay({"k": "4", "m": "2"})
    raw = _obj(8192, seed=9)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    chunk_size = len(np.asarray(chunks[0]))
    sc_size = chunk_size // code.get_sub_chunk_count()
    for lost in range(n):
        minimum = code.minimum_to_decode(
            {lost}, set(range(n)) - {lost})
        helpers = {}
        for node, ranges in minimum.items():
            buf = np.asarray(chunks[node], np.uint8)
            parts = [buf[off * sc_size:(off + cnt) * sc_size]
                     for off, cnt in ranges]
            helpers[node] = np.concatenate(parts)
            assert len(helpers[node]) < chunk_size  # partial read!
        out = code.decode({lost}, helpers, chunk_size)
        assert np.array_equal(np.asarray(out[lost]),
                              np.asarray(chunks[lost])), f"lost={lost}"


def test_clay_with_isa_scalar_mds():
    code = make_clay({"k": "3", "m": "2", "scalar_mds": "isa"})
    raw = _obj(3000)
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    for erased in itertools.combinations(range(n), 2):
        avail = {i: c for i, c in chunks.items() if i not in erased}
        assert code.decode_concat(avail)[:len(raw)] == raw
