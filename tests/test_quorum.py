"""Monitor quorum: election, replicated epochs, leader failover.

The VERDICT round-3 acceptance test: a 3-monitor MiniCluster keeps
accepting writes after the leader is killed mid-workload, a restarted
monitor rejoins and catches up, and committed epochs NEVER fork — every
epoch present on two members is byte-identical.
"""

import itertools
import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.services.cluster import MiniCluster


def fast_conf():
    c = Config()
    c.set("osd_heartbeat_interval", 0.3)
    c.set("osd_heartbeat_grace", 1.5)
    c.set("mon_osd_down_out_interval", 2.0)
    c.set("mon_lease", 0.25)
    c.set("mon_election_timeout", 0.4)
    return c


def assert_no_fork(cluster):
    stores = [(r, dict(m._epochs)) for r, m in cluster.mons.items()]
    for (r1, e1), (r2, e2) in itertools.combinations(stores, 2):
        for v in sorted(set(e1) & set(e2)):
            assert e1[v] == e2[v], \
                f"epoch {v} forked between mon.{r1} and mon.{r2}"


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=4, hosts=4, config=fast_conf(),
                    n_mons=3).start()
    yield c
    c.shutdown()


def test_quorum_elects_and_replicates(cluster):
    ldr = cluster.wait_for_quorum()
    assert ldr.quorum.is_leader()
    # lowest reachable rank wins the steady-state election
    assert ldr is cluster.mons[0]
    cluster.create_replicated_pool(1, pg_num=8, size=3)
    cli = cluster.client()
    cli.put(1, "obj-a", b"alpha")
    assert cli.get(1, "obj-a") == b"alpha"
    # every member holds the committed history
    lead_lc = ldr.last_committed()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(m.last_committed() >= lead_lc
               for m in cluster.mons.values()):
            break
        time.sleep(0.1)
    assert all(m.last_committed() >= lead_lc
               for m in cluster.mons.values())
    assert_no_fork(cluster)


def test_leader_failover_mid_workload(cluster):
    cluster.wait_for_quorum()
    cluster.create_replicated_pool(1, pg_num=8, size=3)
    cli = cluster.client()
    for i in range(5):
        cli.put(1, f"pre-{i}", f"v{i}".encode())

    cluster.kill_mon(0)  # the leader dies mid-workload

    # a new leader (rank 1, the lowest survivor) takes over and WRITES
    # continue: both data-path puts and map-mutating commands
    deadline = time.monotonic() + 15
    new_leader = None
    while time.monotonic() < deadline and new_leader is None:
        for m in cluster.mons.values():
            if m.quorum.is_leader():
                new_leader = m
        time.sleep(0.1)
    assert new_leader is cluster.mons[1]

    cluster.create_replicated_pool(2, pg_num=8, size=2)
    cli.refresh_map()
    for i in range(5):
        cli.put(2, f"post-{i}", f"w{i}".encode())
    for i in range(5):
        assert cli.get(1, f"pre-{i}") == f"v{i}".encode()
        assert cli.get(2, f"post-{i}") == f"w{i}".encode()
    assert_no_fork(cluster)


def test_restarted_mon_rejoins_and_catches_up(cluster):
    cluster.wait_for_quorum()
    cluster.create_replicated_pool(1, pg_num=8, size=3)
    cluster.kill_mon(2)
    cli = cluster.client()
    cli.put(1, "while-down", b"data")
    cluster.create_replicated_pool(3, pg_num=4, size=2)
    lead_lc = cluster.leader().last_committed()

    m2 = cluster.revive_mon(2)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if m2.last_committed() >= lead_lc:
            break
        time.sleep(0.1)
    assert m2.last_committed() >= lead_lc
    assert_no_fork(cluster)
    # the rejoined member serves committed reads
    got = m2.msgr.call(m2.addr, {"type": "get_map"}, timeout=5)
    assert got["epoch"] >= lead_lc


def test_minority_partition_commits_nothing(cluster):
    """Kill two of three: the survivor must refuse writes (no quorum)
    rather than fork its own history."""
    cluster.wait_for_quorum()
    base = max(m.last_committed() for m in cluster.mons.values())
    cluster.kill_mon(1)
    cluster.kill_mon(2)
    m0 = cluster.mons[0]
    # wait out the lease so the survivor knows it lost the quorum
    time.sleep(2.0)
    with pytest.raises(Exception):
        rep = m0.msgr.call(m0.addr, {"type": "pool_create",
                                     "pool_id": 9,
                                     "pool": {"pool_type": 1,
                                              "size": 2,
                                              "min_size": 1,
                                              "pg_num": 4,
                                              "crush_rule": 0}},
                           timeout=8)
        if isinstance(rep, dict) and "error" in rep:
            raise RuntimeError(rep["error"])
    assert m0.last_committed() <= base + 1


def test_staged_entry_survives_leader_crash_and_peon_restart(tmp_path):
    """Paxos durability (Paxos.cc:330-560 persistent accepted_pn +
    uncommitted value via MonitorDBStore): stage an entry on one peon
    as if the leader crashed mid-replicate, kill the leader AND restart
    the staged peon, and require the next election to recover and
    commit that exact entry — never a different one at that version."""
    import json

    c = MiniCluster(n_osds=2, hosts=2, config=fast_conf(), n_mons=3,
                    data_dir=str(tmp_path)).start()
    try:
        ldr = c.wait_for_quorum()
        assert ldr is c.mons[0]
        lc = ldr.last_committed()
        m2 = c.mons[2]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and m2.last_committed() < lc:
            time.sleep(0.05)
        assert m2.last_committed() == lc

        # hand-deliver an accept to mon.2 only — the moment after a
        # real leader got its first (and only) accept ack and died
        p = ldr.get_epoch_payload(lc)
        p["epoch"] = lc + 1
        p["map"]["epoch"] = lc + 1
        entry = {"payload": json.dumps(p), "inc": None}
        e = m2.quorum.election_epoch
        rep = m2.msgr.call(m2.addr, {"type": "mon_accept", "e": e,
                                     "v": lc + 1, "entry": entry},
                           timeout=5)
        assert rep.get("ack")

        c.kill_mon(0)        # leader dies without ever committing
        c.kill_mon(2)        # the one staged holder crashes too...
        c.revive_mon(2)      # ...and restarts from its store
        new = c.wait_for_quorum()
        assert new is c.mons[1]  # the new leader never saw the entry
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                new.last_committed() < lc + 1:
            time.sleep(0.1)
        # the restarted peon's persisted stage rode its propose ack
        # into the new leader's collect majority and was re-proposed
        assert new.last_committed() >= lc + 1
        assert json.loads(new._epochs[lc + 1]) == p
        assert_no_fork(c)
    finally:
        c.shutdown()


def test_quorum_with_auth_keyring(tmp_path):
    """Signed clusters: election, replication, forwarding, and the
    data path all ride HMAC-authenticated frames (mon↔mon quorum
    traffic included)."""
    c = MiniCluster(n_osds=3, hosts=3, config=fast_conf(), n_mons=3,
                    auth=True, data_dir=str(tmp_path)).start()
    try:
        ldr = c.wait_for_quorum()
        assert ldr.quorum.is_leader()
        c.create_replicated_pool(1, pg_num=8, size=2)
        cli = c.client()
        cli.put(1, "signed", b"authenticated-bytes")
        assert cli.get(1, "signed") == b"authenticated-bytes"

        # failover still works with signed election traffic: kill the
        # OBSERVED leader (not a hardcoded rank)
        leader_rank = next(r for r, m in c.mons.items()
                           if m is ldr)
        c.kill_mon(leader_rank)
        new_leader = c.wait_for_quorum()
        assert new_leader is not ldr
        cli.put(1, "signed2", b"post-failover")
        assert cli.get(1, "signed2") == b"post-failover"
        assert_no_fork(c)

        # an unkeyed intruder's frames are dropped silently
        from ceph_tpu.msg.messenger import Messenger

        intruder = Messenger("intruder")
        intruder.start()
        try:
            with pytest.raises(TimeoutError):
                intruder.call(new_leader.addr,
                              {"type": "mark_down", "osd": 1},
                              timeout=2)
            assert 1 in c.status()["up_osds"]
        finally:
            intruder.shutdown()
    finally:
        c.shutdown()


def test_asymmetric_isolation_reelects_without_deposing():
    """One-way isolation (satellite of PR 15): rank 2 can SEND but
    cannot HEAR — its proposes reach the quorum while the leader's
    leases never reach it.  The standing majority must keep serving
    (re-electing through rank 2's blind candidacies), and once the cut
    heals the rejoining rank must settle as a peon WITHOUT deposing
    the leader again: its failed round's nacks carry the standing
    election epoch, so it drops to probing and joins peacefully."""
    from ceph_tpu.analysis import faults

    conf = fast_conf()
    c = MiniCluster(n_osds=2, hosts=2, config=conf, n_mons=3).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=2)
        ldr = c.wait_for_quorum()
        assert ldr is c.mons[0]
        cli = c.client()
        # inbound-only cut INTO rank 2 (replies carry no sender name,
        # so rank 2's own calls still round-trip — true asymmetry)
        c.set_faults("net.partition=p:1.0,"
                     "pairs:mon.0>mon.2|mon.1>mon.2")
        deadline = time.monotonic() + 3.0
        i = 0
        while time.monotonic() < deadline:
            # the majority serves commands throughout the cut, across
            # whatever re-elections rank 2's blind proposes force
            cli.put(1, f"cut-{i}", b"served")
            i += 1
            time.sleep(0.2)
        assert i >= 5
        c.set_faults("")
        faults.reset()
        # settle: rank 2 back as a peon under the rank-0 leader
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            q = c.mons[2].quorum
            if q.state == "peon" and q.leader_rank == 0 and \
                    c.mons[0].quorum.is_leader():
                break
            time.sleep(0.1)
        assert c.mons[2].quorum.state == "peon"
        assert c.mons[2].quorum.leader_rank == 0
        # the rejoined rank must NOT depose: the election epoch holds
        # still across several lease+retry windows
        epoch0 = c.mons[0].quorum.election_epoch
        time.sleep(2.0)
        assert c.mons[0].quorum.is_leader()
        assert c.mons[0].quorum.election_epoch == epoch0
        cli.put(1, "healed", b"stable")
        assert cli.get(1, "healed") == b"stable"
        assert_no_fork(c)
    finally:
        c.shutdown()
