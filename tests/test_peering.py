"""Peering: divergent-history reconciliation (the PeeringState/PGLog
acceptance test from the round-3 review).

Scenario: write with B down (only A has it); kill A, revive B, write
more (divergent history on B at a higher epoch); revive A.  Peering
must merge both logs — newest version wins per object, tombstones
propagate — and every object must read its latest acked data, with
both replicas converging to identical state.
"""

import time

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.services.client import ObjectNotFound
from ceph_tpu.services.cluster import MiniCluster


def fast_conf():
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.0)
    c.set("mon_osd_down_out_interval", 1.0)
    return c


@pytest.fixture
def cluster(tmp_path):
    # persistent (WALStore) OSDs: a revived daemon remounts its data,
    # which is what makes "divergent histories" possible at all
    c = MiniCluster(n_osds=2, hosts=2, config=fast_conf(),
                    data_dir=str(tmp_path)).start()
    c.create_replicated_pool(1, pg_num=8, size=2)
    yield c
    c.shutdown()


def _wait_converged(cluster, pool_id, expect, timeout=30.0):
    """Every live OSD that the map assigns an object holds it at the
    SAME newest version, and reads return the expected bytes."""
    cli = cluster.client(f"conv{time.time_ns()}")
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            for oid, want in expect.items():
                if want is None:
                    with pytest.raises(ObjectNotFound):
                        cli.get(pool_id, oid, notfound_retries=0)
                else:
                    assert cli.get(pool_id, oid) == want
            # replica convergence: identical version xattrs everywhere
            from ceph_tpu.services.client import object_to_ps
            payload = cluster.mon_command({"type": "get_map"})
            from ceph_tpu.osdmap.bincode_maps import payload_map
            m = payload_map(payload)
            pool = m.pools[pool_id]
            for oid, want in expect.items():
                ps = object_to_ps(oid) % pool.pg_num
                cid = f"{pool_id}.{ps}"
                up, _p, _a, _ap = m.pg_to_up_acting_osds(pool_id, ps)
                vs = set()
                for osd in up:
                    svc = cluster.osds.get(osd)
                    assert svc is not None
                    if want is None:
                        assert svc.store.stat(cid, f"{oid}.s0") \
                            is None, f"{oid} not deleted on osd.{osd}"
                    else:
                        ver = svc.store.getattr(cid, f"{oid}.s0", "v")
                        assert ver is not None, \
                            f"{oid} missing on osd.{osd}"
                        vs.add(ver)
                if want is not None:
                    assert len(vs) == 1, f"{oid} versions diverge"
            return
        except (AssertionError, Exception) as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise AssertionError(f"never converged: {last}")


def test_divergent_histories_reconcile(cluster):
    A, B = 0, 1
    cli = cluster.client()

    # interval 1: both up — baseline object
    cli.put(1, "x", b"x-v1")
    cli.put(1, "y", b"y-v1")

    # interval 2: B down — writes land only on A
    cluster.kill_osd(B)
    cluster.wait_for_down(B, timeout=10)
    time.sleep(1.5)  # let auto-out remap to [A]
    cli.refresh_map()
    cli.put(1, "x", b"x-v2-on-A")
    cli.put(1, "only-a", b"a-data")

    # interval 3: A down, B revived — divergent writes on B
    cluster.kill_osd(A)
    cluster.revive_osd(B)
    cluster.wait_for_down(A, timeout=10)
    cluster.wait_for_up(B, timeout=10)
    time.sleep(1.5)
    cli.refresh_map()
    cli.put(1, "x", b"x-v3-on-B")       # newer than A's x-v2
    cli.put(1, "only-b", b"b-data")
    cli.delete(1, "y")                  # tombstone while A holds y-v1

    # interval 4: A revived — both divergent logs must reconcile
    cluster.revive_osd(A)
    cluster.wait_for_up(A, timeout=10)

    _wait_converged(cluster, 1, {
        "x": b"x-v3-on-B",   # B's later write wins over A's
        "only-a": b"a-data",  # A's solo write survives
        "only-b": b"b-data",  # B's solo write survives
        "y": None,            # the delete beats the older write
    })


def test_reads_survive_reconciliation_window(cluster):
    """Every read during the reconciliation returns either nothing
    stale-after-newer data: the version-aware read picks the newest
    reachable copy the moment both replicas answer."""
    A, B = 0, 1
    cli = cluster.client()
    cli.put(1, "w", b"w-v1")
    cluster.kill_osd(B)
    cluster.wait_for_down(B, timeout=10)
    time.sleep(1.5)
    cli.refresh_map()
    cli.put(1, "w", b"w-v2")
    cluster.revive_osd(B)
    cluster.wait_for_up(B, timeout=10)
    # from the instant B is back (holding stale w-v1), reads must
    # never regress to v1
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        assert cli.get(1, "w") == b"w-v2"
        time.sleep(0.1)
