"""dmClock scheduler, cephx-style auth, KV wrapper, versioned
encoding — the remaining §2.5 foundation rows."""

import time

import pytest

from ceph_tpu.common.encoding import (MalformedInput, Versioned,
                                      decode, encode)
from ceph_tpu.common.op_queue import (CLIENT, RECOVERY, SCRUB,
                                      ClientInfo, MClockQueue,
                                      default_osd_queue)
from ceph_tpu.msg.auth import Keyring
from ceph_tpu.msg.messenger import Messenger
from ceph_tpu.os.kv import KeyValueDB, KVTransaction


# -- dmClock ----------------------------------------------------------------

def test_mclock_reservation_floor():
    """A class with a reservation gets its floor even against a
    heavier competitor."""
    q = MClockQueue({
        CLIENT: ClientInfo(reservation=0, weight=10.0),
        RECOVERY: ClientInfo(reservation=5.0, weight=0.1),
    })
    now = 0.0
    for i in range(100):
        q.enqueue(CLIENT, f"c{i}", now)
        q.enqueue(RECOVERY, f"r{i}", now)
    served = {CLIENT: 0, RECOVERY: 0}
    # one simulated second at 20 ops/sec service rate
    for tick in range(20):
        got = q.dequeue(now)
        assert got is not None
        served[got[0]] += 1
        now += 0.05
    # recovery's 5 ops/sec floor over 1s => ~5 served despite weight 0.1
    assert served[RECOVERY] >= 4
    assert served[CLIENT] > served[RECOVERY]  # weight still dominates


def test_mclock_limit_ceiling():
    q = MClockQueue({
        SCRUB: ClientInfo(reservation=0, weight=1.0, limit=2.0),
    })
    now = 0.0
    for i in range(10):
        q.enqueue(SCRUB, i, now)
    served = 0
    for tick in range(100):
        if q.dequeue(now) is not None:
            served += 1
        now += 0.01  # one simulated second total
    assert served <= 3  # 2 ops/sec limit (+1 for the t=0 op)


def test_mclock_weight_sharing_and_idle():
    q = MClockQueue({
        "a": ClientInfo(weight=3.0),
        "b": ClientInfo(weight=1.0),
    })
    now = 0.0
    for i in range(40):
        q.enqueue("a", i, now)
        q.enqueue("b", i, now)
    served = {"a": 0, "b": 0}
    for _ in range(24):
        cls, _item = q.dequeue(now)
        served[cls] += 1
        now += 0.001
    assert served["a"] > 2.0 * served["b"]  # ~3:1 sharing
    assert len(default_osd_queue().qos) == 3


def test_mclock_next_ready():
    q = MClockQueue({SCRUB: ClientInfo(weight=1.0, limit=1.0)})
    q.enqueue(SCRUB, "x", 0.0)
    assert q.dequeue(0.0) is not None
    q.enqueue(SCRUB, "y", 0.001)
    assert q.dequeue(0.001) is None  # limit-throttled
    assert 0.9 < q.next_ready_at() <= 1.1
    assert q.dequeue(1.1) is not None


# -- auth -------------------------------------------------------------------

def test_keyring_sign_verify_and_tickets():
    k = Keyring.generate()
    msg = {"type": "boot", "osd": 1}
    signed = dict(msg, mac=k.sign(msg))
    assert k.verify(signed)
    signed["osd"] = 2  # tamper
    assert not k.verify(signed)
    k2 = Keyring.from_hex(k.to_hex())
    t = k2.issue_ticket("client.admin", lifetime=60)
    assert k.verify_ticket(t)
    t_expired = k.issue_ticket("x", lifetime=-1)
    assert not k.verify_ticket(t_expired)
    t["name"] = "client.evil"
    assert not k.verify_ticket(t)


def test_messenger_rejects_unauthenticated():
    key = Keyring.generate()
    server = Messenger("srv", keyring=key)
    server.register("ping", lambda m: {"pong": True})
    server.start()
    good = Messenger("good", keyring=Keyring.from_hex(key.to_hex()))
    good.start()
    bad = Messenger("bad")  # no keyring
    bad.start()
    wrong = Messenger("wrong", keyring=Keyring.generate())
    wrong.start()
    try:
        assert good.call(server.addr, {"type": "ping"}) == \
            {"pong": True}
        with pytest.raises(TimeoutError):
            bad.call(server.addr, {"type": "ping"}, timeout=0.6)
        with pytest.raises(TimeoutError):
            wrong.call(server.addr, {"type": "ping"}, timeout=0.6)
    finally:
        for m in (server, good, bad, wrong):
            m.shutdown()


def test_authenticated_cluster_end_to_end():
    from ceph_tpu.common.config import Config
    from ceph_tpu.services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.2)
    conf.set("osd_heartbeat_grace", 1.5)
    cl = MiniCluster(n_osds=3, config=conf, auth=True).start()
    try:
        cl.create_replicated_pool(1, pg_num=4, size=2)
        c = cl.client("authed")
        c.put(1, "o", b"secured payload")
        assert c.get(1, "o") == b"secured payload"
        # an unauthenticated messenger cannot talk to the mon at all
        intruder = Messenger("intruder")
        intruder.start()
        try:
            with pytest.raises(TimeoutError):
                intruder.call(cl.mon.addr, {"type": "status"},
                              timeout=0.6)
        finally:
            intruder.shutdown()
    finally:
        cl.shutdown()


# -- kv wrapper -------------------------------------------------------------

def test_kv_roundtrip_and_prefixes():
    db = KeyValueDB()
    db.submit_transaction(
        KVTransaction().set("osdmap", "epoch", b"7")
        .set("osdmap", "fsid", b"abc").set("pg", "1.0", b"log"))
    assert db.get("osdmap", "epoch") == b"7"
    assert db.get_by_prefix("osdmap") == {"epoch": b"7",
                                          "fsid": b"abc"}
    assert list(db.iterator("osdmap"))[0] == ("epoch", b"7")
    db.submit_transaction(KVTransaction().rmkey("osdmap", "fsid"))
    assert db.get("osdmap", "fsid") is None
    db.submit_transaction(KVTransaction().rmkeys_by_prefix("osdmap"))
    assert db.get_by_prefix("osdmap") == {}
    assert db.get("pg", "1.0") == b"log"  # other prefixes untouched


# -- versioned encoding -----------------------------------------------------

def test_encoding_envelope():
    blob = encode({"x": 1}, version=3, compat=2)
    v, data = decode(blob, supported=3)
    assert (v, data) == (3, {"x": 1})
    with pytest.raises(MalformedInput):
        decode(blob, supported=1)  # too old to read compat=2
    with pytest.raises(MalformedInput):
        decode("not json")
    with pytest.raises(ValueError):
        encode({}, version=1, compat=2)


def test_versioned_mixin_upgrade():
    class Thing(Versioned):
        STRUCT_V = 2
        COMPAT_V = 1

        def __init__(self, a, b):
            self.a, self.b = a, b

        def to_dict(self):
            return {"a": self.a, "b": self.b}

        @classmethod
        def from_dict(cls, d):
            return cls(d["a"], d["b"])

        @classmethod
        def upgrade(cls, writer_v, data):
            if writer_v < 2:
                data = dict(data, b=0)  # field added in v2
            return data

    t = Thing(1, 2)
    t2 = Thing.decode_versioned(t.encode_versioned())
    assert (t2.a, t2.b) == (1, 2)
    old_blob = encode({"a": 9}, version=1, compat=1)
    t3 = Thing.decode_versioned(old_blob)
    assert (t3.a, t3.b) == (9, 0)
