"""Bit-exactness of the rjenkins1 hash vs golden vectors from the C core."""

import json

import numpy as np
import pytest

from conftest import GOLDEN_DIR

from ceph_tpu.crush import hash as H


@pytest.fixture(scope="module")
def cases():
    d = json.load(open(GOLDEN_DIR / "hash.json"))
    return np.array(d["cases"], dtype=np.uint64)


def test_seed():
    assert H.CRUSH_HASH_SEED == 1315423911


def test_numpy_vectorized(cases):
    a = cases[:, 0].astype(np.uint32)
    b = cases[:, 1].astype(np.uint32)
    np.testing.assert_array_equal(H.crush_hash32(a), cases[:, 2].astype(np.uint32))
    np.testing.assert_array_equal(H.crush_hash32_2(a, b), cases[:, 3].astype(np.uint32))
    np.testing.assert_array_equal(H.crush_hash32_3(a, b, a ^ b), cases[:, 4].astype(np.uint32))
    with np.errstate(over="ignore"):
        np.testing.assert_array_equal(
            H.crush_hash32_4(a, b, a + b, a - b), cases[:, 5].astype(np.uint32))
        np.testing.assert_array_equal(
            H.crush_hash32_5(a, b, a + b, a - b, a * np.uint32(3) + b),
            cases[:, 6].astype(np.uint32))


def test_int_fast_path(cases):
    for row in cases[:50]:
        a, b = int(row[0]), int(row[1])
        assert H.hash32_int(a) == int(row[2])
        assert H.hash32_2_int(a, b) == int(row[3])
        assert H.hash32_3_int(a, b, a ^ b) == int(row[4])
        assert H.hash32_4_int(a, b, a + b, a - b) == int(row[5])
        assert H.hash32_5_int(a, b, a + b, a - b, a * 3 + b) == int(row[6])


def test_jax_matches_numpy(cases):
    import jax.numpy as jnp

    a32 = cases[:, 0].astype(np.uint32)
    b32 = cases[:, 1].astype(np.uint32)
    got = H.crush_hash32_3(jnp.asarray(a32), jnp.asarray(b32),
                           jnp.asarray(a32 ^ b32))
    np.testing.assert_array_equal(np.asarray(got),
                                  cases[:, 4].astype(np.uint32))
