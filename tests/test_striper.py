"""Striper layout math + compressor registry (pure units; the
cluster-backed striper path lives in test_cluster.py)."""

import numpy as np
import pytest

from ceph_tpu.common.compressor import Compressor, plugins
from ceph_tpu.services.striper import Striper, _piece_name


class FakeClient:
    """Minimal put/get dict backend for layout tests."""

    def __init__(self):
        self.objects = {}

    def put(self, pool_id, oid, data):
        self.objects[(pool_id, oid)] = bytes(data)

    def get(self, pool_id, oid):
        return self.objects[(pool_id, oid)]


def test_extent_map_round_robin():
    s = Striper(FakeClient(), stripe_unit=4, stripe_count=3)
    # logical units 0..5 land on objects 0,1,2,0,1,2 (object set 0,
    # then set 1 continues on the same three objects at offset 4)
    ext = s.extent_map(0, 24)
    assert [(e[0], e[1]) for e in ext] == [
        (0, 0), (1, 0), (2, 0), (0, 4), (1, 4), (2, 4)]
    # unaligned span splits at unit boundaries
    ext = s.extent_map(2, 6)
    assert ext[0] == (0, 2, 2, 2)
    assert ext[1] == (1, 0, 4, 4)


def test_extent_map_object_set_advance():
    """Small object_size: the object set advances once objects fill."""
    s = Striper(FakeClient(), stripe_unit=4, stripe_count=2,
                object_size=8)  # 2 stripes per object, 4 per set
    ext = s.extent_map(0, 24)
    assert [(e[0], e[1]) for e in ext] == [
        (0, 0), (1, 0), (0, 4), (1, 4),   # set 0 fills objects 0,1
        (2, 0), (3, 0)]                   # set 1 starts objects 2,3


def test_striper_write_read_roundtrip():
    c = FakeClient()
    s = Striper(c, stripe_unit=8, stripe_count=3, object_size=32)
    data = bytes(range(256)) * 3 + b"tail"
    s.write(1, "big", data)
    assert s.read(1, "big") == data
    assert s.stat(1, "big")[0] == len(data)
    # partial reads at awkward offsets
    for off, ln in ((0, 10), (7, 9), (8, 8), (100, 200), (770, 50)):
        assert s.read(1, "big", off, ln) == data[off:off + ln]
    # pieces really are distributed
    piece_keys = [k for k in c.objects if k[1].startswith("big.")]
    assert len(piece_keys) > 3


def test_striper_layout_mismatch_rejected():
    c = FakeClient()
    Striper(c, 8, 3).write(1, "o", b"x" * 100)
    with pytest.raises(ValueError):
        Striper(c, 16, 3).read(1, "o")


def test_compressor_registry():
    assert {"none", "zlib", "lzma"} <= set(plugins())
    payload = b"abc" * 1000
    for name in plugins():
        comp = Compressor(name)
        blob = comp.compress(payload)
        assert comp.decompress(blob) == payload
        if name != "none":
            assert len(blob) < len(payload)
    with pytest.raises(KeyError):
        Compressor("snappy-nope")
