"""Generate ec_parity.json — pins this repo's own on-wire EC parity.

The reference's vendored jerasure/gf-complete/isa-l submodules are
absent, so the parity bytes cannot be diffed against the reference
binaries; instead this pins OUR constructions (ceph_tpu.ec.matrices,
documented divergences included) so refactors cannot silently change
encoded data.  Regenerate only on a deliberate, documented format
change:  python tests/golden/_gen_ec_parity.py
"""

import hashlib
import json
import pathlib
import sys

import numpy as np

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

import jax  # noqa: E402  (preloaded images pin a hardware backend;
jax.config.update("jax_platforms", "cpu")  # golden gen is host-only)

from ceph_tpu.ec.jerasure import make_jerasure  # noqa: E402

CONFIGS = [
    {"technique": "reed_sol_van", "k": "2", "m": "2", "w": "8"},
    {"technique": "reed_sol_van", "k": "3", "m": "2", "w": "16"},
    {"technique": "reed_sol_van", "k": "4", "m": "3", "w": "32"},
    {"technique": "reed_sol_r6_op", "k": "4", "m": "2", "w": "8"},
    {"technique": "cauchy_orig", "k": "2", "m": "2", "w": "4",
     "packetsize": "8"},
    {"technique": "cauchy_orig", "k": "4", "m": "3", "w": "8",
     "packetsize": "8"},
    {"technique": "cauchy_good", "k": "4", "m": "3", "w": "8",
     "packetsize": "8"},
    {"technique": "liberation", "k": "2", "m": "2", "w": "7",
     "packetsize": "8"},
    {"technique": "blaum_roth", "k": "2", "m": "2", "w": "6",
     "packetsize": "8"},
    {"technique": "liber8tion", "k": "2", "m": "2", "w": "8",
     "packetsize": "8"},
]

OBJECT_SIZE = 1537  # deliberately unaligned to exercise padding


def main():
    rng = np.random.default_rng(0xEC)
    raw = rng.integers(0, 256, OBJECT_SIZE, dtype=np.uint8).tobytes()
    out = {"object_sha256": hashlib.sha256(raw).hexdigest(),
           "object_size": OBJECT_SIZE, "seed": "0xEC", "cases": []}
    for cfg in CONFIGS:
        code = make_jerasure(dict(cfg))
        n = code.get_chunk_count()
        chunks = code.encode(range(n), raw)
        out["cases"].append({
            "profile": cfg,
            "chunk_size": int(chunks[0].shape[0]),
            "chunk_sha256": {
                str(i): hashlib.sha256(
                    np.asarray(chunks[i], np.uint8).tobytes()).hexdigest()
                for i in sorted(chunks)},
        })
    path = pathlib.Path(__file__).parent / "ec_parity.json"
    path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {path} ({len(out['cases'])} cases)")


if __name__ == "__main__":
    main()
