#!/usr/bin/env python
"""Golden wire-encoding corpus generator + freshness gate.

The ceph-object-corpus / check-generated.sh role: every type in the
wirecheck registry has its current example encoding committed under

    tests/corpus/encodings/<type>/<struct_v>/example.bin

``--check`` re-encodes every registered type and byte-compares against
the committed blob — tier-1 fails when an encoding changed WITHOUT a
struct_v bump (silent wire drift), or when a new type/version has no
committed blob yet.  ``--write`` regenerates the current-version blobs
(never touching archived older-version directories, which exist to
prove old blobs stay decodable forever).

Regeneration workflow (after an INTENTIONAL format change):
  1. bump the type's STRUCT_V (and COMPAT_V if old readers cannot
     skip the change),
  2. move nothing: the old  <type>/<old_v>/  directory stays as the
     archived back-decode witness,
  3. run  python tests/golden/_gen_wire_corpus.py --write
  4. commit the new  <type>/<new_v>/example.bin  with the code.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
CORPUS = REPO / "tests" / "corpus" / "encodings"

sys.path.insert(0, str(REPO))


def _blob(entry) -> bytes:
    raw = entry.encode(entry.factory())
    return raw.encode() if isinstance(raw, str) else bytes(raw)


def current_path(entry) -> pathlib.Path:
    return CORPUS / entry.name / str(entry.struct_v) / "example.bin"


def write() -> List[str]:
    from ceph_tpu.analysis import wirecheck

    wrote = []
    for e in wirecheck.entries():
        p = current_path(e)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(_blob(e))
        wrote.append(str(p.relative_to(REPO)))
    return wrote


def check() -> List[str]:
    """Empty list = fresh.  Each entry's current encoding must
    byte-match its committed blob at the CURRENT struct_v; a registry
    entry without a committed blob is also stale (a new type/version
    whose pin was not committed)."""
    from ceph_tpu.analysis import wirecheck

    problems = []
    for e in wirecheck.entries():
        p = current_path(e)
        if not p.exists():
            problems.append(
                f"{e.name}: no committed corpus blob at "
                f"{p.relative_to(REPO)} — run "
                f"tests/golden/_gen_wire_corpus.py --write and "
                f"commit it")
            continue
        if p.read_bytes() != _blob(e):
            problems.append(
                f"{e.name}: current encoding diverges from the "
                f"committed corpus at struct_v {e.struct_v}.  Either "
                f"this change is accidental wire drift (fix the "
                f"code), or it is intentional: bump STRUCT_V, keep "
                f"the old version dir as the archived witness, and "
                f"regenerate with --write")
    return problems


def main(argv) -> int:
    if "--write" in argv:
        for p in write():
            print(f"wrote {p}")
        return 0
    problems = check()
    for p in problems:
        print(p)
    if problems:
        print(f"{len(problems)} stale corpus entr"
              f"{'y' if len(problems) == 1 else 'ies'}")
        return 1
    print("wire corpus fresh")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
