"""Partition-tolerant failure detection (PR 15).

The detector's whole contract, end to end:

- ``faults.partitioned`` pair matching is directional (asymmetric cuts
  are first-class) with prefix scoping and wildcards;
- a healthy OSD that loses only its mon link is NOT marked down — its
  peers still hear it and the direct beacon is last-resort only
  (the false-markdown scenario the beacon-only detector failed);
- a truly isolated OSD IS marked down, by reporter quorum, within the
  heartbeat grace, and re-boots itself once it learns the markdown;
- ``check_failure`` dedups reporters by CRUSH failure-domain subtree:
  reports from one host are ONE witness, not a quorum;
- the ``osd_markdown_log`` dampener: a flapping daemon crosses its
  markdown budget, gets auto-outed with boots deferred, raises
  OSD_FLAPPING, and rejoins once the log drains;
- markdown/out racing re-boots never oscillates the map faster than
  one grace window (the satellite-4 monotone-epoch story).
"""

import time

import pytest

from ceph_tpu.analysis import faults
from ceph_tpu.common.config import Config
from ceph_tpu.services.cluster import MiniCluster


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.reset()
    yield
    faults.reset()


def _conf():
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 0.8)
    # peer reports do the detecting; the beacon timeout must never
    # fire inside a test's partition window
    c.set("mon_osd_report_timeout", 30.0)
    c.set("mon_osd_down_out_interval", 30.0)
    return c


# -- faults.partitioned unit surface ----------------------------------

def test_partitioned_is_directional_with_wildcards():
    faults.arm("net.partition", "p", p=1.0,
               pairs="osd.1>osd.2|mon>*")
    assert faults.partitioned("osd.1", "osd.2")
    # asymmetric: the reverse direction flows
    assert not faults.partitioned("osd.2", "osd.1")
    # prefix scoping: osd.1 does not match osd.10-style names only
    # by accident — the pair names daemons by prefix
    assert not faults.partitioned("osd.3", "osd.2")
    # wildcard destination
    assert faults.partitioned("mon.0", "osd.1")
    assert faults.partitioned("mon.2", "client.x")
    # an unnamed sender (reply frames carry no frm) never matches:
    # one-way cuts must not sever call replies
    assert not faults.partitioned("", "osd.2")
    assert not faults.partitioned(None, "osd.2")
    faults.clear()
    assert not faults.partitioned("osd.1", "osd.2")


def test_partition_spec_roundtrip():
    fps = faults.parse_spec(
        "net.partition=p:1.0,pairs:osd.0>mon|mon>osd.0")
    assert fps["net.partition"].extras["pairs"] == \
        "osd.0>mon|mon>osd.0"


# -- the detector itself ----------------------------------------------

def test_mon_partition_alone_is_no_markdown():
    """A cut mon link must not kill a healthy OSD: its peers still
    ack its pings, so nobody reports it, and the beacon timeout is
    far out of reach."""
    c = MiniCluster(n_osds=3, hosts=3, config=_conf()).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=3)
        cli = c.client("hb-t1")
        cli.put(1, "k", b"v1")
        time.sleep(1.0)  # peer clocks established
        base_md = int(c.mon.pc.dump().get("markdowns", 0))
        c.set_faults("net.partition=p:1.0,pairs:osd.1>mon|mon>osd.1")
        time.sleep(2.5)  # > 3x grace with the mon link dark
        assert 1 in c.status()["up_osds"]
        assert int(c.mon.pc.dump().get("markdowns", 0)) == base_md
        # client I/O through the partitioned-from-mon osd still works
        cli.put(1, "k", b"v2")
        assert cli.get(1, "k") == b"v2"
        c.set_faults("")
        c.wait_for_health_ok(timeout=20.0)
    finally:
        c.shutdown()


def test_isolated_osd_marked_down_by_peers_then_rejoins():
    """Full isolation: the peers' reports (>= 2 reporters from
    distinct host subtrees) get the victim marked down around the
    heartbeat grace — nowhere near the 30s beacon timeout — and the
    still-alive victim re-boots itself once the healed link shows it
    the markdown epoch."""
    c = MiniCluster(n_osds=4, hosts=4, config=_conf()).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=3)
        time.sleep(1.0)
        c.set_faults("net.partition=p:1.0,pairs:osd.2>*|*>osd.2")
        t0 = time.monotonic()
        c.wait_for_down(2, timeout=10.0)
        detect = time.monotonic() - t0
        # grace 0.8 + ticks + report handling; the strict
        # grace+2*interval gate lives in the seeded NETSPLIT drill —
        # here we only pin "peer detection, not beacon timeout"
        assert detect < 5.0, f"detection took {detect:.2f}s"
        assert int(c.mon.pc.dump().get("failure_reports", 0)) > 0
        c.set_faults("")
        # alive + wrongly-down-in-its-own-eyes -> requests re-boot
        c.wait_for_up(2, timeout=15.0)
        c.wait_for_health_ok(timeout=20.0)
    finally:
        c.shutdown()


def test_same_host_reporters_are_one_witness():
    """Subtree dedup: osd.0 (host0) cut from both host1 osds.  Two
    reporters, ONE failure-domain subtree -> no markdown; the same-host
    peer osd.2 still hears osd.0 and never reports it."""
    conf = _conf()
    c = MiniCluster(n_osds=4, hosts=2, config=conf).start()
    # host0 = {osd.0, osd.2}, host1 = {osd.1, osd.3} (d % hosts)
    try:
        c.create_replicated_pool(1, pg_num=4, size=2)
        time.sleep(1.0)
        base_md = int(c.mon.pc.dump().get("markdowns", 0))
        c.set_faults("net.partition=p:1.0,"
                     "pairs:osd.0>osd.1|osd.1>osd.0|"
                     "osd.0>osd.3|osd.3>osd.0")
        deadline = time.monotonic() + 2.5
        while time.monotonic() < deadline:
            assert 0 in c.status()["up_osds"], \
                "one host's reporters must not be a quorum"
            time.sleep(0.1)
        # the reports DID arrive — they were deduped, not lost
        assert int(c.mon.pc.dump().get("failure_reports", 0)) > 0
        assert int(c.mon.pc.dump().get("markdowns", 0)) == base_md
        c.set_faults("")
        c.wait_for_health_ok(timeout=20.0)
    finally:
        c.shutdown()


def test_flapping_osd_dampened_and_health_coded():
    """A flapping link (peers cut, mon link open): every re-boot is
    followed by another reporter-quorum markdown; crossing
    osd_max_markdown_count dampens the daemon — boots deferred, the
    osd auto-outed — and raises the OSD_FLAPPING health check; once
    the link heals and the log drains it rejoins and health clears."""
    conf = _conf()
    conf.set("osd_max_markdown_count", 2)
    conf.set("osd_max_markdown_period", 8.0)
    c = MiniCluster(n_osds=4, hosts=4, config=conf).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=3)
        time.sleep(1.0)
        c.set_faults("net.partition=p:1.0,"
                     "pairs:osd.3>osd.|osd.>osd.3")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if int(c.mon.pc.dump().get("markdowns_dampened", 0)) >= 1:
                break
            time.sleep(0.1)
        dump = c.mon.pc.dump()
        assert int(dump.get("markdowns_dampened", 0)) >= 1
        assert int(dump.get("markdowns", 0)) >= 2
        assert "OSD_FLAPPING" in c.health().get("check_codes", [])
        c.set_faults("")
        # rejoin waits for the oldest markdown to age out (delayed
        # re-boot), then the auto-outed weight is restored on boot
        c.wait_for_up(3, timeout=20.0)
        c.wait_for_health_ok(timeout=30.0)
        assert "OSD_FLAPPING" not in c.health().get("check_codes", [])
    finally:
        c.shutdown()


def test_markdown_out_reboot_interplay_is_monotone():
    """Satellite 4: down->out racing reporter-quorum markdowns and
    concurrent re-boots must not oscillate the map inside one grace
    window.  With peers cut and the mon link open the victim cycles
    markdown -> nudge -> re-boot -> markdown; every cycle restarts
    the peers' grace clocks (a booting incarnation is a FRESH peer),
    so consecutive markdowns are at least one grace apart."""
    conf = _conf()
    grace = conf["osd_heartbeat_grace"]
    # short enough that the auto-out lands INSIDE the down blip,
    # racing the re-boot the nudged victim is about to send
    conf.set("mon_osd_down_out_interval", 0.15)
    conf.set("osd_max_markdown_count", 1000)  # never dampen here
    c = MiniCluster(n_osds=4, hosts=4, config=conf).start()
    try:
        c.create_replicated_pool(1, pg_num=4, size=3)
        time.sleep(1.0)
        c.set_faults("net.partition=p:1.0,"
                     "pairs:osd.1>osd.|osd.>osd.1")
        samples = []  # (mono, epoch)
        deadline = time.monotonic() + 4.5
        while time.monotonic() < deadline:
            st = c.status()
            samples.append((time.monotonic(), int(st["epoch"])))
            time.sleep(0.05)
        # the victim's down windows are too short for a status poller
        # (its open mon link delivers the markdown epoch immediately
        # and it re-boots within a beat) — read the markdown stamps
        # the dampener keeps instead
        downs = list(c.mon._markdown_log.get(1, ()))
        c.set_faults("")
        # the epoch story is monotone — no commit ever rewinds it
        epochs = [e for _t, e in samples]
        assert epochs == sorted(epochs)
        assert len(downs) >= 2, "expected repeated markdown cycles"
        gaps = [b - a for a, b in zip(downs, downs[1:])]
        assert min(gaps) >= grace * 0.9, \
            f"markdown cycle faster than the grace window: {gaps}"
        c.wait_for_up(1, timeout=20.0)
        c.wait_for_health_ok(timeout=30.0)
        # the final boot restored the auto-outed weight
        assert c.mon.map.osd_weight[1] > 0
    finally:
        c.shutdown()
