"""ECUtil stripe-layer tests — mirrors src/test/osd/TestECUtil.cc
(stripe_info_t offset math) plus the batched==per-stripe equivalence
that justifies the one-launch encode/decode design."""

import numpy as np
import pytest

from ceph_tpu.ec import stripe
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.jerasure import make_jerasure
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.stripe import HashInfo, StripeInfo, crc32c, sinfo_for


def test_stripe_info_math():
    """TestECUtil.cc stripe_info_t cases."""
    s = StripeInfo(2, 8192)  # k=2, width 8192, chunk 4096
    assert s.chunk_size == 4096
    assert s.logical_offset_is_stripe_aligned(8192)
    assert not s.logical_offset_is_stripe_aligned(4096)
    assert s.logical_to_prev_chunk_offset(0) == 0
    assert s.logical_to_prev_chunk_offset(8191) == 0
    assert s.logical_to_prev_chunk_offset(8192) == 4096
    assert s.logical_to_next_chunk_offset(0) == 0
    assert s.logical_to_next_chunk_offset(1) == 4096
    assert s.logical_to_prev_stripe_offset(8193) == 8192
    assert s.logical_to_next_stripe_offset(8193) == 16384
    assert s.aligned_logical_offset_to_chunk_offset(16384) == 8192
    assert s.aligned_chunk_offset_to_logical_offset(8192) == 16384
    assert s.offset_len_to_stripe_bounds(8193, 8192) == (8192, 16384)
    with pytest.raises(ValueError):
        StripeInfo(3, 8192)  # width not a multiple


def test_batched_encode_equals_per_stripe():
    """One-launch encode over N stripes == the reference's per-stripe
    loop with per-shard append (ECUtil.cc:139-151)."""
    code = make_jerasure({"technique": "reed_sol_van", "k": "3",
                          "m": "2", "w": "8"})
    si = sinfo_for(code, stripe_unit=256)
    nstripes = 5
    rng = np.random.default_rng(11)
    buf = rng.integers(0, 256, nstripes * si.stripe_width,
                       dtype=np.uint8).tobytes()

    batched = stripe.encode(si, code, buf)

    # per-stripe re-derivation through the plain interface
    cs = si.chunk_size
    want = range(code.get_chunk_count())
    per = {i: [] for i in want}
    for s in range(nstripes):
        piece = buf[s * si.stripe_width:(s + 1) * si.stripe_width]
        enc = code.encode(want, piece)
        for i in want:
            per[i].append(np.asarray(enc[i]))
    for i in want:
        joined = np.concatenate(per[i])
        assert np.array_equal(np.asarray(batched[i]), joined), f"shard {i}"
        assert len(batched[i]) == nstripes * cs


def test_batched_decode_recovers_lost_shards():
    code = factory("isa", {"k": "4", "m": "2"})
    si = sinfo_for(code, stripe_unit=128)
    nstripes = 4
    rng = np.random.default_rng(5)
    buf = rng.integers(0, 256, nstripes * si.stripe_width,
                       dtype=np.uint8).tobytes()
    shards = stripe.encode(si, code, buf)
    lost = {1, 4}
    surviving = {i: v for i, v in shards.items() if i not in lost}
    out = stripe.recover_stripes(si, code, surviving, lost)
    for i in lost:
        assert np.array_equal(out[i], shards[i])


def test_decode_unaligned_or_infeasible_raises():
    code = make_jerasure({"technique": "reed_sol_van", "k": "2",
                          "m": "1", "w": "8"})
    si = sinfo_for(code, stripe_unit=64)
    with pytest.raises(ValueError):
        stripe.decode(si, code, {0: np.zeros(65, np.uint8),
                                 1: np.zeros(65, np.uint8)}, {2})
    with pytest.raises(ErasureCodeError):
        stripe.decode(si, code, {0: np.zeros(64, np.uint8)}, {1, 2})


def test_encode_requires_stripe_alignment():
    code = make_jerasure({"technique": "reed_sol_van", "k": "2",
                          "m": "1", "w": "8"})
    si = sinfo_for(code, stripe_unit=64)
    with pytest.raises(ValueError):
        stripe.encode(si, code, b"x" * 100)


def test_crc32c_known_vector():
    """CRC-32C (Castagnoli) standard check value."""
    assert crc32c(b"123456789") ^ 0xFFFFFFFF == 0xE3069283
    # empty input leaves the seed untouched
    assert crc32c(b"", 0x12345678) == 0x12345678


def test_hash_info_cumulative():
    h = HashInfo(3)
    a = np.arange(64, dtype=np.uint8)
    b = (np.arange(64, dtype=np.uint8) * 3).astype(np.uint8)
    h.append(0, {0: a, 1: a, 2: a})
    h.append(64, {0: b, 1: b, 2: b})
    assert h.total_chunk_size == 128
    whole = crc32c(np.concatenate([a, b]))
    assert h.get_chunk_hash(0) == whole
    assert h.get_chunk_hash(1) == whole
