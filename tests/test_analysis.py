"""Concurrency-correctness layer: lockdep, watchdog, dump_blocked.

The lockdep.cc-analogue acceptance tests: a deliberately inverted
lock pair is caught with BOTH witness stacks, a deliberately stalled
handler is reported by the watchdog with a thread dump, and the
``dump_blocked`` admin-socket command serves the same snapshot a
wedged daemon would be debugged with.
"""

import threading
import time

import pytest

from ceph_tpu.analysis import lockdep, watchdog


def test_lockdep_catches_inverted_lock_pair():
    a = lockdep.DLock("tla::a")
    b = lockdep.DLock("tla::b")
    try:
        with lockdep.trap() as got:
            with a:
                with b:
                    pass
            # no violation yet: one order observed exactly once
            assert not got
            with b:
                with a:  # the inversion
                    pass
        assert len(got) == 1
        v = got[0]
        assert v["first"] == "tla::b" and v["then"] == "tla::a"
        # both witness stacks point at THIS file — the lockdep.cc
        # two-backtrace report
        assert "test_analysis.py" in v["existing_stack"]
        assert "test_analysis.py" in v["current_stack"]
    finally:
        lockdep.forget("tla::")


def test_lockdep_transitive_cycle():
    """a->b and b->c recorded, then c->a closes the cycle."""
    a, b, c = (lockdep.DLock(f"tlc::{n}") for n in "abc")
    try:
        with lockdep.trap() as got:
            with a, b:
                pass
            with b, c:
                pass
            with c, a:
                pass
        assert len(got) == 1
        assert got[0]["first"] == "tlc::c"
        assert got[0]["then"] == "tlc::a"
        # the report names the recorded path that the new edge closes
        assert "tlc::a -> tlc::b -> tlc::c" in got[0]["message"]
    finally:
        lockdep.forget("tlc::")


def test_lockdep_reports_each_pair_once():
    a = lockdep.DLock("tlo::a")
    b = lockdep.DLock("tlo::b")
    try:
        with lockdep.trap() as got:
            with a, b:
                pass
            for _ in range(3):
                with b, a:
                    pass
        assert len(got) == 1
    finally:
        lockdep.forget("tlo::")


def test_lockdep_recursive_rlock_is_clean():
    r = lockdep.DRLock("tlr::r")
    with lockdep.trap() as got:
        with r:
            with r:
                assert r._is_owned()
    assert not got


def test_lockdep_self_deadlock_raises():
    lk = lockdep.DLock("tls::self")
    lk.acquire()
    try:
        with lockdep.trap() as got:
            with pytest.raises(RuntimeError, match="self-deadlock"):
                lk.acquire()
        assert len(got) == 1
    finally:
        lk.release()
        lockdep.forget("tls::")


def test_lockdep_nonblocking_probe_does_not_raise():
    """Condition's default _is_owned probes acquire(False); a failed
    non-blocking acquire is not a deadlock and must stay silent."""
    lk = lockdep.DLock("tlp::probe")
    lk.acquire()
    try:
        with lockdep.trap() as got:
            assert lk.acquire(blocking=False) is False
        assert not got
    finally:
        lk.release()


def test_condition_wait_releases_held_bookkeeping():
    """A thread waiting on a Condition does NOT hold its lock: no
    phantom entries for the watchdog, no phantom order edges."""
    cv = threading.Condition(lockdep.DRLock("tlw::cv"))
    entered = threading.Event()
    release = threading.Event()

    def waiter():
        with cv:
            entered.set()
            cv.wait_for(release.is_set, timeout=5)

    th = threading.Thread(target=waiter)
    th.start()
    try:
        assert entered.wait(timeout=5)
        time.sleep(0.05)  # let the wait() release the lock
        held = [h for h in lockdep.held_snapshot()
                if h["name"] == "tlw::cv"]
        assert not held, held
    finally:
        release.set()
        with cv:
            cv.notify_all()
        th.join(timeout=5)


def test_make_lock_is_raw_when_disabled():
    lockdep.enable(False)
    try:
        assert not isinstance(lockdep.make_lock("x"), lockdep.DLock)
        assert not isinstance(lockdep.make_rlock("x"), lockdep.DRLock)
    finally:
        lockdep.enable(True)  # the suite runs with lockdep on
    assert isinstance(lockdep.make_lock("x"), lockdep.DLock)


def test_watchdog_reports_stalled_handler_and_held_lock():
    wd = watchdog.Watchdog(threshold=0.15, interval=0.05)
    lk = lockdep.DLock("twd::held")
    lk.acquire()
    try:
        with watchdog.section("handler:deliberate_stall"):
            time.sleep(0.2)
            reports = wd.poll()
    finally:
        lk.release()
    kinds = {r["kind"] for r in reports}
    assert kinds == {"lock", "section"}, reports
    names = {r["name"] for r in reports}
    assert "twd::held" in names
    assert "handler:deliberate_stall" in names
    # one report per offender instance, not one per scan
    assert wd.poll() == []


def test_dump_blocked_snapshot():
    lk = lockdep.DLock("tdb::held")
    lk.acquire()
    try:
        with watchdog.section("handler:tdb"):
            d = watchdog.dump_blocked(threshold=0.0)
    finally:
        lk.release()
    assert any(e["name"] == "tdb::held" for e in d["blocked_locks"])
    assert any(s["name"] == "handler:tdb"
               for s in d["stalled_sections"])
    # the all-thread stack dump includes this very test frame
    me = f"MainThread({threading.get_ident()})"
    assert me in d["threads"]
    assert "test_dump_blocked_snapshot" in d["threads"][me]


def test_dump_blocked_over_admin_socket(tmp_path):
    from ceph_tpu.common.admin_socket import AdminSocket
    from ceph_tpu.common.context import Context

    ctx = Context("analysis-test", admin_dir=str(tmp_path))
    sock = ctx.start_admin_socket()
    try:
        with watchdog.section("handler:via_asok"):
            rep = AdminSocket.request(ctx.admin_socket_path,
                                      "dump_blocked", stacks=False)
        assert any(s["name"] == "handler:via_asok"
                   for s in rep["stalled_sections"])
        assert "threads" not in rep  # stacks=False honored
    finally:
        ctx.shutdown()


def test_messenger_handlers_are_watchdog_sections():
    """A wedged messenger handler is visible in dump_blocked — the
    watchdog regression test the ISSUE asks for, end to end."""
    from ceph_tpu.msg.messenger import Messenger

    server = Messenger("wd-server")
    client = Messenger("wd-client")
    server.start()
    client.start()
    gate = threading.Event()
    entered = threading.Event()

    def stall(_msg):
        entered.set()
        gate.wait(timeout=10)
        return {"ok": True}

    server.register("stall", stall)
    try:
        th = threading.Thread(
            target=lambda: client.call(server.addr,
                                       {"type": "stall"}, timeout=15))
        th.start()
        assert entered.wait(timeout=5)
        time.sleep(0.2)
        wd = watchdog.Watchdog(threshold=0.1)
        reports = wd.poll()
        assert any(r["kind"] == "section"
                   and r["name"] == "wd-server:stall"
                   for r in reports), reports
        gate.set()
        th.join(timeout=10)
    finally:
        gate.set()
        client.shutdown()
        server.shutdown()


def test_op_scheduler_shutdown_abandons_requeueing_job():
    """Regression for the requeue/shutdown stall (ADVICE low #4): a
    job whose resource never frees is abandoned at shutdown with its
    final run OUTSIDE the scheduler lock, so shutdown completes and
    the submitter gets the abandonment error instead of hanging."""
    from ceph_tpu.common.op_queue import OpScheduler, Requeue

    sched = OpScheduler(n_workers=1)
    box = []

    def starved():
        time.sleep(0.05)
        raise Requeue()

    def submitter():
        try:
            sched.submit("client", starved)
        except RuntimeError as e:
            box.append(e)

    th = threading.Thread(target=submitter)
    th.start()
    time.sleep(0.15)  # let it requeue at least once
    sched.shutdown()
    th.join(timeout=5)
    assert not th.is_alive(), "submitter wedged through shutdown"
    assert box and "abandoned" in str(box[0])
