"""Concurrency-correctness layer: lockdep, watchdog, racecheck.

The lockdep.cc-analogue acceptance tests: a deliberately inverted
lock pair is caught with BOTH witness stacks, a deliberately stalled
handler is reported by the watchdog with a thread dump, and the
``dump_blocked`` admin-socket command serves the same snapshot a
wedged daemon would be debugged with.  The racecheck suite is the
data-race twin: a synthetic racy class is caught with both access
stacks, clean code under its declared guard stays silent, and the
Eraser state machine's edges (init phase, publication, thread
confinement, lockset refinement) are each pinned.  The asyncheck
suite covers the blocking-safety plane's runtime half: a scope that
overruns its budget is recorded with both witnesses, the Enforcer
names a stall WHILE the callback is still blocking, and the
``__hello__`` reply offload is pinned against regression by
re-running the static analyzer over a deliberately reverted
messenger.
"""

import pathlib
import threading
import time

import pytest

from ceph_tpu.analysis import asyncheck, lockdep, racecheck, watchdog


def test_lockdep_catches_inverted_lock_pair():
    a = lockdep.DLock("tla::a")
    b = lockdep.DLock("tla::b")
    try:
        with lockdep.trap() as got:
            with a:
                with b:
                    pass
            # no violation yet: one order observed exactly once
            assert not got
            with b:
                with a:  # the inversion
                    pass
        assert len(got) == 1
        v = got[0]
        assert v["first"] == "tla::b" and v["then"] == "tla::a"
        # both witness stacks point at THIS file — the lockdep.cc
        # two-backtrace report
        assert "test_analysis.py" in v["existing_stack"]
        assert "test_analysis.py" in v["current_stack"]
    finally:
        lockdep.forget("tla::")


def test_lockdep_transitive_cycle():
    """a->b and b->c recorded, then c->a closes the cycle."""
    a, b, c = (lockdep.DLock(f"tlc::{n}") for n in "abc")
    try:
        with lockdep.trap() as got:
            with a, b:
                pass
            with b, c:
                pass
            with c, a:
                pass
        assert len(got) == 1
        assert got[0]["first"] == "tlc::c"
        assert got[0]["then"] == "tlc::a"
        # the report names the recorded path that the new edge closes
        assert "tlc::a -> tlc::b -> tlc::c" in got[0]["message"]
    finally:
        lockdep.forget("tlc::")


def test_lockdep_reports_each_pair_once():
    a = lockdep.DLock("tlo::a")
    b = lockdep.DLock("tlo::b")
    try:
        with lockdep.trap() as got:
            with a, b:
                pass
            for _ in range(3):
                with b, a:
                    pass
        assert len(got) == 1
    finally:
        lockdep.forget("tlo::")


def test_lockdep_recursive_rlock_is_clean():
    r = lockdep.DRLock("tlr::r")
    with lockdep.trap() as got:
        with r:
            with r:
                assert r._is_owned()
    assert not got


def test_lockdep_self_deadlock_raises():
    lk = lockdep.DLock("tls::self")
    lk.acquire()
    try:
        with lockdep.trap() as got:
            with pytest.raises(RuntimeError, match="self-deadlock"):
                lk.acquire()
        assert len(got) == 1
    finally:
        lk.release()
        lockdep.forget("tls::")


def test_lockdep_nonblocking_probe_does_not_raise():
    """Condition's default _is_owned probes acquire(False); a failed
    non-blocking acquire is not a deadlock and must stay silent."""
    lk = lockdep.DLock("tlp::probe")
    lk.acquire()
    try:
        with lockdep.trap() as got:
            assert lk.acquire(blocking=False) is False
        assert not got
    finally:
        lk.release()


def test_condition_wait_releases_held_bookkeeping():
    """A thread waiting on a Condition does NOT hold its lock: no
    phantom entries for the watchdog, no phantom order edges."""
    cv = threading.Condition(lockdep.DRLock("tlw::cv"))
    entered = threading.Event()
    release = threading.Event()

    def waiter():
        with cv:
            entered.set()
            cv.wait_for(release.is_set, timeout=5)

    th = threading.Thread(target=waiter)
    th.start()
    try:
        assert entered.wait(timeout=5)
        time.sleep(0.05)  # let the wait() release the lock
        held = [h for h in lockdep.held_snapshot()
                if h["name"] == "tlw::cv"]
        assert not held, held
    finally:
        release.set()
        with cv:
            cv.notify_all()
        th.join(timeout=5)


def test_make_lock_is_raw_when_disabled():
    lockdep.enable(False)
    try:
        assert not isinstance(lockdep.make_lock("x"), lockdep.DLock)
        assert not isinstance(lockdep.make_rlock("x"), lockdep.DRLock)
    finally:
        lockdep.enable(True)  # the suite runs with lockdep on
    assert isinstance(lockdep.make_lock("x"), lockdep.DLock)


def test_watchdog_reports_stalled_handler_and_held_lock():
    wd = watchdog.Watchdog(threshold=0.15, interval=0.05)
    lk = lockdep.DLock("twd::held")
    lk.acquire()
    try:
        with watchdog.section("handler:deliberate_stall"):
            time.sleep(0.2)
            reports = wd.poll()
    finally:
        lk.release()
    kinds = {r["kind"] for r in reports}
    assert kinds == {"lock", "section"}, reports
    names = {r["name"] for r in reports}
    assert "twd::held" in names
    assert "handler:deliberate_stall" in names
    # one report per offender instance, not one per scan
    assert wd.poll() == []


def test_dump_blocked_snapshot():
    lk = lockdep.DLock("tdb::held")
    lk.acquire()
    try:
        with watchdog.section("handler:tdb"):
            d = watchdog.dump_blocked(threshold=0.0)
    finally:
        lk.release()
    assert any(e["name"] == "tdb::held" for e in d["blocked_locks"])
    assert any(s["name"] == "handler:tdb"
               for s in d["stalled_sections"])
    # the all-thread stack dump includes this very test frame
    me = f"MainThread({threading.get_ident()})"
    assert me in d["threads"]
    assert "test_dump_blocked_snapshot" in d["threads"][me]


def test_dump_blocked_over_admin_socket(tmp_path):
    from ceph_tpu.common.admin_socket import AdminSocket
    from ceph_tpu.common.context import Context

    ctx = Context("analysis-test", admin_dir=str(tmp_path))
    sock = ctx.start_admin_socket()
    try:
        with watchdog.section("handler:via_asok"):
            rep = AdminSocket.request(ctx.admin_socket_path,
                                      "dump_blocked", stacks=False)
        assert any(s["name"] == "handler:via_asok"
                   for s in rep["stalled_sections"])
        assert "threads" not in rep  # stacks=False honored
    finally:
        ctx.shutdown()


def test_messenger_handlers_are_watchdog_sections():
    """A wedged messenger handler is visible in dump_blocked — the
    watchdog regression test the ISSUE asks for, end to end."""
    from ceph_tpu.msg.messenger import Messenger

    server = Messenger("wd-server")
    client = Messenger("wd-client")
    server.start()
    client.start()
    gate = threading.Event()
    entered = threading.Event()

    def stall(_msg):
        entered.set()
        gate.wait(timeout=10)
        return {"ok": True}

    server.register("stall", stall)
    try:
        th = threading.Thread(
            target=lambda: client.call(server.addr,
                                       {"type": "stall"}, timeout=15))
        th.start()
        assert entered.wait(timeout=5)
        time.sleep(0.2)
        wd = watchdog.Watchdog(threshold=0.1)
        reports = wd.poll()
        assert any(r["kind"] == "section"
                   and r["name"] == "wd-server:stall"
                   for r in reports), reports
        gate.set()
        th.join(timeout=10)
    finally:
        gate.set()
        client.shutdown()
        server.shutdown()


def test_op_scheduler_shutdown_abandons_requeueing_job():
    """Regression for the requeue/shutdown stall (ADVICE low #4): a
    job whose resource never frees is abandoned at shutdown with its
    final run OUTSIDE the scheduler lock, so shutdown completes and
    the submitter gets the abandonment error instead of hanging."""
    from ceph_tpu.common.op_queue import OpScheduler, Requeue

    sched = OpScheduler(n_workers=1)
    box = []

    def starved():
        time.sleep(0.05)
        raise Requeue()

    def submitter():
        try:
            sched.submit("client", starved)
        except RuntimeError as e:
            box.append(e)

    th = threading.Thread(target=submitter)
    th.start()
    time.sleep(0.15)  # let it requeue at least once
    sched.shutdown()
    th.join(timeout=5)
    assert not th.is_alive(), "submitter wedged through shutdown"
    assert box and "abandoned" in str(box[0])


# ---------------------------------------------------------------------------
# racecheck: the Eraser-style lockset checker
# ---------------------------------------------------------------------------

def _run_in_thread(fn):
    th = threading.Thread(target=fn)
    th.start()
    th.join(timeout=5)
    assert not th.is_alive()


def test_racecheck_catches_unguarded_write_with_both_stacks():
    """The acceptance test: a synthetic racy class — two threads
    writing a declared-guarded field with no lock — is reported with
    BOTH access stacks, like lockdep's two-backtrace cycle report."""
    @racecheck.guarded_by("tra::lock", "counter")
    class Racy:
        def __init__(self):
            self.counter = 0

    obj = Racy()
    with racecheck.trap() as got:
        _run_in_thread(lambda: setattr(obj, "counter", 1))
        obj.counter = 2  # main thread, no lock held either
    assert len(got) == 1, got
    v = got[0]
    assert v["kind"] == "lockset"
    assert "Racy.counter" in v["message"]
    assert "tra::lock" in v["message"]
    # both witnesses point at this file
    assert "test_analysis.py" in v["existing_stack"]
    assert "test_analysis.py" in v["current_stack"]


def test_racecheck_clean_class_under_its_guard():
    """Hammering a guarded field from several threads that all hold
    the declared lock stays silent."""
    lk = lockdep.make_lock("trc::lock")

    @racecheck.guarded_by("trc::lock", "table")
    class Clean:
        def __init__(self):
            self.table = {}

    obj = Clean()

    def worker():
        for _ in range(30):
            with lk:
                obj.table = dict(obj.table, n=len(obj.table))

    with racecheck.trap() as got:
        threads = [threading.Thread(target=worker) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        with lk:
            obj.table = {}
    assert not got, got


def test_racecheck_publish_ends_init_phase():
    """Construction-thread accesses are unchecked until publish();
    after publication the normal lockset discipline applies."""
    @racecheck.guarded_by("tpb::lock", "field")
    class Obj:
        def __init__(self):
            self.field = 0

    o = Obj()
    with racecheck.trap() as got:
        o.field = 1          # owner, pre-publish: free
        assert o.field == 1
        racecheck.publish(o)
        o.field = 2          # first post-publish access: exclusive
        assert not got
        _run_in_thread(lambda: setattr(o, "field", 3))
    assert len(got) == 1
    assert got[0]["kind"] == "lockset"


def test_racecheck_foreign_access_implicitly_publishes():
    """Handing the object to another thread IS publication: the
    first foreign access ends the init phase without publish()."""
    @racecheck.guarded_by("tip::lock", "field")
    class Obj:
        def __init__(self):
            self.field = 0

    o = Obj()
    with racecheck.trap() as got:
        _run_in_thread(lambda: setattr(o, "field", 1))
        assert not got       # the foreign access itself published
        o.field = 2          # now a racing second thread: caught
    assert len(got) == 1


def test_racecheck_owned_by_thread_confinement():
    """owned_by_thread fields: the first post-publish WRITER owns the
    field; reads from anywhere stay free; a foreign write is a
    confinement violation."""
    @racecheck.guarded_by("tow::lock", "data",
                          owned_by_thread=("books",))
    class Sampler:
        def __init__(self):
            self.books = 0

    s = Sampler()
    racecheck.publish(s)
    with racecheck.trap() as got:
        def owner():
            s.books = 1      # binds ownership to this thread
            s.books = 2
        _run_in_thread(owner)
        assert s.books == 2  # cross-thread READ is fine
        assert not got
        s.books = 3          # cross-thread WRITE is not
    assert len(got) == 1
    assert got[0]["kind"] == "confinement"
    assert "Sampler.books" in got[0]["message"]


def test_racecheck_lockset_refines_to_common_guard():
    """Accesses under {A,B} then under {A} alone refine the candidate
    lockset to {A}: non-empty, so no violation — the Eraser
    intersection at work."""
    a = lockdep.make_lock("trf::a")
    b = lockdep.make_lock("trf::b")

    @racecheck.guarded_by("trf::a", "x")
    class Obj:
        def __init__(self):
            self.x = 0

    o = Obj()
    with racecheck.trap() as got:
        def w1():
            with a:
                with b:
                    o.x = 1
        _run_in_thread(w1)
        with a:
            o.x = 2          # candidate set seeds/refines to {trf::a}
        with a:
            with b:
                o.x = 3      # {trf::a} & {trf::a, trf::b} -> {trf::a}
    assert not got, got


def test_racecheck_shared_container_mutation_guard():
    """shared() wraps a bare dict: mutations need the declared guard
    once published, reads stay lock-free (the GIL-atomic idiom the
    messenger's _sock_writers relies on)."""
    g = lockdep.make_lock("tsh::guard")
    table = racecheck.shared({}, "tsh::guard", "tsh.table")

    def seed():
        with g:
            table["a"] = 1   # foreign access publishes the proxy
    _run_in_thread(seed)
    with racecheck.trap() as got:
        with g:
            table["b"] = 2
        assert table.get("a") == 1  # unguarded READ: legal
        assert not got
        table["c"] = 3              # unguarded MUTATION: caught
    assert len(got) == 1
    assert "tsh.table" in got[0]["message"]
    assert "tsh::guard" in got[0]["message"]


def test_racecheck_gate_accept_and_reject():
    """The conftest gate pair: a clean window passes, a window with a
    violation fails with both stacks in the message, and gate_check
    drains the buffer so the suite's own teardown gate stays green."""
    base = racecheck.mark()
    assert racecheck.gate_check(base) is None  # clean window

    @racecheck.guarded_by("tgg::lock", "f")
    class Obj:
        def __init__(self):
            self.f = 0

    o = Obj()
    _run_in_thread(lambda: setattr(o, "f", 1))
    o.f = 2  # deliberately unguarded — recorded, not trapped
    msg = racecheck.gate_check(base)
    assert msg is not None
    assert "racing access" in msg and "current access" in msg
    assert "test_analysis.py" in msg
    # drained: nothing left for the fixture's own gate
    assert not racecheck.violations()


def test_racecheck_dump_counts_registry():
    # force the swept daemons' modules in so their declarations are
    # registered even when this file runs alone
    import ceph_tpu.common.op_tracker  # noqa: F401
    import ceph_tpu.mgr.daemon  # noqa: F401
    import ceph_tpu.msg.messenger  # noqa: F401
    import ceph_tpu.os.wal_store  # noqa: F401
    import ceph_tpu.services.monitor  # noqa: F401
    import ceph_tpu.services.osd_service  # noqa: F401

    d = racecheck.dump()
    assert d["enabled"] and d["active"]
    # the sweep declared guards across the real daemons at import
    assert any("OpTracker[optracker]" in c
               for c in d["guarded_classes"])
    assert len(d["guarded_classes"]) >= 6
    assert d["guarded_fields"] >= 15
    assert d["shared_objects"] >= 1
    assert isinstance(d["violations"], list)


def test_mgr_sched_state_is_race_guarded():
    """Regression for the mgr tick-loop race: _ModuleSched fields
    (due/bo/error) were written by the tick thread without the state
    lock while admin handlers wrote them under it.  Pin that the
    promoted class stays guarded: unlocked cross-thread writes trip
    racecheck, locked ones do not."""
    from ceph_tpu.mgr.daemon import _ModuleSched

    lk = lockdep.make_rlock("mgr::state")
    st = _ModuleSched()
    with racecheck.trap() as got:
        def handler():
            with lk:
                st.error = "boom"   # publishes; correct discipline
        _run_in_thread(handler)
        with lk:
            st.error = None         # locked: candidate set {mgr::state}
        assert not got
        st.error = "tick-crash"     # the old unlocked tick-loop write
    assert len(got) == 1
    assert "_ModuleSched" in got[0]["message"]


def test_osd_beacon_pass_membership_check_is_locked():
    """Regression for the OSD stat/beacon race: the tick thread read
    `(pool_id, ps) in self._pg_states` without the state lock while
    dispatch threads popped entries.  Pin (lexically) that every
    _pg_states access in _stat_beacon_pass sits under `with
    self._lock`."""
    import ast
    import inspect
    import textwrap

    from ceph_tpu.services.osd_service import OSDService

    src = textwrap.dedent(inspect.getsource(
        OSDService._stat_beacon_pass))
    tree = ast.parse(src)

    def uses_pg_states(node):
        return any(isinstance(n, ast.Attribute) and
                   n.attr == "_pg_states"
                   for n in ast.walk(node))

    unlocked = []

    def walk(node, locked):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                guards = any(
                    isinstance(i.context_expr, ast.Attribute) and
                    i.context_expr.attr == "_lock"
                    for i in child.items)
                walk(child, locked or guards)
            else:
                if not locked and uses_pg_states(child) and not any(
                        isinstance(n, ast.With)
                        for n in ast.walk(child)):
                    unlocked.append(child.lineno)
                walk(child, locked)

    walk(tree, False)
    assert not unlocked, (
        f"_pg_states accessed outside self._lock in "
        f"_stat_beacon_pass at source lines {unlocked}")


def test_lockdep_cross_thread_release_scrubs_holder():
    """Regression for the held-set corruption: a `with lock:`
    suspended inside a generator and close()d on another thread runs
    __exit__ on THAT thread.  The acquiring thread's held list must
    be scrubbed, or it carries a phantom hold that poisons every
    later order edge and racecheck lockset on that thread."""
    lk = lockdep.make_lock("tcx::gen")
    other = lockdep.make_lock("tcx::other")
    try:
        def gen():
            with lk:
                yield 1

        g = gen()
        assert next(g) == 1  # main thread now holds tcx::gen
        assert any(n == "tcx::gen" for n, _ in lockdep._held())
        _run_in_thread(g.close)  # release runs on the other thread
        # no phantom hold on ANY thread
        assert not [h for h in lockdep.held_snapshot()
                    if h["name"] == "tcx::gen"]
        assert not [n for n, _ in lockdep._held()
                    if n == "tcx::gen"]
        # and no phantom order edge from the scrubbed entry
        with lockdep.trap() as got:
            with other:
                pass
        assert not got
        assert "tcx::other" not in lockdep._follows.get("tcx::gen", {})
    finally:
        lockdep.forget("tcx::")


# ---------------------------------------------------------------------
# asyncheck: @nonblocking contracts + loop-stall enforcement
# ---------------------------------------------------------------------
#
# The plane is wallclock-based, so tier-1 drives it deterministically:
# _forced is monkeypatched (auto-restored) instead of arming
# CEPH_TPU_ASYNCHECK suite-wide, budgets are per-scope overrides, and
# Enforcer.poll() is called directly — no enforcer thread to leak into
# the conftest thread gate.


def test_asyncheck_disabled_is_identity(monkeypatch):
    """Decoration while the plane is off must be a true no-op: the
    decorator returns the function itself (zero production overhead)
    and scope()/poll() record nothing."""
    monkeypatch.setattr(asyncheck, "_forced", False)

    def fn():
        return 1

    assert asyncheck.nonblocking(fn) is fn
    with asyncheck.trap() as got:
        with asyncheck.scope("tas::off", budget_ms=0.0):
            time.sleep(0.005)
    assert not got
    assert asyncheck.Enforcer().poll() == []


def test_asyncheck_exit_overrun_records_both_stacks(monkeypatch):
    monkeypatch.setattr(asyncheck, "_forced", True)
    with asyncheck.trap() as got:
        with asyncheck.scope("tas::slow", budget_ms=1.0):
            time.sleep(0.02)
        with asyncheck.scope("tas::fast", budget_ms=5000.0):
            pass  # within budget: silent
    assert [v["scope"] for v in got] == ["tas::slow"]
    rec = got[0]
    assert rec["kind"] == "overrun"
    assert rec["elapsed_ms"] > rec["budget_ms"]
    assert "tas::slow" in rec["message"]
    # both witnesses point back here: who declared the scope, and
    # the exit path it finally returned through
    assert "test_analysis.py" in rec["entry_stack"]
    assert "test_analysis.py" in rec["witness_stack"]


def test_asyncheck_enforcer_names_midstall_scope(monkeypatch):
    """The in-flight half: a poll finds a scope still open past
    budget and captures the owning thread's CURRENT stack — the
    witness that names the blocking call while it blocks.  The
    later exit must not double-report the scope."""
    monkeypatch.setattr(asyncheck, "_forced", True)
    entered = threading.Event()
    release = threading.Event()

    def victim():
        with asyncheck.scope("tas::victim", budget_ms=1.0):
            entered.set()
            release.wait(5)  # the blocking call the witness names

    th = threading.Thread(target=victim, name="tas-victim")
    with asyncheck.trap() as got:
        th.start()
        try:
            assert entered.wait(5)
            enf = asyncheck.Enforcer()
            made = []
            deadline = time.monotonic() + 5
            while not made and time.monotonic() < deadline:
                time.sleep(0.01)
                made = enf.poll()
            # the live-overrun view (dump_asyncheck's payload) sees
            # the same stall without an enforcer
            live = asyncheck.live_overruns()
        finally:
            release.set()
            th.join(timeout=5)
        assert not th.is_alive()
    assert made, "enforcer never witnessed the stall"
    rec = made[0]
    assert rec["kind"] == "stall"
    assert rec["scope"] == "tas::victim"
    assert rec["thread"] == "tas-victim"
    assert "still blocked" in rec["message"]
    assert "victim" in rec["entry_stack"]
    assert "wait" in rec["witness_stack"]  # names release.wait mid-flight
    assert any(o["scope"] == "tas::victim" and "wait" in o["stack"]
               for o in live)
    # poll marked the scope reported: its exit adds no second record
    assert [v["scope"] for v in got].count("tas::victim") == 1


def test_asyncheck_nonblocking_decorator_enforces_budget(monkeypatch):
    """@nonblocking decorated while the plane is on: registers the
    contract and times the body against the module budget."""
    monkeypatch.setattr(asyncheck, "_forced", True)
    monkeypatch.setattr(asyncheck, "_budget_ms", 1.0)

    @asyncheck.nonblocking
    def slow_handler():
        time.sleep(0.02)
        return 7

    assert any(c.endswith("slow_handler")
               for c in asyncheck.dump()["contracts"])
    with asyncheck.trap() as got:
        assert slow_handler() == 7
    assert len(got) == 1
    assert "slow_handler" in got[0]["scope"]
    assert got[0]["kind"] == "overrun"


def test_asyncheck_gate_accept_and_reject(monkeypatch):
    """The gate pair mirrors racecheck's: a clean window passes, a
    window with an overrun fails with both witnesses formatted, and
    the check drains the buffer."""
    monkeypatch.setattr(asyncheck, "_forced", True)
    base = asyncheck.mark()
    assert asyncheck.gate_check(base) is None  # clean window
    with asyncheck.scope("tas::gate", budget_ms=1.0):
        time.sleep(0.02)
    msg = asyncheck.gate_check(base)
    assert msg is not None
    assert "tas::gate" in msg
    assert "scope entered at" in msg and "witness" in msg
    # drained: nothing left for a later gate
    assert not asyncheck.violations()


def test_messenger_hello_reply_stays_off_reader_thread(tmp_path):
    """Regression for the blocking-under-dispatch bug BLOCK001
    found: the ``__hello__`` handshake reply was sent inline on the
    reader thread (_dispatch -> _reply -> _send -> sendall), so one
    backpressured peer socket froze acks, replies and dispatch for
    every frame behind it on that connection.  Pin both halves:
    lexically, the hello reply goes through _pool_submit; statically,
    reverting it to an inline _reply resurfaces the full BLOCK001
    chain under the analyzer that caught it."""
    from tools import lint_async

    import ceph_tpu.msg.messenger as messenger

    src_path = pathlib.Path(messenger.__file__)
    src = src_path.read_text()
    offloaded = ("self._pool_submit(self._reply, conn, msg,\n"
                 "                                  "
                 "{\"in_seq\": ins.in_seq, \"ok\": True},\n"
                 "                                  control=True)")
    assert offloaded in src, "hello reply no longer offloaded"

    # the fix keeps the messenger clean under single-file analysis
    clean, _ = lint_async.analyze([src_path])
    assert clean == []

    # revert the hunk: the pre-fix inline reply on the reader thread
    bad = tmp_path / "messenger.py"
    bad.write_text(src.replace(
        offloaded,
        "self._reply(conn, msg,\n"
        "                            "
        "{\"in_seq\": ins.in_seq, \"ok\": True})"))
    vs, _ = lint_async.analyze([bad])
    chains = [v.message for v in vs if v.code == "BLOCK001"]
    assert chains, "analyzer lost the reverted hello-reply bug"
    assert any("@nonblocking 'Messenger._dispatch'" in m
               and "Messenger._reply" in m
               and "Messenger._send" in m
               for m in chains)
    # the terminal primitive is the peer-socket send
    assert any("sendall" in m or "sendmsg" in m for m in chains)
