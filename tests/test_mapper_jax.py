"""The vmapped JAX mapper vs every golden do_rule vector.

Same corpus as test_mapper_ref.py, but the whole x-range of each case is
mapped in ONE batched call — exercising exactly the program that runs on
TPU (vmap over x, lax.while_loop retry descents, masked bucket chooses).
"""

import json

import numpy as np
import pytest

from conftest import GOLDEN_DIR

from ceph_tpu.crush.map import CrushMap
from ceph_tpu.crush.mapper_jax import BatchedMapper

MAP_FILES = [
    "map_flat12", "map_tree3", "map_tree3_chooseargs", "map_tree3_legacy",
    "map_uniform", "map_list", "map_straw", "map_weird", "map_big10k",
]


def load(name):
    d = json.load(open(GOLDEN_DIR / f"{name}.json"))
    cmap = CrushMap.from_dict(d["map"])
    return cmap, d


@pytest.mark.parametrize("name", MAP_FILES)
def test_golden_map_batched(name):
    cmap, d = load(name)
    cargs = cmap.choose_args.get("golden")
    mapper = BatchedMapper(cmap, choose_args=cargs)
    for case in d["cases"]:
        ruleno = case["ruleno"]
        numrep = case["numrep"]
        weight = np.asarray(case["weight"], np.uint32)
        x0, x1 = case["x0"], case["x1"]
        n = x1 - x0 if name != "map_big10k" else 256
        xs = np.arange(x0, x0 + n, dtype=np.uint32)
        res, lens = mapper.map_batch(ruleno, xs, numrep, weight)
        res = np.asarray(res)
        lens = np.asarray(lens)
        for i in range(n):
            want = case["results"][i]
            got = list(res[i, :lens[i]])
            assert got == want, (name, ruleno, numrep, int(xs[i]),
                                 got, want)
