#!/usr/bin/env python
"""Unified lint runner — every static-analysis family in one pass.

One invocation, one exit code: runs each ``tools/lint_*.py`` family's
``main()`` over the same targets and fails if ANY family found a
violation — the single CI entry point, so a new lint family added to
``tools/`` cannot be forgotten by the build (tests/test_lint.py pins
the FAMILIES registry against the ``lint_*.py`` module set on disk).

Families:
    async        BLOCK001     may-block ops reachable from
                              @nonblocking dispatch contexts
                              (whole-program call-graph walk)
    concurrency  CONC001-005  lock registry, blocking-under-lock,
                              swallowed run-loops, span leaks,
                              unguarded writes to declared state
    jax          JAX001-004   device calls under locks/handlers,
                              host-device sync points, stale jit
                              captures, traced-value branching
    wire         WIRE001-003  wire-format/codec drift
    obs          OBS001-003 + COPY001  counter-registry drift,
                              profiler gating, hot-path copies
    faults       FAULT001-002 failpoint table drift
    config       CONF001      option names absent from the schema

Usage:
    python tools/lint.py [paths...]   # default: each family's own
                                      # default target (ceph_tpu/)
    python tools/lint.py --json       # machine-readable per-family
                                      # findings + timings, one exit
                                      # code
    python tools/lint.py --audit-suppressions
                                      # audit every ``# <fam>-ok:``
                                      # mark in the repo: must name a
                                      # real family, carry a reason,
                                      # and still suppress something

Exit status 1 when any family found violations (or, under
``--audit-suppressions``, when any mark fails the audit).
"""

from __future__ import annotations

import contextlib
import io
import json
import pathlib
import re
import sys
import tempfile
import time
import tokenize
from typing import Dict, List, Tuple

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools import (lint_async, lint_concurrency, lint_config,  # noqa: E402
                   lint_faults, lint_jax, lint_obs, lint_wire)

# family key -> the module whose main() runs it; keys are the
# lint_*.py stem minus the prefix (tests pin this against the on-disk
# module set, so adding tools/lint_foo.py without registering it here
# fails the suite)
FAMILIES = {
    "async": lint_async,
    "concurrency": lint_concurrency,
    "config": lint_config,
    "faults": lint_faults,
    "jax": lint_jax,
    "obs": lint_obs,
    "wire": lint_wire,
}

# suppression-mark word -> owning family key.  Every ``# <word>-ok:``
# comment in the repo must appear here — a typo'd mark suppresses
# nothing silently, which is exactly what --audit-suppressions exists
# to catch.
MARK_FAMILIES: Dict[str, str] = {
    "block": "async",
    "conc": "concurrency",
    "race": "concurrency",
    "conf": "config",
    "fault": "faults",
    "jax": "jax",
    "obs": "obs",
    "copy": "obs",
    "wire": "wire",
}

# directories the suppression audit sweeps (repo-relative)
AUDIT_DIRS = ("ceph_tpu", "tools", "tests")

# a real mark opens its comment (``code  # fam-ok: reason``); a doc
# comment that merely MENTIONS a mark mid-sentence is not audited
_MARK_RE = re.compile(r"^#+\s*([A-Za-z_]+)-ok:(.*)")
# a printed violation line: ``path:line: CODE001 message``
_FINDING_RE = re.compile(r"^\S+:\d+: [A-Z]+\d+\b")


# -- suppression audit -------------------------------------------------
def _iter_marks(repo: pathlib.Path):
    """Yield (path, lineno, family_word, reason) for every
    ``# <word>-ok:`` COMMENT token under AUDIT_DIRS.  Marks quoted
    inside strings/docstrings (lint documentation, test fixtures) are
    not comments and are not audited."""
    for d in AUDIT_DIRS:
        base = repo / d
        if not base.is_dir():
            continue
        for f in sorted(base.rglob("*.py")):
            try:
                toks = tokenize.generate_tokens(
                    io.StringIO(f.read_text()).readline)
                for tok in toks:
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _MARK_RE.match(tok.string)
                    if m:
                        yield (f, tok.start[0], m.group(1),
                               m.group(2).strip())
            except (SyntaxError, tokenize.TokenError,
                    UnicodeDecodeError):
                continue


# strips the leading "# fam-ok:" of a mark comment, leaving "#"
_MARK_STRIP_RE = re.compile(r"#+\s*[A-Za-z_]+-ok:")


def _strip_relint(repo: pathlib.Path, path: pathlib.Path,
                  lineno: int, word: str) -> bool:
    """True when the mark at ``path:lineno`` is STALE: removing it
    does not increase the owning family's finding count for the file.
    Both runs lint a temp MIRROR that preserves the repo-relative
    path (allowlists and per-subtree checks key on it), so the two
    runs differ only by the mark and baseline findings cancel out."""
    mod = FAMILIES[MARK_FAMILIES[word]]
    rel = path.relative_to(repo)
    lines = path.read_text().splitlines(keepends=True)
    if lineno > len(lines):
        return True
    stripped = list(lines)
    stripped[lineno - 1] = _MARK_STRIP_RE.sub(
        "#", stripped[lineno - 1], count=1)
    with tempfile.TemporaryDirectory() as td:
        counts = []
        for sub, text in (("with_mark", lines), ("no_mark", stripped)):
            root = pathlib.Path(td) / sub
            f = root / rel
            f.parent.mkdir(parents=True)
            f.write_text("".join(text))
            try:
                try:
                    vs = mod.lint_file(f, root=root)
                except TypeError:  # family takes no root kwarg
                    vs = mod.lint_file(f)
            except Exception:
                return False  # can't judge -> keep (conservative)
            counts.append(len(vs))
    return counts[1] <= counts[0]


def audit_suppressions(repo: pathlib.Path,
                       as_json: bool = False) -> int:
    """Audit every suppression mark in the repo: the family word must
    exist, the reason must be non-empty, and the mark must still
    suppress a finding (block marks are checked against the set of
    marks the whole-program walk actually consulted; other families
    are strip-and-relinted per file)."""
    # one whole-program walk gives the consulted # block-ok: set —
    # per-file relinting cannot see reachability, so a block mark is
    # stale exactly when no @nonblocking walk consulted it
    _avs, used_block = lint_async.analyze(
        [repo / d for d in AUDIT_DIRS if (repo / d).is_dir()])
    findings: List[str] = []
    marks: List[Tuple[pathlib.Path, int, str, str]] = \
        list(_iter_marks(repo))
    for path, lineno, word, reason in marks:
        rel = path.relative_to(repo).as_posix()
        if word not in MARK_FAMILIES:
            findings.append(
                f"{rel}:{lineno}: SUP001 mark '# {word}-ok:' names no "
                f"lint family (known: "
                f"{', '.join(sorted(MARK_FAMILIES))}) — it suppresses "
                f"nothing")
            continue
        if not reason:
            findings.append(
                f"{rel}:{lineno}: SUP002 mark '# {word}-ok:' carries "
                f"no reason — the reason is the review record")
            continue
        if word == "block":
            stale = (rel, lineno) not in used_block
        else:
            stale = _strip_relint(repo, path, lineno, word)
        if stale:
            findings.append(
                f"{rel}:{lineno}: SUP003 stale mark '# {word}-ok:' — "
                f"removing it produces no "
                f"{MARK_FAMILIES[word]} finding; delete the mark")
    if as_json:
        print(json.dumps({"marks": len(marks),
                          "findings": findings,
                          "ok": not findings}, indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"suppression audit FAILED: {len(findings)} of "
              f"{len(marks)} mark(s)")
        return 1
    print(f"suppression audit clean ({len(marks)} marks)")
    return 0


# -- the family runner -------------------------------------------------
def main(argv: List[str]) -> int:
    argv = list(argv)
    as_json = "--json" in argv
    if as_json:
        argv.remove("--json")
    if "--audit-suppressions" in argv:
        argv.remove("--audit-suppressions")
        repo = pathlib.Path(__file__).resolve().parents[1]
        return audit_suppressions(repo, as_json=as_json)
    failed = []
    results: Dict[str, Dict] = {}
    for name in sorted(FAMILIES):
        t0 = time.monotonic()
        if as_json:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = FAMILIES[name].main(list(argv))
            findings = [ln for ln in buf.getvalue().splitlines()
                        if _FINDING_RE.match(ln)]
        else:
            print(f"== lint: {name} ==")
            rc = FAMILIES[name].main(list(argv))
            findings = []
        results[name] = {
            "rc": rc,
            "findings": findings,
            "elapsed_s": round(time.monotonic() - t0, 3),
        }
        if rc != 0:
            failed.append(name)
    if as_json:
        print(json.dumps({"families": results, "ok": not failed},
                         indent=2))
        return 1 if failed else 0
    if failed:
        print(f"lint FAILED: {', '.join(failed)}")
        return 1
    print(f"lint clean ({len(FAMILIES)} families)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
