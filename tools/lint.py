#!/usr/bin/env python
"""Unified lint runner — every static-analysis family in one pass.

One invocation, one exit code: runs each ``tools/lint_*.py`` family's
``main()`` over the same targets and fails if ANY family found a
violation — the single CI entry point, so a new lint family added to
``tools/`` cannot be forgotten by the build (tests/test_lint.py pins
the FAMILIES registry against the ``lint_*.py`` module set on disk).

Families:
    concurrency  CONC001-005  lock registry, blocking-under-lock,
                              swallowed run-loops, span leaks,
                              unguarded writes to declared state
    jax          JAX001-004   device calls under locks/handlers,
                              host-device sync points, stale jit
                              captures, traced-value branching
    wire         WIRE001-003  wire-format/codec drift
    obs          OBS001-003 + COPY001  counter-registry drift,
                              profiler gating, hot-path copies
    faults       FAULT001-002 failpoint table drift
    config       CONF001      option names absent from the schema

Usage:
    python tools/lint.py [paths...]   # default: each family's own
                                      # default target (ceph_tpu/)
Exit status 1 when any family found violations.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools import (lint_concurrency, lint_config, lint_faults,  # noqa: E402
                   lint_jax, lint_obs, lint_wire)

# family key -> the module whose main() runs it; keys are the
# lint_*.py stem minus the prefix (tests pin this against the on-disk
# module set, so adding tools/lint_foo.py without registering it here
# fails the suite)
FAMILIES = {
    "concurrency": lint_concurrency,
    "config": lint_config,
    "faults": lint_faults,
    "jax": lint_jax,
    "obs": lint_obs,
    "wire": lint_wire,
}


def main(argv: List[str]) -> int:
    failed = []
    for name in sorted(FAMILIES):
        print(f"== lint: {name} ==")
        if FAMILIES[name].main(list(argv)) != 0:
            failed.append(name)
    if failed:
        print(f"lint FAILED: {', '.join(failed)}")
        return 1
    print(f"lint clean ({len(FAMILIES)} families)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
