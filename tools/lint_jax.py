#!/usr/bin/env python
"""JAX compile-hygiene lint — static companion to analysis/jaxcheck.

AST-level checks for the XLA-axis bug classes that never raise on CPU
but destroy TPU throughput (recompilation storms, host-device sync
points, device work on latency-critical threads), enforced by
tests/test_lint.py like the CONC rules:

JAX001  a ``jnp.*`` / ``jax.*`` / ``lax.*`` call lexically inside a
        ``with <lock>`` block or inside a messenger handler (a
        function named ``_h_*``, the services dispatch convention).
        Device dispatch blocks on the backend and — worse — the first
        call with a new shape blocks on XLA *compilation*; doing that
        while holding a lock or occupying a dispatch-pool worker
        stalls every thread behind it (the CONC002 class, XLA
        edition).

JAX002  a host-device sync point in a hot-path module: ``.item()``,
        ``float(x)``, ``np.asarray(...)``, ``.block_until_ready()``.
        Each one forces the async dispatch queue to drain — the
        silent serializer that turns an overlapped pipeline into
        lockstep.  ``__init__`` bodies are exempt (setup is not the
        hot path); benchmark/sync points that are deliberate carry a
        ``# jax-ok: <reason>``.

JAX003  a jit-decorated function whose body reads ``self.*`` or
        declares ``global``.  jax.jit captures closed-over values at
        TRACE time: mutated state silently serves stale values from
        the compiled cache (or retraces per call if used as a
        hashable static) — the classic "jit ate my update" bug.

JAX004  a Python ``if``/``while`` testing a parameter of a
        jit-decorated function (minus ``static_argnames``).  Traced
        values have no truth value — this either raises
        ``TracerBoolConversionError`` at runtime or, when the branch
        collapses at trace time, silently bakes one path in.

Suppression: append ``# jax-ok: <reason>`` to the offending line (or
the introducing ``with``/``def`` line).  The reason is mandatory — it
is the allowlist entry.  tests/test_lint.py additionally carries a
committed allowlist for known-acceptable hits in ``ceph_tpu/``.

Usage:
    python tools/lint_jax.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional

SUPPRESS_MARK = "jax-ok:"

# the roots whose attribute calls mean "device work"
_JAX_ROOTS = {"jnp", "jax", "lax"}

# modules where a host-device sync point is a throughput bug, not a
# style point: the EC engines, both CRUSH lowerings, the fused OSDMap
# pipeline, and the mesh data plane
HOT_MODULES = (
    "ec/engine.py",
    "ec/rs_jax.py",
    "ec/pallas_kernels.py",
    "crush/mapper_jax.py",
    "crush/mapper_spec.py",
    "crush/ln.py",
    "crush/hash.py",
    "osdmap/pipeline_jax.py",
    "parallel/placement.py",
)

_SYNC_ATTRS = {"item", "block_until_ready"}
# lock-ish context-manager spellings (shared with lint_concurrency)
LOCKISH_MARKERS = ("lock", "_cv", "_cond", "_serial", "mutex")


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressed(src_lines: List[str], *linenos: int) -> bool:
    for ln in linenos:
        if 1 <= ln <= len(src_lines) and \
                SUPPRESS_MARK in src_lines[ln - 1]:
            return True
    return False


def _is_lockish(expr: ast.AST) -> bool:
    try:
        text = ast.unparse(expr)
    except Exception:
        return False
    tail = text.split("(", 1)[0].rsplit(".", 1)[-1].lower()
    return any(m in tail for m in LOCKISH_MARKERS)


def _dotted_root(expr: ast.AST) -> Optional[str]:
    """'jnp' for jnp.where(...), 'jax' for jax.lax.cond(...), etc."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_jax_call(node: ast.Call) -> bool:
    return _dotted_root(node.func) in _JAX_ROOTS


def _jit_static_argnames(deco: ast.AST) -> Optional[List[str]]:
    """Non-None when ``deco`` spells a jax.jit decoration; the list
    holds any literal static_argnames."""
    target = deco
    statics: List[str] = []
    if isinstance(deco, ast.Call):
        # functools.partial(jax.jit, static_argnames=(...)) or
        # jax.jit(...)-with-options used as a decorator factory
        root = _dotted_root(deco.func)
        name = deco.func.attr if isinstance(deco.func, ast.Attribute) \
            else (deco.func.id if isinstance(deco.func, ast.Name)
                  else "")
        if name == "partial" and deco.args:
            target = deco.args[0]
        elif name == "jit":
            target = deco.func
        else:
            return None
        for kw in deco.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                try:
                    val = ast.literal_eval(kw.value)
                except Exception:
                    continue
                if isinstance(val, str):
                    statics.append(val)
                elif isinstance(val, (tuple, list)):
                    statics.extend(str(v) for v in val)
        del root
    if isinstance(target, ast.Attribute) and target.attr == "jit":
        return statics
    if isinstance(target, ast.Name) and target.id == "jit":
        return statics
    return None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.out: List[Violation] = []
        self.hot = any(self.rel.endswith(m) for m in HOT_MODULES)
        self._with_lock_stack: List[int] = []
        self._handler_stack: List[str] = []  # _h_* function names
        self._init_depth = 0  # inside an __init__ body

    def _emit(self, code: str, node: ast.AST, message: str,
              *extra_lines: int) -> None:
        if _suppressed(self.lines, node.lineno, *extra_lines):
            return
        self.out.append(Violation(self.rel, node.lineno, code,
                                  message))

    # -- JAX001 / JAX002 ----------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if _is_jax_call(node):
            if self._with_lock_stack:
                self._emit(
                    "JAX001", node,
                    f"device call {ast.unparse(node.func)!r} while a "
                    f"lock is held (with-block at line "
                    f"{self._with_lock_stack[-1]}): dispatch — and "
                    f"first-shape XLA compilation — blocks every "
                    f"thread behind this lock",
                    self._with_lock_stack[-1])
            elif self._handler_stack:
                self._emit(
                    "JAX001", node,
                    f"device call {ast.unparse(node.func)!r} inside "
                    f"messenger handler {self._handler_stack[-1]!r}: "
                    f"device work on a dispatch-pool worker "
                    f"head-of-line-blocks the daemon's message plane")
        if self.hot and not self._init_depth:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
                self._emit(
                    "JAX002", node,
                    f"host-device sync {ast.unparse(f)!r}() in "
                    f"hot-path module: drains the async dispatch "
                    f"queue")
            elif isinstance(f, ast.Attribute) and \
                    f.attr == "asarray" and \
                    _dotted_root(f) == "np":
                self._emit(
                    "JAX002", node,
                    "np.asarray() in hot-path module copies device "
                    "memory to host (a sync point); keep hot data on "
                    "device or mark the deliberate boundary with "
                    "# jax-ok:")
            elif isinstance(f, ast.Name) and f.id == "float" and \
                    node.args and not isinstance(node.args[0],
                                                 ast.Constant):
                self._emit(
                    "JAX002", node,
                    "float(x) in hot-path module forces a scalar "
                    "device→host readback")
        self.generic_visit(node)

    # -- lock-scope tracking (the CONC002 walker) ---------------------
    def visit_With(self, node: ast.With) -> None:
        lockish = any(_is_lockish(item.context_expr)
                      for item in node.items)
        for item in node.items:
            self.visit(item)
        if lockish:
            self._with_lock_stack.append(node.lineno)
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self._with_lock_stack.pop()

    # -- JAX003 / JAX004 ----------------------------------------------
    def _check_jit_body(self, node, statics: List[str]) -> None:
        params = {a.arg for a in (node.args.posonlyargs
                                  + node.args.args
                                  + node.args.kwonlyargs)}
        traced = params - set(statics) - {"self"}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == "self":
                self._emit(
                    "JAX003", sub,
                    f"jitted {node.name!r} reads 'self': jit captures "
                    f"closed-over state at trace time — a later "
                    f"mutation silently serves stale compiled "
                    f"results", node.lineno)
                break
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                self._emit(
                    "JAX003", sub,
                    f"jitted {node.name!r} declares global state; "
                    f"thread it through as an argument", node.lineno)
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.If, ast.While)):
                continue
            names = {n.id for n in ast.walk(sub.test)
                     if isinstance(n, ast.Name)}
            hit = names & traced
            if hit:
                self._emit(
                    "JAX004", sub,
                    f"Python {'if' if isinstance(sub, ast.If) else 'while'} "
                    f"on traced value(s) {sorted(hit)} inside jitted "
                    f"{node.name!r}: traced values have no truth "
                    f"value — use lax.cond/lax.select (or mark the "
                    f"arg static)", node.lineno)

    def _visit_function(self, node) -> None:
        statics = None
        for deco in node.decorator_list:
            s = _jit_static_argnames(deco)
            if s is not None:
                statics = s
                break
        is_handler = node.name.startswith("_h_")
        is_init = node.name == "__init__"
        # a nested def is a fresh frame: locks held around the def are
        # not held when it runs
        saved = self._with_lock_stack
        self._with_lock_stack = []
        if is_handler:
            self._handler_stack.append(node.name)
        if is_init:
            self._init_depth += 1
        self.generic_visit(node)
        if is_init:
            self._init_depth -= 1
        if is_handler:
            self._handler_stack.pop()
        self._with_lock_stack = saved
        if statics is not None and not _suppressed(self.lines,
                                                   node.lineno):
            self._check_jit_body(node, statics)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_function(node)


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    rel = str(path if root is None else path.relative_to(root))
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "JAX000",
                          f"unparseable: {e.msg}")]
    linter = _FileLinter(str(path), rel, src)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: v.line)


def lint_paths(paths: Iterable[pathlib.Path]) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                out.extend(lint_file(f, root=root))
        else:
            out.extend(lint_file(p))
    return out


# Known-acceptable JAX002 hits in ceph_tpu/: every one is a deliberate
# host<->device API boundary, not a hot-loop sync point.  An entry is
# (path suffix, code, substring that must appear on the flagged line);
# a NEW violation matches none of these and fails both the CLI and
# tests/test_lint.py (which imports this table — one source of truth,
# so `python tools/lint_jax.py` and the unified tools/lint.py runner
# agree with the test about what is clean).
ALLOWLIST = (
    # batch ingest: normalize caller arrays once before device upload
    ("crush/mapper_jax.py", "JAX002", "np.asarray(xs, np.uint32)"),
    ("crush/mapper_jax.py", "JAX002", "np.asarray(weight, np.uint32)"),
    ("crush/mapper_spec.py", "JAX002", "np.asarray(xs, np.uint32)"),
    ("crush/mapper_spec.py", "JAX002",
     "np.asarray(weight, np.uint32)"),
    # the explicit *_np host-egress API of the RS facade
    ("ec/rs_jax.py", "JAX002", "np.asarray(self.encode(data))"),
    ("ec/rs_jax.py", "JAX002", "np.asarray(self.decode(chunks"),
    # per-epoch upload of the mutable OSD map vectors
    ("osdmap/pipeline_jax.py", "JAX002", "np.asarray(m.osd_weight"),
    ("osdmap/pipeline_jax.py", "JAX002", "np.asarray(m.osd_state"),
    ("osdmap/pipeline_jax.py", "JAX002", "np.asarray("),
    # np.asarray over the device LIST building a Mesh (no data moved)
    ("parallel/placement.py", "JAX002", "np.asarray(devices)"),
)


def allowlisted(v: Violation) -> bool:
    """Does this violation match a committed ALLOWLIST entry (path
    suffix + code + line substring)?"""
    src = pathlib.Path(v.path)
    if not src.is_absolute():
        src = pathlib.Path(__file__).resolve().parents[1] / v.path
    try:
        line = src.read_text().splitlines()[v.line - 1]
    except (OSError, IndexError):
        return False
    return any(v.path.endswith(path) and v.code == code and sub in line
               for path, code, sub in ALLOWLIST)


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = [v for v in lint_paths(targets) if not allowlisted(v)]
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} JAX hygiene lint violation(s)")
        return 1
    print("jax hygiene lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
