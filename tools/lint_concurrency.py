#!/usr/bin/env python
"""Concurrency lint — static companion to ceph_tpu/analysis.

AST-level checks for the thread-bug classes this framework has
actually shipped (ADVICE round 5), enforced by tests/test_lint.py:

CONC001  raw ``threading.Lock()`` / ``threading.RLock()`` construction
         outside the lock registry.  Unregistered locks are invisible
         to lockdep's order graph and the stall watchdog; build them
         with ``make_lock(name)`` / ``make_rlock(name)``
         (ceph_tpu/analysis/lockdep.py, re-exported by
         common/context.py).

CONC002  a known-blocking call (``fsync``, ``*.recv``, ``*.sleep`` /
         ``time.sleep``, ``sched.submit``) lexically inside a ``with
         <lock>`` block.  Blocking while holding a lock stalls every
         other thread that needs it — the op_queue shutdown stall and
         the "fsync per write serializes the daemon" class.

CONC003  an except clause in a thread run-loop (a function containing
         a ``while`` loop) that can swallow the loop's death: bare
         ``except:`` / ``except BaseException`` anywhere in the loop,
         or ``except Exception`` whose body is only pass/continue.
         The messenger reader died silently from exactly this shape —
         an exception class its narrow except missed, no log, a stale
         connection leaked (messenger.py reader, ADVICE low #2).

CONC004  a ``start_span(...)`` call whose result is not the context
         expression of a ``with`` statement.  A manually begin/end'd
         span leaks on any exception path between begin and end —
         exactly what the per-test span-leak gate
         (tests/conftest.py) then fails; ``with
         tracer.start_span(...) as sp:`` finishes on every path.

CONC005  a write to an attribute a ``@guarded_by(<lock>, ...)``
         declaration (analysis/racecheck.py) covers, lexically
         outside a ``with`` block holding that class's lock for the
         declared guard name.  The static half of the runtime lockset
         checker: the dynamic checker needs the racing interleaving
         to actually run; this catches the unguarded write at review
         time.  ``owned_by_thread`` fields are thread-confined, not
         lock-disciplined, and are exempt.  Suppress with
         ``# race-ok: <reason>`` — the reason is mandatory.

Suppression: append ``# conc-ok: <reason>`` to the offending line (or
the ``with``/``except``/``def`` line introducing it).  The reason is
mandatory — it is the allowlist entry.

Usage:
    python tools/lint_concurrency.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional

SUPPRESS_MARK = "conc-ok:"
RACE_MARK = "race-ok:"

# files allowed to touch raw threading primitives: the registry itself
# (and racecheck, whose violation-record lock must not feed back into
# the lockset checker it implements)
ALLOW_RAW_FILES = ("analysis/lockdep.py", "analysis/watchdog.py",
                   "analysis/racecheck.py", "analysis/asyncheck.py")

# names whose .attr call blocks by design
BLOCKING_ATTRS = {"fsync", "recv", "sleep"}
# lock-ish context-manager expressions: with self._lock, with
# self._pg_lock(...), with clock, with sess.buf_lock, with self._cv ...
LOCKISH_MARKERS = ("lock", "_cv", "_cond", "_serial", "mutex")


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressed(src_lines: List[str], *linenos: int) -> bool:
    for ln in linenos:
        if 1 <= ln <= len(src_lines) and \
                SUPPRESS_MARK in src_lines[ln - 1]:
            return True
    return False


def _is_lockish(expr: ast.AST) -> bool:
    """Heuristic: does this with-item expression denote a mutex?"""
    try:
        text = ast.unparse(expr)
    except Exception:
        return False
    tail = text.split("(", 1)[0].rsplit(".", 1)[-1].lower()
    return any(m in tail for m in LOCKISH_MARKERS)


def _is_raw_lock_ctor(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("Lock", "RLock")
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading")


def _is_blocking_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in BLOCKING_ATTRS:
            return True
        if f.attr == "submit":
            # scheduler submission blocks until the op is served;
            # executor .submit() does not — match the sched spelling
            try:
                owner = ast.unparse(f.value)
            except Exception:
                return False
            return owner.rsplit(".", 1)[-1] == "sched"
    elif isinstance(f, ast.Name) and f.id in BLOCKING_ATTRS:
        return True
    return False


def _guarded_decls(cls: ast.ClassDef) -> dict:
    """{field: guard name} from the class's stacked ``@guarded_by``
    decorators.  ``owned_by_thread`` fields are excluded — they are
    writer-confined, not lock-disciplined (CONC005's scope)."""
    out: dict = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        f = dec.func
        fname = f.attr if isinstance(f, ast.Attribute) \
            else getattr(f, "id", "")
        if fname != "guarded_by":
            continue
        consts = [a.value for a in dec.args
                  if isinstance(a, ast.Constant)
                  and isinstance(a.value, str)]
        if len(consts) < 2:
            continue
        for field in consts[1:]:
            out[field] = consts[0]
    return out


def _broad_except(handler: ast.ExceptHandler) -> Optional[str]:
    """None, or why this handler can swallow the loop's death."""
    def names(t) -> List[str]:
        if t is None:
            return ["<bare>"]
        if isinstance(t, ast.Tuple):
            return [n for e in t.elts for n in names(e)]
        try:
            return [ast.unparse(t).rsplit(".", 1)[-1]]
        except Exception:
            return []

    caught = names(handler.type)
    if "<bare>" in caught or "BaseException" in caught:
        return ("catches everything (KeyboardInterrupt/SystemExit "
                "included)")
    if "Exception" in caught:
        silent = all(isinstance(s, (ast.Pass, ast.Continue))
                     for s in handler.body)
        if silent:
            return "catches Exception and discards it silently"
    return None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.out: List[Violation] = []
        self._with_lock_stack: List[int] = []  # lineno of lock withs
        self._span_with_ok: set = set()  # id() of start_span calls
        # that ARE a with-item context expression

    def _emit(self, code: str, node: ast.AST, message: str,
              *extra_lines: int) -> None:
        if _suppressed(self.lines, node.lineno, *extra_lines):
            return
        self.out.append(Violation(self.rel, node.lineno, code,
                                  message))

    # -- CONC001 ------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if _is_raw_lock_ctor(node) and not any(
                self.rel.endswith(f) for f in ALLOW_RAW_FILES):
            self._emit(
                "CONC001", node,
                "raw threading lock bypasses the lockdep registry; "
                "use make_lock(name)/make_rlock(name)")
        if self._with_lock_stack and _is_blocking_call(node):
            self._emit(
                "CONC002", node,
                f"blocking call {ast.unparse(node.func)!r} while a "
                f"lock is held (with-block at line "
                f"{self._with_lock_stack[-1]})",
                self._with_lock_stack[-1])
        # -- CONC004 --------------------------------------------------
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "start_span" \
                and id(node) not in self._span_with_ok:
            self._emit(
                "CONC004", node,
                "span opened outside a with statement leaks on any "
                "exception path; use `with ....start_span(...) as "
                "sp:`")
        self.generic_visit(node)

    # -- CONC002 scope tracking --------------------------------------
    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call) and \
                    isinstance(ce.func, ast.Attribute) and \
                    ce.func.attr == "start_span":
                self._span_with_ok.add(id(ce))
        lockish = any(_is_lockish(item.context_expr)
                      for item in node.items)
        for item in node.items:
            self.visit(item)
        if lockish:
            self._with_lock_stack.append(node.lineno)
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self._with_lock_stack.pop()

    def _visit_function(self, node) -> None:
        # a nested def is a fresh frame: a lock held by the enclosing
        # function is NOT held when the inner one eventually runs
        saved = self._with_lock_stack
        self._with_lock_stack = []
        self.generic_visit(node)
        self._with_lock_stack = saved
        # -- CONC003 --------------------------------------------------
        for loop in ast.walk(node):
            if not isinstance(loop, ast.While):
                continue
            for sub in ast.walk(loop):
                if not isinstance(sub, ast.Try):
                    continue
                for handler in sub.handlers:
                    why = _broad_except(handler)
                    if why:
                        self._emit(
                            "CONC003", handler,
                            f"run-loop except in {node.name!r} {why}; "
                            f"a dying loop thread must log or "
                            f"re-raise, never vanish", node.lineno)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_function(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_function(node)

    # -- CONC005 ------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        guards = _guarded_decls(node)
        if guards:
            self._check_guarded_class(node, guards)
        self.generic_visit(node)

    def _check_guarded_class(self, cls: ast.ClassDef,
                             guards: dict) -> None:
        # guard name -> the self attribute holding that named lock
        # (``self._lock = make_lock("osd::state")``); a guard whose
        # lock lives elsewhere (module level) matches any lockish with
        lock_attrs: dict = {}
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)
                    and n.value.args
                    and isinstance(n.value.args[0], ast.Constant)):
                continue
            f = n.value.func
            fname = f.attr if isinstance(f, ast.Attribute) \
                else getattr(f, "id", "")
            if fname not in ("make_lock", "make_rlock"):
                continue
            for t in n.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    lock_attrs[n.value.args[0].value] = t.attr
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    item.name != "__init__":
                self._check_guarded_writes(item, guards, lock_attrs)

    def _check_guarded_writes(self, fn, guards: dict,
                              lock_attrs: dict) -> None:
        def with_lock_attrs(node: ast.With) -> List[str]:
            out = []
            for item in node.items:
                if _is_lockish(item.context_expr):
                    try:
                        text = ast.unparse(item.context_expr)
                    except Exception:
                        continue
                    out.append(text.split("(", 1)[0]
                               .rsplit(".", 1)[-1])
            return out

        def walk(node, held: frozenset) -> None:
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = node.targets if isinstance(
                    node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and t.attr in guards:
                        gname = guards[t.attr]
                        want = lock_attrs.get(gname)
                        ok = (want in held) if want else bool(held)
                        if not ok:
                            self._emit_race(node, t.attr, gname, want)
            if isinstance(node, ast.With):
                inner = held | frozenset(with_lock_attrs(node))
                for item in node.items:
                    walk(item, held)
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                # a nested def is a fresh frame: the enclosing with
                # is not held when the inner function eventually runs
                for child in ast.iter_child_nodes(node):
                    walk(child, frozenset())
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, frozenset())

    def _emit_race(self, node: ast.AST, field: str, gname: str,
                   want: Optional[str]) -> None:
        line = self.lines[node.lineno - 1] \
            if 1 <= node.lineno <= len(self.lines) else ""
        if RACE_MARK in line:
            reason = line.split(RACE_MARK, 1)[1].strip()
            if reason:
                return  # suppressed, with its mandatory reason
            self.out.append(Violation(
                self.rel, node.lineno, "CONC005",
                f"'# race-ok:' on the write to {field!r} carries no "
                f"reason — the reason is the allowlist entry"))
            return
        hold = f"`with self.{want}:`" if want \
            else f"a with-block holding {gname!r}"
        self.out.append(Violation(
            self.rel, node.lineno, "CONC005",
            f"write to {field!r} (declared guarded by {gname!r}) "
            f"outside {hold}"))


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    rel = str(path if root is None else path.relative_to(root))
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "CONC000",
                          f"unparseable: {e.msg}")]
    linter = _FileLinter(str(path), rel, src)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: v.line)


def lint_paths(paths: Iterable[pathlib.Path]) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                out.extend(lint_file(f, root=root))
        else:
            out.extend(lint_file(p))
    return out


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} concurrency lint violation(s)")
        return 1
    print("concurrency lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
