#!/usr/bin/env python
"""thrasher — seeded chaos soak over the MiniCluster.

The qa/tasks/thrashosds.py role as a standalone tool: concurrent
writers against a replicated and an EC pool while OSDs (and quorum
monitors) are killed/revived under them AND the fault-injection plane
(ceph_tpu/analysis/faults.py) is armed — dropped/corrupted frames on
the wire, an injected shard-read EIO, a slowed OSD.  The invariants
checked are the storage system's whole promise:

  * every ACKED write is readable afterwards, at its acked value;
  * the cluster converges back to HEALTH_OK once the chaos stops;
  * the analysis planes stay clean (no lockdep violations, no leaked
    tracing spans);
  * every armed failpoint actually fired (a soak that injected
    nothing proved nothing).

Determinism: ONE seed drives both the thrash schedule (victim choice,
action pacing) and the fault plane's probability draws
(``faults.seed``), so a failing run reproduces from its recorded
seed::

    python tools/thrasher.py --seed 8 --duration 20
    python tools/thrasher.py --seed 8 --duration 20   # same schedule

Each run emits a ``CHAOS_rNN.json`` record beside the BENCH_r*.json
series; tools/perf_history.py ingests them into the same trajectory
table (``chaos_ops`` / ``chaos_converge_s`` columns) and flags a run
with lost writes or failed convergence as a regression.

``--host-kill`` runs the whole-host failure drill instead: every OSD
under one CRUSH host bucket dies at once (the failure domain the EC
rule promises to survive), every acked write must read back degraded,
and the host revives EMPTY so the measured traffic is pure recovery.
The cycle runs twice — pipeline depth 1 (the serial per-object
baseline) and the pipelined default — so the emitted
``DRILL_rNN.json`` carries recovery MB/s for both plus the speedup
the red-check gates (>1.5x), then a degraded-read soak races reader
threads against active recovery with shard-read EIOs armed and gates
the p99 against an SLO block.

``--netsplit`` runs the directional network-partition drills on the
``net.partition`` failpoint family instead: (a) a healthy OSD loses
only its mon link — its peers still hear it, so zero false markdowns
and uninterrupted client I/O; (b) full isolation — peer reports must
get it marked down within ``osd_heartbeat_grace + 2x
osd_heartbeat_interval`` with zero acked-write loss and a clean
re-join after the heal; (c) a flapping link — repeated markdowns must
trip the ``osd_markdown_log`` dampener, raise OSD_FLAPPING, and stop
the epoch churn.  The ``NETSPLIT_rNN.json`` record's
``false_markdowns`` / ``detect_s`` / ``epoch_churn`` columns are
red-checked by tools/perf_history.py.

``--slow-ops`` runs the SLO-escalation drill instead: one OSD is
throttled (every op past ``osd_op_complaint_time``, every sent frame
dragged) under write load — SLOW_OPS must rise naming the victim,
OSD_SLOW_PING_TIME must rise from its ping lag, the send stall must
book on the victim's messenger only, and once the throttle lifts the
cluster must clear to HEALTH_OK with zero acked-write loss (emits
``SLODRILL_rNN.json``).

``--race-audit`` runs the chaos soak, the netsplit drills and the
SLO-escalation drill back to back with the data-race checker
(ceph_tpu/analysis/racecheck.py) armed over every swept daemon, then
probes the checker's overhead on a clean write lane in paired
subprocesses (checker on vs off).  The ``RACE_rNN.json`` record is
red-checked hard by tools/perf_history.py: any lockset/confinement
violation, any acked-write loss, or >=10% checker overhead fails.

``--loop-stall`` runs the async-safety drill: the
``msgr.stall_dispatch`` failpoint delays one OSD's control-lane
dispatch callbacks inside their ``@nonblocking`` scopes
(analysis/asyncheck.py), the runtime enforcer must name the victim
callback mid-stall with both-end stacks, and disarming must heal to
HEALTH_OK with zero acked-write loss.  The ``ASYNC_rNN.json`` record
is red-checked by tools/perf_history.py: any unsuppressed static
BLOCK001 violation or >=5% enforcement overhead fails.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import random
import re
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# --race-audit arms the data-race checker, whose guarded_by
# decorators install their descriptors at class-definition time — the
# env must be set BEFORE any ceph_tpu import or the sweep's guard
# declarations are identity no-ops for this process
if "--race-audit" in sys.argv:
    os.environ["CEPH_TPU_RACECHECK"] = "1"
    os.environ.setdefault("CEPH_TPU_LOCKDEP", "1")
# --loop-stall arms the async-safety runtime, whose @nonblocking
# decorators are decoration-time identity no-ops when disabled — same
# before-any-import rule as the race audit
if "--loop-stall" in sys.argv:
    os.environ["CEPH_TPU_ASYNCHECK"] = "1"

from ceph_tpu.analysis import asyncheck, faults, lockdep, racecheck  # noqa: E402
from ceph_tpu.common import tracing  # noqa: E402
from ceph_tpu.common.admin_socket import AdminSocket  # noqa: E402
from ceph_tpu.common.backoff import Backoff  # noqa: E402
from ceph_tpu.common.config import Config  # noqa: E402
from ceph_tpu.services.client import ObjectNotFound  # noqa: E402
from ceph_tpu.services.cluster import MiniCluster  # noqa: E402

# the acceptance fault mix: wire chaos probabilistic (recoverable by
# design — reconnect+replay), the destructive arms COUNTED so a soak
# can't cascade shard removals past the EC profile's m (that would
# manufacture data loss no real cluster promised to survive)
DEFAULT_SPEC = ("msgr.drop_frame=p:0.02;"
                "msgr.corrupt_frame=p:0.02;"
                "msgr.dup_frame=p:0.02;"
                "osd.slow_op=p:0.05,delay:0.03;"
                "osd.shard_read_eio=count:1")


def _conf() -> Config:
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.2)
    c.set("mon_osd_down_out_interval", 1.5)
    c.set("mon_lease", 0.3)
    c.set("mon_election_timeout", 0.5)
    # the soak's kill/revive cadence IS flapping by design (the
    # qa thrasher sets noout/nodown for the same reason): give the
    # markdown log enough budget that dampening never defers the
    # revives the verify phase depends on
    c.set("osd_max_markdown_count", 1000)
    # the balancer rides the soak with a tight loop and deviation
    # target so its pause gate is exercised while OSDs flap
    c.set("balancer_interval", 1.0)
    c.set("balancer_max_deviation", 1)
    return c


class _Writer(threading.Thread):
    """Loops put/delete (and EC partial overwrites) over its own key
    space, recording the last ACKED value per key; keys touched by an
    UNACKED attempt are 'dirty' — the op may still have landed
    durably (a legal outcome), so only readability is asserted."""

    def __init__(self, cluster: MiniCluster, wid: int, pool_id: int,
                 ec: bool):
        super().__init__(daemon=True, name=f"chaos-w{wid}")
        self.cluster = cluster
        self.wid = wid
        self.pool = pool_id
        self.ec = ec
        self.cli = cluster.client(f"chaos-w{wid}-{pool_id}")
        self.acked: Dict[str, Optional[bytes]] = {}
        self.dirty: set = set()
        self.ops = 0
        self.stop = threading.Event()

    def run(self) -> None:
        i = 0
        while not self.stop.is_set():
            key = f"w{self.wid}-k{i % 7}"
            val = f"{self.wid}:{i}:".encode() * 40
            op = None
            try:
                if i % 11 == 10:
                    op = "delete"
                    self.cli.delete(self.pool, key)
                    self.acked[key] = None
                    self.dirty.discard(key)
                else:
                    op = "put"
                    self.cli.put(self.pool, key, val)
                    self.acked[key] = val
                    self.dirty.discard(key)
                self.ops += 1
            except Exception:
                if op is not None:
                    self.dirty.add(key)
            i += 1
        self.cli.shutdown()


def _verify(cluster: MiniCluster,
            writers: List[_Writer]) -> List[tuple]:
    """Read back every acked key; returns the violations."""
    checker = cluster.client("chaos-check")
    bad: List[tuple] = []
    try:
        for w in writers:
            for key, want in w.acked.items():
                fuzzy = key in w.dirty
                bo = Backoff(base=0.2, cap=1.0, deadline=20.0)
                while True:
                    try:
                        try:
                            got = checker.get(w.pool, key,
                                              notfound_retries=0)
                        except ObjectNotFound:
                            got = None
                        if fuzzy:
                            break  # readable (or legally absent)
                        if got == want:
                            break
                        if not bo.sleep():
                            bad.append((w.pool, key, "mismatch"))
                            break
                    except Exception as e:  # Backoff-paced
                        if not bo.sleep():
                            bad.append((w.pool, key, repr(e)))
                            break
    finally:
        checker.shutdown()
    return bad


def soak(seed: int = 0, duration: float = 20.0, n_osds: int = 5,
         n_mons: int = 1, spec: str = DEFAULT_SPEC,
         settle_timeout: float = 60.0) -> Dict:
    """One seeded chaos soak; returns the CHAOS record dict."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    base_lockdep = len(lockdep.violations())
    base_spans = {id(s) for _svc, s in tracing.active_spans()}

    # persistent stores: kill/revive is a daemon crash+restart over
    # the OSD's surviving disk (the thrashosds contract), NOT a disk
    # wipe.  Without this, every revive reformats the store, and two
    # kills inside one recovery window erase 2 of 3 shards — loss the
    # k=2/m=1 profile never promised to survive.
    data_root = tempfile.mkdtemp(prefix=f"chaos-s{seed}-")
    c = MiniCluster(n_osds=n_osds, hosts=n_osds, config=_conf(),
                    n_mons=n_mons, data_dir=data_root).start()
    result: Dict = {"kind": "chaos", "seed": seed,
                    "duration": duration, "n_osds": n_osds,
                    "n_mons": n_mons, "spec": spec}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.create_ec_pool(2, "chaos21", {"plugin": "jerasure",
                                        "technique": "reed_sol_van",
                                        "k": "2", "m": "1", "w": "8"},
                         pg_num=8)
        writers = [_Writer(c, 0, 1, ec=False),
                   _Writer(c, 1, 1, ec=False),
                   _Writer(c, 2, 2, ec=True)]
        for w in writers:
            w.start()
        # an ACTIVE balancer rides the whole soak: its pause gate
        # (no upmap proposals while the cluster is degraded) is a
        # robustness invariant this soak asserts below
        mgr = c.start_mgr()
        bal = mgr.modules["balancer"]
        bal.active = True
        c.set_faults(spec)

        end = time.monotonic() + duration
        while time.monotonic() < end:
            victim = rng.randrange(n_osds)
            c.kill_osd(victim)
            if n_mons > 1 and rng.random() < 0.3:
                rank = rng.randrange(1, n_mons)
                if rank in c.mons and len(c.mons) == n_mons:
                    c.kill_mon(rank)
                    time.sleep(0.5 + rng.random())
                    c.revive_mon(rank)
            time.sleep(0.8 + rng.random())
            c.revive_osd(victim)
            time.sleep(0.4 + rng.random() * 0.4)

        # chaos off; give in-flight faulted ops a beat to drain so
        # the writers' LAST acked values are post-fault reality
        c.set_faults("")
        time.sleep(1.0)
        for w in writers:
            w.stop.set()
        for w in writers:
            w.join(timeout=30)
        result["ops"] = sum(w.ops for w in writers)

        # settle: all osds up, then time the HEALTH_OK convergence
        for o in range(n_osds):
            if o not in c.osds:
                c.revive_osd(o)
        t0 = time.monotonic()
        try:
            c.wait_for_health_ok(timeout=settle_timeout)
            result["health_converge_s"] = round(
                time.monotonic() - t0, 3)
            converged = True
        except TimeoutError as e:
            result["health_converge_s"] = None
            result["health_error"] = str(e)
            converged = False
        time.sleep(2.0)  # a peering pass after the last epoch

        bad = _verify(c, writers)
        result["checked"] = sum(len(w.acked) for w in writers)
        result["lost"] = len(bad)
        result["bad"] = [list(b) for b in bad[:5]]
        result["fired"] = faults.snapshot()
        armed = [p.strip().split("=")[0]
                 for p in spec.split(";") if p.strip()]
        result["unfired_armed"] = sorted(
            n for n in armed if not result["fired"].get(n))
        result["balancer_rounds"] = bal.rounds
        result["balancer_pauses"] = int(
            mgr.pc.dump().get("balancer_paused", 0))
        result["balancer_proposals"] = sum(
            p["proposed"] for p in bal.proposal_log)
        result["balancer_degraded_proposals"] = sum(
            1 for p in bal.proposal_log if p["degraded"])
    finally:
        c.shutdown()
        faults.reset()
        shutil.rmtree(data_root, ignore_errors=True)

    result["lockdep_violations"] = \
        len(lockdep.violations()) - base_lockdep
    # daemon threads die with their sockets; give them a beat before
    # judging the span plane
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaks = [s for _svc, s in tracing.active_spans()
                 if id(s) not in base_spans]
        if not leaks:
            break
        time.sleep(0.1)
    result["span_leaks"] = len(
        [s for _svc, s in tracing.active_spans()
         if id(s) not in base_spans])
    result["ok"] = bool(
        result.get("lost") == 0 and converged
        and result["lockdep_violations"] == 0
        and result["span_leaks"] == 0
        and not result["unfired_armed"]
        and result.get("balancer_degraded_proposals", 0) == 0)
    return result


# -- whole-host failure drill + degraded-read soak --------------------

def _drill_conf(depth: int) -> Config:
    c = _conf()
    # keep the killed host's OSDs IN while they are down: the drill
    # reads degraded against the stable mapping, then revives the
    # same OSDs empty — so the measured traffic is pure recovery
    # pushes back onto the wiped host, not a CRUSH remap shuffle
    c.set("mon_osd_down_out_interval", 60.0)
    c.set("osd_recovery_pipeline_depth", depth)
    # small units -> many of them: the pipeline's overlap (unit N+1
    # gathering while unit N decodes) is what the speedup gate
    # measures, and it needs units to overlap
    c.set("osd_recovery_batch_max_objects", 2)
    c.set("osd_recovery_sleep", 0.0)
    return c


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def _recovery_bytes(cluster: MiniCluster) -> int:
    return sum(int(svc.pc.dump().get("recovery_bytes", 0))
               for svc in cluster.osds.values())


def _host_members(cluster: MiniCluster, host: str) -> List[int]:
    """OSD ids under one CRUSH host bucket (the blast radius of a
    whole-host failure)."""
    crush = cluster.wrapper
    bucket = crush.get_item_id(host)
    return sorted(d for d in range(cluster.n_osds)
                  if crush.get_immediate_parent_id(d) == bucket)


def _kill_host_phase(seed: int, depth: int, n_osds: int, hosts: int,
                     n_objects: int, obj_bytes: int,
                     settle_timeout: float,
                     net_delay: float = 0.015) -> Dict:
    """One measured whole-host kill/recover cycle at a given pipeline
    depth: write, kill every OSD of host0, verify every acked write
    reads back degraded, revive the host EMPTY (fresh stores — real
    recovery traffic), and time the recovery to clean.

    ``net_delay`` models per-link network latency on OSD-to-OSD
    frames via the seeded ``msgr.delay_frame`` failpoint for the
    timed window.  In-process loopback RTT is microseconds, which
    hides exactly the cost the pipeline exists to overlap (helper
    reads wait on the network in any real deployment); the delay is
    identical at every depth, recorded in the output, and cleared
    before the post-recovery readback."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    c = MiniCluster(n_osds=n_osds, hosts=hosts,
                    config=_drill_conf(depth)).start()
    out: Dict = {"depth": depth}
    try:
        # k=2/m=1 with failure-domain host across `hosts` hosts: a
        # whole-host failure costs exactly ONE shard per PG — the
        # survivable worst case the profile promises
        c.create_ec_pool(3, "drill21", {"plugin": "jerasure",
                                        "technique": "reed_sol_van",
                                        "k": "2", "m": "1", "w": "8"},
                         pg_num=4)
        cli = c.client(f"drill-d{depth}")
        acked: Dict[str, bytes] = {}
        for i in range(n_objects):
            val = bytes(rng.randrange(256)
                        for _ in range(7)) * (obj_bytes // 7)
            cli.put(3, f"drill-{i}", val)
            acked[f"drill-{i}"] = val

        # expected shard layout, computed BEFORE the kill while every
        # OSD is up (a down OSD drops out of the reported up set):
        # placement is stable (down-out disabled, the kill never
        # remaps), so recovery is done exactly when every shard the
        # victims held has been rebuilt onto them
        from ceph_tpu.osdmap.bincode_maps import payload_map
        from ceph_tpu.services.client import object_to_ps

        victims = _host_members(c, "host0")
        m = payload_map(c.mon_command({"type": "get_map"}))
        pool = m.pools[3]
        expect: List[Tuple[int, str, str]] = []
        for oid in acked:
            ps = object_to_ps(oid) % pool.pg_num
            up, _p, _a, _ap = m.pg_to_up_acting_osds(3, ps)
            for pos, osd in enumerate(up):
                if osd in victims:
                    expect.append((osd, f"3.{ps}", f"{oid}.s{pos}"))

        for o in victims:
            c.kill_osd(o)
        for o in victims:
            c.wait_for_down(o, timeout=20)

        # degraded reads: every ACKED write must read back from the
        # survivors while the host is dark — zero acked-write loss
        lost = 0
        for key, want in acked.items():
            try:
                if cli.get(3, key) != want:
                    lost += 1
            except Exception:
                lost += 1
        out["lost_degraded"] = lost

        # revive the whole host with EMPTY stores and time the
        # recovery that rebuilds every lost shard from survivors.
        # The speedup gate compares gather/decode time across pipeline
        # depths, so the clock runs from the FIRST recovered byte to
        # the last rebuilt victim shard — the revive/heartbeat
        # detection latency ahead of it is identical at every depth
        # and the harness's 0.2s poll would quantize it away.
        if net_delay > 0:
            c.set_faults(f"msgr.delay_frame=p:1.0,"
                         f"delay:{net_delay},who:osd.")
            out["net_delay_s"] = net_delay
        base = _recovery_bytes(c)
        t0 = time.monotonic()
        for o in victims:
            c.revive_osd(o)

        def _rebuilt() -> bool:
            return all(c.osds[osd].store.stat(cid, sh) is not None
                       for osd, cid, sh in expect
                       if osd in c.osds)

        t_first = None
        deadline = time.monotonic() + settle_timeout
        while time.monotonic() < deadline:
            if t_first is None and _recovery_bytes(c) > base:
                t_first = time.monotonic()
            if _rebuilt():
                break
            time.sleep(0.005)  # measurement poll cadence
        t_done = time.monotonic()
        c.set_faults("")  # readback + convergence at loopback speed
        try:
            c.wait_for_recovery(3, acked, timeout=settle_timeout)
            out["detect_s"] = round((t_first or t_done) - t0, 3)
            out["recover_s"] = round(t_done - (t_first or t0), 3)
            c.wait_for_health_ok(timeout=settle_timeout)
            out["converge_s"] = round(time.monotonic() - t0, 3)
        except TimeoutError as e:
            out["error"] = str(e)
            return out
        moved = _recovery_bytes(c) - base
        out["recovered_bytes"] = moved
        out["recovery_mbps"] = round(
            moved / 1e6 / max(1e-9, out["recover_s"]), 3)

        # post-recovery readback: recovery must hand back the same
        # acked bytes it found
        lost_after = 0
        for key, want in acked.items():
            try:
                if cli.get(3, key) != want:
                    lost_after += 1
            except Exception:
                lost_after += 1
        out["lost"] = lost + lost_after
        out["checked"] = len(acked)
        rec = {}
        for svc in c.osds.values():
            for k_, v_ in svc.rec_pc.dump().items():
                if isinstance(v_, (int, float)) and v_:
                    rec[k_] = rec.get(k_, 0) + int(v_)
        out["recovery_counters"] = rec
    finally:
        c.shutdown()
        faults.reset()
    return out


def host_kill_drill(seed: int = 8, n_osds: int = 6, hosts: int = 3,
                    n_objects: int = 48, obj_bytes: int = 14 * 1024,
                    depth: int = 3, net_delay: float = 0.015,
                    settle_timeout: float = 90.0) -> Dict:
    """The whole-host failure drill: the same seeded kill/recover
    cycle measured twice — once serial (pipeline depth 1, the
    per-object gather-then-decode baseline) and once pipelined — so
    the record carries the recovery-MB/s speedup the pipeline gate
    red-checks (>1.5x), alongside the durability verdicts."""
    result: Dict = {"kind": "drill", "seed": seed, "n_osds": n_osds,
                    "hosts": hosts, "objects": n_objects,
                    "obj_bytes": obj_bytes}
    serial = _kill_host_phase(seed, 1, n_osds, hosts, n_objects,
                              obj_bytes, settle_timeout,
                              net_delay=net_delay)
    piped = _kill_host_phase(seed, depth, n_osds, hosts, n_objects,
                             obj_bytes, settle_timeout,
                             net_delay=net_delay)
    result["serial"] = serial
    result["pipelined"] = piped
    result["recovery_mbps_serial"] = serial.get("recovery_mbps")
    result["recovery_mbps"] = piped.get("recovery_mbps")
    result["converge_s"] = piped.get("converge_s")
    result["lost"] = (serial.get("lost", 1) + piped.get("lost", 1))
    result["checked"] = (serial.get("checked", 0)
                         + piped.get("checked", 0))
    if serial.get("recovery_mbps") and piped.get("recovery_mbps"):
        result["pipeline_speedup"] = round(
            piped["recovery_mbps"] / serial["recovery_mbps"], 3)
    result["ok"] = bool(
        result["lost"] == 0
        and serial.get("converge_s") is not None
        and piped.get("converge_s") is not None
        and result.get("pipeline_speedup", 0) > 1.5)
    return result


def degraded_read_soak(seed: int = 8, duration: float = 8.0,
                       n_osds: int = 4, n_objects: int = 48,
                       obj_bytes: int = 14 * 1024,
                       slo_p99_ms: float = 250.0,
                       eio_p: float = 0.02,
                       settle_timeout: float = 90.0) -> Dict:
    """Degraded reads under ACTIVE recovery with helper EIOs armed:
    one OSD dies and comes back empty; while its shards rebuild
    (osd_recovery_sleep stretches the window), reader threads hammer
    the pool through the degraded path with ``osd.shard_read_eio``
    firing probabilistically.  The p99 read latency gates against the
    SLO block — recovery must not starve clients.

    The EIO arm is scoped to ONE surviving OSD: an injected shard
    EIO drops the shard for repair, so on a k=2,m=2 pool the worst
    case is the empty victim plus the scoped OSD's shards = exactly
    m losses — every object stays recoverable by construction, and
    the soak measures latency, not data loss."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    conf = _drill_conf(depth=3)
    # stretch recovery across the soak window so every latency sample
    # really races active recovery pushes
    conf.set("osd_recovery_sleep", 0.05)
    conf.set("osd_recovery_batch_max_objects", 1)
    c = MiniCluster(n_osds=n_osds, hosts=n_osds, config=conf).start()
    result: Dict = {"kind": "drill_soak", "seed": seed,
                    "duration": duration, "eio_p": eio_p}
    try:
        c.create_ec_pool(3, "soak22", {"plugin": "jerasure",
                                       "technique": "reed_sol_van",
                                       "k": "2", "m": "2", "w": "8"},
                         pg_num=4)
        cli = c.client("soak-w")
        acked: Dict[str, bytes] = {}
        for i in range(n_objects):
            val = bytes(rng.randrange(256)
                        for _ in range(7)) * (obj_bytes // 7)
            cli.put(3, f"soak-{i}", val)
            acked[f"soak-{i}"] = val
        victim = rng.randrange(n_osds)
        eio_osd = (victim + 1) % n_osds
        c.kill_osd(victim)
        c.wait_for_down(victim, timeout=20)
        c.revive_osd(victim)  # empty store: recovery starts now
        c.set_faults(
            f"osd.shard_read_eio=p:{eio_p},who:osd.{eio_osd}")

        lats: List[float] = []
        errors = [0]
        stop = threading.Event()

        def reader(wid: int) -> None:
            r = random.Random(seed * 1000 + wid)
            rcli = c.client(f"soak-r{wid}")
            keys = sorted(acked)
            while not stop.is_set():
                key = keys[r.randrange(len(keys))]
                t0 = time.monotonic()
                try:
                    got = rcli.get(3, key)
                    lats.append(time.monotonic() - t0)
                    if got != acked[key]:
                        errors[0] += 1
                except Exception:
                    errors[0] += 1

        threads = [threading.Thread(target=reader, args=(w,),
                                    daemon=True) for w in range(3)]
        for t in threads:
            t.start()
        time.sleep(duration)
        stop.set()
        for t in threads:
            t.join(timeout=20)
        c.set_faults("")

        lats.sort()
        p99 = _percentile(lats, 0.99) * 1000
        result["reads"] = len(lats)
        result["read_errors"] = errors[0]
        result["p50_ms"] = round(_percentile(lats, 0.50) * 1000, 3)
        result["p99_ms"] = round(p99, 3)
        result["fired"] = faults.snapshot()
        result["slo"] = {"metric": "degraded_read_p99_ms",
                         "limit": slo_p99_ms,
                         "value": round(p99, 3),
                         "pass": bool(lats) and p99 <= slo_p99_ms}
        # the soak must end in a healthy cluster with zero mismatches
        try:
            c.wait_for_recovery(3, acked, timeout=settle_timeout)
            c.wait_for_health_ok(timeout=settle_timeout)
            converged = True
        except TimeoutError as e:
            result["error"] = str(e)
            converged = False
        result["ok"] = bool(result["slo"]["pass"] and converged
                            and errors[0] == 0)
    finally:
        c.shutdown()
        faults.reset()
    return result


def drill(seed: int = 8, soak_duration: float = 8.0,
          slo_p99_ms: float = 250.0) -> Dict:
    """The full DRILL record: whole-host kill cycle (serial +
    pipelined) then the degraded-read soak, one combined verdict."""
    rec = host_kill_drill(seed=seed)
    rec["soak"] = degraded_read_soak(seed=seed,
                                     duration=soak_duration,
                                     slo_p99_ms=slo_p99_ms)
    rec["ok"] = bool(rec["ok"] and rec["soak"]["ok"])
    return rec


# -- netsplit drills (directional net.partition failpoints) -----------

def _netsplit_conf() -> Config:
    c = _conf()
    c.set("osd_heartbeat_interval", 0.25)
    c.set("osd_heartbeat_grace", 1.0)
    # the whole point: peer reports detect failure; the direct mon
    # beacon is liveness-of-last-resort only, far outside the drill
    c.set("mon_osd_report_timeout", 30.0)
    c.set("mon_osd_down_out_interval", 2.0)
    c.set("mon_osd_min_down_reporters", 2)
    return c


def _mon_partition_phase(seed: int, n_osds: int = 4,
                         hold_s: float = 5.0) -> Dict:
    """(a) cut the mon<->osd link of one HEALTHY osd, both ways, for
    ~5x grace: its peers still hear it, so the detector must record
    ZERO false markdowns and client I/O must keep flowing.  This is
    the exact scenario the old beacon-only detector failed (a cut mon
    link killed a serving osd)."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    c = MiniCluster(n_osds=n_osds, hosts=n_osds,
                    config=_netsplit_conf()).start()
    out: Dict = {"phase": "mon_partition", "hold_s": hold_s}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        w = _Writer(c, 0, 1, ec=False)
        w.start()
        victim = rng.randrange(n_osds)
        out["victim"] = victim
        time.sleep(1.5)  # steady state: peers established, writes up
        base_md = int(c.mon.pc.dump().get("markdowns", 0))
        ops0 = w.ops
        c.set_faults(f"net.partition=p:1.0,"
                     f"pairs:osd.{victim}>mon|mon>osd.{victim}")
        went_down = False
        t_end = time.monotonic() + hold_s
        while time.monotonic() < t_end:
            if victim not in c.status()["up_osds"]:
                went_down = True
                break
            time.sleep(0.1)  # drill observation cadence
        c.set_faults("")
        out["false_markdowns"] = int(went_down) + max(
            0, int(c.mon.pc.dump().get("markdowns", 0)) - base_md)
        out["ops_during_cut"] = w.ops - ops0
        w.stop.set()
        w.join(timeout=20)
        bad = _verify(c, [w])
        out["checked"] = len(w.acked)
        out["lost"] = len(bad)
        c.wait_for_health_ok(timeout=30)
        out["ok"] = bool(out["false_markdowns"] == 0
                         and out["lost"] == 0
                         and out["ops_during_cut"] > 0)
    finally:
        c.shutdown()
        faults.reset()
    return out


def _isolation_phase(seed: int, n_osds: int = 4) -> Dict:
    """(b) fully isolate one osd (both directions, everyone): peers
    must report it and the mon must mark it down within
    osd_heartbeat_grace + 2*osd_heartbeat_interval; writes keep
    acking on the survivors with zero acked-write loss; after the
    heal the victim learns its markdown, re-boots, and the cluster
    reconverges to HEALTH_OK."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    conf = _netsplit_conf()
    grace = conf["osd_heartbeat_grace"]
    interval = conf["osd_heartbeat_interval"]
    c = MiniCluster(n_osds=n_osds, hosts=n_osds, config=conf).start()
    out: Dict = {"phase": "isolation",
                 "detect_bound_s": round(grace + 2 * interval, 3)}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        writers = [_Writer(c, 0, 1, ec=False),
                   _Writer(c, 1, 1, ec=False)]
        for w in writers:
            w.start()
        victim = rng.randrange(n_osds)
        out["victim"] = victim
        time.sleep(1.5)
        epoch0 = int(c.status()["epoch"])
        c.set_faults(f"net.partition=p:1.0,"
                     f"pairs:osd.{victim}>*|*>osd.{victim}")
        t0 = time.monotonic()
        detect = None
        deadline = t0 + 20.0
        while time.monotonic() < deadline:
            if victim not in c.status()["up_osds"]:
                detect = time.monotonic() - t0
                break
            time.sleep(0.05)  # detection-latency poll
        out["detect_s"] = round(detect, 3) if detect else None
        # hold through down->out so the markdown/out interplay runs
        # while the victim is dark, then heal: the victim's beats
        # resume, the mon nudges it the map it missed, and it re-boots
        time.sleep(conf["mon_osd_down_out_interval"] + 1.0)
        c.set_faults("")
        c.wait_for_up(victim, timeout=30)
        for w in writers:
            w.stop.set()
        for w in writers:
            w.join(timeout=20)
        bad = _verify(c, writers)
        out["checked"] = sum(len(w.acked) for w in writers)
        out["lost"] = len(bad)
        c.wait_for_health_ok(timeout=60)
        out["epoch_churn"] = int(c.status()["epoch"]) - epoch0
        out["ok"] = bool(detect is not None
                         and detect <= grace + 2 * interval
                         and out["lost"] == 0)
    finally:
        c.shutdown()
        faults.reset()
    return out


def _flap_phase(seed: int, n_osds: int = 4,
                hold_s: float = 8.0) -> Dict:
    """(c) a flapping link: the victim keeps its mon link but loses
    its peers, so every re-boot is followed by another reporter-quorum
    markdown.  Crossing osd_max_markdown_count must dampen the daemon
    (boot deferred + auto-out), raise OSD_FLAPPING, and STOP the epoch
    churn; once the link heals and the markdown log drains, the osd
    rejoins and health clears."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    conf = _netsplit_conf()
    conf.set("osd_max_markdown_count", 3)
    conf.set("osd_max_markdown_period", 12.0)
    c = MiniCluster(n_osds=n_osds, hosts=n_osds, config=conf).start()
    out: Dict = {"phase": "flap", "hold_s": hold_s}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        w = _Writer(c, 0, 1, ec=False)
        w.start()
        victim = rng.randrange(n_osds)
        out["victim"] = victim
        time.sleep(1.5)
        epoch0 = int(c.status()["epoch"])
        # peers cut both ways, mon link OPEN: markdown epochs reach
        # the victim, it re-boots, and the flap cycle spins
        c.set_faults(f"net.partition=p:1.0,"
                     f"pairs:osd.{victim}>osd.|osd.>osd.{victim}")
        time.sleep(hold_s - 3.0)
        epoch_mid = int(c.status()["epoch"])
        time.sleep(3.0)  # the dampened tail: churn must have stopped
        epoch_end = int(c.status()["epoch"])
        health = c.health()
        dump = c.mon.pc.dump()
        out["flapping_raised"] = "OSD_FLAPPING" in health.get(
            "check_codes", [])
        out["dampened"] = int(dump.get("markdowns_dampened", 0))
        out["markdowns"] = int(dump.get("markdowns", 0))
        out["epoch_churn"] = epoch_end - epoch0
        out["epoch_churn_dampened_tail"] = epoch_end - epoch_mid
        c.set_faults("")
        # rejoin waits for the oldest markdown to age out of the
        # window (the delayed re-boot role), then boot restores the
        # auto-outed weight
        c.wait_for_up(victim, timeout=30)
        w.stop.set()
        w.join(timeout=20)
        bad = _verify(c, [w])
        out["checked"] = len(w.acked)
        out["lost"] = len(bad)
        c.wait_for_health_ok(timeout=60)
        out["flapping_cleared"] = "OSD_FLAPPING" not in c.health().get(
            "check_codes", [])
        out["ok"] = bool(out["flapping_raised"]
                         and out["dampened"] >= 1
                         and out["epoch_churn_dampened_tail"] <= 2
                         and out["lost"] == 0
                         and out["flapping_cleared"])
    finally:
        c.shutdown()
        faults.reset()
    return out


def slow_ops_drill(seed: int = 8, n_osds: int = 3) -> Dict:
    """The SLO-escalation drill (``--slow-ops``): ONE throttled OSD
    under cluster write load.  Every op on the victim sleeps past
    ``osd_op_complaint_time`` and every frame it sends drags against
    the ``msgr.delay_frame`` failpoint, so the drill must see the
    whole saturation plane fire: SLOW_OPS naming the victim and
    OSD_SLOW_PING_TIME raised by the monitor, the send stall booked
    on the victim's messenger (``dump_messenger`` over the admin
    socket) and NOT on a healthy peer's — then, once the throttle
    lifts, in-flight ops drain, the RTT windows decay, and health
    returns to HEALTH_OK with zero acked-write loss."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    conf = _conf()
    conf.set("osd_op_complaint_time", 0.2)
    conf.set("osd_heartbeat_ping_threshold_ms", 20.0)
    c = MiniCluster(n_osds=n_osds, config=conf).start()
    out: Dict = {"kind": "slowops", "seed": seed,
                 "n_osds": n_osds}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.wait_for_health_ok()
        w = _Writer(c, 0, 1, ec=False)
        w.start()
        victim = rng.randrange(n_osds)
        out["victim"] = victim
        t0 = time.monotonic()
        c.set_faults(
            f"osd.slow_op=p:1.0,delay:0.5,who:osd.{victim};"
            f"msgr.delay_frame=p:1.0,delay:0.04,who:osd.{victim}")
        codes: set = set()
        h: Dict = {}
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            h = c.health()
            codes = set(h.get("check_codes", []))
            if {"SLOW_OPS", "OSD_SLOW_PING_TIME"} <= codes:
                break
            time.sleep(0.25)
        out["raise_s"] = round(time.monotonic() - t0, 2)
        out["slow_ops_raised"] = "SLOW_OPS" in codes
        out["ping_time_raised"] = "OSD_SLOW_PING_TIME" in codes
        checks = {ck.split(":", 1)[0]: ck
                  for ck in h.get("checks", [])}
        out["named_victim"] = \
            f"osd.{victim}" in checks.get("SLOW_OPS", "")
        # admin-socket proof the telemetry attributes the stall to
        # the right daemon, not just that health went red
        dm_v = AdminSocket.request(
            os.path.join(c.asok_dir, f"osd.{victim}.asok"),
            "dump_messenger")
        dm_h = AdminSocket.request(
            os.path.join(c.asok_dir,
                         f"osd.{(victim + 1) % n_osds}.asok"),
            "dump_messenger")
        out["victim_stall_s"] = dm_v["totals"]["send_stall_s"]
        out["healthy_stall_s"] = dm_h["totals"]["send_stall_s"]
        c.set_faults("")
        w.stop.set()
        w.join(timeout=20)
        bad = _verify(c, [w])
        out["checked"] = len(w.acked)
        out["lost"] = len(bad)
        t1 = time.monotonic()
        cleared = False
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            h = c.health()
            if h.get("status") == "HEALTH_OK":
                cleared = True
                break
            time.sleep(0.5)
        out["cleared"] = cleared
        out["clear_s"] = round(time.monotonic() - t1, 2)
        out["ok"] = bool(out["slow_ops_raised"]
                         and out["ping_time_raised"]
                         and out["named_victim"]
                         and out["victim_stall_s"]
                         > 2 * out["healthy_stall_s"]
                         and out["lost"] == 0
                         and out["cleared"])
    finally:
        c.shutdown()
        faults.reset()
    return out


def netsplit(seed: int = 8) -> Dict:
    """The full NETSPLIT record: mon-link cut (no false markdowns),
    full isolation (fast true-positive detection, zero acked loss),
    flapping link (dampening + OSD_FLAPPING + bounded churn)."""
    rec: Dict = {"kind": "netsplit", "seed": seed}
    a = _mon_partition_phase(seed)
    b = _isolation_phase(seed)
    fl = _flap_phase(seed)
    rec["mon_partition"] = a
    rec["isolation"] = b
    rec["flap"] = fl
    # the trajectory columns perf_history red-checks
    rec["false_markdowns"] = a.get("false_markdowns")
    rec["detect_s"] = b.get("detect_s")
    rec["epoch_churn"] = fl.get("epoch_churn")
    rec["lost"] = (a.get("lost", 1) + b.get("lost", 1)
                   + fl.get("lost", 1))
    rec["checked"] = (a.get("checked", 0) + b.get("checked", 0)
                      + fl.get("checked", 0))
    rec["ok"] = bool(a.get("ok") and b.get("ok") and fl.get("ok"))
    return rec


def write_bench(seed: int = 8, duration: float = 4.0,
                n_osds: int = 3) -> Dict:
    """The checker-overhead probe body (hidden ``--write-bench``): a
    steady replicated write lane, no chaos — ops/s under whatever
    ``CEPH_TPU_RACECHECK`` setting this process was started with.
    race_audit() runs it twice in subprocesses (checker armed vs not)
    and gates the delta, so the comparison is decoration-time real on
    both sides."""
    c = MiniCluster(n_osds=n_osds, hosts=n_osds,
                    config=_conf()).start()
    out: Dict = {"kind": "write_bench", "seed": seed,
                 "racecheck": racecheck.enabled()}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.wait_for_health_ok()
        cli = c.client("rc-bench")
        val = b"x" * 4096
        try:
            t0 = time.monotonic()
            ops = 0
            while time.monotonic() - t0 < duration:
                cli.put(1, f"k{ops % 64}", val)
                ops += 1
            dt = time.monotonic() - t0
        finally:
            cli.shutdown()
        out["ops"] = ops
        out["ops_per_s"] = round(ops / dt, 1)
    finally:
        c.shutdown()
    return out


def _bench_overhead(seed: int, runs: int = 3,
                    env_var: str = "CEPH_TPU_RACECHECK") -> Dict:
    """Best-of-N write-bench ops/s with the checker named by
    ``env_var`` armed vs disarmed, each in its own subprocess (the
    guard/contract declarations are decoration-time, so an in-process
    toggle would measure nothing).  Shared by --race-audit
    (CEPH_TPU_RACECHECK) and --loop-stall (CEPH_TPU_ASYNCHECK)."""
    import subprocess

    def probe(armed: bool) -> float:
        env = dict(os.environ)
        env[env_var] = "1" if armed else "0"
        env.setdefault("CEPH_TPU_LOCKDEP", "1")
        env.setdefault("JAX_PLATFORMS", "cpu")
        best = 0.0
        for _ in range(runs):
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--write-bench", "--seed", str(seed)],
                capture_output=True, text=True, env=env,
                timeout=300)
            if p.returncode != 0:
                raise RuntimeError(
                    f"write-bench subprocess failed: {p.stderr[-500:]}")
            rec = json.loads(p.stdout.strip().splitlines()[-1])
            best = max(best, float(rec["ops_per_s"]))
        return best

    on = probe(True)
    off = probe(False)
    return {"ops_per_s_checked": on, "ops_per_s_raw": off,
            "overhead_pct": round(max(0.0, (1 - on / off) * 100), 2)
            if off else None}


def loop_stall_drill(seed: int = 8, n_osds: int = 3) -> Dict:
    """The async-safety drill (``--loop-stall``): arm
    ``msgr.stall_dispatch`` over one OSD so every control-lane
    dispatch callback on the victim sleeps 0.25s INSIDE its
    ``@nonblocking`` scope (5x the 50ms budget).  The runtime
    enforcer must catch the stall in flight and name the victim
    callback (a ``handler:osd.N:<type>`` scope) with both-end stacks
    — the contract entry stack and the mid-stall witness — while the
    static pass stays clean (the delay is a fault hook, invisible to
    the call graph on purpose: this is exactly the dynamic blocking
    the runtime twin exists for).  Disarm must heal to HEALTH_OK
    with zero acked-write loss, and enforcement overhead on a clean
    write lane must stay under 5%."""
    if not asyncheck.enabled():
        raise RuntimeError(
            "loop_stall needs CEPH_TPU_ASYNCHECK=1 before ceph_tpu "
            "imports (run via --loop-stall)")
    import pathlib

    from tools import lint_async

    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    conf = _conf()
    conf.set("asyncheck_loop_budget_ms", 50.0)
    c = MiniCluster(n_osds=n_osds, config=conf).start()
    out: Dict = {"kind": "async", "seed": seed, "n_osds": n_osds,
                 "budget_ms": 50.0}
    # the static half of the gate: zero unsuppressed BLOCK001
    # reachability violations project-wide
    out["static_violations"] = len(lint_async.lint_paths(
        [pathlib.Path(_ROOT) / "ceph_tpu"]))
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.wait_for_health_ok()
        base = asyncheck.mark()
        w = _Writer(c, 0, 1, ec=False)
        w.start()
        victim = rng.randrange(n_osds)
        out["victim"] = victim
        want = f"handler:osd.{victim}:"
        t0 = time.monotonic()
        c.set_faults(
            f"msgr.stall_dispatch=p:1.0,delay:0.25,"
            f"who:osd.{victim}")
        named: Optional[str] = None
        stalled = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            for r in asyncheck.violations()[base:]:
                if str(r["scope"]).startswith(want):
                    named = named or str(r["scope"])
                    if r["kind"] == "stall":
                        stalled = True
            if named and stalled:
                break
            time.sleep(0.1)
        out["raise_s"] = round(time.monotonic() - t0, 2)
        recs = [r for r in asyncheck.violations()[base:]
                if str(r["scope"]).startswith(want)]
        out["victim_scope"] = named
        out["victim_named"] = named is not None
        out["stall_witnessed"] = stalled
        out["overruns"] = len(recs)
        out["both_stacks"] = bool(recs) and all(
            r["entry_stack"] and r["witness_stack"]
            for r in recs[:10])
        # the admin surface serves the same evidence per daemon
        d = AdminSocket.request(
            os.path.join(c.asok_dir, f"osd.{victim}.asok"),
            "dump_asyncheck")
        out["dump_contracts"] = len(d.get("contracts", []))
        c.set_faults("")
        w.stop.set()
        w.join(timeout=20)
        bad = _verify(c, [w])
        out["checked"] = len(w.acked)
        out["lost"] = len(bad)
        t1 = time.monotonic()
        cleared = False
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            if c.health().get("status") == "HEALTH_OK":
                cleared = True
                break
            time.sleep(0.5)
        out["cleared"] = cleared
        out["clear_s"] = round(time.monotonic() - t1, 2)
    finally:
        c.shutdown()
        faults.reset()
    out.update(_bench_overhead(seed, env_var="CEPH_TPU_ASYNCHECK"))
    out["ok"] = bool(
        out["static_violations"] == 0 and out["victim_named"]
        and out["stall_witnessed"] and out["overruns"] > 0
        and out["both_stacks"] and out["lost"] == 0
        and out["cleared"] and out["overhead_pct"] is not None
        and out["overhead_pct"] < 5.0)
    return out


def race_audit(seed: int = 8, soak_duration: float = 8.0) -> Dict:
    """``--race-audit``: the full drill battery — chaos soak,
    directional netsplits, SLO-escalation — with the data-race
    checker armed over every swept daemon, then the checker-overhead
    probe on the clean write lane.  The gate (red-checked via
    RACE_rNN.json): ZERO racecheck violations, zero acked-write loss
    anywhere, and checker overhead under 10%."""
    if not (racecheck.enabled() and lockdep.enabled()):
        raise RuntimeError(
            "race_audit needs CEPH_TPU_RACECHECK=1 and lockdep "
            "armed before ceph_tpu imports (run via --race-audit)")
    base = racecheck.mark()
    out: Dict = {"kind": "race", "seed": seed,
                 "racecheck_enabled": True}
    phases: Dict[str, Dict] = {}
    vmark = base
    for name, run in (
            ("chaos", lambda: soak(seed=seed,
                                   duration=soak_duration)),
            ("netsplit", lambda: netsplit(seed=seed)),
            ("slow_ops", lambda: slow_ops_drill(seed=seed))):
        rec = run()
        now = len(racecheck.violations())
        phases[name] = {"ok": bool(rec.get("ok")),
                        "lost": rec.get("lost", 0),
                        "checked": rec.get("checked", 0),
                        "violations": now - vmark}
        vmark = now
    out["phases"] = phases
    new = racecheck.violations()[base:]
    out["violations"] = len(new)
    out["violation_reports"] = [v["message"] for v in new[:5]]
    out["lost"] = sum(p["lost"] or 0 for p in phases.values())
    out["checked"] = sum(p["checked"] or 0 for p in phases.values())
    d = racecheck.dump()
    out["guarded_classes"] = len(d["guarded_classes"])
    out["guarded_fields"] = d["guarded_fields"]
    out["shared_objects"] = d["shared_objects"]
    out.update(_bench_overhead(seed))
    out["ok"] = bool(
        out["violations"] == 0 and out["lost"] == 0
        and all(p["ok"] for p in phases.values())
        and out["overhead_pct"] is not None
        and out["overhead_pct"] < 10.0)
    return out


def next_run_number(directory: str) -> int:
    """One past the newest committed record of ANY series (BENCH /
    MULTICHIP / CHAOS / DRILL) so the record pairs with its PR's
    run."""
    n = 0
    for path in glob.glob(os.path.join(directory, "*_r*.json")):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            n = max(n, int(m.group(1)))
    return n or 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="thrasher")
    ap.add_argument("--seed", type=int, default=8,
                    help="drives the thrash schedule AND the fault "
                         "plane's probability draws (default 8)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="seconds of active chaos (default 20)")
    ap.add_argument("--osds", type=int, default=5)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="fault_inject_spec to arm during the soak")
    ap.add_argument("--host-kill", action="store_true",
                    help="run the whole-host failure drill + "
                         "degraded-read soak instead of the chaos "
                         "soak (emits DRILL_rNN.json)")
    ap.add_argument("--netsplit", action="store_true",
                    help="run the directional network-partition "
                         "drills (mon-link cut, full isolation, "
                         "flapping link) instead of the chaos soak "
                         "(emits NETSPLIT_rNN.json)")
    ap.add_argument("--slow-ops", action="store_true",
                    help="run the SLO-escalation drill (one "
                         "throttled OSD must raise SLOW_OPS + "
                         "OSD_SLOW_PING_TIME and clear to "
                         "HEALTH_OK) instead of the chaos soak "
                         "(emits SLODRILL_rNN.json)")
    ap.add_argument("--race-audit", action="store_true",
                    help="run the chaos soak + netsplit + slow-ops "
                         "drills with the data-race checker armed, "
                         "then the checker-overhead probe; the gate "
                         "is zero violations, zero acked-write loss "
                         "and <10%% overhead (emits RACE_rNN.json)")
    ap.add_argument("--loop-stall", action="store_true",
                    help="run the async-safety drill: delay one "
                         "OSD's control-lane dispatch callbacks "
                         "inside their @nonblocking scopes; the "
                         "runtime enforcer must name the victim "
                         "callback with both-end stacks, then heal "
                         "to HEALTH_OK; gates static cleanliness "
                         "and <5%% enforcement overhead (emits "
                         "ASYNC_rNN.json)")
    ap.add_argument("--write-bench", action="store_true",
                    help=argparse.SUPPRESS)  # overhead-probe subprocess
    ap.add_argument("--slo-p99-ms", type=float, default=250.0,
                    help="degraded-read soak p99 SLO in ms "
                         "(default 250)")
    ap.add_argument("--out", default=None,
                    help="output record path (default "
                         "CHAOS_rNN.json / DRILL_rNN.json, NN from "
                         "the newest committed record)")
    args = ap.parse_args(argv)

    if args.write_bench:
        # hidden overhead-probe worker: bare JSON on stdout for the
        # parent race_audit(); no committed record
        print(json.dumps(write_bench(seed=args.seed)))
        return 0

    series = "DRILL" if args.host_kill else \
        "NETSPLIT" if args.netsplit else \
        "SLODRILL" if args.slow_ops else \
        "RACE" if args.race_audit else \
        "ASYNC" if args.loop_stall else "CHAOS"
    out = args.out
    if out is None:
        n = next_run_number(_ROOT)
        out = os.path.join(_ROOT, f"{series}_r{n:02d}.json")
    m = re.search(r"_r(\d+)\.json$", out)
    if args.race_audit:
        rec = race_audit(seed=args.seed)
    elif args.loop_stall:
        rec = loop_stall_drill(seed=args.seed)
    elif args.host_kill:
        rec = drill(seed=args.seed, slo_p99_ms=args.slo_p99_ms)
    elif args.netsplit:
        rec = netsplit(seed=args.seed)
    elif args.slow_ops:
        rec = slow_ops_drill(seed=args.seed)
    else:
        rec = soak(seed=args.seed, duration=args.duration,
                   n_osds=args.osds, n_mons=args.mons,
                   spec=args.spec)
    rec["n"] = int(m.group(1)) if m else 0
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    if args.race_audit:
        print(f"# race seed={rec['seed']} "
              f"violations={rec.get('violations')} "
              f"lost={rec.get('lost')}/{rec.get('checked')} "
              f"guarded={rec.get('guarded_classes')}cls/"
              f"{rec.get('guarded_fields')}flds "
              f"overhead={rec.get('overhead_pct')}% "
              f"({rec.get('ops_per_s_checked')} vs "
              f"{rec.get('ops_per_s_raw')} op/s) -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    elif args.loop_stall:
        print(f"# async seed={rec['seed']} victim=osd."
              f"{rec.get('victim')} scope={rec.get('victim_scope')} "
              f"overruns={rec.get('overruns')} "
              f"static={rec.get('static_violations')} "
              f"raise={rec.get('raise_s')}s "
              f"clear={rec.get('clear_s')}s "
              f"lost={rec.get('lost')}/{rec.get('checked')} "
              f"overhead={rec.get('overhead_pct')}% -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    elif args.slow_ops:
        print(f"# slowops seed={rec['seed']} victim=osd."
              f"{rec.get('victim')} raise={rec.get('raise_s')}s "
              f"stall={rec.get('victim_stall_s')}s "
              f"(healthy {rec.get('healthy_stall_s')}s) "
              f"clear={rec.get('clear_s')}s "
              f"lost={rec.get('lost')}/{rec.get('checked')} -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    elif args.netsplit:
        print(f"# netsplit seed={rec['seed']} "
              f"false_markdowns={rec.get('false_markdowns')} "
              f"detect={rec.get('detect_s')}s "
              f"(bound {rec['isolation'].get('detect_bound_s')}s) "
              f"churn={rec.get('epoch_churn')} "
              f"lost={rec.get('lost')}/{rec.get('checked')} -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    elif args.host_kill:
        soak_rec = rec.get("soak", {})
        print(f"# drill seed={rec['seed']} "
              f"mbps={rec.get('recovery_mbps')} "
              f"(serial {rec.get('recovery_mbps_serial')}, "
              f"speedup {rec.get('pipeline_speedup')}x) "
              f"lost={rec.get('lost')}/{rec.get('checked')} "
              f"converge={rec.get('converge_s')}s "
              f"soak_p99={soak_rec.get('p99_ms')}ms -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    else:
        print(f"# chaos seed={rec['seed']} ops={rec.get('ops')} "
              f"lost={rec.get('lost')} "
              f"converge={rec.get('health_converge_s')}s "
              f"fired={rec.get('fired')} -> "
              f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
