#!/usr/bin/env python
"""thrasher — seeded chaos soak over the MiniCluster.

The qa/tasks/thrashosds.py role as a standalone tool: concurrent
writers against a replicated and an EC pool while OSDs (and quorum
monitors) are killed/revived under them AND the fault-injection plane
(ceph_tpu/analysis/faults.py) is armed — dropped/corrupted frames on
the wire, an injected shard-read EIO, a slowed OSD.  The invariants
checked are the storage system's whole promise:

  * every ACKED write is readable afterwards, at its acked value;
  * the cluster converges back to HEALTH_OK once the chaos stops;
  * the analysis planes stay clean (no lockdep violations, no leaked
    tracing spans);
  * every armed failpoint actually fired (a soak that injected
    nothing proved nothing).

Determinism: ONE seed drives both the thrash schedule (victim choice,
action pacing) and the fault plane's probability draws
(``faults.seed``), so a failing run reproduces from its recorded
seed::

    python tools/thrasher.py --seed 8 --duration 20
    python tools/thrasher.py --seed 8 --duration 20   # same schedule

Each run emits a ``CHAOS_rNN.json`` record beside the BENCH_r*.json
series; tools/perf_history.py ingests them into the same trajectory
table (``chaos_ops`` / ``chaos_converge_s`` columns) and flags a run
with lost writes or failed convergence as a regression.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import random
import re
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from ceph_tpu.analysis import faults, lockdep  # noqa: E402
from ceph_tpu.common import tracing  # noqa: E402
from ceph_tpu.common.backoff import Backoff  # noqa: E402
from ceph_tpu.common.config import Config  # noqa: E402
from ceph_tpu.services.client import ObjectNotFound  # noqa: E402
from ceph_tpu.services.cluster import MiniCluster  # noqa: E402

# the acceptance fault mix: wire chaos probabilistic (recoverable by
# design — reconnect+replay), the destructive arms COUNTED so a soak
# can't cascade shard removals past the EC profile's m (that would
# manufacture data loss no real cluster promised to survive)
DEFAULT_SPEC = ("msgr.drop_frame=p:0.02;"
                "msgr.corrupt_frame=p:0.02;"
                "msgr.dup_frame=p:0.02;"
                "osd.slow_op=p:0.05,delay:0.03;"
                "osd.shard_read_eio=count:1")


def _conf() -> Config:
    c = Config()
    c.set("osd_heartbeat_interval", 0.2)
    c.set("osd_heartbeat_grace", 1.2)
    c.set("mon_osd_down_out_interval", 1.5)
    c.set("mon_lease", 0.3)
    c.set("mon_election_timeout", 0.5)
    # the balancer rides the soak with a tight loop and deviation
    # target so its pause gate is exercised while OSDs flap
    c.set("balancer_interval", 1.0)
    c.set("balancer_max_deviation", 1)
    return c


class _Writer(threading.Thread):
    """Loops put/delete (and EC partial overwrites) over its own key
    space, recording the last ACKED value per key; keys touched by an
    UNACKED attempt are 'dirty' — the op may still have landed
    durably (a legal outcome), so only readability is asserted."""

    def __init__(self, cluster: MiniCluster, wid: int, pool_id: int,
                 ec: bool):
        super().__init__(daemon=True, name=f"chaos-w{wid}")
        self.cluster = cluster
        self.wid = wid
        self.pool = pool_id
        self.ec = ec
        self.cli = cluster.client(f"chaos-w{wid}-{pool_id}")
        self.acked: Dict[str, Optional[bytes]] = {}
        self.dirty: set = set()
        self.ops = 0
        self.stop = threading.Event()

    def run(self) -> None:
        i = 0
        while not self.stop.is_set():
            key = f"w{self.wid}-k{i % 7}"
            val = f"{self.wid}:{i}:".encode() * 40
            op = None
            try:
                if i % 11 == 10:
                    op = "delete"
                    self.cli.delete(self.pool, key)
                    self.acked[key] = None
                    self.dirty.discard(key)
                else:
                    op = "put"
                    self.cli.put(self.pool, key, val)
                    self.acked[key] = val
                    self.dirty.discard(key)
                self.ops += 1
            except Exception:
                if op is not None:
                    self.dirty.add(key)
            i += 1
        self.cli.shutdown()


def _verify(cluster: MiniCluster,
            writers: List[_Writer]) -> List[tuple]:
    """Read back every acked key; returns the violations."""
    checker = cluster.client("chaos-check")
    bad: List[tuple] = []
    try:
        for w in writers:
            for key, want in w.acked.items():
                fuzzy = key in w.dirty
                bo = Backoff(base=0.2, cap=1.0, deadline=20.0)
                while True:
                    try:
                        try:
                            got = checker.get(w.pool, key,
                                              notfound_retries=0)
                        except ObjectNotFound:
                            got = None
                        if fuzzy:
                            break  # readable (or legally absent)
                        if got == want:
                            break
                        if not bo.sleep():
                            bad.append((w.pool, key, "mismatch"))
                            break
                    except Exception as e:  # fault-ok: Backoff-paced
                        if not bo.sleep():
                            bad.append((w.pool, key, repr(e)))
                            break
    finally:
        checker.shutdown()
    return bad


def soak(seed: int = 0, duration: float = 20.0, n_osds: int = 5,
         n_mons: int = 1, spec: str = DEFAULT_SPEC,
         settle_timeout: float = 60.0) -> Dict:
    """One seeded chaos soak; returns the CHAOS record dict."""
    rng = random.Random(seed)
    faults.reset()
    faults.seed(seed)
    base_lockdep = len(lockdep.violations())
    base_spans = {id(s) for _svc, s in tracing.active_spans()}

    # persistent stores: kill/revive is a daemon crash+restart over
    # the OSD's surviving disk (the thrashosds contract), NOT a disk
    # wipe.  Without this, every revive reformats the store, and two
    # kills inside one recovery window erase 2 of 3 shards — loss the
    # k=2/m=1 profile never promised to survive.
    data_root = tempfile.mkdtemp(prefix=f"chaos-s{seed}-")
    c = MiniCluster(n_osds=n_osds, hosts=n_osds, config=_conf(),
                    n_mons=n_mons, data_dir=data_root).start()
    result: Dict = {"kind": "chaos", "seed": seed,
                    "duration": duration, "n_osds": n_osds,
                    "n_mons": n_mons, "spec": spec}
    try:
        c.create_replicated_pool(1, pg_num=8, size=3)
        c.create_ec_pool(2, "chaos21", {"plugin": "jerasure",
                                        "technique": "reed_sol_van",
                                        "k": "2", "m": "1", "w": "8"},
                         pg_num=8)
        writers = [_Writer(c, 0, 1, ec=False),
                   _Writer(c, 1, 1, ec=False),
                   _Writer(c, 2, 2, ec=True)]
        for w in writers:
            w.start()
        # an ACTIVE balancer rides the whole soak: its pause gate
        # (no upmap proposals while the cluster is degraded) is a
        # robustness invariant this soak asserts below
        mgr = c.start_mgr()
        bal = mgr.modules["balancer"]
        bal.active = True
        c.set_faults(spec)

        end = time.monotonic() + duration
        while time.monotonic() < end:
            victim = rng.randrange(n_osds)
            c.kill_osd(victim)
            if n_mons > 1 and rng.random() < 0.3:
                rank = rng.randrange(1, n_mons)
                if rank in c.mons and len(c.mons) == n_mons:
                    c.kill_mon(rank)
                    time.sleep(0.5 + rng.random())
                    c.revive_mon(rank)
            time.sleep(0.8 + rng.random())
            c.revive_osd(victim)
            time.sleep(0.4 + rng.random() * 0.4)

        # chaos off; give in-flight faulted ops a beat to drain so
        # the writers' LAST acked values are post-fault reality
        c.set_faults("")
        time.sleep(1.0)
        for w in writers:
            w.stop.set()
        for w in writers:
            w.join(timeout=30)
        result["ops"] = sum(w.ops for w in writers)

        # settle: all osds up, then time the HEALTH_OK convergence
        for o in range(n_osds):
            if o not in c.osds:
                c.revive_osd(o)
        t0 = time.monotonic()
        try:
            c.wait_for_health_ok(timeout=settle_timeout)
            result["health_converge_s"] = round(
                time.monotonic() - t0, 3)
            converged = True
        except TimeoutError as e:
            result["health_converge_s"] = None
            result["health_error"] = str(e)
            converged = False
        time.sleep(2.0)  # a peering pass after the last epoch

        bad = _verify(c, writers)
        result["checked"] = sum(len(w.acked) for w in writers)
        result["lost"] = len(bad)
        result["bad"] = [list(b) for b in bad[:5]]
        result["fired"] = faults.snapshot()
        armed = [p.strip().split("=")[0]
                 for p in spec.split(";") if p.strip()]
        result["unfired_armed"] = sorted(
            n for n in armed if not result["fired"].get(n))
        result["balancer_rounds"] = bal.rounds
        result["balancer_pauses"] = int(
            mgr.pc.dump().get("balancer_paused", 0))
        result["balancer_proposals"] = sum(
            p["proposed"] for p in bal.proposal_log)
        result["balancer_degraded_proposals"] = sum(
            1 for p in bal.proposal_log if p["degraded"])
    finally:
        c.shutdown()
        faults.reset()
        shutil.rmtree(data_root, ignore_errors=True)

    result["lockdep_violations"] = \
        len(lockdep.violations()) - base_lockdep
    # daemon threads die with their sockets; give them a beat before
    # judging the span plane
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaks = [s for _svc, s in tracing.active_spans()
                 if id(s) not in base_spans]
        if not leaks:
            break
        time.sleep(0.1)
    result["span_leaks"] = len(
        [s for _svc, s in tracing.active_spans()
         if id(s) not in base_spans])
    result["ok"] = bool(
        result.get("lost") == 0 and converged
        and result["lockdep_violations"] == 0
        and result["span_leaks"] == 0
        and not result["unfired_armed"]
        and result.get("balancer_degraded_proposals", 0) == 0)
    return result


def next_run_number(directory: str) -> int:
    """One past the newest committed record of ANY series (BENCH /
    MULTICHIP / CHAOS) so the chaos record pairs with its PR's run."""
    n = 0
    for path in glob.glob(os.path.join(directory, "*_r*.json")):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            n = max(n, int(m.group(1)))
    return n or 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="thrasher")
    ap.add_argument("--seed", type=int, default=8,
                    help="drives the thrash schedule AND the fault "
                         "plane's probability draws (default 8)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="seconds of active chaos (default 20)")
    ap.add_argument("--osds", type=int, default=5)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--spec", default=DEFAULT_SPEC,
                    help="fault_inject_spec to arm during the soak")
    ap.add_argument("--out", default=None,
                    help="output record path (default "
                         "CHAOS_rNN.json, NN from the newest "
                         "committed record)")
    args = ap.parse_args(argv)

    out = args.out
    if out is None:
        n = next_run_number(_ROOT)
        out = os.path.join(_ROOT, f"CHAOS_r{n:02d}.json")
    m = re.search(r"_r(\d+)\.json$", out)
    rec = soak(seed=args.seed, duration=args.duration,
               n_osds=args.osds, n_mons=args.mons, spec=args.spec)
    rec["n"] = int(m.group(1)) if m else 0
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(f"# chaos seed={rec['seed']} ops={rec.get('ops')} "
          f"lost={rec.get('lost')} "
          f"converge={rec.get('health_converge_s')}s "
          f"fired={rec.get('fired')} -> "
          f"{'OK' if rec['ok'] else 'FAIL'} ({out})")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
