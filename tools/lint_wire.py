#!/usr/bin/env python
"""Wire-schema lint — static companion to analysis/wirecheck.

AST-level checks that keep every wire/disk path inside the versioned
envelope + conformance registry, enforced by tests/test_lint.py like
the CONC/JAX rules:

WIRE001  raw ``json.dumps``/``json.loads`` in a wire/disk module
         (msg/, os/, osdmap/, the services persistence files,
         crush/map.py).  Ad-hoc JSON has no struct_v, no compat
         floor, no corpus pin — the drift class this layer exists to
         close.  The envelope seam itself (common/encoding.py,
         common/bincode.py) is exempt; deliberate codec seams carry
         ``# wire-ok: <reason>``.

WIRE002  a class in msg/ / os/ / osdmap/ defining BOTH to_dict and
         from_dict (a wire-shaped type) that no wirecheck registry
         entry covers: its encoding can drift silently because
         nothing round-trips, corpus-pins, or mutation-tests it.

WIRE003  a frame-type literal (``__xxx__``) compared in msg/ without
         a registry entry owning it: a typed frame family handled on
         the wire but absent from the conformance surface.

WIRE004  a broad handler (bare ``except:`` / ``except Exception``)
         whose body is only pass/continue wrapped around a decode
         call: it swallows MalformedInput, turning tampered bytes
         into silent data loss instead of a surfaced protocol error.
         (Narrow catches that log, break, or re-raise are fine.)

Suppression: append ``# wire-ok: <reason>`` to the offending line (or
the introducing ``class``/``try`` line).  tests/test_lint.py carries
the committed allowlist for known-acceptable hits in ``ceph_tpu/``.

Usage:
    python tools/lint_wire.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional, Set

SUPPRESS_MARK = "wire-ok:"

# module scope per rule, matched against the path relative to the
# package root (endswith for files, substring for dirs)
WIRE_DIRS = ("msg/", "os/", "osdmap/")
WIRE_FILES = ("services/monitor.py", "services/image.py",
              "services/osd_service.py", "services/pg_log.py",
              "crush/map.py")
SEAM_FILES = ("common/encoding.py", "common/bincode.py")

_DECODEISH = ("decode", "loads", "from_dict", "unpack", "from_json",
              "from_wire")


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressed(src_lines: List[str], *linenos: int) -> bool:
    for ln in linenos:
        if 1 <= ln <= len(src_lines) and \
                SUPPRESS_MARK in src_lines[ln - 1]:
            return True
    return False


def _registry_sets():
    """(covered class names, frame-type literals) from the live
    wirecheck registry; empty sets when the package is unimportable
    (linting a foreign tree)."""
    try:
        from ceph_tpu.analysis import wirecheck

        return wirecheck.covered_classes(), wirecheck.frame_type_names()
    except Exception:
        return set(), set()


def _in_scope(rel: str) -> bool:
    if any(rel.endswith(f) for f in SEAM_FILES):
        return False
    return any(d in rel for d in WIRE_DIRS) or \
        any(rel.endswith(f) for f in WIRE_FILES)


def _in_dir_scope(rel: str) -> bool:
    return any(d in rel for d in WIRE_DIRS)


def _is_msg(rel: str) -> bool:
    return "msg/" in rel


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str,
                 covered: Set[str], frames: Set[str]):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.out: List[Violation] = []
        self.covered = covered
        self.frames = frames
        self.scope = _in_scope(rel)
        self.dir_scope = _in_dir_scope(rel)
        self.msg_scope = _is_msg(rel)
        # names bound to the json module in this file
        self.json_names: Set[str] = set()

    def _emit(self, code: str, node: ast.AST, message: str,
              *extra_lines: int) -> None:
        if _suppressed(self.lines, node.lineno, *extra_lines):
            return
        self.out.append(Violation(self.rel, node.lineno, code,
                                  message))

    # -- import tracking ----------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "json":
                self.json_names.add(alias.asname or "json")
        self.generic_visit(node)

    # -- WIRE001 -------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if self.scope and isinstance(f, ast.Attribute) and \
                f.attr in ("dumps", "loads") and \
                isinstance(f.value, ast.Name) and \
                f.value.id in (self.json_names or {"json"}):
            self._emit(
                "WIRE001", node,
                f"raw json.{f.attr} on a wire/disk path: no "
                f"struct_v, no compat floor, no corpus pin — go "
                f"through common.encoding (or mark the codec seam "
                f"with # wire-ok:)")
        self.generic_visit(node)

    # -- WIRE002 -------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self.dir_scope:
            meths = {n.name for n in node.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))}
            if {"to_dict", "from_dict"} <= meths and \
                    node.name not in self.covered:
                self._emit(
                    "WIRE002", node,
                    f"wire-shaped class {node.name!r} "
                    f"(to_dict/from_dict) has no wirecheck registry "
                    f"entry: nothing round-trips, corpus-pins, or "
                    f"mutation-tests its encoding")
        self.generic_visit(node)

    # -- WIRE003 -------------------------------------------------------
    def visit_Compare(self, node: ast.Compare) -> None:
        if self.msg_scope:
            for side in [node.left] + list(node.comparators):
                lits = []
                if isinstance(side, ast.Constant) and \
                        isinstance(side.value, str):
                    lits = [side.value]
                elif isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                    lits = [e.value for e in side.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)]
                for lit in lits:
                    if lit.startswith("__") and lit.endswith("__") \
                            and lit not in self.frames:
                        self._emit(
                            "WIRE003", node,
                            f"frame-type literal {lit!r} handled "
                            f"without a wirecheck registry entry: "
                            f"the frame family is on the wire but "
                            f"off the conformance surface")
        self.generic_visit(node)

    # -- WIRE004 -------------------------------------------------------
    def visit_Try(self, node: ast.Try) -> None:
        if self.scope and self._try_decodes(node):
            for h in node.handlers:
                if not self._broad(h.type):
                    continue
                if all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in h.body):
                    self._emit(
                        "WIRE004", h,
                        "broad except swallowing MalformedInput "
                        "around a decode: tampered bytes become "
                        "silent data loss — narrow the catch or "
                        "surface the error", node.lineno)
        self.generic_visit(node)

    @staticmethod
    def _broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare except
        names = []
        if isinstance(t, ast.Name):
            names = [t.id]
        elif isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        return any(n in ("Exception", "BaseException") for n in names)

    @staticmethod
    def _try_decodes(node: ast.Try) -> bool:
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else "")
                if any(d in name for d in _DECODEISH):
                    return True
        return False


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None,
              covered: Optional[Set[str]] = None,
              frames: Optional[Set[str]] = None) -> List[Violation]:
    rel = str(path if root is None else path.relative_to(root))
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "WIRE000",
                          f"unparseable: {e.msg}")]
    if covered is None or frames is None:
        rc, rf = _registry_sets()
        covered = rc if covered is None else covered
        frames = rf if frames is None else frames
    linter = _FileLinter(str(path), rel, src, covered, frames)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: v.line)


def lint_paths(paths: Iterable[pathlib.Path],
               covered: Optional[Set[str]] = None,
               frames: Optional[Set[str]] = None) -> List[Violation]:
    if covered is None or frames is None:
        rc, rf = _registry_sets()
        covered = rc if covered is None else covered
        frames = rf if frames is None else frames
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                out.extend(lint_file(f, root=root, covered=covered,
                                     frames=frames))
        else:
            out.extend(lint_file(p, covered=covered, frames=frames))
    return out


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} wire-schema lint violation(s)")
        return 1
    print("wire-schema lint clean")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    raise SystemExit(main(sys.argv[1:]))
