#!/usr/bin/env python
"""Observability lint — static companion to the counter registry.

The rules, enforced by tests/test_lint.py like the CONC/JAX/WIRE
families:

OBS001  a perf-counter declaration (``add_u64_counter``/``add_u64``/
        ``add_time``/``add_u64_avg``/``add_histogram``) or update
        (``inc``/``dec``/``set``/``tinc``/``avg_add``/``hist_add``)
        on a counter object (receiver named ``pc``/``_pc``, any
        attribute depth: ``self.pc``, ``mod._pc``) whose counter NAME
        is not declared in the central registry
        (``ceph_tpu/common/counters.py``).  Undeclared counters are
        exactly how daemonperf/telemetry column schemas silently
        drift from what daemons actually book — the column reads 0
        forever and nobody notices.

OBS002  the continuous-profiling plane must stay in sync with the
        registry, and the sampler must be provably off by default:

        (a) every attribution stage name
            (``ceph_tpu.common.attribution.STAGES``) must have an
            ``obs.latency`` histogram in the registry, and every
            copy-ledger site (``ceph_tpu.common.copytrack.SITES``)
            must have both its ``<site>_bytes`` and ``<site>_copies``
            counters under ``obs.copy`` — a stage/site added without
            its registry row would fold into telemetry columns that
            read 0 forever (the exact drift OBS001 exists to stop);

        (b) a ``profile_start(...)`` call outside ``tests/`` and the
            bench drivers (``bench.py``/``rados_bench.py``) must sit
            lexically inside an ``if`` — the wallclock sampler is an
            operator-triggered admin-socket verb, and an
            unconditional start in daemon code would silently tax
            every op in production.  Gate it (as Context's admin hook
            does behind ``if sub == "start":``) or add
            ``# obs-ok: <reason>``.

OBS003  every counter name in the registry must round-trip through
        the prometheus exporter: a synthetic snapshot carrying one
        daemon with EVERY registered counter (dumped in its type's
        wire shape — plain number for u64/gauge/time, ``{avgcount,
        sum}`` for avg, ``{buckets, min}`` for hist) is fed to
        ``telemetry.to_prometheus`` and every name's sanitized metric
        family (``ceph_tpu_<name>``; for histograms the ``_bucket``/
        ``_count`` series under it) must come back with a ``# HELP``
        header.  A registered-but-unexported counter is the scrape-
        side twin of OBS001's drift: the daemon books it, daemonperf
        can read it, and the prometheus surface silently never shows
        it.  Also fails on a sanitization COLLISION that merges two
        registered names of different types into one family — the
        exporter would emit conflicting ``# TYPE`` claims.

COPY001 a ``bytes(...)`` (single-argument) or ``.tobytes()`` call in a
        hot-path data-plane module (``msg/``, ``os/``,
        ``ec/engine.py``, ``ec/batcher.py``) without a
        ``# copy-ok: <reason>`` suppression naming why the copy is
        deliberate.  The zero-copy buffer plane (ROADMAP item 2)
        threads memoryviews from recv_into through the frame codec,
        the store staging, and the EC encode input; every remaining
        materialisation on those paths must be a DECISION — booked in
        the ``obs.copy`` ledger and justified in place — or it is
        exactly the silent re-copy the plane exists to delete.  The
        reason is mandatory; the mark may sit on the call line or an
        immediately preceding comment line.

Name resolution, in order:
- a literal string: checked directly against the registry;
- a Name bound by an enclosing ``for <name> in (<literals>,)`` loop
  (the declaration-block idiom): every literal element is checked;
- an f-string with literal fragments (``f"{kind}_ops"``): its
  constant parts become a pattern — at least one registered name
  must match, so a family rename that orphans the pattern still
  fails;
- anything else needs an explicit ``# obs-ok: <reason>``.

Suppression: append ``# obs-ok: <reason>`` to the offending line.
The reason is mandatory — it is the allowlist entry.

Usage:
    python tools/lint_obs.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ceph_tpu.common.counters import all_names, declared  # noqa: E402

SUPPRESS_MARK = "obs-ok:"
COPY_MARK = "copy-ok:"

# hot-path data-plane scope for COPY001: the messenger, the stores,
# and the EC dispatch seam (the engine/batcher pair the views feed)
_COPY_HOT_SUFFIXES = ("ec/engine.py", "ec/batcher.py")


def copy_hot_path(path) -> bool:
    """True when ``path`` is in COPY001's hot-path scope."""
    p = pathlib.Path(path)
    if "tests" in p.parts:
        return False
    return "msg" in p.parts or "os" in p.parts or \
        p.as_posix().endswith(_COPY_HOT_SUFFIXES)

# paths allowed to call profile_start unconditionally: tests drive the
# sampler directly, and the bench lanes switch it on around a measured
# burst — both are deliberate, bounded, and never ship in a daemon
PROFILE_EXEMPT_NAMES = {"bench.py", "rados_bench.py"}

RECEIVERS = {"pc", "_pc"}
DECLARE_METHODS = {"add_u64_counter", "add_u64", "add_time",
                   "add_u64_avg", "add_histogram"}
UPDATE_METHODS = {"inc", "dec", "set", "tinc", "avg_add", "hist_add"}


@dataclass
class Violation:
    code: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressed(source_lines: List[str], lineno: int) -> bool:
    if 1 <= lineno <= len(source_lines):
        return SUPPRESS_MARK in source_lines[lineno - 1]
    return False


def _copy_suppressed(source_lines: List[str], lineno: int) -> bool:
    """``# copy-ok: <reason>`` on the call line or on the comment
    line(s) immediately above it; the reason text is mandatory."""

    def has_reason(line: str) -> bool:
        at = line.find(COPY_MARK)
        return at >= 0 and bool(line[at + len(COPY_MARK):].strip())

    if not (1 <= lineno <= len(source_lines)):
        return False
    if has_reason(source_lines[lineno - 1]):
        return True
    i = lineno - 2
    while i >= 0 and source_lines[i].strip().startswith("#"):
        if has_reason(source_lines[i]):
            return True
        i -= 1
    return False


def _receiver_name(func: ast.expr) -> Optional[str]:
    """`pc.inc` -> 'pc'; `self.pc.inc` -> 'pc'; `a.b._pc.inc` ->
    '_pc' (the attribute the method hangs off)."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Name):
        return base.id
    if isinstance(base, ast.Attribute):
        return base.attr
    return None


class _Checker(ast.NodeVisitor):
    def __init__(self, path: str, source: str,
                 profile_exempt: bool = False,
                 copy_hot: bool = False):
        self.path = path
        self.lines = source.splitlines()
        self.violations: List[Violation] = []
        self.registry = all_names()
        self.profile_exempt = profile_exempt
        self.copy_hot = copy_hot
        # Name -> literal candidates, from enclosing `for x in (...)`
        self._loop_bindings: dict = {}
        self._if_depth = 0

    def visit_If(self, node: ast.If) -> None:
        self._if_depth += 1
        self.generic_visit(node)
        self._if_depth -= 1

    # -- collect `for key in ("a", "b"):` bindings --------------------
    def visit_For(self, node: ast.For) -> None:
        bound = None
        if isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)) and \
                all(isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                    for e in node.iter.elts):
            bound = node.target.id
            prev = self._loop_bindings.get(bound)
            self._loop_bindings[bound] = [e.value
                                          for e in node.iter.elts]
        self.generic_visit(node)
        if bound is not None:
            if prev is None:
                self._loop_bindings.pop(bound, None)
            else:
                self._loop_bindings[bound] = prev

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        func = node.func
        called = func.attr if isinstance(func, ast.Attribute) else \
            (func.id if isinstance(func, ast.Name) else None)
        if called == "profile_start" and not self.profile_exempt \
                and self._if_depth == 0 \
                and not _suppressed(self.lines, node.lineno):
            self.violations.append(Violation(
                "OBS002", self.path, node.lineno,
                "unconditional profile_start() outside tests/bench — "
                "the wallclock sampler must be off by default; gate "
                "the call behind an `if` (admin-verb dispatch) or "
                "add `# obs-ok: <reason>`"))
        if self.copy_hot:
            copies = (isinstance(func, ast.Name) and func.id == "bytes"
                      and len(node.args) == 1) or \
                (isinstance(func, ast.Attribute)
                 and func.attr == "tobytes")
            if copies and not _copy_suppressed(self.lines,
                                               node.lineno):
                what = "bytes(...)" if isinstance(func, ast.Name) \
                    else ".tobytes()"
                self.violations.append(Violation(
                    "COPY001", self.path, node.lineno,
                    f"{what} in a hot-path data-plane module "
                    f"materialises a host copy; make it deliberate — "
                    f"book it in the obs.copy ledger and add "
                    f"`# copy-ok: <reason>` — or keep the view"))
        if not isinstance(func, ast.Attribute):
            return
        if func.attr not in DECLARE_METHODS | UPDATE_METHODS:
            return
        if _receiver_name(func) not in RECEIVERS:
            return
        if not node.args:
            return
        if _suppressed(self.lines, node.lineno):
            return
        self._check_name(node, node.args[0], func.attr)

    def _check_name(self, node: ast.Call, arg: ast.expr,
                    method: str) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                       str):
            if arg.value not in self.registry:
                self._flag(node, method, repr(arg.value))
            return
        if isinstance(arg, ast.Name):
            candidates = self._loop_bindings.get(arg.id)
            if candidates is not None:
                for name in candidates:
                    if name not in self.registry:
                        self._flag(node, method, repr(name))
                return
        if isinstance(arg, ast.JoinedStr):
            # constant fragments -> pattern; >=1 registered name must
            # match or the whole family is orphaned
            parts = []
            for v in arg.values:
                if isinstance(v, ast.Constant):
                    parts.append(re.escape(str(v.value)))
                else:
                    parts.append(".+")
            pat = re.compile("^" + "".join(parts) + "$")
            if not any(pat.match(n) for n in self.registry):
                self._flag(node, method,
                           f"f-string pattern {pat.pattern!r}")
            return
        self._flag(node, method,
                   "dynamic counter name (add `# obs-ok: <reason>` "
                   "if intentional)")

    def _flag(self, node: ast.Call, method: str, what: str) -> None:
        self.violations.append(Violation(
            "OBS001", self.path, node.lineno,
            f"counter {what} in .{method}() is not declared in "
            f"ceph_tpu/common/counters.py"))


def _profile_exempt(path: pathlib.Path) -> bool:
    return path.name in PROFILE_EXEMPT_NAMES or \
        "tests" in path.parts


def lint_file(path) -> List[Violation]:
    path = pathlib.Path(path)
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation("OBS000", str(path), e.lineno or 0,
                          f"syntax error: {e.msg}")]
    checker = _Checker(str(path), source,
                       profile_exempt=_profile_exempt(path),
                       copy_hot=copy_hot_path(path))
    checker.visit(tree)
    return checker.violations


def lint_registry_sync() -> List[Violation]:
    """OBS002(a): the attribution stages and copy-ledger sites the
    profiling plane books by name must each have their registry row —
    checked against the live modules, so adding a stage/site without
    the counter (or renaming the counter out from under the stage)
    fails the lint, not a telemetry column two PRs later."""
    from ceph_tpu.common.attribution import STAGES  # noqa: E402
    from ceph_tpu.common.copytrack import SITES  # noqa: E402
    out: List[Violation] = []
    for stage in STAGES:
        if not declared("obs.latency", stage):
            out.append(Violation(
                "OBS002", "ceph_tpu/common/attribution.py", 0,
                f"attribution stage {stage!r} has no 'obs.latency' "
                f"histogram in ceph_tpu/common/counters.py"))
    for site in SITES:
        for suffix in ("_bytes", "_copies"):
            if not declared("obs.copy", site + suffix):
                out.append(Violation(
                    "OBS002", "ceph_tpu/common/copytrack.py", 0,
                    f"copy-ledger counter '{site + suffix}' is not "
                    f"declared under 'obs.copy' in "
                    f"ceph_tpu/common/counters.py"))
    return out


def lint_prometheus_export() -> List[Violation]:
    """OBS003: every registered counter must surface on the
    prometheus scrape.  Build a synthetic one-daemon snapshot whose
    perf dump carries EVERY registry counter in its type's dump
    shape, run it through the real exporter, and demand each name's
    sanitized family HELP header back — plus no cross-type family
    collision from sanitization."""
    from ceph_tpu.common.counters import (AVG, HIST,  # noqa: E402
                                          REGISTRY)
    from ceph_tpu.tools.telemetry import (_sanitize,  # noqa: E402
                                          to_prometheus)
    perf: dict = {}
    for family, names in REGISTRY.items():
        perf[family] = {}
        for name, typ in names.items():
            if typ == HIST:
                perf[family][name] = {"buckets": [1, 2], "min": 1e-6}
            elif typ == AVG:
                perf[family][name] = {"avgcount": 1, "sum": 1.0,
                                      "avg": 1.0}
            else:
                perf[family][name] = 1
    text = to_prometheus(
        {"daemons": {"lint.0": {"perf": perf}}})
    helped = {line.split()[2] for line in text.splitlines()
              if line.startswith("# HELP ")}
    out: List[Violation] = []
    metric_types: dict = {}
    for family, names in sorted(REGISTRY.items()):
        for name, typ in sorted(names.items()):
            metric = f"ceph_tpu_{_sanitize(name)}"
            prev = metric_types.setdefault(metric, (family, name,
                                                    typ))
            if prev[2] != typ:
                out.append(Violation(
                    "OBS003", "ceph_tpu/common/counters.py", 0,
                    f"sanitized family {metric!r} merges "
                    f"{prev[0]}/{prev[1]} ({prev[2]}) with "
                    f"{family}/{name} ({typ}) — the exporter would "
                    f"emit conflicting # TYPE claims"))
            if metric not in helped:
                out.append(Violation(
                    "OBS003", "ceph_tpu/common/counters.py", 0,
                    f"registered counter {family}/{name} ({typ}) is "
                    f"not exported by telemetry.to_prometheus — no "
                    f"'# HELP {metric}' in the scrape of a snapshot "
                    f"that books it"))
    return out


def lint_paths(paths: Iterable) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            # the registry declares, it does not book
            if f.name == "counters.py":
                continue
            out.extend(lint_file(f))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    roots = args or [pathlib.Path(__file__).resolve().parent.parent
                     / "ceph_tpu"]
    violations = lint_registry_sync() + lint_prometheus_export() \
        + lint_paths(roots)
    for v in violations:
        print(v)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
