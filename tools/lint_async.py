#!/usr/bin/env python
"""Async-safety lint — may-block reachability from ``@nonblocking``.

The static half of ceph_tpu/analysis/asyncheck.py (the runtime twin
times declared scopes against a wallclock budget): a project-wide AST
call-graph walk that proves which primitive blocking operations are
reachable from a declared non-blocking context — Linux's
sleep-in-atomic checker, for this codebase — enforced by
tests/test_lint.py:

BLOCK001  a primitive may-block operation reachable through the
          static call graph from a function decorated
          ``@nonblocking`` (analysis/asyncheck.py).  The report
          carries the full call chain root -> ... -> primitive, each
          hop with its call-site line.  Primitives:

            * ``time.sleep`` / bare ``sleep``
            * ``*.wait(...)``       Event/Condition wait (bounded
                                    waits still stall the loop for
                                    the bound — mark with the bound
                                    as the reason)
            * ``*.acquire(...)``    lock acquire, unless
                                    ``blocking=False``
            * ``*.result(...)``     Future result
            * ``*.get(...)``        on queue-ish receivers (name
                                    contains ``queue``/``fifo`` or
                                    ends ``_q``), or with a
                                    ``timeout=``/``block=`` kwarg
            * ``os.fsync`` / ``*.fsync`` / ``*.flush``
            * socket ops: ``recv``/``recv_into``/``recvfrom``/
              ``recvmsg``/``accept``/``connect``/``sendall``/
              ``sendmsg``/``create_connection``
            * ``subprocess.*``
            * ``*.join(...)``       on thread-ish receivers

Call-graph resolution, and its two documented fallbacks:

  * bare names resolve through local binds (nested defs, lambdas,
    ``functools.partial(f, ...)`` assignments), imports (project
    imports follow the graph, stdlib imports are primitive-table-
    classified), module-level functions, and class constructors
    (``C()`` follows ``C.__init__``);
  * ``self.m()`` resolves through the class registry's MRO (inherited
    methods included);
  * ``obj.m()`` on any other receiver resolves by class-hierarchy
    analysis: edges to EVERY project method named ``m`` — except
    generic container/stdlib method names (``get``, ``update``,
    ``submit``, ...), which resolve only through ``self`` (CHA on
    ``d.get(...)`` would wire every dict read to every project
    ``get``);
  * CONSERVATIVE fallback: a call whose callee is a computed value —
    a subscript (``self._handlers[t](msg)``), a call result, a bound
    dynamic lookup (``cb = self._cbs.get(k); cb()``), a function
    parameter, or an unresolvable bare name — is assumed MAY-BLOCK
    and reported as a primitive at the call site.  Dynamic dispatch
    is where blocking hides; the analyzer refuses to guess.
  * OPTIMISTIC fallback: a named attribute call matching no project
    symbol and no primitive pattern (``json.dumps``, ``math.floor``)
    is assumed non-blocking — the primitive table names the stdlib
    blockers.

Arguments are not callees: ``pool.submit(fn)`` / ``Thread(target=fn)``
create NO edge to ``fn`` — handing work off the loop is exactly the
non-blocking idiom.  Decorators are assumed transparent (a call to a
decorated name follows the def).

Suppression: append ``# block-ok: <reason>`` to the primitive line
(suppresses that site for every root) or to a call-site line (cuts
that edge).  The reason is mandatory — it is the allowlist entry, and
for bounded waits it must name the bound.

Usage:
    python tools/lint_async.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import builtins
import pathlib
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

MARK = "block-ok:"

SOCKET_ATTRS = {"recv", "recv_into", "recvfrom", "recvmsg", "accept",
                "connect", "sendall", "sendmsg"}

# attribute names that resolve only through ``self.`` — CHA on these
# generic container/stdlib method names would wire every dict/list/
# executor call to same-named project methods
GENERIC_ATTRS = {
    "get", "put", "set", "pop", "update", "keys", "values", "items",
    "copy", "clear", "add", "append", "appendleft", "extend",
    "insert", "remove", "sort", "count", "index", "join", "split",
    "strip", "format", "encode", "decode", "setdefault", "popitem",
    "popleft", "submit", "close", "release", "discard", "info",
    "debug", "warning", "error",
    # socket.shutdown(SHUT_RDWR) would CHA-wire every raw-socket
    # close to project daemons' shutdown() methods, and Encoder/
    # Thread/span .start()/.stop() to daemon lifecycle methods
    "shutdown", "start", "stop",
}

_BUILTINS = frozenset(dir(builtins))


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _queueish(recv: str) -> bool:
    tail = recv.rsplit(".", 1)[-1].lower()
    return ("queue" in tail or "fifo" in tail or tail == "q"
            or tail.endswith("_q"))


def _threadish(recv: str) -> bool:
    tail = recv.rsplit(".", 1)[-1].lower()
    return "thread" in tail or "proc" in tail


def _recv_text(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:
        return "?"


def _kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _primitive(call: ast.Call) -> Optional[str]:
    """The primitive may-block table: a description when this call
    blocks by its own nature, else None."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "sleep":
            return "sleep [time.sleep]"
        if f.id == "fsync":
            return "fsync [durability barrier]"
        if f.id == "create_connection":
            return "create_connection [socket connect]"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    recv = _recv_text(f.value)
    if attr == "sleep":
        return f"{recv}.sleep [time.sleep]"
    if attr == "wait":
        return f"{recv}.wait [event/condition wait]"
    if attr == "acquire":
        b = _kw(call, "blocking")
        if isinstance(b, ast.Constant) and b.value is False:
            return None
        return f"{recv}.acquire [lock wait]"
    if attr == "result":
        return f"{recv}.result [future wait]"
    if attr == "fsync":
        return f"{recv}.fsync [durability barrier]"
    if attr == "flush":
        return f"{recv}.flush [buffered-io flush]"
    if attr in SOCKET_ATTRS:
        return f"{recv}.{attr} [socket {attr}]"
    if attr == "create_connection":
        return f"{recv}.create_connection [socket connect]"
    if recv.rsplit(".", 1)[-1] == "subprocess":
        return f"subprocess.{attr} [child process]"
    if attr == "get":
        if _queueish(recv) or _kw(call, "timeout") is not None \
                or _kw(call, "block") is not None:
            return f"{recv}.get [queue get]"
        return None
    if attr == "join" and _threadish(recv):
        return f"{recv}.join [thread join]"
    return None


def _is_partial(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "partial":
        return True
    return isinstance(f, ast.Attribute) and f.attr == "partial"


def _is_nonblocking_deco(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Name):
        return dec.id == "nonblocking"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "nonblocking"
    return False


class _FileInfo:
    __slots__ = ("rel", "lines", "modules", "from_imports")

    def __init__(self, rel: str, lines: List[str]):
        self.rel = rel
        self.lines = lines
        # alias -> module name (``import X as a``)
        self.modules: Dict[str, str] = {}
        # alias -> (module, original name) (``from M import n as a``)
        self.from_imports: Dict[str, Tuple[str, str]] = {}


def _project_module(mod: str) -> bool:
    return (mod.startswith(".") or mod.startswith("ceph_tpu")
            or mod.startswith("tools"))


class _Func:
    """One analyzed function/method/lambda: its primitive sites and
    outgoing call edges (specs resolved after all files parse)."""

    __slots__ = ("qual", "cls", "file", "lineno", "prims", "calls",
                 "is_root", "edges")

    def __init__(self, qual: str, cls: Optional[str], file: _FileInfo,
                 lineno: int):
        self.qual = qual
        self.cls = cls
        self.file = file
        self.lineno = lineno
        # (lineno, end_lineno, desc) primitive may-block sites
        self.prims: List[Tuple[int, int, str]] = []
        # (lineno, end_lineno, spec) unresolved call edges
        self.calls: List[Tuple[int, int, tuple]] = []
        self.is_root = False
        # resolved: (lineno, end_lineno, target _Func)
        self.edges: List[Tuple[int, int, "_Func"]] = []


class _Class:
    __slots__ = ("name", "bases", "methods")

    def __init__(self, name: str, bases: List[str]):
        self.name = name
        self.bases = bases
        self.methods: Dict[str, _Func] = {}


class _Env:
    """Per-function-body name environment: parameters (calls through
    them are dynamic) and local binds (nested defs, lambdas, partial
    results, dynamic lookups)."""

    __slots__ = ("params", "binds")

    def __init__(self, params: Set[str]):
        self.params = params
        self.binds: Dict[str, tuple] = {}


class _Project:
    """The whole-program view: every parsed file's classes/functions
    plus the name tables resolution consults."""

    def __init__(self):
        self.classes: Dict[str, _Class] = {}
        self.funcs_by_name: Dict[str, List[_Func]] = {}
        self.methods_by_name: Dict[str, List[_Func]] = {}
        self.roots: List[_Func] = []
        self.all_funcs: List[_Func] = []
        self.violations: List[Violation] = []
        # (rel, lineno) of every consulted # block-ok: mark — the
        # staleness set lint.py --audit-suppressions reads
        self.used_marks: Set[Tuple[str, int]] = set()
        self._no_reason: Set[Tuple[str, int]] = set()
        self._reported: Set[Tuple[str, int, str]] = set()

    # -- parsing ------------------------------------------------------

    def add_file(self, path: pathlib.Path,
                 root: Optional[pathlib.Path]) -> None:
        rel = str(path if root is None else path.relative_to(root))
        src = path.read_text()
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as e:
            self.violations.append(Violation(
                rel, e.lineno or 0, "BLOCK000",
                f"unparseable: {e.msg}"))
            return
        fi = _FileInfo(rel, src.splitlines())
        for n in ast.walk(tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    fi.modules[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(n, ast.ImportFrom):
                mod = ("." * n.level) + (n.module or "")
                for a in n.names:
                    fi.from_imports[a.asname or a.name] = \
                        (mod, a.name)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                f = self._def_func(node, None, fi)
                self.funcs_by_name.setdefault(node.name,
                                              []).append(f)
            elif isinstance(node, ast.ClassDef):
                self._add_class(node, fi)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Lambda) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                f = self._lambda_func(node.value,
                                      node.targets[0].id, None, fi)
                self.funcs_by_name.setdefault(
                    node.targets[0].id, []).append(f)

    def _add_class(self, node: ast.ClassDef, fi: _FileInfo) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        cls = self.classes.setdefault(node.name,
                                      _Class(node.name, bases))
        for item in node.body:
            if isinstance(item, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                f = self._def_func(item, node.name, fi)
                cls.methods[item.name] = f
                self.methods_by_name.setdefault(item.name,
                                                []).append(f)

    def _def_func(self, node, cls: Optional[str],
                  fi: _FileInfo) -> _Func:
        qual = f"{cls}.{node.name}" if cls else node.name
        f = _Func(qual, cls, fi, node.lineno)
        f.is_root = any(_is_nonblocking_deco(d)
                        for d in node.decorator_list)
        if f.is_root:
            self.roots.append(f)
        self.all_funcs.append(f)
        args = node.args
        params = {a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)}
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        env = _Env(params)
        for stmt in node.body:
            self._scan(stmt, f, env)
        return f

    def _lambda_func(self, node: ast.Lambda, name: str,
                     cls: Optional[str], fi: _FileInfo) -> _Func:
        f = _Func(f"{name}<lambda>", cls, fi, node.lineno)
        self.all_funcs.append(f)
        args = node.args
        params = {a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)}
        env = _Env(params)
        self._scan(node.body, f, env)
        return f

    # -- per-body scan ------------------------------------------------

    def _callee_spec(self, expr: ast.AST, fn: _Func,
                     env: _Env) -> tuple:
        """Classify a callee expression into a resolution spec."""
        if isinstance(expr, ast.Name):
            nm = expr.id
            if nm in env.binds:
                return env.binds[nm]
            if nm in env.params:
                return ("dynamic",
                        f"call through parameter {nm!r}")
            return ("name", nm)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                return ("self", expr.attr)
            if isinstance(expr.value, ast.Call) and \
                    isinstance(expr.value.func, ast.Name) and \
                    expr.value.func.id == "super":
                return ("super", expr.attr)
            return ("attr", expr.attr, _recv_text(expr.value))
        if isinstance(expr, ast.Lambda):
            return ("func",
                    self._lambda_func(expr, "<inline>", fn.cls,
                                      fn.file))
        if isinstance(expr, ast.Call):
            if _is_partial(expr) and expr.args:
                return self._callee_spec(expr.args[0], fn, env)
            return ("dynamic", "call on a call result")
        if isinstance(expr, ast.Subscript):
            return ("dynamic",
                    f"call through container lookup "
                    f"{_recv_text(expr)!r}")
        return ("dynamic", f"computed callee {_recv_text(expr)!r}")

    def _scan(self, node: ast.AST, fn: _Func, env: _Env) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = self._def_func(node, fn.cls, fn.file)
            env.binds[node.name] = ("func", inner)
            return  # own body already scanned with a fresh env
        if isinstance(node, ast.Lambda):
            self._lambda_func(node, "<inline>", fn.cls, fn.file)
            return
        if isinstance(node, ast.Assign) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            v = node.value
            if isinstance(v, ast.Lambda):
                env.binds[name] = (
                    "func", self._lambda_func(v, name, fn.cls,
                                              fn.file))
                return
            if isinstance(v, ast.Call) and _is_partial(v) and v.args:
                env.binds[name] = self._callee_spec(v.args[0], fn,
                                                    env)
                for a in v.args[1:]:
                    self._scan(a, fn, env)
                for kw in v.keywords:
                    self._scan(kw.value, fn, env)
                return
            if isinstance(v, (ast.Name, ast.Attribute)):
                spec = self._callee_spec(v, fn, env)
                if spec[0] != "dynamic":
                    env.binds[name] = spec
                self._scan(v, fn, env)
                return
            if isinstance(v, (ast.Call, ast.Subscript)):
                # ``cb = self._cbs.get(k)`` — a later ``cb()`` is a
                # dynamic call (the conservative fallback)
                env.binds[name] = (
                    "dynamic",
                    f"{name!r} bound from "
                    f"{_recv_text(v)!r}")
                self._scan(v, fn, env)
                return
        if isinstance(node, ast.Call):
            endl = getattr(node, "end_lineno", None) or node.lineno
            desc = _primitive(node)
            if desc is not None:
                fn.prims.append((node.lineno, endl, desc))
            else:
                spec = self._callee_spec(node.func, fn, env)
                if spec[0] == "dynamic":
                    fn.prims.append((
                        node.lineno, endl,
                        f"dynamic call ({spec[1]}): assumed "
                        f"may-block (conservative fallback)"))
                elif spec[0] != "safe":
                    fn.calls.append((node.lineno, endl, spec))
            for a in node.args:
                self._scan(a, fn, env)
            for kw in node.keywords:
                self._scan(kw.value, fn, env)
            # a computed func expression may itself contain calls
            if not isinstance(node.func, (ast.Name, ast.Attribute)):
                self._scan(node.func, fn, env)
            elif isinstance(node.func, ast.Attribute):
                self._scan(node.func.value, fn, env)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(child, fn, env)

    # -- resolution ---------------------------------------------------

    def _mro_lookup(self, cls_name: str,
                    attr: str) -> Optional[_Func]:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            nm = stack.pop(0)
            if nm in seen:
                continue
            seen.add(nm)
            c = self.classes.get(nm)
            if c is None:
                continue
            if attr in c.methods:
                return c.methods[attr]
            stack.extend(c.bases)
        return None

    def _resolve(self, fn: _Func, spec: tuple,
                 lineno: int) -> Tuple[List[_Func], Optional[str]]:
        """spec -> (target functions, dynamic-fallback description)."""
        kind = spec[0]
        if kind == "func":
            return [spec[1]], None
        if kind == "name":
            nm = spec[1]
            fi = fn.file
            if nm in fi.from_imports:
                mod, orig = fi.from_imports[nm]
                if _project_module(mod):
                    fs = self.funcs_by_name.get(orig)
                    if fs:
                        return list(fs), None
                    c = self.classes.get(orig)
                    if c is not None:
                        init = self._mro_lookup(orig, "__init__")
                        return ([init] if init else []), None
                return [], None  # stdlib import: primitive-table job
            if nm in fi.modules:
                return [], None
            fs = self.funcs_by_name.get(nm)
            if fs:
                return list(fs), None
            if nm in self.classes:
                init = self._mro_lookup(nm, "__init__")
                return ([init] if init else []), None
            if nm in _BUILTINS:
                return [], None
            return [], (f"unresolvable name {nm!r}: assumed "
                        f"may-block (conservative fallback)")
        if kind == "self":
            attr = spec[1]
            if fn.cls:
                m = self._mro_lookup(fn.cls, attr)
                if m is not None:
                    return [m], None
            ms = self.methods_by_name.get(attr)
            if ms:
                return list(ms), None
            return [], (f"self.{attr} resolves to no known method: "
                        f"assumed may-block (conservative fallback)")
        if kind == "super":
            attr = spec[1]
            if fn.cls and fn.cls in self.classes:
                for base in self.classes[fn.cls].bases:
                    m = self._mro_lookup(base, attr)
                    if m is not None:
                        return [m], None
            return [], None  # external base (Exception, Thread, ...)
        if kind == "attr":
            attr = spec[1]
            if attr in GENERIC_ATTRS:
                return [], None
            if attr.startswith("__") and attr.endswith("__"):
                # dunder CHA (x.__init__, cm.__exit__) wires every
                # constructor/protocol call project-wide; dunders
                # resolve only through Name-call constructors and
                # self/super
                return [], None
            root = spec[2].split(".", 1)[0].split("(", 1)[0]
            fi = fn.file
            mod = fi.modules.get(root)
            if mod is None and root in fi.from_imports:
                m, orig = fi.from_imports[root]
                mod = f"{m}.{orig}" if _project_module(m) else "stdlib"
            if mod is not None:
                # the receiver IS a module: a project module's
                # functions join the graph, a stdlib module's are
                # primitive-table-classified
                if _project_module(mod):
                    return list(self.funcs_by_name.get(attr, ())), \
                        None
                return [], None
            # object receiver: CHA over project METHODS of this name
            # (module-level functions of the same name are unrelated)
            return list(self.methods_by_name.get(attr, ())), None
        return [], None

    def link(self) -> None:
        """Resolve every recorded call spec into graph edges (and
        fold dynamic fallbacks into primitive sites)."""
        for fn in self.all_funcs:
            for lineno, endl, spec in fn.calls:
                targets, dyn = self._resolve(fn, spec, lineno)
                if dyn is not None:
                    fn.prims.append((
                        lineno, endl,
                        f"dynamic call ({dyn})"))
                for t in targets:
                    fn.edges.append((lineno, endl, t))

    # -- suppression --------------------------------------------------

    def _mark_at(self, fn: _Func, lineno: int,
                 endl: int) -> Optional[Tuple[int, str]]:
        """(mark line, reason) when a # block-ok: mark covers the
        statement spanning lineno..endl."""
        lines = fn.file.lines
        for ln in range(lineno, min(endl, lineno + 10,
                                    len(lines)) + 1):
            if MARK in lines[ln - 1]:
                return ln, lines[ln - 1].split(MARK, 1)[1].strip()
        return None

    def _consume_mark(self, fn: _Func, lineno: int,
                      endl: int) -> bool:
        """True when a valid (reasoned) mark suppresses this site;
        an empty reason emits its own violation and suppresses
        nothing."""
        hit = self._mark_at(fn, lineno, endl)
        if hit is None:
            return False
        mline, reason = hit
        if reason:
            self.used_marks.add((fn.file.rel, mline))
            return True
        key = (fn.file.rel, mline)
        if key not in self._no_reason:
            self._no_reason.add(key)
            self.violations.append(Violation(
                fn.file.rel, mline, "BLOCK001",
                "'# block-ok:' carries no reason — the reason is "
                "the allowlist entry"))
        return False

    # -- reachability -------------------------------------------------

    def _chain(self, parent: Dict[int, Tuple[_Func, int]],
               fn: _Func) -> str:
        hops = []
        cur: Optional[_Func] = fn
        while cur is not None:
            prev = parent.get(id(cur))
            if prev is None:
                hops.append(cur.qual)
                break
            pfn, ln = prev
            hops.append(f"{cur.qual} "
                        f"({pathlib.Path(cur.file.rel).name}:"
                        f"{cur.lineno}, called at "
                        f"{pathlib.Path(pfn.file.rel).name}:{ln})")
            cur = pfn
        return " -> ".join(reversed(hops))

    def report(self) -> None:
        for root in sorted(self.roots,
                           key=lambda f: (f.file.rel, f.lineno)):
            visited: Set[int] = {id(root)}
            parent: Dict[int, Tuple[_Func, int]] = {}
            queue: List[_Func] = [root]
            while queue:
                fn = queue.pop(0)
                for lineno, endl, desc in fn.prims:
                    if self._consume_mark(fn, lineno, endl):
                        continue
                    key = (fn.file.rel, lineno, desc)
                    if key in self._reported:
                        continue  # one report per site; the fix or
                        # mark there covers every root reaching it
                    self._reported.add(key)
                    chain = self._chain(parent, fn)
                    self.violations.append(Violation(
                        fn.file.rel, lineno, "BLOCK001",
                        f"may-block op {desc} reachable from "
                        f"@nonblocking {root.qual!r} via: {chain} "
                        f"-> [{desc} at line {lineno}]; move it "
                        f"off-loop, bound it, or mark the site "
                        f"'# block-ok: <reason>'"))
                for lineno, endl, tgt in fn.edges:
                    if id(tgt) in visited:
                        continue
                    if self._consume_mark(fn, lineno, endl):
                        continue
                    visited.add(id(tgt))
                    parent[id(tgt)] = (fn, lineno)
                    queue.append(tgt)


def analyze(paths: Iterable[pathlib.Path]
            ) -> Tuple[List[Violation], Set[Tuple[str, int]]]:
    """Whole-program analysis over ``paths``; returns the violation
    list and the set of (relpath, lineno) # block-ok: marks the walk
    actually consulted (lint.py --audit-suppressions' staleness
    input)."""
    proj = _Project()
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                proj.add_file(f, root)
        else:
            proj.add_file(p, None)
    proj.link()
    proj.report()
    return (sorted(proj.violations, key=lambda v: (v.path, v.line)),
            proj.used_marks)


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    if root is not None:
        vs, _ = analyze([root / pathlib.Path(path).relative_to(root)
                         if pathlib.Path(path).is_absolute()
                         else path])
    else:
        vs, _ = analyze([path])
    return vs


def lint_paths(paths: Iterable[pathlib.Path]) -> List[Violation]:
    return analyze(paths)[0]


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} async-safety lint violation(s)")
        return 1
    print("async lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
