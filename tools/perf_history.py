#!/usr/bin/env python
"""perf_history — the bench trajectory table with regression deltas.

Every committed ``BENCH_rNN.json`` records one driver bench run
(headline JSON under ``parsed``, the run's stderr under ``tail``).
Until now comparing runs was archaeology: open two files, grep the
tails, eyeball the numbers.  This tool ingests the whole series and
renders it as a trajectory table — one row per run, one column per
metric, with per-metric percentage deltas vs the previous run that
recorded the metric — and turns regressions into a red check:

    python tools/perf_history.py              # table, r01 -> rNN
    python tools/perf_history.py --check      # exit 1 if the LATEST
                                              # run regressed any
                                              # throughput metric
                                              # beyond --threshold
    python tools/perf_history.py --json       # rows as JSON

Metrics come from two places: the structured headline (``parsed``:
crush mappings/s, vs_baseline, and — from this PR on — the ``slo``
block), and the stderr tail (cluster IOPS, EC GB/s, batched-encode
speedup, and the staged lane's backend-init outcome: ``init_probe_s``
is how long the run burned before giving up on a dead accelerator
tunnel — the fail-fast satellite's acceptance signal).

Regression policy: throughput metrics (higher is better) flag when
they drop more than ``--threshold`` (default 25%) vs the previous
recorded value; ``init_probe_s`` (lower is better) flags when it
grows past the fail-fast deadline band.  SLO blocks recorded by the
bench itself flag directly when ``pass`` is false.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

# (metric, higher_is_better) — column order of the table
METRICS = [
    ("crush_mappings_s", True),
    ("vs_baseline", True),
    ("cluster_wr_iops", True),
    ("cluster_seq_iops", True),
    ("ec_encode_gbps", True),
    ("ec_batch_speedup", True),
    ("mc_crush_ndev_s", True),
    ("mc_crush_eff", True),
    ("mc_ec_eff", True),
    ("mc_dry_crush_eff", True),
    ("mc_dry_ec_eff", True),
    ("init_probe_s", False),
    ("chaos_ops", True),
    ("chaos_converge_s", False),
    ("balance_rounds", False),
    ("balance_final_stddev", False),
    ("balance_sweep_mappings_s", True),
    ("drill_recovery_mbs", True),
    ("drill_speedup", True),
    ("drill_p99_ms", False),
    ("netsplit_false_markdowns", False),
    ("netsplit_detect_s", False),
    ("netsplit_epoch_churn", False),
    ("race_violations", False),
    ("race_overhead_pct", False),
    ("async_violations", False),
    ("async_overhead_pct", False),
    ("attr_unattr_pct", False),
    ("copy_bytes_per_op", False),
    ("prof_overhead_pct", False),
    ("net.send_stall_share", False),
    ("net.dispatch_p99_ms", False),
]

_TAIL_PATTERNS = {
    "cluster_wr_iops": re.compile(
        r"# cluster [^:]*: write ([\d.]+) IOPS"),
    "cluster_seq_iops": re.compile(r"; seq ([\d.]+) IOPS"),
    "ec_encode_gbps": re.compile(
        r"# ec k=8,m=3: encode ([\d.]+) GB/s"),
    "ec_batch_speedup": re.compile(
        r"# ec batched encode .*\(([\d.]+)x\)"),
}
_INIT_KILL = re.compile(
    r"# staged/default: killed \((?:no init line|deadline)[^)]*\) "
    r"at t=([\d.]+)s")
_INIT_HANG_LEGACY = re.compile(
    r"backend never initialized within ([\d.]+)s")
# the multichip scaling block: BENCH tails carry the bench lane's
# stage JSON ("# multichip json: {...}"), MULTICHIP dryrun tails carry
# the dryrun-sized twin ("multichip scaling: {...}")
_MC_JSON = re.compile(r"multichip (?:json|scaling): (\{.*\})")
# the cluster lane's stage JSON ("# cluster json: {...}") — from the
# profiling-plane PR on it carries the attribution / copy-ledger /
# profiler blocks alongside the IOPS headline
_CL_JSON = re.compile(r"# cluster json: (\{.*\})")

# zero-copy buffer-plane goal (ROADMAP item 2): r13 measured the
# baseline at 191,329.9 copied bytes per acked op; the buffer plane
# landed in r14 with a >=40% reduction acceptance bar.  Any run after
# the baseline that books more than 0.6x the baseline is a red check
# regardless of run-over-run drift — the goal is absolute.
_COPY_BASELINE_RUN = 13
_COPY_BASELINE = 191330.0
_COPY_GOAL = 0.6 * _COPY_BASELINE


def _multichip_metrics(tail: str,
                       dryrun: bool = False) -> Dict[str, float]:
    """Scaling metrics from a tail's multichip JSON block: the
    N-device CRUSH throughput and the scaling-efficiency figures
    (N-device throughput / (N x 1-device)) for CRUSH and EC encode —
    the ROADMAP item 1 acceptance numbers, red-checked like any other
    trajectory metric when they drop more than the threshold.

    Dryrun (MULTICHIP_r*) records measure a deliberately smaller
    workload than the bench lane, so their efficiency lands in its
    own ``mc_dry_*`` columns — each series deltas like-for-like —
    and their absolute rate (small-map, incomparable) is dropped."""
    m = _MC_JSON.search(tail)
    if not m:
        return {}
    try:
        d = json.loads(m.group(1))
    except ValueError:
        return {}
    pre = "mc_dry_" if dryrun else "mc_"
    keys = [("crush_scaling_efficiency", pre + "crush_eff"),
            ("ec_scaling_efficiency", pre + "ec_eff")]
    if not dryrun:
        keys.append(("crush_ndev_mappings_per_sec",
                     "mc_crush_ndev_s"))
    out: Dict[str, float] = {}
    for key, name in keys:
        if isinstance(d.get(key), (int, float)):
            out[name] = float(d[key])
    return out


def _profiling_metrics(tail: str) -> Dict[str, float]:
    """Profiling-plane metrics from a tail's cluster JSON block —
    all lower-is-better: the share of the client critical path the
    attribution fold could not name (``attr_unattr_pct``), the bytes
    the hot write path copies per acked op (``copy_bytes_per_op``),
    and the IOPS tax of running the wallclock sampler at its default
    rate (``prof_overhead_pct``).  Growth past the threshold is a red
    check: unattributed share creeping up means a new untagged span
    on the critical path; bytes/op creeping up means a new copy."""
    m = _CL_JSON.search(tail)
    if not m:
        return {}
    try:
        d = json.loads(m.group(1))
    except ValueError:
        return {}
    out: Dict[str, float] = {}
    attr = d.get("attribution") or {}
    if isinstance(attr.get("unattr_pct"), (int, float)):
        out["attr_unattr_pct"] = float(attr["unattr_pct"])
    copyb = d.get("copy") or {}
    if isinstance(copyb.get("bytes_per_op"), (int, float)):
        out["copy_bytes_per_op"] = float(copyb["bytes_per_op"])
    prof = d.get("profiler") or {}
    if isinstance(prof.get("overhead_pct"), (int, float)):
        out["prof_overhead_pct"] = float(prof["overhead_pct"])
    # saturation plane (PR 17): whole-run messenger backpressure —
    # stall share creeping up means the send path is blocking on the
    # wire; dispatch p99 creeping up means frames are sitting in the
    # handler pool queue before any handler runs
    net = d.get("net") or {}
    if isinstance(net.get("send_stall_share"), (int, float)):
        out["net.send_stall_share"] = float(net["send_stall_share"])
    if isinstance(net.get("dispatch_p99_ms"), (int, float)):
        out["net.dispatch_p99_ms"] = float(net["dispatch_p99_ms"])
    return out


def load_run(path: str) -> Optional[Dict]:
    try:
        raw = json.load(open(path))
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    parsed = raw.get("parsed") or {}
    tail = raw.get("tail") or ""
    row: Dict = {
        "run": f"r{int(raw.get('n', 0)):02d}",
        "n": int(raw.get("n", 0)),
        "path": os.path.basename(path),
        "rc": raw.get("rc"),
        "platform": parsed.get("platform"),
        "metrics": {},
        "slo_fail": [],
    }
    if isinstance(parsed.get("value"), (int, float)):
        row["metrics"]["crush_mappings_s"] = float(parsed["value"])
    if isinstance(parsed.get("vs_baseline"), (int, float)):
        row["metrics"]["vs_baseline"] = float(parsed["vs_baseline"])
    for metric, pat in _TAIL_PATTERNS.items():
        m = pat.search(tail)
        if m:
            row["metrics"][metric] = float(m.group(1))
    row["metrics"].update(_multichip_metrics(tail))
    row["metrics"].update(_profiling_metrics(tail))
    # how long the staged lane burned before the accelerator verdict:
    # the backend-init fail-fast probe should cap this at ~60 s (the
    # r05 run burned 300 s; the probe landed after that measurement)
    m = _INIT_KILL.search(tail) or _INIT_HANG_LEGACY.search(tail)
    if m:
        row["metrics"]["init_probe_s"] = float(m.group(1))
    elif parsed.get("backend_init_failed"):
        row["metrics"]["init_probe_s"] = float(
            os.environ.get("CEPH_TPU_BENCH_INIT_DEADLINE", 60))
    slo = parsed.get("slo")
    if isinstance(slo, dict) and slo.get("pass") is False:
        row["slo_fail"].append(slo.get("metric", "headline"))
    for m_ in re.finditer(r"# slo (\S+): .*-> FAIL", tail):
        row["slo_fail"].append(m_.group(1))
    return row


def load_multichip(path: str) -> Optional[Dict]:
    """One MULTICHIP_rNN.json dryrun record: run number + the scaling
    metrics parsed from its tail (absent on records that predate the
    scaling block)."""
    try:
        raw = json.load(open(path))
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    return {"ok": raw.get("ok"),
            "metrics": _multichip_metrics(raw.get("tail") or "",
                                          dryrun=True)}


def load_chaos(path: str) -> Optional[Dict]:
    """One CHAOS_rNN.json thrasher-soak record (tools/thrasher.py):
    acked-op volume and HEALTH_OK convergence time become trajectory
    metrics; lost acked writes or a failed soak (``ok`` false) are
    regressions outright — there is no acceptable drift on
    durability."""
    try:
        raw = json.load(open(path))
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("ops"), (int, float)):
        metrics["chaos_ops"] = float(raw["ops"])
    if isinstance(raw.get("health_converge_s"), (int, float)):
        metrics["chaos_converge_s"] = float(raw["health_converge_s"])
    fail: List[str] = []
    if raw.get("lost"):
        fail.append(f"chaos_lost_writes={raw['lost']}")
    if raw.get("ok") is False:
        fail.append("chaos_soak_failed")
    return {"metrics": metrics, "fail": fail}


def load_balance(path: str) -> Optional[Dict]:
    """One BALANCE_rNN.json balancer-convergence record (bench.py
    --worker balancer over ceph_tpu/mgr/run_offline): rounds to
    converge, final deviation stddev, sweep throughput.  A run that
    exits without converging is a red check outright."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("rounds"), (int, float)):
        metrics["balance_rounds"] = float(raw["rounds"])
    if isinstance(raw.get("final_stddev"), (int, float)):
        metrics["balance_final_stddev"] = float(raw["final_stddev"])
    if isinstance(raw.get("sweep_mappings_per_sec"), (int, float)):
        metrics["balance_sweep_mappings_s"] = float(
            raw["sweep_mappings_per_sec"])
    fail: List[str] = []
    if raw.get("converged") is False:
        fail.append("balance_not_converged")
    return {"metrics": metrics, "fail": fail}


def load_drill(path: str) -> Optional[Dict]:
    """One DRILL_rNN.json whole-host-failure record (tools/thrasher.py
    --host-kill): pipelined recovery MB/s, the speedup over the serial
    per-object baseline, and the degraded-read soak p99 become
    trajectory metrics.  Lost acked writes, a failed reconvergence, a
    failed SLO, or a speedup under the 1.5x pipeline gate are
    regressions outright."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("recovery_mbps"), (int, float)):
        metrics["drill_recovery_mbs"] = float(raw["recovery_mbps"])
    if isinstance(raw.get("pipeline_speedup"), (int, float)):
        metrics["drill_speedup"] = float(raw["pipeline_speedup"])
    soak = raw.get("soak") or {}
    if isinstance(soak.get("p99_ms"), (int, float)):
        metrics["drill_p99_ms"] = float(soak["p99_ms"])
    fail: List[str] = []
    if raw.get("lost"):
        fail.append(f"drill_lost_writes={raw['lost']}")
    if raw.get("converge_s") is None:
        fail.append("drill_not_converged")
    slo = soak.get("slo")
    if isinstance(slo, dict) and slo.get("pass") is False:
        fail.append(f"drill_slo_fail:{slo.get('metric')}")
    speedup = raw.get("pipeline_speedup")
    if not isinstance(speedup, (int, float)) or speedup <= 1.5:
        fail.append("drill_speedup_below_1.5x")
    if raw.get("ok") is False:
        fail.append("drill_failed")
    return {"metrics": metrics, "fail": fail}


def load_netsplit(path: str) -> Optional[Dict]:
    """One NETSPLIT_rNN.json partition-drill record (tools/thrasher.py
    --netsplit): false markdowns under a mon-link cut, true-isolation
    detection latency, and flap-drill epoch churn become trajectory
    metrics.  ANY false markdown, lost acked write, or failed drill
    verdict is a regression outright — partition tolerance has no
    acceptable drift."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("false_markdowns"), (int, float)):
        metrics["netsplit_false_markdowns"] = float(
            raw["false_markdowns"])
    if isinstance(raw.get("detect_s"), (int, float)):
        metrics["netsplit_detect_s"] = float(raw["detect_s"])
    if isinstance(raw.get("epoch_churn"), (int, float)):
        metrics["netsplit_epoch_churn"] = float(raw["epoch_churn"])
    fail: List[str] = []
    if raw.get("false_markdowns"):
        fail.append(
            f"netsplit_false_markdowns={raw['false_markdowns']}")
    if raw.get("lost"):
        fail.append(f"netsplit_lost_writes={raw['lost']}")
    if raw.get("ok") is False:
        fail.append("netsplit_drill_failed")
    return {"metrics": metrics, "fail": fail}


def load_race(path: str) -> Optional[Dict]:
    """One RACE_rNN.json data-race-audit record (tools/thrasher.py
    --race-audit): the violation count and checker-overhead metrics
    join the trajectory, and the gate is absolute — ANY recorded
    lockset/confinement violation, any acked-write loss under the
    drills, a failed audit verdict, or checker overhead at/over 10%
    is a regression outright (a data race has no acceptable drift)."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("violations"), (int, float)):
        metrics["race_violations"] = float(raw["violations"])
    if isinstance(raw.get("overhead_pct"), (int, float)):
        metrics["race_overhead_pct"] = float(raw["overhead_pct"])
    fail: List[str] = []
    if raw.get("violations"):
        fail.append(f"race_violations={raw['violations']}")
    if raw.get("lost"):
        fail.append(f"race_lost_writes={raw['lost']}")
    ov = raw.get("overhead_pct")
    if not isinstance(ov, (int, float)) or ov >= 10.0:
        fail.append(f"race_checker_overhead={ov}")
    if raw.get("ok") is False:
        fail.append("race_audit_failed")
    return {"metrics": metrics, "fail": fail}


def load_async(path: str) -> Optional[Dict]:
    """One ASYNC_rNN.json loop-stall record (tools/thrasher.py
    --loop-stall): the static-violation count and enforcement
    overhead join the trajectory, and the gate is absolute — ANY
    unsuppressed BLOCK001 reachability violation, any acked-write
    loss, an unnamed victim callback, a cluster that failed to heal,
    a failed drill verdict, or enforcement overhead at/over 5% is a
    regression outright (a blocking dispatch loop has no acceptable
    drift)."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        print(f"# {path}: unreadable ({e})", file=sys.stderr)
        return None
    metrics: Dict[str, float] = {}
    if isinstance(raw.get("static_violations"), (int, float)):
        metrics["async_violations"] = \
            float(raw["static_violations"])
    if isinstance(raw.get("overhead_pct"), (int, float)):
        metrics["async_overhead_pct"] = float(raw["overhead_pct"])
    fail: List[str] = []
    if raw.get("static_violations"):
        fail.append(
            f"async_violations={raw['static_violations']}")
    if raw.get("lost"):
        fail.append(f"async_lost_writes={raw['lost']}")
    if not raw.get("victim_named"):
        fail.append("async_victim_unnamed")
    if not raw.get("cleared"):
        fail.append("async_not_healed")
    ov = raw.get("overhead_pct")
    if not isinstance(ov, (int, float)) or ov >= 5.0:
        fail.append(f"async_enforcer_overhead={ov}")
    if raw.get("ok") is False:
        fail.append("loop_stall_drill_failed")
    return {"metrics": metrics, "fail": fail}


def load_all(directory: str) -> List[Dict]:
    rows = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json"))):
        row = load_run(path)
        if row is not None:
            rows.append(row)
    by_n = {r["n"]: r for r in rows}
    # MULTICHIP_rNN dryrun records ride the same trajectory: their
    # scaling metrics merge into the same-numbered bench row (the
    # driver emits both per run), creating a standalone row when no
    # bench run shares the number.  Bench-measured values win — the
    # dryrun twin is smaller-scale.
    for path in sorted(glob.glob(os.path.join(directory,
                                              "MULTICHIP_r*.json"))):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", path)
        mc = load_multichip(path)
        if mc is None or m is None or not mc["metrics"]:
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in mc["metrics"].items():
            row["metrics"].setdefault(k, v)
    # CHAOS_rNN thrasher records merge the same way: chaos metrics
    # land on the same-numbered bench row (or a standalone row), and
    # their hard failures ride slo_fail into the regression check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "CHAOS_r*.json"))):
        m = re.search(r"CHAOS_r(\d+)\.json$", path)
        ch = load_chaos(path)
        if ch is None or m is None or \
                not (ch["metrics"] or ch["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in ch["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(ch["fail"])
    # BALANCE_rNN balancer-convergence records: placement-quality
    # metrics merge onto the same-numbered row; a non-converged run
    # rides slo_fail into the regression check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BALANCE_r*.json"))):
        m = re.search(r"BALANCE_r(\d+)\.json$", path)
        bal = load_balance(path)
        if bal is None or m is None or \
                not (bal["metrics"] or bal["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in bal["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(bal["fail"])
    # DRILL_rNN whole-host-failure records: recovery-throughput and
    # degraded-read-latency metrics merge onto the same-numbered row;
    # durability / SLO / pipeline-gate failures ride slo_fail into
    # the regression check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "DRILL_r*.json"))):
        m = re.search(r"DRILL_r(\d+)\.json$", path)
        dr = load_drill(path)
        if dr is None or m is None or \
                not (dr["metrics"] or dr["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in dr["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(dr["fail"])
    # NETSPLIT_rNN partition-drill records: detection-latency and
    # churn metrics merge onto the same-numbered row; false markdowns
    # and lost writes ride slo_fail into the regression check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "NETSPLIT_r*.json"))):
        m = re.search(r"NETSPLIT_r(\d+)\.json$", path)
        ns = load_netsplit(path)
        if ns is None or m is None or \
                not (ns["metrics"] or ns["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in ns["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(ns["fail"])
    # RACE_rNN data-race-audit records: violation count and checker
    # overhead merge onto the same-numbered row; any violation, lost
    # write or overhead breach rides slo_fail into the regression
    # check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "RACE_r*.json"))):
        m = re.search(r"RACE_r(\d+)\.json$", path)
        rc_ = load_race(path)
        if rc_ is None or m is None or \
                not (rc_["metrics"] or rc_["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in rc_["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(rc_["fail"])
    # ASYNC_rNN loop-stall records: static-violation count and
    # enforcement overhead merge onto the same-numbered row; any
    # violation, lost write, unnamed victim, failed heal or overhead
    # breach rides slo_fail into the regression check
    for path in sorted(glob.glob(os.path.join(directory,
                                              "ASYNC_r*.json"))):
        m = re.search(r"ASYNC_r(\d+)\.json$", path)
        ac = load_async(path)
        if ac is None or m is None or \
                not (ac["metrics"] or ac["fail"]):
            continue
        n = int(m.group(1))
        row = by_n.get(n)
        if row is None:
            row = {"run": f"r{n:02d}", "n": n,
                   "path": os.path.basename(path), "rc": None,
                   "platform": None, "metrics": {}, "slo_fail": []}
            by_n[n] = row
            rows.append(row)
        for k, v in ac["metrics"].items():
            row["metrics"].setdefault(k, v)
        row["slo_fail"].extend(ac["fail"])
    rows.sort(key=lambda r: r["n"])
    return rows


def compute_deltas(rows: List[Dict],
                   threshold: float = 0.25) -> None:
    """Annotate each row with per-metric % delta vs the previous run
    that recorded the metric, and a ``regressions`` list for drops
    (or, for lower-is-better metrics, growth) beyond the threshold."""
    last_seen: Dict[str, float] = {}
    for row in rows:
        row["deltas"] = {}
        row["regressions"] = list(row["slo_fail"])
        for metric, higher_better in METRICS:
            val = row["metrics"].get(metric)
            if val is None:
                continue
            prev = last_seen.get(metric)
            if prev not in (None, 0):
                pct = (val - prev) / abs(prev)
                row["deltas"][metric] = pct
                regressed = (pct < -threshold) if higher_better \
                    else (pct > threshold)
                if regressed:
                    row["regressions"].append(
                        f"{metric} {prev:g} -> {val:g} "
                        f"({pct * 100:+.0f}%)")
            last_seen[metric] = val
        cbpo = row["metrics"].get("copy_bytes_per_op")
        if cbpo is not None and row["n"] > _COPY_BASELINE_RUN \
                and cbpo > _COPY_GOAL:
            row["regressions"].append(
                f"copy_bytes_per_op {cbpo:g} above the zero-copy "
                f"goal {_COPY_GOAL:g} (0.6 x r{_COPY_BASELINE_RUN}'s "
                f"{_COPY_BASELINE:g})")


def render(rows: List[Dict]) -> str:
    headers = ["run"] + [m for m, _ in METRICS] + ["flags"]
    widths = [max(len(h), 14) for h in headers]
    widths[0] = 5

    def cell(row: Dict, metric: str) -> str:
        val = row["metrics"].get(metric)
        if val is None:
            return "-"
        pct = row["deltas"].get(metric)
        s = f"{val:g}"
        if pct is not None:
            s += f" ({pct * 100:+.0f}%)"
        return s

    lines = ["".join(h.ljust(w + 1) for h, w in zip(headers,
                                                    widths))]
    for row in rows:
        flags = "REGRESSED" if row["regressions"] else "ok"
        cells = [row["run"]] + [cell(row, m) for m, _ in METRICS] \
            + [flags]
        lines.append("".join(c.ljust(w + 1)
                             for c, w in zip(cells, widths)))
    for row in rows:
        for reg in row["regressions"]:
            lines.append(f"  ! {row['run']}: {reg}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="perf_history")
    ap.add_argument("directory", nargs="?",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding BENCH_r*.json "
                         "(default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="fractional drop that counts as a "
                         "regression (default 0.25)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the LATEST run regressed")
    ap.add_argument("--json", action="store_true",
                    help="emit rows as JSON instead of the table")
    args = ap.parse_args(argv)

    rows = load_all(args.directory)
    if not rows:
        print(f"no BENCH_r*.json under {args.directory}",
              file=sys.stderr)
        return 2
    compute_deltas(rows, threshold=args.threshold)
    if args.json:
        print(json.dumps(rows, indent=1))
    else:
        print(render(rows))
    if args.check and rows[-1]["regressions"]:
        print(f"REGRESSION in {rows[-1]['run']}: "
              f"{rows[-1]['regressions']}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
