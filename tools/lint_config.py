#!/usr/bin/env python
"""Config-option lint — static companion to the option schema.

CONF001  a literal (or f-string) option name passed to a ``Config``
         access — ``conf.get("name")`` / ``conf.set("name", v)`` /
         ``conf.add_observer("name", cb)`` / ``conf["name"]`` on a
         receiver named ``conf``/``config``/``cfg`` at any attribute
         depth (``self.ctx.conf``, ``ctx.conf``) — that does not
         exist in the option schema
         (``ceph_tpu/common/config.py`` OPTIONS).  ``Config.get``
         raises ``KeyError`` on unknown names, so a typo'd option is
         a latent crash on whatever path first reads it — usually a
         rarely-exercised error branch; this catches it at review
         time instead.  F-string names (``f"debug_{subsys}"``) turn
         their literal fragments into a pattern: at least one
         registered option must match, so renaming a family away
         from under the pattern still fails.

Suppression: append ``# conf-ok: <reason>`` to the offending line.
The reason is mandatory — it is the allowlist entry.

Usage:
    python tools/lint_config.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ceph_tpu.common.config import OPTIONS  # noqa: E402

SUPPRESS_MARK = "conf-ok:"

RECEIVERS = {"conf", "config", "cfg", "_conf", "_config"}
ACCESS_METHODS = {"get", "set", "add_observer", "remove_observer",
                  "rm_override", "source_of"}


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _receiver_name(expr: ast.AST) -> str:
    """Last dotted component of the receiver expression
    (``self.ctx.conf`` -> ``conf``)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _fstring_pattern(node: ast.JoinedStr) -> Optional[str]:
    """Anchored regex from an f-string's literal fragments, or None
    when it has no constant text to pin a match on."""
    parts: List[str] = []
    has_literal = False
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
            has_literal = True
        else:
            parts.append(".*")
    return "^" + "".join(parts) + "$" if has_literal else None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, rel: str, src: str):
        self.rel = rel
        self.lines = src.splitlines()
        self.out: List[Violation] = []

    def _suppressed(self, lineno: int) -> bool:
        line = self.lines[lineno - 1] \
            if 1 <= lineno <= len(self.lines) else ""
        if SUPPRESS_MARK not in line:
            return False
        if line.split(SUPPRESS_MARK, 1)[1].strip():
            return True
        self.out.append(Violation(
            self.rel, lineno, "CONF001",
            "'# conf-ok:' carries no reason — the reason is the "
            "allowlist entry"))
        return True

    def _check_name(self, node: ast.AST, name_node: ast.AST,
                    how: str) -> None:
        if isinstance(name_node, ast.Constant):
            if not isinstance(name_node.value, str):
                return
            name = name_node.value
            if name in OPTIONS or self._suppressed(node.lineno):
                return
            self.out.append(Violation(
                self.rel, node.lineno, "CONF001",
                f"option {name!r} ({how}) is not in the schema "
                f"(ceph_tpu/common/config.py OPTIONS) — "
                f"Config.get raises KeyError on it"))
        elif isinstance(name_node, ast.JoinedStr):
            pat = _fstring_pattern(name_node)
            if pat is None:
                return
            if any(re.match(pat, opt) for opt in OPTIONS) or \
                    self._suppressed(node.lineno):
                return
            self.out.append(Violation(
                self.rel, node.lineno, "CONF001",
                f"f-string option pattern {pat!r} ({how}) matches "
                f"no schema option — the family it named is gone"))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and \
                f.attr in ACCESS_METHODS and \
                _receiver_name(f.value) in RECEIVERS and node.args:
            self._check_name(node, node.args[0], f"conf.{f.attr}")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _receiver_name(node.value) in RECEIVERS:
            self._check_name(node, node.slice, "conf[...]")
        self.generic_visit(node)


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    rel = str(path if root is None else path.relative_to(root))
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "CONF000",
                          f"unparseable: {e.msg}")]
    linter = _FileLinter(rel, src)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: v.line)


def lint_paths(paths: Iterable[pathlib.Path]) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                out.extend(lint_file(f, root=root))
        else:
            out.extend(lint_file(p))
    return out


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} config lint violation(s)")
        return 1
    print("config lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
