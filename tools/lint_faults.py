#!/usr/bin/env python
"""Retry-pacing lint — static companion to ceph_tpu/common/backoff.py.

One check, enforced by tests/test_lint.py:

FAULT001  a literal ``time.sleep(...)`` / ``sleep(...)`` call inside
          a retry loop — a ``for``/``while`` whose body also contains
          a ``try``/``except`` — anywhere outside the backoff helper.
          Fixed-interval retry pacing is how retry storms happen
          (every waiter wakes in lockstep and re-hits the recovering
          service together) and it ignores any op deadline; pace
          retries with ``common/backoff.py``'s ``Backoff`` — jittered,
          decorrelated, budgeted — instead.

Poll loops without an except clause (``while not done: sleep``) are
fine: they wait on local state, not on a failing peer, so there is
nothing to storm.

Suppression: append ``# fault-ok: <reason>`` to the sleep line (or
the loop's introducing line).  The reason is mandatory — it is the
allowlist entry.

Usage:
    python tools/lint_faults.py [paths...]   # default: ceph_tpu/
Exit status 1 when violations are found.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from dataclasses import dataclass
from typing import Iterable, List, Optional

SUPPRESS_MARK = "fault-ok:"

# the backoff helper itself sleeps by design
ALLOW_RAW_FILES = ("common/backoff.py",)


@dataclass
class Violation:
    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressed(src_lines: List[str], *linenos: int) -> bool:
    for ln in linenos:
        if 1 <= ln <= len(src_lines) and \
                SUPPRESS_MARK in src_lines[ln - 1]:
            return True
    return False


def _is_sleep_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep":
        # time.sleep / <anything>.sleep — but a Backoff handle's
        # .sleep() IS the sanctioned pacing call
        try:
            owner = ast.unparse(f.value)
        except Exception:
            return True
        tail = owner.rsplit(".", 1)[-1].lower()
        return not ("backoff" in tail or tail in ("bo", "b_o"))
    return isinstance(f, ast.Name) and f.id == "sleep"


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.out: List[Violation] = []
        self._seen: set = set()  # id() of already-reported sleeps
        # (nested loops would otherwise report the same call twice)

    def _emit(self, node: ast.AST, message: str,
              *extra_lines: int) -> None:
        if _suppressed(self.lines, node.lineno, *extra_lines):
            return
        self.out.append(Violation(self.rel, node.lineno, "FAULT001",
                                  message))

    @staticmethod
    def _walk_frame(node):
        """Descendants of ``node`` within the same frame: nested defs
        are fresh frames — a sleep in an inner callback is not paced
        by THIS loop."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef,
                                ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def _check_loop(self, loop) -> None:
        # a retry loop: the loop body catches failures and goes
        # around again
        has_try = False
        sleeps: List[ast.Call] = []
        for sub in self._walk_frame(loop):
            if isinstance(sub, ast.Try) and sub.handlers:
                has_try = True
            if isinstance(sub, ast.Call) and _is_sleep_call(sub) \
                    and id(sub) not in self._seen:
                sleeps.append(sub)
        if not has_try:
            return
        for call in sleeps:
            self._seen.add(id(call))
            self._emit(
                call,
                "fixed sleep inside a retry loop (try/except at "
                f"loop line {loop.lineno}): pace retries with "
                "common/backoff.py Backoff (jittered + deadline-"
                "budgeted), not a literal interval",
                loop.lineno)

    def visit_While(self, node: ast.While) -> None:
        self._check_loop(node)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._check_loop(node)
        self.generic_visit(node)


def lint_file(path: pathlib.Path,
              root: Optional[pathlib.Path] = None) -> List[Violation]:
    rel = str(path if root is None else path.relative_to(root))
    if any(rel.endswith(f) for f in ALLOW_RAW_FILES):
        return []
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [Violation(rel, e.lineno or 0, "FAULT000",
                          f"unparseable: {e.msg}")]
    linter = _FileLinter(str(path), rel, src)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: v.line)


def lint_paths(paths: Iterable[pathlib.Path]) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            root = p.parent
            for f in sorted(p.rglob("*.py")):
                out.extend(lint_file(f, root=root))
        else:
            out.extend(lint_file(p))
    return out


def main(argv: List[str]) -> int:
    targets = [pathlib.Path(a) for a in argv] or \
        [pathlib.Path(__file__).resolve().parents[1] / "ceph_tpu"]
    violations = lint_paths(targets)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} fault-lint violation(s)")
        return 1
    print("fault lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
