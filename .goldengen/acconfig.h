/* stub acconfig.h for building the reference CRUSH core standalone */
#ifndef GOLDEN_ACCONFIG_H
#define GOLDEN_ACCONFIG_H
#endif
