/*
 * Golden-vector generator for the TPU-native CRUSH reimplementation.
 *
 * Compiles the *reference* CRUSH C core (read-only mount at
 * /root/reference/src/crush) and dumps, as JSON data:
 *   - crush_hash32_{1..5} vectors
 *   - crush_ln(x) for all x in [0, 0xffff]
 *   - the __RH_LH_tbl / __LL_tbl fixed-point log tables (numeric data)
 *   - several maps (in our own JSON map schema) with crush_do_rule
 *     results over x ranges, rules, numreps and weight vectors
 *   - a single-thread CPU throughput measurement of crush_do_rule on a
 *     10k-OSD map (the measured baseline for bench.py vs_baseline)
 *
 * Only JSON *data* produced by this program is committed; this scratch
 * directory is gitignored.
 */
#include <stdio.h>
#include <stdarg.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

/* pull in the reference implementation (static fns become visible) */
#include "mapper.c"
#include "builder.h"

/* hash.c / builder.c / crush.c are compiled separately and linked */

static FILE *out;

static FILE *xfopen(const char *p)
{
	FILE *f = fopen(p, "w");
	if (!f) { fprintf(stderr, "cannot open %s\n", p); exit(1); }
	return f;
}

static void emit(const char *fmt, ...)
{
	va_list ap;
	va_start(ap, fmt);
	vfprintf(out, fmt, ap);
	va_end(ap);
}

/* ---------- JSON helpers ---------- */
static void emit_i32_array(const char *name, const int *v, int n)
{
	int i;
	emit("\"%s\": [", name);
	for (i = 0; i < n; i++)
		emit("%s%d", i ? "," : "", v[i]);
	emit("]");
}
static void emit_u32_array(const char *name, const __u32 *v, int n)
{
	int i;
	emit("\"%s\": [", name);
	for (i = 0; i < n; i++)
		emit("%s%u", i ? "," : "", v[i]);
	emit("]");
}

/* ---------- map construction helpers ---------- */

struct testmap {
	struct crush_map *map;
	struct crush_choose_arg *choose_args; /* may be NULL */
};

static int add_bucket(struct crush_map *map, int alg, int type,
		      int size, int *items, int *weights, int *id_out)
{
	struct crush_bucket *b =
		crush_make_bucket(map, alg, CRUSH_HASH_RJENKINS1, type,
				  size, items, weights);
	if (!b) { fprintf(stderr, "make_bucket failed\n"); exit(1); }
	int r = crush_add_bucket(map, 0, b, id_out);
	if (r < 0) { fprintf(stderr, "add_bucket failed\n"); exit(1); }
	return *id_out;
}

/* build an H-level hierarchy: nroot children per level ... leaves are osds.
 * returns root bucket id.  types: osd=0, level1=1, ... root=levels  */
static int build_tree_map(struct crush_map *map, int alg,
			  int levels, const int *fanout /* len=levels */,
			  int *osd_count_out, const int *osd_weights /* or NULL */)
{
	/* recursively build */
	int next_osd = 0;
	int build(int level) { /* gcc nested fn, fine for the generator */
		if (level == 0)
			return next_osd++;
		int n = fanout[level - 1];
		int items[n], weights[n];
		for (int i = 0; i < n; i++) {
			items[i] = build(level - 1);
			if (level == 1)
				weights[i] = osd_weights ? osd_weights[items[i]]
							 : 0x10000;
			else
				weights[i] = 0; /* fixed up by make_bucket: it
						   sums child weights only for
						   leaf weights we pass; for
						   bucket children pass their
						   weight */
		}
		if (level > 1) {
			/* child bucket weights: look them up */
			for (int i = 0; i < n; i++) {
				struct crush_bucket *cb =
					map->buckets[-1 - items[i]];
				weights[i] = cb->weight;
			}
		}
		int id;
		add_bucket(map, alg, level, n, items, weights, &id);
		return id;
	}
	int root = build(levels);
	*osd_count_out = next_osd;
	return root;
}

static struct crush_rule *mk_rule(struct crush_map *map, int len)
{
	struct crush_rule *r = crush_make_rule(len, 1);
	if (!r) exit(1);
	return r;
}

/* ---------- dumping a map in our JSON schema ---------- */
static void dump_bucket(struct crush_bucket *b, int first)
{
	emit("%s{\"id\": %d, \"alg\": %d, \"hash\": %d, \"type\": %d, "
	     "\"weight\": %u, \"size\": %u, ",
	     first ? "" : ",", b->id, b->alg, b->hash, b->type, b->weight,
	     b->size);
	emit_i32_array("items", b->items, b->size);
	switch (b->alg) {
	case CRUSH_BUCKET_UNIFORM: {
		struct crush_bucket_uniform *u = (void *)b;
		emit(", \"item_weight\": %u", u->item_weight);
		break;
	}
	case CRUSH_BUCKET_LIST: {
		struct crush_bucket_list *l = (void *)b;
		emit(", ");
		emit_u32_array("item_weights", l->item_weights, b->size);
		emit(", ");
		emit_u32_array("sum_weights", l->sum_weights, b->size);
		break;
	}
	case CRUSH_BUCKET_TREE: {
		struct crush_bucket_tree *t = (void *)b;
		emit(", \"num_nodes\": %d, ", t->num_nodes);
		emit_u32_array("node_weights", t->node_weights, t->num_nodes);
		break;
	}
	case CRUSH_BUCKET_STRAW: {
		struct crush_bucket_straw *s = (void *)b;
		emit(", ");
		emit_u32_array("item_weights", s->item_weights, b->size);
		emit(", ");
		emit_u32_array("straws", s->straws, b->size);
		break;
	}
	case CRUSH_BUCKET_STRAW2: {
		struct crush_bucket_straw2 *s = (void *)b;
		emit(", ");
		emit_u32_array("item_weights", s->item_weights, b->size);
		break;
	}
	}
	emit("}");
}

static void dump_map(struct crush_map *map, struct crush_choose_arg *cargs)
{
	int i, j, first;
	emit("\"map\": {");
	emit("\"max_devices\": %d, \"max_buckets\": %d, \"max_rules\": %u, ",
	     map->max_devices, map->max_buckets, map->max_rules);
	emit("\"tunables\": {\"choose_local_tries\": %u, "
	     "\"choose_local_fallback_tries\": %u, \"choose_total_tries\": %u, "
	     "\"chooseleaf_descend_once\": %u, \"chooseleaf_vary_r\": %u, "
	     "\"chooseleaf_stable\": %u}, ",
	     map->choose_local_tries, map->choose_local_fallback_tries,
	     map->choose_total_tries, map->chooseleaf_descend_once,
	     map->chooseleaf_vary_r, map->chooseleaf_stable);
	emit("\"buckets\": [");
	first = 1;
	for (i = 0; i < map->max_buckets; i++) {
		if (!map->buckets[i])
			continue;
		dump_bucket(map->buckets[i], first);
		first = 0;
	}
	emit("], \"rules\": [");
	first = 1;
	for (i = 0; i < (int)map->max_rules; i++) {
		struct crush_rule *r = map->rules[i];
		if (!r)
			continue;
		emit("%s{\"ruleno\": %d, \"steps\": [", first ? "" : ",", i);
		for (j = 0; j < (int)r->len; j++)
			emit("%s[%u,%d,%d]", j ? "," : "", r->steps[j].op,
			     r->steps[j].arg1, r->steps[j].arg2);
		emit("]}");
		first = 0;
	}
	emit("]");
	if (cargs) {
		emit(", \"choose_args\": [");
		first = 1;
		for (i = 0; i < map->max_buckets; i++) {
			struct crush_choose_arg *a = &cargs[i];
			if (!map->buckets[i])
				continue;
			emit("%s{\"bucket_index\": %d", first ? "" : ",", i);
			if (a->ids) {
				emit(", ");
				emit_i32_array("ids", a->ids, a->ids_size);
			}
			if (a->weight_set) {
				emit(", \"weight_set\": [");
				for (j = 0; j < (int)a->weight_set_positions; j++) {
					emit("%s[", j ? "," : "");
					for (unsigned k = 0; k < a->weight_set[j].size; k++)
						emit("%s%u", k ? "," : "",
						     a->weight_set[j].weights[k]);
					emit("]");
				}
				emit("]");
			}
			emit("}");
			first = 0;
		}
		emit("]");
	}
	emit("}");
}

/* ---------- run do_rule over a range and dump results ---------- */
static void run_cases(struct crush_map *map, struct crush_choose_arg *cargs,
		      const __u32 *weight, int weight_max,
		      int ruleno, int numrep, int x0, int x1)
{
	int *result = malloc(sizeof(int) * (numrep + 8) * 4);
	char *cwin = malloc(map->working_size + sizeof(int) * 3 * numrep);
	emit("{\"ruleno\": %d, \"numrep\": %d, \"x0\": %d, \"x1\": %d, ",
	     ruleno, numrep, x0, x1);
	emit_u32_array("weight", weight, weight_max);
	emit(", \"results\": [");
	for (int x = x0; x < x1; x++) {
		crush_init_workspace(map, cwin);
		int n = crush_do_rule(map, ruleno, x, result, numrep,
				      weight, weight_max, cwin, cargs);
		emit("%s[", x == x0 ? "" : ",");
		for (int i = 0; i < n; i++)
			emit("%s%d", i ? "," : "", result[i]);
		emit("]");
	}
	emit("]}");
	free(result); free(cwin);
}

/* weight vector builders */
static void w_fill(__u32 *w, int n, __u32 v) { for (int i = 0; i < n; i++) w[i] = v; }

/* ---------- main ---------- */
int main(int argc, char **argv)
{
	const char *outdir = argc > 1 ? argv[1] : ".";
	char path[512];

	/* ===== 1. hash goldens ===== */
	snprintf(path, sizeof(path), "%s/hash.json", outdir);
	out = xfopen(path);
	emit("{\"seed\": %u, \"cases\": [", 1315423911u);
	__u32 inputs[] = {0, 1, 2, 3, 12345, 0x7fffffff, 0x80000000u,
			  0xffffffffu, 0xdeadbeefu, 1315423911u, 65535, 65536};
	int ni = sizeof(inputs) / sizeof(inputs[0]);
	int first = 1;
	for (int i = 0; i < ni; i++)
		for (int j = 0; j < ni; j++) {
			__u32 a = inputs[i], b = inputs[j];
			emit("%s[%u,%u,%u,%u,%u,%u,%u]", first ? "" : ",",
			     a, b,
			     crush_hash32(CRUSH_HASH_RJENKINS1, a),
			     crush_hash32_2(CRUSH_HASH_RJENKINS1, a, b),
			     crush_hash32_3(CRUSH_HASH_RJENKINS1, a, b, a ^ b),
			     crush_hash32_4(CRUSH_HASH_RJENKINS1, a, b, a + b, a - b),
			     crush_hash32_5(CRUSH_HASH_RJENKINS1, a, b, a + b, a - b, a * 3 + b));
			first = 0;
		}
	emit("]}");
	fclose(out);

	/* ===== 2. crush_ln sweep + tables ===== */
	snprintf(path, sizeof(path), "%s/crush_ln.json", outdir);
	out = xfopen(path);
	emit("{\"ln\": [");
	for (int x = 0; x <= 0xffff; x++)
		emit("%s%llu", x ? "," : "", (unsigned long long)crush_ln(x));
	emit("], \"RH_LH_tbl\": [");
	for (int i = 0; i < 128 * 2 + 2; i++)
		emit("%s%lld", i ? "," : "", (long long)__RH_LH_tbl[i]);
	emit("], \"LL_tbl\": [");
	for (int i = 0; i < 256; i++)
		emit("%s%lld", i ? "," : "", (long long)__LL_tbl[i]);
	emit("]}");
	fclose(out);

	/* ===== 3. maps + do_rule goldens ===== */

	/* --- M1: flat 12-osd straw2, mixed weights --- */
	{
		struct crush_map *m = crush_create();
		int items[12], weights[12];
		for (int i = 0; i < 12; i++) {
			items[i] = i;
			weights[i] = 0x10000;
		}
		weights[3] = 0x18000;  /* 1.5 */
		weights[7] = 0x8000;   /* 0.5 */
		weights[11] = 0x20000; /* 2.0 */
		int root;
		add_bucket(m, CRUSH_BUCKET_STRAW2, 1, 12, items, weights, &root);
		struct crush_rule *r = mk_rule(m, 3);
		crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSE_FIRSTN, 0, 0);
		crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r, 0);
		struct crush_rule *r2 = mk_rule(m, 3);
		crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSE_INDEP, 0, 0);
		crush_rule_set_step(r2, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r2, 1);
		crush_finalize(m);

		snprintf(path, sizeof(path), "%s/map_flat12.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		__u32 w[12];
		w_fill(w, 12, 0x10000);
		run_cases(m, NULL, w, 12, 0, 3, 0, 1024); emit(",");
		run_cases(m, NULL, w, 12, 1, 4, 0, 1024); emit(",");
		/* osd.2 out, osd.5 half-out */
		w[2] = 0; w[5] = 0x8000;
		run_cases(m, NULL, w, 12, 0, 3, 0, 1024); emit(",");
		run_cases(m, NULL, w, 12, 1, 6, 0, 1024);
		emit("]}");
		fclose(out);
		crush_destroy(m);
	}

	/* --- M2: 3-level hierarchy (3 racks x 3 hosts x 4 osds) straw2 --- */
	{
		struct crush_map *m = crush_create();
		int fanout[3] = {4, 3, 3}; /* level1(host)=4 osds, level2(rack)=3 hosts, level3(root)=3 racks */
		int nosd;
		int osd_w[36];
		for (int i = 0; i < 36; i++)
			osd_w[i] = 0x10000 + (i % 5) * 0x4000; /* varied */
		int root = build_tree_map(m, CRUSH_BUCKET_STRAW2, 3, fanout,
					  &nosd, osd_w);
		/* rule 0: replicated chooseleaf firstn over racks */
		struct crush_rule *r = mk_rule(m, 3);
		crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 2);
		crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r, 0);
		/* rule 1: EC chooseleaf indep over hosts */
		struct crush_rule *r1 = mk_rule(m, 3);
		crush_rule_set_step(r1, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r1, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
		crush_rule_set_step(r1, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r1, 1);
		/* rule 2: two-step choose: 2 racks then 2 hosts then osds */
		struct crush_rule *r2 = mk_rule(m, 5);
		crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r2, 1, CRUSH_RULE_CHOOSE_FIRSTN, 2, 2);
		crush_rule_set_step(r2, 2, CRUSH_RULE_CHOOSE_FIRSTN, 2, 1);
		crush_rule_set_step(r2, 3, CRUSH_RULE_CHOOSELEAF_FIRSTN, 1, 0);
		crush_rule_set_step(r2, 4, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r2, 2);
		/* rule 3: indep with set_chooseleaf_tries + set_choose_tries */
		struct crush_rule *r3 = mk_rule(m, 5);
		crush_rule_set_step(r3, 0, CRUSH_RULE_SET_CHOOSE_TRIES, 100, 0);
		crush_rule_set_step(r3, 1, CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5, 0);
		crush_rule_set_step(r3, 2, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r3, 3, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
		crush_rule_set_step(r3, 4, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r3, 3);
		/* rule 4: multi-take/emit */
		struct crush_rule *r4 = mk_rule(m, 6);
		int rack0 = m->buckets[-1 - root]->items[0];
		int rack1 = m->buckets[-1 - root]->items[1];
		crush_rule_set_step(r4, 0, CRUSH_RULE_TAKE, rack0, 0);
		crush_rule_set_step(r4, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1);
		crush_rule_set_step(r4, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_rule_set_step(r4, 3, CRUSH_RULE_TAKE, rack1, 0);
		crush_rule_set_step(r4, 4, CRUSH_RULE_CHOOSELEAF_FIRSTN, 1, 1);
		crush_rule_set_step(r4, 5, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r4, 4);
		crush_finalize(m);

		snprintf(path, sizeof(path), "%s/map_tree3.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		__u32 w[36];
		w_fill(w, 36, 0x10000);
		run_cases(m, NULL, w, 36, 0, 3, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 1, 6, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 2, 4, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 3, 6, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 4, 3, 0, 512); emit(",");
		/* failures: one host down (osds 4..7), a few singles */
		for (int i = 4; i < 8; i++) w[i] = 0;
		w[17] = 0; w[30] = 0x4000;
		run_cases(m, NULL, w, 36, 0, 3, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 1, 6, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 3, 6, 0, 512);
		emit("]}");
		fclose(out);

		/* --- M7: same topology + choose_args --- */
		struct crush_choose_arg *ca = crush_make_choose_args(m, 2);
		/* perturb the weight sets & ids to be different from defaults */
		for (int b = 0; b < m->max_buckets; b++) {
			if (!m->buckets[b]) continue;
			struct crush_choose_arg *a = &ca[b];
			for (unsigned p = 0; p < a->weight_set_positions; p++)
				for (unsigned k = 0; k < a->weight_set[p].size; k++) {
					__u32 wv = a->weight_set[p].weights[k];
					a->weight_set[p].weights[k] =
						wv - (wv >> (2 + p + (k & 1)));
				}
			/* remap ids for leaf buckets only (type 1 = host):
			 * mimic the balancer's pseudo-id trick */
			if (m->buckets[b]->type == 1)
				for (unsigned k = 0; k < a->ids_size; k++)
					a->ids[k] = a->ids[k] + 1000;
		}
		snprintf(path, sizeof(path), "%s/map_tree3_chooseargs.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, ca);
		emit(", \"cases\": [");
		w_fill(w, 36, 0x10000);
		run_cases(m, ca, w, 36, 0, 3, 0, 512); emit(",");
		run_cases(m, ca, w, 36, 1, 6, 0, 512); emit(",");
		run_cases(m, ca, w, 36, 2, 4, 0, 512);
		emit("]}");
		fclose(out);
		crush_destroy_choose_args(ca);

		/* --- M6: legacy tunables on same topology --- */
		set_legacy_crush_map(m);
		m->allowed_bucket_algs |= (1 << CRUSH_BUCKET_STRAW2);
		crush_finalize(m);
		snprintf(path, sizeof(path), "%s/map_tree3_legacy.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		w_fill(w, 36, 0x10000);
		run_cases(m, NULL, w, 36, 0, 3, 0, 512); emit(",");
		run_cases(m, NULL, w, 36, 1, 6, 0, 512); emit(",");
		for (int i = 4; i < 8; i++) w[i] = 0;
		run_cases(m, NULL, w, 36, 0, 3, 0, 512);
		emit("]}");
		fclose(out);
		crush_destroy(m);
	}

	/* --- M3/M4/M5: uniform / list / straw hierarchies --- */
	int algs[3] = {CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST,
		       CRUSH_BUCKET_STRAW};
	const char *algname[3] = {"uniform", "list", "straw"};
	for (int ai = 0; ai < 3; ai++) {
		struct crush_map *m = crush_create();
		int fanout[2] = {4, 4}; /* 4 hosts x 4 osds */
		int nosd;
		/* uniform requires equal weights within a bucket */
		int osd_w[16];
		for (int i = 0; i < 16; i++)
			osd_w[i] = (algs[ai] == CRUSH_BUCKET_UNIFORM)
					   ? 0x10000
					   : 0x10000 + (i % 4) * 0x6000;
		int root = build_tree_map(m, algs[ai], 2, fanout, &nosd, osd_w);
		struct crush_rule *r = mk_rule(m, 3);
		crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
		crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r, 0);
		struct crush_rule *r1 = mk_rule(m, 3);
		crush_rule_set_step(r1, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r1, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
		crush_rule_set_step(r1, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r1, 1);
		crush_finalize(m);
		snprintf(path, sizeof(path), "%s/map_%s.json", outdir,
			 algname[ai]);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		__u32 w[16];
		w_fill(w, 16, 0x10000);
		run_cases(m, NULL, w, 16, 0, 3, 0, 512); emit(",");
		run_cases(m, NULL, w, 16, 1, 4, 0, 512); emit(",");
		w[1] = 0; w[9] = 0;
		run_cases(m, NULL, w, 16, 0, 3, 0, 512); emit(",");
		run_cases(m, NULL, w, 16, 1, 4, 0, 512);
		emit("]}");
		fclose(out);
		crush_destroy(m);
	}

	/* --- M8: weird cases: empty-ish buckets, N_MINUS, big numrep --- */
	{
		struct crush_map *m = crush_create();
		int items[6], weights[6];
		for (int i = 0; i < 6; i++) { items[i] = i; weights[i] = 0x10000; }
		weights[4] = 0; weights[5] = 0; /* zero-weight items in bucket */
		int hostA, hostB, root;
		add_bucket(m, CRUSH_BUCKET_STRAW2, 1, 6, items, weights, &hostA);
		int itemsB[2] = {6, 7};
		int weightsB[2] = {0x10000, 0x30000};
		add_bucket(m, CRUSH_BUCKET_STRAW2, 1, 2, itemsB, weightsB, &hostB);
		int ritems[2] = {hostA, hostB};
		int rweights[2];
		rweights[0] = m->buckets[-1 - hostA]->weight;
		rweights[1] = m->buckets[-1 - hostB]->weight;
		add_bucket(m, CRUSH_BUCKET_STRAW2, 2, 2, ritems, rweights, &root);
		/* rule 0: numrep larger than available leaves */
		struct crush_rule *r = mk_rule(m, 3);
		crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
		crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r, 0);
		/* rule 1: N_MINUS(-1) */
		struct crush_rule *r1 = mk_rule(m, 3);
		crush_rule_set_step(r1, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r1, 1, CRUSH_RULE_CHOOSE_INDEP, -1, 1);
		crush_rule_set_step(r1, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r1, 1);
		/* rule 2: take a device directly (degenerate) */
		struct crush_rule *r2 = mk_rule(m, 2);
		crush_rule_set_step(r2, 0, CRUSH_RULE_TAKE, 0, 0);
		crush_rule_set_step(r2, 1, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r2, 2);
		crush_finalize(m);
		snprintf(path, sizeof(path), "%s/map_weird.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		__u32 w[8];
		w_fill(w, 8, 0x10000);
		run_cases(m, NULL, w, 8, 0, 8, 0, 512); emit(",");
		run_cases(m, NULL, w, 8, 1, 4, 0, 512); emit(",");
		run_cases(m, NULL, w, 8, 2, 3, 0, 128); emit(",");
		w[0] = 0; w[6] = 0x2000;
		run_cases(m, NULL, w, 8, 0, 8, 0, 512);
		emit("]}");
		fclose(out);
		crush_destroy(m);
	}

	/* --- M9: 10k-OSD map: 20 racks x 25 hosts x 20 osds --- */
	{
		struct crush_map *m = crush_create();
		int fanout[3] = {20, 25, 20};
		int nosd;
		int root = build_tree_map(m, CRUSH_BUCKET_STRAW2, 3, fanout,
					  &nosd, NULL);
		struct crush_rule *r = mk_rule(m, 3);
		crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
		crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r, 0);
		struct crush_rule *r1 = mk_rule(m, 3);
		crush_rule_set_step(r1, 0, CRUSH_RULE_TAKE, root, 0);
		crush_rule_set_step(r1, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
		crush_rule_set_step(r1, 2, CRUSH_RULE_EMIT, 0, 0);
		crush_add_rule(m, r1, 1);
		crush_finalize(m);
		fprintf(stderr, "10k map: %d osds, working_size %zu\n", nosd,
			m->working_size);

		__u32 *w = malloc(sizeof(__u32) * nosd);
		w_fill(w, nosd, 0x10000);
		/* golden sample */
		snprintf(path, sizeof(path), "%s/map_big10k.json", outdir);
		out = xfopen(path);
		emit("{");
		dump_map(m, NULL);
		emit(", \"cases\": [");
		run_cases(m, NULL, w, nosd, 0, 3, 0, 256); emit(",");
		run_cases(m, NULL, w, nosd, 1, 11, 0, 256);
		emit("]}");
		fclose(out);

		/* CPU throughput measurement (single thread), numrep=3,
		 * mirrors the CrushTester x-loop (CrushTester.cc:573) */
		{
			int result[3];
			char *cwin = malloc(m->working_size + sizeof(int) * 3 * 3);
			struct timespec t0, t1;
			int iters = 200000;
			long long acc = 0;
			clock_gettime(CLOCK_MONOTONIC, &t0);
			for (int x = 0; x < iters; x++) {
				crush_init_workspace(m, cwin);
				int n = crush_do_rule(m, 0, x, result, 3, w,
						      nosd, cwin, NULL);
				acc += n ? result[0] : 0;
			}
			clock_gettime(CLOCK_MONOTONIC, &t1);
			double dt = (t1.tv_sec - t0.tv_sec) +
				    (t1.tv_nsec - t0.tv_nsec) * 1e-9;
			snprintf(path, sizeof(path), "%s/cpu_baseline.json",
				 outdir);
			out = xfopen(path);
			emit("{\"config\": \"10k-osd 3-level straw2, chooseleaf firstn numrep=3\", "
			     "\"iters\": %d, \"seconds\": %.6f, "
			     "\"mappings_per_sec\": %.1f, \"checksum\": %lld}",
			     iters, dt, iters / dt, acc);
			fclose(out);
			free(cwin);
		}
		free(w);
		crush_destroy(m);
	}

	fprintf(stderr, "golden generation done\n");
	return 0;
}
