// crush_host — the native batched host mapper.
//
// The framework's hot host-side loop (tools' scalar sweeps, the bench's
// CPU fallback, balancer candidate evaluation) implemented in C++
// against the SAME flat SoA map encoding the TPU mapper consumes
// (ceph_tpu/crush/map_arrays.py) — not the reference's pointer-forest
// bucket structs.  Semantics are a re-derivation of this repo's own
// executable specification (ceph_tpu/crush/mapper_ref.py, itself
// golden-tested against the reference C core): rjenkins mix draws,
// fixed-point straw2 via the shared ln LUT, all five bucket algorithms,
// firstn retry descent and positionally-stable indep, the full rule VM
// with tunables.  Built as a shared library; loaded via ctypes
// (ceph_tpu/crush/native.py) with a pure-Python fallback when absent.

#include <cstdint>
#include <cstring>
#include <vector>

#include "crush_ln_tables.h"

namespace {

constexpr uint32_t kHashSeed = 0x4E67C6A7u;  // 1315423911
constexpr int32_t kItemUndef = 0x7FFFFFFE;
constexpr int32_t kItemNone = 0x7FFFFFFF;
constexpr int64_t kS64Min = INT64_MIN;

constexpr int kAlgUniform = 1;
constexpr int kAlgList = 2;
constexpr int kAlgTree = 3;
constexpr int kAlgStraw = 4;
constexpr int kAlgStraw2 = 5;

constexpr int kOpTake = 1;
constexpr int kOpChooseFirstn = 2;
constexpr int kOpChooseIndep = 3;
constexpr int kOpEmit = 4;
constexpr int kOpChooseleafFirstn = 6;
constexpr int kOpChooseleafIndep = 7;
constexpr int kOpSetChooseTries = 8;
constexpr int kOpSetChooseleafTries = 9;
constexpr int kOpSetChooseLocalTries = 10;
constexpr int kOpSetChooseLocalFallbackTries = 11;
constexpr int kOpSetChooseleafVaryR = 12;
constexpr int kOpSetChooseleafStable = 13;

// ---- rjenkins mix (the one every draw goes through) -----------------------

inline void mix(uint32_t& a, uint32_t& b, uint32_t& c) {
  a = a - b - c; a ^= c >> 13;
  b = b - c - a; b ^= a << 8;
  c = c - a - b; c ^= b >> 13;
  a = a - b - c; a ^= c >> 12;
  b = b - c - a; b ^= a << 16;
  c = c - a - b; c ^= b >> 5;
  a = a - b - c; a ^= c >> 3;
  b = b - c - a; b ^= a << 10;
  c = c - a - b; c ^= b >> 15;
}

inline uint32_t hash2(uint32_t a, uint32_t b) {
  uint32_t h = kHashSeed ^ a ^ b;
  uint32_t x = 231232, y = 1232;
  mix(a, b, h);
  mix(x, a, h);
  mix(b, y, h);
  return h;
}

inline uint32_t hash3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t h = kHashSeed ^ a ^ b ^ c;
  uint32_t x = 231232, y = 1232;
  mix(a, b, h);
  mix(c, x, h);
  mix(y, a, h);
  mix(b, x, h);
  mix(y, c, h);
  return h;
}

inline uint32_t hash4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
  uint32_t h = kHashSeed ^ a ^ b ^ c ^ d;
  uint32_t x = 231232, y = 1232;
  mix(a, b, h);
  mix(c, d, h);
  mix(a, x, h);
  mix(y, b, h);
  mix(c, x, h);
  mix(y, d, h);
  return h;
}

// ---- fixed-point 2^44*log2 via the shared LUT -----------------------------

inline uint64_t crush_ln(uint32_t xin) {
  uint32_t x = xin + 1;
  int iexpon = 15;
  if (!(x & 0x18000)) {
    int bits = 0;
    uint32_t v = x & 0x1FFFF;
    while (v) { bits++; v >>= 1; }
    bits = 16 - bits;
    x <<= bits;
    iexpon = 15 - bits;
  }
  uint32_t index1 = (x >> 8) << 1;
  uint64_t rh = CRUSH_RH_LH_TBL[index1 - 256];
  uint64_t lh = CRUSH_RH_LH_TBL[index1 + 1 - 256];
  uint64_t xl64 = ((uint64_t)x * rh) >> 48;
  uint32_t index2 = xl64 & 0xFF;
  lh = (lh + CRUSH_LL_TBL[index2]) >> (48 - 12 - 32);
  return ((uint64_t)iexpon << (12 + 32)) + lh;
}

inline int64_t straw2_draw(uint32_t x, int32_t item_id, uint32_t r,
                           uint32_t weight) {
  if (weight == 0) return kS64Min;
  uint32_t u = hash3(x, (uint32_t)item_id, r) & 0xFFFF;
  int64_t ln = (int64_t)crush_ln(u) - 0x1000000000000LL;
  // truncation toward zero on a negative numerator: native C++ division
  return ln / (int64_t)weight;
}

// ---- the SoA map view -----------------------------------------------------

struct MapView {
  int B, S, N, P, max_devices;
  const int32_t *alg, *btype, *bhash, *size, *nnodes;
  const int32_t *items;         // [B,S]
  const uint32_t *weights;      // [B,S]
  const uint32_t *sum_weights;  // [B,S]
  const uint32_t *straws;       // [B,S]
  const uint32_t *node_weights; // [B,N]
  const int32_t *arg_ids;       // [B,S]
  const uint32_t *arg_weights;  // [B,P,S]
  const uint8_t *has_arg;       // [B]

  bool valid_bucket(int32_t id) const {
    int idx = -1 - id;
    return id < 0 && idx < B && alg[idx] != 0;
  }
  int idx(int32_t id) const { return -1 - id; }
};

struct Tunables {
  int local_tries, local_fallback_tries, total_tries, descend_once,
      vary_r, stable;
};

// per-x workspace: uniform-bucket permutation state
struct PermState {
  uint32_t perm_x = 0;
  uint32_t perm_n = 0;
  std::vector<int> perm;
};

struct Workspace {
  std::vector<PermState> perm;  // indexed by bucket index
  explicit Workspace(int B) : perm(B) {}
};

// ---- bucket choose methods ------------------------------------------------

int bucket_perm_choose(const MapView& m, int bi, PermState& ws,
                       uint32_t x, uint32_t r) {
  int size = m.size[bi];
  int32_t id = -1 - bi;
  uint32_t pr = r % size;
  if (ws.perm.empty()) {
    ws.perm.resize(m.S);
    for (int i = 0; i < m.S; i++) ws.perm[i] = i;
  }
  if (ws.perm_x != x || ws.perm_n == 0) {
    ws.perm_x = x;
    if (pr == 0) {
      int s = hash3(x, (uint32_t)id, 0) % size;
      ws.perm[0] = s;
      ws.perm_n = 0xFFFF;
      return m.items[bi * m.S + s];
    }
    for (int i = 0; i < size; i++) ws.perm[i] = i;
    ws.perm_n = 0;
  } else if (ws.perm_n == 0xFFFF) {
    for (int i = 1; i < size; i++) ws.perm[i] = i;
    ws.perm[ws.perm[0]] = 0;
    ws.perm_n = 1;
  }
  while (ws.perm_n <= pr) {
    unsigned p = ws.perm_n;
    if ((int)p < size - 1) {
      unsigned i = hash3(x, (uint32_t)id, p) % (size - p);
      if (i) {
        int t = ws.perm[p + i];
        ws.perm[p + i] = ws.perm[p];
        ws.perm[p] = t;
      }
    }
    ws.perm_n++;
  }
  return m.items[bi * m.S + ws.perm[pr]];
}

int bucket_list_choose(const MapView& m, int bi, uint32_t x, uint32_t r) {
  int32_t id = -1 - bi;
  for (int i = m.size[bi] - 1; i >= 0; i--) {
    uint64_t w = hash4(x, (uint32_t)m.items[bi * m.S + i], r,
                       (uint32_t)id) & 0xFFFF;
    w = (w * m.sum_weights[bi * m.S + i]) >> 16;
    if (w < m.weights[bi * m.S + i]) return m.items[bi * m.S + i];
  }
  return m.items[bi * m.S + 0];
}

int bucket_tree_choose(const MapView& m, int bi, uint32_t x, uint32_t r) {
  int32_t id = -1 - bi;
  int n = m.nnodes[bi] >> 1;
  while (!(n & 1)) {
    uint32_t w = m.node_weights[bi * m.N + n];
    uint64_t t = (uint64_t)hash4(x, (uint32_t)n, r, (uint32_t)id) * w;
    t >>= 32;
    int h = 0, nn = n;
    while ((nn & 1) == 0) { h++; nn >>= 1; }
    int left = n - (1 << (h - 1));
    n = (t < m.node_weights[bi * m.N + left]) ? left
                                              : n + (1 << (h - 1));
  }
  return m.items[bi * m.S + (n >> 1)];
}

int bucket_straw_choose(const MapView& m, int bi, uint32_t x, uint32_t r) {
  int high = 0;
  uint64_t high_draw = 0;
  for (int i = 0; i < m.size[bi]; i++) {
    uint64_t draw = (uint64_t)(hash3(x,
        (uint32_t)m.items[bi * m.S + i], r) & 0xFFFF)
        * m.straws[bi * m.S + i];
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return m.items[bi * m.S + high];
}

int bucket_straw2_choose(const MapView& m, int bi, uint32_t x, uint32_t r,
                         int position) {
  const int32_t* ids = m.items + bi * m.S;
  const uint32_t* ws = m.weights + bi * m.S;
  if (m.has_arg[bi]) {
    ids = m.arg_ids + bi * m.S;
    int pos = position < m.P ? position : m.P - 1;
    ws = m.arg_weights + ((size_t)bi * m.P + pos) * m.S;
  }
  int high = 0;
  int64_t high_draw = 0;
  for (int i = 0; i < m.size[bi]; i++) {
    int64_t draw = straw2_draw(x, ids[i], r, ws[i]);
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return m.items[bi * m.S + high];
}

int bucket_choose(const MapView& m, Workspace& work, int bi, uint32_t x,
                  uint32_t r, int position) {
  switch (m.alg[bi]) {
    case kAlgUniform:
      return bucket_perm_choose(m, bi, work.perm[bi], x, r);
    case kAlgList:
      return bucket_list_choose(m, bi, x, r);
    case kAlgTree:
      return bucket_tree_choose(m, bi, x, r);
    case kAlgStraw:
      return bucket_straw_choose(m, bi, x, r);
    case kAlgStraw2:
      return bucket_straw2_choose(m, bi, x, r, position);
    default:
      return m.items[bi * m.S + 0];
  }
}

inline bool is_out(const uint32_t* weight, int weight_len, int item,
                   uint32_t x) {
  if (item >= weight_len) return true;
  uint32_t w = weight[item];
  if (w >= 0x10000) return false;
  if (w == 0) return true;
  return (hash2(x, (uint32_t)item) & 0xFFFF) >= w;
}

// ---- firstn retry descent -------------------------------------------------

int choose_firstn(const MapView& m, const Tunables& t, Workspace& work,
                  int bucket_bi, const uint32_t* weight, int weight_len,
                  uint32_t x, int numrep, int type, int32_t* out, int outpos,
                  int out_size, int tries, int recurse_tries,
                  int local_retries, int local_fallback_retries,
                  bool recurse_to_leaf, int vary_r, int stable,
                  int32_t* out2, int parent_r) {
  int count = out_size;
  int rep = stable ? 0 : outpos;
  while (rep < numrep && count > 0) {
    int ftotal = 0;
    bool skip_rep = false;
    int item = 0;
    bool retry_descent = true;
    while (retry_descent) {
      retry_descent = false;
      int in_bi = bucket_bi;
      int flocal = 0;
      bool retry_bucket = true;
      while (retry_bucket) {
        retry_bucket = false;
        bool collide = false, reject = false;
        uint32_t r = rep + parent_r + ftotal;
        if (m.size[in_bi] == 0) {
          reject = true;
        } else {
          if (local_fallback_retries > 0 &&
              flocal >= (m.size[in_bi] >> 1) &&
              flocal > local_fallback_retries) {
            item = bucket_perm_choose(m, in_bi, work.perm[in_bi], x, r);
          } else {
            item = bucket_choose(m, work, in_bi, x, r, outpos);
          }
          if (item >= m.max_devices) {
            skip_rep = true;
            break;
          }
          int itemtype = -1;  // "no such bucket" sentinel
          if (item < 0) {
            if (m.valid_bucket(item)) itemtype = m.btype[m.idx(item)];
          } else {
            itemtype = 0;
          }
          if (itemtype != type) {
            if (item >= 0 || !m.valid_bucket(item)) {
              skip_rep = true;
              break;
            }
            in_bi = m.idx(item);
            retry_bucket = true;
            continue;
          }
          for (int i = 0; i < outpos; i++) {
            if (out[i] == item) {
              collide = true;
              break;
            }
          }
          if (!collide && recurse_to_leaf) {
            if (item < 0) {
              int sub_r = vary_r ? ((int)r >> (vary_r - 1)) : 0;
              int got = choose_firstn(
                  m, t, work, m.idx(item), weight, weight_len, x,
                  stable ? 1 : outpos + 1, 0, out2, outpos, count,
                  recurse_tries, 0, local_retries,
                  local_fallback_retries, false, vary_r, stable,
                  nullptr, sub_r);
              if (got <= outpos) reject = true;
            } else {
              out2[outpos] = item;
            }
          }
          if (!reject && !collide && itemtype == 0) {
            reject = is_out(weight, weight_len, item, x);
          }
        }
        if (reject || collide) {
          ftotal++;
          flocal++;
          if (collide && flocal <= local_retries) {
            retry_bucket = true;
          } else if (local_fallback_retries > 0 &&
                     flocal <= m.size[in_bi] + local_fallback_retries) {
            retry_bucket = true;
          } else if (ftotal < tries) {
            retry_descent = true;
            break;
          } else {
            skip_rep = true;
          }
        }
      }
    }
    if (!skip_rep) {
      out[outpos] = item;
      outpos++;
      count--;
    }
    rep++;
  }
  return outpos;
}

// ---- indep breadth-first variant ------------------------------------------

void choose_indep(const MapView& m, const Tunables& t, Workspace& work,
                  int bucket_bi, const uint32_t* weight, int weight_len,
                  uint32_t x, int left, int numrep, int type, int32_t* out,
                  int outpos, int tries, int recurse_tries,
                  bool recurse_to_leaf, int32_t* out2, int parent_r) {
  int endpos = outpos + left;
  for (int rep = outpos; rep < endpos; rep++) {
    out[rep] = kItemUndef;
    if (out2) out2[rep] = kItemUndef;
  }
  int ftotal = 0;
  while (left > 0 && ftotal < tries) {
    for (int rep = outpos; rep < endpos; rep++) {
      if (out[rep] != kItemUndef) continue;
      int in_bi = bucket_bi;
      for (;;) {
        uint32_t r = rep + parent_r;
        if (m.alg[in_bi] == kAlgUniform && m.size[in_bi] % numrep == 0) {
          r += (numrep + 1) * ftotal;
        } else {
          r += numrep * ftotal;
        }
        if (m.size[in_bi] == 0) break;
        int item = bucket_choose(m, work, in_bi, x, r, outpos);
        if (item >= m.max_devices) {
          out[rep] = kItemNone;
          if (out2) out2[rep] = kItemNone;
          left--;
          break;
        }
        int itemtype = -1;
        if (item < 0) {
          if (m.valid_bucket(item)) itemtype = m.btype[m.idx(item)];
        } else {
          itemtype = 0;
        }
        if (itemtype != type) {
          if (item >= 0 || !m.valid_bucket(item)) {
            out[rep] = kItemNone;
            if (out2) out2[rep] = kItemNone;
            left--;
            break;
          }
          in_bi = m.idx(item);
          continue;
        }
        bool collide = false;
        for (int i = outpos; i < endpos; i++) {
          if (out[i] == item) {
            collide = true;
            break;
          }
        }
        if (collide) break;
        if (recurse_to_leaf) {
          if (item < 0) {
            choose_indep(m, t, work, m.idx(item), weight, weight_len, x,
                         1, numrep, 0, out2, rep, recurse_tries, 0,
                         false, nullptr, r);
            if (out2 && out2[rep] == kItemNone) break;
          } else if (out2) {
            out2[rep] = item;
          }
        }
        if (itemtype == 0 && is_out(weight, weight_len, item, x)) break;
        out[rep] = item;
        left--;
        break;
      }
    }
    ftotal++;
  }
  for (int rep = outpos; rep < endpos; rep++) {
    if (out[rep] == kItemUndef) out[rep] = kItemNone;
    if (out2 && out2[rep] == kItemUndef) out2[rep] = kItemNone;
  }
}

// ---- the rule VM ----------------------------------------------------------

int do_rule_one(const MapView& m, const Tunables& tun, int nsteps,
                const int32_t* steps, const uint32_t* weight,
                int weight_len, uint32_t x, int result_max,
                int32_t* result, Workspace& work) {
  std::vector<int32_t> wv(result_max), ov(result_max), cv(result_max);
  int32_t* w = wv.data();
  int32_t* o = ov.data();
  int32_t* c = cv.data();
  int wsize = 0;
  int result_len = 0;

  int choose_tries = tun.total_tries + 1;  // off-by-one heritage
  int choose_leaf_tries = 0;
  int local_retries = tun.local_tries;
  int local_fallback_retries = tun.local_fallback_tries;
  int vary_r = tun.vary_r;
  int stable = tun.stable;

  for (int s = 0; s < nsteps; s++) {
    int op = steps[s * 3], arg1 = steps[s * 3 + 1],
        arg2 = steps[s * 3 + 2];
    switch (op) {
      case kOpTake:
        if ((arg1 >= 0 && arg1 < m.max_devices) || m.valid_bucket(arg1)) {
          w[0] = arg1;
          wsize = 1;
        }
        break;
      case kOpSetChooseTries:
        if (arg1 > 0) choose_tries = arg1;
        break;
      case kOpSetChooseleafTries:
        if (arg1 > 0) choose_leaf_tries = arg1;
        break;
      case kOpSetChooseLocalTries:
        if (arg1 >= 0) local_retries = arg1;
        break;
      case kOpSetChooseLocalFallbackTries:
        if (arg1 >= 0) local_fallback_retries = arg1;
        break;
      case kOpSetChooseleafVaryR:
        if (arg1 >= 0) vary_r = arg1;
        break;
      case kOpSetChooseleafStable:
        if (arg1 >= 0) stable = arg1;
        break;
      case kOpChooseFirstn:
      case kOpChooseIndep:
      case kOpChooseleafFirstn:
      case kOpChooseleafIndep: {
        if (wsize == 0) break;
        bool firstn =
            (op == kOpChooseFirstn || op == kOpChooseleafFirstn);
        bool to_leaf =
            (op == kOpChooseleafFirstn || op == kOpChooseleafIndep);
        int osize = 0;
        for (int i = 0; i < wsize; i++) {
          int numrep = arg1;
          if (numrep <= 0) {
            numrep += result_max;
            if (numrep <= 0) continue;
          }
          if (w[i] >= 0 || !m.valid_bucket(w[i])) continue;
          int bi = m.idx(w[i]);
          if (firstn) {
            int recurse_tries =
                choose_leaf_tries ? choose_leaf_tries
                                  : (tun.descend_once ? 1 : choose_tries);
            osize += choose_firstn(
                m, tun, work, bi, weight, weight_len, x, numrep, arg2,
                o + osize, 0, result_max - osize, choose_tries,
                recurse_tries, local_retries, local_fallback_retries,
                to_leaf, vary_r, stable, c + osize, 0);
          } else {
            int out_size =
                numrep < result_max - osize ? numrep : result_max - osize;
            choose_indep(m, tun, work, bi, weight, weight_len, x,
                         out_size, numrep, arg2, o + osize, 0,
                         choose_tries,
                         choose_leaf_tries ? choose_leaf_tries : 1,
                         to_leaf, c + osize, 0);
            osize += out_size;
          }
        }
        if (to_leaf) memcpy(o, c, osize * sizeof(int32_t));
        int32_t* tmp = w;
        w = o;
        o = tmp;
        wsize = osize;
        break;
      }
      case kOpEmit:
        for (int i = 0; i < wsize && result_len < result_max; i++) {
          result[result_len++] = w[i];
        }
        wsize = 0;
        break;
      default:
        break;
    }
  }
  return result_len;
}

}  // namespace

extern "C" {

// Map every x in the batch.  Arrays follow the MapArrays layout.
// results: [nx, result_max]; result_lens: [nx].  Returns 0.
int crush_do_rule_batched(
    int B, int S, int N, int P, int max_devices,
    const int32_t* alg, const int32_t* btype, const int32_t* bhash,
    const int32_t* size, const int32_t* nnodes, const int32_t* items,
    const uint32_t* weights, const uint32_t* sum_weights,
    const uint32_t* straws, const uint32_t* node_weights,
    const int32_t* arg_ids, const uint32_t* arg_weights,
    const uint8_t* has_arg,
    int choose_local_tries, int choose_local_fallback_tries,
    int choose_total_tries, int chooseleaf_descend_once,
    int chooseleaf_vary_r, int chooseleaf_stable,
    int nsteps, const int32_t* steps,
    const uint32_t* weight, int weight_len,
    int nx, const uint32_t* xs, int result_max,
    int32_t* results, int32_t* result_lens) {
  MapView m{B, S, N, P, max_devices, alg, btype, bhash, size, nnodes,
            items, weights, sum_weights, straws, node_weights, arg_ids,
            arg_weights, has_arg};
  Tunables t{choose_local_tries, choose_local_fallback_tries,
             choose_total_tries, chooseleaf_descend_once,
             chooseleaf_vary_r, chooseleaf_stable};
  // Each x owns its output row: embarrassingly parallel.  The uniform-
  // bucket permutation workspace is allocated once per THREAD and
  // reused across xs — perm state is keyed by perm_x, so a different x
  // rebuilds on first touch (the same invalidation rule the per-x
  // workspace relied on), and per-x allocation of B PermStates was
  // the dominant overhead on bucket-heavy maps.
#pragma omp parallel
  {
    Workspace work(m.B);
#pragma omp for schedule(dynamic, 256)
    for (int i = 0; i < nx; i++) {
      result_lens[i] = do_rule_one(m, t, nsteps, steps, weight,
                                   weight_len, xs[i], result_max,
                                   results + (size_t)i * result_max,
                                   work);
    }
  }
  return 0;
}

}  // extern "C"

// ---- GF(2^8) table matmul — the EC host/CPU engine ------------------------
//
// The isa-l role on the host side: encode/decode as a GF(2^8) matrix
// applied via 256-entry multiply tables (poly 0x11D, the gf-complete
// default this framework's field uses).  The TPU path is the MXU
// bit-matmul; this is its native CPU twin for benches and host tools.

namespace {

struct GfTables {
  uint8_t mul[256][256];
  GfTables() {
    for (int a = 0; a < 256; a++) {
      for (int b = 0; b < 256; b++) {
        int r = 0, aa = a, bb = b;
        while (bb) {
          if (bb & 1) r ^= aa;
          bb >>= 1;
          aa <<= 1;
          if (aa & 0x100) aa ^= 0x11D;
        }
        mul[a][b] = (uint8_t)r;
      }
    }
  }
};

const GfTables& gf_tables() {
  // function-local static: C++11 guarantees thread-safe one-time
  // construction (ctypes calls arrive GIL-free from many threads)
  static const GfTables t;
  return t;
}

}  // namespace

extern "C" {

// out[rows, L] = mat[rows, k] (GF(2^8)) * data[k, L]
int gf8_matmul(int rows, int k, const uint8_t* mat,
               const uint8_t* data, uint8_t* out, int64_t L) {
  const GfTables& t = gf_tables();
#pragma omp parallel for schedule(static)
  for (int r = 0; r < rows; r++) {
    uint8_t* dst = out + (size_t)r * L;
    std::memset(dst, 0, (size_t)L);
    for (int j = 0; j < k; j++) {
      const uint8_t c = mat[r * k + j];
      if (!c) continue;
      const uint8_t* tab = t.mul[c];
      const uint8_t* src = data + (size_t)j * L;
      for (int64_t i = 0; i < L; i++) dst[i] ^= tab[src[i]];
    }
  }
  return 0;
}

}  // extern "C"

// ---- crc32c (Castagnoli) — slicing-by-8 -----------------------------------
//
// ceph_crc32c semantics (src/common/crc32c.h: seed as passed, no
// final xor).  The Python table walker in ec/stripe.py is the
// bit-exact reference; this is the hot-path engine the OSD data path
// uses per shard write/read/scrub (sctp-style slicing-by-8, ~GB/s).

namespace {

struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    const uint32_t poly = 0x82F63B78u;  // reflected 0x1EDC6F41
    for (int i = 0; i < 256; i++) {
      uint32_t c = (uint32_t)i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
      t[0][i] = c;
    }
    for (int i = 0; i < 256; i++) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; s++) {
        c = t[0][c & 0xFF] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

const Crc32cTables& crc_tables() {
  static const Crc32cTables tabs;
  return tabs;
}

}  // namespace

extern "C" {

uint32_t crc32c_sb8(uint32_t crc, const uint8_t* p, int64_t n) {
  const Crc32cTables& tabs = crc_tables();
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = tabs.t[7][lo & 0xFF] ^ tabs.t[6][(lo >> 8) & 0xFF] ^
          tabs.t[5][(lo >> 16) & 0xFF] ^ tabs.t[4][lo >> 24] ^
          tabs.t[3][hi & 0xFF] ^ tabs.t[2][(hi >> 8) & 0xFF] ^
          tabs.t[1][(hi >> 16) & 0xFF] ^ tabs.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) crc = tabs.t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

}  // extern "C"
