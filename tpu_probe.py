"""Opportunistic TPU probe — land an accelerator number whenever the
flaky TPU tunnel happens to be up.

The driver's bench window has missed the tunnel four rounds running
(BENCH_r01..r04: "backend never initialized").  This probe is the
complement: run it repeatedly across the whole round (``--loop``), and
the moment a quick ``jax.devices()`` subprocess resolves to a real
accelerator, run the measurement stages (speculative + general CRUSH
mapper on the 10k-OSD map with a k_tries x straw2-mode sweep, and the
RS/Pallas EC kernels) and append the timestamped results to
``TPU_PROBE.json`` — committing that artifact immediately so the
evidence survives even if the round ends mid-flight.

Failed attempts are recorded too (timestamped), so "the tunnel never
rose" is itself provable.

Usage:
  python tpu_probe.py              one attempt (quick probe -> stages)
  python tpu_probe.py --loop [s]   probe forever, sleeping s (def 600)
  python tpu_probe.py --worker X   internal subprocess entry

Matches the reference harnesses: crushtool --test hot loop
(src/crush/CrushTester.cc:432-680) and the EC benchmark
(src/test/erasure-code/ceph_erasure_code_benchmark.cc:176-315).
"""

import json
import os
import pathlib
import subprocess
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent
ARTIFACT = REPO / "TPU_PROBE.json"
RESULT_TAG = "BENCH_RESULT "

QUICK_TIMEOUT = float(os.environ.get("CEPH_TPU_PROBE_QUICK_TIMEOUT", 90))
STAGE_DEADLINE = float(os.environ.get("CEPH_TPU_PROBE_STAGE_DEADLINE", 900))


def _now():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _emit(**kw):
    print(RESULT_TAG + json.dumps(kw), flush=True)


# ---------------------------------------------------------------------------
# workers (subprocess side — the only code that imports jax)
# ---------------------------------------------------------------------------

def worker_quick():
    """Resolve the backend and print one line.  Hangs here (killed by
    the parent's timeout) are the tunnel being down."""
    import jax

    d = jax.devices()
    print(json.dumps({"platform": d[0].platform,
                      "n_devices": len(d)}), flush=True)


def _stage_spec(bench, name, plat, k_tries, mode, batch, iters):
    """One speculative-mapper measurement at a given (k_tries, straw2
    mode) point — the sweep the VERDICT asked for (weak #1/#8)."""
    import jax
    import jax.numpy as jnp

    os.environ["CEPH_TPU_STRAW2"] = mode
    from ceph_tpu.crush.mapper_spec import build_spec_rule_fn

    cmap, case = bench._load_case(name)
    t0 = time.perf_counter()
    fn, static, arrays = build_spec_rule_fn(
        cmap, case["ruleno"], case["numrep"], k_tries=k_tries)
    A = jax.tree_util.tree_map(jnp.asarray, arrays)
    weight = jnp.asarray(case["weight_np"])
    xs = jnp.arange(batch, dtype=jnp.uint32)
    res, lens = fn(A, weight, xs)
    res.block_until_ready()
    compile_s = time.perf_counter() - t0
    bench._golden_check(case, res, lens,
                        f"{plat}/{name}/spec-k{k_tries}-{mode}")
    rate, dt = bench._measure_crush(fn, A, weight, batch, iters)
    _emit(stage="crush", map=name, rate=rate, platform=plat,
          engine="xla-spec", k_tries=k_tries, straw2=mode,
          compile_s=round(compile_s, 2), measure_s=round(dt, 3),
          batch=batch, iters=iters)
    return rate


def _stage_pallas_ec(plat, k=8, m=3, chunk=1 << 20, batch=4, iters=8):
    """The fused GF(2) bit-plane matmul Pallas kernel, measured raw —
    the TPU analogue of ISA-L's ec_encode_data hot loop."""
    import numpy as np
    import jax.numpy as jnp

    from ceph_tpu.ec import gf
    from ceph_tpu.ec.pallas_kernels import fused_gf2_matmul_w8

    gfm = gf.rs_vandermonde_matrix(k, m)[k:]     # parity rows only
    bm = jnp.asarray(gf.expand_bitmatrix(gfm))
    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (k, batch * chunk), dtype=np.uint8))
    t0 = time.perf_counter()
    out = fused_gf2_matmul_w8(bm, data)
    out.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fused_gf2_matmul_w8(bm, data)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    _emit(stage="ec_pallas", platform=plat, k=k, m=m,
          encode_gbps=round(k * batch * chunk * iters / dt / 1e9, 3),
          chunk=chunk, compile_s=round(compile_s, 2))


def worker_stages():
    """Full measurement sweep, cheapest-first so every extra second of
    tunnel uptime converts to at least one more landed number."""
    sys.path.insert(0, str(REPO))
    import bench

    t_boot = time.perf_counter()
    import jax

    bench._enable_compile_cache()
    plat = jax.devices()[0].platform
    _emit(stage="init", platform=plat,
          init_s=round(time.perf_counter() - t_boot, 1),
          n_devices=jax.device_count())
    on = plat != "cpu"
    batch = (1 << 16) if on else (1 << 13)
    iters = 8 if on else 2
    # k_tries x straw2-mode sweep, expected-value order (table first:
    # the LN16-table reciprocal-mulhi key built for TPU, never measured
    # there; k=1 compiles fastest)
    for k_tries, mode in ((1, "table"), (4, "table"), (8, "table"),
                          (1, "compute"), (8, "compute"),
                          (16, "table"), (4, "compute"),
                          (16, "compute")):
        bench._try_stage(
            f"spec/big10k/k{k_tries}/{mode}", _stage_spec, bench,
            "map_big10k", plat, k_tries, mode, batch, iters)
    bench._try_stage("gen/big10k", bench._stage_crush, "map_big10k",
                     plat, batch=(1 << 14) if on else (1 << 13),
                     iters=8 if on else 2)
    bench._try_stage("ec_pallas", _stage_pallas_ec, plat)
    bench._try_stage("ec/large", bench._stage_ec, plat,
                     chunk=1 << 20, batch=4, iters=8, tag="large")


# ---------------------------------------------------------------------------
# parent orchestration (never imports jax)
# ---------------------------------------------------------------------------

def _load_artifact():
    if ARTIFACT.exists():
        try:
            return json.load(open(ARTIFACT))
        except Exception:
            pass
    return {"attempts": []}


def _save_artifact(doc):
    ARTIFACT.write_text(json.dumps(doc, indent=1) + "\n")


def _commit_artifact(msg):
    """Commit ONLY the artifact (git commit -o) so a background probe
    can never sweep up unrelated in-progress work."""
    try:
        subprocess.run(["git", "add", "--intent-to-add",
                        str(ARTIFACT)], cwd=str(REPO), check=False,
                       capture_output=True)
        subprocess.run(["git", "commit", "-o", str(ARTIFACT),
                        "-m", msg], cwd=str(REPO), check=False,
                       capture_output=True, timeout=60)
    except Exception as e:
        print(f"# commit failed: {e}", file=sys.stderr)


def attempt():
    """One probe attempt.  Returns True if an accelerator number landed."""
    doc = _load_artifact()
    rec = {"ts": _now(), "quick_timeout_s": QUICK_TIMEOUT}
    env = dict(os.environ)
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "tpu_probe.py"), "--worker",
         "quick"], env=env, stdout=subprocess.PIPE, stderr=None,
        text=True, cwd=str(REPO))
    t0 = time.perf_counter()
    try:
        out, _ = proc.communicate(timeout=QUICK_TIMEOUT)
        rec["quick_s"] = round(time.perf_counter() - t0, 1)
        info = json.loads(out.strip().splitlines()[-1])
        rec.update(info)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        rec["outcome"] = "timeout"
        rec["detail"] = f"jax.devices() hung {QUICK_TIMEOUT:.0f}s " \
            "(tunnel down)"
        doc["attempts"].append(rec)
        _save_artifact(doc)
        print(f"# probe {rec['ts']}: tunnel down (quick probe hung)",
              file=sys.stderr)
        return False
    except Exception as e:
        proc.kill()
        rec["outcome"] = "error"
        rec["detail"] = repr(e)
        doc["attempts"].append(rec)
        _save_artifact(doc)
        return False

    if rec.get("platform") in (None, "cpu"):
        rec["outcome"] = "cpu_only"
        doc["attempts"].append(rec)
        _save_artifact(doc)
        print(f"# probe {rec['ts']}: resolved to cpu (no accelerator)",
              file=sys.stderr)
        return False

    # tunnel is UP — run the measurement stages, streaming results so a
    # mid-flight tunnel drop still keeps everything landed so far
    print(f"# probe {rec['ts']}: {rec['platform']} x"
          f"{rec.get('n_devices')} UP — running stages",
          file=sys.stderr)
    rec["outcome"] = "up"
    rec["results"] = []
    doc["attempts"].append(rec)
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "tpu_probe.py"), "--worker",
         "stages"], env=env, stdout=subprocess.PIPE, stderr=None,
        text=True, cwd=str(REPO))
    # hard watchdog: a tunnel drop mid-stage hangs the worker with no
    # further output, and a blocked readline would otherwise stall the
    # probe loop for the rest of the round
    watchdog = threading.Timer(STAGE_DEADLINE, proc.kill)
    watchdog.daemon = True
    watchdog.start()
    try:
        for line in proc.stdout:
            if line.startswith(RESULT_TAG):
                r = json.loads(line[len(RESULT_TAG):])
                r["ts"] = _now()
                rec["results"].append(r)
                print(f"# stage landed: {r}", file=sys.stderr)
                _save_artifact(doc)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
            rec["detail"] = "stage deadline hit"
        proc.wait()
    crush = [r for r in rec["results"] if r.get("stage") == "crush"]
    if crush:
        best = max(crush, key=lambda r: r.get("rate", 0.0))
        doc["best"] = best
        _save_artifact(doc)
        _commit_artifact(
            f"TPU probe: {best['rate']:.0f} mappings/s on "
            f"{best['platform']} ({best.get('engine')})")
        return True
    _save_artifact(doc)
    _commit_artifact("TPU probe: tunnel up, stage results recorded")
    return bool(rec["results"])


def main():
    args = sys.argv[1:]
    if args[:1] == ["--worker"]:
        from ceph_tpu.utils.platform import apply_platform_env

        apply_platform_env()
        {"quick": worker_quick, "stages": worker_stages}[args[1]]()
        return
    if args[:1] == ["--loop"]:
        interval = float(args[1]) if len(args) > 1 else 600.0
        while True:
            ok = attempt()
            time.sleep(interval if not ok else interval * 3)
        return
    sys.exit(0 if attempt() else 1)


if __name__ == "__main__":
    main()
