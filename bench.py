"""Framework benchmark — prints ONE JSON line with the headline metric.

Headline: CRUSH placement throughput (mappings/s) on the 10k-OSD
3-level straw2 map, numrep=3 chooseleaf — the exact workload of the
reference's `crushtool --test` hot loop (src/crush/CrushTester.cc:573
calling crush_do_rule, src/crush/mapper.c:878), whose single-thread CPU
rate was measured in-container from the reference's own C core:
85099.6 mappings/s (BASELINE_MEASURED.json).  vs_baseline is the
speedup over that number; the BASELINE.json target is 50x.

Platform handling: the default backend (the TPU under the driver) is
probed in a *subprocess with a timeout* so a hung/unavailable chip can
never hang the bench; unavailability is retried with backoff (busy
chip), then falls back to the CPU backend so a number is always
produced.  The JSON line records which platform actually ran.

Secondary metrics (EC encode/decode GB/s) go to stderr so stdout stays
one line.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent

CPU_BASELINE_MAPPINGS_PER_SEC = json.load(
    open(REPO / "BASELINE_MEASURED.json"))["crush_mappings_per_sec_cpu"]

PROBE_SRC = (
    "import jax; d = jax.devices()[0]; print('PLATFORM=' + d.platform)"
)


def probe_default_backend(timeout=150, attempts=3, backoff=20):
    """Initialize the default jax backend in a subprocess with a hard
    timeout.  Returns the platform name or None if unusable.  Bounded
    worst case (~8.5 min) so the guaranteed-fallback JSON line always
    lands within a driver budget."""
    env = dict(os.environ)
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", PROBE_SRC], env=env,
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            print(f"# backend probe attempt {i + 1}: timeout after "
                  f"{timeout}s", file=sys.stderr)
            out = None
        if out is not None:
            for line in out.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    return line.split("=", 1)[1]
            tail = (out.stderr or "").strip().splitlines()
            print(f"# backend probe attempt {i + 1}: rc={out.returncode} "
                  f"{tail[-1] if tail else ''}", file=sys.stderr)
        if i + 1 < attempts:  # no dead sleep after the final attempt
            time.sleep(backoff * (i + 1))
    return None


def bench_crush(batch=None, iters=None):
    import jax
    import jax.numpy as jnp

    on_accel = jax.devices()[0].platform != "cpu"
    if batch is None:
        batch = (1 << 17) if on_accel else (1 << 13)
    if iters is None:
        iters = 8 if on_accel else 2

    from ceph_tpu.crush.map import CrushMap
    from ceph_tpu.crush.mapper_jax import build_rule_fn

    d = json.load(open(REPO / "tests/golden/map_big10k.json"))
    cmap = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    fn, static, arrays = build_rule_fn(cmap, case["ruleno"],
                                       case["numrep"])
    A = jax.tree_util.tree_map(jnp.asarray, arrays)
    weight = jnp.asarray(np.asarray(case["weight"], np.uint32))

    xs = jnp.arange(batch, dtype=jnp.uint32)
    res, lens = fn(A, weight, xs)  # compile + warm
    res.block_until_ready()

    t0 = time.perf_counter()
    for i in range(iters):
        xs_i = jnp.arange(i * batch, (i + 1) * batch, dtype=jnp.uint32)
        res, lens = fn(A, weight, xs_i)
    res.block_until_ready()
    dt = time.perf_counter() - t0
    rate = batch * iters / dt

    # cross-check a slice against the golden vectors
    n = min(256, case["x1"] - case["x0"])
    gres, glens = fn(A, weight,
                     jnp.arange(case["x0"], case["x0"] + n,
                                dtype=jnp.uint32))
    gres = np.asarray(gres)
    glens = np.asarray(glens)
    for i in range(n):
        want = case["results"][i]
        got = list(gres[i, :glens[i]])
        assert got == want, f"golden mismatch at x={case['x0'] + i}"
    return rate


def bench_ec(k=8, m=3, chunk=None, batch=4, iters=8):
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ec.rs_jax import RSCode

    if chunk is None:
        chunk = (1 << 20) if jax.devices()[0].platform != "cpu" \
            else (1 << 16)
    code = RSCode(k, m)
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (k, batch * chunk),
                                    dtype=np.uint8))
    out = code.encode(data)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.encode(data)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    enc_gbps = (k * batch * chunk * iters) / dt / 1e9

    # decode workload (ceph_erasure_code_benchmark.cc:288-315): two
    # erased chunks reconstructed from k survivors
    full = code.all_chunks(data)
    chunks = {i: full[i] for i in range(k + m)}
    erasures = [0, 1]
    out = code.decode(chunks, erasures)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.decode(chunks, erasures)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    dec_gbps = (k * batch * chunk * iters) / dt / 1e9
    return enc_gbps, dec_gbps


def main():
    from ceph_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # CEPH_TPU_PLATFORM=cpu forces the CPU backend

    if not os.environ.get("CEPH_TPU_PLATFORM"):
        plat = probe_default_backend()
        if plat is None:
            print("# default backend unusable; falling back to cpu",
                  file=sys.stderr)
            import jax

            jax.config.update("jax_platforms", "cpu")

    import jax

    dev = jax.devices()[0].platform
    rate = bench_crush()
    try:
        enc_gbps, dec_gbps = bench_ec()
        print(f"# ec k=8,m=3: encode {enc_gbps:.2f} GB/s, "
              f"decode {dec_gbps:.2f} GB/s on {dev}", file=sys.stderr)
    except Exception as e:  # EC is secondary; never break the one line
        print(f"# ec bench failed: {e}", file=sys.stderr)
    print(json.dumps({
        "metric": "crush_mappings_per_sec",
        "value": round(rate, 1),
        "unit": "mappings/s",
        "platform": dev,
        "vs_baseline": round(rate / CPU_BASELINE_MAPPINGS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
