"""Framework benchmark — prints ONE JSON line with the headline metric.

Headline: CRUSH placement throughput (mappings/s) on the 10k-OSD
3-level straw2 map, numrep=3 chooseleaf — the exact workload of the
reference's `crushtool --test` hot loop (src/crush/CrushTester.cc:573
calling crush_do_rule, src/crush/mapper.c:878), whose single-thread CPU
rate was measured in-container from the reference's own C core:
85099.6 mappings/s (BASELINE_MEASURED.json).  vs_baseline is the
speedup over that number; the BASELINE.json target is 50x.

Architecture (the "a number ALWAYS lands" contract):

- The parent process never initializes any JAX backend.  Every bench
  phase runs in a *subprocess* with a hard deadline and is killed on
  expiry; a hung experimental TPU backend can cost its deadline,
  nothing more.
- The CPU measurement and the TPU attempt launch *concurrently*; the
  headline JSON (TPU if it landed, else the CPU figure — with the CPU
  figure recorded either way) prints immediately after the CRUSH phase,
  before any EC work, so later phases can never lose it.
- Workers enable JAX's persistent compilation cache under
  ``.jax_cache/`` so the driver's next invocation hits warm XLA
  artifacts; compile and measure wall times are reported separately.
- Secondary metrics (EC encode/decode GB/s) follow on stderr.

Deadlines (seconds, env-overridable):
  CEPH_TPU_BENCH_TPU_DEADLINE   (default 300)
  CEPH_TPU_BENCH_CPU_DEADLINE   (default 270)
  CEPH_TPU_BENCH_EC_DEADLINE    (default 150)
"""

import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent

CPU_BASELINE_MAPPINGS_PER_SEC = json.load(
    open(REPO / "BASELINE_MEASURED.json"))["crush_mappings_per_sec_cpu"]

TPU_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_TPU_DEADLINE", 300))
CPU_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_CPU_DEADLINE", 270))
EC_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_EC_DEADLINE", 150))

RESULT_TAG = "BENCH_RESULT "


# ---------------------------------------------------------------------------
# worker side (runs inside a subprocess; the only code that imports jax)
# ---------------------------------------------------------------------------

def _enable_compile_cache():
    import jax

    cache = str(REPO / ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"# compile cache unavailable: {e}", file=sys.stderr)


def worker_crush(batch=None, iters=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    _enable_compile_cache()
    plat = jax.devices()[0].platform
    on_accel = plat != "cpu"
    if batch is None:
        batch = (1 << 17) if on_accel else (1 << 13)
    if iters is None:
        iters = 8 if on_accel else 2

    from ceph_tpu.crush.map import CrushMap
    from ceph_tpu.crush.mapper_jax import build_rule_fn

    d = json.load(open(REPO / "tests/golden/map_big10k.json"))
    cmap = CrushMap.from_dict(d["map"])
    case = d["cases"][0]

    if not on_accel:
        # the CPU engine of this framework is the native C++ batched
        # mapper (XLA's while-loop lowering is not competitive on CPU);
        # the accelerated path below is the TPU engine
        try:
            from ceph_tpu.crush.native import available

            if available():
                return _native_crush_rate(cmap, case, np)
        except AssertionError:
            raise  # golden mismatch = wrong mappings; never mask it
        except Exception as e:
            print(f"# native cpu engine unavailable: {e}",
                  file=sys.stderr)
    t0 = time.perf_counter()
    fn, static, arrays = build_rule_fn(cmap, case["ruleno"], case["numrep"])
    A = jax.tree_util.tree_map(jnp.asarray, arrays)
    weight = jnp.asarray(np.asarray(case["weight"], np.uint32))
    xs = jnp.arange(batch, dtype=jnp.uint32)
    res, lens = fn(A, weight, xs)  # trace + compile + first run
    res.block_until_ready()
    compile_s = time.perf_counter() - t0
    # golden cross-check on EVERY platform — the headline number must be
    # a validated computation.  The golden xs [x0, x0+n) are a prefix of
    # the warmup batch (x0 == 0), so this costs zero extra compiles.
    n = min(256, case["x1"] - case["x0"], batch)
    assert case["x0"] == 0, "golden case must start at x=0"
    gres = np.asarray(res[:n])
    glens = np.asarray(lens[:n])
    for i in range(n):
        want = case["results"][i]
        got = list(gres[i, :glens[i]])
        assert got == want, f"golden mismatch at x={i} on {plat}"

    t0 = time.perf_counter()
    for i in range(iters):
        xs_i = jnp.arange(i * batch, (i + 1) * batch, dtype=jnp.uint32)
        res, lens = fn(A, weight, xs_i)
    res.block_until_ready()
    measure_s = time.perf_counter() - t0
    rate = batch * iters / measure_s

    print(RESULT_TAG + json.dumps({
        "rate": rate, "platform": plat, "engine": "xla",
        "compile_s": round(compile_s, 2),
        "measure_s": round(measure_s, 3),
        "batch": batch, "iters": iters,
    }), flush=True)


def _native_crush_rate(cmap, case, np):
    from ceph_tpu.crush.native import NativeMapper

    t0 = time.perf_counter()
    nm = NativeMapper(cmap)
    weight = np.asarray(case["weight"], np.uint32)
    # golden validation first — the number must be a checked computation
    n = case["x1"] - case["x0"]
    res, lens = nm.map_batch(
        case["ruleno"],
        np.arange(case["x0"], case["x1"], dtype=np.uint32),
        case["numrep"], weight)
    for i in range(n):
        assert list(res[i, :lens[i]]) == case["results"][i], \
            f"golden mismatch at x={case['x0'] + i} on native"
    setup_s = time.perf_counter() - t0

    batch, iters = 1 << 16, 4
    t0 = time.perf_counter()
    for i in range(iters):
        xs = np.arange(i * batch, (i + 1) * batch, dtype=np.uint32)
        nm.map_batch(case["ruleno"], xs, case["numrep"], weight)
    measure_s = time.perf_counter() - t0
    print(RESULT_TAG + json.dumps({
        "rate": batch * iters / measure_s, "platform": "cpu",
        "engine": "native", "compile_s": round(setup_s, 2),
        "measure_s": round(measure_s, 3),
        "batch": batch, "iters": iters,
    }), flush=True)


def worker_ec(k=8, m=3, chunk=None, batch=4, iters=8):
    import jax
    import jax.numpy as jnp
    import numpy as np

    _enable_compile_cache()
    plat = jax.devices()[0].platform
    engine = "xla"
    if plat == "cpu":
        # the CPU EC engine is the native GF table matmul (the isa-l
        # role); the accelerated path below is the MXU bit-matmul
        try:
            from ceph_tpu.ec.native_gf import NativeRS, available

            if available():
                engine = "native"
        except Exception as e:
            print(f"# native gf engine unavailable: {e}",
                  file=sys.stderr)
    if engine == "native":
        code = NativeRS(k, m)
    else:
        from ceph_tpu.ec.rs_jax import RSCode

        code = RSCode(k, m)

    if chunk is None:
        chunk = (1 << 20) if plat != "cpu" else (1 << 18)
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, (k, batch * chunk), dtype=np.uint8)
    data = raw if engine == "native" else jnp.asarray(raw)

    def _sync(v):
        getattr(v, "block_until_ready", lambda: None)()

    t0 = time.perf_counter()
    out = code.encode(data)
    _sync(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.encode(data)
    _sync(out)
    dt = time.perf_counter() - t0
    enc_gbps = (k * batch * chunk * iters) / dt / 1e9

    # decode workload (ceph_erasure_code_benchmark.cc:288-315): two
    # erased chunks reconstructed from k survivors
    full = code.all_chunks(data)
    chunks = {i: full[i] for i in range(k + m)}
    erasures = [0, 1]
    out = code.decode(chunks, erasures)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.decode(chunks, erasures)
    _sync(out)
    dt = time.perf_counter() - t0
    dec_gbps = (k * batch * chunk * iters) / dt / 1e9
    print(RESULT_TAG + json.dumps({
        "encode_gbps": round(enc_gbps, 3),
        "decode_gbps": round(dec_gbps, 3),
        "platform": plat, "engine": engine,
        "compile_s": round(compile_s, 2),
    }), flush=True)


# ---------------------------------------------------------------------------
# parent side (orchestration; no jax import)
# ---------------------------------------------------------------------------

def _spawn(phase: str, platform: str):
    """Start a worker subprocess; platform 'cpu' pins the CPU backend
    through BOTH channels (env var and CEPH_TPU_PLATFORM → jax.config),
    since preloaded images can make the env var alone a no-op."""
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["CEPH_TPU_PLATFORM"] = "cpu"
    return subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--worker", phase],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=str(REPO))


def _collect(proc, deadline: float, label: str):
    """Wait for a worker up to its deadline; returns parsed result or
    None.  Kills the process tree on expiry — a hung backend cannot
    outlive its budget."""
    if proc is None:
        return None
    try:
        out, err = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        print(f"# {label}: killed after {deadline:.0f}s deadline",
              file=sys.stderr)
        return None
    for line in (out or "").splitlines():
        if line.startswith(RESULT_TAG):
            return json.loads(line[len(RESULT_TAG):])
    tail = (err or "").strip().splitlines()
    print(f"# {label}: rc={proc.returncode} "
          f"{tail[-1] if tail else '(no output)'}", file=sys.stderr)
    return None


def main():
    force_cpu = os.environ.get("CEPH_TPU_PLATFORM", "") == "cpu"

    # CRUSH phase: CPU measurement and TPU attempt race concurrently.
    t_start = time.perf_counter()
    cpu_proc = _spawn("crush", "cpu")
    tpu_proc = None if force_cpu else _spawn("crush", "default")

    cpu_res = _collect(cpu_proc, CPU_DEADLINE, "crush/cpu")
    elapsed = time.perf_counter() - t_start
    tpu_res = _collect(tpu_proc, max(10.0, TPU_DEADLINE - elapsed),
                       "crush/default")
    if tpu_res is not None and tpu_res.get("platform") == "cpu":
        # default backend resolved to cpu (no accelerator attached);
        # the two identical CPU runs contended for cores, so keep the
        # higher (less-depressed) rate as the CPU figure
        if cpu_res is None or tpu_res["rate"] > cpu_res["rate"]:
            cpu_res = tpu_res
        tpu_res = None

    headline = tpu_res or cpu_res
    if headline is None:
        # last resort: tiny in-process CPU run so the line still lands
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["CEPH_TPU_PLATFORM"] = "cpu"
        print("# both crush workers failed; in-process cpu fallback",
              file=sys.stderr)
        import io
        import contextlib
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf):
                worker_crush(batch=1 << 10, iters=1)
        except Exception as e:
            print(f"# in-process fallback failed too: {e}",
                  file=sys.stderr)
        for line in buf.getvalue().splitlines():
            if line.startswith(RESULT_TAG):
                headline = json.loads(line[len(RESULT_TAG):])
    if headline is None:
        # absolute sentinel: the contract is one JSON line, always
        headline = {"rate": 0.0, "platform": "none"}

    rate = headline["rate"]
    out = {
        "metric": "crush_mappings_per_sec",
        "value": round(rate, 1),
        "unit": "mappings/s",
        "platform": headline["platform"],
        "vs_baseline": round(rate / CPU_BASELINE_MAPPINGS_PER_SEC, 2),
        "engine": headline.get("engine"),
        "compile_s": headline.get("compile_s"),
        "measure_s": headline.get("measure_s"),
        "cpu_rate": round(cpu_res["rate"], 1) if cpu_res else None,
        "cpu_engine": cpu_res.get("engine") if cpu_res else None,
    }
    print(json.dumps(out), flush=True)  # the ONE line — lands first

    # EC phase (secondary; stderr only, can never cost the headline)
    ec_proc = None if force_cpu else _spawn("ec", "default")
    ec_res = _collect(ec_proc, EC_DEADLINE, "ec/default")
    if ec_res is None:
        ec_res = _collect(_spawn("ec", "cpu"), EC_DEADLINE, "ec/cpu")
    if ec_res is not None:
        print(f"# ec k=8,m=3: encode {ec_res['encode_gbps']:.2f} GB/s, "
              f"decode {ec_res['decode_gbps']:.2f} GB/s on "
              f"{ec_res['platform']} (compile {ec_res['compile_s']}s)",
              file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        from ceph_tpu.utils.platform import apply_platform_env

        apply_platform_env()
        {"crush": worker_crush, "ec": worker_ec}[sys.argv[2]]()
    else:
        main()
