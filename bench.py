"""Framework benchmark — prints ONE JSON line with the headline metric.

Headline: CRUSH placement throughput (mappings/s) on the 10k-OSD
3-level straw2 map, numrep=3 chooseleaf — the exact workload of the
reference's `crushtool --test` hot loop (src/crush/CrushTester.cc:573
calling crush_do_rule, src/crush/mapper.c:878), whose single-thread CPU
rate was measured in-container from the reference's own C core:
85099.6 mappings/s (BASELINE_MEASURED.json).  vs_baseline is the
speedup over that number; the BASELINE.json target is 50x.

Architecture (the "a number ALWAYS lands" contract), staged:

- The parent process never initializes any JAX backend.  Every bench
  phase runs in a *subprocess*; the parent reads worker stdout as a
  STREAM, so each stage's result lands the instant it completes — a
  hung or slow later stage can never erase an earlier number.
- The accelerator worker is one process emitting incremental
  ``BENCH_RESULT`` lines: (1) backend-init timestamp, (2) tiny-map
  (flat12) compile+measure, (3) the 10k-OSD map, (4) EC encode/decode.
  If the worker dies or times out, whatever stages landed still count;
  zero lines pins the hang to backend init.
- The CPU measurement (native C++ engine) runs concurrently; the
  headline JSON (best accelerator CRUSH figure if any landed, else the
  CPU figure — the CPU figure recorded either way) prints immediately
  after the CRUSH stages resolve, before waiting on EC.
- Workers enable JAX's persistent compilation cache under
  ``.jax_cache/`` so the driver's next invocation hits warm XLA
  artifacts; compile and measure wall times are reported separately.

Deadlines (seconds, env-overridable):
  CEPH_TPU_BENCH_TPU_DEADLINE   (default 300) — whole accel worker
  CEPH_TPU_BENCH_INIT_DEADLINE  (default 60) — accel BACKEND INIT
                                 probe: the worker's first emitted
                                 line is its backend-init timestamp;
                                 if it hasn't landed by this deadline
                                 the backend is hung (TPU tunnel
                                 down), and waiting out the full
                                 worker deadline would burn 300 s to
                                 learn nothing — fail fast, record
                                 ``backend_init_failed`` in the JSON,
                                 and let the CPU figure own the line.
  CEPH_TPU_BENCH_CPU_DEADLINE   (default 270)
  CEPH_TPU_BENCH_EC_DEADLINE    (default 150) — extra EC wait after
                                 the headline printed
"""

import json
import os
import pathlib
import subprocess
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent

CPU_BASELINE_MAPPINGS_PER_SEC = json.load(
    open(REPO / "BASELINE_MEASURED.json"))["crush_mappings_per_sec_cpu"]

TPU_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_TPU_DEADLINE", 300))
INIT_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_INIT_DEADLINE",
                                     60))
CPU_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_CPU_DEADLINE", 270))
EC_DEADLINE = float(os.environ.get("CEPH_TPU_BENCH_EC_DEADLINE", 150))
MULTICHIP_DEADLINE = float(os.environ.get(
    "CEPH_TPU_BENCH_MULTICHIP_DEADLINE", 420))

RESULT_TAG = "BENCH_RESULT "

# SLO floors (env-overridable): the throughput a stage must clear for
# its slo block to record pass=true — what tools/perf_history.py turns
# into a red check instead of archaeology.  Floors are deliberately
# below the measured trajectory (r01-r05) so they flag regressions,
# not noise.
SLO_FLOORS = {
    "crush_big10k_mappings_per_sec": float(os.environ.get(
        "CEPH_TPU_SLO_CRUSH_FLOOR", 80_000)),
    "ec_encode_gbps": float(os.environ.get(
        "CEPH_TPU_SLO_EC_ENCODE_FLOOR", 0.3)),
    "ec_batch_speedup": float(os.environ.get(
        "CEPH_TPU_SLO_EC_BATCH_FLOOR", 1.5)),
    "cluster_write_iops": float(os.environ.get(
        "CEPH_TPU_SLO_CLUSTER_IOPS_FLOOR", 100)),
    # the multichip lane's floor is the N-DEVICE absolute throughput,
    # set low enough that N virtual devices time-slicing ONE CPU core
    # still clear it (the lane's job on CPU CI is producing the
    # per-device breakdown + efficiency figure; perf_history red-checks
    # run-over-run efficiency drops, which is where regressions show)
    "multichip_crush_mappings_per_sec": float(os.environ.get(
        "CEPH_TPU_SLO_MULTICHIP_CRUSH_FLOOR", 500)),
    "multichip_encode_gbps": float(os.environ.get(
        "CEPH_TPU_SLO_MULTICHIP_EC_FLOOR", 0.01)),
    # the balancer lane's floor is sweep throughput (batched remapped
    # PGs per second across the loop's evaluation sweeps) on CPU CI,
    # where early sweeps pay compile; convergence itself is gated by
    # perf_history (a non-converged BALANCE record is a red check)
    "balancer_sweep_mappings_per_sec": float(os.environ.get(
        "CEPH_TPU_SLO_BALANCE_SWEEP_FLOOR", 50)),
}


def _emit(**kw):
    print(RESULT_TAG + json.dumps(kw), flush=True)


def _slo(metric: str, value, floor_key: str = None, **lat):
    """One stage's SLO block: value vs floor (+p50/p99 latency when
    the stage measures per-op latency)."""
    floor = SLO_FLOORS.get(floor_key or metric)
    block = {"metric": metric,
             "value": round(value, 3) if isinstance(
                 value, float) else value}
    if floor is not None:
        block["floor"] = floor
        block["pass"] = bool(value is not None and value >= floor)
    block.update({k: v for k, v in lat.items() if v is not None})
    return block


def _lib_counters():
    """Flattened numeric snapshot of the process-global perf
    collection ('logger.key': value) — what stage counter deltas
    diff.  Import is lazy: only workers (which already load the
    library) pay for it."""
    from ceph_tpu.common.perf_counters import collection

    out = {}
    for logger, counters in collection().dump().items():
        for key, val in counters.items():
            if isinstance(val, (int, float)):
                out[f"{logger}.{key}"] = val
    return out


def _counter_deltas(before, after):
    """Non-zero counter movement during a stage — the device-plane
    story (kernel launches, transfer bytes, jit compiles) attached to
    every stage JSON."""
    out = {}
    for key, val in after.items():
        d = val - before.get(key, 0)
        if d:
            out[key] = round(d, 6) if isinstance(d, float) else d
    return out


# ---------------------------------------------------------------------------
# worker side (runs inside a subprocess; the only code that imports jax)
# ---------------------------------------------------------------------------

def _enable_compile_cache():
    import jax

    cache = str(REPO / ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"# compile cache unavailable: {e}", file=sys.stderr)


def _load_case(name):
    import numpy as np

    from ceph_tpu.crush.map import CrushMap

    d = json.load(open(REPO / f"tests/golden/{name}.json"))
    cmap = CrushMap.from_dict(d["map"])
    case = d["cases"][0]
    case["weight_np"] = np.asarray(case["weight"], np.uint32)
    return cmap, case


def _golden_check(case, res, lens, label):
    """The headline number must be a validated computation: the golden
    xs [0, n) are a prefix of the warmup batch, costing zero compiles."""
    import numpy as np

    n = min(256, case["x1"] - case["x0"], res.shape[0])
    assert case["x0"] == 0, "golden case must start at x=0"
    gres, glens = np.asarray(res[:n]), np.asarray(lens[:n])
    for i in range(n):
        want = case["results"][i]
        got = list(gres[i, :glens[i]])
        assert got == want, f"golden mismatch at x={i} on {label}"


def _measure_crush(fn, A, weight, batch, iters):
    import jax.numpy as jnp

    t0 = time.perf_counter()
    for i in range(iters):
        xs_i = jnp.arange(i * batch, (i + 1) * batch, dtype=jnp.uint32)
        res, lens = fn(A, weight, xs_i)
    res.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * iters / dt, dt


def _stage_crush(name, plat, batch, iters, engine="xla"):
    """One CRUSH measurement stage: build (general or speculative
    lowering), compile+warmup, golden-validate, measure, emit."""
    import jax
    import jax.numpy as jnp

    cmap, case = _load_case(name)
    t0 = time.perf_counter()
    if engine == "xla-spec":
        from ceph_tpu.crush.mapper_spec import build_spec_rule_fn

        fn, static, arrays = build_spec_rule_fn(
            cmap, case["ruleno"], case["numrep"], k_tries=1)
    else:
        from ceph_tpu.crush.mapper_jax import build_rule_fn

        fn, static, arrays = build_rule_fn(cmap, case["ruleno"],
                                           case["numrep"])
    A = jax.tree_util.tree_map(jnp.asarray, arrays)
    weight = jnp.asarray(case["weight_np"])
    xs = jnp.arange(batch, dtype=jnp.uint32)
    res, lens = fn(A, weight, xs)  # trace + compile + first run
    res.block_until_ready()
    compile_s = time.perf_counter() - t0
    _golden_check(case, res, lens, f"{plat}/{name}/{engine}")
    c0 = _lib_counters()
    rate, dt = _measure_crush(fn, A, weight, batch, iters)
    _emit(stage="crush", map=name, rate=rate, platform=plat,
          engine=engine, compile_s=round(compile_s, 2),
          measure_s=round(dt, 3), batch=batch, iters=iters,
          counters=_counter_deltas(c0, _lib_counters()),
          slo=_slo(f"crush_{name[4:]}_mappings_per_sec", rate,
                   floor_key="crush_big10k_mappings_per_sec"
                   if name == "map_big10k" else None))
    return rate


def _try_stage(label, fn, *a, **kw):
    """One stage must never cost the later ones.  A golden mismatch
    (wrong mappings) is never masked — it lands as an explicit
    BENCH_RESULT line the parent uses to refuse that engine's rate —
    but it must not kill the OTHER engine's stages either."""
    try:
        return fn(*a, **kw)
    except AssertionError as e:
        print(f"# stage {label} GOLDEN FAILURE: {e}", file=sys.stderr)
        _emit(stage="golden_failure", label=label, error=str(e))
        return None
    except Exception as e:
        print(f"# stage {label} failed: {e!r}", file=sys.stderr)
        return None


def worker_staged():
    """The accelerator worker: emits one BENCH_RESULT line per stage,
    cheapest first, so a number lands no matter where time runs out."""
    t_boot = time.perf_counter()
    import jax

    _enable_compile_cache()
    plat = jax.devices()[0].platform  # ← the historical hang point
    _emit(stage="init", platform=plat,
          init_s=round(time.perf_counter() - t_boot, 1),
          n_devices=jax.device_count())
    if plat == "cpu" and not os.environ.get(
            "CEPH_TPU_BENCH_STAGED_ON_CPU"):
        # no accelerator attached: the CPU engine of record is the
        # native C++ mapper in the concurrent cpu worker; exit now
        # rather than burn its cores on the XLA-CPU lowering.  (The
        # env override exercises the full staged path in tests.)
        return
    on = plat != "cpu"
    # speculative lowering first: fastest compile AND fastest measured
    # engine, so the best-known number lands earliest (Ineligible on a
    # non-eligible rule is caught like any stage failure)
    _try_stage("spec/flat12", _stage_crush, "map_flat12", plat,
               batch=1 << 14, iters=4, engine="xla-spec")
    _try_stage("spec/big10k", _stage_crush, "map_big10k", plat,
               batch=(1 << 16) if on else (1 << 13),
               iters=8 if on else 3, engine="xla-spec")
    _try_stage("gen/flat12", _stage_crush, "map_flat12", plat,
               batch=1 << 14, iters=4)
    # gen mapper batch is HBM-bound on big maps: the general lowering
    # materializes (batch, buckets, slots) intermediates, and 2^17
    # lanes x 521 x 25 s32 overflowed v5e HBM (measured r5 probe)
    _try_stage("gen/big10k", _stage_crush, "map_big10k", plat,
               batch=(1 << 14) if on else (1 << 13),
               iters=8 if on else 2)
    _try_stage("ec/small", _stage_ec, plat, chunk=1 << 16, batch=4,
               iters=4, tag="small")
    _try_stage("ec/large", _stage_ec, plat, chunk=1 << 20, batch=4,
               iters=8, tag="large")
    _try_stage("ec/batch", _stage_ec_batch, plat)


def worker_crush_cpu(batch=None, iters=None):
    """CPU figure: the native C++ batched mapper (the XLA while-loop
    lowering is not competitive on CPU; the accelerator path is the
    staged worker)."""
    import numpy as np

    from ceph_tpu.crush.native import NativeMapper, available

    cmap, case = _load_case("map_big10k")
    if not available():
        # native engine missing (no compiler?) — fall back to XLA-CPU
        # so a validated CPU line still lands
        import jax  # noqa: F401  (backend pinned to cpu by caller env)

        _enable_compile_cache()
        _stage_crush("map_big10k", "cpu", batch or (1 << 13),
                     iters or 2)
        return

    t0 = time.perf_counter()
    nm = NativeMapper(cmap)
    weight = case["weight_np"]
    n = case["x1"] - case["x0"]
    res, lens = nm.map_batch(
        case["ruleno"],
        np.arange(case["x0"], case["x1"], dtype=np.uint32),
        case["numrep"], weight)
    for i in range(n):
        assert list(res[i, :lens[i]]) == case["results"][i], \
            f"golden mismatch at x={case['x0'] + i} on native"
    setup_s = time.perf_counter() - t0

    batch, iters = batch or (1 << 16), iters or 4
    c0 = _lib_counters()
    t0 = time.perf_counter()
    for i in range(iters):
        xs = np.arange(i * batch, (i + 1) * batch, dtype=np.uint32)
        nm.map_batch(case["ruleno"], xs, case["numrep"], weight)
    dt = time.perf_counter() - t0
    rate = batch * iters / dt
    _emit(stage="crush", map="map_big10k", rate=rate,
          platform="cpu", engine="native", compile_s=round(setup_s, 2),
          measure_s=round(dt, 3), batch=batch, iters=iters,
          counters=_counter_deltas(c0, _lib_counters()),
          slo=_slo("crush_big10k_mappings_per_sec", rate))


def _stage_ec(plat, k=8, m=3, chunk=1 << 18, batch=4, iters=8,
              tag="default"):
    import numpy as np

    engine = "xla"
    if plat == "cpu":
        try:
            from ceph_tpu.ec.native_gf import NativeRS, available

            if available():
                engine = "native"
        except Exception as e:
            print(f"# native gf engine unavailable: {e}",
                  file=sys.stderr)
    if engine == "native":
        code = NativeRS(k, m)
        data_of = lambda raw: raw  # noqa: E731
        _sync = lambda v: None  # noqa: E731
    else:
        import jax.numpy as jnp

        from ceph_tpu.ec.rs_jax import RSCode

        code = RSCode(k, m)
        data_of = jnp.asarray
        _sync = lambda v: getattr(  # noqa: E731
            v, "block_until_ready", lambda: None)()

    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, (k, batch * chunk), dtype=np.uint8)
    data = data_of(raw)

    c_pre = _lib_counters()
    t0 = time.perf_counter()
    out = code.encode(data)
    _sync(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.encode(data)
    _sync(out)
    dt = time.perf_counter() - t0
    enc_gbps = (k * batch * chunk * iters) / dt / 1e9

    # decode workload (ceph_erasure_code_benchmark.cc:288-315): two
    # erased chunks reconstructed from k survivors
    full = code.all_chunks(data)
    chunks = {i: full[i] for i in range(k + m)}
    erasures = [0, 1]
    out = code.decode(chunks, erasures)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = code.decode(chunks, erasures)
    _sync(out)
    dt = time.perf_counter() - t0
    dec_gbps = (k * batch * chunk * iters) / dt / 1e9
    _emit(stage="ec", tag=tag, encode_gbps=round(enc_gbps, 3),
          decode_gbps=round(dec_gbps, 3), platform=plat, engine=engine,
          k=k, m=m, chunk=chunk, compile_s=round(compile_s, 2),
          counters=_counter_deltas(c_pre, _lib_counters()),
          slo=_slo("ec_encode_gbps", enc_gbps))


def _stage_ec_profiles():
    """BASELINE configs 2 and 4: jerasure RS k=4,m=2 encode/decode and
    the LRC k=4,m=2,l=3 layered LOCAL repair (one lost chunk recovered
    from its locality group, the point of the code)."""
    import time as _t

    import numpy as np

    from ceph_tpu.ec.native_gf import engine_choice
    from ceph_tpu.ec.registry import factory

    engine = f"{engine_choice()}-cpu"
    rng = np.random.default_rng(1)
    size = 1 << 20

    code = factory("jerasure", {"technique": "reed_sol_van",
                                "k": "4", "m": "2", "w": "8"})
    data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
    n = code.get_chunk_count()
    chunks = code.encode(range(n), data)
    t0 = _t.perf_counter()
    iters = 8
    for _ in range(iters):
        code.encode(range(n), data)
    enc = size * iters / (_t.perf_counter() - t0) / 1e9
    avail = {i: np.asarray(chunks[i]) for i in range(n) if i not in (0, 5)}
    t0 = _t.perf_counter()
    for _ in range(iters):
        code.decode({0, 5}, dict(avail))
    dec = size * iters / (_t.perf_counter() - t0) / 1e9
    _emit(stage="ec_profile", profile="jerasure k=4,m=2",
          engine=engine, encode_gbps=round(enc, 3),
          decode_gbps=round(dec, 3))

    lrc = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    n = lrc.get_chunk_count()
    chunks = lrc.encode(range(n), data)
    lost = 1
    need = lrc.minimum_to_decode({lost}, set(range(n)) - {lost})
    avail = {i: np.asarray(chunks[i]) for i in need}
    t0 = _t.perf_counter()
    for _ in range(iters):
        lrc.decode({lost}, dict(avail))
    rep = size * iters / (_t.perf_counter() - t0) / 1e9
    _emit(stage="ec_profile", profile="lrc k=4,m=2,l=3",
          engine=engine,
          local_repair_gbps=round(rep, 3),
          repair_reads=len(need), total_chunks=n)


def _stage_ec_batch(plat, k=4, m=2, n_stripes=64, chunk=1024,
                    iters=16):
    """Batched vs per-stripe encode on small stripes (64 x 4 KiB by
    default): dispatch overhead dominates tiny launches, and
    ``encode_batched`` amortizes it into ONE launch — the data-plane
    coalescing win, measured."""
    import numpy as np

    from ceph_tpu.ec.rs_jax import RSCode

    bc = RSCode(k, m)._bit
    rng = np.random.default_rng(2)
    stripes = rng.integers(0, 256, (n_stripes, k, chunk),
                           dtype=np.uint8)
    dev = [s for s in stripes]  # per-stripe views

    def sync(v):
        getattr(v, "block_until_ready", lambda: None)()

    # warm both shapes (compiles excluded from the measurement)
    sync(bc.encode(dev[0]))
    sync(bc.encode_batched(stripes))
    c_pre = _lib_counters()
    t0 = time.perf_counter()
    for _ in range(iters):
        for s in dev:
            out = bc.encode(s)
    sync(out)
    per_stripe = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = bc.encode_batched(stripes)
    sync(out)
    batched = time.perf_counter() - t0
    nbytes = n_stripes * k * chunk * iters
    speedup = per_stripe / batched
    _emit(stage="ec_batch", platform=plat, k=k, m=m,
          n_stripes=n_stripes, chunk=chunk,
          per_stripe_gbps=round(nbytes / per_stripe / 1e9, 3),
          batched_gbps=round(nbytes / batched / 1e9, 3),
          speedup=round(speedup, 2),
          counters=_counter_deltas(c_pre, _lib_counters()),
          slo=_slo("ec_batch_speedup", speedup))


def worker_ec_cpu():
    _stage_ec("cpu")
    _try_stage("ec/batch", _stage_ec_batch, "cpu")
    _try_stage("ec/profiles", _stage_ec_profiles)


def worker_cluster():
    """End-to-end MiniCluster throughput (the rados-bench analogue,
    src/common/obj_bencher.cc role): a pipelined-write queue-depth
    sweep (the aio window keeps the OSD queues full; the knee of the
    curve is the write pipeline's capacity) + seq-read IOPS/latency."""
    from ceph_tpu.tools.rados_bench import bench_minicluster

    c_pre = _lib_counters()
    out = bench_minicluster(op="seq", seconds=2.0, concurrent=8,
                            object_size=1 << 16, n_osds=4,
                            qd_sweep=[8, 16, 32],
                            ec_engine=os.environ.get(
                                "CEPH_TPU_BENCH_EC_ENGINE", ""))
    _emit(stage="cluster",
          write_iops=out["write"].get("iops"),
          write_mbps=out["write"].get("mb_per_sec"),
          write_p99_ms=out["write"].get("lat_p99_ms"),
          write_qd=out["write"].get("qd"),
          qd_sweep=out.get("qd_sweep"),
          seq_iops=out.get("seq", {}).get("iops"),
          seq_mbps=out.get("seq", {}).get("mb_per_sec"),
          seq_p99_ms=out.get("seq", {}).get("lat_p99_ms"),
          n_osds=out.get("n_osds"),
          attribution=out.get("attribution"),
          copy=out.get("copy"),
          profiler=out.get("profiler"),
          net=out.get("net"),
          counters=_counter_deltas(c_pre, _lib_counters()),
          slo=_slo("cluster_write_iops",
                   out["write"].get("iops") or 0.0,
                   p50_ms=out["write"].get("lat_p50_ms"),
                   p99_ms=out["write"].get("lat_p99_ms"),
                   engine=out.get("copy", {}).get("engine")))


def worker_balancer():
    """The placement-quality lane (ROADMAP item 5): the mgr balancer
    module's closed loop driven offline against a synthetic N-OSD map
    with seeded-uneven weights (ceph_tpu/mgr/synthetic.py), every
    evaluation ONE batched PoolMapper launch per pool.  Records
    rounds-to-converge, initial/final deviation stddev, and sweep
    mappings/s; CEPH_TPU_BALANCE_OUT writes the BALANCE_r*.json body
    tools/perf_history.py ingests.

    Env knobs (the tier-1 smoke test shrinks the workload):
    CEPH_TPU_BALANCE_OSDS / _PGS / _SEED / _MAX_DEVIATION / _ITERS /
    _ROUNDS / _CLASSES (comma list, e.g. 'ssd,hdd') / _OUT."""
    t_boot = time.perf_counter()
    import jax

    _enable_compile_cache()
    plat = jax.devices()[0].platform
    _emit(stage="init", platform=plat,
          init_s=round(time.perf_counter() - t_boot, 1))

    from ceph_tpu.mgr import make_synthetic_map, run_offline

    n_osds = int(os.environ.get("CEPH_TPU_BALANCE_OSDS", 1000))
    pg_num = int(os.environ.get("CEPH_TPU_BALANCE_PGS", 4096))
    seed = int(os.environ.get("CEPH_TPU_BALANCE_SEED", 10))
    max_dev = int(os.environ.get("CEPH_TPU_BALANCE_MAX_DEVIATION", 1))
    iters = int(os.environ.get("CEPH_TPU_BALANCE_ITERS", 400))
    rounds = int(os.environ.get("CEPH_TPU_BALANCE_ROUNDS", 40))
    classes = [c for c in os.environ.get(
        "CEPH_TPU_BALANCE_CLASSES", "").split(",") if c]

    m, w, _rules = make_synthetic_map(
        n_osds=n_osds, pg_num=pg_num, seed=seed, uneven=True,
        device_classes=classes or None)
    c0 = _lib_counters()
    rec = run_offline(m, w, max_deviation=max_dev,
                      max_iterations=iters, max_rounds=rounds,
                      seed=seed)
    reduction = (rec["initial_stddev"] / rec["final_stddev"]
                 if rec["final_stddev"] else float("inf"))
    rec.update(platform=plat, pg_num=pg_num,
               stddev_reduction=round(reduction, 2))
    _emit(stage="balancer",
          counters=_counter_deltas(c0, _lib_counters()),
          slo=_slo("balancer_sweep_mappings_per_sec",
                   rec["sweep_mappings_per_sec"]),
          **rec)
    out = os.environ.get("CEPH_TPU_BALANCE_OUT")
    if out:
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")


def worker_multichip():
    """The multichip scaling lane (ROADMAP item 1's acceptance gate):
    the mesh-sharded data plane measured 1-device vs N-device —
    PlacementPlane CRUSH mappings/s and stripe-batch-sharded EC encode
    GB/s — with a computed scaling-efficiency figure (N-device
    throughput / (N x 1-device)) and the per-device work breakdown in
    the stage JSON.

    On a host with no accelerator the worker forces the CPU backend to
    expose N virtual devices (--xla_force_host_platform_device_count,
    the dryrun/conftest layout): same code path, same breakdown, and
    the SLO floors are set so one core time-slicing N virtual devices
    still clears them.  Env knobs (the tier-1 smoke test shrinks the
    workload): CEPH_TPU_MULTICHIP_DEVICES / _MAP / _BATCH / _ITERS."""
    n_want = int(os.environ.get("CEPH_TPU_MULTICHIP_DEVICES", 8))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={n_want}").strip()
    t_boot = time.perf_counter()
    import jax

    _enable_compile_cache()
    plat = jax.devices()[0].platform
    devs = jax.devices()
    _emit(stage="init", platform=plat,
          init_s=round(time.perf_counter() - t_boot, 1),
          n_devices=len(devs))

    import numpy as np

    from ceph_tpu.parallel.placement import (PlacementPlane, make_mesh,
                                             mesh_device_report)

    on_accel = plat != "cpu"
    map_name = os.environ.get(
        "CEPH_TPU_MULTICHIP_MAP", "map_big10k")
    batch = int(os.environ.get(
        "CEPH_TPU_MULTICHIP_BATCH", (1 << 16) if on_accel else 4096))
    iters = int(os.environ.get(
        "CEPH_TPU_MULTICHIP_ITERS", 8 if on_accel else 4))

    cmap, case = _load_case(map_name)
    weight = case["weight_np"]
    mesh1 = make_mesh(devs[:1])
    meshN = make_mesh(devs)
    n_dev = len(devs)
    c0 = _lib_counters()

    def measure_plane(mesh, label):
        plane = PlacementPlane(cmap, mesh=mesh)
        # warmup = compile; golden-validate the sharded results
        res, lens = plane.map_batch(case["ruleno"],
                                    np.arange(batch, dtype=np.uint32),
                                    case["numrep"], weight)
        jax.block_until_ready(res)
        _golden_check(case, np.asarray(res), np.asarray(lens),
                      f"{plat}/multichip/{label}")
        t0 = time.perf_counter()
        for i in range(iters):
            xs = np.arange(i * batch, (i + 1) * batch,
                           dtype=np.uint32)
            res, lens = plane.map_batch(case["ruleno"], xs,
                                        case["numrep"], weight)
        jax.block_until_ready(res)
        dt = time.perf_counter() - t0
        return batch * iters / dt

    crush_1 = measure_plane(mesh1, "1dev")
    crush_n = measure_plane(meshN, f"{n_dev}dev")
    crush_eff = crush_n / (n_dev * crush_1) if crush_1 else 0.0

    # EC: the stripe-batch-sharded encode, RS(8,3) over B stripes
    from ceph_tpu.ec.rs_jax import RSCode

    bc = RSCode(8, 3)._bit
    B = int(os.environ.get("CEPH_TPU_MULTICHIP_EC_BATCH", 16))
    chunk = int(os.environ.get(
        "CEPH_TPU_MULTICHIP_EC_CHUNK",
        (1 << 18) if on_accel else (1 << 16)))
    rng = np.random.default_rng(5)
    stripes = rng.integers(0, 256, (B, 8, chunk), dtype=np.uint8)
    ec_iters = max(2, iters)

    def measure_encode(mesh):
        out = bc.encode_batched_sharded(stripes, mesh)
        jax.block_until_ready(out)  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(ec_iters):
            out = bc.encode_batched_sharded(stripes, mesh)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        return B * 8 * chunk * ec_iters / dt / 1e9

    ec_1 = measure_encode(mesh1)
    ec_n = measure_encode(meshN)
    ec_eff = ec_n / (n_dev * ec_1) if ec_1 else 0.0

    _emit(stage="multichip", platform=plat, n_devices=n_dev,
          map=map_name, batch=batch, iters=iters,
          crush_1dev_mappings_per_sec=round(crush_1, 1),
          crush_ndev_mappings_per_sec=round(crush_n, 1),
          crush_scaling_efficiency=round(crush_eff, 4),
          ec_batch=B, ec_chunk=chunk,
          ec_1dev_gbps=round(ec_1, 4),
          ec_ndev_gbps=round(ec_n, 4),
          ec_scaling_efficiency=round(ec_eff, 4),
          per_device=mesh_device_report(meshN),
          counters=_counter_deltas(c0, _lib_counters()),
          slo=[_slo("multichip_crush_mappings_per_sec", crush_n),
               _slo("multichip_encode_gbps", ec_n)])


# ---------------------------------------------------------------------------
# parent side (orchestration; no jax import)
# ---------------------------------------------------------------------------

def _spawn(phase: str, platform: str):
    """Start a worker subprocess; platform 'cpu' pins the CPU backend
    through BOTH channels (env var and CEPH_TPU_PLATFORM → jax.config),
    since preloaded images can make the env var alone a no-op.  Worker
    stderr is inherited so its diagnostics stream into the bench log."""
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["CEPH_TPU_PLATFORM"] = "cpu"
        # the axon sitecustomize hook registers the TPU PJRT plugin in
        # every process when this var is set, and a registered plugin is
        # initialized by backend discovery even under JAX_PLATFORMS=cpu —
        # hanging forever when the TPU tunnel is down.  CPU workers must
        # never touch it.
        env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--worker", phase],
        env=env, stdout=subprocess.PIPE, stderr=None,
        text=True, cwd=str(REPO))


class Stream:
    """Reads a worker's stdout in a thread, collecting BENCH_RESULT
    lines the moment they appear — a stalled later stage can never cost
    an earlier one."""

    def __init__(self, proc, label):
        self.proc, self.label = proc, label
        self.results = []
        self.t0 = time.perf_counter()
        self._th = threading.Thread(target=self._read, daemon=True)
        self._th.start()

    def _read(self):
        try:
            for line in self.proc.stdout:
                if not line.startswith(RESULT_TAG):
                    continue
                r = json.loads(line[len(RESULT_TAG):])
                r["_t"] = round(time.perf_counter() - self.t0, 1)
                self.results.append(r)
                print(f"# {self.label}: {r.get('stage')}"
                      f"{('/' + r['map']) if 'map' in r else ''}"
                      f"{('/' + r['tag']) if 'tag' in r else ''}"
                      f" landed at t={r['_t']}s", file=sys.stderr)
        except Exception:
            pass

    def find(self, pred):
        return next((r for r in self.results if pred(r)), None)

    def wait(self, pred, deadline):
        """Poll until pred matches, the worker exits (grace for the
        reader to drain), or the deadline expires."""
        end = self.t0 + deadline
        while True:
            got = self.find(pred)
            if got is not None:
                return got
            if self.proc.poll() is not None:
                self._th.join(timeout=5)
                return self.find(pred)
            if time.perf_counter() >= end:
                return None
            time.sleep(0.1)

    def alive(self):
        return self.proc.poll() is None

    def kill(self, why=""):
        if self.alive():
            self.proc.kill()
            print(f"# {self.label}: killed"
                  f"{' (' + why + ')' if why else ''} at "
                  f"t={time.perf_counter() - self.t0:.0f}s",
                  file=sys.stderr)


def main():
    force_cpu = os.environ.get("CEPH_TPU_PLATFORM", "") == "cpu"

    cpu = Stream(_spawn("crush_cpu", "cpu"), "crush/cpu")
    acc = None if force_cpu else Stream(_spawn("staged", "default"),
                                        "staged/default")

    is_crush = lambda r: r.get("stage") == "crush"  # noqa: E731
    is_big = lambda r: is_crush(r) and \
        r.get("map") == "map_big10k"  # noqa: E731

    acc_big = acc_tiny = None
    backend_init_failed = False
    if acc is not None:
        # short-deadline backend-init probe: the init line is the
        # worker's FIRST emission (before any compile), so its absence
        # pins the hang to backend init — fail fast with a diagnostic
        # instead of burning the full worker deadline on a dead tunnel
        init = acc.wait(lambda r: r.get("stage") == "init",
                        min(INIT_DEADLINE, TPU_DEADLINE))
        if init is None:
            backend_init_failed = True
            acc.kill("no init line — backend init hang")
            print("# staged/default: accelerator backend never "
                  f"initialized within {INIT_DEADLINE:.0f}s — hang "
                  "pinned to backend init (TPU tunnel down / PJRT "
                  "plugin wedged); recording backend_init_failed and "
                  "falling back to the CPU figure", file=sys.stderr)
            acc = None
        elif init["platform"] == "cpu":
            print("# staged/default: resolved to cpu (no accelerator "
                  "attached)", file=sys.stderr)
            acc.kill("cpu resolution; native worker owns the figure")
            acc = None
        else:
            acc_big = acc.wait(is_big, TPU_DEADLINE)
            if acc_big is not None:
                # both mapper engines (xla-spec, xla) report on the big
                # map; give the second a bounded grace window and keep
                # the faster figure
                grace = min(TPU_DEADLINE,
                            (time.perf_counter() - acc.t0) + 90)
                acc.wait(lambda r: sum(
                    1 for x in acc.results if is_big(x)) >= 2, grace)

            def engine_of(label):
                return "xla-spec" if label.startswith("spec/") \
                    else "xla"

            tainted = {engine_of(r.get("label", ""))
                       for r in acc.results
                       if r.get("stage") == "golden_failure"}
            usable = lambda r: r.get("engine") not in tainted  # noqa
            bigs = [r for r in acc.results if is_big(r)
                    and usable(r)]
            acc_big = max(bigs, key=lambda r: r.get("rate", 0.0)) \
                if bigs else None
            acc_tiny = max(
                (r for r in acc.results
                 if is_crush(r) and not is_big(r) and usable(r)),
                key=lambda r: r.get("rate", 0.0), default=None)
            if acc_big is None and acc_tiny is None:
                acc.kill("no crush stage within deadline")

    cpu_res = cpu.wait(is_crush, CPU_DEADLINE)
    if cpu_res is None:
        cpu.kill("deadline")

    headline = acc_big or acc_tiny or cpu_res
    if headline is None:
        # last resort: tiny in-process CPU run so the line still lands
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["CEPH_TPU_PLATFORM"] = "cpu"
        print("# all crush workers failed; in-process cpu fallback",
              file=sys.stderr)
        import contextlib
        import io
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf):
                worker_crush_cpu(batch=1 << 10, iters=1)
        except Exception as e:
            print(f"# in-process fallback failed too: {e}",
                  file=sys.stderr)
        for line in buf.getvalue().splitlines():
            if line.startswith(RESULT_TAG):
                headline = json.loads(line[len(RESULT_TAG):])
    if headline is None:
        # absolute sentinel: the contract is one JSON line, always
        headline = {"rate": 0.0, "platform": "none"}

    rate = headline["rate"]
    out = {
        "metric": "crush_mappings_per_sec",
        "value": round(rate, 1),
        "unit": "mappings/s",
        "platform": headline["platform"],
        "vs_baseline": round(rate / CPU_BASELINE_MAPPINGS_PER_SEC, 2),
        "engine": headline.get("engine"),
        "map": headline.get("map"),
        "compile_s": headline.get("compile_s"),
        "measure_s": headline.get("measure_s"),
        "cpu_rate": round(cpu_res["rate"], 1) if cpu_res else None,
        "cpu_engine": cpu_res.get("engine") if cpu_res else None,
        "slo": headline.get("slo") or _slo(
            "crush_big10k_mappings_per_sec", rate),
    }
    if backend_init_failed:
        out["backend_init_failed"] = True
    if headline.get("map") == "map_flat12":
        # tiny-map figure: comparable in spirit, not in map scale —
        # flagged so the record can never overclaim
        out["note"] = "accel rate from flat12 tiny map; 10k-map stage "\
            "did not land"
    print(json.dumps(out), flush=True)  # the ONE line — lands first

    # EC phase (secondary; stderr only, can never cost the headline)
    is_ec = lambda r: r.get("stage") == "ec"  # noqa: E731
    ec_res = None
    if acc is not None and (acc.alive() or acc.find(is_ec)):
        elapsed = time.perf_counter() - acc.t0
        ec_res = acc.wait(is_ec, elapsed + EC_DEADLINE)
        large = acc.wait(
            lambda r: is_ec(r) and r.get("tag") == "large",
            elapsed + EC_DEADLINE)
        ec_res = large or ec_res
        acc.kill("ec stages resolved")
    prof_res = []
    batch_res = None
    if acc is not None:
        batch_res = acc.find(lambda r: r.get("stage") == "ec_batch")
    if ec_res is None:
        ecw = Stream(_spawn("ec_cpu", "cpu"), "ec/cpu")
        ec_res = ecw.wait(is_ec, EC_DEADLINE)
        # the profile stages run after the headline stage: give them
        # their own window beyond whatever the headline consumed
        ecw.wait(lambda r: sum(1 for x in ecw.results
                               if x.get("stage") == "ec_profile") >= 2,
                 (time.perf_counter() - ecw.t0) + 60)
        prof_res = [r for r in ecw.results
                    if r.get("stage") == "ec_profile"]
        if batch_res is None:
            batch_res = ecw.find(
                lambda r: r.get("stage") == "ec_batch")
        ecw.kill("done")
    else:
        # the accelerator worker covered the headline EC stage; the
        # BASELINE config 2/4 profiles are CPU-engine figures and must
        # land either way
        pw = Stream(_spawn("ec_profiles", "cpu"), "ec/profiles")
        pw.wait(lambda r: sum(1 for x in pw.results
                              if x.get("stage") == "ec_profile") >= 2,
                90)
        prof_res = [r for r in pw.results
                    if r.get("stage") == "ec_profile"]
        pw.kill("done")
    if ec_res is not None:
        print(f"# ec k=8,m=3: encode {ec_res['encode_gbps']:.2f} GB/s, "
              f"decode {ec_res['decode_gbps']:.2f} GB/s on "
              f"{ec_res['platform']} (compile {ec_res['compile_s']}s)",
              file=sys.stderr)
    for r in prof_res:  # BASELINE configs 2 and 4
        extras = {k: v for k, v in r.items()
                  if k not in ("stage", "profile", "_t")}
        print(f"# ec {r['profile']}: {extras}", file=sys.stderr)
    if batch_res is not None:
        print(f"# ec batched encode {batch_res['n_stripes']}x"
              f"{batch_res['k']}x{batch_res['chunk']}B: "
              f"{batch_res['batched_gbps']} GB/s batched vs "
              f"{batch_res['per_stripe_gbps']} GB/s per-stripe "
              f"({batch_res['speedup']}x) on "
              f"{batch_res['platform']}", file=sys.stderr)
    if acc is not None:
        acc.kill("bench done")

    # cluster throughput phase (secondary; rados-bench analogue):
    # pipelined-write qd sweep + seq read
    clw = Stream(_spawn("cluster", "cpu"), "cluster/cpu")
    cl_res = clw.wait(lambda r: r.get("stage") == "cluster", 120)
    clw.kill("done")
    # multichip scaling phase (ROADMAP item 1's measurement surface):
    # ride the accelerator when the staged lane proved one is alive,
    # else the 8-virtual-device CPU mesh; same init fail-fast probe as
    # the staged lane so a dead tunnel costs INIT_DEADLINE, not the
    # full multichip budget
    mc_plat = "default" if headline.get("platform") not in (
        None, "cpu", "none") else "cpu"
    mcw = Stream(_spawn("multichip", mc_plat), f"multichip/{mc_plat}")
    mc_res = None
    if mcw.wait(lambda r: r.get("stage") == "init",
                min(INIT_DEADLINE, MULTICHIP_DEADLINE)) is None:
        mcw.kill("no init line — backend init hang")
    else:
        mc_res = mcw.wait(lambda r: r.get("stage") == "multichip",
                          MULTICHIP_DEADLINE)
    mcw.kill("done")
    if mc_res is not None:
        print(f"# multichip {mc_res['n_devices']}-dev "
              f"({mc_res['platform']}): crush "
              f"{mc_res['crush_ndev_mappings_per_sec']} vs "
              f"{mc_res['crush_1dev_mappings_per_sec']} mappings/s "
              f"1-dev (eff {mc_res['crush_scaling_efficiency']}); "
              f"ec encode {mc_res['ec_ndev_gbps']} vs "
              f"{mc_res['ec_1dev_gbps']} GB/s 1-dev (eff "
              f"{mc_res['ec_scaling_efficiency']})", file=sys.stderr)
        print("# multichip json: " + json.dumps(mc_res),
              file=sys.stderr)
        for blk in mc_res.get("slo") or []:
            if "pass" in blk:
                print(f"# slo {blk['metric']}: value "
                      f"{blk.get('value')} floor {blk.get('floor')} "
                      f"-> {'PASS' if blk['pass'] else 'FAIL'}",
                      file=sys.stderr)
    if cl_res is not None:
        print(f"# cluster 4-osd: write {cl_res['write_iops']} IOPS "
              f"({cl_res['write_mbps']} MB/s, p99 "
              f"{cl_res['write_p99_ms']} ms) at qd="
              f"{cl_res.get('write_qd')}; qd sweep "
              f"{cl_res.get('qd_sweep')}; seq {cl_res['seq_iops']}"
              f" IOPS ({cl_res['seq_mbps']} MB/s)", file=sys.stderr)
        print("# cluster json: " + json.dumps(cl_res),
              file=sys.stderr)
        attr = cl_res.get("attribution") or {}
        if attr:
            print(f"# attribution: {attr.get('n_ops')} traced ops, "
                  f"unattr {attr.get('unattr_pct')}% of "
                  f"critical path, client p50 "
                  f"{attr.get('client_p50_ms')} ms", file=sys.stderr)
        copyb = cl_res.get("copy") or {}
        if copyb:
            print(f"# copy ledger: "
                  f"{copyb.get('bytes_per_op')} bytes copied/op "
                  f"({copyb.get('copies')} copies, sites "
                  f"{copyb.get('sites')})", file=sys.stderr)
        prof = cl_res.get("profiler") or {}
        if prof:
            print(f"# profiler: {prof.get('samples')} samples at "
                  f"{prof.get('hz')} Hz across "
                  f"{prof.get('daemons')} daemons, overhead "
                  f"{prof.get('overhead_pct')}%", file=sys.stderr)
        slo = cl_res.get("slo") or {}
        if "pass" in slo:
            print(f"# slo cluster_write_iops: value "
                  f"{slo.get('value')} floor {slo.get('floor')} -> "
                  f"{'PASS' if slo['pass'] else 'FAIL'}",
                  file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        from ceph_tpu.utils.platform import apply_platform_env

        apply_platform_env()
        {"staged": worker_staged,
         "crush_cpu": worker_crush_cpu,
         "ec_cpu": worker_ec_cpu,
         "ec_profiles": lambda: _try_stage(
             "ec/profiles", _stage_ec_profiles),
         "cluster": worker_cluster,
         "multichip": worker_multichip,
         "balancer": worker_balancer}[sys.argv[2]]()
    else:
        main()
