"""EC benchmark CLI — encode/decode throughput per plugin/profile.

The role of src/test/erasure-code/ceph_erasure_code_benchmark.cc:40-330
with the same knobs: --plugin, --workload encode|decode, --size,
--iterations, --parameter k=v profile entries, --erasures N and
--erasures-generation random|exhaustive (the decode sweep), --verify
(decode output checked against the original, :225-236).  Output is the
reference's two-column `elapsed \t KiB` line per run plus a summary
GB/s figure.

Usage: python -m ceph_tpu.tools.ec_benchmark --plugin jerasure \
         -P k=4 -P m=2 --workload encode --size 16777216
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np

from ..ec.registry import factory


def exhaustive_erasures(n: int, count: int):
    return itertools.combinations(range(n), count)


def random_erasures(n: int, count: int, iterations: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    for _ in range(iterations):
        yield tuple(sorted(rng.choice(n, count, replace=False)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_benchmark")
    p.add_argument("--plugin", default="jerasure")
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="profile key=value")
    p.add_argument("--workload", choices=["encode", "decode"],
                   default="encode")
    p.add_argument("--size", type=int, default=1 << 20,
                   help="total object bytes per iteration")
    p.add_argument("--iterations", type=int, default=8)
    p.add_argument("--erasures", type=int, default=1)
    p.add_argument("--erasures-generation",
                   choices=["random", "exhaustive"], default="random")
    p.add_argument("--verify", action="store_true")
    args = p.parse_args(argv)

    profile = {}
    for kv in args.parameter:
        k, _, v = kv.partition("=")
        profile[k] = v
    code = factory(args.plugin, profile)
    n = code.get_chunk_count()

    rng = np.random.default_rng(1)
    raw = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    chunks = code.encode(range(n), raw)

    total_bytes = 0
    t0 = time.perf_counter()
    if args.workload == "encode":
        for _ in range(args.iterations):
            code.encode(range(n), raw)
            total_bytes += args.size
    else:
        if args.erasures_generation == "exhaustive":
            gen = exhaustive_erasures(n, args.erasures)
        else:
            gen = random_erasures(n, args.erasures, args.iterations)
        want = {code.chunk_index(i)
                for i in range(code.get_data_chunk_count())}
        for erased in gen:
            avail = {i: c for i, c in chunks.items()
                     if i not in erased}
            out = code.decode(want, avail)
            if args.verify:
                got = b"".join(
                    np.asarray(out[code.chunk_index(i)],
                               np.uint8).tobytes()
                    for i in range(code.get_data_chunk_count()))
                assert got[:len(raw)] == raw, \
                    f"verify failed for erasures {erased}"
            total_bytes += args.size
    elapsed = time.perf_counter() - t0

    # the reference's output shape (benchmark.cc:184,315)
    print(f"{elapsed:.6f}\t{total_bytes // 1024}")
    print(f"# {args.plugin} {args.workload}: "
          f"{total_bytes / elapsed / 1e9:.3f} GB/s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
