"""CrushCompiler — text crushmap ⇄ CrushWrapper.

The role of src/crush/CrushCompiler.cc (grammar per src/crush/grammar.h
:30-200): the `crushtool -c/-d` text format — tunables, devices (with
device classes), types, buckets (id / shadow class ids / alg / hash /
items with float weights), and rules (take [class], choose/chooseleaf
firstn/indep, set_* steps, emit).  The grammar is line-oriented, so the
parser here is a line tokenizer rather than a spirit grammar; it
accepts the reference's own decompiler output.

Not carried: `tunable straw_calc_version` / `allowed_bucket_algs`
(parsed and ignored — the framework always computes straw v1 and
allows every alg) and the `# choose_args` section (weight-sets travel
in the native JSON map format instead; the balancer's crush-compat
mode operates on live maps, not text files).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from ..crush import constants as C
from ..crush.map import Bucket, CrushMap, Rule, RuleStep, Tunables
from ..crush.wrapper import CrushWrapper

_TUNABLES = {
    "choose_local_tries": "choose_local_tries",
    "choose_local_fallback_tries": "choose_local_fallback_tries",
    "choose_total_tries": "choose_total_tries",
    "chooseleaf_descend_once": "chooseleaf_descend_once",
    "chooseleaf_vary_r": "chooseleaf_vary_r",
    "chooseleaf_stable": "chooseleaf_stable",
}
_IGNORED_TUNABLES = {"straw_calc_version", "allowed_bucket_algs"}

_SET_STEPS = {
    "set_choose_tries": C.CRUSH_RULE_SET_CHOOSE_TRIES,
    "set_choose_local_tries": C.CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries":
        C.CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_tries": C.CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    "set_chooseleaf_vary_r": C.CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": C.CRUSH_RULE_SET_CHOOSELEAF_STABLE,
}
_SET_STEP_NAMES = {v: k for k, v in _SET_STEPS.items()}

_CHOOSE_OPS = {
    ("choose", "firstn"): C.CRUSH_RULE_CHOOSE_FIRSTN,
    ("choose", "indep"): C.CRUSH_RULE_CHOOSE_INDEP,
    ("chooseleaf", "firstn"): C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
    ("chooseleaf", "indep"): C.CRUSH_RULE_CHOOSELEAF_INDEP,
}


class CompileError(ValueError):
    def __init__(self, lineno: int, msg: str):
        super().__init__(f"line {lineno}: {msg}")
        self.lineno = lineno


def _tokens(text: str):
    """Yield (lineno, [token...]) with comments stripped."""
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if line:
            yield lineno, line.replace("{", " { ").replace(
                "}", " } ").split()


def _w16(s: str) -> int:
    return int(round(float(s) * 0x10000))


def _wf(w: int) -> str:
    return f"{w / 0x10000:.5f}"


# ---------------------------------------------------------------------------
# compile: text -> CrushWrapper
# ---------------------------------------------------------------------------

def compile_crushmap(text: str) -> CrushWrapper:
    w = CrushWrapper(CrushMap(), types={})
    # (bucket_name, shadow_id, class_name) declarations to register
    shadow_decls: List[Tuple[str, int, str]] = []
    lines = list(_tokens(text))
    i = 0
    while i < len(lines):
        lineno, t = lines[i]
        head = t[0]
        if head == "tunable":
            if len(t) != 3:
                raise CompileError(lineno, "tunable <name> <value>")
            if t[1] in _TUNABLES:
                setattr(w.crush.tunables, _TUNABLES[t[1]], int(t[2]))
            elif t[1] not in _IGNORED_TUNABLES:
                raise CompileError(lineno,
                                   f"tunable {t[1]} not recognized")
            i += 1
        elif head == "device":
            # device <id> <name> [class <class>]
            if len(t) < 3:
                raise CompileError(lineno, "device <id> <name>")
            dev = int(t[1])
            name = t[2]
            if name != f"device{dev}":  # unnamed holes use deviceN
                w.set_item_name(dev, name)
            w.crush.max_devices = max(w.crush.max_devices, dev + 1)
            if len(t) >= 5 and t[3] == "class":
                w.set_item_class(dev, t[4])
            i += 1
        elif head == "type":
            if len(t) != 3:
                raise CompileError(lineno, "type <id> <name>")
            w.set_type_name(int(t[1]), t[2])
            i += 1
        elif head == "rule":
            i = _parse_rule(w, lines, i)
        elif len(t) >= 3 and t[-1] == "{":
            i = _parse_bucket(w, lines, i, shadow_decls)
        else:
            raise CompileError(lineno, f"unrecognized: {' '.join(t)}")

    # shadow-id declarations: pin the registry so populate_classes
    # reuses the ids the text map promised
    for bname, sid, cname in shadow_decls:
        bid = w.get_item_id(bname)
        cid = w.get_or_create_class_id(cname)
        w._shadow_id_registry[(bid, cid)] = sid
    if w.class_map:
        w.populate_classes()
    _resolve_takes(w)
    return w


def _parse_bucket(w: CrushWrapper, lines, i, shadow_decls) -> int:
    lineno, t = lines[i]
    type_name, name = t[0], t[1]
    try:
        type_id = w.get_type_id(type_name)
    except KeyError:
        raise CompileError(lineno, f"unknown type {type_name}")
    bid = 0
    alg = C.CRUSH_BUCKET_STRAW2
    hash_ = C.CRUSH_HASH_RJENKINS1
    items: List[Tuple[str, int]] = []
    i += 1
    while i < len(lines):
        lineno, t = lines[i]
        if t[0] == "}":
            i += 1
            break
        if t[0] == "id":
            if len(t) >= 4 and t[2] == "class":
                shadow_decls.append((name, int(t[1]), t[3]))
            else:
                bid = int(t[1])
        elif t[0] == "alg":
            if t[1] not in C.ALG_IDS:
                raise CompileError(lineno, f"unknown alg {t[1]}")
            alg = C.ALG_IDS[t[1]]
        elif t[0] == "hash":
            hash_ = int(t[1])
        elif t[0] == "item":
            # item <name> weight <w> [pos <n>]
            iw = 0x10000
            if "weight" in t:
                iw = _w16(t[t.index("weight") + 1])
            items.append((t[1], iw))
        elif t[0] == "weight":
            pass  # informational; recomputed from items
        else:
            raise CompileError(lineno, f"unrecognized in bucket: {t[0]}")
        i += 1
    else:
        raise CompileError(lineno, f"bucket {name}: missing }}")

    ids: List[int] = []
    weights: List[int] = []
    for iname, iw in items:
        try:
            ids.append(w.get_item_id(iname))
        except KeyError:
            raise CompileError(lineno, f"unknown item {iname}")
        weights.append(iw)
    from ..crush.builder import (make_list_bucket, make_straw2_bucket,
                                 make_tree_bucket, make_uniform_bucket,
                                 calc_straw)

    if alg == C.CRUSH_BUCKET_UNIFORM:
        if len(set(weights)) > 1:
            raise CompileError(
                lineno, f"bucket {name}: uniform buckets require "
                        f"equal item weights")
        b = make_uniform_bucket(ids, weights[0] if weights else 0x10000,
                                type_id, bid, hash_)
    elif alg == C.CRUSH_BUCKET_LIST:
        b = make_list_bucket(ids, weights, type_id, bid, hash_)
    elif alg == C.CRUSH_BUCKET_TREE:
        b = make_tree_bucket(ids, weights, type_id, bid, hash_)
    else:
        b = make_straw2_bucket(ids, weights, type_id, bid, hash_)
        b.alg = alg  # straw or straw2
        if alg == C.CRUSH_BUCKET_STRAW:
            b.straws = calc_straw(weights)
    got = w.crush.add_bucket(b)
    w.set_item_name(got, name)
    return i


def _parse_rule(w: CrushWrapper, lines, i) -> int:
    lineno, t = lines[i]
    name = t[1] if len(t) >= 3 else f"rule{len(w.crush.rules)}"
    ruleno = -1
    rtype = 1
    steps: List = []  # RuleStep or ("take", name, class)
    i += 1
    while i < len(lines):
        lineno, t = lines[i]
        if t[0] == "}":
            i += 1
            break
        if t[0] in ("id", "ruleset"):
            ruleno = int(t[1])
        elif t[0] == "type":
            rtype = {"replicated": 1, "erasure": 3}.get(
                t[1], None)
            if rtype is None:
                rtype = int(t[1])
        elif t[0] in ("min_size", "max_size"):
            pass  # deprecated, accepted
        elif t[0] == "step":
            steps.append(_parse_step(lineno, t[1:], w))
        else:
            raise CompileError(lineno, f"unrecognized in rule: {t[0]}")
        i += 1
    else:
        raise CompileError(lineno, f"rule {name}: missing }}")
    rule = Rule(steps=[], type=rtype)
    rule.steps = steps  # may contain symbolic takes; resolved later
    rid = w.crush.add_rule(rule, ruleno)
    w.rule_name_map[rid] = name
    return i


def _parse_step(lineno, t, w):
    op = t[0]
    if op == "noop":
        return RuleStep(C.CRUSH_RULE_NOOP, 0, 0)
    if op == "emit":
        return RuleStep(C.CRUSH_RULE_EMIT, 0, 0)
    if op == "take":
        cls = t[t.index("class") + 1] if "class" in t else ""
        return ("take", t[1], cls)
    if op in _SET_STEPS:
        return RuleStep(_SET_STEPS[op], int(t[1]), 0)
    if op in ("choose", "chooseleaf"):
        key = (op, t[1])
        if key not in _CHOOSE_OPS:
            raise CompileError(lineno, f"step {op} {t[1]}?")
        n = int(t[2])
        if t[3] != "type":
            raise CompileError(lineno, f"step {op}: expected 'type'")
        try:
            type_id = w.get_type_id(t[4])
        except KeyError:
            raise CompileError(lineno, f"unknown type {t[4]}")
        return RuleStep(_CHOOSE_OPS[key], n, type_id)
    raise CompileError(lineno, f"unknown step {op}")


def _resolve_takes(w: CrushWrapper) -> None:
    """Resolve symbolic ('take', name, class) steps to item ids (after
    all buckets exist and shadows are built)."""
    for rule in w.crush.rules.values():
        resolved = []
        for s in rule.steps:
            if isinstance(s, tuple):
                _tag, name, cls = s
                bid = w.get_item_id(name)
                if cls:
                    cid = w.get_or_create_class_id(cls)
                    w.populate_classes()
                    shadow = w.class_bucket.get((bid, cid))
                    if shadow is None:
                        raise CompileError(
                            0, f"take {name} class {cls}: no such "
                               f"shadow tree")
                    bid = shadow
                resolved.append(RuleStep(C.CRUSH_RULE_TAKE, bid, 0))
            else:
                resolved.append(s)
        rule.steps = resolved


# ---------------------------------------------------------------------------
# decompile: CrushWrapper -> text
# ---------------------------------------------------------------------------

def decompile_crushmap(w: CrushWrapper) -> str:
    out: List[str] = ["# begin crush map"]
    tn = w.crush.tunables
    for key in _TUNABLES.values():
        out.append(f"tunable {key} {getattr(tn, key)}")

    out.append("\n# devices")
    for dev in range(w.crush.max_devices):
        name = w.name_map.get(dev)
        if name is None:
            continue
        cls = w.get_item_class(dev)
        out.append(f"device {dev} {name}"
                   + (f" class {cls}" if cls else ""))

    out.append("\n# types")
    for t in sorted(w.type_map):
        out.append(f"type {t} {w.type_map[t]}")

    out.append("\n# buckets")
    # reverse id order, skipping shadow trees (they are emitted as
    # `id ... class ...` lines inside their original bucket)
    shadow_by_orig: Dict[int, List[Tuple[int, str]]] = {}
    for (oid, cid), sid in sorted(w.class_bucket.items()):
        shadow_by_orig.setdefault(oid, []).append(
            (sid, w.class_name[cid]))
    for idx in sorted(w.crush.buckets):
        b = w.crush.buckets[idx]
        if b.id in w._shadow_ids:
            continue
        out.append(f"{w.get_type_name(b.type)} "
                   f"{w.get_item_name(b.id)} {{")
        out.append(f"\tid {b.id}")
        for sid, cname in shadow_by_orig.get(b.id, []):
            out.append(f"\tid {sid} class {cname}")
        out.append(f"\t# weight {_wf(b.weight)}")
        out.append(f"\talg {C.ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for pos, item in enumerate(b.items):
            out.append(f"\titem {w.get_item_name(item)} "
                       f"weight {_wf(b.item_weight_at(pos))}")
        out.append("}")

    out.append("\n# rules")
    inv_shadow = {sid: (oid, cid)
                  for (oid, cid), sid in w.class_bucket.items()}
    for rno in sorted(w.crush.rules):
        rule = w.crush.rules[rno]
        out.append(f"rule {w.get_rule_name(rno)} {{")
        out.append(f"\tid {rno}")
        tname = {1: "replicated", 3: "erasure"}.get(rule.type,
                                                    str(rule.type))
        out.append(f"\ttype {tname}")
        for s in rule.steps:
            if s.op == C.CRUSH_RULE_NOOP:
                out.append("\tstep noop")
            elif s.op == C.CRUSH_RULE_TAKE:
                tgt = s.arg1
                if tgt in inv_shadow:
                    oid, cid = inv_shadow[tgt]
                    out.append(f"\tstep take {w.get_item_name(oid)} "
                               f"class {w.class_name[cid]}")
                else:
                    out.append(f"\tstep take {w.get_item_name(tgt)}")
            elif s.op == C.CRUSH_RULE_EMIT:
                out.append("\tstep emit")
            elif s.op in _SET_STEP_NAMES:
                out.append(f"\tstep {_SET_STEP_NAMES[s.op]} {s.arg1}")
            elif s.op in (C.CRUSH_RULE_CHOOSE_FIRSTN,
                          C.CRUSH_RULE_CHOOSE_INDEP,
                          C.CRUSH_RULE_CHOOSELEAF_FIRSTN,
                          C.CRUSH_RULE_CHOOSELEAF_INDEP):
                kind = "choose" if s.op in (
                    C.CRUSH_RULE_CHOOSE_FIRSTN,
                    C.CRUSH_RULE_CHOOSE_INDEP) else "chooseleaf"
                mode = "firstn" if s.op in (
                    C.CRUSH_RULE_CHOOSE_FIRSTN,
                    C.CRUSH_RULE_CHOOSELEAF_FIRSTN) else "indep"
                out.append(f"\tstep {kind} {mode} {s.arg1} type "
                           f"{w.get_type_name(s.arg2)}")
            else:
                raise ValueError(f"cannot decompile step op {s.op}")
        out.append("}")

    out.append("\n# end crush map")
    return "\n".join(out) + "\n"
