"""crushtool — compile/decompile/test/build crush maps.

The role of src/tools/crushtool.cc:365-1333 with the same verbs:

  -c <text>  -o <out.json>    compile text map -> native JSON map
  -d <map>   [-o <out.txt>]   decompile -> text
  -i <map> --test [...]       CrushTester sweep (batched mapper)
  -i <map> --compare <map2>   mapping diff between two maps
  -i <map> --build --num-osds N layer1 straw2 4 layer2 straw2 0 ...
  -i <map> --reweight         recompute bucket weights bottom-up
  -i <map> --tree             topology dump (CrushTreeDumper role)

The native binary format is JSON (CrushWrapper.to_dict) — the
framework's wire format; text maps are reference-grammar compatible.

Usage: python -m ceph_tpu.tools.crushtool ...
"""

from __future__ import annotations

import argparse
import json
import sys

from ..crush.builder import build_hierarchy
from ..crush.map import CrushMap
from ..crush.wrapper import CrushWrapper
from .compiler import compile_crushmap, decompile_crushmap
from .tester import CrushTester, format_report


def load_map(path: str) -> CrushWrapper:
    with open(path) as f:
        content = f.read()
    stripped = content.lstrip()
    if stripped.startswith("{"):
        d = json.loads(content)
        if "map" in d:
            return CrushWrapper.from_dict(d)
        return CrushWrapper(CrushMap.from_dict(d))
    return compile_crushmap(content)


def save_map(w: CrushWrapper, path: str) -> None:
    with open(path, "w") as f:
        json.dump(w.to_dict(), f)


def cmd_build(args) -> CrushWrapper:
    """--build: synthetic uniform hierarchy (crushtool.cc:135)."""
    w = CrushWrapper(CrushMap(), types={0: "osd"})
    spec = []
    layers = args.layers
    if len(layers) % 3:
        raise SystemExit("--build layers: <name> <alg> <size> triples")
    for i in range(0, len(layers), 3):
        name, alg, size = layers[i], layers[i + 1], int(layers[i + 2])
        if alg != "straw2":
            raise SystemExit(f"--build: only straw2 supported, "
                             f"got {alg}")
        type_id = i // 3 + 1
        w.set_type_name(type_id, name)
        spec.append((type_id,
                     size if size > 0 else args.num_osds))
    # fan-outs: size 0 means "all remaining" (one root)
    n = args.num_osds
    fixed = []
    for type_id, size in spec:
        if size == 0 or size >= n:
            fixed.append((type_id, n))
            n = 1
        else:
            fixed.append((type_id, size))
            n = (n + size - 1) // size
    root = build_hierarchy(w.crush, fixed)
    w.set_item_name(root, layers[-3] if layers else "root")
    for d in range(args.num_osds):
        w.set_item_name(d, f"osd.{d}")
    return w


def cmd_tree(w: CrushWrapper, out) -> None:
    """CrushTreeDumper-style topology listing."""
    def walk(bid: int, depth: int):
        name = w.get_item_name(bid)
        if bid >= 0:
            weight = 0
            p = w.get_immediate_parent_id(bid)
            if p is not None:
                b = w.get_bucket(p)
                weight = b.item_weight_at(b.items.index(bid))
            cls = w.get_item_class(bid)
            out.write(f"{'  ' * depth}{bid}\t{weight / 0x10000:.5f}"
                      f"\t{name}{' class ' + cls if cls else ''}\n")
            return
        b = w.get_bucket(bid)
        out.write(f"{'  ' * depth}{bid}\t{b.weight / 0x10000:.5f}"
                  f"\t{w.get_type_name(b.type)} {name}\n")
        for child in b.items:
            walk(child, depth + 1)

    roots = [b.id for b in w.crush.buckets.values()
             if w.get_immediate_parent_id(b.id) is None
             and b.id not in w._shadow_ids]
    for r in sorted(roots, reverse=True):
        walk(r, 0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-i", "--infn", help="input map (json or text)")
    p.add_argument("-o", "--outfn", help="output file")
    p.add_argument("-c", "--compile", dest="compilefn",
                   help="compile text map")
    p.add_argument("-d", "--decompile", dest="decompilefn",
                   help="decompile map")
    p.add_argument("--test", action="store_true")
    p.add_argument("--compare", help="second map to compare against")
    p.add_argument("--build", action="store_true")
    p.add_argument("--num-osds", type=int, default=0)
    p.add_argument("layers", nargs="*",
                   help="--build: <name> <alg> <size> triples")
    p.add_argument("--reweight", action="store_true")
    p.add_argument("--tree", action="store_true")
    p.add_argument("--create-replicated-rule", nargs=3,
                   metavar=("NAME", "ROOT", "FAILURE_DOMAIN"),
                   help="add a simple replicated rule "
                        "(crushtool.cc:1161 add_rule verb)")
    p.add_argument("--device-class", default="",
                   help="device class for --create-replicated-rule")
    # tester flags (crushtool.cc --test family)
    p.add_argument("--rule", type=int, default=-1)
    p.add_argument("--num-rep", type=int, default=0)
    p.add_argument("--min-rep", type=int, default=0)
    p.add_argument("--max-rep", type=int, default=0)
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1023)
    p.add_argument("--pool", type=int, default=None)
    p.add_argument("--weight", nargs=2, action="append", default=[],
                   metavar=("DEV", "WEIGHT"))
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    p.add_argument("--scalar", action="store_true",
                   help="use the scalar spec instead of the batched "
                        "mapper (tiny runs; no compile cost)")
    p.add_argument("--native", action="store_true",
                   help="use the native C++ host mapper (fast CPU "
                        "sweeps; builds on first use)")
    args = p.parse_args(argv)

    if args.compilefn:
        with open(args.compilefn) as f:
            w = compile_crushmap(f.read())
        save_map(w, args.outfn or "crushmap.json")
        return 0

    if args.decompilefn:
        w = load_map(args.decompilefn)
        text = decompile_crushmap(w)
        if args.outfn:
            with open(args.outfn, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0

    if args.build:
        if not args.num_osds:
            raise SystemExit("--build requires --num-osds")
        w = cmd_build(args)
        save_map(w, args.outfn or "crushmap.json")
        return 0

    if not args.infn:
        p.print_help()
        return 1
    w = load_map(args.infn)

    if args.create_replicated_rule:
        name, root, fd = args.create_replicated_rule
        w.add_simple_rule(name, root, fd, args.device_class, "firstn")
        save_map(w, args.outfn or args.infn)
        return 0

    if args.reweight:
        w.reweight()
        save_map(w, args.outfn or args.infn)
        return 0

    if args.tree:
        cmd_tree(w, sys.stdout)
        return 0

    if args.compare:
        other = load_map(args.compare)
        ta, tb = CrushTester(w), CrushTester(other)
        rules = [args.rule] if args.rule >= 0 \
            else sorted(w.crush.rules)
        for rno in rules:
            nrep = args.num_rep or 3
            diff, total = ta.compare(tb, rno, nrep, args.min_x,
                                     args.max_x, scalar=args.scalar)
            print(f"rule {rno}: {diff}/{total} mappings differ "
                  f"({100.0 * diff / max(1, total):.2f}%)")
        return 0

    if args.test:
        tester = CrushTester(w)
        for dev, wt in args.weight:
            tester.set_device_weight(int(dev), float(wt))
        rules = [args.rule] if args.rule >= 0 \
            else sorted(w.crush.rules)
        if not rules:
            print("crushtool: map has no rules; nothing to test "
                  "(use --create-replicated-rule)", file=sys.stderr)
            return 1
        min_rep = args.min_rep or args.num_rep or 3
        max_rep = args.max_rep or args.num_rep or 3
        for rno in rules:
            for nrep in range(min_rep, max_rep + 1):
                rep = tester.test_rule(
                    rno, nrep, args.min_x, args.max_x,
                    pool=args.pool, scalar=args.scalar,
                    native=args.native,
                    collect_mappings=args.show_mappings)
                print(format_report(
                    rep, w,
                    show_utilization=args.show_utilization,
                    show_statistics=args.show_statistics,
                    show_bad_mappings=args.show_bad_mappings,
                    show_mappings=args.show_mappings))
        return 0

    p.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
