"""CrushTester — the `crushtool --test` stats engine.

The role of src/crush/CrushTester.cc:432-747: run a rule over a range
of inputs, tally per-device utilization against the weight-proportional
expectation, report result-size statistics, bad mappings, and compare
two maps.  Where the reference loops ``crush.do_rule`` one x at a time
(:573, the hot loop the 50x BASELINE target measures), this engine maps
the whole x range in ONE batched launch (``BatchedMapper``) and derives
every statistic from the result arrays; ``scalar=True`` routes through
the executable spec instead (tiny runs, no compile cost).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..crush.hash import hash32_2_int
from ..crush.map import CrushMap
from ..crush.mapper_ref import crush_do_rule
from ..crush.wrapper import CrushWrapper


@dataclass
class RuleReport:
    """Stats for one (rule, num_rep) sweep."""

    ruleno: int
    num_rep: int
    min_x: int
    max_x: int
    total: int = 0
    size_counts: Dict[int, int] = field(default_factory=dict)
    device_stored: Optional[np.ndarray] = None
    device_expected: Optional[np.ndarray] = None
    bad: List[Tuple[int, List[int]]] = field(default_factory=list)
    mappings: Optional[List[List[int]]] = None

    @property
    def batch_size(self) -> int:
        return self.max_x - self.min_x + 1


class CrushTester:
    def __init__(self, wrapper: CrushWrapper,
                 weights: Optional[List[int]] = None):
        self.w = wrapper
        n = max(1, wrapper.crush.max_devices)
        self.weights = list(weights) if weights is not None \
            else [0x10000] * n
        while len(self.weights) < n:
            self.weights.append(0x10000)

    def set_device_weight(self, dev: int, weight: float) -> None:
        """--weight <dev> <w> (CrushTester.cc:454-462 semantics:
        fraction of full weight)."""
        self.weights[dev] = int(weight * 0x10000)

    # -- the sweep -----------------------------------------------------
    def test_rule(self, ruleno: int, num_rep: int, min_x: int = 0,
                  max_x: int = 1023, pool: Optional[int] = None,
                  scalar: bool = False, native: bool = False,
                  collect_mappings: bool = False,
                  mesh=None) -> RuleReport:
        """``mesh``: a ``jax.sharding.Mesh`` runs the sweep through
        ``parallel.PlacementPlane`` — ONE pjit launch maps the whole x
        range across every chip, and the per-device utilization tally
        comes back as the plane's all-reduced counts instead of a
        host-side loop (the CrushTester.cc:588-648 stats pass executed
        on-device)."""
        cmap = self.w.crush
        xs = np.arange(min_x, max_x + 1, dtype=np.uint32)
        if pool is not None:
            xs = np.asarray([hash32_2_int(int(x), pool) for x in xs],
                            np.uint32)  # CrushTester.cc:570-572
        counts = None
        if scalar:
            results = [crush_do_rule(cmap, ruleno, int(x), num_rep,
                                     self.weights) for x in xs]
            lens = [len(r) for r in results]
        elif mesh is not None:
            from ..parallel.placement import PlacementPlane

            plane = PlacementPlane(cmap, mesh=mesh)
            res, ln, counts = plane.map_batch(
                ruleno, xs, num_rep,
                np.asarray(self.weights, np.uint32),
                gather_stats=True)
            res, ln = np.asarray(res), np.asarray(ln)
            counts = np.asarray(counts)
            results = [list(res[i, :ln[i]]) for i in range(len(xs))]
            lens = list(ln)
        elif native:
            from ..crush.native import NativeMapper

            nm = NativeMapper(cmap)
            res, ln = nm.map_batch(
                ruleno, xs, num_rep,
                np.asarray(self.weights, np.uint32))
            results = [list(res[i, :ln[i]]) for i in range(len(xs))]
            lens = list(ln)
        else:
            from ..crush.mapper_jax import BatchedMapper

            bm = BatchedMapper(cmap)
            res, ln = bm.map_batch(
                ruleno, xs, num_rep,
                np.asarray(self.weights, np.uint32))
            res, ln = np.asarray(res), np.asarray(ln)
            results = [list(res[i, :ln[i]]) for i in range(len(xs))]
            lens = list(ln)

        rep = RuleReport(ruleno, num_rep, min_x, max_x)
        rep.total = len(xs)
        n_dev = cmap.max_devices
        if counts is not None:
            # the plane's all-reduced on-device tally IS the stats
            # pass — only the size histogram stays host-side
            stored = counts.astype(np.int64)
            for r in results:
                rep.size_counts[len(r)] = \
                    rep.size_counts.get(len(r), 0) + 1
        else:
            stored = np.zeros(n_dev, np.int64)
            for r in results:
                rep.size_counts[len(r)] = \
                    rep.size_counts.get(len(r), 0) + 1
                for o in r:
                    if 0 <= o < n_dev:
                        stored[o] += 1
        rep.device_stored = stored
        # expected: weight-proportional share of all placed replicas
        wv = np.asarray(self.weights[:n_dev], np.float64)
        placed = stored.sum()
        rep.device_expected = (wv / wv.sum() * placed) if wv.sum() \
            else np.zeros(n_dev)
        for i, r in enumerate(results):
            if len(r) != num_rep:
                rep.bad.append((int(xs[i]), r))
        if collect_mappings:
            rep.mappings = results
        return rep

    # -- compare (CrushTester.cc:682-747) ------------------------------
    def compare(self, other: "CrushTester", ruleno: int, num_rep: int,
                min_x: int = 0, max_x: int = 1023,
                scalar: bool = False) -> Tuple[int, int]:
        """Returns (#different mappings, total)."""
        a = self.test_rule(ruleno, num_rep, min_x, max_x,
                           scalar=scalar, collect_mappings=True)
        b = other.test_rule(ruleno, num_rep, min_x, max_x,
                            scalar=scalar, collect_mappings=True)
        diff = sum(1 for x, y in zip(a.mappings, b.mappings) if x != y)
        return diff, a.total


def format_report(rep: RuleReport, w: CrushWrapper,
                  show_utilization: bool = False,
                  show_statistics: bool = False,
                  show_bad_mappings: bool = False,
                  show_mappings: bool = False) -> str:
    """The crushtool --test output shapes (CrushTester.cc:588-680)."""
    name = w.get_rule_name(rep.ruleno)
    out = [f"rule {rep.ruleno} ({name}), x = {rep.min_x}..{rep.max_x}, "
           f"numrep = {rep.num_rep}..{rep.num_rep}"]
    if show_mappings and rep.mappings is not None:
        for i, m in enumerate(rep.mappings):
            out.append(f"CRUSH rule {rep.ruleno} x {rep.min_x + i} "
                       f"{list(m)}")
    if show_statistics:
        for size in sorted(rep.size_counts):
            out.append(f"rule {rep.ruleno} ({name}) num_rep "
                       f"{rep.num_rep} result size == {size}:\t"
                       f"{rep.size_counts[size]}/{rep.total}")
    if show_bad_mappings:
        for x, m in rep.bad:
            out.append(f"bad mapping rule {rep.ruleno} x {x} "
                       f"num_rep {rep.num_rep} result {list(m)}")
    if show_utilization:
        for dev in range(len(rep.device_stored)):
            st = int(rep.device_stored[dev])
            ex = float(rep.device_expected[dev])
            out.append(f"  device {dev}:\t\t stored : {st}\t "
                       f"expected : {ex:.6g}")
    return "\n".join(out)
