"""CLI tools — the reference's src/tools/ surface, TPU-backed.

- ``crushtool`` (compiler + tester): text crushmap compile/decompile,
  --test sweeps on the batched mapper, --build, --compare, --tree.
- ``osdmaptool``: --createsimple, --test-map-pgs over the fused
  placement pipeline, --upmap (the balancer), --upmap-cleanup.
- ``ec_benchmark``: per-plugin encode/decode throughput with
  exhaustive-erasure sweeps.

Each is an importable module (``main(argv)``) and a console entry
(``python -m ceph_tpu.tools.<name>``).
"""
