"""rados bench — the cluster throughput/latency harness.

The role of `rados bench` (src/tools/rados/rados.cc:107) and its
engine ObjBencher (src/common/obj_bencher.cc): drive a cluster with N
concurrent writers/readers for a fixed duration and report throughput,
IOPS, and latency percentiles.  Works against any mon address
(a running cluster) or self-hosts a MiniCluster for one-shot runs.

CLI:
    python -m ceph_tpu.tools.rados_bench write --seconds 5 \
        --concurrent 8 --object-size 65536 [--ec]
    ... seq | rand                     (read back what write created)

Output: one human summary on stderr and ONE JSON line on stdout —
the same one-line contract bench.py uses.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional

from ..analysis.lockdep import make_lock


class BenchResult:
    def __init__(self, op: str, object_size: int):
        self.op = op
        self.object_size = object_size
        self.latencies: List[float] = []
        self.errors = 0
        self.wall = 0.0
        self._lock = make_lock("bench::result")

    def add(self, dt: float) -> None:
        with self._lock:
            self.latencies.append(dt)

    def add_error(self) -> None:
        with self._lock:
            self.errors += 1

    def summary(self) -> Dict:
        lat = sorted(self.latencies)
        n = len(lat)
        if n == 0:
            return {"op": self.op, "ops": 0, "errors": self.errors}
        total_bytes = n * self.object_size
        return {
            "op": self.op,
            "ops": n,
            "errors": self.errors,
            "seconds": round(self.wall, 3),
            "iops": round(n / self.wall, 1) if self.wall else None,
            "mb_per_sec": round(total_bytes / self.wall / 1e6, 2)
            if self.wall else None,
            "object_size": self.object_size,
            "lat_avg_ms": round(1e3 * sum(lat) / n, 3),
            "lat_min_ms": round(1e3 * lat[0], 3),
            "lat_p50_ms": round(1e3 * lat[n // 2], 3),
            "lat_p99_ms": round(1e3 * lat[min(n - 1,
                                              (99 * n) // 100)], 3),
            "lat_max_ms": round(1e3 * lat[-1], 3),
            "lat_stddev_ms": round(
                1e3 * statistics.pstdev(lat), 3) if n > 1 else 0.0,
        }


class ObjBencher:
    """N concurrent workers against one pool through one client map
    (each worker owns its own messenger-level concurrency through the
    shared client; placements are computed client-side per op)."""

    def __init__(self, client, pool_id: int,
                 object_size: int = 1 << 16, concurrent: int = 8,
                 prefix: Optional[str] = None):
        self.client = client
        self.pool_id = pool_id
        self.object_size = object_size
        self.concurrent = concurrent
        self.prefix = prefix or f"benchmark_data_{time.time_ns()}"
        self.written = 0

    def _run(self, op: str, seconds: float, fn) -> BenchResult:
        res = BenchResult(op, self.object_size)
        stop = time.monotonic() + seconds
        counter = [0]
        clock = make_lock("bench::counter")

        def worker(wid: int):
            while time.monotonic() < stop:
                with clock:
                    i = counter[0]
                    counter[0] += 1
                t0 = time.perf_counter()
                try:
                    fn(i)
                except Exception:
                    res.add_error()
                    continue
                res.add(time.perf_counter() - t0)

        t0 = time.monotonic()
        ths = [threading.Thread(target=worker, args=(w,))
               for w in range(self.concurrent)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        res.wall = time.monotonic() - t0
        return res

    def write(self, seconds: float) -> BenchResult:
        blob = bytes(
            (i * 131 + 17) & 0xFF for i in range(self.object_size))

        def one(i: int) -> None:
            self.client.put(self.pool_id, f"{self.prefix}_{i}", blob)

        res = self._run("write", seconds, one)
        self.written = res.summary().get("ops", 0) + res.errors
        return res

    def write_aio(self, seconds: float) -> BenchResult:
        """Pipelined write phase: ONE submitter drives ``aio_put``,
        paced by the client's bounded in-flight window (the rados
        bench -t queue-depth semantics) so the OSD queues stay full
        instead of ping-ponging per-thread synchronous ops.  Latency
        samples are per-op submit→complete, recorded at completion."""
        blob = bytes(
            (i * 131 + 17) & 0xFF for i in range(self.object_size))
        res = BenchResult("write", self.object_size)
        stop = time.monotonic() + seconds
        i = 0
        t0 = time.monotonic()
        while time.monotonic() < stop:
            t_op = time.perf_counter()

            def done(c, t=t_op):
                if c.error is not None:
                    res.add_error()
                else:
                    res.add(time.perf_counter() - t)

            # blocks while the window is full — the submit loop runs
            # exactly at the client's queue depth
            self.client.aio_put(self.pool_id, f"{self.prefix}_{i}",
                                blob, on_complete=done)
            i += 1
        try:
            self.client.flush(timeout=60)
        except Exception:
            pass  # per-op errors were already counted by callbacks
        res.wall = time.monotonic() - t0
        self.written = i
        return res

    def seq(self, seconds: float) -> BenchResult:
        limit = max(1, self.written)

        def one(i: int) -> None:
            self.client.get(self.pool_id,
                            f"{self.prefix}_{i % limit}",
                            notfound_retries=0)

        return self._run("seq", seconds, one)

    def rand(self, seconds: float) -> BenchResult:
        import random

        limit = max(1, self.written)
        rng = random.Random(42)

        def one(i: int) -> None:
            self.client.get(
                self.pool_id,
                f"{self.prefix}_{rng.randrange(limit)}",
                notfound_retries=0)

        return self._run("rand", seconds, one)


def bench_minicluster(op: str = "write", seconds: float = 5.0,
                      concurrent: int = 8, object_size: int = 1 << 16,
                      n_osds: int = 4, ec: bool = False,
                      pg_num: int = 16, qd: Optional[int] = None,
                      qd_sweep: Optional[List[int]] = None,
                      ec_engine: str = "") -> Dict:
    """One-shot: boot a MiniCluster, run write (then optionally a read
    phase), return the summary dict.

    ``qd``: drive the write phase through the pipelined aio path at
    that queue depth instead of ``concurrent`` synchronous threads.
    ``qd_sweep``: run one aio write phase per depth and report the
    best (plus the whole sweep under ``qd_sweep``) — the knee of that
    curve is the cluster's write pipeline capacity.

    ``ec_engine``: EC engine profile key for the EC pool(s) —
    '', 'native', 'bitplane' or 'pallas-fused'; the resolved choice
    is recorded in the copy block as ``engine``."""
    from ..common.config import Config
    from ..services.cluster import MiniCluster

    conf = Config()
    conf.set("osd_heartbeat_interval", 0.5)
    conf.set("osd_heartbeat_grace", 5.0)
    # the bench measures the data path, not the telemetry plane:
    # full-rate span recording is real per-op CPU on a saturated host
    # (the trace_sample_rate knob exists for exactly this call)
    conf.set("trace_sample_rate", 0.0)
    cluster = MiniCluster(n_osds=n_osds, config=conf).start()
    t_boot = time.monotonic()
    try:
        if ec:
            prof = {"plugin": "jerasure",
                    "technique": "reed_sol_van",
                    "k": "2", "m": "1", "w": "8"}
            if ec_engine:
                prof["engine"] = ec_engine
            cluster.create_ec_pool(1, "bench21", prof, pg_num=pg_num)
        else:
            cluster.create_replicated_pool(
                1, pg_num=pg_num, size=min(3, n_osds))
        out: Dict = {}
        if qd_sweep:
            sweep: Dict[str, Dict] = {}
            best = None
            b = None
            for depth in qd_sweep:
                conf.set("client_aio_window", depth)
                cli = cluster.client(f"bench-qd{depth}")
                bench = ObjBencher(cli, 1, object_size=object_size,
                                   concurrent=concurrent)
                s = bench.write_aio(seconds).summary()
                s["qd"] = depth
                sweep[str(depth)] = s
                if best is None or (s.get("iops") or 0) > \
                        (best.get("iops") or 0):
                    best, b = s, bench
            out["write"] = best
            out["qd_sweep"] = {d: s.get("iops")
                               for d, s in sweep.items()}
        elif qd:
            conf.set("client_aio_window", qd)
            cli = cluster.client("bench")
            b = ObjBencher(cli, 1, object_size=object_size,
                           concurrent=concurrent)
            s = b.write_aio(seconds).summary()
            s["qd"] = qd
            out["write"] = s
        else:
            cli = cluster.client("bench")
            b = ObjBencher(cli, 1, object_size=object_size,
                           concurrent=concurrent)
            out["write"] = b.write(seconds).summary()
        if op in ("seq", "rand"):
            out[op] = getattr(b, op)(seconds).summary()

        # -- the profiling plane (PR 13) --------------------------------
        # attribution burst: a short fully-traced write burst (root
        # sampling is decided by the CLIENT's tracer, so a client
        # created after the rate flip records complete cross-daemon
        # trees even though the daemons booted at rate 0), folded
        # into the per-stage critical-path breakdown
        from . import telemetry as _tel
        from ..common import attribution as _attr

        conf.set("trace_sample_rate", 1.0)
        attr_cli = cluster.client("bench-attr")
        attr_bench = ObjBencher(attr_cli, 1,
                                object_size=object_size,
                                concurrent=2)
        attr_bench.write(min(1.0, seconds))
        conf.set("trace_sample_rate", 0.0)

        # EC write burst: the copy ledger's ec_assembly site books
        # only on the EC write lane, so a replicated-only bench run
        # would report 0 there forever (the r13 records did exactly
        # that).  Always push a short burst through an EC pool before
        # the ledger snapshot so every site carries real traffic.
        ec_pool = 1
        if not ec:
            ec_pool = 2
            prof = {"plugin": "jerasure",
                    "technique": "reed_sol_van",
                    "k": "2", "m": "1", "w": "8"}
            if ec_engine:
                prof["engine"] = ec_engine
            cluster.create_ec_pool(ec_pool, "benchec", prof,
                                   pg_num=8)
        ec_cli = cluster.client("bench-ec")
        ObjBencher(ec_cli, ec_pool, object_size=object_size,
                   concurrent=2).write(min(1.0, seconds))

        snap = _tel.cluster_snapshot(cluster.asok_dir)
        folds = _attr.fold_spans(_tel.gather_spans(snap))
        agg = _attr.StageAggregator()
        for f in folds:
            agg.add(f)
        rep = agg.report()
        grand = sum(r["total_s"] for r in rep["stages"].values())
        out["attribution"] = {
            "n_ops": rep["n_ops"],
            "client_p50_ms": rep["total"]["p50_ms"],
            "unattr_pct": round(
                100.0 * rep["stages"]["unattributed"]["total_s"]
                / grand, 3) if grand > 0 else 0.0,
            "shares": {s: r["share"]
                       for s, r in rep["stages"].items()},
        }

        # byte-copy ledger: cluster-wide obs.copy totals normalized
        # per op — the ROADMAP item 2 baseline number
        copy_tot: Dict[str, float] = {}
        op_tot = 0.0
        for _d, data in snap.get("daemons", {}).items():
            perf = data.get("perf") or {}
            for logger, counters in perf.items():
                if not isinstance(counters, dict):
                    continue
                if logger == "obs.copy":
                    for k, v in counters.items():
                        if isinstance(v, (int, float)):
                            copy_tot[k] = copy_tot.get(k, 0) + v
                elif logger.startswith(("osd.", "client.")):
                    for k in ("ops_w", "ops_r", "ops_put",
                              "ops_get", "ops_write"):
                        v = counters.get(k)
                        if isinstance(v, (int, float)):
                            op_tot += v
        out["copy"] = {
            "bytes_copied": int(copy_tot.get("bytes_copied", 0)),
            "copies": int(copy_tot.get("copies", 0)),
            "bytes_per_op": round(
                copy_tot.get("bytes_copied", 0) / op_tot, 1)
            if op_tot > 0 else 0.0,
            "sites": {site: int(copy_tot.get(f"{site}_bytes", 0))
                      for site in ("recv", "send", "store_txn",
                                   "ec_assembly",
                                   "recovery_push")},
        }
        from ..ec.native_gf import engine_choice
        out["copy"]["engine"] = engine_choice(ec_engine)

        # profiler overhead: the same short write burst with the
        # wallclock sampler off vs on at profiler_hz (100 Hz default)
        # — the <5% acceptance gate.  The MiniCluster is a single
        # process and sys._current_frames() is process-wide, so ONE
        # in-process sampler already observes every daemon's threads;
        # starting all N would do N× redundant GIL-bound stack walks
        # and measure the meter instead of the workload.
        # Overhead is measured counterbalanced (off, on, on, off):
        # every burst writes fresh objects, so the cluster gets
        # monotonically heavier across bursts — a naive off-then-on
        # order charges that drift to the profiler.  The ABBA order
        # gives both arms the same mean position, so linear drift
        # cancels exactly.
        prof_s = min(1.0, seconds)
        burst = max(0.25, prof_s / 2.0)
        prof_cli = cluster.client("bench-prof")

        def _burst() -> float:
            return ObjBencher(
                prof_cli, 1, object_size=object_size,
                concurrent=2).write(burst).summary().get("iops") \
                or 0.0

        targets = _tel.discover(cluster.asok_dir)
        pick = next((n for n in sorted(targets)
                     if n.startswith("osd.")),
                    min(targets, default=None))
        one = {pick: targets[pick]} if pick else {}
        off_a = _burst()
        _tel.gather_profiles(paths=one, cmd="start")
        on_a = _burst()
        on_b = _burst()
        dumps = _tel.gather_profiles(paths=one, cmd="stop")
        off_b = _burst()
        final = _tel.gather_profiles(paths=one, cmd="dump")
        samples = sum(d.get("samples", 0) for d in final.values())
        self_s = sum(d.get("self_s", 0.0) for d in final.values())
        elapsed = max((d.get("elapsed", 0.0)
                       for d in final.values()), default=0.0)
        iops_off = (off_a + off_b) / 2.0
        iops_on = (on_a + on_b) / 2.0
        # overhead_pct is the sampler's measured SELF time as a share
        # of the sampled window — the direct meter.  In this single-
        # process GIL-bound cluster every microsecond the sampler
        # holds the GIL is a microsecond stolen from the workload, so
        # self-share IS the expected throughput tax; the ABBA iops
        # pair above corroborates it but carries burst-to-burst noise
        # an order of magnitude above the effect.
        out["profiler"] = {
            "hz": conf["profiler_hz"],
            "daemons": len(dumps),
            "samples": samples,
            "self_s": round(self_s, 4),
            "iops_off": iops_off,
            "iops_on": iops_on,
            "iops_delta_pct": round(
                100.0 * (iops_off - iops_on) / iops_off, 2)
            if iops_off > 0 else 0.0,
            "overhead_pct": round(
                100.0 * self_s / elapsed, 2)
            if elapsed > 0 else 0.0,
        }

        # saturation plane (PR 17): fold the run's cumulative msgr
        # books into the cluster net summary — send-stall share,
        # dispatch p99 and the worst heartbeat peers.  A fresh
        # snapshot here (not ``snap``) covers the profiler bursts
        # too; with no prev snapshot net_summary treats the books as
        # one whole-run delta over dt.
        net_snap = _tel.cluster_snapshot(cluster.asok_dir)
        out["net"] = _tel.net_summary(
            net_snap, dt=time.monotonic() - t_boot)

        out["pool"] = "ec(2,1)" if ec else "replicated(size=" + \
            str(min(3, n_osds)) + ")"
        out["n_osds"] = n_osds
        return out
    finally:
        cluster.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados_bench")
    ap.add_argument("op", choices=["write", "seq", "rand"])
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--concurrent", type=int, default=8)
    ap.add_argument("--object-size", type=int, default=1 << 16)
    ap.add_argument("--osds", type=int, default=4)
    ap.add_argument("--pg-num", type=int, default=16)
    ap.add_argument("--ec", action="store_true",
                    help="bench an EC(2,1) pool instead of replicated")
    ap.add_argument("--qd", type=int, default=None,
                    help="drive writes through the pipelined aio "
                         "path at this queue depth")
    ap.add_argument("--qd-sweep", type=str, default=None,
                    help="comma-separated queue depths to sweep "
                         "(e.g. 8,16,32); reports the best")
    args = ap.parse_args(argv)

    sweep = [int(x) for x in args.qd_sweep.split(",")] \
        if args.qd_sweep else None
    out = bench_minicluster(
        op=args.op, seconds=args.seconds, concurrent=args.concurrent,
        object_size=args.object_size, n_osds=args.osds, ec=args.ec,
        pg_num=args.pg_num, qd=args.qd, qd_sweep=sweep)
    for phase, s in out.items():
        if isinstance(s, dict):
            print(f"# {phase}: {s.get('iops')} IOPS, "
                  f"{s.get('mb_per_sec')} MB/s, avg "
                  f"{s.get('lat_avg_ms')} ms, p99 "
                  f"{s.get('lat_p99_ms')} ms", file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
