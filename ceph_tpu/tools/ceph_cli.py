"""ceph — the cluster admin CLI.

The `ceph` command role (src/ceph.in + the mon command surface):
status/health/df, osd tree/reweight/out/down, pool create/delete/ls —
all against a running cluster's monitor address (quorum lists accepted
as comma-separated host:port pairs).

Plus the local observability plane (no monitor needed — polls daemon
admin sockets, ceph_tpu/tools/telemetry.py):

Plus the wire-format conformance plane (no cluster needed — drives
the ceph_tpu/analysis/wirecheck.py registry, the ceph-dencoder role):

CLI:
    python -m ceph_tpu.tools.ceph_cli --mon HOST:PORT[,HOST:PORT...] \
        status | health | osd tree | osd reweight ID W | osd out ID |
        osd down ID | pool ls | pool create ID PGS SIZE |
        pool delete ID | pool-stats [ID] | progress
    python -m ceph_tpu.tools.ceph_cli --asok-dir DIR \
        daemonperf | top | history | latency | net |
        telemetry snapshot|prom|traces|flame|profile|net
    python -m ceph_tpu.tools.ceph_cli --asok-dir DIR \
        balancer status|on|off|eval|execute |
        mgr module ls|enable|disable NAME
    python -m ceph_tpu.tools.ceph_cli \
        dencoder list | encode TYPE | decode TYPE [HEXFILE] |
        roundtrip [TYPE]
"""

from __future__ import annotations

import argparse
import json
import sys

from ..msg.messenger import Messenger
from ..services.map_follower import failover_call


def _mons(spec: str):
    out = []
    for part in spec.split(","):
        host, port = part.rsplit(":", 1)
        out.append((host, int(port)))
    return out


def _jsonable(obj):
    """Decoded wire objects rendered for the terminal: bytes as hex,
    to_dict forms expanded, tuples as lists."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj).hex()
    if hasattr(obj, "to_dict"):
        return _jsonable(obj.to_dict())
    if hasattr(obj, "export_state"):
        return _jsonable(obj.export_state())
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


def _mgr_verb(args, extra) -> int:
    """Route `balancer ...` / `mgr ...` through the manager daemon's
    admin socket (`ceph balancer status|on|off|eval|execute`, `ceph
    mgr module ls|enable|disable`)."""
    import glob
    import os

    from ..common.admin_socket import AdminSocket

    if not args.asok_dir:
        print("balancer/mgr verbs need --asok-dir", file=sys.stderr)
        return 2
    socks = sorted(glob.glob(
        os.path.join(args.asok_dir, "mgr.*.asok")))
    if not socks:
        print(f"no mgr admin socket under {args.asok_dir}",
              file=sys.stderr)
        return 2
    argv = args.verb[1:] + extra
    try:
        # generous deadline: a cold `balancer eval` pays the batched
        # sweep's first XLA compile inside the request
        rep = AdminSocket.request(socks[0], args.verb[0], timeout=60.0,
                                  argv=argv)
    except OSError as e:
        print(f"mgr admin socket: {e}", file=sys.stderr)
        return 1
    if isinstance(rep, dict) and rep.get("error"):
        print(json.dumps(rep), file=sys.stderr)
        return 1
    if args.verb[0] == "balancer" and argv[:1] == ["eval"] and \
            isinstance(rep, dict):
        # the per-pool score breakdown, human-shaped
        print(f"cluster: stddev {rep.get('stddev', 0.0):.3f} "
              f"score {rep.get('score', 0.0):.6f} "
              f"max_dev {rep.get('max_dev', 0.0):.2f} "
              f"({rep.get('osd_count')} osds, "
              f"{rep.get('sweep_launches')} sweeps)")
        for pid, row in sorted((rep.get("pools") or {}).items()):
            print(f"pool {pid}: pg_num {row.get('pg_num')} "
                  f"size {row.get('size')} "
                  f"stddev {row.get('stddev', 0.0):.3f} "
                  f"score {row.get('score', 0.0):.6f} "
                  f"max_dev {row.get('max_dev', 0.0):.2f}")
        return 0
    print(json.dumps(rep, indent=1, sort_keys=True))
    return 0


def _dencoder(verb, extra) -> int:
    """The ceph-dencoder role over the wirecheck registry: enumerate
    registered wire types, emit an example encode, decode arbitrary
    blobs, and run the five-property conformance check."""
    from ..analysis import wirecheck

    sub = verb[1] if len(verb) > 1 else "list"
    if sub == "list":
        for e in wirecheck.entries():
            print(f"{e.name}  struct_v={e.struct_v} "
                  f"compat_v={e.compat_v} kind={e.kind}"
                  f"{' legacy-ok' if e.legacy else ''}")
        return 0
    if sub == "encode":
        if len(verb) < 3:
            print("dencoder encode needs a TYPE", file=sys.stderr)
            return 2
        e = wirecheck.get(verb[2])
        blob = e.encode(e.factory())
        blob = blob.encode() if isinstance(blob, str) else blob
        print(blob.hex())
        return 0
    if sub == "decode":
        if len(verb) < 3:
            print("dencoder decode needs a TYPE", file=sys.stderr)
            return 2
        e = wirecheck.get(verb[2])
        src = verb[3] if len(verb) > 3 else "-"
        hexstr = sys.stdin.read() if src == "-" else \
            open(src).read()
        try:
            obj = e.decode(bytes.fromhex(hexstr.strip()))
        except ValueError as err:
            print(f"decode failed: {err}", file=sys.stderr)
            return 1
        print(json.dumps(_jsonable(obj), indent=1))
        return 0
    if sub == "roundtrip":
        targets = wirecheck.entries() if len(verb) < 3 else \
            [wirecheck.get(verb[2])]
        bad = 0
        for e in targets:
            fails = wirecheck.check(e)
            print(f"{e.name}: "
                  f"{'ok' if not fails else 'FAIL'}")
            for f in fails:
                print(f"  - {f}")
            bad += bool(fails)
        return 1 if bad else 0
    print(f"unknown dencoder verb {sub!r}", file=sys.stderr)
    return 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--mon",
                    help="monitor address(es), host:port[,host:port]")
    ap.add_argument("--asok-dir",
                    help="daemon admin-socket dir (daemonperf / "
                         "telemetry verbs)")
    ap.add_argument("--keyring", help="cluster key (hex)")
    ap.add_argument("verb", nargs="+")
    # unknown extras (e.g. daemonperf's --interval/--count) pass
    # through to the telemetry tool's own parser
    args, extra = ap.parse_known_args(argv)

    # the conformance plane runs entirely offline
    if args.verb[0] == "dencoder":
        return _dencoder(args.verb, extra)

    # the observability verbs poll admin sockets directly — no
    # monitor, no messenger.  `top` and `history` are the continuous
    # plane (per-daemon metrics-history rings + live rate frames).
    if args.verb[0] in ("daemonperf", "telemetry", "top",
                        "history", "latency", "net"):
        from . import telemetry

        if not args.asok_dir:
            print("daemonperf/telemetry/top/history/latency need "
                  "--asok-dir", file=sys.stderr)
            return 2
        if args.verb[0] == "telemetry":
            sub = args.verb[1] if len(args.verb) > 1 else "snapshot"
        else:
            sub = args.verb[0]
        return telemetry.main(["--asok-dir", args.asok_dir, sub]
                              + args.verb[2:] + extra)

    # the manager verbs route through the mgr's admin socket (the
    # `ceph balancer ...` / `ceph mgr module ...` surfaces): the mgr
    # owns the module plane, not the monitor
    if args.verb[0] in ("balancer", "mgr"):
        return _mgr_verb(args, extra)

    if extra:
        print(f"unrecognized arguments: {' '.join(extra)}",
              file=sys.stderr)
        return 2
    if not args.mon:
        print("this verb needs --mon", file=sys.stderr)
        return 2
    kr = None
    if args.keyring:
        from ..msg.auth import Keyring

        kr = Keyring.from_hex(args.keyring)
    msgr = Messenger("ceph-cli", keyring=kr)
    msgr.start()
    mons = _mons(args.mon)

    def call(msg, timeout=10.0):
        rep, _ = failover_call(msgr, mons, msg, timeout=timeout)
        return rep

    def mutate(rep) -> int:
        """Mutation verbs honor the exit-code contract: a monitor
        error reply is a failure, not a success with sad JSON."""
        print(json.dumps(rep))
        return 1 if isinstance(rep, dict) and rep.get("error") else 0

    v = args.verb
    rc = 0
    try:
        if v[0] == "status":
            st = call({"type": "status"})
            h = call({"type": "health"})
            pg = st.get("pgmap", {})
            print(f"  health:  {h.get('status')}")
            for chk in h.get("checks", []):
                print(f"           {chk}")
            print(f"  epoch:   {st.get('epoch')}")
            print(f"  osds:    {len(st.get('up_osds', []))} up "
                  f"{st.get('up_osds')}")
            print(f"  pools:   {st.get('num_pools')}")
            print(f"  pgs:     {pg.get('pgs_reported')}/"
                  f"{pg.get('pgs_total')} reported "
                  f"{pg.get('by_state')}")
            print(f"  objects: {pg.get('objects')}")
        elif v[0] == "health":
            h = call({"type": "health"})
            print(h["status"])
            for chk in h.get("checks", []):
                print(f"  {chk}")
            if h["status"] != "HEALTH_OK":
                return 1
        elif v[0] == "df":
            st = call({"type": "status"})
            print(json.dumps(st.get("pgmap", {}), indent=1))
        elif v[:2] == ["osd", "tree"]:
            payload = call({"type": "get_map"})
            from ..crush.wrapper import CrushWrapper
            from ..osdmap.bincode_maps import payload_map
            from .crushtool import cmd_tree

            w = CrushWrapper(payload_map(payload).crush)
            cmd_tree(w, sys.stdout)
        elif v[:2] == ["osd", "reweight"] and len(v) == 4:
            rc = mutate(call({"type": "reweight", "osd": int(v[2]),
                              "weight": int(float(v[3]) * 0x10000)}))
        elif v[:2] == ["osd", "out"] and len(v) == 3:
            rc = mutate(call({"type": "mark_out", "osd": int(v[2])}))
        elif v[:2] == ["osd", "down"] and len(v) == 3:
            rc = mutate(call({"type": "mark_down",
                              "osd": int(v[2])}))
        elif v[:2] == ["pool", "ls"]:
            payload = call({"type": "get_map"})
            from ..osdmap.bincode_maps import payload_map

            for pid, pool in sorted(payload_map(payload)
                                    .pools.items()):
                print(f"pool {pid}: type {pool.pool_type} "
                      f"size {pool.size} pg_num {pool.pg_num}")
        elif v[:2] == ["pool", "create"] and len(v) == 5:
            rc = mutate(call(
                {"type": "pool_create", "pool_id": int(v[2]),
                 "pool": {"pool_type": 1,
                          "size": int(v[4]),
                          "min_size": max(1, int(v[4]) - 1),
                          "pg_num": int(v[3]),
                          "crush_rule": 0}}))
        elif v[:2] == ["pool", "delete"] and len(v) == 3:
            rc = mutate(call({"type": "pool_delete",
                              "pool_id": int(v[2])}))
        elif v[0] == "pool-stats":
            msg = {"type": "pool_stats"}
            if len(v) > 1:
                msg["pool"] = int(v[1])
            got = call(msg)
            for pid, st in sorted(got.get("pools", {}).items()):
                cur = st.get("current", {})
                last = (st.get("series") or [{}])[-1]
                print(f"pool {pid}: {cur.get('objects', 0)} objects, "
                      f"{cur.get('degraded_pgs', 0)} pgs degraded; "
                      f"wr {last.get('wr_bps', 0.0):.0f} B/s "
                      f"({last.get('wr_ops_s', 0.0):.1f} op/s), "
                      f"rd {last.get('rd_bps', 0.0):.0f} B/s, "
                      f"recovery "
                      f"{last.get('recovery_bps', 0.0):.0f} B/s")
            print(json.dumps(got))
        elif v[0] == "progress":
            got = call({"type": "progress"})
            events = got.get("events", [])
            if not events:
                print("progress: nothing in progress")
            for ev in events:
                bar_w = 30
                frac = float(ev.get("fraction", 0.0))
                fill = int(bar_w * max(0.0, min(1.0, frac)))
                state = "done" if ev.get("done") else \
                    f"{ev.get('rate_bps', 0.0):.0f} B/s"
                print(f"  {ev.get('id')}: "
                      f"[{'=' * fill}{'.' * (bar_w - fill)}] "
                      f"{frac * 100:.1f}% ({state})")
        else:
            print(f"unknown or incomplete verb: {' '.join(v)}",
                  file=sys.stderr)
            return 2
    finally:
        msgr.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
