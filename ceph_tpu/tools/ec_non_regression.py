"""EC non-regression corpus — archived encodings pinned across versions.

The role of src/test/erasure-code/ceph_erasure_code_non_regression.cc
with the ceph-erasure-code-corpus submodule: encode a deterministic
payload under a profile, ARCHIVE the chunks, and on every future
version re-encode and byte-compare (plus decode round-trips with
erasures) — so on-wire parity can never drift silently between
releases.  Corpus entries live under ``tests/corpus/<slug>/``:
``profile.json``, ``data.bin`` and ``chunk.<i>``.

Usage:
  python -m ceph_tpu.tools.ec_non_regression --create \
      --plugin jerasure -P k=4 -P m=2 [--base DIR]
  python -m ceph_tpu.tools.ec_non_regression --check [--base DIR]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

import numpy as np

from ..ec.registry import factory

DEFAULT_BASE = pathlib.Path(__file__).resolve().parents[2] \
    / "tests" / "corpus"
PAYLOAD_SIZE = 31 * 1024 + 7  # deliberately unaligned


def _payload(seed: int = 0xC0) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, PAYLOAD_SIZE, dtype=np.uint8).tobytes()


def _slug(plugin: str, profile: dict) -> str:
    parts = [plugin] + [f"{k}={profile[k]}"
                        for k in sorted(profile) if k != "plugin"]
    return "-".join(parts).replace("/", "_")


def create_entry(base: pathlib.Path, plugin: str,
                 profile: dict) -> pathlib.Path:
    code = factory(plugin, dict(profile))
    raw = _payload()
    n = code.get_chunk_count()
    chunks = code.encode(range(n), raw)
    entry = base / _slug(plugin, profile)
    entry.mkdir(parents=True, exist_ok=True)
    (entry / "profile.json").write_text(json.dumps(
        {"plugin": plugin, "profile": profile,
         "payload_size": len(raw)}, indent=1))
    (entry / "data.bin").write_bytes(raw)
    for i in range(n):
        (entry / f"chunk.{i}").write_bytes(
            np.asarray(chunks[i], np.uint8).tobytes())
    return entry


def check_entry(entry: pathlib.Path) -> list:
    """Returns a list of failure strings (empty = pass)."""
    meta = json.loads((entry / "profile.json").read_text())
    code = factory(meta["plugin"], dict(meta["profile"]))
    raw = (entry / "data.bin").read_bytes()
    n = code.get_chunk_count()
    failures = []
    chunks = code.encode(range(n), raw)
    archived = {}
    for i in range(n):
        want = (entry / f"chunk.{i}").read_bytes()
        archived[i] = np.frombuffer(want, np.uint8)
        got = np.asarray(chunks[i], np.uint8).tobytes()
        if got != want:
            failures.append(f"{entry.name}: chunk {i} re-encode "
                            f"differs from archive")
    # decode the ARCHIVED chunks (what old clusters actually stored)
    for erased in range(n):
        avail = {i: c for i, c in archived.items() if i != erased}
        try:
            got = code.decode_concat(avail)[:len(raw)]
        except Exception as e:
            failures.append(f"{entry.name}: decode with chunk "
                            f"{erased} erased failed: {e}")
            continue
        if got != raw:
            failures.append(f"{entry.name}: decode with chunk "
                            f"{erased} erased returned wrong bytes")
    return failures


def check_all(base: pathlib.Path) -> list:
    """A gate that compared nothing must FAIL: a missing or empty
    corpus reports itself instead of passing vacuously."""
    if not base.is_dir():
        return [f"corpus base {base} does not exist"]
    # only EC parity entries (marked by profile.json) belong to this
    # checker; tests/corpus/encodings/ is the WIRE corpus, owned by
    # tests/golden/_gen_wire_corpus.py
    entries = sorted(p for p in base.iterdir()
                     if p.is_dir() and (p / "profile.json").exists())
    if not entries:
        return [f"corpus base {base} has no entries"]
    failures = []
    for entry in entries:
        failures.extend(check_entry(entry))
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ec_non_regression")
    p.add_argument("--base", default=str(DEFAULT_BASE))
    p.add_argument("--create", action="store_true")
    p.add_argument("--check", action="store_true")
    p.add_argument("--plugin", default="jerasure")
    p.add_argument("-P", "--parameter", action="append", default=[])
    args = p.parse_args(argv)
    base = pathlib.Path(args.base)

    if args.create:
        profile = {}
        for kv in args.parameter:
            k, _, v = kv.partition("=")
            profile[k] = v
        entry = create_entry(base, args.plugin, profile)
        print(f"archived {entry}")
        return 0
    if args.check:
        failures = check_all(base)
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        n = (sum(1 for p_ in base.iterdir() if p_.is_dir())
             if base.is_dir() else 0)
        print(f"checked {n} corpus entries: "
              f"{'FAIL' if failures else 'OK'}")
        return 1 if failures else 0
    p.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
