"""rados — the object CLI.

The `rados` tool role (src/tools/rados/rados.cc) over this framework's
client: put/get/rm/ls/stat/df against a running cluster's monitor
address, plus `bench` delegating to the obj_bencher analogue
(tools/rados_bench.py).

CLI:
    python -m ceph_tpu.tools.rados --mon HOST:PORT -p POOL \
        put OBJ FILE | get OBJ FILE | rm OBJ | ls | stat OBJ | df
"""

from __future__ import annotations

import argparse
import json
import sys


def _client(mon: str, keyring_hex=None):
    from ..services.client import Client

    host, port = mon.rsplit(":", 1)
    kr = None
    if keyring_hex:
        from ..msg.auth import Keyring

        kr = Keyring.from_hex(keyring_hex)
    return Client("rados-cli", (host, int(port)), keyring=kr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--mon", required=True, help="monitor host:port")
    ap.add_argument("-p", "--pool", type=int, default=1)
    ap.add_argument("--keyring", help="cluster key (hex)")
    sub = ap.add_subparsers(dest="op", required=True)
    p = sub.add_parser("put")
    p.add_argument("obj")
    p.add_argument("file")
    p = sub.add_parser("get")
    p.add_argument("obj")
    p.add_argument("file")
    p = sub.add_parser("rm")
    p.add_argument("obj")
    sub.add_parser("ls")
    p = sub.add_parser("stat")
    p.add_argument("obj")
    sub.add_parser("df")
    args = ap.parse_args(argv)

    cli = _client(args.mon, args.keyring)
    try:
        if args.op == "put":
            data = sys.stdin.buffer.read() if args.file == "-" \
                else open(args.file, "rb").read()
            cli.put(args.pool, args.obj, data)
        elif args.op == "get":
            data = cli.get(args.pool, args.obj)
            if args.file == "-":
                sys.stdout.buffer.write(data)
            else:
                open(args.file, "wb").write(data)
        elif args.op == "rm":
            cli.delete(args.pool, args.obj)
        elif args.op == "ls":
            # walk every PG's primary listing (object names are
            # client-hashed, so the union over PGs is the pool listing)
            pool = cli.map.pools[args.pool]
            seen = set()
            for ps in range(pool.pg_num):
                up, _p, acting, _ap = cli.map.pg_to_up_acting_osds(
                    args.pool, ps)
                members = acting if acting else up
                for osd in members:
                    if osd < 0 or osd not in cli.osd_addrs:
                        continue
                    got = cli.msgr.call(
                        cli.osd_addrs[osd],
                        {"type": "pg_list", "pool": args.pool,
                         "ps": ps}, timeout=5)
                    seen.update(got.get("objects", {}))
                    break
            for name in sorted(seen):
                print(name)
        elif args.op == "stat":
            data = cli.get(args.pool, args.obj)
            print(f"{args.obj} size {len(data)}")
        elif args.op == "df":
            st = cli.mon_call({"type": "status"})
            print(json.dumps({"epoch": st.get("epoch"),
                              "up_osds": st.get("up_osds"),
                              "num_pools": st.get("num_pools")}))
    finally:
        cli.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
